#!/usr/bin/env python3
"""graft-serve driver: seeded open-loop load over the paged-KV engine.

Spins up an :class:`InferenceEngine` (paged KV cache + continuous
batching, ``distributed_pytorch_example_tpu/serving/``) on a randomly
initialized GPT-2/LLaMA of CLI-chosen size and drives it with a seeded
Poisson open-loop workload of mixed prompt/output lengths — the standard
serving-benchmark shape: requests arrive on their own schedule whether or
not the server is keeping up.

Driver contract (same as bench.py): stdout gets exactly ONE JSON line —
TTFT and per-output-token latency p50/p95/p99, tokens/sec, slot
occupancy, preempted/rejected counts, config. Per-request detail lines
go to stderr as requests finish.

Run it on the fake CPU mesh (no TPU needed)::

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
      python serve.py --requests 16 --rate 4 --mesh data=2,fsdp=2,tensor=2

``--mesh`` serves sharded exactly like ``generate(partitioner=...)``:
TP-partitioned weights stay sharded, the KV pool shards kv-heads over
``tensor`` and pool blocks over the data axes.

``--replicas N`` (graft-fleet) serves the same workload through N
engine replicas behind a :class:`FleetRouter` — session-affine
placement, heartbeat failover, journal replay — and the JSON line gains
the router metrics (per-replica occupancy, shed/replayed/redispatched
counts, detection latency). ``--chaos`` takes the same preset / JSON
spec as train.py (``kill-replica``, ``stall-replica``,
``flaky-channel``, ...); with ``--replicas > 1`` the driver first runs
an uninjected baseline pass and reports ``steady_state_ratio`` =
chaos-pass steady per-row cost / clean-pass steady per-row cost::

    JAX_PLATFORMS=cpu python serve.py --replicas 2 --chaos kill-replica
"""

from __future__ import annotations

import argparse
import json
import sys


def _parse_range(spec: str, flag: str):
    try:
        lo, hi = (int(x) for x in spec.split(":"))
    except ValueError:
        raise SystemExit(f"{flag} wants LO:HI, got {spec!r}")
    if lo < 1 or hi < lo:
        raise SystemExit(f"{flag} wants 1 <= LO <= HI, got {spec!r}")
    return lo, hi


def build_requests(args):
    """The seeded workload: Poisson arrivals, uniform mixed lengths."""
    import numpy as np

    from distributed_pytorch_example_tpu.serving import Request

    rng = np.random.default_rng(args.seed)
    plo, phi = _parse_range(args.prompt_len, "--prompt-len")
    olo, ohi = _parse_range(args.max_new, "--max-new")
    arrivals = (
        np.cumsum(rng.exponential(1.0 / args.rate, args.requests))
        if args.rate > 0 else np.zeros(args.requests)
    )
    reqs = []
    for i in range(args.requests):
        plen = int(rng.integers(plo, phi + 1))
        reqs.append(Request(
            rid=f"req{i:04d}",
            prompt=[int(t) for t in rng.integers(0, args.vocab_size, plen)],
            max_new_tokens=int(rng.integers(olo, ohi + 1)),
            seed=args.seed * 100_003 + i,
            arrival=float(arrivals[i]),
            session=(
                f"s{i % args.sessions}" if args.sessions > 0 else None
            ),
        ))
    return reqs


def build_model(args):
    """Model + random-init params + optional partitioner, built ONCE —
    every fleet replica shares them (and therefore the jit cache)."""
    import jax
    import jax.numpy as jnp

    kw = dict(
        vocab_size=args.vocab_size, max_len=args.max_len,
        model_dim=args.model_dim, num_layers=args.num_layers,
        num_heads=args.num_heads, mlp_dim=2 * args.model_dim,
    )
    if args.family == "llama":
        from distributed_pytorch_example_tpu.models.llama import Llama as M

        kw["num_kv_heads"] = args.num_kv_heads or args.num_heads
    else:
        from distributed_pytorch_example_tpu.models.gpt2 import GPT2 as M

    paged = dict(
        paged_num_blocks=args.num_blocks,
        paged_block_size=args.block_size,
        paged_max_blocks=args.max_blocks,
    )
    model = M(**kw, decode=True, **paged)
    # random-init params: this driver exercises serving (scheduling,
    # latency, isolation), not text quality; a trained checkpoint's params
    # drop in unchanged (same tree as the training model)
    params = M(**kw).init(
        jax.random.key(args.seed), jnp.zeros((1, 8), jnp.int32)
    )["params"]

    partitioner = None
    if args.auto_mesh:
        # graft-plan: rank the serve plan space through the static oracle
        # (prefill and decode scored separately; one engine runs both, so
        # the pick minimizes the summed program cost) — zero compiles
        import sys

        from distributed_pytorch_example_tpu.analysis import (
            envelope,
            planner,
        )
        from distributed_pytorch_example_tpu.serving import InferenceEngine

        probe = InferenceEngine(
            model, params, num_slots=args.slots,
            temperature=args.temperature, top_k=args.top_k,
            top_p=args.top_p,
        )
        plan, cost, _ranked = planner.pick_serve_plan(
            probe, hbm_limit=envelope.hbm_limit_from_env(),
            log=lambda m: print(m, file=sys.stderr),
        )
        if plan is None:
            raise ValueError(
                "--auto-mesh: no plan feasible for both prefill and decode"
            )
        print(
            f"serve: --auto-mesh picked {plan.name()} "
            f"(prefill+decode cost {cost:.4f} ms)",
            file=sys.stderr,
        )
        args._auto_mesh_plan = plan.name()
        partitioner = plan.lower()
    elif args.mesh:
        # --mesh lowers through PlanSpec too: transformer_partitioner is
        # the PlanSpec(family="transformer") lowering (parallel/plan.py)
        from distributed_pytorch_example_tpu.parallel.partition import (
            transformer_partitioner,
        )
        from distributed_pytorch_example_tpu.runtime import (
            MeshSpec, make_mesh,
        )

        axes = dict(
            (k, int(v)) for k, v in
            (kv.split("=") for kv in args.mesh.split(","))
        )
        partitioner = transformer_partitioner(make_mesh(MeshSpec(**axes)))
    return model, params, partitioner


def build_engines(args, trace, built, n):
    """N engines over the shared (model, params, partitioner)."""
    from distributed_pytorch_example_tpu.serving import InferenceEngine
    from distributed_pytorch_example_tpu.telemetry.trace import PrefixedTrace

    model, params, partitioner = built
    spec = {}
    if args.spec_tokens:
        # self-speculation: the target drafts for itself. Zero accuracy
        # risk (exact-match acceptance keeps output bit-identical either
        # way) and the win is real whenever drafting a token is cheaper
        # than a full decode boundary; a separately trained small draft
        # drops into the same two kwargs.
        spec = dict(
            draft_model=model, draft_params=params,
            spec_tokens=args.spec_tokens,
        )
    engines = []
    for i in range(n):
        engines.append(InferenceEngine(
            model, params, num_slots=args.slots,
            temperature=args.temperature, top_k=args.top_k,
            top_p=args.top_p, partitioner=partitioner,
            # graft-lens: each replica gets its own Perfetto process lane
            # (pid 0 is the router/host) inside the ONE shared trace file
            trace=(
                PrefixedTrace(trace, f"r{i}", pid=i + 1)
                if n > 1 else trace
            ),
            mode=args.mode, **spec,
        ))
    return engines


def build_engine(args, trace):
    return build_engines(args, trace, build_model(args), 1)[0]


def parse_chaos(spec: str):
    """Same contract as train.py --chaos: a preset name or a JSON plan."""
    from distributed_pytorch_example_tpu.robustness import chaos

    return (
        chaos.ChaosPlan.from_json(spec)
        if spec.lstrip().startswith("{") else chaos.preset(spec)
    )


def run_fleet(args, trace, built, requests):
    """graft-fleet: route the workload across --replicas engine replicas.

    Returns ``(report, baseline_metrics)``: with ``--chaos`` an
    uninjected baseline pass runs first on its own engines/handles (the
    shared jit cache means only the warmup compiles), giving the clean
    ``steady_per_row_ms`` that ``steady_state_ratio`` divides by.
    """
    from distributed_pytorch_example_tpu.robustness import chaos
    from distributed_pytorch_example_tpu.robustness.publish import (
        PublishChannel,
    )
    from distributed_pytorch_example_tpu.serving import (
        FleetRouter, ReplicaHandle, SwapController,
    )
    from distributed_pytorch_example_tpu.telemetry import ServeSentinels

    def one_pass(tag):
        engines = build_engines(args, trace, built, args.replicas)
        handles = [
            ReplicaHandle(f"r{i}", eng) for i, eng in enumerate(engines)
        ]
        sentinels = ServeSentinels(
            trace=trace,
            straggler_age_s=max(args.heartbeat_timeout / 2.0, 0.25),
        )
        router = FleetRouter(
            handles,
            heartbeat_timeout_s=args.heartbeat_timeout,
            max_queue=args.queue_cap,
            queue_deadline_s=args.queue_deadline,
            trace=trace,
            sentinels=sentinels,
        )
        ctrl = None
        if args.publish_dir:
            # graft-swap: the router ticks the controller once per loop
            # iteration; any version committed into the channel while
            # the workload runs rolls through drain/install/readmit
            ctrl = SwapController(
                PublishChannel(args.publish_dir),
                handles,
                poll_s=(
                    args.swap_poll_s
                    if args.swap_poll_s is not None else 0.25
                ),
            )
        print(f"serve: fleet pass '{tag}' ({args.replicas} replicas)",
              file=sys.stderr)
        report = router.run(requests, swap=ctrl)
        # fleet decode throughput: each worker thread runs serve_loop
        # exactly once per pass, so per-engine counters cover the pass;
        # rates pool by summed counts (not averaged per-replica ratios)
        dm = [eng.decode_metrics() for eng in engines]
        t = sum(d["decode_time_s"] for d in dm)
        toks = sum(d["decode_tokens"] for d in dm)
        prop = sum(d["spec_proposed"] for d in dm)
        acc = sum(d["spec_accepted"] for d in dm)
        report["metrics"].update(
            decode_time_s=t,
            decode_tokens=toks,
            decode_tokens_per_sec=toks / t if t > 0 else 0.0,
            spec_accept_rate=acc / prop if prop else None,
        )
        return report

    # XLA compile freezes replica heartbeats, so the fleet must be warm
    # before any router with a finite deadline sees it
    warm = build_engines(args, trace, built, 1)[0]
    warm.warmup()

    if not args.chaos:
        return one_pass("fleet"), None

    # interleaved clean/chaos pairs; steady_state_ratio = MIN over pair
    # ratios of best-boundary per-row cost. Three noise defenses, all
    # needed on a small host: (a) the min within a run is robust to the
    # one-sided scheduling jitter; (b) the clean stream is TRUNCATED to
    # the chaos run's pre-loss window length — the pre-loss window is
    # all-replicas-contended, while a full clean run ends in an
    # uncontended solo tail whose fast boundaries would bias the ratio
    # upward; (c) each pair is back-to-back, so the host floor's slow
    # drift cancels within a pair, while real machinery overhead is in
    # EVERY pair and survives the min. Each chaos pass gets a FRESH plan
    # (fired-counters reset) installed before its engines are built
    # (train.py order).
    baseline = None
    report = None
    best = None
    for _ in range(3):
        chaos.uninstall()
        b = one_pass("baseline")["metrics"]
        baseline = baseline or b
        chaos.install(parse_chaos(args.chaos))
        r = one_pass("chaos")
        report = report or r
        chaos_samples = r["metrics"]["steady_samples_ms"]
        clean_samples = b["steady_samples_ms"][:len(chaos_samples)]
        if chaos_samples and clean_samples:
            pair = (min(clean_samples), min(chaos_samples))
            if best is None or pair[1] / pair[0] < best[1] / best[0]:
                best = pair
    chaos.uninstall()
    if best is not None:
        baseline["steady_per_row_ms_min"] = best[0]
        report["metrics"]["steady_per_row_ms_min"] = best[1]
    return report, baseline


def _config_dict(args):
    return {
        "family": args.family, "requests": args.requests,
        "rate": args.rate, "mode": args.mode, "slots": args.slots,
        "num_blocks": args.num_blocks, "block_size": args.block_size,
        "max_blocks": args.max_blocks,
        "prompt_len": args.prompt_len, "max_new": args.max_new,
        "temperature": args.temperature, "top_k": args.top_k,
        "top_p": args.top_p, "seed": args.seed,
        **({"mesh": args.mesh} if args.mesh else {}),
        **({"auto_mesh": getattr(args, "_auto_mesh_plan", None)}
           if getattr(args, "_auto_mesh_plan", None) else {}),
        **({"chaos": args.chaos} if args.chaos else {}),
        **({"sessions": args.sessions} if args.sessions else {}),
        **({"replicas": args.replicas} if args.replicas > 1 else {}),
        **({"spec_tokens": args.spec_tokens} if args.spec_tokens else {}),
        **({
            "publish_dir": args.publish_dir,
            "swap_poll_s": (
                args.swap_poll_s if args.swap_poll_s is not None else 0.25
            ),
        } if getattr(args, "publish_dir", "") else {}),
    }


def _round(value, digits):
    return round(value, digits) if value is not None else None


def write_metrics_snapshot(path, metrics, config):
    """``--metrics-snapshot``: dump the full rolling-histogram summary
    (every metric's p50/p99/max, not just the JSON line's headline p99s)
    next to the trace, for offline inspection."""
    import os

    payload = {
        "metrics": {
            k: v for k, v in metrics.items()
            if k in (
                "latency", "ttft_ms", "tpot_ms", "queue_wait_ms",
                "sentinel_triggers",
            )
        },
        "config": config,
    }
    parent = os.path.dirname(path)
    if parent:
        os.makedirs(parent, exist_ok=True)
    with open(path, "w") as fh:
        json.dump(payload, fh, indent=2, sort_keys=True)
        fh.write("\n")


def emit_fleet_line(args, report, baseline) -> int:
    """The fleet-mode stdout line: same ONE-JSON-line contract, headline
    metric unchanged, plus the router/failover counters the acceptance
    gate reads (per-replica occupancy, shed/replayed/redispatched,
    detection latency, and — when a chaos baseline ran —
    ``steady_state_ratio``)."""
    import numpy as np

    for rid, r in sorted(report["results"].items()):
        print(json.dumps({
            "rid": rid, "status": r["status"], "replica": r["replica"],
            "new_tokens": len(r["tokens"]), "dispatches": r["dispatches"],
            "replays": r["replays"],
            **({"replay_token_exact": r["replay_token_exact"]}
               if r["replay_token_exact"] is not None else {}),
            **({"error": r["error"]} if r["error"] else {}),
        }), file=sys.stderr)

    m = report["metrics"]
    line = {
        "metric": "serve_tokens_per_sec",
        "value": round(m["tokens_per_sec"], 2),
        "unit": "tokens/sec",
        "replicas": m["replicas"],
        "completed": m["completed"],
        "errored": m["errored"],
        "rejected": m["rejected"],
        "shed": m["shed"],
        "replayed": m["replayed"],
        "redispatched": m["redispatched"],
        "dispatch_retries": m["dispatch_retries"],
        "replicas_lost": m["replicas_lost"],
        "detection_latency_s": (
            round(m["detection_latency_s"], 4)
            if m["detection_latency_s"] is not None else None
        ),
        "replay_token_exact": m["replay_token_exact"],
        # graft-swap roll summary: defaults (no controller) report a
        # fleet that never swapped — version v0, zero swaps, no blackout
        "weights_version": m.get("weights_version", "v0"),
        "swaps_completed": m.get("swaps_completed", 0),
        "swap_blackout_ms": (
            round(m["swap_blackout_ms"], 3)
            if m.get("swap_blackout_ms") is not None else None
        ),
        "replay_cross_version_exact": m["replay_cross_version_exact"],
        "queue_depth_max": m["queue_depth_max"],
        # graft-lens rolling latency summaries (ms over the run's window)
        "ttft_p99_ms": _round(m["ttft_p99_ms"], 3),
        "queue_wait_p99_ms": _round(m["queue_wait_p99_ms"], 3),
        "journal_lag_p99_ms": _round(m["journal_lag_p99_ms"], 3),
        "kv_occupancy_max": _round(m["kv_occupancy_max"], 4),
        "sentinel_triggers": [t["kind"] for t in m["sentinel_triggers"]],
        "generated_tokens": m["generated_tokens"],
        "elapsed_s": round(m["elapsed_s"], 3),
        "steady_per_row_ms": (
            round(m["steady_per_row_ms"], 3)
            if m["steady_per_row_ms"] is not None else None
        ),
        "steady_per_row_ms_min": (
            round(m["steady_per_row_ms_min"], 3)
            if m["steady_per_row_ms_min"] is not None else None
        ),
        "decode_tokens_per_sec": round(m["decode_tokens_per_sec"], 2),
        # fleet TPOT proxy: p99 of full-occupancy per-row boundary cost
        # across replicas (the router's steady-state samples)
        "tpot_p99_ms": (
            round(
                float(np.percentile(m["steady_samples_ms"], 99)), 3
            ) if m["steady_samples_ms"] else None
        ),
        "spec_accept_rate": (
            round(m["spec_accept_rate"], 4)
            if m["spec_accept_rate"] is not None else None
        ),
        "per_replica": {
            rep: {
                "state": stats["state"],
                "occupancy": round(stats["occupancy"], 4),
                "decode_steps": stats["decode_steps"],
                "finished": stats["finished"],
                **({"error": stats["error"]} if stats["error"] else {}),
            }
            for rep, stats in m["per_replica"].items()
        },
        "config": _config_dict(args),
    }
    if baseline is not None and baseline.get("steady_per_row_ms"):
        line["baseline_steady_per_row_ms"] = round(
            baseline["steady_per_row_ms"], 3
        )
        # ratio from the min statistic: host scheduling noise is one-
        # sided (it only adds time), so best-boundary cost compares the
        # machinery, not the box's mood during either pass
        if (
            m["steady_per_row_ms_min"] is not None
            and baseline.get("steady_per_row_ms_min")
        ):
            line["steady_state_ratio"] = round(
                m["steady_per_row_ms_min"]
                / baseline["steady_per_row_ms_min"], 3
            )
    print(json.dumps(line))
    return 0


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--family", default="gpt2",
                        choices=("gpt2", "llama"))
    parser.add_argument("--vocab-size", type=int, default=256)
    parser.add_argument("--max-len", type=int, default=128)
    parser.add_argument("--model-dim", type=int, default=64)
    parser.add_argument("--num-layers", type=int, default=2)
    parser.add_argument("--num-heads", type=int, default=4)
    parser.add_argument("--num-kv-heads", type=int, default=0,
                        help="llama GQA kv heads (0 = num-heads)")
    parser.add_argument("--slots", type=int, default=4,
                        help="decode batch rows (the fixed slot array)")
    parser.add_argument("--num-blocks", type=int, default=64,
                        help="KV pool blocks per layer (incl. scratch)")
    parser.add_argument("--block-size", type=int, default=8,
                        help="tokens per pool block")
    parser.add_argument("--max-blocks", type=int, default=16,
                        help="page-table width (max context / block size)")
    parser.add_argument("--requests", type=int, default=32)
    parser.add_argument("--rate", type=float, default=8.0,
                        help="Poisson arrival rate, req/s (0 = all at t=0)")
    parser.add_argument("--prompt-len", default="4:24", metavar="LO:HI",
                        help="uniform prompt-length range")
    parser.add_argument("--max-new", default="8:32", metavar="LO:HI",
                        help="uniform output-length range")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--temperature", type=float, default=1.0,
                        help="0 = greedy")
    parser.add_argument("--top-k", type=int, default=None)
    parser.add_argument("--top-p", type=float, default=None)
    parser.add_argument("--spec-tokens", type=int, default=0,
                        help="speculative decoding window K >= 2 (0 = "
                        "off): the model drafts for itself "
                        "(self-speculation), the verify step commits the "
                        "exact-match prefix — output stays bit-identical "
                        "to non-speculative decode at any temperature")
    parser.add_argument("--mode", default="continuous",
                        choices=("continuous", "static"),
                        help="static = classic wave batching (admit only "
                        "when every slot drained)")
    parser.add_argument("--mesh", default="",
                        help="serve sharded, e.g. data=2,fsdp=2,tensor=2 "
                        "(axes product must equal the device count)")
    parser.add_argument("--auto-mesh", action="store_true",
                        help="graft-plan: pick the serving mesh via the "
                        "static three-tier oracle (prefill and decode "
                        "scored separately, best summed cost wins); "
                        "replaces --mesh. DPX_HBM_LIMIT gates would-OOM "
                        "plans pre-compile")
    parser.add_argument("--trace", default=None, metavar="PATH",
                        help="write per-request Chrome trace spans here")
    parser.add_argument("--metrics-snapshot", default=None, metavar="PATH",
                        help="graft-lens: dump the full rolling-histogram "
                        "summary (p50/p99/max per latency metric, sentinel "
                        "triggers) as JSON here")
    parser.add_argument("--replicas", type=int, default=1,
                        help="graft-fleet: serve through N engine replicas "
                        "behind the failover router")
    parser.add_argument("--sessions", type=int, default=0,
                        help="tag requests with K round-robin session ids "
                        "(fleet placement is session-affine; 0 = none)")
    parser.add_argument("--chaos", default="",
                        help="fault-injection preset name or JSON plan "
                        "(same contract as train.py; e.g. kill-replica)")
    parser.add_argument("--publish-dir", default="", metavar="DIR",
                        help="graft-swap: poll this publish channel "
                        "(robustness/publish.py) and hot-swap newly "
                        "committed weight versions through the fleet's "
                        "drain/install/readmit roll plane (fleet mode "
                        "only: needs --replicas >= 2)")
    parser.add_argument("--swap-poll-s", type=float, default=None,
                        help="graft-swap: publish-channel poll interval "
                        "in seconds (default 0.25; needs --publish-dir)")
    parser.add_argument("--heartbeat-timeout", type=float, default=5.0,
                        help="fleet: seconds without a replica heartbeat "
                        "before the router declares it lost")
    parser.add_argument("--queue-cap", type=int, default=64,
                        help="fleet: router queue bound (overflow sheds)")
    parser.add_argument("--queue-deadline", type=float, default=30.0,
                        help="fleet: shed requests queued longer than this")
    args = parser.parse_args()
    if args.requests < 1:
        parser.error("--requests must be >= 1")
    if args.replicas < 1:
        parser.error("--replicas must be >= 1")
    if args.max_blocks * args.block_size > args.max_len:
        parser.error("--max-blocks * --block-size must be <= --max-len")
    if args.spec_tokens and args.spec_tokens < 2:
        parser.error("--spec-tokens must be 0 (off) or >= 2")
    if args.auto_mesh and args.mesh:
        parser.error("--auto-mesh replaces --mesh; drop one")
    if args.swap_poll_s is not None and not args.publish_dir:
        parser.error("--swap-poll-s needs --publish-dir; add the channel "
                     "or drop the interval")
    if args.publish_dir and args.replicas < 2:
        parser.error("--publish-dir (graft-swap) rolls through the fleet "
                     "router; use --replicas >= 2")
    if args.swap_poll_s is not None and args.swap_poll_s <= 0:
        parser.error("--swap-poll-s must be > 0")

    from distributed_pytorch_example_tpu.telemetry.trace import TraceWriter

    if args.chaos and args.replicas == 1:
        # train.py contract: the plan is live before the engine exists
        from distributed_pytorch_example_tpu.robustness import chaos

        chaos.install(parse_chaos(args.chaos))

    trace = TraceWriter(args.trace)
    built = build_model(args)
    requests = build_requests(args)
    import jax

    print(
        f"serve: {args.family} on {len(jax.devices())} "
        f"{jax.devices()[0].platform} device(s), {args.requests} requests, "
        f"rate={args.rate}/s, mode={args.mode}, slots={args.slots}, "
        f"pool={args.num_blocks}x{args.block_size}, "
        f"replicas={args.replicas}"
        + (f", chaos={args.chaos}" if args.chaos else ""),
        file=sys.stderr,
    )
    if args.replicas > 1:
        report, baseline = run_fleet(args, trace, built, requests)
        trace.close()
        if args.metrics_snapshot:
            write_metrics_snapshot(
                args.metrics_snapshot, report["metrics"],
                _config_dict(args),
            )
        return emit_fleet_line(args, report, baseline)

    engine = build_engines(args, trace, built, 1)[0]
    report = engine.run(requests)
    trace.close()
    if args.metrics_snapshot:
        write_metrics_snapshot(
            args.metrics_snapshot, report["metrics"], _config_dict(args)
        )
    for rid, r in sorted(report["results"].items()):
        print(json.dumps({
            "rid": rid, "status": r["status"],
            "prompt_len": r["prompt_len"], "new_tokens": len(r["tokens"]),
            "ttft_s": r["ttft_s"], "preemptions": r["preemptions"],
            **({"error": r["error"]} if r["error"] else {}),
        }), file=sys.stderr)

    m = report["metrics"]
    line = {
        "metric": "serve_tokens_per_sec",
        "value": round(m["tokens_per_sec"], 2),
        "unit": "tokens/sec",
        "ttft_ms": m["ttft_ms"],
        "tpot_ms": m["tpot_ms"],
        "queue_wait_ms": m["queue_wait_ms"],
        "ttft_p99_ms": m["ttft_ms"]["p99"],
        "tpot_p99_ms": m["tpot_ms"]["p99"],
        "queue_wait_p99_ms": m["queue_wait_ms"]["p99"],
        "decode_tokens_per_sec": round(m["decode_tokens_per_sec"], 2),
        "spec_accept_rate": (
            round(m["spec_accept_rate"], 4)
            if m["spec_accept_rate"] is not None else None
        ),
        "slot_occupancy": round(m["slot_occupancy"], 4),
        "decode_steps": m["decode_steps"],
        "generated_tokens": m["generated_tokens"],
        "elapsed_s": round(m["elapsed_s"], 3),
        "admitted": m["admitted"],
        "completed": m["completed"],
        "errored": m["errored"],
        "rejected": m["rejected"],
        "preempted": m["preempted"],
        "config": _config_dict(args),
    }
    print(json.dumps(line))
    return 0


if __name__ == "__main__":
    sys.exit(main())

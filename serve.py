#!/usr/bin/env python3
"""graft-serve driver: seeded open-loop load over the paged-KV engine.

Spins up an :class:`InferenceEngine` (paged KV cache + continuous
batching, ``distributed_pytorch_example_tpu/serving/``) on a randomly
initialized GPT-2/LLaMA of CLI-chosen size and drives it with a seeded
Poisson open-loop workload of mixed prompt/output lengths — the standard
serving-benchmark shape: requests arrive on their own schedule whether or
not the server is keeping up.

Driver contract (same as bench.py): stdout gets exactly ONE JSON line —
TTFT and per-output-token latency p50/p95/p99, tokens/sec, slot
occupancy, preempted/rejected counts, config. Per-request detail lines
go to stderr as requests finish.

Run it on the fake CPU mesh (no TPU needed)::

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
      python serve.py --requests 16 --rate 4 --mesh data=2,fsdp=2,tensor=2

``--mesh`` serves sharded exactly like ``generate(partitioner=...)``:
TP-partitioned weights stay sharded, the KV pool shards kv-heads over
``tensor`` and pool blocks over the data axes.
"""

from __future__ import annotations

import argparse
import json
import sys


def _parse_range(spec: str, flag: str):
    try:
        lo, hi = (int(x) for x in spec.split(":"))
    except ValueError:
        raise SystemExit(f"{flag} wants LO:HI, got {spec!r}")
    if lo < 1 or hi < lo:
        raise SystemExit(f"{flag} wants 1 <= LO <= HI, got {spec!r}")
    return lo, hi


def build_requests(args):
    """The seeded workload: Poisson arrivals, uniform mixed lengths."""
    import numpy as np

    from distributed_pytorch_example_tpu.serving import Request

    rng = np.random.default_rng(args.seed)
    plo, phi = _parse_range(args.prompt_len, "--prompt-len")
    olo, ohi = _parse_range(args.max_new, "--max-new")
    arrivals = (
        np.cumsum(rng.exponential(1.0 / args.rate, args.requests))
        if args.rate > 0 else np.zeros(args.requests)
    )
    reqs = []
    for i in range(args.requests):
        plen = int(rng.integers(plo, phi + 1))
        reqs.append(Request(
            rid=f"req{i:04d}",
            prompt=[int(t) for t in rng.integers(0, args.vocab_size, plen)],
            max_new_tokens=int(rng.integers(olo, ohi + 1)),
            seed=args.seed * 100_003 + i,
            arrival=float(arrivals[i]),
        ))
    return reqs


def build_engine(args, trace):
    import jax
    import jax.numpy as jnp

    paged = dict(
        paged_num_blocks=args.num_blocks,
        paged_block_size=args.block_size,
        paged_max_blocks=args.max_blocks,
    )
    kw = dict(
        vocab_size=args.vocab_size, max_len=args.max_len,
        model_dim=args.model_dim, num_layers=args.num_layers,
        num_heads=args.num_heads, mlp_dim=2 * args.model_dim,
    )
    if args.family == "llama":
        from distributed_pytorch_example_tpu.models.llama import Llama as M

        kw["num_kv_heads"] = args.num_kv_heads or args.num_heads
    else:
        from distributed_pytorch_example_tpu.models.gpt2 import GPT2 as M

    model = M(**kw, decode=True, **paged)
    # random-init params: this driver exercises serving (scheduling,
    # latency, isolation), not text quality; a trained checkpoint's params
    # drop in unchanged (same tree as the training model)
    params = M(**kw).init(
        jax.random.key(args.seed), jnp.zeros((1, 8), jnp.int32)
    )["params"]

    partitioner = None
    if args.mesh:
        from distributed_pytorch_example_tpu.parallel.partition import (
            transformer_partitioner,
        )
        from distributed_pytorch_example_tpu.runtime import (
            MeshSpec, make_mesh,
        )

        axes = dict(
            (k, int(v)) for k, v in
            (kv.split("=") for kv in args.mesh.split(","))
        )
        partitioner = transformer_partitioner(make_mesh(MeshSpec(**axes)))

    from distributed_pytorch_example_tpu.serving import InferenceEngine

    return InferenceEngine(
        model, params, num_slots=args.slots, temperature=args.temperature,
        top_k=args.top_k, top_p=args.top_p, partitioner=partitioner,
        trace=trace, mode=args.mode,
    )


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--family", default="gpt2",
                        choices=("gpt2", "llama"))
    parser.add_argument("--vocab-size", type=int, default=256)
    parser.add_argument("--max-len", type=int, default=128)
    parser.add_argument("--model-dim", type=int, default=64)
    parser.add_argument("--num-layers", type=int, default=2)
    parser.add_argument("--num-heads", type=int, default=4)
    parser.add_argument("--num-kv-heads", type=int, default=0,
                        help="llama GQA kv heads (0 = num-heads)")
    parser.add_argument("--slots", type=int, default=4,
                        help="decode batch rows (the fixed slot array)")
    parser.add_argument("--num-blocks", type=int, default=64,
                        help="KV pool blocks per layer (incl. scratch)")
    parser.add_argument("--block-size", type=int, default=8,
                        help="tokens per pool block")
    parser.add_argument("--max-blocks", type=int, default=16,
                        help="page-table width (max context / block size)")
    parser.add_argument("--requests", type=int, default=32)
    parser.add_argument("--rate", type=float, default=8.0,
                        help="Poisson arrival rate, req/s (0 = all at t=0)")
    parser.add_argument("--prompt-len", default="4:24", metavar="LO:HI",
                        help="uniform prompt-length range")
    parser.add_argument("--max-new", default="8:32", metavar="LO:HI",
                        help="uniform output-length range")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--temperature", type=float, default=1.0,
                        help="0 = greedy")
    parser.add_argument("--top-k", type=int, default=None)
    parser.add_argument("--top-p", type=float, default=None)
    parser.add_argument("--mode", default="continuous",
                        choices=("continuous", "static"),
                        help="static = classic wave batching (admit only "
                        "when every slot drained)")
    parser.add_argument("--mesh", default="",
                        help="serve sharded, e.g. data=2,fsdp=2,tensor=2 "
                        "(axes product must equal the device count)")
    parser.add_argument("--trace", default=None, metavar="PATH",
                        help="write per-request Chrome trace spans here")
    args = parser.parse_args()
    if args.requests < 1:
        parser.error("--requests must be >= 1")
    if args.max_blocks * args.block_size > args.max_len:
        parser.error("--max-blocks * --block-size must be <= --max-len")

    from distributed_pytorch_example_tpu.telemetry.trace import TraceWriter

    trace = TraceWriter(args.trace)
    engine = build_engine(args, trace)
    requests = build_requests(args)
    import jax

    print(
        f"serve: {args.family} on {len(jax.devices())} "
        f"{jax.devices()[0].platform} device(s), {args.requests} requests, "
        f"rate={args.rate}/s, mode={args.mode}, slots={args.slots}, "
        f"pool={args.num_blocks}x{args.block_size}",
        file=sys.stderr,
    )
    report = engine.run(requests)
    trace.close()
    for rid, r in sorted(report["results"].items()):
        print(json.dumps({
            "rid": rid, "status": r["status"],
            "prompt_len": r["prompt_len"], "new_tokens": len(r["tokens"]),
            "ttft_s": r["ttft_s"], "preemptions": r["preemptions"],
            **({"error": r["error"]} if r["error"] else {}),
        }), file=sys.stderr)

    m = report["metrics"]
    line = {
        "metric": "serve_tokens_per_sec",
        "value": round(m["tokens_per_sec"], 2),
        "unit": "tokens/sec",
        "ttft_ms": m["ttft_ms"],
        "tpot_ms": m["tpot_ms"],
        "slot_occupancy": round(m["slot_occupancy"], 4),
        "decode_steps": m["decode_steps"],
        "generated_tokens": m["generated_tokens"],
        "elapsed_s": round(m["elapsed_s"], 3),
        "admitted": m["admitted"],
        "completed": m["completed"],
        "errored": m["errored"],
        "rejected": m["rejected"],
        "preempted": m["preempted"],
        "config": {
            "family": args.family, "requests": args.requests,
            "rate": args.rate, "mode": args.mode, "slots": args.slots,
            "num_blocks": args.num_blocks, "block_size": args.block_size,
            "max_blocks": args.max_blocks,
            "prompt_len": args.prompt_len, "max_new": args.max_new,
            "temperature": args.temperature, "top_k": args.top_k,
            "top_p": args.top_p, "seed": args.seed,
            **({"mesh": args.mesh} if args.mesh else {}),
        },
    }
    print(json.dumps(line))
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python3
"""Benchmark harness: every BASELINE.json config, with MFU.

Default run covers all five BASELINE.json workloads (ResNet-18/CIFAR,
ResNet-50/ImageNet, ViT-B/16, BERT-base MLM, GPT-2 124M) on synthetic
data. One JSON line per model goes to stderr as it completes; stdout gets
exactly ONE JSON line — the driver metric (ResNet-50 samples/sec/chip,
matching BASELINE.json) with every other model's numbers embedded under
``"models"``.

MFU (model FLOPs utilization) comes from XLA's own cost analysis of the
compiled train step (forward + backward + optimizer), divided by measured
step rate x the chip's peak bf16 FLOP/s — so "fast" is judged against the
hardware ceiling, not just a baseline anchor. NB: XLA counts Pallas
custom calls (the flash-attention kernels) as ZERO FLOPs, so LM MFU here
is CONSERVATIVE — at seq 1024 the uncounted attention FLOPs are ~8% of
the GPT-2 step (scripts/bench_longctx.py reports the analytic accounting
where the attention share grows large).

Anchors in ``BASELINES``: 60% of published torch-xla-order rates (the
BASELINE.json north star); order-of-magnitude GUESSES, not measurements —
the reference publishes no numbers (BASELINE.md). ``vs_baseline`` is kept
for the driver's line format but demoted: the stdout line carries a
``vs_baseline_note`` saying so, and MFU/HFU (XLA cost analysis of the
compiled step / chip peak bf16) is the honest utilization metric.

Usage: python bench.py [--models resnet50,gpt2,...] [--model resnet50]
                       [--batch-per-chip N] [--steps N]
"""

from __future__ import annotations

import argparse
import json
import sys
import time

# vs_baseline anchors: 60% of published torch-xla-order throughput per chip
BASELINES = {
    "resnet18": ("samples", 6_000.0),   # CIFAR-size images
    "resnet50": ("samples", 600.0),     # BASELINE.json north-star metric
    "vit-b16": ("samples", 500.0),
    "bert-base": ("tokens", 30_000.0),
    "gpt2": ("tokens", 30_000.0),
    # beyond-BASELINE zoo entry (RMSNorm/RoPE/GQA/SwiGLU, ~110M); not in
    # the default sweep — `--model llama` benches it
    "llama": ("tokens", 30_000.0),
}
DEFAULT_MODELS = ("resnet18", "resnet50", "vit-b16", "bert-base", "gpt2")

# peak-FLOPs table and the compiled cost/memory accounting now live in
# telemetry/cost.py (graft-scope's compile-time cost registry); bench
# consumes the same record the Trainer registers at each compile


def _chaos_scenario(scenario, step, state, batch, step_time_s, args) -> dict:
    """Post-timing fault-injection demo (graft-armor, --chaos).

    Runs AFTER the timed window so the headline rate is untouched, and
    drives the SAME compiled executable through the fault — the report's
    ``steady_state_ratio`` (post-fault step time / timed-window step time)
    is the in-bench evidence that recovery costs nothing at steady state
    and triggers no recompile.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributed_pytorch_example_tpu.robustness import chaos

    report: dict = {"scenario": scenario}
    if scenario == "nan-step":
        if not any(
            jnp.issubdtype(v.dtype, jnp.floating) for v in batch.values()
        ):
            # LM batches are integer tokens; a NaN can't ride them in
            report["skipped"] = "no float input leaf (token-only batch)"
            return report
        chaos.install(chaos.ChaosPlan(
            faults=[chaos.Fault("nan-batch", step=0)]
        ))
        try:
            poisoned = chaos.corrupt_batch(batch, 0)
        finally:
            chaos.uninstall()
        # snapshot BEFORE the call: the compiled step donates its input
        # state, so the pre-step buffers are gone once it runs
        before = np.asarray(jax.tree_util.tree_leaves(state.params)[0])
        bad_state, metrics = step(state, poisoned)
        report["bad_step"] = float(metrics["bad_step"])
        after = np.asarray(jax.tree_util.tree_leaves(bad_state.params)[0])
        report["params_frozen"] = bool(np.array_equal(before, after))
        clean_state, metrics = step(bad_state, batch)
        report["loss_finite_after"] = bool(
            np.isfinite(float(metrics["loss"]))
        )
        n = max(args.steps // 4, 4)
        t0 = time.perf_counter()
        for _ in range(n):
            clean_state, metrics = step(clean_state, batch)
        float(metrics["loss"])
        report["steady_state_ratio"] = round(
            (time.perf_counter() - t0) / n / step_time_s, 4
        )
    elif scenario == "io-flake":
        import os
        import tempfile

        from distributed_pytorch_example_tpu.train import (
            checkpoint as ckpt_lib,
        )

        chaos.install(chaos.ChaosPlan(
            faults=[chaos.Fault("io-error", path_substr="latest", count=2)]
        ))
        saver = ckpt_lib.AsyncSaver()
        try:
            with tempfile.TemporaryDirectory() as td:
                path = os.path.join(td, "latest_model.ckpt")
                ckpt_lib.save_checkpoint(
                    path, state, epoch=0, loss=0.0, saver=saver
                )
                saver.wait()
                report["checkpoint_written"] = os.path.exists(path)
        finally:
            chaos.uninstall()
        report["io_retries_used"] = saver.io_retries_used
    return report


def _input_plane_probe(batch_np, global_batch, mesh, step_time_s) -> dict:
    """Post-timing graft-intake probe: data_stall_ms / input_stall_frac.

    The timed loop drives a FIXED pre-built device batch (so the headline
    rate measures the step, not the host). This probe runs the real input
    plane once — a DeviceLoader prefetching over an in-memory dataset —
    while the consumer sleeps the measured step time between fetches,
    i.e. the loader sees the same demand pattern training would apply.
    The counters come from the supervised prefetch worker: ms spent on an
    empty queue, and the fraction of fetches that stalled at all.
    """
    import numpy as np

    import distributed_pytorch_example_tpu as dpx

    class _Mem:
        def __init__(self, arrays, n):
            self.arrays, self.n = arrays, n

        def __len__(self):
            return self.n

        def get_batch(self, indices):
            idx = np.asarray(indices) % len(next(iter(self.arrays.values())))
            return {k: v[idx] for k, v in self.arrays.items()}

    steps = 8
    loader = dpx.data.DeviceLoader(
        _Mem(batch_np, global_batch * steps), global_batch, mesh=mesh,
        shuffle=False, prefetch=2, num_shards=1, shard_id=0,
    )
    # cap the simulated compute so the probe stays sub-second even for
    # slow models; the stall FRACTION is what the cap can bias (a shorter
    # sleep under-feeds the prefetcher), never the headline rate
    pause = min(step_time_s, 0.1)
    for _ in loader:
        time.sleep(pause)
    served = max(loader.batches_served, 1)
    return {
        "data_stall_ms": round(loader.data_stall_ms, 3),
        "input_stall_frac": round(loader.stalled_batches / served, 4),
    }


def _shard_cache_probe(cache_mb, mesh, step_time_s) -> dict:
    """Post-timing graft-intake shard-cache probe (--shard-cache-mb).

    Writes a small sealed shard dataset to a temp dir, pins the memmap
    pool far below the shard count (so every epoch would re-touch the
    disk), injects a ``slow-shard-io`` fault at the ``chaos.shard_read``
    site, and drives two epochs of the real input plane. Epoch 1 decodes
    from (slow) disk and stalls; epoch 2 serves every row from the
    in-memory ShardCache — cache hits skip the chaos site along with the
    disk — so its stall fraction collapsing to ~0 is the cache working,
    measured end to end through the supervised prefetch worker.
    """
    import tempfile

    import numpy as np

    import distributed_pytorch_example_tpu as dpx
    from distributed_pytorch_example_tpu.data import streaming
    from distributed_pytorch_example_tpu.robustness import chaos

    rng = np.random.default_rng(0)
    shards, rows, hw, batch = 6, 64, 16, 32
    with tempfile.TemporaryDirectory() as td:
        streaming.write_image_shards(
            td,
            [(rng.integers(0, 256, (rows, hw, hw, 3)).astype(np.uint8),
              rng.integers(0, 10, (rows,)).astype(np.int64))
             for _ in range(shards)],
            shard_size=rows, seal=True,
        )
        ds = streaming.StreamingImageShards(
            td, raw_uint8=True, max_open_shards=2, cache_mb=cache_mb
        )
        chaos.install(chaos.ChaosPlan(faults=[chaos.Fault(
            "slow-shard-io", path_substr="images_",
            count=10_000, delay_s=0.05,
        )]))
        try:
            fracs = []
            for _epoch in range(2):
                loader = dpx.data.DeviceLoader(
                    ds, batch, mesh=mesh, shuffle=False, prefetch=2,
                    num_shards=1, shard_id=0,
                )
                for _ in loader:
                    time.sleep(min(step_time_s, 0.02))
                served = max(loader.batches_served, 1)
                fracs.append(round(loader.stalled_batches / served, 4))
        finally:
            chaos.uninstall()
    report = {
        "input_stall_frac_epoch1": fracs[0],
        "input_stall_frac_epoch2": fracs[1],
    }
    stats = ds.cache_stats
    if stats:
        report.update(stats)
    return report


def run_serve(args) -> dict:
    """--serve: fixed seeded 32-request replay through the paged-KV
    engine (graft-serve), continuous vs static batching.

    The replay is deterministic (seeded lengths, all arrivals at t=0), so
    round-over-round numbers compare the engine, not the workload. Both
    modes run the SAME two compiled programs; the headline metric is
    continuous-batching tokens/sec/chip, with the static-mode rate and
    the continuous/static margin embedded — the margin is the in-bench
    evidence that in-flight insertion actually buys throughput on a
    mixed-length workload.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributed_pytorch_example_tpu.models.gpt2 import GPT2
    from distributed_pytorch_example_tpu.serving import (
        InferenceEngine, Request,
    )

    kw = dict(vocab_size=256, max_len=128, model_dim=64, num_layers=2,
              num_heads=4, mlp_dim=128)
    pool = dict(paged_num_blocks=128, paged_block_size=8,
                paged_max_blocks=16)
    slots, n_requests = 4, 32
    params = GPT2(**kw).init(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    model = GPT2(**kw, decode=True, **pool)
    n_chips = len(jax.devices())
    print(
        f"bench: serve on {n_chips} {jax.devices()[0].platform} device(s), "
        f"{n_requests} requests, {slots} slots",
        file=sys.stderr,
    )

    rng = np.random.default_rng(0)
    requests = [
        Request(
            rid=f"req{i:03d}",
            prompt=[int(t) for t in rng.integers(
                0, 256, int(rng.integers(4, 25))
            )],
            max_new_tokens=int(rng.integers(8, 33)),
            seed=i,
        )
        for i in range(n_requests)
    ]
    engine = InferenceEngine(
        model, params, num_slots=slots, temperature=1.0, top_k=40,
    )
    # untimed warmup replay compiles the two programs (and the per-bucket
    # prefill variants); the timed replays then measure steady state
    engine.run(requests)
    cont_full = engine.run(requests, mode="continuous")
    cont = cont_full["metrics"]
    stat = engine.run(requests, mode="static")["metrics"]

    # speculative before/after at GREEDY (the config speculation serves
    # in practice: an argmax draft against a temperature-1.0 target
    # accepts ~1% of proposals, so the sampled workload above is the
    # wrong yardstick). Self-speculation + exact-match acceptance keeps
    # the greedy output bit-identical to the plain greedy replay
    # (checked below); the accept rate is ~1.0, shy of it only where a
    # request's final window truncates at its token ceiling.
    greedy_engine = InferenceEngine(
        model, params, num_slots=slots, temperature=0.0,
    )
    greedy_engine.run(requests)  # untimed: compiles the greedy programs
    greedy_full = greedy_engine.run(requests, mode="continuous")
    spec_engine = InferenceEngine(
        model, params, num_slots=slots, temperature=0.0,
        draft_model=model, draft_params=params, spec_tokens=4,
    )
    spec_engine.run(requests)  # untimed: compiles propose/verify
    spec_full = spec_engine.run(requests, mode="continuous")
    spec = spec_full["metrics"]
    spec_exact = all(
        spec_full["results"][r.rid]["tokens"]
        == greedy_full["results"][r.rid]["tokens"]
        for r in requests
    )

    fleet = None
    if getattr(args, "replicas", 1) > 1:
        # graft-fleet replay: the SAME workload through N replicas behind
        # the failover router; position-folded rng means the fleet output
        # must be bit-identical to the single-engine run above
        from distributed_pytorch_example_tpu.serving import (
            FleetRouter, ReplicaHandle,
        )

        engines = [
            InferenceEngine(
                model, params, num_slots=slots, temperature=1.0, top_k=40,
            )
            for _ in range(args.replicas)
        ]
        handles = [
            ReplicaHandle(f"r{i}", e) for i, e in enumerate(engines)
        ]
        frep = FleetRouter(handles).run(requests)
        fm = frep["metrics"]
        exact = all(
            frep["results"][r.rid]["tokens"]
            == cont_full["results"][r.rid]["tokens"]
            for r in requests
        )
        fleet = {
            "replicas": args.replicas,
            "tokens_per_sec_per_chip": round(
                fm["tokens_per_sec"] / n_chips, 2
            ),
            "completed": fm["completed"],
            "token_exact_vs_single_engine": exact,
            # graft-swap roll summary (serve.py --publish-dir wires a
            # live controller; this replay runs none, so the defaults
            # report a fleet that never swapped)
            "weights_version": fm.get("weights_version", "v0"),
            "swaps_completed": fm.get("swaps_completed", 0),
            "swap_blackout_ms": (
                round(fm["swap_blackout_ms"], 3)
                if fm.get("swap_blackout_ms") is not None else None
            ),
            "replay_cross_version_exact": fm["replay_cross_version_exact"],
            "steady_per_row_ms": (
                round(fm["steady_per_row_ms"], 3)
                if fm["steady_per_row_ms"] is not None else None
            ),
            "per_replica_occupancy": {
                rep: round(stats["occupancy"], 4)
                for rep, stats in fm["per_replica"].items()
            },
        }

    rate = cont["tokens_per_sec"] / n_chips
    result = {
        "metric": "serve_tokens_per_sec_per_chip",
        "value": round(rate, 2),
        "unit": "tokens/sec/chip",
        "ttft_ms_p50": round(cont["ttft_ms"]["p50"], 3),
        "ttft_ms_p95": round(cont["ttft_ms"]["p95"], 3),
        "tpot_ms_p50": round(cont["tpot_ms"]["p50"], 3),
        "tpot_p99_ms": round(cont["tpot_ms"]["p99"], 3),
        "decode_tokens_per_sec": round(cont["decode_tokens_per_sec"], 2),
        "spec_accept_rate": (
            round(spec["spec_accept_rate"], 4)
            if spec["spec_accept_rate"] is not None else None
        ),
        "spec": {
            "spec_tokens": 4,
            "temperature": 0.0,
            "decode_tokens_per_sec": round(
                spec["decode_tokens_per_sec"], 2
            ),
            "speedup_vs_greedy_decode": (
                round(
                    spec["decode_tokens_per_sec"]
                    / greedy_full["metrics"]["decode_tokens_per_sec"], 3
                ) if greedy_full["metrics"]["decode_tokens_per_sec"]
                else None
            ),
            "token_exact_vs_greedy": spec_exact,
        },
        "slot_occupancy": round(cont["slot_occupancy"], 4),
        "static_tokens_per_sec_per_chip": round(
            stat["tokens_per_sec"] / n_chips, 2
        ),
        "continuous_vs_static": round(
            cont["tokens_per_sec"] / stat["tokens_per_sec"], 3
        ),
        "decode_steps": {
            "continuous": cont["decode_steps"],
            "static": stat["decode_steps"],
        },
        "completed": cont["completed"],
        **({"fleet": fleet} if fleet is not None else {}),
        "config": {
            "requests": n_requests, "slots": slots,
            "num_blocks": pool["paged_num_blocks"],
            "block_size": pool["paged_block_size"],
            "max_blocks": pool["paged_max_blocks"],
            "prompt_len": "4:24", "max_new": "8:32",
            "temperature": 1.0, "top_k": 40, "seed": 0,
        },
    }
    print(json.dumps(result), file=sys.stderr)
    return result


def run_model(name: str, args) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    import distributed_pytorch_example_tpu as dpx

    lm = name.startswith(("gpt", "bert", "llama"))
    batch_per_chip = args.batch_per_chip or (16 if lm else 128)
    if name == "resnet18":
        image_size, num_classes = 32, 10  # BASELINE config 1: CIFAR-10
        batch_per_chip = args.batch_per_chip or 256
    else:
        image_size, num_classes = args.image_size, 1000

    n_chips = len(jax.devices())
    print(
        f"bench: {name} on {n_chips} {jax.devices()[0].platform} device(s), "
        f"batch/chip={batch_per_chip}",
        file=sys.stderr,
    )

    pipelined = args.mesh_pipe > 1
    if pipelined:
        if not name.startswith(("gpt", "llama")):
            raise ValueError(
                f"--mesh-pipe applies to gpt2/llama only, not {name!r}"
            )
        mesh = dpx.runtime.make_mesh(
            dpx.runtime.MeshSpec(
                data=n_chips // args.mesh_pipe, pipe=args.mesh_pipe
            )
        )
        from distributed_pytorch_example_tpu.parallel.partition import (
            transformer_partitioner,
        )

        partitioner = transformer_partitioner(mesh)
    else:
        mesh = dpx.runtime.make_mesh()
        partitioner = dpx.parallel.data_parallel(
            mesh, dp_shard_opt_state=args.zero1
        )
    # graft-wire: compress the gradient collectives (parallel/wire.py);
    # --overlap-buckets additionally opts the sync into the bucketed
    # comm/compute-overlap schedule (-1 = the 4 MiB default target)
    from distributed_pytorch_example_tpu.parallel.wire import (
        DEFAULT_BUCKET_BYTES,
    )

    bucket_bytes = (
        DEFAULT_BUCKET_BYTES if args.overlap_buckets < 0
        else args.overlap_buckets
    )
    partitioner.wire = dpx.parallel.WireConfig(
        compress=args.wire, block_size=args.wire_block,
        bucket_bytes=bucket_bytes,
    )
    global_batch = batch_per_chip * n_chips
    if batch_per_chip % args.grad_accum:
        raise ValueError(
            f"--grad-accum {args.grad_accum} must divide the per-chip "
            f"batch ({batch_per_chip} for {name}; set --batch-per-chip)"
        )
    rng = np.random.default_rng(0)
    if lm:
        flags_apply = True
        overrides = {"dtype": jnp.bfloat16}
        if args.lm_loss == "fused":
            # fused chunked-CE: hidden states out, vocab-blockwise loss
            overrides["logits_mode"] = "hidden"
        if args.remat:
            overrides["remat"] = True
        if args.flash != "auto":
            overrides["use_flash"] = args.flash == "on"
        if pipelined:
            # pipeline-schedule ablation: gpipe vs 1f1b (recompute) vs
            # 1f1b --pipe-no-recompute (stash) on the same mesh
            overrides["pipe_axis"] = "pipe"
            overrides["pipe_schedule"] = args.pipe_schedule
            overrides["pipe_microbatches"] = args.pipe_microbatches
            if args.pipe_no_recompute:
                overrides["pipe_recompute"] = False
        model = dpx.models.get_model(name, **overrides)
        seq_len = min(args.seq_len, model.max_len)  # BERT caps at 512
        if seq_len != args.seq_len:
            print(
                f"bench: clamping seq-len {args.seq_len} -> {seq_len} "
                f"({name} max_len)",
                file=sys.stderr,
            )
        if name.startswith("bert"):
            task = dpx.train.MLMTask(
                vocab_size=model.vocab_size, mask_token_id=103
            )
        else:
            task = dpx.train.CausalLMTask()
        batch_np = {
            "tokens": rng.integers(
                0, model.vocab_size, (global_batch, seq_len)
            ).astype(np.int32),
        }
    else:
        overrides = {"num_classes": num_classes, "dtype": jnp.bfloat16}
        if name == "vit-b16":
            # forward the ablation flags so --flash/--remat actually ablate
            # on the transformer vision model (VERDICT r3 weak #3: silently
            # ignoring them is how the r3 ViT regression went unnoticed)
            flags_apply = True
            if args.remat:
                overrides["remat"] = True
            if args.flash != "auto":
                overrides["use_flash"] = args.flash == "on"
        else:
            flags_apply = False
            if args.remat or args.flash != "auto":
                print(
                    f"bench: NOTE --flash/--remat do not apply to {name} "
                    f"(no attention / no remat knob); running the plain "
                    f"config",
                    file=sys.stderr,
                )
        model = dpx.models.get_model(name, **overrides)
        task = dpx.train.ClassificationTask()
        batch_np = {
            "x": rng.standard_normal(
                (global_batch, image_size, image_size, 3)
            ).astype(np.float32),
            "y": rng.integers(0, num_classes, (global_batch,)).astype(np.int32),
        }
    picked_plan = None
    if args.auto_mesh:
        # graft-plan: replace the flag-built mesh/partitioner with the
        # static oracle's pick (the batch shapes above are plan-neutral)
        if (
            pipelined or args.zero1 or args.wire != "none"
            or args.overlap_buckets
        ):
            raise ValueError(
                "--auto-mesh replaces --mesh-pipe/--zero1/--wire/"
                "--overlap-buckets; drop those flags"
            )
        from distributed_pytorch_example_tpu.analysis import (
            envelope,
            planner,
        )

        batch_abs = {
            k: jax.ShapeDtypeStruct(v.shape, v.dtype)
            for k, v in batch_np.items()
        }
        best, _ = planner.pick_train_plan(
            model, task, optax.adam(1e-3),
            batch_abs["tokens" if lm else "x"], batch_abs,
            kind="lm" if lm else "image",
            program=f"train/{name}",
            hbm_limit=envelope.hbm_limit_from_env(),
            wire_block=args.wire_block,
            log=lambda m: print(m, file=sys.stderr),
        )
        if best is None:
            raise ValueError(f"--auto-mesh: no feasible plan for {name}")
        picked_plan = best.plan.name()
        print(
            f"bench: --auto-mesh picked {best.plan.name()} "
            f"(tier {best.tier}, cost {best.cost_ms():.4f} ms)",
            file=sys.stderr,
        )
        mesh = dpx.runtime.make_mesh(best.plan.mesh)
        partitioner = best.plan.lower(mesh=mesh)
    trainer = dpx.train.Trainer(
        model, task, optax.adam(1e-3), partitioner=partitioner,
        grad_accum_steps=args.grad_accum,
    )
    sharding = partitioner.batch_sharding()
    batch = {
        k: jax.make_array_from_process_local_data(sharding, v)
        for k, v in batch_np.items()
    }

    with mesh:
        trainer.init(batch["tokens" if lm else "x"])
        # the ZeRO-1 observable: per-chip optimizer-state residency
        # (shrinks ~1/n_chips under --zero1 vs the replicated update)
        opt_bytes = dpx.train.opt_state_bytes_per_chip(
            trainer.state.opt_state
        )
        reshard_report = None
        if args.reshard_from:
            # graft-elastic: reload a (possibly other-mesh) checkpoint onto
            # THIS run's mesh and report the cost — reshard_ms is the full
            # reassemble + re-slice wall time, resume_gap_steps the
            # optimizer steps the restored cursor trails the newest
            # on-disk version by (None when unknowable)
            from distributed_pytorch_example_tpu.robustness import elastic
            from distributed_pytorch_example_tpu.train import (
                checkpoint as ckpt_lib,
            )

            t0 = time.perf_counter()
            restored, r_epoch, r_extra = ckpt_lib.load_checkpoint(
                args.reshard_from, trainer.state, trainer.state_shardings
            )
            # value fetch, not block_until_ready: only a real device->host
            # transfer reliably fences under the tunneled TPU platform
            np.asarray(jax.tree_util.tree_leaves(restored.params)[0])
            reshard_ms = (time.perf_counter() - t0) * 1000.0
            trainer.state = restored
            reshard_report = {
                "reshard_ms": round(reshard_ms, 3),
                "resume_gap_steps": elastic.resume_gap_steps(
                    args.reshard_from, r_epoch, r_extra
                ),
                "restored_epoch": r_epoch,
            }
        # AOT-compile once and drive the SAME executable for warmup and the
        # timed loop (a separate jit call would compile a second copy)
        step = trainer.train_step.lower(trainer.state, batch).compile()
        from distributed_pytorch_example_tpu.telemetry import (
            compiled_cost_record,
        )

        cost = compiled_cost_record(step, jax.devices()[0])
        flops_per_step = cost["flops_per_step_per_device"]
        if flops_per_step is None:
            print("bench: cost_analysis unavailable", file=sys.stderr)
        state = trainer.state
        for _ in range(args.warmup):
            state, metrics = step(state, batch)
        # NB: fetch a VALUE, not block_until_ready — under the tunneled
        # remote-TPU platform only a real device->host transfer reliably
        # fences the dispatched step chain
        float(metrics["loss"])

        t0 = time.perf_counter()
        for _ in range(args.steps):
            state, metrics = step(state, batch)
        float(metrics["loss"])
        elapsed = time.perf_counter() - t0

        chaos_report = (
            _chaos_scenario(
                args.chaos, step, state, batch, elapsed / args.steps, args
            )
            if args.chaos != "none"
            else None
        )

        try:
            intake_report = _input_plane_probe(
                batch_np, global_batch, mesh, elapsed / args.steps
            )
        except Exception as e:  # noqa: BLE001 - probe must not kill the run
            print(f"bench: input-plane probe failed: {e}", file=sys.stderr)
            intake_report = None

        cache_report = None
        if args.shard_cache_mb > 0:
            try:
                cache_report = _shard_cache_probe(
                    args.shard_cache_mb, mesh, elapsed / args.steps
                )
            except Exception as e:  # noqa: BLE001 - probe must not kill it
                print(
                    f"bench: shard-cache probe failed: {e}", file=sys.stderr
                )

        # graft-lens overlap accounting (post-timing probe, ROADMAP 5(c)):
        # a short XLA trace of the SAME compiled step, split into
        # collective vs compute self time — overlap_frac is the fraction
        # of collective time hidden behind compute. None when the profile
        # plugin or trace conversion is unavailable (e.g. plain CPU runs).
        overlap_report = None
        try:
            import tempfile

            from distributed_pytorch_example_tpu.telemetry import (
                measure_overlap,
            )

            def _overlap_steps(n, _s=[state]):
                for _ in range(n):
                    _s[0], m = step(_s[0], batch)
                float(m["loss"])  # value fetch fences the dispatch chain

            with tempfile.TemporaryDirectory() as td:
                overlap_report = measure_overlap(_overlap_steps, td)
        except Exception as e:  # noqa: BLE001 - probe must not kill the run
            print(f"bench: overlap probe failed: {e}", file=sys.stderr)
            overlap_report = None

    samples_per_sec = global_batch * args.steps / elapsed
    unit_kind, baseline = BASELINES[name]
    if unit_kind == "tokens":
        rate = samples_per_sec * seq_len / n_chips
        unit = "tokens/sec/chip"
    else:
        rate = samples_per_sec / n_chips
        unit = "samples/sec/chip"
    step_time_ms = elapsed / args.steps * 1000.0
    result = {
        "metric": f"{name.replace('-', '_')}_{unit_kind}_per_sec_per_chip",
        "value": round(rate, 2),
        "unit": unit,
        "vs_baseline": round(rate / baseline, 3),
        "opt_state_bytes_per_chip": opt_bytes,
        "step_time_ms": round(step_time_ms, 3),
        # graft-wire analytic accounting (parallel/wire.py
        # grad_wire_report): per-device gradient-sync payload bytes per
        # step and the fp32/compressed ratio (1.0 when --wire none)
        "grad_wire_bytes_per_step": (
            trainer.wire_report["grad_wire_bytes_per_step"]
            if trainer.wire_report else None
        ),
        "wire_compression_ratio": (
            trainer.wire_report["wire_compression_ratio"]
            if trainer.wire_report else None
        ),
        # compiler-reported HBM residency of the step (args+out+temps−alias;
        # telemetry/cost.py) — None when the backend can't answer
        "hbm_peak_bytes": cost["hbm_peak_bytes"],
        # self-describing config: round-over-round numbers are auditable
        # (VERDICT r3 weak #7 — r2->r3 batch/steps drift went unrecorded).
        # flash/remat appear only for models that CONSUMED the flags, so
        # the record describes the run, not the command line.
        "config": {
            "batch_per_chip": batch_per_chip,
            "steps": args.steps,
            "warmup": args.warmup,
            "grad_accum": args.grad_accum,
            "zero1": args.zero1,
            **(
                {"wire": args.wire, "wire_block": args.wire_block}
                if args.wire != "none"
                else {}
            ),
            **(
                {"overlap_buckets": bucket_bytes} if bucket_bytes else {}
            ),
            **(
                {"shard_cache_mb": args.shard_cache_mb}
                if args.shard_cache_mb
                else {}
            ),
            **(
                {"flash": args.flash, "remat": args.remat}
                if flags_apply
                else {}
            ),
            **(
                {"seq_len": seq_len, "lm_loss": args.lm_loss}
                if lm
                else {"image_size": image_size}
            ),
            **(
                {
                    "mesh_pipe": args.mesh_pipe,
                    "pipe_schedule": args.pipe_schedule,
                    "pipe_recompute": not args.pipe_no_recompute,
                }
                if pipelined
                else {}
            ),
            **({"chaos": args.chaos} if args.chaos != "none" else {}),
            **({"auto_mesh": picked_plan} if picked_plan else {}),
        },
    }
    # measured comm/compute overlap (None = probe unavailable); the
    # per-step split rides along when the probe ran
    result["overlap_frac"] = (
        overlap_report["overlap_frac"] if overlap_report else None
    )
    if overlap_report is not None:
        result["overlap"] = {
            k: (round(v, 3) if isinstance(v, float) else v)
            for k, v in overlap_report.items()
            if k != "overlap_frac"
        }
    # scheduler-level overlap estimate from the static bucket plan
    # (telemetry/overlap.py scheduled_overlap) — the CI-gateable stand-in
    # for overlap_frac on CPU where the HLO probe reports null; non-None
    # only when --overlap-buckets armed the bucketed sync
    result["overlap_frac_scheduled"] = (
        trainer.overlap_report["overlap_frac_scheduled"]
        if trainer.overlap_report else None
    )
    if trainer.overlap_report is not None:
        result["overlap_scheduled"] = {
            k: trainer.overlap_report[k]
            for k in (
                "num_buckets", "hideable_wire_bytes", "total_wire_bytes",
            )
        }
    if args.zero1:
        # measured HLO collective accounting of the SAME compiled step
        # (result-buffer proxy, analysis/collectives.py) — the committed
        # scaling curves (scripts/scaling_sweep.py) plot this against the
        # analytic graft-prove payload prediction above
        try:
            from distributed_pytorch_example_tpu.analysis.collectives import (
                parse_collectives,
            )

            result["hlo_collectives"] = parse_collectives(step.as_text())
        except Exception as e:  # noqa: BLE001 - accounting must not kill it
            print(f"bench: hlo collective parse failed: {e}", file=sys.stderr)
    if cache_report is not None:
        # graft-intake shard-cache evidence: epoch-2 stall collapse +
        # hit/eviction counters from the end-to-end probe
        result["shard_cache"] = cache_report
    if chaos_report is not None:
        result["chaos"] = chaos_report
    if intake_report is not None:
        # graft-intake input-plane health (post-timing probe, not the
        # timed window): consumer-side prefetch-queue stalls
        result.update(intake_report)
    if reshard_report is not None:
        result["reshard_ms"] = reshard_report["reshard_ms"]
        result["resume_gap_steps"] = reshard_report["resume_gap_steps"]
        result["restored_epoch"] = reshard_report["restored_epoch"]
        result["config"]["reshard_from"] = args.reshard_from
    peak = cost.get("peak_bf16_flops")
    if flops_per_step is not None and peak is not None:
        # cost_analysis is of the per-device partitioned executable, so
        # this is already per-chip utilization — no n_chips division.
        # Under --remat the executable's FLOPs include recomputation, so
        # the honest name is HFU (hardware), not MFU (model) — but only
        # when this model actually consumed the flag.
        steps_per_sec = args.steps / elapsed
        util = round(flops_per_step * steps_per_sec / peak, 4)
        result["hfu" if (args.remat and flags_apply) else "mfu"] = util
        result["flops_per_step_per_chip"] = flops_per_step
    # same quantity graft-scope logs per step (CostRegistry.mfu_analytic):
    # XLA-counted FLOPs / measured step time / peak bf16; null off-TPU
    result["mfu_analytic"] = (
        round(flops_per_step / (step_time_ms / 1000.0) / peak, 4)
        if flops_per_step is not None and peak is not None
        else None
    )
    print(
        f"bench: {name}: {elapsed:.2f}s for {args.steps} steps "
        f"({samples_per_sec:.1f} samples/s total)",
        file=sys.stderr,
    )
    print(json.dumps(result), file=sys.stderr)
    return result


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--model", default=None,
                        help="single model (overrides --models)")
    parser.add_argument("--models", default=",".join(DEFAULT_MODELS),
                        help="comma-separated; default: every BASELINE config")
    parser.add_argument("--image-size", type=int, default=224)
    parser.add_argument("--seq-len", type=int, default=1024)
    parser.add_argument("--batch-per-chip", type=int, default=None,
                        help="default: 128 (vision), 256 (resnet18), 16 (LM)")
    parser.add_argument("--warmup", type=int, default=8,
                        help="untimed steady-state steps before timing")
    parser.add_argument("--steps", type=int, default=40,
                        help="timed steps; short windows under-measure by "
                        "several MFU points over the tunneled device link")
    parser.add_argument("--remat", action="store_true",
                        help="rematerialized transformer blocks (LM models)")
    parser.add_argument("--flash", default="auto",
                        choices=("auto", "on", "off"),
                        help="Pallas flash attention (LM models)")
    parser.add_argument("--grad-accum", type=int, default=1,
                        help="microbatches accumulated inside the step "
                        "before ONE gradient collective (train/step.py)")
    parser.add_argument("--wire", default="none",
                        choices=("none", "int8-block"),
                        help="graft-wire gradient-collective compression "
                        "(int8 payloads + per-block bf16 scales; "
                        "parallel/wire.py)")
    parser.add_argument("--wire-block", type=int, default=256,
                        help="elements per bf16 scale block for "
                        "--wire int8-block")
    parser.add_argument("--overlap-buckets", type=int, default=0,
                        metavar="BYTES",
                        help="bucketed comm/compute overlap for the "
                        "gradient sync (parallel/wire.py sync_grads): "
                        "target bucket payload bytes; -1 = the 4 MiB "
                        "default, 0 = the inline per-leaf path")
    parser.add_argument("--shard-cache-mb", type=int, default=0,
                        metavar="MB",
                        help="arm the in-memory decoded-shard cache probe "
                        "(data/intake.py ShardCache): drives two epochs "
                        "of the real streaming input plane under a "
                        "slow-shard-io fault and records the epoch-2 "
                        "stall fraction collapsing to ~0")
    parser.add_argument("--auto-mesh", action="store_true",
                        help="graft-plan: pick mesh + partitioner per model "
                        "via the static three-tier oracle "
                        "(analysis/planner.py) instead of "
                        "--mesh-pipe/--zero1/--wire; DPX_HBM_LIMIT gates "
                        "would-OOM plans pre-compile")
    parser.add_argument("--zero1", action="store_true",
                        help="ZeRO-1: reduce-scatter grads, shard the "
                        "optimizer state over data, all-gather params")
    parser.add_argument("--lm-loss", default="fused",
                        choices=("fused", "dense"),
                        help="LM loss path: fused chunked-CE (default) or "
                        "dense materialized logits")
    parser.add_argument("--mesh-pipe", type=int, default=1,
                        help=">1: pipeline-parallel ablation over a "
                        "data x pipe mesh (gpt2/llama; needs that many "
                        "devices to divide the chip count)")
    parser.add_argument("--pipe-schedule", default="1f1b",
                        choices=("gpipe", "1f1b"),
                        help="schedule for the --mesh-pipe ablation")
    parser.add_argument("--pipe-microbatches", type=int, default=0,
                        help="microbatches for the --mesh-pipe ablation "
                        "(0 = auto)")
    parser.add_argument("--pipe-no-recompute", action="store_true",
                        help="1f1b activation-stash backward (no stage "
                        "replay) for the --mesh-pipe ablation")
    parser.add_argument("--reshard-from", default=None, metavar="CKPT",
                        help="load this checkpoint (either format, any "
                        "stamped mesh shape) onto the bench mesh before "
                        "timing (graft-elastic); records reshard_ms (full "
                        "reassemble + re-slice wall time) and "
                        "resume_gap_steps, and runs the timed loop from "
                        "the restored state")
    parser.add_argument("--serve", action="store_true",
                        help="serving bench instead of training: fixed "
                        "32-request replay through the paged-KV "
                        "continuous-batching engine (graft-serve); the "
                        "stdout line carries continuous tokens/sec/chip "
                        "plus TTFT percentiles and the continuous/static "
                        "margin")
    parser.add_argument("--replicas", type=int, default=1,
                        help="with --serve: additionally replay the same "
                        "workload through N fleet replicas behind the "
                        "failover router (graft-fleet) and report fleet "
                        "throughput + bit-exactness vs the single engine")
    parser.add_argument("--chaos", default="none",
                        choices=("none", "nan-step", "io-flake"),
                        help="post-timing fault-injection demo (graft-"
                        "armor): drive the same compiled step through a "
                        "NaN batch (update predicated out, no recompile) "
                        "or retried checkpoint I/O; adds a 'chaos' block "
                        "to the record without touching the headline rate")
    args = parser.parse_args()
    if args.serve:
        print(json.dumps(run_serve(args)))
        return
    if args.warmup < 1 or args.steps < 1:
        parser.error("--warmup and --steps must be >= 1")
    if args.grad_accum < 1:
        parser.error("--grad-accum must be >= 1")
    if args.pipe_no_recompute and (
        args.mesh_pipe <= 1 or args.pipe_schedule != "1f1b"
    ):
        parser.error("--pipe-no-recompute needs --mesh-pipe > 1 and "
                     "--pipe-schedule 1f1b")
    names = [args.model] if args.model else args.models.split(",")
    for n in names:
        if n not in BASELINES:
            parser.error(f"unknown model {n!r}; choices: {list(BASELINES)}")

    results: dict = {}
    for name in names:
        for attempt in (1, 2):  # the tunneled device link flakes rarely;
            # one retry keeps a transient from blanking a model's entry
            try:
                results[name] = run_model(name, args)
                break
            except Exception as e:  # noqa: BLE001 - must not kill the line
                print(
                    f"bench: {name} FAILED (attempt {attempt}): {e}",
                    file=sys.stderr,
                )
                results[name] = {"error": str(e)}

    # the driver metric stays ResNet-50 (BASELINE.json); fall back to the
    # first successful model when it wasn't benchmarked
    primary = results.get("resnet50")
    if primary is None or "error" in primary:
        primary = next(
            (r for r in results.values() if "error" not in r), None
        )
    if primary is None:  # every model failed: say so loudly, exit nonzero
        print(json.dumps({"error": "all benchmarks failed", "models": results}))
        sys.exit(1)
    line = dict(primary)
    line["vs_baseline_note"] = (
        "anchor is a guessed 60%-of-published-torch-xla-order rate, not a "
        "measurement (the reference publishes none, BASELINE.md); mfu = "
        "XLA-counted step FLOPs / peak bf16 is the honest metric"
    )
    if len(results) > 1:
        line["models"] = results
    print(json.dumps(line))


if __name__ == "__main__":
    main()

#!/usr/bin/env python3
"""Headline benchmark: ResNet-50 synthetic-ImageNet samples/sec/chip.

Matches the driver metric in BASELINE.json ("samples/sec/chip ...
ResNet-50/ImageNet"). The baseline anchor is the north-star threshold: 60%
of published torch-xla ResNet-50 throughput (~1000 samples/sec/chip on
v4 in bf16), i.e. 600 samples/sec/chip → ``vs_baseline = value / 600``.

``--model gpt2`` (or bert-base) switches to the LM workload and reports
tokens/sec/chip instead (BASELINE.json config 5, "tokens/sec stress");
its anchor is 60% of a published-order GPT-2 torch-xla rate.

Prints exactly ONE JSON line on stdout; all logging goes to stderr.

Usage: python bench.py [--model resnet50|gpt2|...] [--batch-per-chip N]
"""

from __future__ import annotations

import argparse
import json
import sys
import time

BASELINE_SAMPLES_PER_SEC_PER_CHIP = 600.0  # 60% of published torch-xla v4
BASELINE_TOKENS_PER_SEC_PER_CHIP = 30_000.0  # 60% of ~50k tok/s/chip GPT-2


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--model", default="resnet50")
    parser.add_argument("--image-size", type=int, default=224)
    parser.add_argument("--seq-len", type=int, default=1024)
    parser.add_argument("--batch-per-chip", type=int, default=None,
                        help="default: 128 (vision) or 8 (LM)")
    parser.add_argument("--warmup", type=int, default=5)
    parser.add_argument("--steps", type=int, default=20)
    args = parser.parse_args()
    if args.warmup < 1 or args.steps < 1:
        parser.error("--warmup and --steps must be >= 1")

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    import distributed_pytorch_example_tpu as dpx

    lm = args.model.startswith(("gpt", "bert"))
    if args.batch_per_chip is None:
        args.batch_per_chip = 8 if lm else 128

    n_chips = len(jax.devices())
    print(
        f"bench: {args.model} on {n_chips} {jax.devices()[0].platform} "
        f"device(s), batch/chip={args.batch_per_chip}",
        file=sys.stderr,
    )

    mesh = dpx.runtime.make_mesh()
    partitioner = dpx.parallel.data_parallel(mesh)
    global_batch = args.batch_per_chip * n_chips
    rng = np.random.default_rng(0)
    if lm:
        model = dpx.models.get_model(args.model, dtype=jnp.bfloat16)
        seq_len = min(args.seq_len, model.max_len)  # BERT caps at 512
        if seq_len != args.seq_len:
            print(
                f"bench: clamping seq-len {args.seq_len} -> {seq_len} "
                f"(model max_len)",
                file=sys.stderr,
            )
        args.seq_len = seq_len
        if args.model.startswith("bert"):
            task = dpx.train.MLMTask(
                vocab_size=model.vocab_size, mask_token_id=103
            )
        else:
            task = dpx.train.CausalLMTask()
        batch_np = {
            "tokens": rng.integers(
                0, model.vocab_size, (global_batch, args.seq_len)
            ).astype(np.int32),
        }
    else:
        model = dpx.models.get_model(
            args.model, num_classes=1000, dtype=jnp.bfloat16
        )
        task = dpx.train.ClassificationTask()
        batch_np = {
            "x": rng.standard_normal(
                (global_batch, args.image_size, args.image_size, 3)
            ).astype(np.float32),
            "y": rng.integers(0, 1000, (global_batch,)).astype(np.int32),
        }
    trainer = dpx.train.Trainer(
        model, task, optax.adam(1e-3), partitioner=partitioner
    )
    sharding = partitioner.batch_sharding()
    batch = {
        k: jax.make_array_from_process_local_data(sharding, v)
        for k, v in batch_np.items()
    }

    with mesh:
        trainer.init(batch["tokens" if lm else "x"])
        state = trainer.state
        for _ in range(args.warmup):
            state, metrics = trainer.train_step(state, batch)
        # NB: fetch a VALUE, not block_until_ready — under the tunneled
        # remote-TPU platform only a real device->host transfer reliably
        # fences the dispatched step chain
        float(metrics["loss"])

        t0 = time.perf_counter()
        for _ in range(args.steps):
            state, metrics = trainer.train_step(state, batch)
        float(metrics["loss"])
        elapsed = time.perf_counter() - t0

    samples_per_sec = global_batch * args.steps / elapsed
    if lm:
        rate = samples_per_sec * args.seq_len / n_chips  # tokens/sec/chip
        metric, unit = f"{args.model}_tokens_per_sec_per_chip", "tokens/sec/chip"
        baseline = BASELINE_TOKENS_PER_SEC_PER_CHIP
    else:
        rate = samples_per_sec / n_chips
        metric, unit = f"{args.model}_samples_per_sec_per_chip", "samples/sec/chip"
        baseline = BASELINE_SAMPLES_PER_SEC_PER_CHIP
    print(
        f"bench: {elapsed:.2f}s for {args.steps} steps "
        f"({samples_per_sec:.1f} samples/s total)",
        file=sys.stderr,
    )
    print(
        json.dumps(
            {
                "metric": metric,
                "value": round(rate, 2),
                "unit": unit,
                "vs_baseline": round(rate / baseline, 3),
            }
        )
    )


if __name__ == "__main__":
    main()

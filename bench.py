#!/usr/bin/env python3
"""Headline benchmark: ResNet-50 synthetic-ImageNet samples/sec/chip.

Matches the driver metric in BASELINE.json ("samples/sec/chip ...
ResNet-50/ImageNet"). The baseline anchor is the north-star threshold: 60%
of published torch-xla ResNet-50 throughput (~1000 samples/sec/chip on
v4 in bf16), i.e. 600 samples/sec/chip → ``vs_baseline = value / 600``.

Prints exactly ONE JSON line on stdout; all logging goes to stderr.

Usage: python bench.py [--model resnet50] [--batch-per-chip N] [--steps N]
"""

from __future__ import annotations

import argparse
import json
import sys
import time

BASELINE_SAMPLES_PER_SEC_PER_CHIP = 600.0  # 60% of published torch-xla v4


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--model", default="resnet50")
    parser.add_argument("--image-size", type=int, default=224)
    parser.add_argument("--batch-per-chip", type=int, default=128)
    parser.add_argument("--warmup", type=int, default=5)
    parser.add_argument("--steps", type=int, default=20)
    args = parser.parse_args()
    if args.warmup < 1 or args.steps < 1:
        parser.error("--warmup and --steps must be >= 1")

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    import distributed_pytorch_example_tpu as dpx

    n_chips = len(jax.devices())
    print(
        f"bench: {args.model} on {n_chips} {jax.devices()[0].platform} "
        f"device(s), batch/chip={args.batch_per_chip}",
        file=sys.stderr,
    )

    mesh = dpx.runtime.make_mesh()
    partitioner = dpx.parallel.data_parallel(mesh)
    model = dpx.models.get_model(
        args.model, num_classes=1000, dtype=jnp.bfloat16
    )
    task = dpx.train.ClassificationTask()
    trainer = dpx.train.Trainer(
        model, task, optax.adam(1e-3), partitioner=partitioner
    )

    global_batch = args.batch_per_chip * n_chips
    rng = np.random.default_rng(0)
    batch_np = {
        "x": rng.standard_normal(
            (global_batch, args.image_size, args.image_size, 3)
        ).astype(np.float32),
        "y": rng.integers(0, 1000, (global_batch,)).astype(np.int32),
    }
    sharding = partitioner.batch_sharding()
    batch = {
        k: jax.make_array_from_process_local_data(sharding, v)
        for k, v in batch_np.items()
    }

    with mesh:
        trainer.init(batch["x"])
        state = trainer.state
        for _ in range(args.warmup):
            state, metrics = trainer.train_step(state, batch)
        # NB: fetch a VALUE, not block_until_ready — under the tunneled
        # remote-TPU platform only a real device->host transfer reliably
        # fences the dispatched step chain
        float(metrics["loss"])

        t0 = time.perf_counter()
        for _ in range(args.steps):
            state, metrics = trainer.train_step(state, batch)
        float(metrics["loss"])
        elapsed = time.perf_counter() - t0

    samples_per_sec = global_batch * args.steps / elapsed
    per_chip = samples_per_sec / n_chips
    print(
        f"bench: {elapsed:.2f}s for {args.steps} steps "
        f"({samples_per_sec:.1f} samples/s total)",
        file=sys.stderr,
    )
    print(
        json.dumps(
            {
                "metric": f"{args.model}_samples_per_sec_per_chip",
                "value": round(per_chip, 2),
                "unit": "samples/sec/chip",
                "vs_baseline": round(per_chip / BASELINE_SAMPLES_PER_SEC_PER_CHIP, 3),
            }
        )
    )


if __name__ == "__main__":
    main()

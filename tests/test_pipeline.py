"""GPipe pipeline parallelism: schedule correctness and gradients."""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_pytorch_example_tpu.parallel.pipeline import (
    gpipe,
    stack_stage_params,
)
from distributed_pytorch_example_tpu.runtime import MeshSpec, make_mesh


class StageBlock(nn.Module):
    """Shape-preserving residual MLP block (one pipeline stage)."""

    dim: int = 16

    @nn.compact
    def __call__(self, x):
        h = nn.Dense(self.dim * 2)(x)
        h = nn.gelu(h)
        return x + nn.Dense(self.dim)(h)


def make_stages(n_stages, dim=16, seed=0):
    block = StageBlock(dim=dim)
    x0 = jnp.zeros((1, dim))
    per_stage = [
        block.init(jax.random.key(seed + s), x0)["params"]
        for s in range(n_stages)
    ]
    stacked = stack_stage_params(per_stage)

    def stage_fn(params, x):
        return block.apply({"params": params}, x)

    return block, per_stage, stacked, stage_fn


def sequential_reference(block, per_stage, x):
    y = x
    for p in per_stage:
        y = block.apply({"params": p}, y)
    return y


@pytest.mark.parametrize("n_micro", [4, 8])
def test_matches_sequential(devices, n_micro):
    mesh = make_mesh(MeshSpec(data=2, pipe=4))
    block, per_stage, stacked, stage_fn = make_stages(4)
    x = jnp.asarray(np.random.default_rng(0).standard_normal((16, 16)), jnp.float32)
    expected = sequential_reference(block, per_stage, x)
    got = gpipe(stage_fn, stacked, x, mesh, n_micro=n_micro)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=1e-5)


def test_full_pipe_axis(devices):
    mesh = make_mesh(MeshSpec(data=1, pipe=8))
    block, per_stage, stacked, stage_fn = make_stages(8)
    x = jnp.asarray(np.random.default_rng(1).standard_normal((8, 16)), jnp.float32)
    expected = sequential_reference(block, per_stage, x)
    got = gpipe(stage_fn, stacked, x, mesh, n_micro=8)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=1e-5)


def test_gradients_match_sequential(devices):
    mesh = make_mesh(MeshSpec(data=2, pipe=4))
    block, per_stage, stacked, stage_fn = make_stages(4)
    x = jnp.asarray(np.random.default_rng(2).standard_normal((8, 16)), jnp.float32)

    def loss_pipe(stacked_params):
        return jnp.sum(gpipe(stage_fn, stacked_params, x, mesh, n_micro=4) ** 2)

    def loss_seq(stacked_params):
        per = [
            jax.tree_util.tree_map(lambda l: l[s], stacked_params)
            for s in range(4)
        ]
        return jnp.sum(sequential_reference(block, per, x) ** 2)

    g_pipe = jax.grad(loss_pipe)(stacked)
    g_seq = jax.grad(loss_seq)(stacked)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-4
        ),
        g_pipe,
        g_seq,
    )


def test_inside_jit_with_transformer_block(devices):
    """A real TransformerBlock as the stage function, under jit."""
    from distributed_pytorch_example_tpu.models.transformer import TransformerBlock

    mesh = make_mesh(MeshSpec(data=2, pipe=4))
    block = TransformerBlock(num_heads=2, head_dim=8, model_dim=16, mlp_dim=32)
    x0 = jnp.zeros((1, 8, 16))
    per_stage = [
        block.init(jax.random.key(s), x0, train=False)["params"] for s in range(4)
    ]
    stacked = stack_stage_params(per_stage)

    def stage_fn(params, x):
        return block.apply({"params": params}, x, train=False)

    x = jnp.asarray(np.random.default_rng(3).standard_normal((8, 8, 16)), jnp.float32)
    expected = x
    for p in per_stage:
        expected = block.apply({"params": p}, expected, train=False)

    got = jax.jit(
        lambda sp, x: gpipe(stage_fn, sp, x, mesh, n_micro=4)
    )(stacked, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=1e-5)


def test_batch_not_divisible_raises(devices):
    mesh = make_mesh(MeshSpec(data=2, pipe=4))
    _, _, stacked, stage_fn = make_stages(4)
    x = jnp.zeros((10, 16))
    with pytest.raises(ValueError, match="divisible"):
        gpipe(stage_fn, stacked, x, mesh, n_micro=4)


def test_n_micro_not_multiple_of_stages_raises(devices):
    mesh = make_mesh(MeshSpec(data=2, pipe=4))
    _, _, stacked, stage_fn = make_stages(4)
    x = jnp.zeros((12, 16))
    with pytest.raises(ValueError, match="pipe size"):
        gpipe(stage_fn, stacked, x, mesh, n_micro=6)


def test_single_stage_pipe(devices):
    """pipe=1 degenerates to sequential microbatching, still exact."""
    mesh = make_mesh(MeshSpec(data=-1, pipe=1))
    block, per_stage, stacked, stage_fn = make_stages(1)
    x = jnp.asarray(np.random.default_rng(4).standard_normal((32, 16)), jnp.float32)
    expected = sequential_reference(block, per_stage, x)
    got = gpipe(stage_fn, stacked, x, mesh, n_micro=4)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=1e-5)


def test_bubble_fraction_pinned():
    """The GPipe schedule's cost is a number, not a docstring (VERDICT r2).

    Useful stage executions are n_micro of gpipe_ticks per stage; the
    dryrun shape (4 microbatches, 2 stages) wastes 20% of stage FLOPs, and
    the bubble shrinks monotonically as microbatches increase.
    """
    from distributed_pytorch_example_tpu.parallel.pipeline import (
        bubble_fraction,
        gpipe_ticks,
    )

    assert gpipe_ticks(4, 2) == 5
    assert bubble_fraction(4, 2) == pytest.approx(0.2)
    assert gpipe_ticks(8, 4) == 11
    assert bubble_fraction(8, 4) == pytest.approx(1 - 8 / 11)
    assert bubble_fraction(16, 2) == pytest.approx(1 - 16 / 17)
    # more microbatches -> smaller bubble, approaching zero
    fracs = [bubble_fraction(k * 4, 4) for k in (1, 2, 4, 8, 16)]
    assert all(a > b for a, b in zip(fracs, fracs[1:]))
    assert fracs[-1] < 0.06


def test_schedule_tick_count_matches_formula(devices):
    """The executed schedule uses exactly gpipe_ticks(n_micro, n_stages)
    stage invocations per device (counted via a param-free probe fn)."""
    from distributed_pytorch_example_tpu.parallel.pipeline import (
        gpipe,
        gpipe_ticks,
    )

    mesh = make_mesh(MeshSpec(data=2, pipe=4))
    n_micro, batch = 8, 16
    x = jnp.ones((batch, 4), jnp.float32)
    params = jnp.zeros((4, 1), jnp.float32)

    def stage_fn(p, h):
        # each invocation adds 1; output microbatches pass all 4 stages
        return h + 1.0 + 0.0 * p.sum()

    with mesh:
        out = gpipe(stage_fn, params, x, mesh, n_micro)
    np.testing.assert_allclose(np.asarray(out), 1.0 + 4.0)
    assert gpipe_ticks(n_micro, 4) == 11


def _softmax_last_fn(head_w, y, t):
    """Per-microbatch CE head for the 1F1B tests: (loss, metrics)."""
    logits = y @ head_w
    logp = jax.nn.log_softmax(logits)
    loss = -jnp.take_along_axis(logp, t[:, None], axis=-1).mean()
    correct = (jnp.argmax(logits, -1) == t).sum().astype(jnp.float32)
    return loss, {"correct": correct}


def test_1f1b_matches_sequential(devices):
    """Loss, metrics, and ALL grads (stage params, head params, input) of
    the 1F1B schedule vs the microbatched sequential reference — at the
    4-stage x 8-microbatch shape (the delivery-ring corner cases GPipe's
    tests under-covered, VERDICT r4 weak #4)."""
    from distributed_pytorch_example_tpu.parallel.pipeline import one_f_one_b

    S, m, dim, n_cls = 4, 8, 16, 5
    mesh = make_mesh(MeshSpec(data=2, pipe=S))
    block, per_stage, stacked, stage_fn = make_stages(S, dim=dim)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((16, dim)), jnp.float32)
    tgt = jnp.asarray(rng.integers(0, n_cls, size=(16,)), jnp.int32)
    head_w = jnp.asarray(
        rng.standard_normal((dim, n_cls)), jnp.float32
    )

    def loss_pipe(sp, hw, xx):
        with mesh:
            loss_sum, mets, _ = one_f_one_b(
                stage_fn, sp, xx, mesh, m,
                last_fn=_softmax_last_fn, last_params=hw, last_args=tgt,
            )
        return loss_sum / m, mets

    def loss_seq(sp, hw, xx):
        mb = xx.reshape(m, -1, dim)
        tb = tgt.reshape(m, -1)
        total, ncorrect = 0.0, 0.0
        for i in range(m):
            y = mb[i]
            for s in range(S):
                p = jax.tree_util.tree_map(lambda l: l[s], sp)
                y = stage_fn(p, y)
            l, mets = _softmax_last_fn(hw, y, tb[i])
            total = total + l
            ncorrect = ncorrect + mets["correct"]
        return total / m, ncorrect

    (lp, mets), g_pipe = jax.value_and_grad(
        loss_pipe, argnums=(0, 1, 2), has_aux=True
    )(stacked, head_w, x)
    (ls, ncorrect), g_seq = jax.value_and_grad(
        loss_seq, argnums=(0, 1, 2), has_aux=True
    )(stacked, head_w, x)
    np.testing.assert_allclose(float(lp), float(ls), rtol=1e-5)
    assert float(mets["correct"]) == float(ncorrect)
    for a, b in zip(g_pipe, g_seq):
        jax.tree_util.tree_map(
            lambda u, v: np.testing.assert_allclose(
                np.asarray(u), np.asarray(v), atol=3e-5
            ),
            a, b,
        )


@pytest.mark.parametrize("S,v", [(2, 2), (4, 2)])
def test_1f1b_interleaved_matches_sequential(devices, S, v):
    """Interleaved (Megatron-style virtual-chunk) 1F1B: v chunks per
    device (device d holds chunks {d, d+S, ...}). Loss, metrics, and ALL
    grads (chunk params in the interleaved (S, v, ...) layout, head
    params, input) match the microbatched sequential reference running
    the chunks in order 0..V-1. The 4-stage case exercises the full-ring
    wraps through middle devices (activation chunk jS+S-1 -> (j+1)S,
    cotangent wrap, stale dx-ring relays through device 0)."""
    from distributed_pytorch_example_tpu.parallel.pipeline import one_f_one_b

    m, dim, n_cls = 8, 16, 5
    V = S * v
    mesh = make_mesh(MeshSpec(data=8 // S, pipe=S))
    block, per_chunk, stacked_V, stage_fn = make_stages(V, dim=dim)
    # interleaved layout: leaf[(d, j)] = chunk j*S + d
    interleaved = jax.tree_util.tree_map(
        lambda p: jnp.swapaxes(p.reshape(v, S, *p.shape[1:]), 0, 1),
        stacked_V,
    )
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((32, dim)), jnp.float32)
    tgt = jnp.asarray(rng.integers(0, n_cls, size=(32,)), jnp.int32)
    head_w = jnp.asarray(rng.standard_normal((dim, n_cls)), jnp.float32)

    def loss_pipe(sp, hw, xx):
        with mesh:
            loss_sum, mets, _ = one_f_one_b(
                stage_fn, sp, xx, mesh, m,
                last_fn=_softmax_last_fn, last_params=hw, last_args=tgt,
                n_virtual=v,
            )
        return loss_sum / m, mets

    def loss_seq(sp, hw, xx):
        spV = jax.tree_util.tree_map(
            lambda p: jnp.swapaxes(p, 0, 1).reshape(V, *p.shape[2:]), sp
        )
        mb = xx.reshape(m, -1, dim)
        tb = tgt.reshape(m, -1)
        total, ncorrect = 0.0, 0.0
        for i in range(m):
            y = mb[i]
            for c in range(V):
                p = jax.tree_util.tree_map(lambda l: l[c], spV)
                y = stage_fn(p, y)
            l, mets = _softmax_last_fn(hw, y, tb[i])
            total = total + l
            ncorrect = ncorrect + mets["correct"]
        return total / m, ncorrect

    (lp, mets), g_pipe = jax.value_and_grad(
        loss_pipe, argnums=(0, 1, 2), has_aux=True
    )(interleaved, head_w, x)
    (ls, ncorrect), g_seq = jax.value_and_grad(
        loss_seq, argnums=(0, 1, 2), has_aux=True
    )(interleaved, head_w, x)
    np.testing.assert_allclose(float(lp), float(ls), rtol=1e-5)
    assert float(mets["correct"]) == float(ncorrect)
    for a, b in zip(g_pipe, g_seq):
        jax.tree_util.tree_map(
            lambda u, v_: np.testing.assert_allclose(
                np.asarray(u), np.asarray(v_), atol=3e-5
            ),
            a, b,
        )


@pytest.mark.parametrize("predicate_head", [True, False])
def test_1f1b_stash_matches_sequential(devices, predicate_head):
    """recompute=False (activation-stash backward): the B sub-tick applies
    the vjp captured at forward time from the residual rings instead of
    replaying the stage forward. Same bar as test_1f1b_matches_sequential
    (4 stages x 8 microbatches, loss + metrics + ALL grads vs the
    microbatched sequential reference), both with and without the
    last-stage head predication (lax.cond vs where-masked head)."""
    from distributed_pytorch_example_tpu.parallel.pipeline import one_f_one_b

    S, m, dim, n_cls = 4, 8, 16, 5
    mesh = make_mesh(MeshSpec(data=2, pipe=S))
    block, per_stage, stacked, stage_fn = make_stages(S, dim=dim)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((16, dim)), jnp.float32)
    tgt = jnp.asarray(rng.integers(0, n_cls, size=(16,)), jnp.int32)
    head_w = jnp.asarray(rng.standard_normal((dim, n_cls)), jnp.float32)

    def loss_pipe(sp, hw, xx):
        with mesh:
            loss_sum, mets, _ = one_f_one_b(
                stage_fn, sp, xx, mesh, m,
                last_fn=_softmax_last_fn, last_params=hw, last_args=tgt,
                recompute=False, predicate_head=predicate_head,
            )
        return loss_sum / m, mets

    def loss_seq(sp, hw, xx):
        mb = xx.reshape(m, -1, dim)
        tb = tgt.reshape(m, -1)
        total, ncorrect = 0.0, 0.0
        for i in range(m):
            y = mb[i]
            for s in range(S):
                p = jax.tree_util.tree_map(lambda l: l[s], sp)
                y = stage_fn(p, y)
            l, mets = _softmax_last_fn(hw, y, tb[i])
            total = total + l
            ncorrect = ncorrect + mets["correct"]
        return total / m, ncorrect

    (lp, mets), g_pipe = jax.value_and_grad(
        loss_pipe, argnums=(0, 1, 2), has_aux=True
    )(stacked, head_w, x)
    (ls, ncorrect), g_seq = jax.value_and_grad(
        loss_seq, argnums=(0, 1, 2), has_aux=True
    )(stacked, head_w, x)
    np.testing.assert_allclose(float(lp), float(ls), rtol=1e-5)
    assert float(mets["correct"]) == float(ncorrect)
    for a, b in zip(g_pipe, g_seq):
        jax.tree_util.tree_map(
            lambda u, v: np.testing.assert_allclose(
                np.asarray(u), np.asarray(v), atol=3e-5
            ),
            a, b,
        )


def test_1f1b_stash_interleaved_matches_sequential(devices):
    """Interleaved (virtual-chunk) 1F1B with recompute=False: the stash
    rings are CHUNK-granular (slot arithmetic over V = S*v chunks, ring
    depth one_f_one_b_stash_slots(S, v)) and the restored vjps must pick
    the right chunk's params at B time. Same reference and tolerances as
    test_1f1b_interleaved_matches_sequential at S=2, v=2."""
    from distributed_pytorch_example_tpu.parallel.pipeline import one_f_one_b

    S, v, m, dim, n_cls = 2, 2, 8, 16, 5
    V = S * v
    mesh = make_mesh(MeshSpec(data=8 // S, pipe=S))
    block, per_chunk, stacked_V, stage_fn = make_stages(V, dim=dim)
    interleaved = jax.tree_util.tree_map(
        lambda p: jnp.swapaxes(p.reshape(v, S, *p.shape[1:]), 0, 1),
        stacked_V,
    )
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((32, dim)), jnp.float32)
    tgt = jnp.asarray(rng.integers(0, n_cls, size=(32,)), jnp.int32)
    head_w = jnp.asarray(rng.standard_normal((dim, n_cls)), jnp.float32)

    def loss_pipe(sp, hw, xx):
        with mesh:
            loss_sum, mets, _ = one_f_one_b(
                stage_fn, sp, xx, mesh, m,
                last_fn=_softmax_last_fn, last_params=hw, last_args=tgt,
                n_virtual=v, recompute=False,
            )
        return loss_sum / m, mets

    def loss_seq(sp, hw, xx):
        spV = jax.tree_util.tree_map(
            lambda p: jnp.swapaxes(p, 0, 1).reshape(V, *p.shape[2:]), sp
        )
        mb = xx.reshape(m, -1, dim)
        tb = tgt.reshape(m, -1)
        total, ncorrect = 0.0, 0.0
        for i in range(m):
            y = mb[i]
            for c in range(V):
                p = jax.tree_util.tree_map(lambda l: l[c], spV)
                y = stage_fn(p, y)
            l, mets = _softmax_last_fn(hw, y, tb[i])
            total = total + l
            ncorrect = ncorrect + mets["correct"]
        return total / m, ncorrect

    (lp, mets), g_pipe = jax.value_and_grad(
        loss_pipe, argnums=(0, 1, 2), has_aux=True
    )(interleaved, head_w, x)
    (ls, ncorrect), g_seq = jax.value_and_grad(
        loss_seq, argnums=(0, 1, 2), has_aux=True
    )(interleaved, head_w, x)
    np.testing.assert_allclose(float(lp), float(ls), rtol=1e-5)
    assert float(mets["correct"]) == float(ncorrect)
    for a, b in zip(g_pipe, g_seq):
        jax.tree_util.tree_map(
            lambda u, v_: np.testing.assert_allclose(
                np.asarray(u), np.asarray(v_), atol=3e-5
            ),
            a, b,
        )


def test_1f1b_stash_temp_memory_n_micro_independent(devices):
    """The vjp-residual rings hold IN-FLIGHT microbatches only (K =
    one_f_one_b_stash_slots slots), so the stash mode's temp-memory
    overhead over recompute mode must NOT grow with n_micro: the extra
    temp bytes at m=32 stay within 1.5x the extra at m=8 (a per-microbatch
    stash would 4x it). Uses a pipe-ONLY mesh so the measurement compiles
    on every supported jax (partial-auto shard_map pipelines need the
    0.9 toolchain; fully-manual ones do not)."""
    from jax.sharding import Mesh
    from distributed_pytorch_example_tpu.parallel.pipeline import one_f_one_b

    S, dim, n_cls = 4, 64, 17
    mesh = Mesh(np.array(jax.devices()[:S]), ("pipe",))
    block, per_stage, stacked, stage_fn = make_stages(S, dim=dim)
    rng = np.random.default_rng(0)
    head_w = jnp.asarray(rng.standard_normal((dim, n_cls)), jnp.float32)

    def temp_bytes(m, recompute):
        x = jnp.asarray(rng.standard_normal((4 * m, dim)), jnp.float32)
        tgt = jnp.asarray(rng.integers(0, n_cls, size=(4 * m,)), jnp.int32)

        def loss_pipe(sp, hw, xx):
            with mesh:
                loss_sum, _, _ = one_f_one_b(
                    stage_fn, sp, xx, mesh, m,
                    last_fn=_softmax_last_fn, last_params=hw, last_args=tgt,
                    recompute=recompute,
                )
            return loss_sum / m

        compiled = jax.jit(
            jax.value_and_grad(loss_pipe, argnums=(0, 1, 2))
        ).lower(stacked, head_w, x).compile()
        return compiled.memory_analysis().temp_size_in_bytes

    rec8, rec32 = temp_bytes(8, True), temp_bytes(32, True)
    st8, st32 = temp_bytes(8, False), temp_bytes(32, False)
    extra8, extra32 = st8 - rec8, st32 - rec32
    # the rings exist (stash mode does pay a constant memory price) ...
    assert extra8 > 0, (st8, rec8)
    # ... but that price is n_micro-independent: 4x the microbatches may
    # not grow it more than 1.5x (queues shared with recompute mode are
    # differenced away; a ring scaling with m would show ~4x here)
    assert extra32 < 1.5 * extra8, (extra8, extra32)


def test_1f1b_interleaved_schedule_formulas():
    """Interleaved cycle/stash/bubble pinned: at v=1 everything reduces to
    the classic 1F1B numbers; at v>1 cycles are CHUNK-granular (~1/v the
    work each) so total TIME ~ cycles/v stage-equivalents shrinks while
    the stash ring grows ~v — the documented trade."""
    from distributed_pytorch_example_tpu.parallel.pipeline import (
        one_f_one_b_bubble,
        one_f_one_b_cycles,
        one_f_one_b_stash_slots,
    )

    # v=1 reduction (same numbers the classic test pins below)
    assert one_f_one_b_cycles(8, 4, 1) == one_f_one_b_cycles(8, 4) == 17
    assert one_f_one_b_stash_slots(4, 1) == one_f_one_b_stash_slots(4) == 7
    # v=2 on 2 stages: V=4 chunks, waves=4 -> 3*4 + 4 + 8 - 3 = 21 cycles
    assert one_f_one_b_cycles(8, 2, 2) == 21
    assert one_f_one_b_stash_slots(2, 2) == 7
    # time in stage-equivalents improves: 21 half-stage cycles = 10.5 < 11
    assert one_f_one_b_cycles(8, 2, 2) / 2 < one_f_one_b_cycles(8, 2, 1)
    # and the per-sub-tick bubble fraction drops too
    assert one_f_one_b_bubble(8, 2, 2) < one_f_one_b_bubble(8, 2, 1)
    # deeper: v=4 on 4 stages, 16 microbatches
    assert (
        one_f_one_b_cycles(16, 4, 4) / 4
        < one_f_one_b_cycles(16, 4, 2) / 2
        < one_f_one_b_cycles(16, 4, 1)
    )


def test_1f1b_aux_weights_seed_gradients(devices):
    """Aux sums exclude bubble garbage and their gradient contribution is
    seeded inside the schedule with the declared weights (the pipe grads
    equal d((loss_sum + sum w*aux_sum)/m) of the sequential reference)."""
    from distributed_pytorch_example_tpu.parallel.pipeline import one_f_one_b

    S, m, dim = 4, 8, 8
    mesh = make_mesh(MeshSpec(data=2, pipe=S))
    W = jnp.asarray(
        np.random.default_rng(1).standard_normal((S, dim, dim)) * 0.3,
        jnp.float32,
    )

    def stage_fn(p, x):
        h = jnp.tanh(x @ p)
        return x + h, {"balance": jnp.mean(h ** 2), "count": jnp.float32(1)}

    AW = {"balance": 0.01, "count": 0.0}
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((16, dim)), jnp.float32)
    tgt = jnp.asarray(rng.integers(0, 3, size=(16,)), jnp.int32)
    head_w = jnp.asarray(rng.standard_normal((dim, 3)), jnp.float32)

    def last_fn(lp, y, t):
        return _softmax_last_fn(lp, y, t)[0], {}

    def total_pipe(sp, hw, xx):
        with mesh:
            loss_sum, _, aux = one_f_one_b(
                stage_fn, sp, xx, mesh, m, last_fn=last_fn, last_params=hw,
                last_args=tgt, aux_weights=AW,
            )
        return loss_sum / m, aux

    def total_seq(sp, hw, xx):
        mb = xx.reshape(m, -1, dim)
        tb = tgt.reshape(m, -1)
        total = 0.0
        aux_tot = {"balance": 0.0, "count": 0.0}
        for i in range(m):
            y = mb[i]
            for s in range(S):
                p = jax.tree_util.tree_map(lambda l: l[s], sp)
                y, aux = stage_fn(p, y)
                aux_tot = {k: aux_tot[k] + aux[k] for k in aux}
            total = total + last_fn(hw, y, tb[i])[0]
        return (
            (total + sum(AW[k] * aux_tot[k] for k in AW)) / m,
            aux_tot,
        )

    (lp, aux_p), g_pipe = jax.value_and_grad(
        total_pipe, argnums=(0, 1, 2), has_aux=True
    )(W, head_w, x)
    (ls, aux_s), g_seq = jax.value_and_grad(
        total_seq, argnums=(0, 1, 2), has_aux=True
    )(W, head_w, x)
    # bubble exclusion: each stage_fn invocation adds count=1; only the
    # S * m useful (stage, microbatch) pairs survive
    assert float(aux_p["count"]) == S * m
    np.testing.assert_allclose(
        float(aux_p["balance"]), float(aux_s["balance"]), rtol=1e-5
    )
    for a, b in zip(g_pipe, g_seq):
        jax.tree_util.tree_map(
            lambda u, v: np.testing.assert_allclose(
                np.asarray(u), np.asarray(v), atol=3e-5
            ),
            a, b,
        )


def test_1f1b_schedule_formulas():
    """Cycle count, stash size, and bubble pinned as numbers: the stash is
    INDEPENDENT of n_micro (the whole point vs GPipe's ~n_micro growth)."""
    from distributed_pytorch_example_tpu.parallel.pipeline import (
        one_f_one_b_bubble,
        one_f_one_b_cycles,
        one_f_one_b_stash_slots,
    )

    from distributed_pytorch_example_tpu.parallel.pipeline import gpipe_ticks

    assert one_f_one_b_cycles(8, 4) == 17
    assert one_f_one_b_cycles(8, 1) == 8  # degenerate: plain microbatching
    assert one_f_one_b_stash_slots(4) == 7
    assert one_f_one_b_stash_slots(1) == 1
    # the stash is a function of n_stages ONLY, while GPipe's per-tick
    # residual count grows with n_micro
    assert gpipe_ticks(32, 4) > gpipe_ticks(8, 4)
    assert one_f_one_b_bubble(8, 4) == pytest.approx(1 - 8 / 17)
    fracs = [one_f_one_b_bubble(k * 4, 4) for k in (1, 2, 4, 8, 16)]
    assert all(a > b for a, b in zip(fracs, fracs[1:]))


def test_1f1b_single_stage_raises_via_models(devices):
    """pipe size 1 cannot interleave; the decoders reject it loudly."""
    from distributed_pytorch_example_tpu.models.gpt2 import GPT2
    from distributed_pytorch_example_tpu.train.tasks import CausalLMTask

    mesh = make_mesh(MeshSpec(data=-1, pipe=1))
    model = GPT2(
        vocab_size=64, max_len=32, model_dim=32, num_layers=2, num_heads=2,
        mlp_dim=64, pipe_axis="pipe", pipe_schedule="1f1b",
        logits_mode="hidden",
    )
    tokens = jnp.zeros((4, 16), jnp.int32)
    with mesh:
        params = model.init(jax.random.key(0), tokens, train=False)["params"]
        with pytest.raises(ValueError, match="size >= 2"):
            CausalLMTask().compute_loss(
                model, params, {}, {"tokens": tokens}, jax.random.key(1),
                train=True,
            )


def test_aux_accumulation_excludes_bubble_ticks(devices):
    """With aux_init, stage_fn aux is summed over (stage, microbatch) and
    the bubble ticks' garbage contributions are EXCLUDED: an aux of 1.0
    per call totals exactly n_stages * n_micro, not n_stages * n_ticks."""
    from distributed_pytorch_example_tpu.parallel.pipeline import (
        gpipe,
        gpipe_ticks,
    )

    mesh = make_mesh(MeshSpec(data=2, pipe=4))
    n_micro, batch = 8, 16
    x = jnp.ones((batch, 4), jnp.float32)
    params = jnp.zeros((4, 1), jnp.float32)

    def stage_fn(p, h):
        return h + 1.0 + 0.0 * p.sum(), {
            "count": jnp.float32(1.0),
            "mean_in": h.mean(),
        }

    with mesh:
        out, aux = gpipe(
            stage_fn, params, x, mesh, n_micro,
            aux_init={"count": jnp.float32(0), "mean_in": jnp.float32(0)},
        )
    np.testing.assert_allclose(np.asarray(out), 5.0)
    assert float(aux["count"]) == 4 * n_micro  # not 4 * gpipe_ticks(...)
    assert gpipe_ticks(n_micro, 4) > n_micro
    # mean_in sums h.mean() over useful (stage, microbatch) pairs: each
    # microbatch enters stage s with value 1 + s
    np.testing.assert_allclose(
        float(aux["mean_in"]), n_micro * (1 + 2 + 3 + 4 - 0), rtol=1e-6
    )


def test_cycles_nondivisible_classic_form():
    """ADVICE r5 back-compat pin: at n_virtual=1 the cycle count is the
    classic closed form for ANY n_micro (no whole-wave precondition);
    only the interleaved schedule raises on ragged waves."""
    from distributed_pytorch_example_tpu.parallel.pipeline import (
        one_f_one_b_cycles,
    )

    assert one_f_one_b_cycles(7, 4) == 7 + 3 * 3  # non-divisible, v=1
    assert one_f_one_b_cycles(1, 4) == 1 + 3 * 3
    with pytest.raises(ValueError, match="interleaved"):
        one_f_one_b_cycles(7, 4, 2)

"""GPipe pipeline parallelism: schedule correctness and gradients."""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_pytorch_example_tpu.parallel.pipeline import (
    gpipe,
    stack_stage_params,
)
from distributed_pytorch_example_tpu.runtime import MeshSpec, make_mesh


class StageBlock(nn.Module):
    """Shape-preserving residual MLP block (one pipeline stage)."""

    dim: int = 16

    @nn.compact
    def __call__(self, x):
        h = nn.Dense(self.dim * 2)(x)
        h = nn.gelu(h)
        return x + nn.Dense(self.dim)(h)


def make_stages(n_stages, dim=16, seed=0):
    block = StageBlock(dim=dim)
    x0 = jnp.zeros((1, dim))
    per_stage = [
        block.init(jax.random.key(seed + s), x0)["params"]
        for s in range(n_stages)
    ]
    stacked = stack_stage_params(per_stage)

    def stage_fn(params, x):
        return block.apply({"params": params}, x)

    return block, per_stage, stacked, stage_fn


def sequential_reference(block, per_stage, x):
    y = x
    for p in per_stage:
        y = block.apply({"params": p}, y)
    return y


@pytest.mark.parametrize("n_micro", [4, 8])
def test_matches_sequential(devices, n_micro):
    mesh = make_mesh(MeshSpec(data=2, pipe=4))
    block, per_stage, stacked, stage_fn = make_stages(4)
    x = jnp.asarray(np.random.default_rng(0).standard_normal((16, 16)), jnp.float32)
    expected = sequential_reference(block, per_stage, x)
    got = gpipe(stage_fn, stacked, x, mesh, n_micro=n_micro)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=1e-5)


def test_full_pipe_axis(devices):
    mesh = make_mesh(MeshSpec(data=1, pipe=8))
    block, per_stage, stacked, stage_fn = make_stages(8)
    x = jnp.asarray(np.random.default_rng(1).standard_normal((8, 16)), jnp.float32)
    expected = sequential_reference(block, per_stage, x)
    got = gpipe(stage_fn, stacked, x, mesh, n_micro=8)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=1e-5)


def test_gradients_match_sequential(devices):
    mesh = make_mesh(MeshSpec(data=2, pipe=4))
    block, per_stage, stacked, stage_fn = make_stages(4)
    x = jnp.asarray(np.random.default_rng(2).standard_normal((8, 16)), jnp.float32)

    def loss_pipe(stacked_params):
        return jnp.sum(gpipe(stage_fn, stacked_params, x, mesh, n_micro=4) ** 2)

    def loss_seq(stacked_params):
        per = [
            jax.tree_util.tree_map(lambda l: l[s], stacked_params)
            for s in range(4)
        ]
        return jnp.sum(sequential_reference(block, per, x) ** 2)

    g_pipe = jax.grad(loss_pipe)(stacked)
    g_seq = jax.grad(loss_seq)(stacked)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-4
        ),
        g_pipe,
        g_seq,
    )


def test_inside_jit_with_transformer_block(devices):
    """A real TransformerBlock as the stage function, under jit."""
    from distributed_pytorch_example_tpu.models.transformer import TransformerBlock

    mesh = make_mesh(MeshSpec(data=2, pipe=4))
    block = TransformerBlock(num_heads=2, head_dim=8, model_dim=16, mlp_dim=32)
    x0 = jnp.zeros((1, 8, 16))
    per_stage = [
        block.init(jax.random.key(s), x0, train=False)["params"] for s in range(4)
    ]
    stacked = stack_stage_params(per_stage)

    def stage_fn(params, x):
        return block.apply({"params": params}, x, train=False)

    x = jnp.asarray(np.random.default_rng(3).standard_normal((8, 8, 16)), jnp.float32)
    expected = x
    for p in per_stage:
        expected = block.apply({"params": p}, expected, train=False)

    got = jax.jit(
        lambda sp, x: gpipe(stage_fn, sp, x, mesh, n_micro=4)
    )(stacked, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=1e-5)


def test_batch_not_divisible_raises(devices):
    mesh = make_mesh(MeshSpec(data=2, pipe=4))
    _, _, stacked, stage_fn = make_stages(4)
    x = jnp.zeros((10, 16))
    with pytest.raises(ValueError, match="divisible"):
        gpipe(stage_fn, stacked, x, mesh, n_micro=4)


def test_n_micro_not_multiple_of_stages_raises(devices):
    mesh = make_mesh(MeshSpec(data=2, pipe=4))
    _, _, stacked, stage_fn = make_stages(4)
    x = jnp.zeros((12, 16))
    with pytest.raises(ValueError, match="pipe size"):
        gpipe(stage_fn, stacked, x, mesh, n_micro=6)


def test_single_stage_pipe(devices):
    """pipe=1 degenerates to sequential microbatching, still exact."""
    mesh = make_mesh(MeshSpec(data=-1, pipe=1))
    block, per_stage, stacked, stage_fn = make_stages(1)
    x = jnp.asarray(np.random.default_rng(4).standard_normal((32, 16)), jnp.float32)
    expected = sequential_reference(block, per_stage, x)
    got = gpipe(stage_fn, stacked, x, mesh, n_micro=4)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=1e-5)


def test_bubble_fraction_pinned():
    """The GPipe schedule's cost is a number, not a docstring (VERDICT r2).

    Useful stage executions are n_micro of gpipe_ticks per stage; the
    dryrun shape (4 microbatches, 2 stages) wastes 20% of stage FLOPs, and
    the bubble shrinks monotonically as microbatches increase.
    """
    from distributed_pytorch_example_tpu.parallel.pipeline import (
        bubble_fraction,
        gpipe_ticks,
    )

    assert gpipe_ticks(4, 2) == 5
    assert bubble_fraction(4, 2) == pytest.approx(0.2)
    assert gpipe_ticks(8, 4) == 11
    assert bubble_fraction(8, 4) == pytest.approx(1 - 8 / 11)
    assert bubble_fraction(16, 2) == pytest.approx(1 - 16 / 17)
    # more microbatches -> smaller bubble, approaching zero
    fracs = [bubble_fraction(k * 4, 4) for k in (1, 2, 4, 8, 16)]
    assert all(a > b for a, b in zip(fracs, fracs[1:]))
    assert fracs[-1] < 0.06


def test_schedule_tick_count_matches_formula(devices):
    """The executed schedule uses exactly gpipe_ticks(n_micro, n_stages)
    stage invocations per device (counted via a param-free probe fn)."""
    from distributed_pytorch_example_tpu.parallel.pipeline import (
        gpipe,
        gpipe_ticks,
    )

    mesh = make_mesh(MeshSpec(data=2, pipe=4))
    n_micro, batch = 8, 16
    x = jnp.ones((batch, 4), jnp.float32)
    params = jnp.zeros((4, 1), jnp.float32)

    def stage_fn(p, h):
        # each invocation adds 1; output microbatches pass all 4 stages
        return h + 1.0 + 0.0 * p.sum()

    with mesh:
        out = gpipe(stage_fn, params, x, mesh, n_micro)
    np.testing.assert_allclose(np.asarray(out), 1.0 + 4.0)
    assert gpipe_ticks(n_micro, 4) == 11


def test_aux_accumulation_excludes_bubble_ticks(devices):
    """With aux_init, stage_fn aux is summed over (stage, microbatch) and
    the bubble ticks' garbage contributions are EXCLUDED: an aux of 1.0
    per call totals exactly n_stages * n_micro, not n_stages * n_ticks."""
    from distributed_pytorch_example_tpu.parallel.pipeline import (
        gpipe,
        gpipe_ticks,
    )

    mesh = make_mesh(MeshSpec(data=2, pipe=4))
    n_micro, batch = 8, 16
    x = jnp.ones((batch, 4), jnp.float32)
    params = jnp.zeros((4, 1), jnp.float32)

    def stage_fn(p, h):
        return h + 1.0 + 0.0 * p.sum(), {
            "count": jnp.float32(1.0),
            "mean_in": h.mean(),
        }

    with mesh:
        out, aux = gpipe(
            stage_fn, params, x, mesh, n_micro,
            aux_init={"count": jnp.float32(0), "mean_in": jnp.float32(0)},
        )
    np.testing.assert_allclose(np.asarray(out), 5.0)
    assert float(aux["count"]) == 4 * n_micro  # not 4 * gpipe_ticks(...)
    assert gpipe_ticks(n_micro, 4) > n_micro
    # mean_in sums h.mean() over useful (stage, microbatch) pairs: each
    # microbatch enters stage s with value 1 + s
    np.testing.assert_allclose(
        float(aux["mean_in"]), n_micro * (1 + 2 + 3 + 4 - 0), rtol=1e-6
    )

"""graft-serve: paged-KV serving equivalence + scheduler contracts.

The load-bearing guarantee: the paged-cache engine reproduces the
contiguous-cache ``generate()`` token-for-token — greedy AND seeded
sampling (``rng_fold="position"``) — on GPT-2-tiny and llama-tiny,
single-chip and TP-sharded. Everything else (admission control, block
recycling, in-flight insertion isolation, preemption, continuous-vs-
static throughput) is the scheduler keeping that guarantee under load.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_pytorch_example_tpu.serving import (
    BlockAllocator,
    InferenceEngine,
    PagedCacheConfig,
    Request,
    Scheduler,
)
from distributed_pytorch_example_tpu.train.generate import generate

GPT2_KW = dict(vocab_size=97, max_len=64, model_dim=32, num_layers=2,
               num_heads=4, mlp_dim=64)
LLAMA_KW = dict(vocab_size=97, max_len=64, model_dim=32, num_layers=2,
                num_heads=4, num_kv_heads=2, mlp_dim=64)
PAGED = dict(paged_num_blocks=32, paged_block_size=4, paged_max_blocks=8)

_CACHE = {}


def _family(family):
    """(decode_model, paged_model, params) per family, built once."""
    if family not in _CACHE:
        if family == "gpt2":
            from distributed_pytorch_example_tpu.models.gpt2 import GPT2 as M

            kw = GPT2_KW
        else:
            from distributed_pytorch_example_tpu.models.llama import (
                Llama as M,
            )

            kw = LLAMA_KW
        params = M(**kw).init(
            jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
        )["params"]
        _CACHE[family] = (
            M(**kw, decode=True), M(**kw, decode=True, **PAGED), params
        )
    return _CACHE[family]


def _prompts(lengths, vocab=97, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, vocab, (n,)).astype(np.int32) for n in lengths]


def _requests(prompts, max_new=8, **kw):
    return [
        Request(rid=f"r{i}", prompt=[int(t) for t in p],
                max_new_tokens=max_new, seed=i, **kw)
        for i, p in enumerate(prompts)
    ]


def _refs(decode_model, params, prompts, max_new=8, **gen_kw):
    """Per-request contiguous-cache generate() outputs (B=1 each, the
    engine's per-request rng contract)."""
    out = []
    for i, p in enumerate(prompts):
        full = generate(
            decode_model, params, jnp.asarray(p)[None], max_new,
            rng=jax.random.key(i), rng_fold="position", **gen_kw,
        )
        out.append(list(np.asarray(full)[0, len(p):]))
    return out


class VirtualClock:
    """Deterministic injectable clock: each read ticks a little (simulated
    work), sleep() jumps. Keeps scheduler tests wall-clock-free."""

    def __init__(self, tick=1e-3):
        self.t = 0.0
        self.tick = tick

    def __call__(self):
        self.t += self.tick
        return self.t

    def sleep(self, s):
        self.t += max(s, 0.0)


# ---------------------------------------------------------------------------
# equivalence: paged decode == contiguous generate(), token for token
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("family", ["gpt2", "llama"])
def test_paged_greedy_matches_generate(family):
    decode_model, paged_model, params = _family(family)
    prompts = _prompts((8, 5, 11))
    refs = _refs(decode_model, params, prompts, temperature=0.0)
    engine = InferenceEngine(
        paged_model, params, num_slots=2, temperature=0.0
    )
    report = engine.run(_requests(prompts))
    for i in range(len(prompts)):
        r = report["results"][f"r{i}"]
        assert r["status"] == "done"
        assert r["tokens"] == refs[i]
    assert report["metrics"]["completed"] == len(prompts)
    # continuous batching actually happened: 3 requests over 2 slots
    assert report["metrics"]["admitted"] == 3


@pytest.mark.parametrize(
    "family,sample_kw",
    [("gpt2", dict(temperature=1.0, top_k=5)),
     ("llama", dict(temperature=1.0, top_p=0.9))],
    ids=["gpt2-topk", "llama-topp"],
)
def test_paged_seeded_sampling_matches_generate(family, sample_kw):
    """Seeded sampling is EXACT, not distributional: the engine's
    position-folded per-request keys (serving/sampling.py) reproduce
    generate(rng_fold="position") bit-for-bit."""
    decode_model, paged_model, params = _family(family)
    prompts = _prompts((8, 5, 11), seed=1)
    refs = _refs(decode_model, params, prompts, **sample_kw)
    engine = InferenceEngine(
        paged_model, params, num_slots=2, **sample_kw
    )
    report = engine.run(_requests(prompts))
    for i in range(len(prompts)):
        assert report["results"][f"r{i}"]["tokens"] == refs[i]


@pytest.mark.parametrize("family", ["gpt2", "llama"])
def test_paged_sharded_tensor2_matches_generate(devices, family):
    """TP-trained checkpoints serve without gathering: the engine under a
    tensor=2 mesh (pool kv-heads TP-sharded, blocks over data axes)
    stays token-exact vs the dense single-logical-device generate()."""
    from distributed_pytorch_example_tpu.parallel.partition import (
        transformer_partitioner,
    )
    from distributed_pytorch_example_tpu.runtime import MeshSpec, make_mesh

    decode_model, paged_model, params = _family(family)
    prompts = _prompts((8, 6, 10), seed=2)
    refs = _refs(decode_model, params, prompts, temperature=0.0)
    mesh = make_mesh(MeshSpec(data=2, fsdp=2, tensor=2))
    engine = InferenceEngine(
        paged_model, params, num_slots=2, temperature=0.0,
        partitioner=transformer_partitioner(mesh),
    )
    report = engine.run(_requests(prompts))
    for i in range(len(prompts)):
        assert report["results"][f"r{i}"]["tokens"] == refs[i]


def test_eos_and_rejection():
    decode_model, paged_model, params = _family("gpt2")
    prompts = _prompts((6,))
    # find the greedy continuation's second token and use it as EOS: the
    # request must stop there (EOS included) instead of running to max
    ref = _refs(decode_model, params, prompts, temperature=0.0,
                max_new=8)[0]
    eos = ref[2]
    engine = InferenceEngine(
        paged_model, params, num_slots=2, temperature=0.0
    )
    reqs = _requests(prompts, max_new=8, eos_id=int(eos))
    # plus one request that can NEVER fit (prompt+new > max context 32)
    reqs.append(Request(rid="huge", prompt=[1] * 30, max_new_tokens=20))
    report = engine.run(reqs)
    done = report["results"]["r0"]
    stop = done["tokens"].index(int(eos))
    assert done["tokens"] == ref[:stop + 1]
    assert report["results"]["huge"]["status"] == "rejected"
    assert report["metrics"]["rejected"] == 1


def test_engine_preemption_restart_bit_identical():
    """Pool pressure mid-decode: the youngest resident is preempted,
    requeued, and — because the rng folds absolute positions — its
    restarted stream reproduces the exact same tokens."""
    from distributed_pytorch_example_tpu.models.gpt2 import GPT2

    _, _, params = _family("gpt2")
    # 11 allocatable blocks; two requests that each grow to 7 blocks
    # (8 prompt + 20 new = 28 tokens) cannot coexist at full length
    model = GPT2(**GPT2_KW, decode=True, paged_num_blocks=12,
                 paged_block_size=4, paged_max_blocks=8)
    decode_model, _, _ = _family("gpt2")
    prompts = _prompts((8, 8), seed=3)
    refs = _refs(decode_model, params, prompts, temperature=0.0,
                 max_new=20)
    engine = InferenceEngine(model, params, num_slots=2, temperature=0.0)
    report = engine.run(_requests(prompts, max_new=20))
    assert report["metrics"]["preempted"] >= 1
    for i in range(2):
        r = report["results"][f"r{i}"]
        assert r["status"] == "done"
        assert r["tokens"] == refs[i]


def test_inflight_insertion_slot_isolation():
    """A request inserted at a decode boundary never perturbs resident
    requests' logits: every request's tokens equal its solo run."""
    decode_model, paged_model, params = _family("gpt2")
    prompts = _prompts((8, 5, 7), seed=4)
    sample_kw = dict(temperature=1.0, top_k=5)
    refs = _refs(decode_model, params, prompts, max_new=12, **sample_kw)
    clock = VirtualClock()
    engine = InferenceEngine(
        paged_model, params, num_slots=3, clock=clock, sleep=clock.sleep,
        **sample_kw,
    )
    # r2 arrives while r0/r1 are mid-decode (virtual clock ticks per read)
    reqs = _requests(prompts[:2], max_new=12)
    reqs.append(Request(rid="r2", prompt=[int(t) for t in prompts[2]],
                        max_new_tokens=12, seed=2, arrival=0.02))
    report = engine.run(reqs)
    assert report["metrics"]["admitted"] == 3
    for i in range(3):
        assert report["results"][f"r{i}"]["tokens"] == refs[i]


def test_continuous_beats_static_batching():
    """Mixed-length workload over 2 slots: continuous batching needs
    strictly fewer decode-program launches (the deterministic throughput
    proxy; the wall-clock margin rides in bench.py --serve)."""
    _, paged_model, params = _family("gpt2")
    prompts = _prompts((8, 8, 8, 8), seed=5)
    reqs = [
        Request(rid=f"r{i}", prompt=[int(t) for t in p],
                max_new_tokens=n, seed=i)
        for i, (p, n) in enumerate(zip(prompts, (4, 16, 4, 16)))
    ]
    engine = InferenceEngine(
        paged_model, params, num_slots=2, temperature=0.0
    )
    cont = engine.run(reqs, mode="continuous")["metrics"]
    stat = engine.run(reqs, mode="static")["metrics"]
    assert cont["completed"] == stat["completed"] == 4
    assert cont["decode_steps"] < stat["decode_steps"]
    assert cont["slot_occupancy"] > stat["slot_occupancy"]


# ---------------------------------------------------------------------------
# scheduler unit tests: pure host bookkeeping, virtual clock, no jax
# ---------------------------------------------------------------------------


def _cfg(**kw):
    base = dict(num_blocks=9, block_size=4, max_blocks_per_slot=8,
                num_slots=2)
    base.update(kw)
    return PagedCacheConfig(**base)


def test_admission_blocks_when_pool_exhausted():
    sched = Scheduler(_cfg())  # 8 allocatable blocks
    # each request: 12-token prompt -> blocks_for(13) = 4 blocks
    a = sched.submit(Request(rid="a", prompt=[0] * 12, max_new_tokens=4), 0.0)
    b = sched.submit(Request(rid="b", prompt=[0] * 12, max_new_tokens=4), 0.0)
    c = sched.submit(Request(rid="c", prompt=[0] * 12, max_new_tokens=4), 0.0)
    admitted = sched.admit(1.0)
    assert [s.request.rid for s in admitted] == ["a", "b"]
    assert sched.allocator.free_count() == 0
    assert sched.admit(2.0) == []  # c blocked: no blocks, no free slot
    # eviction recycles a's blocks; c then admits into the freed slot
    slot_a, slot_b = a.slot, b.slot
    sched.finish(a, "done", now=3.0)
    assert sched.allocator.free_count() == 4
    assert [s.request.rid for s in sched.admit(4.0)] == ["c"]
    assert c.slot == slot_a != slot_b


def test_blocks_recycled_exactly_on_eviction():
    def replay():
        sched = Scheduler(_cfg())
        st = sched.submit(
            Request(rid="a", prompt=[0] * 6, max_new_tokens=20), 0.0
        )
        sched.admit(0.0)
        held = list(st.blocks)
        assert sched.allocator.free_count() == 8 - len(held)
        # simulate decode growth past a block boundary
        st.generated.extend([1] * 4)  # cached_len 9 -> needs 3 blocks
        assert sched.grow(st)
        assert len(st.blocks) == 3
        sched.finish(st, "done", now=1.0)
        assert sched.allocator.free_count() == 8
        assert st.blocks == [] and st.slot == -1
        st2 = sched.submit(
            Request(rid="b", prompt=[0] * 6, max_new_tokens=4), 2.0
        )
        sched.admit(2.0)
        return held, list(st2.blocks)

    # deterministic replay: the identical op sequence allocates the
    # identical block ids both times (the chaos bit-identical lean)
    assert replay() == replay()


def test_head_of_line_fifo_no_overtake():
    sched = Scheduler(_cfg(num_slots=3))  # 8 allocatable blocks
    a = sched.submit(Request(rid="a", prompt=[0] * 12, max_new_tokens=2), 0.0)
    assert [s.request.rid for s in sched.admit(0.0)] == ["a"]  # 4 blocks
    big = sched.submit(
        Request(rid="big", prompt=[0] * 20, max_new_tokens=2), 1.0
    )  # needs blocks_for(21) = 6 > 4 free -> blocked at head of line
    small = sched.submit(
        Request(rid="small", prompt=[0] * 2, max_new_tokens=2), 1.0
    )  # needs 1 block and a slot is free -- but must NOT overtake big
    assert sched.admit(1.0) == []
    sched.finish(a, "done", now=2.0)  # frees 4 -> 8 free
    assert [s.request.rid for s in sched.admit(3.0)] == ["big", "small"]
    assert big.slot != small.slot


def test_static_mode_admits_only_drained_waves():
    sched = Scheduler(_cfg(), mode="static")
    for i in range(4):
        sched.submit(
            Request(rid=f"r{i}", prompt=[0] * 2, max_new_tokens=2), 0.0
        )
    wave1 = sched.admit(0.0)
    assert len(wave1) == 2
    # one slot drains; static mode still refuses to backfill
    sched.finish(wave1[0], "done", now=1.0)
    assert sched.admit(1.0) == []
    sched.finish(wave1[1], "done", now=2.0)
    assert len(sched.admit(2.0)) == 2  # the next full wave


def test_preempt_youngest_requeues_at_front():
    sched = Scheduler(_cfg())
    a = sched.submit(Request(rid="a", prompt=[0] * 4, max_new_tokens=4), 0.0)
    b = sched.submit(Request(rid="b", prompt=[0] * 4, max_new_tokens=4), 0.0)
    sched.admit(0.0)
    a.generated.append(1)
    b.generated.append(1)
    victim = sched.preempt_youngest()
    assert victim is b  # the most recently admitted resident
    assert b.status == "queued" and b.generated == [] and b.blocks == []
    assert sched.queue[0] is b  # front of the line: keeps FIFO seniority
    assert b.preemptions == 1
    assert sched.counters["preempted"] == 1


def test_submit_rejects_never_fit():
    sched = Scheduler(_cfg())
    bad = sched.submit(
        Request(rid="x", prompt=[0] * 30, max_new_tokens=10), 0.0
    )  # 40 > max_context 32
    assert bad.status == "rejected"
    empty = sched.submit(Request(rid="y", prompt=[], max_new_tokens=4), 0.0)
    assert empty.status == "rejected"
    assert sched.counters["rejected"] == 2
    assert not sched.queue


def test_allocator_shard_affinity():
    cfg = PagedCacheConfig(num_blocks=16, block_size=4,
                           max_blocks_per_slot=4, num_slots=4, num_shards=2)
    alloc = BlockAllocator(cfg)
    # slots map onto contiguous shard ranges; scratch only costs shard 0
    assert [alloc.shard_of_slot(s) for s in range(4)] == [0, 0, 1, 1]
    assert alloc.free_count(0) == 7 and alloc.free_count(1) == 8
    got = alloc.alloc(3, shard=1)
    assert got is not None and all(8 <= b < 16 for b in got)
    assert alloc.alloc(8, shard=0) is None  # all-or-nothing
    alloc.release(got)
    assert alloc.free_count(1) == 8
    with pytest.raises(ValueError, match="scratch"):
        alloc.release([0])


def test_paged_model_requires_decode_mode():
    from distributed_pytorch_example_tpu.models.gpt2 import GPT2

    with pytest.raises(ValueError, match="decode"):
        GPT2(**GPT2_KW, **PAGED).init(
            jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
        )


# ---------------------------------------------------------------------------
# speculative decoding: bit-identical output, fewer decode boundaries
# ---------------------------------------------------------------------------


def _spec_engine(paged_model, params, spec_tokens=4, **kw):
    """Self-speculation (draft = target): zero model risk, and the
    exact-match acceptance rule is exercised identically to a real small
    draft — only the accept RATE differs."""
    return InferenceEngine(
        paged_model, params, draft_model=paged_model, draft_params=params,
        spec_tokens=spec_tokens, **kw,
    )


@pytest.mark.parametrize("family", ["gpt2", "llama"])
def test_spec_greedy_token_exact(family):
    """Speculative greedy == generate(): acceptance commits only drafts
    the target would have emitted, so the output is the non-speculative
    stream bit-for-bit — while taking strictly fewer decode boundaries."""
    decode_model, paged_model, params = _family(family)
    prompts = _prompts((8, 5, 11), seed=6)
    refs = _refs(decode_model, params, prompts, max_new=12, temperature=0.0)
    plain = InferenceEngine(
        paged_model, params, num_slots=2, temperature=0.0
    )
    plain_steps = plain.run(_requests(prompts, max_new=12))["metrics"][
        "decode_steps"
    ]
    engine = _spec_engine(paged_model, params, num_slots=2, temperature=0.0)
    report = engine.run(_requests(prompts, max_new=12))
    for i in range(len(prompts)):
        r = report["results"][f"r{i}"]
        assert r["status"] == "done"
        assert r["tokens"] == refs[i]
    # the boundary amortization actually happened (greedy self-spec
    # accepts every draft, so ~K tokens commit per boundary)
    assert report["metrics"]["decode_steps"] < plain_steps


def test_spec_seeded_sampling_token_exact():
    """Exact-match acceptance is temperature-independent: the verify step
    samples each window position with the SAME position-folded key the
    sequential path would use, so sampled speculative output reproduces
    generate(rng_fold="position") bit-for-bit too."""
    decode_model, paged_model, params = _family("gpt2")
    prompts = _prompts((8, 5, 11), seed=7)
    sample_kw = dict(temperature=0.9, top_k=5)
    refs = _refs(decode_model, params, prompts, max_new=10, **sample_kw)
    engine = _spec_engine(paged_model, params, num_slots=2, **sample_kw)
    report = engine.run(_requests(prompts, max_new=10))
    for i in range(len(prompts)):
        assert report["results"][f"r{i}"]["tokens"] == refs[i]


def test_spec_preemption_restart_bit_identical():
    """Block pressure with a speculative window in flight: the preempted
    request replays to the same tokens — speculative growth is clamped to
    the request ceiling and the rng folds absolute positions, so the
    accept/reject sequence replays exactly."""
    from distributed_pytorch_example_tpu.models.gpt2 import GPT2

    decode_model, _, params = _family("gpt2")
    model = GPT2(**GPT2_KW, decode=True, paged_num_blocks=12,
                 paged_block_size=4, paged_max_blocks=8)
    prompts = _prompts((8, 8), seed=8)
    refs = _refs(decode_model, params, prompts, temperature=0.0,
                 max_new=20)
    engine = _spec_engine(model, params, num_slots=2, temperature=0.0)
    report = engine.run(_requests(prompts, max_new=20))
    assert report["metrics"]["preempted"] >= 1
    for i in range(2):
        r = report["results"][f"r{i}"]
        assert r["status"] == "done"
        assert r["tokens"] == refs[i]


def test_spec_metrics_reported():
    """The report carries the serve-line decode metrics: tokens/sec over
    decode-boundary wall time and the drafted-token accept rate (1.0 for
    greedy self-speculation except final-window ceiling truncation)."""
    _, paged_model, params = _family("gpt2")
    prompts = _prompts((8, 5), seed=9)
    engine = _spec_engine(paged_model, params, num_slots=2, temperature=0.0)
    m = engine.run(_requests(prompts, max_new=12))["metrics"]
    assert m["decode_tokens"] > 0
    assert m["decode_tokens_per_sec"] > 0
    assert m["spec_accept_rate"] is not None
    assert 0.8 <= m["spec_accept_rate"] <= 1.0
    plain = InferenceEngine(
        paged_model, params, num_slots=2, temperature=0.0
    )
    pm = plain.run(_requests(prompts, max_new=12))["metrics"]
    assert pm["spec_accept_rate"] is None  # speculation off -> no rate
    assert pm["decode_tokens"] > 0


def test_spec_requires_matching_geometry():
    """A draft with a different paged geometry cannot share the engine's
    table layout; the constructor refuses it up front."""
    from distributed_pytorch_example_tpu.models.gpt2 import GPT2

    _, paged_model, params = _family("gpt2")
    other = GPT2(**GPT2_KW, decode=True, paged_num_blocks=16,
                 paged_block_size=8, paged_max_blocks=4)
    with pytest.raises(ValueError, match="geometry|paged"):
        InferenceEngine(
            paged_model, params, draft_model=other, draft_params=params,
            spec_tokens=4,
        )
    with pytest.raises(ValueError, match="spec_tokens"):
        InferenceEngine(
            paged_model, params, draft_model=paged_model,
            draft_params=params, spec_tokens=1,
        )

"""graft-intake: sealed shards, quarantine remap, supervised prefetch
workers, loader-state resume, and the multi-host epoch-plan crosscheck."""

import os
import threading

import numpy as np
import pytest

from distributed_pytorch_example_tpu.data import intake
from distributed_pytorch_example_tpu.data.streaming import (
    StreamingImageShards,
    write_image_shards,
)
from distributed_pytorch_example_tpu.data.text import (
    load_token_file,
    write_token_file,
)
from distributed_pytorch_example_tpu.robustness import chaos


# ---------------------------------------------------------------------------
# sealed files
# ---------------------------------------------------------------------------


def _write_blob(tmp_path, name="blob.npy", n=512):
    path = str(tmp_path / name)
    np.save(path, np.arange(n, dtype=np.int64))
    return path


def test_seal_verify_roundtrip(tmp_path):
    path = _write_blob(tmp_path)
    assert intake.verify_file(path) is None  # legacy: no sidecar
    side = intake.seal_file(path)
    assert os.path.exists(side) and side == path + intake.SIDECAR_SUFFIX
    assert intake.verify_file(path) is True


@pytest.mark.parametrize("mode", ["bitflip", "truncate"])
def test_verify_catches_payload_damage(tmp_path, mode):
    path = _write_blob(tmp_path)
    intake.seal_file(path)
    chaos.corrupt_file(path, mode=mode, seed=7)
    assert intake.verify_file(path) is False


def test_verify_catches_torn_sidecar(tmp_path):
    path = _write_blob(tmp_path)
    side = intake.seal_file(path)
    chaos.corrupt_file(side, mode="truncate")
    assert intake.verify_file(path) is False


# ---------------------------------------------------------------------------
# quarantine digest + remap
# ---------------------------------------------------------------------------


def test_quarantine_digest_order_independent_and_dedups():
    assert intake.quarantine_digest([]) == 0
    a = intake.quarantine_digest([3, 1, 7])
    assert a == intake.quarantine_digest([7, 3, 1])
    assert a == intake.quarantine_digest([1, 1, 3, 7, 7])
    assert a != intake.quarantine_digest([1, 3])


def test_remap_is_deterministic_and_lands_in_pool():
    indices = np.arange(64, dtype=np.int64)
    bad = (indices >= 16) & (indices < 32)
    pool = np.concatenate([np.arange(16), np.arange(32, 64)])
    salt = intake.quarantine_digest([1])
    out1 = intake.remap_indices(indices, bad, pool, salt)
    out2 = intake.remap_indices(indices.copy(), bad.copy(), pool, salt)
    np.testing.assert_array_equal(out1, out2)
    # untouched samples stay put; remapped ones land in the intact pool
    np.testing.assert_array_equal(out1[~bad], indices[~bad])
    assert np.isin(out1[bad], pool).all()
    # a different quarantine set draws a different replacement stream
    out3 = intake.remap_indices(indices, bad, pool,
                                intake.quarantine_digest([2]))
    assert not np.array_equal(out1[bad], out3[bad])


def test_remap_no_bad_mask_is_identity_and_empty_pool_raises():
    indices = np.arange(8, dtype=np.int64)
    none_bad = np.zeros(8, bool)
    assert intake.remap_indices(indices, none_bad,
                                np.empty(0, np.int64), 0) is indices
    with pytest.raises(intake.ShardCorruptError, match="every shard"):
        intake.remap_indices(indices, ~none_bad, np.empty(0, np.int64), 0)


# ---------------------------------------------------------------------------
# multi-host epoch plan
# ---------------------------------------------------------------------------


def test_epoch_plan_digest_sensitivity():
    base = intake.epoch_plan_digest(0, 1, [])
    assert base == intake.epoch_plan_digest(0, 1, [])
    assert base != intake.epoch_plan_digest(1, 1, [])
    assert base != intake.epoch_plan_digest(0, 2, [])
    assert base != intake.epoch_plan_digest(0, 1, [3])


def test_check_plan_agreement_names_divergent_host():
    d = intake.epoch_plan_digest(0, 1, [])
    intake.check_plan_agreement(np.asarray([d, d, d, d], np.uint64), 1)
    rogue = intake.epoch_plan_digest(0, 1, [5])
    with pytest.raises(RuntimeError, match=r"host\(s\) \[2\]"):
        intake.check_plan_agreement(
            np.asarray([d, d, rogue, d], np.uint64), epoch=1
        )


def test_crosscheck_epoch_plan_single_process(tmp_path, devices):
    """World size 1: returns the digest without any collective; the digest
    folds in the dataset's live quarantine set."""
    from distributed_pytorch_example_tpu.data.loader import DeviceLoader

    root = str(tmp_path / "s")
    rng = np.random.default_rng(0)
    imgs = rng.integers(0, 256, (64, 4, 4, 3)).astype(np.uint8)
    labels = rng.integers(0, 5, 64).astype(np.int64)
    write_image_shards(root, [(imgs, labels)], shard_size=16, seal=True)
    ds = StreamingImageShards(root)
    loader = DeviceLoader(ds, 16, shuffle=True, seed=3, prefetch=0,
                          num_shards=1, shard_id=0)
    d0 = intake.crosscheck_epoch_plan(loader, epoch=1)
    assert d0 == intake.epoch_plan_digest(3, 1, [])
    ds.quarantine([2], reason="test")
    assert intake.crosscheck_epoch_plan(loader, epoch=1) == (
        intake.epoch_plan_digest(3, 1, [2])
    )


# ---------------------------------------------------------------------------
# supervised prefetch worker
# ---------------------------------------------------------------------------


def _drain(worker):
    out = []
    while True:
        item = worker.next_batch()
        if item is None:
            return out
        out.append(item)


def test_prefetch_worker_exact_sequence_from_any_start():
    w = intake.PrefetchWorker(lambda i: i * 10, start=3, stop=9, maxsize=2)
    try:
        assert _drain(w) == [30, 40, 50, 60, 70, 80]
        assert w.next_batch() is None  # exhausted stays exhausted
        assert w.restarts == 0
    finally:
        w.close()


def test_prefetch_worker_restart_reproduces_exact_batch():
    crashed = []

    def make(i):
        if i == 4 and not crashed:
            crashed.append(i)
            raise ValueError("decode exploded")
        return ("batch", i)

    w = intake.PrefetchWorker(make, start=0, stop=8, maxsize=2)
    try:
        got = _drain(w)
        assert got == [("batch", i) for i in range(8)]  # no skip, no repeat
        assert w.restarts == 1
    finally:
        w.close()


def test_prefetch_worker_retries_transient_oserror_in_place():
    flaked = []

    def make(i):
        if i == 2 and len(flaked) < 2:
            flaked.append(i)
            raise OSError("flaky NFS")
        return i

    w = intake.PrefetchWorker(make, start=0, stop=5, maxsize=2)
    try:
        assert _drain(w) == list(range(5))
        assert w.io_retries == 2
        assert w.restarts == 0  # healed in place, no restart consumed
    finally:
        w.close()


def test_prefetch_worker_restart_budget_exhaustion_raises():
    def make(i):
        if i == 1:
            raise ValueError("permanently broken batch")
        return i

    w = intake.PrefetchWorker(make, start=0, stop=4, maxsize=2,
                              max_restarts=2)
    try:
        assert w.next_batch() == 0
        with pytest.raises(ValueError, match="permanently broken"):
            while w.next_batch() is not None:
                pass
        assert w.restarts > 2
    finally:
        w.close()


def test_prefetch_worker_close_joins_thread_and_is_idempotent():
    before = {t.name for t in threading.enumerate()}
    w = intake.PrefetchWorker(lambda i: i, start=0, stop=1000, maxsize=1,
                              name="leakcheck")
    assert w.next_batch() == 0
    w.close()
    w.close()  # idempotent
    leaked = [
        t for t in threading.enumerate()
        if t.name == "intake-leakcheck" and t.is_alive()
        and t.name not in before
    ]
    assert not leaked, f"leaked prefetch threads: {leaked}"
    assert w.next_batch() is None  # closed worker serves nothing


# ---------------------------------------------------------------------------
# chaos hooks
# ---------------------------------------------------------------------------


def test_chaos_corrupt_shard_fires_on_nth_touch(tmp_path):
    path = _write_blob(tmp_path, "images_00001.npy")
    intake.seal_file(path)
    chaos.install(chaos.ChaosPlan(
        [chaos.Fault("corrupt-shard", path_substr="images_00001", nth=2)]
    ))
    try:
        chaos.shard_read(path)
        assert intake.verify_file(path) is True  # first touch: intact
        chaos.shard_read(path)
        assert intake.verify_file(path) is False  # nth touch flipped a bit
        chaos.shard_read(str(tmp_path / "images_00009.npy"))  # no match: noop
    finally:
        chaos.uninstall()


def test_chaos_kill_decode_worker_fires_once():
    chaos.install(chaos.ChaosPlan(
        [chaos.Fault("kill-decode-worker", step=2)]
    ))
    try:
        chaos.decode_worker(0)
        chaos.decode_worker(1)
        with pytest.raises(RuntimeError, match="decode worker killed"):
            chaos.decode_worker(2)
        chaos.decode_worker(2)  # one-shot: restart replays clean
    finally:
        chaos.uninstall()


# ---------------------------------------------------------------------------
# streaming integrity modes
# ---------------------------------------------------------------------------


def _sealed_shards(tmp_path, name="shards", n=128, shard_size=32):
    root = str(tmp_path / name)
    rng = np.random.default_rng(0)
    imgs = rng.integers(0, 256, (n, 4, 4, 3)).astype(np.uint8)
    labels = rng.integers(0, 7, n).astype(np.int64)
    nshards = write_image_shards(
        root, [(imgs, labels)], shard_size=shard_size, seal=True
    )
    return root, imgs, labels, nshards


def test_streaming_writer_seals_every_file(tmp_path):
    root, _, _, nshards = _sealed_shards(tmp_path)
    assert nshards == 4
    for f in sorted(os.listdir(root)):
        if f.endswith(".npy"):
            assert intake.verify_file(os.path.join(root, f)) is True


def test_streaming_quarantines_corrupt_shard_and_remaps(tmp_path):
    root, _, _, _ = _sealed_shards(tmp_path)
    chaos.corrupt_file(os.path.join(root, "images_00002.npy"))
    events = []
    intake.set_event_sink(lambda kind, **f: events.append((kind, f)))
    try:
        ds = StreamingImageShards(root)
        batch = ds.get_batch(np.arange(64, 96))  # exactly shard 2
        assert ds.quarantined_shards == {2}
        # every served sample was remapped off the quarantined shard
        assert batch["x"].shape == (32, 4, 4, 3)
        kinds = [k for k, _ in events]
        assert "shard_quarantine" in kinds
    finally:
        intake.set_event_sink(None)
    # detected-on-touch == pre-armed control: same remapped batches
    control = StreamingImageShards(root)
    control.quarantine([2], reason="control")
    cb = control.get_batch(np.arange(64, 96))
    np.testing.assert_array_equal(batch["x"], cb["x"])
    np.testing.assert_array_equal(batch["y"], cb["y"])


def test_streaming_strict_mode_raises(tmp_path):
    root, _, _, _ = _sealed_shards(tmp_path, "strict")
    chaos.corrupt_file(os.path.join(root, "images_00001.npy"))
    ds = StreamingImageShards(root, integrity="strict")
    with pytest.raises(intake.ShardCorruptError, match="images_00001"):
        ds.get_batch(np.arange(32, 64))


def test_streaming_integrity_off_skips_verification(tmp_path):
    root, _, _, _ = _sealed_shards(tmp_path, "off")
    chaos.corrupt_file(os.path.join(root, "images_00000.npy"))
    ds = StreamingImageShards(root, integrity="off")
    ds.get_batch(np.arange(0, 32))  # corrupt bytes served unchecked
    assert ds.quarantined_shards == set()


def test_streaming_corrupt_label_shard_quarantined_eagerly(tmp_path):
    root, _, _, _ = _sealed_shards(tmp_path, "labels")
    chaos.corrupt_file(os.path.join(root, "labels_00003.npy"))
    ds = StreamingImageShards(root)
    assert ds.quarantined_shards == {3}  # caught at open, pre-np.load
    batch = ds.get_batch(np.arange(96, 128))  # shard 3's index range
    assert np.isin(batch["y"], np.arange(7)).all()


def test_streaming_quarantine_rejects_out_of_range(tmp_path):
    root, _, _, _ = _sealed_shards(tmp_path, "range")
    ds = StreamingImageShards(root)
    with pytest.raises(ValueError, match="out of range"):
        ds.quarantine([99])


def test_streaming_bad_integrity_mode_rejected(tmp_path):
    root, _, _, _ = _sealed_shards(tmp_path, "mode")
    with pytest.raises(ValueError, match="integrity"):
        StreamingImageShards(root, integrity="yolo")


# ---------------------------------------------------------------------------
# token files
# ---------------------------------------------------------------------------


def test_token_file_seal_and_verify(tmp_path):
    path = str(tmp_path / "corpus.bin")
    ids = np.arange(4096, dtype=np.uint16)
    write_token_file(path, ids)  # seal=True default
    ds = load_token_file(path, seq_len=64)
    assert len(ds) == 64
    chaos.corrupt_file(path)
    with pytest.raises(intake.ShardCorruptError, match="sidecar"):
        load_token_file(path, seq_len=64)
    # verify=False: explicit opt-out still loads
    assert len(load_token_file(path, seq_len=64, verify=False)) == 64


# ---------------------------------------------------------------------------
# loader-state resume
# ---------------------------------------------------------------------------


def test_loader_manifest_and_restore_roundtrip(tmp_path, devices):
    from distributed_pytorch_example_tpu.data.loader import DeviceLoader

    root, _, _, _ = _sealed_shards(tmp_path, "resume")
    ds = StreamingImageShards(root)
    ds.quarantine([1], reason="test")
    loader = DeviceLoader(ds, 16, shuffle=True, seed=11, prefetch=0,
                          num_shards=1, shard_id=0)
    man = intake.loader_manifest(loader, epoch=2, batch_in_epoch=5)
    assert man == {
        "format": intake.LOADER_MANIFEST_FORMAT,
        "epoch": 2,
        "batch_in_epoch": 5,
        "seed": 11,
        "shuffle": True,
        "quarantine": [1],
        "quarantine_digest": intake.quarantine_digest([1]),
    }

    fresh_ds = StreamingImageShards(root)
    fresh = DeviceLoader(fresh_ds, 16, shuffle=True, seed=11, prefetch=0,
                         num_shards=1, shard_id=0)
    events = []
    cursor = intake.restore_loader_state(
        fresh, man, on_event=lambda k, **f: events.append((k, f))
    )
    assert cursor == 5
    assert fresh_ds.quarantined_shards == {1}  # re-armed pre-first-batch
    assert events and events[0][0] == "loader_quarantine_restored"


def test_restore_loader_state_seed_mismatch_hard_fails(tmp_path, devices):
    from distributed_pytorch_example_tpu.data.loader import DeviceLoader
    from distributed_pytorch_example_tpu.data.synthetic import _ArrayDataset

    ds = _ArrayDataset({
        "x": np.zeros((64, 4), np.float32),
        "y": np.zeros(64, np.int32),
    })
    loader = DeviceLoader(ds, 16, seed=0, prefetch=0,
                          num_shards=1, shard_id=0)
    man = {"format": 1, "epoch": 0, "batch_in_epoch": 2, "seed": 999,
           "quarantine": []}
    with pytest.raises(ValueError, match="seed 999"):
        intake.restore_loader_state(loader, man)


def test_loader_manifest_none_without_sampler():
    class Bare:
        pass

    assert intake.loader_manifest(Bare(), 0, 0) is None
    with pytest.raises(ValueError, match="no sampler"):
        intake.restore_loader_state(Bare(), {"seed": 0})


# ---------------------------------------------------------------------------
# in-memory decoded-shard cache
# ---------------------------------------------------------------------------


def test_shard_cache_lru_eviction_and_stats():
    cache = intake.ShardCache(capacity_mb=1)  # 1 MiB
    kb = 256 * 1024
    a, b, c = (np.zeros(kb, np.uint8) for _ in range(3))
    assert cache.put(0, a) and cache.put(1, b) and cache.put(2, c)
    assert len(cache) == 3 and cache.stats()["resident_bytes"] == 3 * kb
    cache.get(0)  # refresh 0 — 1 becomes LRU
    assert cache.put(3, np.zeros(2 * kb, np.uint8))  # evicts 1 (LRU)
    assert cache.get(1) is None and cache.get(0) is not None
    st = cache.stats()
    assert st["evictions"] == 1 and st["entries"] == 3
    assert st["resident_bytes"] <= st["capacity_bytes"]
    # an array bigger than the whole cache is refused, never admitted
    big = np.zeros(2 * 1024 * 1024, np.uint8)
    assert not cache.admits(big.nbytes) and not cache.put(9, big)
    # replacement adjusts resident bytes instead of double-counting
    before = cache.stats()["resident_bytes"]
    assert cache.put(0, np.zeros(kb // 2, np.uint8))
    assert cache.stats()["resident_bytes"] == before - kb // 2
    cache.invalidate(0)
    assert cache.get(0) is None
    with pytest.raises(ValueError):
        intake.ShardCache(capacity_mb=0)


def test_shard_cache_serves_identical_rows_and_quarantine_invalidates(
    tmp_path,
):
    root, imgs, labels, nshards = _sealed_shards(tmp_path, "cache")
    ds = StreamingImageShards(root, max_open_shards=1, cache_mb=64)
    cold = ds.get_batch(np.arange(0, 128, 8))  # touches all 4 shards
    warm = ds.get_batch(np.arange(0, 128, 8))  # every row from cache
    np.testing.assert_array_equal(cold["x"], warm["x"])
    np.testing.assert_array_equal(cold["y"], warm["y"])
    st = ds.cache_stats
    assert st["entries"] == nshards and st["hits"] > 0
    # quarantine drops the cached copy along with the memmap
    ds.quarantine([2], reason="test")
    assert ds.cache_stats["entries"] == nshards - 1
    # disabled by default: no stats surface, no cache path
    assert StreamingImageShards(root).cache_stats is None

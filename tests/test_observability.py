"""Observability subsystems: metrics JSONL, throughput records, profiler."""

import json
import os

import jax
import numpy as np
import optax
import pytest

import distributed_pytorch_example_tpu as dpx


def tiny_trainer(tmp_path, **kw):
    mesh = dpx.runtime.make_mesh()
    return dpx.train.Trainer(
        dpx.models.SimpleNet(hidden_size=32),
        dpx.train.ClassificationTask(),
        optax.adam(1e-3),
        partitioner=dpx.parallel.data_parallel(mesh),
        checkpoint_dir=str(tmp_path / "ckpt"),
        **kw,
    ), mesh


def tiny_loader(mesh, n=64):
    ds = dpx.data.SyntheticClassificationDataset(num_samples=n, input_size=784)
    return dpx.data.DeviceLoader(ds, 16, mesh=mesh, seed=0)


def test_metrics_jsonl_written(devices, tmp_path):
    trainer, mesh = tiny_trainer(tmp_path)
    history = trainer.fit(tiny_loader(mesh), tiny_loader(mesh, 32), epochs=2)
    path = tmp_path / "ckpt" / "metrics.jsonl"
    assert path.exists()
    records = [json.loads(l) for l in path.read_text().splitlines()]
    assert len(records) == 2
    assert records[0]["epoch"] == 0 and records[1]["epoch"] == 1
    for rec, hist in zip(records, history):
        assert rec["train_loss"] == pytest.approx(hist["train_loss"])
        assert rec["samples_per_sec"] > 0


def test_metrics_file_explicit_path(devices, tmp_path):
    trainer, mesh = tiny_trainer(
        tmp_path, metrics_file=str(tmp_path / "m.jsonl")
    )
    trainer.fit(tiny_loader(mesh), epochs=1)
    assert (tmp_path / "m.jsonl").exists()


def test_profiler_trace_captured(devices, tmp_path):
    trace_dir = tmp_path / "trace"
    trainer, mesh = tiny_trainer(
        tmp_path, profile_dir=str(trace_dir), profile_window=(1, 3)
    )
    trainer.fit(tiny_loader(mesh), epochs=1)  # 4 steps: window closes inside
    files = [
        os.path.join(dp, f)
        for dp, _, fs in os.walk(trace_dir)
        for f in fs
    ]
    assert files, "profiler produced no trace files"


def test_profiler_window_past_end_still_closes(devices, tmp_path):
    trainer, mesh = tiny_trainer(
        tmp_path, profile_dir=str(tmp_path / "t2"), profile_window=(2, 999)
    )
    trainer.fit(tiny_loader(mesh), epochs=1)  # close() must stop the trace
    # a second fit must not crash on a dangling active trace
    trainer.fit(tiny_loader(mesh), epochs=1)


def _trace_files(trace_dir):
    return [
        os.path.join(dp, f)
        for dp, _, fs in os.walk(trace_dir)
        for f in fs
    ]


def test_profiler_rebase_shifts_window(devices, tmp_path):
    from distributed_pytorch_example_tpu.runtime.profiler import StepProfiler

    p = StepProfiler(str(tmp_path / "tr"), (2, 4))
    p.rebase(100)  # resume at step 100: window becomes [102, 104)
    assert (p.start_step, p.stop_step) == (102, 104)
    for s in range(100, 108):
        p.step(s)
    p.close()
    assert _trace_files(tmp_path / "tr"), "rebased window produced no trace"
    # the passed window frees the arm slot; a pending one blocks reuse
    assert not p.arm(50, 60)  # can't arm a window already in the past
    assert p.arm(110, 112, reason="skew") is True
    assert p.arm(120, 122) is False  # first trigger wins


def test_profiler_rebase_noop_after_stepping(tmp_path):
    from distributed_pytorch_example_tpu.runtime.profiler import StepProfiler

    p = StepProfiler(str(tmp_path / "t4"), (2, 4))
    p.step(0)
    p.rebase(100)  # stepping already began: window must not move
    assert (p.start_step, p.stop_step) == (2, 4)


def test_profiler_armed_window_never_opens_closes_clean(tmp_path):
    from distributed_pytorch_example_tpu.runtime.profiler import StepProfiler

    p = StepProfiler(str(tmp_path / "t3"), (10, 12))
    for s in range(4):
        p.step(s)  # run ends before the window opens
    p.close()  # must not raise, must not leave an active trace
    assert not p._active
    p.close()  # and stays idempotent


def test_resume_rebases_profiler_window(devices, tmp_path):
    trainer, mesh = tiny_trainer(tmp_path)
    trainer.fit(tiny_loader(mesh), tiny_loader(mesh, 32), epochs=1)  # 4 steps
    trace_dir = tmp_path / "resumed-trace"
    trainer2, _ = tiny_trainer(
        tmp_path, profile_dir=str(trace_dir), profile_window=(1, 3)
    )
    ckpt = tmp_path / "ckpt" / "latest_model.ckpt"
    assert ckpt.exists()
    # resumed global step is 4: without rebase the absolute window [1, 3)
    # is already past and would never open; rebased it traces [5, 7)
    trainer2.fit(
        tiny_loader(mesh), tiny_loader(mesh, 32), epochs=2, resume=str(ckpt)
    )
    assert _trace_files(trace_dir), "resumed run captured no trace"


def test_metrics_writer_marks_nonfinite(tmp_path):
    from distributed_pytorch_example_tpu.train.metrics_writer import (
        MetricsWriter,
    )

    path = tmp_path / "m.jsonl"
    w = MetricsWriter(str(path))
    w.write({"epoch": 0, "val_loss": float("nan"), "train_loss": 1.5})
    w.write({"epoch": 1, "val_loss": 0.25, "grad_norm": float("inf")})
    w.close()
    # every line must stay strict-JSON (json.loads == the jq/JSON.parse bar)
    recs = [json.loads(l) for l in path.read_text().splitlines()]
    # dropped value leaves a visible marker, finite neighbors untouched
    assert "val_loss" not in recs[0]
    assert recs[0]["val_loss_nonfinite"] is True
    assert recs[0]["train_loss"] == 1.5
    assert recs[1]["val_loss"] == 0.25
    assert "grad_norm" not in recs[1]
    assert recs[1]["grad_norm_nonfinite"] is True

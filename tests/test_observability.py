"""Observability subsystems: metrics JSONL, throughput records, profiler."""

import json
import os

import jax
import numpy as np
import optax
import pytest

import distributed_pytorch_example_tpu as dpx


def tiny_trainer(tmp_path, **kw):
    mesh = dpx.runtime.make_mesh()
    return dpx.train.Trainer(
        dpx.models.SimpleNet(hidden_size=32),
        dpx.train.ClassificationTask(),
        optax.adam(1e-3),
        partitioner=dpx.parallel.data_parallel(mesh),
        checkpoint_dir=str(tmp_path / "ckpt"),
        **kw,
    ), mesh


def tiny_loader(mesh, n=64):
    ds = dpx.data.SyntheticClassificationDataset(num_samples=n, input_size=784)
    return dpx.data.DeviceLoader(ds, 16, mesh=mesh, seed=0)


def test_metrics_jsonl_written(devices, tmp_path):
    trainer, mesh = tiny_trainer(tmp_path)
    history = trainer.fit(tiny_loader(mesh), tiny_loader(mesh, 32), epochs=2)
    path = tmp_path / "ckpt" / "metrics.jsonl"
    assert path.exists()
    records = [json.loads(l) for l in path.read_text().splitlines()]
    assert len(records) == 2
    assert records[0]["epoch"] == 0 and records[1]["epoch"] == 1
    for rec, hist in zip(records, history):
        assert rec["train_loss"] == pytest.approx(hist["train_loss"])
        assert rec["samples_per_sec"] > 0


def test_metrics_file_explicit_path(devices, tmp_path):
    trainer, mesh = tiny_trainer(
        tmp_path, metrics_file=str(tmp_path / "m.jsonl")
    )
    trainer.fit(tiny_loader(mesh), epochs=1)
    assert (tmp_path / "m.jsonl").exists()


def test_profiler_trace_captured(devices, tmp_path):
    trace_dir = tmp_path / "trace"
    trainer, mesh = tiny_trainer(
        tmp_path, profile_dir=str(trace_dir), profile_window=(1, 3)
    )
    trainer.fit(tiny_loader(mesh), epochs=1)  # 4 steps: window closes inside
    files = [
        os.path.join(dp, f)
        for dp, _, fs in os.walk(trace_dir)
        for f in fs
    ]
    assert files, "profiler produced no trace files"


def test_profiler_window_past_end_still_closes(devices, tmp_path):
    trainer, mesh = tiny_trainer(
        tmp_path, profile_dir=str(tmp_path / "t2"), profile_window=(2, 999)
    )
    trainer.fit(tiny_loader(mesh), epochs=1)  # close() must stop the trace
    # a second fit must not crash on a dangling active trace
    trainer.fit(tiny_loader(mesh), epochs=1)

"""Chunked vocab-blockwise cross-entropy vs the dense reference path.

Pins the fused LM loss (ops/chunked_ce.py) to the semantics of the dense
``tied_head_logits -> optax.softmax_cross_entropy_with_integer_labels``
pipeline it replaces (the reference's ``nn.CrossEntropyLoss``, reference
train.py:250): values, argmax, and gradients w.r.t. hidden states,
embedding, and bias.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributed_pytorch_example_tpu.ops.chunked_ce import chunked_softmax_xent


def _dense(hidden, embedding, targets, bias=None, dtype=jnp.bfloat16):
    logits = jax.lax.dot_general(
        hidden.astype(dtype), embedding.astype(dtype),
        (((hidden.ndim - 1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    if bias is not None:
        logits = logits + bias.astype(jnp.float32)
    loss = optax.softmax_cross_entropy_with_integer_labels(logits, targets)
    return loss, jnp.argmax(logits, axis=-1).astype(jnp.int32)


@pytest.mark.parametrize("vocab,block", [(1000, 256), (1000, 1000), (777, 128)])
@pytest.mark.parametrize("bias", [False, True])
def test_matches_dense(vocab, block, bias):
    k = jax.random.PRNGKey(0)
    kx, ke, kt, kb = jax.random.split(k, 4)
    hidden = jax.random.normal(kx, (4, 9, 32), jnp.float32)
    embedding = jax.random.normal(ke, (vocab, 32), jnp.float32) * 0.1
    targets = jax.random.randint(kt, (4, 9), 0, vocab)
    b = jax.random.normal(kb, (vocab,)) * 0.1 if bias else None

    ref_loss, ref_argmax = _dense(hidden, embedding, targets, b)
    loss, argmax = chunked_softmax_xent(
        hidden, embedding, targets, bias=b, block_size=block
    )
    np.testing.assert_allclose(loss, ref_loss, rtol=2e-5, atol=2e-5)
    np.testing.assert_array_equal(argmax, ref_argmax)


@pytest.mark.parametrize("bias", [False, True])
def test_grads_match_dense(bias):
    vocab, dim = 500, 16
    k = jax.random.PRNGKey(1)
    kx, ke, kt, kb = jax.random.split(k, 4)
    hidden = jax.random.normal(kx, (3, 7, dim), jnp.float32)
    embedding = jax.random.normal(ke, (vocab, dim)) * 0.1
    targets = jax.random.randint(kt, (3, 7), 0, vocab)
    b = jax.random.normal(kb, (vocab,)) * 0.1 if bias else None

    def loss_chunked(h, e, bb):
        loss, _ = chunked_softmax_xent(
            h, e, targets, bias=bb, block_size=128
        )
        return loss.mean()

    def loss_dense(h, e, bb):
        loss, _ = _dense(h, e, targets, bb)
        return loss.mean()

    args = (hidden, embedding, b) if bias else (hidden, embedding, None)
    argnums = (0, 1, 2) if bias else (0, 1)
    g_chunk = jax.grad(loss_chunked, argnums=argnums)(*args)
    g_dense = jax.grad(loss_dense, argnums=argnums)(*args)
    for gc, gd in zip(g_chunk, g_dense):
        # both sides do bf16 matmuls; backward orders differ slightly
        np.testing.assert_allclose(gc, gd, rtol=6e-3, atol=6e-5)


def test_bf16_hidden_states():
    """bf16 hidden states (the model's compute dtype) round-trip cleanly."""
    vocab, dim = 300, 24
    k = jax.random.PRNGKey(2)
    kx, ke, kt = jax.random.split(k, 3)
    hidden = jax.random.normal(kx, (2, 5, dim), jnp.bfloat16)
    embedding = jax.random.normal(ke, (vocab, dim)) * 0.1
    targets = jax.random.randint(kt, (2, 5), 0, vocab)
    ref_loss, ref_argmax = _dense(hidden, embedding, targets)
    loss, argmax = chunked_softmax_xent(
        hidden, embedding, targets, block_size=128
    )
    np.testing.assert_allclose(loss, ref_loss, rtol=2e-5, atol=2e-5)
    np.testing.assert_array_equal(argmax, ref_argmax)

    def f(h, e):
        l, _ = chunked_softmax_xent(h, e, targets, block_size=128)
        return l.mean()

    gh, ge = jax.grad(f, argnums=(0, 1))(hidden, embedding)
    assert gh.dtype == jnp.bfloat16 and ge.dtype == embedding.dtype


def test_argmax_tie_breaks_first():
    """Duplicate embedding rows: argmax picks the lowest id, like dense."""
    dim = 8
    emb_row = jnp.ones((1, dim))
    embedding = jnp.concatenate([emb_row] * 6, axis=0)  # all identical
    hidden = jnp.ones((1, 1, dim))
    targets = jnp.zeros((1, 1), jnp.int32)
    _, argmax = chunked_softmax_xent(
        hidden, embedding, targets, block_size=2
    )
    assert int(argmax[0, 0]) == 0


def test_shape_validation():
    hidden = jnp.zeros((2, 3, 8))
    embedding = jnp.zeros((10, 9))
    targets = jnp.zeros((2, 3), jnp.int32)
    with pytest.raises(ValueError, match="hidden dim"):
        chunked_softmax_xent(hidden, embedding, targets)
    with pytest.raises(ValueError, match="targets shape"):
        chunked_softmax_xent(
            jnp.zeros((2, 3, 9)), embedding, jnp.zeros((2, 4), jnp.int32)
        )


def test_serialized_long_context_path_matches(monkeypatch):
    """The memory-bound serialization path (optimization_barrier threading
    + block shrink, engaged above _SERIALIZE_TOTAL_BYTES) is numerically
    identical to the free-scheduling path: loss, argmax, and grads match
    with the thresholds forced to zero."""
    from distributed_pytorch_example_tpu.ops import chunked_ce as cc

    rng = np.random.default_rng(0)
    n, d, v = 64, 32, 517
    hidden = jnp.asarray(rng.standard_normal((n, d)), jnp.float32)
    emb = jnp.asarray(rng.standard_normal((v, d)) * 0.1, jnp.float32)
    tg = jnp.asarray(rng.integers(0, v, size=(n,)), jnp.int32)

    def f(h, e):
        loss, am = cc.chunked_softmax_xent(
            h, e, tg, block_size=128, dtype=jnp.float32
        )
        return loss.sum(), am

    (l0, am0), g0 = jax.value_and_grad(f, argnums=(0, 1), has_aux=True)(
        hidden, emb
    )
    monkeypatch.setattr(cc, "_SERIALIZE_TOTAL_BYTES", 0)
    monkeypatch.setattr(cc, "_SERIALIZE_BLOCK_BYTES", 0)
    (l1, am1), g1 = jax.value_and_grad(f, argnums=(0, 1), has_aux=True)(
        hidden, emb
    )
    np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6)
    np.testing.assert_array_equal(np.asarray(am0), np.asarray(am1))
    for a, b in zip(g0, g1):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-6
        )


def test_local_token_count_committed_sharding(mesh_2x2x2):
    """The HBM guard sizes tokens from the operand's COMMITTED sharding
    when one is available (ADVICE r5): a batch-sharded placement counts
    one shard, a replicated placement counts every token."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from distributed_pytorch_example_tpu.ops import chunked_ce as cc

    sharded = jax.device_put(
        jnp.zeros((8, 16, 8), jnp.float32),
        NamedSharding(mesh_2x2x2, P(("data", "fsdp"))),
    )
    assert cc._local_token_count(sharded, 128) == 32  # 4-way batch shard
    replicated = jax.device_put(
        jnp.zeros((8, 16, 8), jnp.float32),
        NamedSharding(mesh_2x2x2, P()),
    )
    assert cc._local_token_count(replicated, 128) == 128


def test_serialize_guard_engages_for_replicated_batch(monkeypatch, mesh_2x2x2):
    """ADVICE r5 regression: a replicated-layout trace under an ACTIVE
    multi-chip mesh must not divide the token count by the mesh span —
    the old ``n // data_parallel_size(mesh)`` guess disengaged the HBM
    guard exactly where all ``n`` tokens are chip-resident. With the
    layout unknown at trace time the guard now assumes the full ``n``
    and threads its optimization barriers."""
    from distributed_pytorch_example_tpu.analysis.shardlint import iter_eqns
    from distributed_pytorch_example_tpu.ops import chunked_ce as cc

    n, d, v = 64, 8, 64
    # global all-blocks f32 logits: 64 * 64 * 4 = 16384 bytes. Threshold
    # between that and the old mesh-span estimate (16384 / dp4 = 4096):
    # the fixed guard serializes, the old guess would not.
    monkeypatch.setattr(cc, "_SERIALIZE_TOTAL_BYTES", 8192)
    hidden = jnp.zeros((4, 16, d), jnp.float32)
    emb = jnp.zeros((v, d), jnp.float32)
    tg = jnp.zeros((4, 16), jnp.int32)
    with mesh_2x2x2:
        jaxpr = jax.make_jaxpr(
            lambda h, e, t: cc.chunked_softmax_xent(
                h, e, t, block_size=32, dtype=jnp.float32
            )
        )(hidden, emb, tg)
    barriers = [
        e for e in iter_eqns(jaxpr)
        if e.primitive.name == "optimization_barrier"
    ]
    assert barriers, "guard must engage when the layout is unknown"

"""Ulysses all-to-all sequence parallelism vs dense attention."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from distributed_pytorch_example_tpu.ops.attention import _xla_attention
from distributed_pytorch_example_tpu.ops.ulysses import (
    ulysses_attention,
    ulysses_attention_sharded,
)
from distributed_pytorch_example_tpu.runtime import MeshSpec, make_mesh
from distributed_pytorch_example_tpu.runtime.jax_compat import shard_map as _shard_map


def make_qkv(batch=2, seq=256, heads=4, head_dim=32, seed=0):
    rng = np.random.default_rng(seed)
    shape = (batch, seq, heads, head_dim)
    return tuple(
        jnp.asarray(rng.standard_normal(shape), jnp.float32) for _ in range(3)
    )


@pytest.mark.parametrize("causal", [False, True])
def test_matches_full_attention(devices, causal):
    mesh = make_mesh(MeshSpec(data=2, sequence=4))
    q, k, v = make_qkv()
    scale = q.shape[-1] ** -0.5
    expected = _xla_attention(q, k, v, None, None, causal, scale)
    got = ulysses_attention_sharded(q, k, v, mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_grads_match_full_attention(devices, causal):
    mesh = make_mesh(MeshSpec(data=2, sequence=4))
    q, k, v = make_qkv(seq=128)
    scale = q.shape[-1] ** -0.5

    def loss_ref(q, k, v):
        return jnp.sum(_xla_attention(q, k, v, None, None, causal, scale) ** 2)

    def loss_uly(q, k, v):
        return jnp.sum(
            ulysses_attention_sharded(q, k, v, mesh, causal=causal) ** 2
        )

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_uly = jax.grad(loss_uly, argnums=(0, 1, 2))(q, k, v)
    for gr, gg, name in zip(g_ref, g_uly, "qkv"):
        np.testing.assert_allclose(
            np.asarray(gg), np.asarray(gr), atol=1e-4, err_msg=f"d{name}"
        )


def test_gqa_under_ulysses(devices):
    """GQA works through the all-to-all path (ring serves it too — see
    tests/test_ring_attention.py — with different memory trade-offs)."""
    mesh = make_mesh(MeshSpec(data=2, sequence=4))
    q, _, _ = make_qkv(heads=8)
    _, k, v = make_qkv(heads=4, seed=1)
    scale = q.shape[-1] ** -0.5
    expected = _xla_attention(q, k, v, None, None, True, scale)
    got = ulysses_attention_sharded(q, k, v, mesh, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_gqa_grouped_matches_dense(devices, causal):
    """kv_heads < axis size takes the GROUPED path (no replication):
    kv=2 over a 4-device sequence axis (rep=2) must match dense GQA."""
    mesh = make_mesh(MeshSpec(data=2, sequence=4))
    q, _, _ = make_qkv(heads=8)
    _, k, v = make_qkv(heads=2, seed=1)
    scale = q.shape[-1] ** -0.5
    expected = _xla_attention(q, k, v, None, None, causal, scale)
    got = ulysses_attention_sharded(q, k, v, mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_gqa_grouped_grads_match_dense(devices, causal):
    mesh = make_mesh(MeshSpec(data=2, sequence=4))
    q, _, _ = make_qkv(heads=8, seq=128)
    _, k, v = make_qkv(heads=2, seq=128, seed=3)
    scale = q.shape[-1] ** -0.5

    def loss_ref(q, k, v):
        return jnp.sum(_xla_attention(q, k, v, None, None, causal, scale) ** 2)

    def loss_uly(q, k, v):
        return jnp.sum(
            ulysses_attention_sharded(q, k, v, mesh, causal=causal) ** 2
        )

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_uly = jax.grad(loss_uly, argnums=(0, 1, 2))(q, k, v)
    for gr, gg, name in zip(g_ref, g_uly, "qkv"):
        assert gg.shape == gr.shape
        np.testing.assert_allclose(
            np.asarray(gg), np.asarray(gr), atol=1e-4, err_msg=f"d{name}"
        )


def test_gqa_grouped_kv_mask_and_dead_rows(devices):
    """Key-padding masks stream through the grouped path; a fully-padded
    batch row emits zeros (the _xla_attention contract)."""
    mesh = make_mesh(MeshSpec(data=2, sequence=4))
    q, _, _ = make_qkv(heads=8)
    _, k, v = make_qkv(heads=2, seed=5)
    mask = np.ones((2, 256), bool)
    mask[0, 100:] = False
    mask[1, :] = False  # fully padded row
    kv_mask = jnp.asarray(mask)
    scale = q.shape[-1] ** -0.5
    expected = _xla_attention(q, k, v, None, kv_mask, False, scale)
    got = ulysses_attention_sharded(q, k, v, mesh, kv_mask=kv_mask)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=2e-5)
    np.testing.assert_array_equal(np.asarray(got)[1], 0.0)


def test_gqa_grouped_bf16_forward_and_grads(devices):
    """The custom-VJP grouped path in the training dtype (bfloat16)."""
    mesh = make_mesh(MeshSpec(data=2, sequence=4))
    q, _, _ = make_qkv(heads=8, seq=128)
    _, k, v = make_qkv(heads=2, seq=128, seed=7)
    qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))
    scale = q.shape[-1] ** -0.5

    expected = _xla_attention(qb, kb, vb, None, None, True, scale)
    got = ulysses_attention_sharded(qb, kb, vb, mesh, causal=True)
    assert got.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(expected, np.float32),
        atol=2e-2,
    )

    def loss_ref(q, k, v):
        return jnp.sum(
            _xla_attention(q, k, v, None, None, True, scale)
            .astype(jnp.float32) ** 2
        )

    def loss_uly(q, k, v):
        return jnp.sum(
            ulysses_attention_sharded(q, k, v, mesh, causal=True)
            .astype(jnp.float32) ** 2
        )

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(qb, kb, vb)
    g_uly = jax.grad(loss_uly, argnums=(0, 1, 2))(qb, kb, vb)
    for gr, gg, name in zip(g_ref, g_uly, "qkv"):
        assert gg.dtype == jnp.bfloat16
        np.testing.assert_allclose(
            np.asarray(gg, np.float32), np.asarray(gr, np.float32),
            atol=0.15, rtol=0.05, err_msg=f"d{name}",
        )


def test_gqa_grouped_exchange_layout_and_bytes(devices):
    """The grouped K/V exchange routes each device exactly its group
    head's 1/rep sequence shard: content pinned against manual slicing,
    and per-device KV bytes are rep x SMALLER than the replicating
    layout's (B, S, 1, H)."""
    from distributed_pytorch_example_tpu.ops.ulysses import (
        _grouped_kv_exchange,
    )

    mesh = make_mesh(MeshSpec(data=2, sequence=4))
    p, rep, kv = 4, 2, 2
    B, S, H = 2, 64, 8
    Sp, c = S // p, S // p // rep
    rng = np.random.default_rng(11)
    k = jnp.asarray(rng.standard_normal((B, S, kv, H)), jnp.float32)

    fn = _shard_map(
        lambda x: _grouped_kv_exchange(x, "sequence", rep)[None],
        mesh=mesh,
        in_specs=P(None, "sequence", None, None),
        out_specs=P("sequence"),
    )
    per_dev = np.asarray(fn(k))  # (p, B, p, c, H): leading dim = device
    for d in range(p):
        g, r = d // rep, d % rep
        for s in range(p):
            expect = np.asarray(k)[:, s * Sp + r * c : s * Sp + (r + 1) * c, g]
            np.testing.assert_array_equal(per_dev[d, :, s], expect)
    # per-device KV: S/rep positions vs the replicated layout's S
    local_bytes = per_dev[0].nbytes
    assert local_bytes == B * (S // rep) * H * 4
    assert local_bytes * rep == B * S * H * 4  # rep x reduction


def test_indivisible_heads_raise(devices):
    mesh = make_mesh(MeshSpec(data=2, sequence=4))
    q, k, v = make_qkv(heads=6)
    with pytest.raises(ValueError, match="divisible"):
        ulysses_attention_sharded(q, k, v, mesh)


def test_llama_sequence_parallel_matches_dense(devices):
    """Full LLaMA (RoPE + GQA) under ulysses SP == no-SP output."""
    from distributed_pytorch_example_tpu.models.llama import Llama

    kw = dict(vocab_size=101, max_len=64, model_dim=32, num_layers=2,
              num_heads=4, num_kv_heads=2, mlp_dim=64)
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, 101, (2, 64)), jnp.int32
    )
    dense = Llama(**kw)
    sp = Llama(seq_axis="sequence", sp_mode="ulysses", **kw)
    variables = dense.init(jax.random.key(0), tokens)
    expected = dense.apply(variables, tokens)
    mesh = make_mesh(MeshSpec(data=2, sequence=4))
    with mesh:
        got = sp.apply(variables, tokens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=2e-5)


def test_llama_gqa_grouped_through_trainer(devices):
    """The grouped GQA path (kv_heads < sequence axis) inside the real
    training graph: custom VJP + shard_map + jit + donated state."""
    import optax

    import distributed_pytorch_example_tpu as dpx
    from distributed_pytorch_example_tpu.models.llama import Llama

    mesh = make_mesh(MeshSpec(data=2, sequence=4))
    model = Llama(
        vocab_size=64, max_len=64, model_dim=32, num_layers=2, num_heads=4,
        num_kv_heads=2, mlp_dim=64, seq_axis="sequence",
        sp_mode="ulysses",  # kv=2 < axis 4 -> grouped exchange + ring
    )
    ds = dpx.data.SyntheticTokenDataset(num_samples=16, seq_len=32, vocab_size=64)
    loader = dpx.data.DeviceLoader(ds, 4, mesh=mesh, num_shards=1, shard_id=0)
    trainer = dpx.train.Trainer(
        model, dpx.train.CausalLMTask(), optax.adam(1e-3),
        partitioner=dpx.parallel.data_parallel(mesh),
    )
    # the mesh context is REQUIRED for the SP dispatch to see the axis:
    # without it _ring_mesh raises instead of silently tracing dense
    # attention (the raw train_step is jitted outside Trainer.train_epoch)
    with mesh:
        it = iter(loader)
        trainer.init(next(it)["tokens"])
        state = trainer.state
        losses = []
        for batch in loader:
            state, metrics = trainer.train_step(state, batch)
            losses.append(float(metrics["loss"]))
    assert len(losses) >= 3
    assert all(np.isfinite(l) for l in losses)
    with pytest.raises(RuntimeError, match="with mesh"):
        # no mesh context: loud error, not a silent dense fallback
        model.init(jax.random.key(0), jnp.zeros((2, 32), jnp.int32))


def test_gpt2_ulysses_through_trainer(devices):
    """GPT-2 with sp_mode=ulysses trains on a data x sequence mesh."""
    import optax

    import distributed_pytorch_example_tpu as dpx
    from distributed_pytorch_example_tpu.models.gpt2 import GPT2

    mesh = make_mesh(MeshSpec(data=2, sequence=4))
    model = GPT2(vocab_size=64, max_len=32, model_dim=32, num_layers=1,
                 num_heads=4, mlp_dim=64, seq_axis="sequence",
                 sp_mode="ulysses")
    ds = dpx.data.SyntheticTokenDataset(num_samples=16, seq_len=16, vocab_size=64)
    loader = dpx.data.DeviceLoader(ds, 4, mesh=mesh, num_shards=1, shard_id=0)
    trainer = dpx.train.Trainer(
        model, dpx.train.CausalLMTask(), optax.adam(1e-3),
        partitioner=dpx.parallel.data_parallel(mesh),
    )
    with mesh:  # required for SP dispatch (see the llama twin above)
        it = iter(loader)
        trainer.init(next(it)["tokens"])
        _, metrics = trainer.train_step(trainer.state, next(it))
    assert np.isfinite(float(metrics["loss"]))

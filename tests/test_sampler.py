"""ShardedSampler: the DistributedSampler determinism contract.

Covers the properties SURVEY.md §4 calls out as untested in the reference:
set_epoch reshuffle semantics (reference train.py:267), disjoint coverage,
wrap padding, and cross-host determinism without communication.
"""

import numpy as np
import pytest

from distributed_pytorch_example_tpu.data.sampler import (
    ShardedSampler,
    permutation,
)


def test_permutation_is_a_permutation():
    for n in (1, 2, 7, 100, 1000):
        p = permutation(n, seed=42)
        assert sorted(p.tolist()) == list(range(n))


def test_permutation_deterministic_and_seed_sensitive():
    assert np.array_equal(permutation(100, 7), permutation(100, 7))
    assert not np.array_equal(permutation(100, 7), permutation(100, 8))


def test_shards_disjoint_and_cover():
    n, shards = 1000, 4
    samplers = [
        ShardedSampler(n, num_shards=shards, shard_id=i, seed=3) for i in range(shards)
    ]
    all_indices = np.concatenate([s.shard_indices() for s in samplers])
    assert len(all_indices) == n  # 1000 divides evenly by 4
    assert sorted(all_indices.tolist()) == list(range(n))


def test_wrap_padding_uneven():
    # 10 samples over 4 shards → 12 total, wraps the first 2 indices
    n, shards = 10, 4
    samplers = [
        ShardedSampler(n, num_shards=shards, shard_id=i, shuffle=False)
        for i in range(shards)
    ]
    assert all(len(s) == 3 for s in samplers)
    combined = np.concatenate([s.shard_indices() for s in samplers])
    assert len(combined) == 12
    assert set(combined.tolist()) == set(range(10))  # every sample appears
    counts = np.bincount(combined, minlength=10)
    assert counts.sum() == 12 and counts.max() == 2  # exactly 2 wrapped


def test_drop_last():
    s = ShardedSampler(10, num_shards=4, shard_id=0, drop_last=True, shuffle=False)
    assert len(s) == 2
    combined = np.concatenate(
        [
            ShardedSampler(10, 4, i, drop_last=True, shuffle=False).shard_indices()
            for i in range(4)
        ]
    )
    assert len(combined) == 8 and len(set(combined.tolist())) == 8


def test_set_epoch_reshuffles_deterministically():
    a = ShardedSampler(100, num_shards=2, shard_id=0, seed=5)
    b = ShardedSampler(100, num_shards=2, shard_id=0, seed=5)
    a.set_epoch(0)
    b.set_epoch(0)
    e0 = a.shard_indices()
    assert np.array_equal(e0, b.shard_indices())
    a.set_epoch(1)
    assert not np.array_equal(e0, a.shard_indices())
    a.set_epoch(0)
    assert np.array_equal(e0, a.shard_indices())


def test_hosts_agree_without_communication():
    """Every shard derives from the same global permutation independently."""
    n, shards, epoch = 64, 8, 3
    views = []
    for i in range(shards):
        s = ShardedSampler(n, num_shards=shards, shard_id=i, seed=11)
        s.set_epoch(epoch)
        views.append(s.global_indices())
    for v in views[1:]:
        assert np.array_equal(views[0], v)


def test_no_shuffle_is_identity_order():
    s = ShardedSampler(8, num_shards=2, shard_id=0, shuffle=False)
    assert s.shard_indices().tolist() == [0, 2, 4, 6]


def test_invalid_shard_id():
    with pytest.raises(ValueError):
        ShardedSampler(10, num_shards=2, shard_id=2)

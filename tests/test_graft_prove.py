"""graft-prove: shardflow verdict fixtures, congruence hang detection,
and static-HBM-envelope cross-validation.

Tier-1 scope: pure spec/verdict unit tests (``-m lint``, backend-free),
tiny traced fixtures per shardflow verdict, the deliberately
branch-mismatched ``shard_map`` fixture the congruence checker must flag,
the shipped-schedules-pass-clean check on one pipe config, and ONE cheap
config's envelope-vs-measured tolerance. The all-config static sweep runs
under ``-m slow``.
"""

import json
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from distributed_pytorch_example_tpu.analysis import congruence as cong
from distributed_pytorch_example_tpu.analysis import envelope as env_mod
from distributed_pytorch_example_tpu.analysis import shardflow as sf

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CHEAP_CONFIG = "data+fsdp+expert"


# ---------------------------------------------------------------------------
# spec algebra (backend-free: -m lint)
# ---------------------------------------------------------------------------


@pytest.mark.lint
def test_canon_spec_normalizes_forms():
    assert sf.canon_spec(None, 2) == ((), ())
    assert sf.canon_spec(P("data", None), 2) == (("data",), ())
    assert sf.canon_spec(P(("data", "fsdp")), 3) == (("data", "fsdp"), (), ())
    # over-long specs truncate to rank; short ones pad
    assert sf.canon_spec(P("a", "b"), 1) == (("a",),)


@pytest.mark.lint
def test_classify_transition_verdicts():
    src = sf.canon_spec(P("data", None), 2)
    assert sf.classify_transition(src, src) == "keep"
    assert sf.classify_transition(src, sf.canon_spec(None, 2)) == "gather"
    assert sf.classify_transition(sf.canon_spec(None, 2), src) == "slice"
    assert sf.classify_transition(
        src, sf.canon_spec(P("model", None), 2)
    ) == "reshard"
    # axis moving between dims is a reshard, not gather+slice
    assert sf.classify_transition(
        sf.canon_spec(P("data", None), 2), sf.canon_spec(P(None, "data"), 2)
    ) == "reshard"


@pytest.mark.lint
def test_spec_span_and_axes():
    mesh_shape = {"data": 2, "model": 4}
    spec = sf.canon_spec(P(("data", "model"), None), 2)
    assert sf.spec_span(spec, mesh_shape) == 8
    assert sf.spec_axes(spec) == ("data", "model")
    assert sf.spec_span(sf.canon_spec(None, 2), mesh_shape) == 1


# ---------------------------------------------------------------------------
# envelope gates (backend-free: -m lint)
# ---------------------------------------------------------------------------


@pytest.mark.lint
def test_envelope_compare_drift_and_band():
    committed = {"predicted_peak_bytes": 1000}
    assert env_mod.compare_envelope("cfg", committed, 1005, None) == []
    v = env_mod.compare_envelope("cfg", committed, 1200, None)
    assert [x.rule for x in v] == ["envelope-drift"]
    # measured band: predicted must stay an upper bound...
    v = env_mod.compare_envelope("cfg", {}, 900, 1000)
    assert [x.rule for x in v] == ["envelope-underestimate"]
    # ...but not an absurdly loose one
    v = env_mod.compare_envelope("cfg", {}, 5000, 1000)
    assert [x.rule for x in v] == ["envelope-slack"]
    assert env_mod.compare_envelope("cfg", {}, 2500, 1000) == []


@pytest.mark.lint
def test_envelope_would_oom_gate():
    assert env_mod.gate_envelope("cfg", 100, None) is None
    assert env_mod.gate_envelope("cfg", 100, 200) is None
    gate = env_mod.gate_envelope("cfg", 300, 200)
    assert gate is not None and gate.rule == "would-oom"
    assert "before compile" in gate.detail


@pytest.mark.lint
def test_hbm_limit_env_parsing(monkeypatch):
    monkeypatch.setenv("DPX_HBM_LIMIT", "2G")
    assert env_mod.hbm_limit_from_env() == 2 << 30
    monkeypatch.setenv("DPX_HBM_LIMIT", "512M")
    assert env_mod.hbm_limit_from_env() == 512 << 20
    monkeypatch.setenv("DPX_HBM_LIMIT", "12345")
    assert env_mod.hbm_limit_from_env() == 12345
    monkeypatch.setenv("DPX_HBM_LIMIT", "garbage")
    assert env_mod.hbm_limit_from_env() is None
    monkeypatch.delenv("DPX_HBM_LIMIT")
    assert env_mod.hbm_limit_from_env() is None


# ---------------------------------------------------------------------------
# shardflow verdict fixtures: one traced jaxpr per verdict
# ---------------------------------------------------------------------------


@pytest.fixture
def mesh_2x4(devices):
    from jax.sharding import Mesh

    return Mesh(np.array(devices[:8]).reshape(2, 4), ("data", "model"))


MESH_SHAPE = {"data": 2, "model": 4}


def _constrain(mesh, spec):
    def f(x):
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, spec)
        )

    return jax.make_jaxpr(f)(jnp.zeros((8, 16)))


def test_shardflow_keep_no_events(mesh_2x4):
    jaxpr = _constrain(mesh_2x4, P("data", None))
    rep = sf.trace_shardings(jaxpr, [P("data", None)], MESH_SHAPE)
    assert rep.events == [] and rep.lost == 0
    assert rep.out_specs == [sf.canon_spec(P("data", None), 2)]


def test_shardflow_gather_fixture(mesh_2x4):
    jaxpr = _constrain(mesh_2x4, P(None, None))
    rep = sf.trace_shardings(jaxpr, [P("data", None)], MESH_SHAPE)
    (e,) = rep.events
    assert (e.kind, e.collective, e.axes) == ("gather", "all-gather",
                                              ("data",))
    assert e.bytes == 8 * 16 * 4 and e.source  # full-buffer gather


def test_shardflow_reshard_fixture(mesh_2x4):
    jaxpr = _constrain(mesh_2x4, P("model", None))
    rep = sf.trace_shardings(jaxpr, [P("data", None)], MESH_SHAPE)
    (e,) = rep.events
    assert (e.kind, e.collective) == ("reshard", "all-to-all")


def test_shardflow_partial_sum_and_mismatch(mesh_2x4):
    jaxpr = jax.make_jaxpr(lambda x, w: x @ w)(
        jnp.zeros((8, 16)), jnp.zeros((16, 4))
    )
    # both operands shard the contracted dim the same way: partial sum
    rep = sf.trace_shardings(
        jaxpr, [P(None, "model"), P("model", None)], MESH_SHAPE
    )
    (e,) = rep.events
    assert (e.kind, e.collective, e.axes) == ("partial-sum", "all-reduce",
                                              ("model",))
    # one-sided contracted-dim sharding: the implicit FSDP-style gather
    rep = sf.trace_shardings(jaxpr, [None, P("model", None)], MESH_SHAPE)
    (e,) = rep.events
    assert (e.kind, e.collective, e.axes) == ("mismatch", "all-gather",
                                              ("model",))


def test_shardflow_explicit_collective_in_shard_map(mesh_2x4):
    from jax.experimental.shard_map import shard_map

    def body(x):
        return jax.lax.psum(x, "data")

    f = shard_map(body, mesh=mesh_2x4, in_specs=P("data", None),
                  out_specs=P(None, None), check_rep=False)
    jaxpr = jax.make_jaxpr(f)(jnp.zeros((8, 16)))
    rep = sf.trace_shardings(jaxpr, [P("data", None)], MESH_SHAPE)
    (e,) = rep.events
    assert (e.kind, e.collective, e.axes) == ("explicit", "all-reduce",
                                              ("data",))
    # out_names propagate: the psum'd output leaves the region replicated
    assert rep.out_specs == [sf.canon_spec(None, 2)]


def test_shardflow_liveness_peak_positive(mesh_2x4):
    jaxpr = _constrain(mesh_2x4, P("data", None))
    rep = sf.trace_shardings(jaxpr, [P("data", None)], MESH_SHAPE)
    # per-chip arg bytes: (8,16) f32 split 2-way over 'data'
    assert rep.arg_bytes == 8 * 16 * 4 // 2
    assert rep.peak_bytes >= rep.arg_bytes


# ---------------------------------------------------------------------------
# congruence: the branch-mismatched shard_map fixture MUST be flagged;
# benign/uniform variants must not
# ---------------------------------------------------------------------------


def _cond_fixture(mesh, pred_axis, true_branch, false_branch):
    from jax.experimental.shard_map import shard_map

    def body(x):
        idx = jax.lax.axis_index(pred_axis)
        return jax.lax.cond(idx == 0, true_branch, false_branch, x)

    f = shard_map(body, mesh=mesh, in_specs=P("data", None),
                  out_specs=P("data", None), check_rep=False)
    return jax.make_jaxpr(f)(jnp.zeros((8, 16)))


def test_congruence_flags_branch_mismatched_shard_map(mesh_2x4):
    """The acceptance fixture: predicate varies along 'data', one branch
    psums over 'data', the other doesn't — a guaranteed real-TPU hang,
    caught statically."""
    jaxpr = _cond_fixture(
        mesh_2x4, "data",
        lambda v: jax.lax.psum(v, "data"), lambda v: v * 2.0,
    )
    rep = cong.check_congruence(jaxpr)
    assert not rep.ok
    (f,) = rep.hazards
    assert f.predicate_axes == ("data",)
    assert f.mismatch_axes == ("data",)
    assert "HAZARD" in f.render()
    # one branch psums, the other is collective-free (branch order in the
    # jaxpr is index order, not source order)
    assert sorted(len(s) for s in f.branch_seqs) == [0, 1]


def test_congruence_benign_mismatch_on_disjoint_axis(mesh_2x4):
    """Predicate varies along 'model' but the mismatched collective spans
    'data': every member of any data-group agrees on the predicate, so no
    rendezvous splits — reported as a note-level finding, not a hazard
    (the shipped predicate_head pattern)."""
    jaxpr = _cond_fixture(
        mesh_2x4, "model",
        lambda v: jax.lax.psum(v, "data"), lambda v: v * 2.0,
    )
    rep = cong.check_congruence(jaxpr)
    assert rep.ok
    (f,) = rep.findings
    assert not f.hazard and f.predicate_axes == ("model",)


def test_congruence_identical_sequences_clean(mesh_2x4):
    jaxpr = _cond_fixture(
        mesh_2x4, "data",
        lambda v: jax.lax.psum(v, "data"),
        lambda v: jax.lax.psum(v * 2.0, "data"),
    )
    rep = cong.check_congruence(jaxpr)
    assert rep.ok and rep.findings == [] and rep.conds == 1


def test_congruence_psum_clears_predicate_taint(mesh_2x4):
    """A predicate derived from a psum'd value is identical on every chip
    of the reduced axis — the mismatch cannot split the mesh."""
    from jax.experimental.shard_map import shard_map

    def body(x):
        s = jax.lax.psum(x.sum(), "data")
        return jax.lax.cond(
            s > 0, lambda v: jax.lax.psum(v, "data"), lambda v: v, x
        )

    f = shard_map(body, mesh=mesh_2x4, in_specs=P("data", None),
                  out_specs=P("data", None), check_rep=False)
    rep = cong.check_congruence(jax.make_jaxpr(f)(jnp.zeros((8, 16))))
    assert rep.ok
    (f_,) = rep.findings
    assert not f_.hazard and f_.predicate_axes == ()


def test_congruence_shipped_pipe_schedule_clean(devices):
    """The acceptance criterion's other half: a shipped pipeline schedule
    (cond-predicated, collectives inside shard_map) audits clean — its
    bad-step predication and schedule conds never split a rendezvous."""
    case = _build_case("data+pipe", devices)
    rep = cong.congruence_for_case(case)
    assert rep.ok, [f.render() for f in rep.hazards]
    assert rep.regions >= 1


# ---------------------------------------------------------------------------
# real-config acceptance: attribution on the cheap config + envelope band
# ---------------------------------------------------------------------------


def _build_case(name, devices):
    sys.path.insert(0, REPO_ROOT)
    import __graft_entry__ as entry

    config = next(
        c for c in entry.DRYRUN_CONFIGS
        if entry.dryrun_config_name(c) == name
    )
    case = entry.build_dryrun_case(config, devices)
    assert not isinstance(case, str), case
    return case


def test_shardflow_attributes_collectives_on_cheap_config(devices):
    """shardflow must attribute at least one known collective to an op
    AND param path on a green config: the FSDP weight all-gathers and DP
    gradient partial-sums carry flax module paths through the jaxpr."""
    case = _build_case(CHEAP_CONFIG, devices)
    rep = sf.flow_for_case(case)
    events = rep.comm_events()
    assert events, "no communication events on a sharded config"
    kinds = rep.attributed_kinds()
    assert "all-reduce" in kinds  # the DP gradient sync class
    # at least one event names a module path (flax name stack survives)
    pathed = [e for e in events if "decoder" in e.path or "GPT2" in e.path]
    assert pathed, [e.render() for e in events[:5]]
    # and honest accounting: propagation gave up on only a sliver of eqns
    assert rep.lost <= rep.eqns * 0.05


def test_envelope_within_band_on_cheap_config(devices):
    """Predicted static peak vs the compiler's measured residency stays
    inside the stated ratio band on a config that compiles here."""
    from distributed_pytorch_example_tpu.analysis import collectives as coll
    from distributed_pytorch_example_tpu.telemetry import cost

    case = _build_case(CHEAP_CONFIG, devices)
    _, compiled = coll.compile_case(case)
    measured = cost.measured_hbm_peak(compiled)
    assert measured and measured > 0
    rep = sf.flow_for_case(case)
    ratio = rep.peak_bytes / measured
    assert env_mod.RATIO_MIN <= ratio <= env_mod.RATIO_MAX, (
        f"predicted={rep.peak_bytes} measured={measured} ratio={ratio:.2f}"
    )
    assert env_mod.compare_envelope(
        CHEAP_CONFIG, {}, rep.peak_bytes, measured
    ) == []


def test_envelope_file_commits_stated_tolerance():
    envelopes = env_mod.load_envelopes()
    assert envelopes is not None, "analysis/memory_envelopes.json missing"
    meta = envelopes["_meta"]
    assert meta["ratio_band"] == [env_mod.RATIO_MIN, env_mod.RATIO_MAX]
    assert "jax" in meta and meta["n_devices"] == 8
    configs = envelopes["configs"]
    # every measured entry in the committed file respects the band
    measured_entries = {
        k: v for k, v in configs.items()
        if v.get("measured_hbm_peak_bytes")
    }
    assert measured_entries, "no measured entries committed"
    for name, rec in measured_entries.items():
        ratio = rec["predicted_peak_bytes"] / rec["measured_hbm_peak_bytes"]
        assert env_mod.RATIO_MIN <= ratio <= env_mod.RATIO_MAX, (name, ratio)
    # serve programs are first-class envelope entries too
    assert "serve/prefill" in configs and "serve/decode" in configs


def test_serve_traced_programs_flow(devices):
    """The serving engine's two programs run through shardflow: the
    tensor-sharded attention/MLP matmuls must yield attributed events."""
    sys.path.insert(0, REPO_ROOT)
    import __graft_entry__ as entry

    case = entry.build_serve_case(devices)
    assert not isinstance(case, str), case
    mesh_shape = {str(k): int(v) for k, v in dict(case.mesh.shape).items()}
    programs = case.engine.traced_programs()
    assert set(programs) == {"serve/prefill", "serve/decode"}
    for name, (jaxpr, in_specs) in programs.items():
        rep = sf.trace_shardings(jaxpr, in_specs, mesh_shape)
        assert rep.comm_events(), f"{name}: no events"
        assert cong.check_congruence(jaxpr).ok, name


# ---------------------------------------------------------------------------
# full static sweep (slow): every traceable config flows + audits clean
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_full_static_sweep_all_configs(devices):
    """Every dryrun config (including the 9 the backend cannot compile)
    traces, flows, and passes congruence; every green config attributes
    at least one collective (the tentpole acceptance criterion)."""
    sys.path.insert(0, REPO_ROOT)
    import __graft_entry__ as entry

    envelopes = env_mod.load_envelopes() or {"configs": {}}
    green = {
        k for k, v in envelopes["configs"].items()
        if v.get("measured_hbm_peak_bytes")
    }
    flowed = 0
    for config in entry.DRYRUN_CONFIGS:
        name = entry.dryrun_config_name(config)
        case = entry.build_dryrun_case(config, jax.devices()[:8])
        if isinstance(case, str):
            continue
        rep = sf.flow_for_case(case)
        assert rep.eqns > 0
        crep = cong.congruence_for_case(case)
        assert crep.ok, (name, [f.render() for f in crep.hazards])
        if name in green:
            assert rep.comm_events(), f"{name}: green config, no events"
            assert any(e.path for e in rep.comm_events()), name
        flowed += 1
    assert flowed >= 7

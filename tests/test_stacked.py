"""Stacked decoder: scan-over-layers params, pipelined vs sequential."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributed_pytorch_example_tpu.models.stacked import StackedDecoder
from distributed_pytorch_example_tpu.runtime import MeshSpec, make_mesh

CFG = dict(
    num_layers=4, num_heads=2, head_dim=8, model_dim=16, mlp_dim=32
)


def _init_and_input(model, seed=0, batch=8, seq=8):
    x = jnp.asarray(
        np.random.default_rng(seed).standard_normal((batch, seq, 16)),
        jnp.float32,
    )
    params = model.init(jax.random.key(0), x)["params"]
    return params, x


def test_param_shapes_are_layer_stacked(devices):
    model = StackedDecoder(**CFG)
    params, _ = _init_and_input(model)
    assert params["q_kernel"].shape == (4, 16, 16)
    assert params["down_kernel"].shape == (4, 32, 16)
    assert params["ln1_scale"].shape == (4, 16)


def test_stacked_init_std_matches_per_layer(devices):
    """Stacked kernels must init like the per-layer blocks they mirror:
    leading layer (and expert) dims are batch axes, NOT fan-in — otherwise
    init std shrinks by sqrt(L) (sqrt(L*E) for experts) and a pipelined
    model trained from init differs from the sequential reference."""
    from distributed_pytorch_example_tpu.models.stacked import (
        StackedLlamaDecoder,
    )

    model = StackedDecoder(**CFG, moe_experts=4, moe_top_k=2)
    params, _ = _init_and_input(model)
    expect = 1.0 / np.sqrt(16)  # lecun: sqrt(1/fan_in), fan_in = model_dim
    got = float(np.std(np.asarray(params["q_kernel"])))
    np.testing.assert_allclose(got, expect, rtol=0.2)
    got_e = float(np.std(np.asarray(params["moe_up_kernel"])))
    np.testing.assert_allclose(got_e, expect, rtol=0.2)

    lmodel = StackedLlamaDecoder(**LLAMA_MOE_CFG)
    lp = lmodel.init(
        jax.random.key(0), jnp.zeros((2, 8, 16), jnp.float32)
    )["params"]
    np.testing.assert_allclose(
        float(np.std(np.asarray(lp["moe_gate_kernel"]))), expect, rtol=0.2
    )


def test_pipelined_matches_sequential(devices):
    seq_model = StackedDecoder(**CFG)
    pipe_model = StackedDecoder(**CFG, pipe_axis="pipe")
    params, x = _init_and_input(seq_model)
    expected = seq_model.apply({"params": params}, x)
    mesh = make_mesh(MeshSpec(data=2, pipe=4))
    with mesh:
        got = jax.jit(
            lambda p, x: pipe_model.apply({"params": p}, x)
        )(params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=1e-5)


def test_pipelined_grads_match_sequential(devices):
    seq_model = StackedDecoder(**CFG)
    pipe_model = StackedDecoder(**CFG, pipe_axis="pipe")
    params, x = _init_and_input(seq_model, seed=1)
    mesh = make_mesh(MeshSpec(data=2, pipe=4))

    def loss_seq(p):
        return jnp.mean(seq_model.apply({"params": p}, x) ** 2)

    def loss_pipe(p):
        return jnp.mean(pipe_model.apply({"params": p}, x) ** 2)

    g_seq = jax.grad(loss_seq)(params)
    with mesh:
        g_pipe = jax.jit(jax.grad(loss_pipe))(params)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-4
        ),
        g_pipe,
        g_seq,
    )


def test_remat_pipelined_matches(devices):
    seq_model = StackedDecoder(**CFG)
    pipe_model = StackedDecoder(**CFG, pipe_axis="pipe", remat=True)
    params, x = _init_and_input(seq_model, seed=2)
    expected = seq_model.apply({"params": params}, x)
    mesh = make_mesh(MeshSpec(data=2, pipe=4))
    with mesh:
        got = jax.jit(lambda p, x: pipe_model.apply({"params": p}, x))(params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=1e-5)


def test_matches_per_layer_transformer_stack(devices):
    """Stacked block math == TransformerBlock math with copied weights."""
    from distributed_pytorch_example_tpu.models.transformer import (
        TransformerStack,
    )

    ref = TransformerStack(
        num_layers=2, num_heads=2, head_dim=8, model_dim=16, mlp_dim=32,
        causal=True, prenorm=True,
    )
    x = jnp.asarray(
        np.random.default_rng(3).standard_normal((2, 8, 16)), jnp.float32
    )
    ref_params = ref.init(jax.random.key(1), x, train=False)["params"]

    # copy per-layer module weights into the stacked layout
    def layer(i, name, leaf):
        return ref_params[f"layer_{i}"][name][leaf]

    stacked_params = {}
    for new, (mod, leaf) in {
        "q_kernel": ("attn/q", "kernel"), "q_bias": ("attn/q", "bias"),
        "k_kernel": ("attn/k", "kernel"), "k_bias": ("attn/k", "bias"),
        "v_kernel": ("attn/v", "kernel"), "v_bias": ("attn/v", "bias"),
        "o_kernel": ("attn/o", "kernel"), "o_bias": ("attn/o", "bias"),
        "up_kernel": ("mlp/up", "kernel"), "up_bias": ("mlp/up", "bias"),
        "down_kernel": ("mlp/down", "kernel"), "down_bias": ("mlp/down", "bias"),
        "ln1_scale": ("ln1", "scale"), "ln1_bias": ("ln1", "bias"),
        "ln2_scale": ("ln2", "scale"), "ln2_bias": ("ln2", "bias"),
    }.items():
        parts = mod.split("/")
        leaves = []
        for i in range(2):
            node = ref_params[f"layer_{i}"]
            for p in parts:
                node = node[p]
            leaves.append(node[leaf])
        stacked_params[new] = jnp.stack(leaves)

    model = StackedDecoder(
        num_layers=2, num_heads=2, head_dim=8, model_dim=16, mlp_dim=32,
        causal=True,
    )
    expected = ref.apply({"params": ref_params}, x, train=False)
    got = model.apply({"params": stacked_params}, x)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(expected), atol=1e-5
    )


def test_gpt2_pipelined_through_trainer(devices):
    """Tiny pipelined GPT-2 trains end-to-end on a data x pipe mesh."""
    from distributed_pytorch_example_tpu.data.loader import DeviceLoader
    from distributed_pytorch_example_tpu.data.synthetic import (
        SyntheticTokenDataset,
    )
    from distributed_pytorch_example_tpu.models.gpt2 import GPT2
    from distributed_pytorch_example_tpu.parallel.partition import (
        transformer_partitioner,
    )
    from distributed_pytorch_example_tpu.train.loop import Trainer
    from distributed_pytorch_example_tpu.train.tasks import CausalLMTask

    mesh = make_mesh(MeshSpec(data=2, pipe=4))
    model = GPT2(
        vocab_size=64, max_len=32, model_dim=16, num_layers=4, num_heads=2,
        mlp_dim=32, pipe_axis="pipe",
    )
    dataset = SyntheticTokenDataset(num_samples=32, seq_len=16, vocab_size=64)
    loader = DeviceLoader(dataset, 8, mesh=mesh, num_shards=1, shard_id=0)
    trainer = Trainer(
        model, CausalLMTask(), optax.adam(1e-2),
        partitioner=transformer_partitioner(mesh),
    )
    with mesh:
        trainer.init(next(iter(loader))["tokens"])
        # stage stacks must actually live sharded on the pipe axis
        q_sharding = trainer.state.params["decoder"]["q_kernel"].sharding
        assert "pipe" in (q_sharding.spec[0],)
        losses = []
        state = trainer.state
        for _ in range(4):
            batch = next(iter(loader))
            state, metrics = trainer.train_step(state, batch)
            losses.append(float(metrics["loss"]))
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0], losses


def test_gpt2_pipe_rejects_conflicting_features(devices):
    from distributed_pytorch_example_tpu.models.gpt2 import GPT2

    model = GPT2(
        vocab_size=64, max_len=32, model_dim=16, num_layers=4, num_heads=2,
        mlp_dim=32, pipe_axis="pipe", moe_experts=4,
    )
    with pytest.raises(ValueError, match="pipe_axis"):
        model.init(jax.random.key(0), jnp.zeros((2, 8), jnp.int32))


# -- 1F1B schedule at the model level ----------------------------------------


@pytest.mark.parametrize("family", ["gpt2", "llama"])
def test_1f1b_model_matches_gpipe_schedule(devices, family):
    """Same model under pipe_schedule='1f1b' vs 'gpipe' (4 stages x 8
    microbatches): identical param trees, matching train loss/accuracy and
    matching grads — the GPipe side is itself pinned against sequential,
    so this transitively gives the sequential-equivalence bar."""
    from distributed_pytorch_example_tpu.models.gpt2 import GPT2
    from distributed_pytorch_example_tpu.models.llama import Llama
    from distributed_pytorch_example_tpu.train.tasks import CausalLMTask

    mesh = make_mesh(MeshSpec(data=2, pipe=4))
    task = CausalLMTask()
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, 64, size=(16, 16)), jnp.int32
    )
    common = dict(
        vocab_size=64, max_len=32, model_dim=32, num_layers=4,
        mlp_dim=64, pipe_axis="pipe", pipe_microbatches=8,
        logits_mode="hidden",
    )
    if family == "gpt2":
        mk = lambda sched: GPT2(
            num_heads=4, pipe_schedule=sched, **common
        )
    else:
        mk = lambda sched: Llama(
            num_heads=4, num_kv_heads=2, pipe_schedule=sched, **common
        )
    m_1f1b, m_gpipe = mk("1f1b"), mk("gpipe")
    with mesh:
        params = m_1f1b.init(jax.random.key(0), tokens, train=False)["params"]
        params_g = m_gpipe.init(
            jax.random.key(0), tokens, train=False
        )["params"]
    # schedules must be checkpoint-compatible: identical param trees
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)
        ),
        params, params_g,
    )
    rng = jax.random.key(1)

    def loss_fn(model):
        def f(p):
            with mesh:
                loss, mets, _ = task.compute_loss(
                    model, p, {}, {"tokens": tokens}, rng, train=True
                )
            return loss, mets

        return f

    (l1, mets1), g1 = jax.value_and_grad(
        loss_fn(m_1f1b), has_aux=True
    )(params)
    (l2, mets2), g2 = jax.value_and_grad(
        loss_fn(m_gpipe), has_aux=True
    )(params)
    np.testing.assert_allclose(float(l1), float(l2), rtol=2e-5)
    np.testing.assert_allclose(
        float(mets1["accuracy"]), float(mets2["accuracy"]), atol=1e-3
    )
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-4
        ),
        g1, g2,
    )


def test_1f1b_through_trainer(devices):
    """1F1B GPT-2 trains end-to-end through the Trainer on a data x pipe
    mesh (4 stages, 8 microbatches) and eval still works (GPipe forward)."""
    from distributed_pytorch_example_tpu.data.loader import DeviceLoader
    from distributed_pytorch_example_tpu.data.synthetic import (
        SyntheticTokenDataset,
    )
    from distributed_pytorch_example_tpu.models.gpt2 import GPT2
    from distributed_pytorch_example_tpu.parallel.partition import (
        transformer_partitioner,
    )
    from distributed_pytorch_example_tpu.train.loop import Trainer
    from distributed_pytorch_example_tpu.train.tasks import CausalLMTask

    mesh = make_mesh(MeshSpec(data=2, pipe=4))
    model = GPT2(
        vocab_size=64, max_len=32, model_dim=16, num_layers=4, num_heads=2,
        mlp_dim=32, pipe_axis="pipe", pipe_schedule="1f1b",
        pipe_microbatches=8, logits_mode="hidden",
    )
    dataset = SyntheticTokenDataset(num_samples=32, seq_len=16, vocab_size=64)
    loader = DeviceLoader(dataset, 16, mesh=mesh, num_shards=1, shard_id=0)
    trainer = Trainer(
        model, CausalLMTask(), optax.adam(1e-2),
        partitioner=transformer_partitioner(mesh),
    )
    with mesh:
        trainer.init(next(iter(loader))["tokens"])
        q_sharding = trainer.state.params["decoder"]["q_kernel"].sharding
        assert "pipe" in (q_sharding.spec[0],)
        losses = []
        state = trainer.state
        for _ in range(4):
            batch = next(iter(loader))
            state, metrics = trainer.train_step(state, batch)
            losses.append(float(metrics["loss"]))
        # eval path (train=False) uses the GPipe forward on the same params
        val_loss, val_mets, _ = trainer.task.compute_loss(
            model, state.params, {}, next(iter(loader)), jax.random.key(3),
            train=False,
        )
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0], losses
    assert np.isfinite(float(val_loss))


# -- SP x PP composition -----------------------------------------------------


@pytest.mark.parametrize("family", ["gpt2", "llama"])
def test_sp_pp_matches_dense_pipelined(devices, family):
    """Sequence parallelism INSIDE pipeline stages (the pipeline shard_map
    goes manual over {pipe, sequence}; ring/Ulysses run chunk-local): loss
    and grads equal the same pipelined model on a sequence-span-1 mesh
    (itself pinned against sequential)."""
    from distributed_pytorch_example_tpu.models.gpt2 import GPT2
    from distributed_pytorch_example_tpu.models.llama import Llama
    from distributed_pytorch_example_tpu.train.tasks import CausalLMTask

    mesh_sp = make_mesh(MeshSpec(data=2, pipe=2, sequence=2))
    mesh_dense = make_mesh(MeshSpec(data=4, pipe=2))
    task = CausalLMTask()
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, 64, size=(16, 16)), jnp.int32
    )
    common = dict(
        vocab_size=64, max_len=32, model_dim=32, num_layers=2, mlp_dim=64,
        pipe_axis="pipe", pipe_microbatches=4, logits_mode="hidden",
    )
    if family == "gpt2":
        mk = lambda sp: GPT2(num_heads=4, sp_mode="ring", seq_axis=sp,
                             **common)
    else:
        mk = lambda sp: Llama(num_heads=4, num_kv_heads=2,
                              sp_mode="ulysses", seq_axis=sp, **common)
    m_sp, m_dense = mk("sequence"), mk(None)
    with mesh_sp:
        params = m_sp.init(jax.random.key(0), tokens, train=False)["params"]
    rng = jax.random.key(1)

    def loss(model, mesh):
        def f(p):
            with mesh:
                l, _, _ = task.compute_loss(
                    model, p, {}, {"tokens": tokens}, rng, train=True
                )
            return l

        return f

    l_sp, g_sp = jax.value_and_grad(loss(m_sp, mesh_sp))(params)
    l_d, g_d = jax.value_and_grad(loss(m_dense, mesh_dense))(params)
    np.testing.assert_allclose(float(l_sp), float(l_d), rtol=3e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-4
        ),
        g_sp, g_d,
    )


def test_sp_pp_trainer_actually_uses_sp(devices, monkeypatch):
    """The SP path really traces inside a pipeline stage: spy on the
    chunk-local ring_attention through a Trainer train step on a
    data x pipe x sequence mesh (the VERDICT r4 ask-#2 wiring guard —
    the dense fallback is numerically identical)."""
    from distributed_pytorch_example_tpu.data.loader import DeviceLoader
    from distributed_pytorch_example_tpu.data.synthetic import (
        SyntheticTokenDataset,
    )
    from distributed_pytorch_example_tpu.models.gpt2 import GPT2
    from distributed_pytorch_example_tpu.models import stacked as stacked_mod
    from distributed_pytorch_example_tpu.ops import ring_attention as ring_mod
    from distributed_pytorch_example_tpu.parallel.partition import (
        transformer_partitioner,
    )
    from distributed_pytorch_example_tpu.train.loop import Trainer
    from distributed_pytorch_example_tpu.train.tasks import CausalLMTask

    calls = []
    real = ring_mod.ring_attention

    def spy(*a, **kw):
        calls.append(1)
        return real(*a, **kw)

    monkeypatch.setattr(ring_mod, "ring_attention", spy)

    mesh = make_mesh(MeshSpec(data=2, pipe=2, sequence=2))
    model = GPT2(
        vocab_size=64, max_len=32, model_dim=16, num_layers=2, num_heads=2,
        mlp_dim=32, pipe_axis="pipe", pipe_microbatches=4,
        seq_axis="sequence", sp_mode="ring", logits_mode="hidden",
    )
    dataset = SyntheticTokenDataset(num_samples=32, seq_len=16, vocab_size=64)
    loader = DeviceLoader(dataset, 16, mesh=mesh, num_shards=1, shard_id=0)
    trainer = Trainer(
        model, CausalLMTask(), optax.adam(1e-2),
        partitioner=transformer_partitioner(mesh),
    )
    with mesh:
        trainer.init(next(iter(loader))["tokens"])
        state, metrics = trainer.train_step(trainer.state, next(iter(loader)))
    assert calls, "ring_attention never traced inside the pipeline stages"
    assert np.isfinite(float(metrics["loss"]))


def test_1f1b_composes_with_tensor_parallelism(devices):
    """Megatron TP stays automatic inside the pipe-manual region under
    the 1F1B schedule exactly as under GPipe: a data x pipe x tensor mesh
    trains end-to-end and the loss decreases."""
    from distributed_pytorch_example_tpu.data.loader import DeviceLoader
    from distributed_pytorch_example_tpu.data.synthetic import (
        SyntheticTokenDataset,
    )
    from distributed_pytorch_example_tpu.models.gpt2 import GPT2
    from distributed_pytorch_example_tpu.parallel.partition import (
        transformer_partitioner,
    )
    from distributed_pytorch_example_tpu.train.loop import Trainer
    from distributed_pytorch_example_tpu.train.tasks import CausalLMTask

    mesh = make_mesh(MeshSpec(data=2, pipe=2, tensor=2))
    model = GPT2(
        vocab_size=64, max_len=32, model_dim=32, num_layers=2, num_heads=4,
        mlp_dim=64, pipe_axis="pipe", pipe_schedule="1f1b",
        pipe_microbatches=2, logits_mode="hidden",
    )
    dataset = SyntheticTokenDataset(num_samples=32, seq_len=16, vocab_size=64)
    loader = DeviceLoader(dataset, 8, mesh=mesh, num_shards=1, shard_id=0)
    trainer = Trainer(
        model, CausalLMTask(), optax.adam(1e-2),
        partitioner=transformer_partitioner(mesh),
    )
    with mesh:
        trainer.init(next(iter(loader))["tokens"])
        # TP rules actually engaged: q kernels sharded on 'tensor'
        q_sharding = trainer.state.params["decoder"]["q_kernel"].sharding
        assert "tensor" in tuple(q_sharding.spec)
        losses = []
        state = trainer.state
        for _ in range(3):
            state, m = trainer.train_step(state, next(iter(loader)))
            losses.append(float(m["loss"]))
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0], losses


def test_1f1b_seq_axis_moe_rejected(devices):
    """PP x SP x EP stays rejected on the 1F1B schedule (as on GPipe):
    SP alone now composes (test_sp_pp_1f1b_matches_dense_pipelined), but
    aux accumulation over sequence chunks does not."""
    from distributed_pytorch_example_tpu.models.gpt2 import GPT2

    model = GPT2(
        vocab_size=64, max_len=32, model_dim=16, num_layers=4, num_heads=2,
        mlp_dim=32, pipe_axis="pipe", pipe_schedule="1f1b",
        seq_axis="sequence", moe_experts=4,
    )
    with pytest.raises(ValueError, match="MoE"):
        model.init(jax.random.key(0), jnp.zeros((2, 8), jnp.int32))


def test_interleaved_1f1b_matches_plain_1f1b(devices):
    """pipe_virtual=2 (Megatron-style interleaved chunks: device d holds
    layer chunks {d, d+S}) vs pipe_virtual=1 on the same GPT-2: identical
    flax param tree (the interleaved layout is internal to the runner),
    matching loss/accuracy and grads. 12 layers / (2 stages x 2 chunks)
    = 3 LAYERS PER CHUNK — the multi-layer-chunk shape class (a CLI drive
    caught the Lc>1 reshape leaking into the GPipe eval path; this pins
    both the 1F1B layout and the contiguous eval split)."""
    from distributed_pytorch_example_tpu.models.gpt2 import GPT2
    from distributed_pytorch_example_tpu.train.tasks import CausalLMTask

    mesh = make_mesh(MeshSpec(data=4, pipe=2))
    task = CausalLMTask()
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, 64, size=(16, 16)), jnp.int32
    )
    mk = lambda v: GPT2(
        vocab_size=64, max_len=32, model_dim=32, num_layers=12, num_heads=4,
        mlp_dim=64, pipe_axis="pipe", pipe_schedule="1f1b",
        pipe_microbatches=4, pipe_virtual=v, logits_mode="hidden",
    )
    m_il, m_plain = mk(2), mk(1)
    with mesh:
        params = m_il.init(jax.random.key(0), tokens, train=False)["params"]
    rng = jax.random.key(1)

    def loss(model):
        def f(p):
            with mesh:
                l, mets, _ = task.compute_loss(
                    model, p, {}, {"tokens": tokens}, rng, train=True
                )
            return l, mets

        return f

    (l_il, mets_il), g_il = jax.value_and_grad(
        loss(m_il), has_aux=True
    )(params)
    (l_pl, mets_pl), g_pl = jax.value_and_grad(
        loss(m_plain), has_aux=True
    )(params)
    np.testing.assert_allclose(float(l_il), float(l_pl), rtol=2e-5)
    np.testing.assert_allclose(
        float(mets_il["accuracy"]), float(mets_pl["accuracy"]), atol=1e-3
    )
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-4
        ),
        g_il, g_pl,
    )


def test_interleaved_requires_1f1b_and_divisible_layers(devices):
    from distributed_pytorch_example_tpu.models.gpt2 import GPT2

    with pytest.raises(ValueError, match="pipe_virtual"):
        GPT2(
            vocab_size=64, max_len=32, model_dim=16, num_layers=4,
            num_heads=2, mlp_dim=32, pipe_axis="pipe", pipe_virtual=2,
        ).init(jax.random.key(0), jnp.zeros((2, 8), jnp.int32))
    mesh = make_mesh(MeshSpec(data=2, pipe=4))
    model = GPT2(
        vocab_size=64, max_len=32, model_dim=16, num_layers=6, num_heads=2,
        mlp_dim=32, pipe_axis="pipe", pipe_schedule="1f1b", pipe_virtual=4,
        pipe_microbatches=4, logits_mode="hidden",
    )
    tokens = jnp.zeros((8, 8), jnp.int32)
    with mesh, pytest.raises(ValueError, match="divisible"):
        jax.eval_shape(
            lambda: model.init(
                jax.random.key(0), tokens, train=True,
                targets=tokens,
            )
        )


@pytest.mark.parametrize("family", ["gpt2", "llama"])
def test_sp_pp_1f1b_matches_dense_pipelined(devices, family):
    """SP x PP x 1F1B: ring/Ulysses attention runs chunk-local inside the
    1F1B schedule (shard_map manual over {pipe, sequence}) and the loss is
    the chunk-local pre-shifted-target CE (stacked.shifted_ce_last_args).
    Loss, accuracy sums, and grads equal the same 1F1B model on a
    sequence-span-1 mesh (itself pinned against GPipe -> sequential)."""
    from distributed_pytorch_example_tpu.models.gpt2 import GPT2
    from distributed_pytorch_example_tpu.models.llama import Llama
    from distributed_pytorch_example_tpu.train.tasks import CausalLMTask

    mesh_sp = make_mesh(MeshSpec(data=2, pipe=2, sequence=2))
    mesh_dense = make_mesh(MeshSpec(data=4, pipe=2))
    task = CausalLMTask()
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, 64, size=(16, 16)), jnp.int32
    )
    common = dict(
        vocab_size=64, max_len=32, model_dim=32, num_layers=2, mlp_dim=64,
        pipe_axis="pipe", pipe_schedule="1f1b", pipe_microbatches=4,
        logits_mode="hidden",
    )
    if family == "gpt2":
        mk = lambda sp: GPT2(num_heads=4, sp_mode="ring", seq_axis=sp,
                             **common)
    else:
        mk = lambda sp: Llama(num_heads=4, num_kv_heads=2,
                              sp_mode="ulysses", seq_axis=sp, **common)
    m_sp, m_dense = mk("sequence"), mk(None)
    with mesh_sp:
        params = m_sp.init(jax.random.key(0), tokens, train=False)["params"]
    rng = jax.random.key(1)

    def loss(model, mesh):
        def f(p):
            with mesh:
                l, mets, _ = task.compute_loss(
                    model, p, {}, {"tokens": tokens}, rng, train=True
                )
            return l, mets

        return f

    (l_sp, mets_sp), g_sp = jax.value_and_grad(
        loss(m_sp, mesh_sp), has_aux=True
    )(params)
    (l_d, mets_d), g_d = jax.value_and_grad(
        loss(m_dense, mesh_dense), has_aux=True
    )(params)
    np.testing.assert_allclose(float(l_sp), float(l_d), rtol=3e-5)
    np.testing.assert_allclose(
        float(mets_sp["accuracy"]), float(mets_d["accuracy"]), atol=1e-3
    )
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-4
        ),
        g_sp, g_d,
    )


def test_1f1b_stash_composes_with_tensor_parallelism(devices):
    """pipe_recompute=False under data x pipe x tensor: the stashed vjp
    residuals are TP-sharded arrays riding through the pipe-manual scan
    carry while Megatron TP stays automatic inside the stage, exactly as
    with the recompute backward — and the two backward modes produce the
    SAME loss trajectory from the same init."""
    from distributed_pytorch_example_tpu.data.loader import DeviceLoader
    from distributed_pytorch_example_tpu.data.synthetic import (
        SyntheticTokenDataset,
    )
    from distributed_pytorch_example_tpu.models.gpt2 import GPT2
    from distributed_pytorch_example_tpu.parallel.partition import (
        transformer_partitioner,
    )
    from distributed_pytorch_example_tpu.train.loop import Trainer
    from distributed_pytorch_example_tpu.train.tasks import CausalLMTask

    mesh = make_mesh(MeshSpec(data=2, pipe=2, tensor=2))
    dataset = SyntheticTokenDataset(num_samples=32, seq_len=16, vocab_size=64)

    def run(recompute):
        model = GPT2(
            vocab_size=64, max_len=32, model_dim=32, num_layers=2,
            num_heads=4, mlp_dim=64, pipe_axis="pipe", pipe_schedule="1f1b",
            pipe_microbatches=2, pipe_recompute=recompute,
            logits_mode="hidden",
        )
        loader = DeviceLoader(dataset, 8, mesh=mesh, num_shards=1, shard_id=0)
        trainer = Trainer(
            model, CausalLMTask(), optax.adam(1e-2),
            partitioner=transformer_partitioner(mesh),
        )
        losses = []
        with mesh:
            trainer.init(next(iter(loader))["tokens"])
            q_sharding = trainer.state.params["decoder"]["q_kernel"].sharding
            assert "tensor" in tuple(q_sharding.spec)
            state = trainer.state
            for _ in range(3):
                state, m = trainer.train_step(state, next(iter(loader)))
                losses.append(float(m["loss"]))
        return losses

    l_stash, l_rec = run(False), run(True)
    assert all(np.isfinite(l) for l in l_stash)
    assert l_stash[-1] < l_stash[0], l_stash
    np.testing.assert_allclose(l_stash, l_rec, rtol=1e-5)


@pytest.mark.parametrize("recompute", [True, False])
def test_sp_pp_interleaved_1f1b_matches_dense_pipelined(devices, recompute):
    """INTERLEAVED (pipe_virtual=2) 1F1B x SP: chunk-granular stash-ring
    arithmetic composes with the {pipe, sequence}-manual schedule — loss,
    accuracy sums, and grads equal the same interleaved model on a
    sequence-span-1 mesh, under BOTH backward modes (recompute and
    activation-stash)."""
    from distributed_pytorch_example_tpu.models.gpt2 import GPT2
    from distributed_pytorch_example_tpu.train.tasks import CausalLMTask

    mesh_sp = make_mesh(MeshSpec(data=2, pipe=2, sequence=2))
    mesh_dense = make_mesh(MeshSpec(data=4, pipe=2))
    task = CausalLMTask()
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, 64, size=(16, 16)), jnp.int32
    )
    mk = lambda sp: GPT2(
        vocab_size=64, max_len=32, model_dim=32, num_layers=4, num_heads=4,
        mlp_dim=64, pipe_axis="pipe", pipe_schedule="1f1b",
        pipe_microbatches=4, pipe_virtual=2, pipe_recompute=recompute,
        sp_mode="ring", seq_axis=sp, logits_mode="hidden",
    )
    m_sp, m_dense = mk("sequence"), mk(None)
    with mesh_sp:
        params = m_sp.init(jax.random.key(0), tokens, train=False)["params"]
    rng = jax.random.key(1)

    def loss(model, mesh):
        def f(p):
            with mesh:
                l, mets, _ = task.compute_loss(
                    model, p, {}, {"tokens": tokens}, rng, train=True
                )
            return l, mets

        return f

    (l_sp, mets_sp), g_sp = jax.value_and_grad(
        loss(m_sp, mesh_sp), has_aux=True
    )(params)
    (l_d, mets_d), g_d = jax.value_and_grad(
        loss(m_dense, mesh_dense), has_aux=True
    )(params)
    np.testing.assert_allclose(float(l_sp), float(l_d), rtol=3e-5)
    np.testing.assert_allclose(
        float(mets_sp["accuracy"]), float(mets_d["accuracy"]), atol=1e-3
    )
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-4
        ),
        g_sp, g_d,
    )


@pytest.mark.parametrize("save_recompute", [True, False])
def test_checkpoint_resume_across_pipe_recompute_flip(
    tmp_path, devices, save_recompute
):
    """A checkpoint saved under one 1F1B backward mode resumes under the
    other with the SAME loss trajectory (both flip directions): the vjp
    stash is schedule state inside a single step, never train state, so
    the checkpoint format is mode-independent — the two modes' TrainState
    treedefs are identical."""
    from distributed_pytorch_example_tpu.data.loader import DeviceLoader
    from distributed_pytorch_example_tpu.data.synthetic import (
        SyntheticTokenDataset,
    )
    from distributed_pytorch_example_tpu.models.gpt2 import GPT2
    from distributed_pytorch_example_tpu.parallel.partition import (
        transformer_partitioner,
    )
    from distributed_pytorch_example_tpu.train.checkpoint import (
        load_checkpoint,
        save_checkpoint,
    )
    from distributed_pytorch_example_tpu.train.loop import Trainer
    from distributed_pytorch_example_tpu.train.tasks import CausalLMTask

    mesh = make_mesh(MeshSpec(data=2, pipe=2))
    dataset = SyntheticTokenDataset(num_samples=64, seq_len=16, vocab_size=64)
    loader = DeviceLoader(dataset, 16, mesh=mesh, num_shards=1, shard_id=0)
    batches = [b for _, b in zip(range(4), iter(loader))]

    def make(recompute):
        model = GPT2(
            vocab_size=64, max_len=32, model_dim=32, num_layers=2,
            num_heads=4, mlp_dim=64, pipe_axis="pipe", pipe_schedule="1f1b",
            pipe_microbatches=4, pipe_recompute=recompute,
            logits_mode="hidden",
        )
        trainer = Trainer(
            model, CausalLMTask(), optax.adam(1e-2),
            partitioner=transformer_partitioner(mesh),
        )
        with mesh:
            trainer.init(batches[0]["tokens"])
        return trainer

    t_save, t_flip = make(save_recompute), make(not save_recompute)
    # mode-independent checkpoint format: identical state treedef
    assert jax.tree_util.tree_structure(
        t_save.state
    ) == jax.tree_util.tree_structure(t_flip.state)

    state = t_save.state
    with mesh:
        for b in batches[:2]:
            state, _ = t_save.train_step(state, b)
    path = str(tmp_path / "flip.ckpt")
    save_checkpoint(path, state, epoch=1, loss=0.0)

    def resume(trainer):
        st, epoch, _ = load_checkpoint(path, trainer.state)
        assert epoch == 1
        losses = []
        with mesh:
            for b in batches[2:]:
                st, m = trainer.train_step(st, b)
                losses.append(float(m["loss"]))
        return losses

    l_flip, l_cont = resume(t_flip), resume(t_save)
    np.testing.assert_allclose(l_flip, l_cont, rtol=1e-6)


def test_interleaved_1f1b_moe_matches_plain(devices):
    """PP x EP under INTERLEAVED 1F1B (pipe_virtual=2): the per-cycle aux
    accumulation and in-schedule aux-gradient seeding behave identically
    under the virtual-chunk layout — loss (incl. weighted aux) and grads
    equal the plain 1F1B MoE (itself pinned against GPipe -> sequential)."""
    from distributed_pytorch_example_tpu.models.gpt2 import GPT2
    from distributed_pytorch_example_tpu.train.tasks import CausalLMTask

    mesh = make_mesh(MeshSpec(data=2, pipe=2, expert=2))
    task = CausalLMTask()
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, 64, size=(8, 16)), jnp.int32
    )
    mk = lambda v: GPT2(
        vocab_size=64, max_len=32, model_dim=32, num_layers=4, num_heads=4,
        mlp_dim=64, pipe_axis="pipe", pipe_schedule="1f1b",
        pipe_microbatches=4, pipe_virtual=v, logits_mode="hidden",
        moe_experts=4, moe_every=1, moe_top_k=2, moe_capacity_factor=8.0,
    )
    m_il, m_pl = mk(2), mk(1)
    with mesh:
        params = m_il.init(jax.random.key(0), tokens, train=False)["params"]
    rng = jax.random.key(1)

    def loss_fn(model):
        def f(p):
            with mesh:
                loss, mets, _ = task.compute_loss(
                    model, p, {}, {"tokens": tokens}, rng, train=True
                )
            return loss, mets

        return f

    (l1, mets1), g1 = jax.value_and_grad(loss_fn(m_il), has_aux=True)(params)
    (l2, mets2), g2 = jax.value_and_grad(loss_fn(m_pl), has_aux=True)(params)
    np.testing.assert_allclose(float(l1), float(l2), rtol=3e-5)
    np.testing.assert_allclose(
        float(mets1["moe_dropped_fraction"]),
        float(mets2["moe_dropped_fraction"]), atol=1e-6,
    )
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=7e-4
        ),
        g1, g2,
    )


@pytest.mark.parametrize("family", ["gpt2", "llama"])
def test_1f1b_moe_matches_gpipe_schedule(devices, family):
    """PP x EP under 1F1B: aux-loss gradients are seeded inside the
    schedule with the model's weights; total loss and grads equal the
    GPipe schedule's (whose MoE path is pinned against sequential)."""
    from distributed_pytorch_example_tpu.models.gpt2 import GPT2
    from distributed_pytorch_example_tpu.models.llama import Llama
    from distributed_pytorch_example_tpu.train.tasks import CausalLMTask

    mesh = make_mesh(MeshSpec(data=2, pipe=2, expert=2))
    task = CausalLMTask()
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, 64, size=(8, 16)), jnp.int32
    )
    common = dict(
        vocab_size=64, max_len=32, model_dim=32, num_layers=2, mlp_dim=64,
        pipe_axis="pipe", pipe_microbatches=4, logits_mode="hidden",
        moe_experts=4, moe_every=1, moe_top_k=2,
        # big capacity: no dropped tokens, so schedules are exactly
        # comparable (drops are order-dependent at the margin)
        moe_capacity_factor=8.0,
    )
    if family == "gpt2":
        mk = lambda sched: GPT2(num_heads=4, pipe_schedule=sched, **common)
    else:
        mk = lambda sched: Llama(
            num_heads=4, num_kv_heads=2, pipe_schedule=sched, **common
        )
    m_1f1b, m_gpipe = mk("1f1b"), mk("gpipe")
    with mesh:
        params = m_1f1b.init(jax.random.key(0), tokens, train=False)["params"]
    rng = jax.random.key(1)

    def loss_fn(model):
        def f(p):
            with mesh:
                loss, mets, _ = task.compute_loss(
                    model, p, {}, {"tokens": tokens}, rng, train=True
                )
            return loss, mets

        return f

    (l1, mets1), g1 = jax.value_and_grad(
        loss_fn(m_1f1b), has_aux=True
    )(params)
    (l2, mets2), g2 = jax.value_and_grad(
        loss_fn(m_gpipe), has_aux=True
    )(params)
    # total loss includes the weighted aux values on both schedules
    np.testing.assert_allclose(float(l1), float(l2), rtol=3e-5)
    assert "moe_dropped_fraction" in mets1 and "moe_dropped_fraction" in mets2
    np.testing.assert_allclose(
        float(mets1["moe_dropped_fraction"]),
        float(mets2["moe_dropped_fraction"]), atol=1e-6,
    )
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=7e-4
        ),
        g1, g2,
    )


# -- LLaMA-family stacked decoder (RMSNorm/RoPE/GQA/SwiGLU) -----------------

LLAMA_CFG = dict(
    num_layers=4, num_heads=4, num_kv_heads=2, head_dim=8, model_dim=16,
    mlp_dim=32,
)


def _llama_init_and_input(model, seed=0, batch=8, seq=8):
    x = jnp.asarray(
        np.random.default_rng(seed).standard_normal((batch, seq, 16)),
        jnp.float32,
    )
    params = model.init(jax.random.key(0), x)["params"]
    return params, x


def test_llama_param_shapes_are_layer_stacked(devices):
    from distributed_pytorch_example_tpu.models.stacked import (
        StackedLlamaDecoder,
    )

    model = StackedLlamaDecoder(**LLAMA_CFG)
    params, _ = _llama_init_and_input(model)
    assert params["q_kernel"].shape == (4, 16, 32)  # (L, D, heads*hd)
    assert params["k_kernel"].shape == (4, 16, 16)  # GQA: kv_heads*hd
    assert params["gate_kernel"].shape == (4, 16, 32)
    assert params["ln1_scale"].shape == (4, 16)
    assert "q_bias" not in params  # LLaMA family: no biases


def test_llama_pipelined_matches_sequential(devices):
    from distributed_pytorch_example_tpu.models.stacked import (
        StackedLlamaDecoder,
    )

    seq_model = StackedLlamaDecoder(**LLAMA_CFG)
    pipe_model = StackedLlamaDecoder(**LLAMA_CFG, pipe_axis="pipe")
    params, x = _llama_init_and_input(seq_model)
    expected = seq_model.apply({"params": params}, x)
    mesh = make_mesh(MeshSpec(data=2, pipe=4))
    with mesh:
        got = jax.jit(
            lambda p, x: pipe_model.apply({"params": p}, x)
        )(params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=1e-5)


def test_llama_pipelined_grads_match_sequential(devices):
    from distributed_pytorch_example_tpu.models.stacked import (
        StackedLlamaDecoder,
    )

    seq_model = StackedLlamaDecoder(**LLAMA_CFG)
    pipe_model = StackedLlamaDecoder(**LLAMA_CFG, pipe_axis="pipe")
    params, x = _llama_init_and_input(seq_model, seed=1)
    mesh = make_mesh(MeshSpec(data=2, pipe=4))

    def loss_seq(p):
        return jnp.mean(seq_model.apply({"params": p}, x) ** 2)

    def loss_pipe(p):
        return jnp.mean(pipe_model.apply({"params": p}, x) ** 2)

    g_seq = jax.grad(loss_seq)(params)
    with mesh:
        g_pipe = jax.jit(jax.grad(loss_pipe))(params)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-5
        ),
        g_pipe, g_seq,
    )


def test_llama_stacked_matches_per_layer_blocks(devices):
    """Stacked block math == models/llama.py LlamaBlock with copied kernels.

    The per-layer blocks carry (zero-initialized) attention biases the
    true-LLaMA stacked layout omits; at init the math must agree exactly.
    """
    from distributed_pytorch_example_tpu.models.llama import Llama
    from distributed_pytorch_example_tpu.models.stacked import (
        StackedLlamaDecoder,
    )

    ref = Llama(
        vocab_size=64, max_len=32, model_dim=16, num_layers=2, num_heads=4,
        num_kv_heads=2, mlp_dim=32, logits_mode="hidden",
    )
    tokens = jnp.asarray(
        np.random.default_rng(4).integers(0, 64, (2, 8)), jnp.int32
    )
    ref_params = ref.init(jax.random.key(2), tokens)["params"]

    stacked_params = {}
    for new, path in {
        "q_kernel": ("attn", "q"), "k_kernel": ("attn", "k"),
        "v_kernel": ("attn", "v"), "o_kernel": ("attn", "o"),
        "gate_kernel": ("mlp", "gate"), "up_kernel": ("mlp", "up"),
        "down_kernel": ("mlp", "down"),
    }.items():
        stacked_params[new] = jnp.stack([
            ref_params[f"layer_{i}"][path[0]][path[1]]["kernel"]
            for i in range(2)
        ])
    for new, mod in {"ln1_scale": "ln1", "ln2_scale": "ln2"}.items():
        stacked_params[new] = jnp.stack([
            ref_params[f"layer_{i}"][mod]["scale"] for i in range(2)
        ])

    x = ref_params["tok_embed"]["embedding"][tokens]
    model = StackedLlamaDecoder(
        num_layers=2, num_heads=4, num_kv_heads=2, head_dim=4, model_dim=16,
        mlp_dim=32,
    )
    got = model.apply({"params": stacked_params}, x)

    # reference: run the per-layer blocks only (strip embed + final head)
    from distributed_pytorch_example_tpu.models.llama import LlamaBlock

    expected = x
    for i in range(2):
        block = LlamaBlock(
            num_heads=4, num_kv_heads=2, head_dim=4, model_dim=16,
            mlp_dim=32,
        )
        expected = block.apply(
            {"params": ref_params[f"layer_{i}"]}, expected
        )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(expected), atol=1e-5
    )


def test_llama_pipelined_through_trainer(devices):
    """Tiny pipelined LLaMA trains end-to-end on a data x pipe mesh."""
    from distributed_pytorch_example_tpu.data.loader import DeviceLoader
    from distributed_pytorch_example_tpu.data.synthetic import (
        SyntheticTokenDataset,
    )
    from distributed_pytorch_example_tpu.models.llama import Llama
    from distributed_pytorch_example_tpu.parallel.partition import (
        transformer_partitioner,
    )
    from distributed_pytorch_example_tpu.train.loop import Trainer
    from distributed_pytorch_example_tpu.train.tasks import CausalLMTask

    mesh = make_mesh(MeshSpec(data=2, pipe=4))
    model = Llama(
        vocab_size=64, max_len=32, model_dim=16, num_layers=4, num_heads=4,
        num_kv_heads=2, mlp_dim=32, pipe_axis="pipe",
    )
    dataset = SyntheticTokenDataset(num_samples=32, seq_len=16, vocab_size=64)
    loader = DeviceLoader(dataset, 8, mesh=mesh, num_shards=1, shard_id=0)
    trainer = Trainer(
        model, CausalLMTask(), optax.adam(1e-2),
        partitioner=transformer_partitioner(mesh),
    )
    with mesh:
        trainer.init(next(iter(loader))["tokens"])
        q_sharding = trainer.state.params["decoder"]["q_kernel"].sharding
        assert "pipe" in (q_sharding.spec[0],)
        losses = []
        state = trainer.state
        for _ in range(4):
            batch = next(iter(loader))
            state, metrics = trainer.train_step(state, batch)
            losses.append(float(metrics["loss"]))
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0], losses


def test_llama_pipe_rejects_conflicting_features(devices):
    """PP x SP is supported since r5; the remaining exclusion is all three
    of PP x SP x EP in one stack."""
    from distributed_pytorch_example_tpu.models.llama import Llama

    model = Llama(
        vocab_size=64, max_len=32, model_dim=16, num_layers=4, num_heads=4,
        num_kv_heads=2, mlp_dim=32, pipe_axis="pipe", seq_axis="sequence",
        moe_experts=4, moe_every=1,
    )
    with pytest.raises(ValueError, match="PP x SP x EP"):
        model.init(jax.random.key(0), jnp.zeros((2, 8), jnp.int32))


# -- MoE inside the layer-stacked decoder (PP x EP) -------------------------

MOE_CFG = dict(
    num_layers=4, num_heads=2, head_dim=8, model_dim=16, mlp_dim=32,
    moe_experts=4, moe_top_k=2, moe_capacity_factor=8.0,
)


def _moe_apply_collect(model, params, x):
    out, state = model.apply(
        {"params": params}, x, mutable=["losses", "moe_metrics"]
    )
    losses = sum(jax.tree_util.tree_leaves(state["losses"]))
    metric = sum(jax.tree_util.tree_leaves(state.get("moe_metrics", {})))
    return out, losses, metric


def test_moe_stacked_matches_per_layer_blocks(devices):
    """Stacked every-block-MoE math == TransformerStack(moe_every=1) with
    copied weights — outputs AND aux losses."""
    from distributed_pytorch_example_tpu.models.transformer import (
        TransformerStack,
    )

    ref = TransformerStack(
        num_layers=2, num_heads=2, head_dim=8, model_dim=16, mlp_dim=32,
        causal=True, prenorm=True, moe_experts=4, moe_every=1, moe_top_k=2,
        moe_capacity_factor=8.0,
    )
    x = jnp.asarray(
        np.random.default_rng(6).standard_normal((2, 8, 16)), jnp.float32
    )
    ref_params = ref.init(jax.random.key(5), x, train=False)["params"]

    stacked_params = {}
    plain = {
        "q_kernel": ("attn", "q", "kernel"), "q_bias": ("attn", "q", "bias"),
        "k_kernel": ("attn", "k", "kernel"), "k_bias": ("attn", "k", "bias"),
        "v_kernel": ("attn", "v", "kernel"), "v_bias": ("attn", "v", "bias"),
        "o_kernel": ("attn", "o", "kernel"), "o_bias": ("attn", "o", "bias"),
        "ln1_scale": ("ln1", "scale"), "ln1_bias": ("ln1", "bias"),
        "ln2_scale": ("ln2", "scale"), "ln2_bias": ("ln2", "bias"),
        "router_kernel": ("moe", "router", "kernel"),
        "router_bias": ("moe", "router", "bias"),
        "moe_up_kernel": ("moe", "up_kernel"),
        "moe_up_bias": ("moe", "up_bias"),
        "moe_down_kernel": ("moe", "down_kernel"),
        "moe_down_bias": ("moe", "down_bias"),
    }
    for new, path in plain.items():
        leaves = []
        for i in range(2):
            node = ref_params[f"layer_{i}"]
            for part in path:
                node = node[part]
            leaves.append(node)
        stacked_params[new] = jnp.stack(leaves)

    model = StackedDecoder(
        num_layers=2, num_heads=2, head_dim=8, model_dim=16, mlp_dim=32,
        causal=True, moe_experts=4, moe_top_k=2, moe_capacity_factor=8.0,
    )
    got, got_losses, _ = _moe_apply_collect(model, stacked_params, x)
    expected, ref_state = ref.apply(
        {"params": ref_params}, x, train=False,
        mutable=["losses", "moe_metrics"],
    )
    exp_losses = sum(jax.tree_util.tree_leaves(ref_state["losses"]))
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(expected), atol=1e-5
    )
    np.testing.assert_allclose(
        float(got_losses), float(exp_losses), rtol=1e-5
    )


def test_moe_pipelined_matches_sequential(devices):
    """PP x EP: pipelined every-block-MoE == the same stacked params run
    sequentially PER MICROBATCH — outputs, aux losses (bubble ticks
    excluded), metric, and gradients.

    Routing statistics (load balancing, capacity drops) are computed per
    microbatch inside the pipeline — a different, equally valid estimator
    than the full-batch statistic (identical to gradient-accumulation
    semantics) — so the sequential reference is microbatched too; the
    main-path outputs are microbatch-invariant and compared full-batch."""
    n_micro = 4
    seq_model = StackedDecoder(**MOE_CFG)
    pipe_model = StackedDecoder(
        **MOE_CFG, pipe_axis="pipe", pipe_microbatches=n_micro
    )
    x = jnp.asarray(
        np.random.default_rng(7).standard_normal((8, 8, 16)), jnp.float32
    )
    params = seq_model.init(jax.random.key(0), x)["params"]
    mesh = make_mesh(MeshSpec(data=2, pipe=2, expert=2))

    def seq_micro(p, xs):
        outs, tot_losses, tot_metric = [], 0.0, 0.0
        for i in range(n_micro):
            xm = xs[i * 2 : (i + 1) * 2]
            out, losses, metric = _moe_apply_collect(seq_model, p, xm)
            outs.append(out)
            tot_losses = tot_losses + losses
            tot_metric = tot_metric + metric
        return (
            jnp.concatenate(outs), tot_losses / n_micro,
            tot_metric / n_micro,
        )

    exp_out, exp_losses, exp_metric = seq_micro(params, x)
    with mesh:
        got_out, got_losses, got_metric = jax.jit(
            lambda p, x: _moe_apply_collect(pipe_model, p, x)
        )(params, x)
    np.testing.assert_allclose(
        np.asarray(got_out), np.asarray(exp_out), atol=2e-5
    )
    np.testing.assert_allclose(
        float(got_losses), float(exp_losses), rtol=1e-5
    )
    np.testing.assert_allclose(
        float(got_metric), float(exp_metric), rtol=1e-5, atol=1e-7
    )

    def loss_seq(p):
        out, losses, _ = seq_micro(p, x)
        return jnp.mean(out ** 2) + losses

    def loss_pipe(p):
        out, losses, _ = _moe_apply_collect(pipe_model, p, x)
        return jnp.mean(out ** 2) + losses

    g_seq = jax.grad(loss_seq)(params)
    with mesh:
        g_pipe = jax.jit(jax.grad(loss_pipe))(params)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=3e-5
        ),
        g_pipe, g_seq,
    )


LLAMA_MOE_CFG = dict(
    num_layers=4, num_heads=4, num_kv_heads=2, head_dim=8, model_dim=16,
    mlp_dim=32, moe_experts=4, moe_top_k=2, moe_capacity_factor=8.0,
)


def test_llama_moe_stacked_matches_per_layer_blocks(devices):
    """Stacked SwiGLU-expert math == LlamaBlock(moe_experts) with copied
    weights — outputs AND aux losses."""
    from distributed_pytorch_example_tpu.models.llama import LlamaBlock
    from distributed_pytorch_example_tpu.models.stacked import (
        StackedLlamaDecoder,
    )

    x = jnp.asarray(
        np.random.default_rng(8).standard_normal((2, 8, 16)), jnp.float32
    )
    blocks, ref_params = [], []
    for i in range(2):
        block = LlamaBlock(
            num_heads=4, num_kv_heads=2, head_dim=4, model_dim=16,
            mlp_dim=32, moe_experts=4, moe_top_k=2, moe_capacity_factor=8.0,
        )
        p = block.init(jax.random.key(10 + i), x)["params"]
        blocks.append(block)
        ref_params.append(p)

    stacked_params = {}
    for new, path in {
        "q_kernel": ("attn", "q", "kernel"), "k_kernel": ("attn", "k", "kernel"),
        "v_kernel": ("attn", "v", "kernel"), "o_kernel": ("attn", "o", "kernel"),
        "ln1_scale": ("ln1", "scale"), "ln2_scale": ("ln2", "scale"),
        "router_kernel": ("moe", "router", "kernel"),
        "router_bias": ("moe", "router", "bias"),
        "moe_gate_kernel": ("moe", "gate_kernel"),
        "moe_up_kernel": ("moe", "up_kernel"),
        "moe_down_kernel": ("moe", "down_kernel"),
    }.items():
        leaves = []
        for p in ref_params:
            node = p
            for part in path:
                node = node[part]
            leaves.append(node)
        stacked_params[new] = jnp.stack(leaves)

    model = StackedLlamaDecoder(
        num_layers=2, num_heads=4, num_kv_heads=2, head_dim=4, model_dim=16,
        mlp_dim=32, moe_experts=4, moe_top_k=2, moe_capacity_factor=8.0,
    )
    got, got_losses, _ = _moe_apply_collect(model, stacked_params, x)

    expected, exp_losses = x, 0.0
    for block, p in zip(blocks, ref_params):
        expected, state = block.apply(
            {"params": p}, expected, mutable=["losses", "moe_metrics"]
        )
        exp_losses = exp_losses + sum(
            jax.tree_util.tree_leaves(state["losses"])
        )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(expected), atol=1e-5
    )
    np.testing.assert_allclose(float(got_losses), float(exp_losses), rtol=1e-5)


def test_llama_moe_pipelined_matches_sequential(devices):
    """PP x EP for the LLaMA family: pipelined SwiGLU-expert stack == the
    same stacked params run sequentially per microbatch — outputs, aux
    losses, metric, and gradients (microbatched reference for the routing
    statistics, as in the GPT-2 twin above)."""
    n_micro = 4
    from distributed_pytorch_example_tpu.models.stacked import (
        StackedLlamaDecoder,
    )

    seq_model = StackedLlamaDecoder(**LLAMA_MOE_CFG)
    pipe_model = StackedLlamaDecoder(
        **LLAMA_MOE_CFG, pipe_axis="pipe", pipe_microbatches=n_micro
    )
    x = jnp.asarray(
        np.random.default_rng(9).standard_normal((8, 8, 16)), jnp.float32
    )
    params = seq_model.init(jax.random.key(0), x)["params"]
    mesh = make_mesh(MeshSpec(data=2, pipe=2, expert=2))

    def seq_micro(p, xs):
        outs, tot_losses, tot_metric = [], 0.0, 0.0
        for i in range(n_micro):
            xm = xs[i * 2 : (i + 1) * 2]
            out, losses, metric = _moe_apply_collect(seq_model, p, xm)
            outs.append(out)
            tot_losses = tot_losses + losses
            tot_metric = tot_metric + metric
        return (
            jnp.concatenate(outs), tot_losses / n_micro,
            tot_metric / n_micro,
        )

    exp_out, exp_losses, exp_metric = seq_micro(params, x)
    with mesh:
        got_out, got_losses, got_metric = jax.jit(
            lambda p, x: _moe_apply_collect(pipe_model, p, x)
        )(params, x)
    np.testing.assert_allclose(
        np.asarray(got_out), np.asarray(exp_out), atol=2e-5
    )
    np.testing.assert_allclose(float(got_losses), float(exp_losses), rtol=1e-5)
    np.testing.assert_allclose(
        float(got_metric), float(exp_metric), rtol=1e-5, atol=1e-7
    )

    def loss_seq(p):
        out, losses, _ = seq_micro(p, x)
        return jnp.mean(out ** 2) + losses

    def loss_pipe(p):
        out, losses, _ = _moe_apply_collect(pipe_model, p, x)
        return jnp.mean(out ** 2) + losses

    g_seq = jax.grad(loss_seq)(params)
    with mesh:
        g_pipe = jax.jit(jax.grad(loss_pipe))(params)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=3e-5
        ),
        g_pipe, g_seq,
    )


def test_llama_moe_pipelined_through_trainer(devices):
    """PP x EP x DP for the modern-LM family: pipelined SwiGLU-expert
    LLaMA trains end-to-end, expert weights sharded P('pipe','expert')."""
    from distributed_pytorch_example_tpu.data.loader import DeviceLoader
    from distributed_pytorch_example_tpu.data.synthetic import (
        SyntheticTokenDataset,
    )
    from distributed_pytorch_example_tpu.models.llama import Llama
    from distributed_pytorch_example_tpu.parallel.partition import (
        transformer_partitioner,
    )
    from distributed_pytorch_example_tpu.train.loop import Trainer
    from distributed_pytorch_example_tpu.train.tasks import CausalLMTask

    mesh = make_mesh(MeshSpec(data=2, pipe=2, expert=2))
    model = Llama(
        vocab_size=64, max_len=32, model_dim=16, num_layers=4, num_heads=4,
        num_kv_heads=2, mlp_dim=32, pipe_axis="pipe", moe_experts=4,
        moe_every=1, moe_top_k=2,
    )
    dataset = SyntheticTokenDataset(num_samples=32, seq_len=16, vocab_size=64)
    loader = DeviceLoader(dataset, 8, mesh=mesh, num_shards=1, shard_id=0)
    trainer = Trainer(
        model, CausalLMTask(), optax.adam(1e-2),
        partitioner=transformer_partitioner(mesh),
    )
    with mesh:
        trainer.init(next(iter(loader))["tokens"])
        spec = (
            trainer.state.params["decoder"]["moe_gate_kernel"].sharding.spec
        )
        assert spec[0] == "pipe" and spec[1] == "expert"
        losses = []
        state = trainer.state
        for _ in range(4):
            batch = next(iter(loader))
            state, metrics = trainer.train_step(state, batch)
            losses.append(float(metrics["loss"]))
        assert "moe_dropped_fraction" in metrics
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0], losses


def test_llama_pipe_moe_needs_every_block(devices):
    """moe_every != 1 cannot pipeline (heterogeneous stages) — loud error."""
    from distributed_pytorch_example_tpu.models.llama import Llama

    model = Llama(
        vocab_size=64, max_len=32, model_dim=16, num_layers=4, num_heads=4,
        num_kv_heads=2, mlp_dim=32, pipe_axis="pipe", moe_experts=4,
        moe_every=2,
    )
    with pytest.raises(ValueError, match="moe_every=1"):
        model.init(jax.random.key(0), jnp.zeros((2, 8), jnp.int32))


def test_gpt2_moe_pipelined_through_trainer(devices):
    """PP x EP x DP in one program: pipelined every-block-MoE GPT-2 trains
    end-to-end with expert weights sharded on 'expert' and stage stacks on
    'pipe'; aux losses and the drop metric flow."""
    from distributed_pytorch_example_tpu.data.loader import DeviceLoader
    from distributed_pytorch_example_tpu.data.synthetic import (
        SyntheticTokenDataset,
    )
    from distributed_pytorch_example_tpu.models.gpt2 import GPT2
    from distributed_pytorch_example_tpu.parallel.partition import (
        transformer_partitioner,
    )
    from distributed_pytorch_example_tpu.train.loop import Trainer
    from distributed_pytorch_example_tpu.train.tasks import CausalLMTask

    mesh = make_mesh(MeshSpec(data=2, pipe=2, expert=2))
    model = GPT2(
        vocab_size=64, max_len=32, model_dim=16, num_layers=4, num_heads=2,
        mlp_dim=32, pipe_axis="pipe", moe_experts=4, moe_every=1,
        moe_top_k=2,
    )
    dataset = SyntheticTokenDataset(num_samples=32, seq_len=16, vocab_size=64)
    loader = DeviceLoader(dataset, 8, mesh=mesh, num_shards=1, shard_id=0)
    trainer = Trainer(
        model, CausalLMTask(), optax.adam(1e-2),
        partitioner=transformer_partitioner(mesh),
    )
    with mesh:
        trainer.init(next(iter(loader))["tokens"])
        spec = trainer.state.params["decoder"]["moe_up_kernel"].sharding.spec
        assert spec[0] == "pipe" and spec[1] == "expert"
        losses = []
        state = trainer.state
        for _ in range(4):
            batch = next(iter(loader))
            state, metrics = trainer.train_step(state, batch)
            losses.append(float(metrics["loss"]))
        assert "moe_dropped_fraction" in metrics
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0], losses

"""graft-armor: fault injection, self-healing recovery, bounded retry.

The robustness contract (ISSUE 5), each clause pinned by a real
``Trainer.fit`` (or the exact library surface the Trainer drives) under a
seeded :mod:`robustness.chaos` fault plan:

- nonfinite batch ⇒ the update is predicated out DEVICE-side (params
  bit-frozen, no recompile), the skip is counted, and the trajectory is
  deterministic;
- skips exceeding ``max_bad_steps`` ⇒ ONE rollback to the last good
  checkpoint, a second exhaustion ⇒ :class:`BadStepBudgetExceeded`;
- corrupt/torn `latest` ⇒ ``load_checkpoint`` walks back to the newest
  intact ancestor (gathered history / older sharded version) and reports
  what it skipped; nothing intact ⇒ :class:`CheckpointCorruptError`;
- transient I/O and rendezvous failures ⇒ bounded deterministic
  exponential-backoff retries; persistent failures surface at the next
  submit()/check() boundary, not minutes later.

The sweep (scripts/chaos_sweep.py) re-runs the same matrix end-to-end as
subprocess scenarios; its fast subset rides tier-1 here and the full
matrix (SIGKILL torn-save, SIGINT) is ``-m slow``.
"""

import errno
import json
import os
import subprocess
import sys

import jax
import numpy as np
import optax
import pytest

import distributed_pytorch_example_tpu as dpx
from distributed_pytorch_example_tpu.data.synthetic import _ArrayDataset
from distributed_pytorch_example_tpu.models import SimpleNet
from distributed_pytorch_example_tpu.robustness import (
    BadStepBudgetExceeded,
    CheckpointCorruptError,
    chaos,
    retry,
)
from distributed_pytorch_example_tpu.robustness.integrity import (
    is_sealed,
    read_verified,
    seal,
    unseal,
)
from distributed_pytorch_example_tpu.train import checkpoint as ckpt_lib

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _no_leaked_plan():
    """Every test leaves the process chaos-free (module-global plan)."""
    yield
    chaos.uninstall()


def learnable_dataset(n=256, d=16, classes=4, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, d), dtype=np.float32)
    w = rng.standard_normal((d, classes), dtype=np.float32)
    y = np.argmax(x @ w, axis=1).astype(np.int32)
    return _ArrayDataset({"x": x, "y": y})


def make_trainer(mesh, ckpt=None, **kw):
    return dpx.train.Trainer(
        SimpleNet(input_size=16, hidden_size=32, num_classes=4),
        dpx.train.ClassificationTask(),
        optax.adam(1e-2),
        partitioner=dpx.parallel.data_parallel(mesh),
        checkpoint_dir=ckpt,
        log_every=kw.pop("log_every", 2),
        **kw,
    )


def _loader(mesh):
    return dpx.data.DeviceLoader(learnable_dataset(), 64, mesh=mesh, seed=0)


def _digest(tree) -> bytes:
    import hashlib

    h = hashlib.sha256()
    for leaf in jax.tree_util.tree_leaves(tree):
        if jnp_is_key(leaf):
            continue
        h.update(np.asarray(leaf).tobytes())
    return h.digest()


def jnp_is_key(x):
    import jax.numpy as jnp

    return jnp.issubdtype(jnp.asarray(x).dtype, jax.dtypes.prng_key)


# ---------------------------------------------------------------------------
# retry: deterministic exponential backoff
# ---------------------------------------------------------------------------


def test_backoff_schedule_deterministic_and_capped():
    assert retry.backoff_schedule(4, 0.05, 2.0) == [0.05, 0.1, 0.2]
    assert retry.backoff_schedule(6, 1.0, 4.0) == [1.0, 2.0, 4.0, 4.0, 4.0]
    assert retry.backoff_schedule(1, 1.0, 4.0) == []


def test_with_retries_retries_then_succeeds():
    calls, slept = [], []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise OSError(errno.EIO, "transient")
        return "ok"

    out = retry.with_retries(
        flaky, attempts=4, base_delay=0.5, retry_on=(OSError,),
        sleep=slept.append,
    )
    assert out == "ok" and len(calls) == 3
    assert slept == [0.5, 1.0]  # deterministic: replayable chaos runs


def test_with_retries_final_failure_propagates_unchanged():
    boom = OSError(errno.EIO, "persistent")

    def always():
        raise boom

    with pytest.raises(OSError) as ei:
        retry.with_retries(
            always, attempts=3, base_delay=0, retry_on=(OSError,),
            sleep=lambda _: None,
        )
    assert ei.value is boom


def test_with_retries_non_retryable_raises_immediately():
    calls = []

    def typed():
        calls.append(1)
        raise ValueError("config error, not transient")

    with pytest.raises(ValueError):
        retry.with_retries(
            typed, attempts=5, retry_on=(OSError,), sleep=lambda _: None
        )
    assert len(calls) == 1


# ---------------------------------------------------------------------------
# chaos plan: seeded, serializable, env-installable
# ---------------------------------------------------------------------------


def test_plan_json_roundtrip_and_preset():
    plan = chaos.ChaosPlan(faults=[
        chaos.Fault("nan-batch", step=3),
        chaos.Fault("io-error", path_substr="latest", count=2),
    ], seed=7)
    back = chaos.ChaosPlan.from_json(plan.to_json())
    assert back.seed == 7 and len(back.faults) == 2
    assert back.faults[0].kind == "nan-batch" and back.faults[0].step == 3
    assert chaos.preset("nan-step").faults[0].kind == "nan-batch"
    assert chaos.preset("io-flake").faults[0].kind == "io-error"
    with pytest.raises(ValueError):
        chaos.Fault("frobnicate")
    with pytest.raises(ValueError, match="unknown chaos preset"):
        chaos.preset("no-such-preset")


def test_env_var_installs_plan(monkeypatch):
    plan = chaos.ChaosPlan(faults=[chaos.Fault("nan-batch", step=1)])
    monkeypatch.setenv(chaos.ENV_VAR, plan.to_json())
    chaos.uninstall()  # clears the plan AND the env-checked latch
    active = chaos.active()
    assert active is not None and active.faults[0].kind == "nan-batch"
    monkeypatch.setenv(chaos.ENV_VAR, "io-flake")  # preset-name form
    chaos.uninstall()
    assert chaos.active().faults[0].kind == "io-error"


# ---------------------------------------------------------------------------
# integrity envelope
# ---------------------------------------------------------------------------


def test_seal_unseal_roundtrip_and_legacy_passthrough():
    body = b"\x00\x01payload" * 100
    sealed = seal(body)
    assert is_sealed(sealed) and unseal(sealed, "t") == body
    # legacy (pre-r10, unsealed) files pass through unverified
    assert not is_sealed(body) and unseal(body, "t") == body


@pytest.mark.parametrize("mode", ["bitflip", "truncate"])
def test_corrupted_sealed_file_raises(tmp_path, mode):
    p = str(tmp_path / "f.bin")
    with open(p, "wb") as f:
        f.write(seal(b"x" * 4096))
    assert read_verified(p) == b"x" * 4096
    chaos.corrupt_file(p, mode=mode)
    with pytest.raises(CheckpointCorruptError):
        read_verified(p)


# ---------------------------------------------------------------------------
# AsyncSaver: failure surfaces at the boundary; transient OSError healed
# ---------------------------------------------------------------------------


def test_async_saver_failure_surfaces_at_next_submit():
    saver = ckpt_lib.AsyncSaver()

    def boom():
        raise RuntimeError("disk on fire")

    saver.submit(boom)
    with pytest.raises(RuntimeError, match="async checkpoint save failed"):
        saver.submit(lambda: None)  # NEXT boundary, not silence
    saver.wait()  # error already consumed; saver is reusable
    done = []
    saver.submit(lambda: done.append(1))
    saver.wait()
    assert done == [1]


def test_async_saver_check_surfaces_without_new_submit():
    saver = ckpt_lib.AsyncSaver()

    def boom():
        raise RuntimeError("gone")

    saver.submit(boom)
    with pytest.raises(RuntimeError, match="async checkpoint save failed"):
        for _ in range(100):  # per-step poll; must not need a new save
            saver.check()


def test_async_saver_heals_transient_oserror():
    saver = ckpt_lib.AsyncSaver(retry_base_delay=0.01)
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise OSError(errno.EIO, "flake")

    saver.submit(flaky)
    saver.wait()  # no raise: healed
    assert len(calls) == 3 and saver.io_retries_used == 2


def test_async_saver_persistent_oserror_still_fails():
    saver = ckpt_lib.AsyncSaver(io_retries=1, retry_base_delay=0.0)

    def dead():
        raise OSError(errno.ENOSPC, "disk full")

    saver.submit(dead)
    with pytest.raises(RuntimeError, match="async checkpoint save failed"):
        saver.wait()


# ---------------------------------------------------------------------------
# bad-step auto-recovery (real fit)
# ---------------------------------------------------------------------------


def test_nan_batch_skipped_params_frozen_no_recompile(devices):
    """The poisoned step leaves params bit-identical, fires the bad_step
    metric, and reuses the SAME compiled executable (no recompile)."""
    mesh = dpx.runtime.make_mesh()
    trainer = make_trainer(mesh)
    loader = _loader(mesh)
    batch = next(iter(loader))
    with mesh:
        trainer.init(batch["x"])
        step = trainer.train_step.lower(trainer.state, batch).compile()
        state1, m1 = step(trainer.state, batch)
        assert float(m1["bad_step"]) == 0.0
        before = _digest(state1.params)
        step1 = int(state1.step)  # read BEFORE donation deletes state1
        chaos.install(chaos.ChaosPlan(
            faults=[chaos.Fault("nan-batch", step=0)]
        ))
        poisoned = chaos.corrupt_batch(batch, 0)
        chaos.uninstall()
        # the SAME executable accepts the poisoned batch: the layout is
        # preserved by corrupt_batch, so nothing recompiles
        state2, m2 = step(state1, poisoned)
        assert float(m2["bad_step"]) == 1.0
        assert _digest(state2.params) == before  # update predicated out
        assert int(state2.step) == step1 + 1  # step advances regardless
        # and the next clean step trains normally
        state3, m3 = step(state2, batch)
        assert float(m3["bad_step"]) == 0.0
        assert _digest(state3.params) != before


def test_fit_counts_skips_and_keeps_training(devices):
    mesh = dpx.runtime.make_mesh()
    chaos.install(chaos.ChaosPlan(faults=[chaos.Fault("nan-batch", step=2)]))
    trainer = make_trainer(mesh)
    history = trainer.fit(_loader(mesh), epochs=2)
    assert trainer.recovery["bad_steps"] == 1
    assert trainer.recovery["rollbacks"] == 0
    assert np.isfinite(history[-1]["train_loss"])


def test_budget_rollback_then_hard_fail(tmp_path, devices):
    """Persistent NaN: one rollback to `latest`, then
    BadStepBudgetExceeded — never an unbounded skip loop."""
    mesh = dpx.runtime.make_mesh()
    chaos.install(chaos.ChaosPlan(
        faults=[chaos.Fault("nan-batch", step=2, count=10_000)]
    ))
    trainer = make_trainer(
        mesh, ckpt=str(tmp_path), log_every=1, max_bad_steps=1,
        save_every_steps=1,
    )
    with pytest.raises(BadStepBudgetExceeded, match="again after a rollback"):
        trainer.fit(_loader(mesh), epochs=3)
    assert trainer.recovery["rollbacks"] == 1
    assert trainer.recovery["bad_steps"] >= 2


def test_budget_without_checkpoint_fails_without_rollback(devices):
    mesh = dpx.runtime.make_mesh()
    chaos.install(chaos.ChaosPlan(
        faults=[chaos.Fault("nan-batch", step=0, count=10_000)]
    ))
    trainer = make_trainer(mesh, log_every=1, max_bad_steps=1)
    with pytest.raises(
        BadStepBudgetExceeded, match="no checkpoint to roll back to"
    ):
        trainer.fit(_loader(mesh), epochs=1)
    assert trainer.recovery["rollbacks"] == 0


def test_skip_nonfinite_false_restores_pre_r10_step(devices):
    """Opt-out: without predication a poisoned batch poisons params."""
    mesh = dpx.runtime.make_mesh()
    trainer = make_trainer(mesh, skip_nonfinite=False)
    loader = _loader(mesh)
    batch = next(iter(loader))
    with mesh:
        trainer.init(batch["x"])
        chaos.install(chaos.ChaosPlan(
            faults=[chaos.Fault("nan-batch", step=0)]
        ))
        poisoned = chaos.corrupt_batch(batch, 0)
        chaos.uninstall()
        state, metrics = trainer.train_step(trainer.state, poisoned)
        assert "bad_step" not in metrics
        # the NaN reaches the kernels (layer-1 bias grads are zeroed by
        # relu'(NaN) == 0, so not EVERY leaf is poisoned)
        leaves = [
            np.asarray(x) for x in jax.tree_util.tree_leaves(state.params)
        ]
        assert any(not np.isfinite(x).all() for x in leaves)


# ---------------------------------------------------------------------------
# checkpoint integrity: retention + fallback walk (real files)
# ---------------------------------------------------------------------------


def _gathered_run(tmp_path, mesh, epochs=3):
    trainer = make_trainer(mesh, ckpt=str(tmp_path))
    trainer.fit(_loader(mesh), epochs=epochs)
    return trainer, os.path.join(str(tmp_path), ckpt_lib.LATEST_NAME)


def test_gathered_retention_keeps_last_k(tmp_path, devices):
    mesh = dpx.runtime.make_mesh()
    _trainer, latest = _gathered_run(tmp_path, mesh, epochs=5)
    hist = ckpt_lib._gathered_history_paths(latest)
    assert len(hist) == ckpt_lib.DEFAULT_RETAIN
    # `latest` IS the newest history entry (hard link), not a 4th copy
    assert os.path.samefile(latest, hist[0])


def test_corrupt_latest_falls_back_to_intact_ancestor(tmp_path, devices):
    mesh = dpx.runtime.make_mesh()
    trainer, latest = _gathered_run(tmp_path, mesh)
    chaos.corrupt_file(latest, mode="bitflip", seed=1)
    events = []
    _state, epoch, _extra = ckpt_lib.load_checkpoint(
        latest, trainer.state, trainer.state_shardings,
        on_event=lambda kind, **f: events.append({"event": kind, **f}),
    )
    assert epoch == 2  # newest intact ancestor (epoch-3 copy was flipped)
    fb = [e for e in events if e["event"] == "checkpoint_fallback"]
    assert len(fb) == 1 and len(fb[0]["skipped"]) == 1
    assert "checksum mismatch" in fb[0]["skipped"][0]["reason"]


def test_all_candidates_corrupt_raises_listing_attempts(tmp_path, devices):
    mesh = dpx.runtime.make_mesh()
    trainer, latest = _gathered_run(tmp_path, mesh)
    for i, p in enumerate([latest] + ckpt_lib._gathered_history_paths(latest)):
        chaos.corrupt_file(p, mode="bitflip", seed=i)
    with pytest.raises(CheckpointCorruptError, match="no intact"):
        ckpt_lib.load_checkpoint(
            latest, trainer.state, trainer.state_shardings
        )


def test_fallback_disabled_raises_first_error(tmp_path, devices):
    mesh = dpx.runtime.make_mesh()
    trainer, latest = _gathered_run(tmp_path, mesh)
    chaos.corrupt_file(latest, mode="bitflip")
    with pytest.raises(CheckpointCorruptError, match="checksum mismatch"):
        ckpt_lib.load_checkpoint(
            latest, trainer.state, trainer.state_shardings, fallback=False
        )


def test_truncated_shard_falls_back_to_previous_version(tmp_path, devices):
    import glob

    mesh = dpx.runtime.make_mesh()
    trainer = make_trainer(
        mesh, ckpt=str(tmp_path), checkpoint_format="sharded"
    )
    trainer.fit(_loader(mesh), epochs=3)
    latest = os.path.join(str(tmp_path), ckpt_lib.LATEST_NAME)
    versions = sorted(glob.glob(os.path.join(f"{latest}.shards", "*")))
    assert len(versions) == ckpt_lib.DEFAULT_RETAIN  # keep-last-K GC
    shard = glob.glob(os.path.join(versions[-1], "shard_*.msgpack"))[0]
    chaos.corrupt_file(shard, mode="truncate")
    events = []
    _state, epoch, _extra = ckpt_lib.load_checkpoint(
        latest, trainer.state, trainer.state_shardings,
        on_event=lambda kind, **f: events.append(kind),
    )
    assert epoch == 2  # previous intact version (pointer said epoch 3)
    assert events.count("checkpoint_fallback") == 1


def test_corrupt_sharded_pointer_falls_back_to_version_scan(
    tmp_path, devices
):
    """A bit-flipped POINTER (not shard) still resolves: the version-dir
    scan finds the newest intact version without the pointer's help."""
    mesh = dpx.runtime.make_mesh()
    trainer = make_trainer(
        mesh, ckpt=str(tmp_path), checkpoint_format="sharded"
    )
    trainer.fit(_loader(mesh), epochs=2)
    latest = os.path.join(str(tmp_path), ckpt_lib.LATEST_NAME)
    with open(latest, "wb") as f:  # pointer destroyed entirely
        f.write(b"garbage that is neither magic nor msgpack")
    _state, epoch, _extra = ckpt_lib.load_checkpoint(
        latest, trainer.state, trainer.state_shardings
    )
    assert epoch == 2


def test_fit_resume_from_corrupt_latest_auto_falls_back(tmp_path, devices):
    """End-to-end acceptance: corrupt `latest`, rerun fit --resume, and
    training continues from the intact ancestor with the event counted."""
    mesh = dpx.runtime.make_mesh()
    _t, latest = _gathered_run(tmp_path, mesh)
    chaos.corrupt_file(latest, mode="bitflip")
    t2 = make_trainer(mesh, ckpt=str(tmp_path))
    history = t2.fit(_loader(mesh), epochs=4, resume=latest)
    assert t2.recovery["checkpoint_fallbacks"] == 1
    # resumed from the intact epoch-2 ancestor, so epochs 2..3 train
    assert [r["epoch"] for r in history] == [2, 3]


# ---------------------------------------------------------------------------
# transient I/O + rendezvous through the real paths
# ---------------------------------------------------------------------------


def test_fit_survives_transient_checkpoint_io_errors(tmp_path, devices):
    mesh = dpx.runtime.make_mesh()
    chaos.install(chaos.ChaosPlan(
        faults=[chaos.Fault("io-error", path_substr="latest", count=2)]
    ))
    trainer = make_trainer(mesh, ckpt=str(tmp_path), save_every_steps=2)
    trainer.fit(_loader(mesh), epochs=2)
    assert trainer._saver.io_retries_used >= 1
    assert os.path.exists(os.path.join(str(tmp_path), ckpt_lib.LATEST_NAME))


def test_rendezvous_retries_with_backoff(monkeypatch):
    from distributed_pytorch_example_tpu.runtime import distributed as dist

    fault = chaos.Fault("rendezvous-flake", count=2)
    chaos.install(chaos.ChaosPlan(faults=[fault]))
    monkeypatch.setattr(dist, "_initialized", False)
    monkeypatch.setenv("DPX_RENDEZVOUS_BACKOFF", "0.01")
    dist.initialize()
    assert fault.fired == 2  # two flakes healed by the third attempt


def test_rendezvous_retries_exhausted_raises(monkeypatch):
    from distributed_pytorch_example_tpu.runtime import distributed as dist

    chaos.install(chaos.ChaosPlan(
        faults=[chaos.Fault("rendezvous-flake", count=100)]
    ))
    monkeypatch.setattr(dist, "_initialized", False)
    monkeypatch.setenv("DPX_RENDEZVOUS_BACKOFF", "0.0")
    with pytest.raises(RuntimeError, match="chaos"):
        dist.initialize(max_attempts=3)


# ---------------------------------------------------------------------------
# the sweep harness itself
# ---------------------------------------------------------------------------


def _run_sweep(extra):
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("DPX_CHAOS", None)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "chaos_sweep.py"),
         *extra],
        capture_output=True, text=True, cwd=REPO, timeout=900, env=env,
    )
    lines = [json.loads(ln) for ln in proc.stdout.splitlines() if ln.strip()]
    return proc, lines


def test_chaos_sweep_fast_subset_green():
    proc, lines = _run_sweep(["--fast"])
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert [r["scenario"] for r in lines] == [
        "nan-skip", "corrupt-latest", "io-flake", "rendezvous-flake",
        "kill-slice", "poison-request", "kill-replica-midstream",
        "corrupt-shard-midepoch", "kill-decode-worker",
        "hot-swap-midstream",
    ]
    assert all(r["ok"] for r in lines), lines
    by_name = {r["scenario"]: r for r in lines}
    kill_slice = by_name["kill-slice"]
    assert kill_slice["action"] == "shrink-to-survivors-resume"
    assert kill_slice["max_loss_diff"] <= 1e-3 + 1e-4
    poison = by_name["poison-request"]
    assert poison["action"] == "evict-poisoned-request"
    assert poison["co_resident_bit_identical"] is True
    fleet = by_name["kill-replica-midstream"]
    assert fleet["action"] == "failover-replay"
    assert fleet["greedy"]["bit_identical_to_clean"] is True
    assert fleet["seeded-topk"]["replay_token_exact"] is True
    assert fleet["steady_state_ratio"] <= 1.05
    shard = by_name["corrupt-shard-midepoch"]
    assert shard["action"] == "quarantine-and-remap"
    assert shard["quarantined"] == [2]
    assert shard["max_loss_diff_vs_prequarantined_control"] == 0.0
    assert shard["params_match_control"] is True
    assert shard["steady_state_ratio"] <= 1.05
    decode = by_name["kill-decode-worker"]
    assert decode["action"] == "supervised-worker-restart"
    assert decode["worker_restarts"] >= 1
    assert decode["max_loss_diff_vs_uninjected"] == 0.0
    assert decode["params_match_uninjected"] is True
    swap = by_name["hot-swap-midstream"]
    assert swap["action"] == "drain-install-readmit"
    assert swap["channel_latest"] == swap["published_good"]
    for regime in ("greedy", "seeded-topk"):
        assert swap[regime]["swaps_completed"] == 1
        assert swap[regime]["co_resident_bit_identical"] is True
        assert swap[regime]["fresh_sessions_on_new_version"] is True
        assert swap[regime]["swap_blackout_ms"] is not None


@pytest.mark.slow
def test_chaos_sweep_full_matrix_green():
    proc, lines = _run_sweep([])
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert all(r["ok"] for r in lines), lines
    actions = {r["scenario"]: r["action"] for r in lines}
    assert actions["torn-save-kill"] == "resume-from-intact-ancestor"
    assert actions["sigint"] == "checkpoint-and-exit-130"


# ---------------------------------------------------------------------------
# steady-state overhead of the predication (satellite 6)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_predication_overhead_within_budget(devices):
    """skip_nonfinite adds ≤2% to the compiled step (min-of-N; the ISSUE's
    ≤1% claim is measured on TPU via `bench.py --chaos`, where the fixed
    host-side cost this fake CPU mesh amplifies is invisible)."""
    import gc
    import time

    mesh = dpx.runtime.make_mesh()
    rng = np.random.default_rng(0)
    batch_np = {
        "x": rng.standard_normal((64, 784)).astype(np.float32),
        "y": rng.integers(0, 10, (64,)).astype(np.int32),
    }

    def compiled_step(skip):
        trainer = dpx.train.Trainer(
            dpx.models.SimpleNet(hidden_size=512),
            dpx.train.ClassificationTask(),
            optax.adam(1e-3),
            partitioner=dpx.parallel.data_parallel(mesh),
            telemetry=False,
            skip_nonfinite=skip,
        )
        sharding = trainer.partitioner.batch_sharding()
        batch = {
            k: jax.make_array_from_process_local_data(sharding, v)
            for k, v in batch_np.items()
        }
        trainer.init(batch["x"])
        return (
            trainer.train_step.lower(trainer.state, batch).compile(),
            trainer.state,
            batch,
        )

    n_steps, rounds = 15, 8

    def run(step, state, batch):
        holder = {"state": state}
        metrics = None
        for _ in range(5):
            holder["state"], metrics = step(holder["state"], batch)
        float(metrics["loss"])
        times = []
        for _ in range(rounds):
            t0 = time.perf_counter()
            for _ in range(n_steps):
                holder["state"], metrics = step(holder["state"], batch)
            float(metrics["loss"])
            times.append(time.perf_counter() - t0)
        return min(times)

    with mesh:
        step_off, state_off, batch = compiled_step(False)
        step_on, state_on, _ = compiled_step(True)
        gc.disable()
        try:
            t_off = run(step_off, state_off, batch)
            t_on = run(step_on, state_on, batch)
        finally:
            gc.enable()
    # 2% + a 15ms absolute floor (fake-mesh step times sit near host
    # timer jitter; same floor as the graft-scope overhead gate)
    assert t_on <= t_off * 1.02 + 0.015, (t_on, t_off)

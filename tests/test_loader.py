"""DeviceLoader: sharded global batch assembly, static shapes, prefetch."""

import numpy as np

import jax

from distributed_pytorch_example_tpu.data import (
    DeviceLoader,
    SyntheticClassificationDataset,
)


def test_batch_shapes_and_count(mesh_1d):
    ds = SyntheticClassificationDataset(num_samples=100, input_size=16)
    loader = DeviceLoader(ds, global_batch_size=32, mesh=mesh_1d, shuffle=False)
    batches = list(loader)
    # 100 samples / 32 → 4 steps, final one wrap-padded to full size
    assert len(batches) == len(loader) == 4
    for b in batches:
        assert b["x"].shape == (32, 16)
        assert b["y"].shape == (32,)


def test_batches_sharded_over_data_axis(mesh_1d):
    ds = SyntheticClassificationDataset(num_samples=64, input_size=8)
    loader = DeviceLoader(ds, global_batch_size=32, mesh=mesh_1d, shuffle=False)
    b = next(iter(loader))
    sharding = b["x"].sharding
    assert sharding.is_fully_addressable
    # 32-row batch over 8 devices → 4 rows per device
    shard_shapes = {s.data.shape for s in b["x"].addressable_shards}
    assert shard_shapes == {(4, 8)}


def test_drop_last(mesh_1d):
    ds = SyntheticClassificationDataset(num_samples=100, input_size=4)
    loader = DeviceLoader(
        ds, global_batch_size=32, mesh=mesh_1d, shuffle=False, drop_last=True
    )
    assert len(loader) == 3


def test_content_matches_sampler_order(mesh_1d):
    ds = SyntheticClassificationDataset(num_samples=64, input_size=4, seed=9)
    loader = DeviceLoader(ds, global_batch_size=16, mesh=mesh_1d, shuffle=True, seed=5)
    loader.set_epoch(2)
    batches = [np.asarray(b["x"]) for b in loader]
    indices = loader.sampler.shard_indices()
    expected = ds.arrays["x"][indices]
    got = np.concatenate(batches)
    assert np.array_equal(got, expected)


def test_epoch_reshuffle_changes_batches(mesh_1d):
    ds = SyntheticClassificationDataset(num_samples=64, input_size=4)
    loader = DeviceLoader(ds, global_batch_size=32, mesh=mesh_1d, shuffle=True)
    loader.set_epoch(0)
    first0 = np.asarray(next(iter(loader))["x"])
    loader.set_epoch(1)
    first1 = np.asarray(next(iter(loader))["x"])
    assert not np.array_equal(first0, first1)
    loader.set_epoch(0)
    assert np.array_equal(first0, np.asarray(next(iter(loader))["x"]))


def test_no_mesh_plain_arrays():
    ds = SyntheticClassificationDataset(num_samples=32, input_size=4)
    loader = DeviceLoader(ds, global_batch_size=16, mesh=None, shuffle=False)
    b = next(iter(loader))
    assert isinstance(b["x"], jax.Array)
    assert b["x"].shape == (16, 4)


def test_prefetch_disabled_equivalent(mesh_1d):
    ds = SyntheticClassificationDataset(num_samples=64, input_size=4)
    kwargs = dict(global_batch_size=16, mesh=mesh_1d, shuffle=True, seed=1)
    a = [np.asarray(b["x"]) for b in DeviceLoader(ds, prefetch=2, **kwargs)]
    b = [np.asarray(b["x"]) for b in DeviceLoader(ds, prefetch=0, **kwargs)]
    assert all(np.array_equal(x, y) for x, y in zip(a, b))


def test_tuple_dataset_convention(mesh_1d):
    class TupleDs:
        def __len__(self):
            return 16

        def __getitem__(self, i):
            return np.full((4,), i, np.float32), np.int32(i % 3)

    loader = DeviceLoader(TupleDs(), global_batch_size=8, mesh=mesh_1d, shuffle=False)
    b = next(iter(loader))
    assert b["x"].shape == (8, 4)
    assert np.asarray(b["y"]).tolist() == [0, 1, 2, 0, 1, 2, 0, 1]


def test_abandoned_iteration_joins_prefetch_thread(mesh_1d):
    """Abandoning a prefetching iterator mid-epoch (GeneratorExit — e.g.
    a bad-step rollback unwinding the epoch loop) must stop, drain, and
    JOIN the supervised worker — the old fire-and-forget thread stayed
    parked on a full queue forever, leaking one thread per abandonment."""
    import gc
    import threading

    from distributed_pytorch_example_tpu.data.synthetic import (
        SyntheticClassificationDataset,
    )

    ds = SyntheticClassificationDataset(num_samples=512)
    for _ in range(5):  # one leak per abandonment would accumulate here
        loader = DeviceLoader(ds, 8, mesh=mesh_1d, prefetch=2)
        it = iter(loader)
        next(it)  # worker is live and its queue fills behind the consumer
        it.close()  # deliver GeneratorExit to iter_from's finally
    gc.collect()
    leaked = [
        t for t in threading.enumerate()
        if t.name.startswith("intake-") and t.is_alive()
    ]
    assert not leaked, f"abandoned iterations leaked threads: {leaked}"
    # the close path still accumulated the iteration's counters
    assert loader.batches_served == 1

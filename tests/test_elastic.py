"""graft-elastic: mesh-shape-agnostic resume + shrink-to-survivors.

Three surfaces, one contract:

- the cross-mesh resume EQUIVALENCE MATRIX: a checkpoint saved at one
  (data, tensor, pipe) shape resumes onto a different shape and the
  post-resume loss trajectory matches an uninterrupted run within the
  tolerances tests/test_zero1.py pins for gradient-sync equivalence
  (5e-4 params / 1e-3 loss) — the global batch is mesh-shape-independent,
  so the math only differs by floating-point reduction order;
- the format-3 ``mesh_manifest`` stamp and its backward-compat contract:
  unstamped r10-era checkpoints still load on the SAME mesh, elastic
  resume from them raises :class:`MissingMeshManifestError`, and the
  corrupt-fallback walk-back prefers same-mesh ancestors exactly when
  ``DPX_ELASTIC`` is unset;
- the shrink-to-survivors launcher path (``runtime/distributed.py``):
  pure survivor-set derivation, probe semantics, and the env-gated
  shrink retry inside ``initialize`` — all unit-tested with fake probes
  (the end-to-end kill-a-slice run lives in scripts/chaos_sweep.py).
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from distributed_pytorch_example_tpu.models.gpt2 import GPT2
from distributed_pytorch_example_tpu.parallel.api import data_parallel
from distributed_pytorch_example_tpu.parallel.partition import (
    transformer_partitioner,
)
from distributed_pytorch_example_tpu.robustness import chaos, elastic
from distributed_pytorch_example_tpu.runtime import (
    MeshSpec,
    distributed,
    make_mesh,
)
from distributed_pytorch_example_tpu.runtime.distributed import (
    DistributedConfig,
)
from distributed_pytorch_example_tpu.train import checkpoint as ckpt_lib
from distributed_pytorch_example_tpu.train.step import (
    build_train_step,
    init_state,
)
from distributed_pytorch_example_tpu.train.tasks import CausalLMTask

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# the bars tests/test_zero1.py pins for reduction-order equivalence
TOL_PARAMS = 5e-4
TOL_LOSS = 1e-3
PRE_STEPS = 2   # steps before the save on the source mesh
K_RESUME = 3    # post-resume steps compared against the control

_TOKENS = np.random.default_rng(0).integers(0, 64, (16, 16)).astype(np.int32)


def _tiny_model():
    return GPT2(
        vocab_size=64, max_len=32, model_dim=32, num_layers=1,
        num_heads=2, mlp_dim=64, logits_mode="hidden",
    )


def _copy(state):
    # compiled steps donate their input state; never feed a cached state
    # object into a step twice
    return jax.tree_util.tree_map(
        lambda x: x.copy() if isinstance(x, jax.Array) else x, state
    )


def _max_diff(a, b):
    # via host: the two trees may live on different device subsets
    diffs = jax.tree_util.tree_map(
        lambda x, y: float(np.max(np.abs(
            np.asarray(x, np.float32) - np.asarray(y, np.float32)
        ))),
        a, b,
    )
    return max(jax.tree_util.tree_leaves(diffs))


_CFG_CACHE = {}


def _config(name):
    """(mesh, batch, state0, shardings, step) for one named mesh shape.

    All five shapes host the SAME tiny GPT-2, so any config's checkpoint
    restores into any other's template. Memoized: each entry costs one
    jit compile on the one-core build box.
    """
    if name in _CFG_CACHE:
        return _CFG_CACHE[name]
    model, task, opt = _tiny_model(), CausalLMTask(), optax.adam(1e-3)
    if name == "dp8":
        mesh = make_mesh()
        part = data_parallel(mesh)
    elif name == "dp4":
        mesh = make_mesh(devices=jax.devices()[:4])
        part = data_parallel(mesh)
    elif name == "dp8z":
        mesh = make_mesh()
        part = data_parallel(
            mesh, dp_shard_opt_state=True, opt_shard_min_size=1
        )
    elif name == "dp2tp2":
        mesh = make_mesh(
            MeshSpec(data=2, tensor=2), devices=jax.devices()[:4]
        )
        part = transformer_partitioner(mesh)
    else:
        raise KeyError(name)
    batch = {"tokens": jax.device_put(_TOKENS, part.batch_sharding())}
    with mesh:
        state0, shardings = init_state(
            model, opt, batch["tokens"], jax.random.key(0), part
        )
        step = build_train_step(
            model, task, opt, partitioner=part, grad_accum_steps=1
        )
    if name != "dp8":
        # jax RNG values depend on the sharding the init jit runs under
        # (the dim-0 "tensor"-sharded leaves draw different bits), so a
        # per-config init would diverge at step 0. Re-slice ONE canonical
        # init onto this config's layout instead — exactly what a
        # checkpoint restore does, which is the surface under test.
        state0 = jax.device_put(_config("dp8")[2], shardings)
    _CFG_CACHE[name] = (mesh, batch, state0, shardings, step)
    return _CFG_CACHE[name]


_TRAJ_CACHE = {}


def _traj(name, n, start=None):
    """(state after n steps, loss trajectory) for one config.

    ``start=None`` runs from the config's init (memoized); passing a
    restored state runs the post-resume continuation (not cached).
    """
    key = (name, n)
    if start is None and key in _TRAJ_CACHE:
        return _TRAJ_CACHE[key]
    mesh, batch, state0, _, step = _config(name)
    state = _copy(state0 if start is None else start)
    losses = []
    with mesh:
        for _ in range(n):
            state, metrics = step(state, batch)
            losses.append(float(metrics["loss"]))
    if start is None:
        _TRAJ_CACHE[key] = (state, losses)
    return state, losses


# ---------------------------------------------------------------------------
# cross-mesh resume equivalence matrix
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "src,tgt,fmt",
    [
        ("dp8", "dp4", "gathered"),   # shrink, both formats
        ("dp8", "dp4", "sharded"),
        ("dp4", "dp8", "gathered"),   # grow
        ("dp2tp2", "dp4", "sharded"),  # TP regather into pure DP
        ("dp8z", "dp4", "sharded"),   # ZeRO-1 -> replicated across shapes
        ("dp4", "dp8z", "sharded"),   # replicated -> ZeRO-1 across shapes
    ],
)
def test_cross_mesh_resume_matches_uninterrupted(
    tmp_path, devices, src, tgt, fmt
):
    """Save at ``src``'s shape, restore onto ``tgt``'s, continue K steps:
    the post-resume trajectory matches an uninterrupted control run."""
    src_state, _ = _traj(src, PRE_STEPS)
    path = str(tmp_path / "ck")
    ckpt_lib.save_checkpoint(
        path, src_state, 1, 0.0, {}, sharded=(fmt == "sharded")
    )

    _, _, state0_t, shardings_t, _ = _config(tgt)
    restored, epoch, _ = ckpt_lib.load_checkpoint(path, state0_t, shardings_t)
    assert epoch == 1
    # restored leaves landed on the TARGET layout, not the stamped one
    leaf_r = jax.tree_util.tree_leaves(restored.params)[0]
    leaf_t = jax.tree_util.tree_leaves(shardings_t.params)[0]
    assert leaf_r.sharding == leaf_t

    final, losses = _traj(tgt, K_RESUME, start=restored)
    ctrl_state, ctrl_losses = _traj("dp8", PRE_STEPS + K_RESUME)
    for got, want in zip(losses, ctrl_losses[PRE_STEPS:]):
        assert abs(got - want) < TOL_LOSS, (losses, ctrl_losses)
    assert _max_diff(final.params, ctrl_state.params) < TOL_PARAMS


def test_pipe_shrink_resume_matches_uninterrupted(tmp_path, devices):
    """pipe=2 -> pipe=1: the pipe-stacked parameter stacks re-balance onto
    a mesh with no pipeline span, and training continues equivalently."""
    def mk(sched):
        # the schedules are checkpoint-compatible (identical param trees,
        # pinned by test_stacked.py); 1f1b refuses a pipe span of 1, so
        # the shrunken mesh runs the same params under gpipe
        return GPT2(
            vocab_size=64, max_len=32, model_dim=32, num_layers=2,
            num_heads=2, mlp_dim=64, pipe_axis="pipe", pipe_schedule=sched,
            pipe_microbatches=4, logits_mode="hidden",
        )

    task, opt = CausalLMTask(), optax.adam(1e-3)

    def build(model, mesh, canon=None):
        part = transformer_partitioner(mesh)
        batch = {"tokens": jax.device_put(_TOKENS, part.batch_sharding())}
        with mesh:
            state0, shardings = init_state(
                model, opt, batch["tokens"], jax.random.key(0), part
            )
            step = build_train_step(
                model, task, opt, partitioner=part, grad_accum_steps=1
            )
        if canon is not None:
            # same canonical-init rationale as _config
            state0 = jax.device_put(canon, shardings)
        return mesh, batch, state0, shardings, step

    def run(cfg, n, start):
        mesh, batch, _, _, step = cfg
        state, losses = _copy(start), []
        with mesh:
            for _ in range(n):
                state, metrics = step(state, batch)
                losses.append(float(metrics["loss"]))
        return state, losses

    src = build(mk("1f1b"), make_mesh(MeshSpec(data=4, pipe=2)))
    tgt = build(
        mk("gpipe"), make_mesh(MeshSpec(data=4), devices=jax.devices()[:4]),
        canon=src[2],
    )

    # the source actually spans the pipe axis (stacked stage dim sharded)
    q = src[2].params["decoder"]["q_kernel"]
    assert "pipe" in str(q.sharding.spec)

    try:
        src_state, _ = run(src, PRE_STEPS, src[2])
    except Exception as err:  # pragma: no cover - backend-dependent
        if "PartitionId" in str(err):
            pytest.skip(
                "pipeline step does not SPMD-partition on this backend "
                "(XLA 'PartitionId instruction is not supported' — the "
                "same environmental limit the test_stacked.py pipeline "
                "suite hits on this box)"
            )
        raise
    path = str(tmp_path / "ck")
    ckpt_lib.save_checkpoint(path, src_state, 1, 0.0, {}, sharded=True)

    restored, epoch, _ = ckpt_lib.load_checkpoint(path, tgt[2], tgt[3])
    assert epoch == 1
    final, losses = run(tgt, K_RESUME, restored)
    ctrl_state, ctrl_losses = run(tgt, PRE_STEPS + K_RESUME, tgt[2])
    for got, want in zip(losses, ctrl_losses[PRE_STEPS:]):
        assert abs(got - want) < TOL_LOSS, (losses, ctrl_losses)
    assert _max_diff(final.params, ctrl_state.params) < TOL_PARAMS


# ---------------------------------------------------------------------------
# format-3 stamp + backward compat
# ---------------------------------------------------------------------------


def test_both_formats_carry_format3_stamp(tmp_path, devices):
    from flax import serialization

    from distributed_pytorch_example_tpu.robustness.integrity import (
        read_verified,
    )

    state, _ = _traj("dp8z", 1)
    g_path = str(tmp_path / "g.ckpt")
    s_path = str(tmp_path / "s.ckpt")
    ckpt_lib.save_checkpoint(g_path, state, 1, 0.0, sharded=False)
    ckpt_lib.save_checkpoint(s_path, state, 1, 0.0, sharded=True)

    payload = serialization.msgpack_restore(read_verified(g_path))
    manifest = serialization.msgpack_restore(read_verified(os.path.join(
        ckpt_lib._pointed_version_dir(s_path), "manifest.msgpack"
    )))
    for blob in (payload, manifest):
        stamp = blob[elastic.MANIFEST_KEY]
        assert int(stamp["format"]) == elastic.MANIFEST_FORMAT
        assert elastic.canonical_axes(stamp["axes"]) == {"data": 8}
        # ZeRO-1 scatter dims recorded for the opt-state leaves
        assert stamp["zero1_dims"], stamp
        assert all(
            elastic._OPT_STATE_RE.search(p) for p in stamp["zero1_dims"]
        )


def test_mesh_manifest_from_live_state(devices):
    _, _, state0, _, _ = _config("dp8z")
    stamp = elastic.mesh_manifest(state0)
    assert stamp["format"] == elastic.MANIFEST_FORMAT
    assert elastic.canonical_axes(stamp["axes"]) == {"data": 8}
    # replicated params: empty/None spec entries; sharded opt moments:
    # a 'data' axis on the scatter dim named by zero1_dims
    for p, dim in stamp["zero1_dims"].items():
        assert "data" in elastic._entry_axes(stamp["specs"][p][dim])
    # pure-host trees carry no sharding: no stamp, legacy contract
    assert elastic.mesh_manifest({"a": 1}) is None
    # size-1 axes never count as a topology difference
    assert elastic.canonical_axes({"data": 4, "tensor": 1}) == {"data": 4}


@pytest.mark.parametrize("fmt", ["gathered", "sharded"])
def test_unstamped_checkpoint_backward_compat(
    tmp_path, devices, monkeypatch, fmt
):
    """r10-era (unstamped) checkpoints: same-mesh load keeps working with
    no env set; elastic resume refuses with the clear manifest error."""
    _, _, state0, shardings, _ = _config("dp8")
    path = str(tmp_path / "ck")
    # save exactly like r10 did: no stamp at all
    monkeypatch.setattr(elastic, "mesh_manifest", lambda state: None)
    ckpt_lib.save_checkpoint(
        path, state0, 1, 0.0, {}, sharded=(fmt == "sharded")
    )
    monkeypatch.undo()

    monkeypatch.delenv(elastic.ELASTIC_ENV, raising=False)
    restored, epoch, _ = ckpt_lib.load_checkpoint(path, state0, shardings)
    assert epoch == 1

    monkeypatch.setenv(elastic.ELASTIC_ENV, "1")
    with pytest.raises(
        elastic.MissingMeshManifestError, match=elastic.MANIFEST_KEY
    ):
        ckpt_lib.load_checkpoint(path, state0, shardings)


def test_fallback_ordering_elastic_vs_conservative(
    tmp_path, devices, monkeypatch
):
    """Corrupt newest + mixed-mesh ancestors: DPX_ELASTIC unset restores
    the older SAME-mesh ancestor; DPX_ELASTIC=1 restores the newest
    intact one regardless of its stamped shape."""
    state8, _ = _traj("dp8", 1)
    _, _, state0_4, _, _ = _config("dp4")
    path = str(tmp_path / "ck")
    ckpt_lib.save_checkpoint(path, state8, 1, 0.0, {}, sharded=True)   # mesh A
    ckpt_lib.save_checkpoint(path, state0_4, 2, 0.0, {}, sharded=True)  # mesh B
    ckpt_lib.save_checkpoint(path, state8, 3, 0.0, {}, sharded=True)   # mesh A
    chaos.corrupt_file(os.path.join(
        f"{path}.shards", "00000003", "shard_00000.msgpack"
    ))

    _, _, state0_8, shardings8, _ = _config("dp8")
    monkeypatch.delenv(elastic.ELASTIC_ENV, raising=False)
    _, epoch, _ = ckpt_lib.load_checkpoint(path, state0_8, shardings8)
    assert epoch == 1  # same-mesh ancestor preferred over newer cross-mesh

    monkeypatch.setenv(elastic.ELASTIC_ENV, "1")
    _, epoch, _ = ckpt_lib.load_checkpoint(path, state0_8, shardings8)
    assert epoch == 2  # newest intact wins, reshard-on-load absorbs shape


def test_resume_gap_steps(tmp_path):
    path = str(tmp_path / "ck")
    shards = f"{path}.shards"
    os.makedirs(os.path.join(shards, "00000002.00000001"))
    os.makedirs(os.path.join(shards, "00000002.00000003"))
    gap = elastic.resume_gap_steps(path, 2, {"batch_in_epoch": 1})
    assert gap == 2  # two mid-epoch saves newer than the restored cursor
    assert elastic.resume_gap_steps(path, 2, {"batch_in_epoch": 3}) == 0
    assert elastic.resume_gap_steps(path, 1, {}) is None  # epoch boundary

    g_path = str(tmp_path / "g.ckpt")
    with open(g_path, "w") as f:
        f.write("x")
    assert elastic.resume_gap_steps(g_path, 1) == 0  # single artifact
    hist = f"{g_path}.history"
    os.makedirs(hist)
    os.link(g_path, os.path.join(hist, "00000001.ckpt"))
    assert elastic.resume_gap_steps(g_path, 1) == 0  # newest entry IS path
    with open(os.path.join(hist, "00000002.ckpt"), "w") as f:
        f.write("y")
    assert elastic.resume_gap_steps(g_path, 1) is None  # newer torn save


# ---------------------------------------------------------------------------
# shrink-to-survivors (fake probes; the real kill lives in chaos_sweep)
# ---------------------------------------------------------------------------


def test_peer_address():
    cfg = DistributedConfig(4, 1, "myjob-0.svc.cluster.local:29500")
    assert distributed.peer_address(cfg, 3) == (
        "myjob-3.svc.cluster.local:29500"
    )
    bare = DistributedConfig(4, 0, "node-0:29")
    assert distributed.peer_address(bare, 2) == "node-2:29"
    with pytest.raises(ValueError):
        distributed.peer_address(DistributedConfig(1, 0, None), 0)


def test_compute_survivor_config():
    cfg = DistributedConfig(8, 5, "w-0.svc:29500")
    shrunk = distributed.compute_survivor_config(cfg, [0, 1, 6])
    assert shrunk.num_processes == 4
    assert shrunk.process_id == 2  # dense renumbering in original order
    assert shrunk.coordinator_address == "w-0.svc:29500"

    # the coordinator itself was lost: lowest survivor takes over
    cfg = DistributedConfig(8, 6, "w-0.svc:29500")
    shrunk = distributed.compute_survivor_config(cfg, [5, 7])
    assert shrunk.num_processes == 3
    assert shrunk.process_id == 1
    assert shrunk.coordinator_address == "w-5.svc:29500"


def test_shrink_to_survivors_probes_peers():
    cfg = DistributedConfig(4, 0, "job-0.svc:29500")
    probed = []

    def probe(address):
        probed.append(address)
        return "job-2" not in address

    shrunk = distributed.shrink_to_survivors(cfg, probe=probe)
    assert len(probed) == 3  # everyone but self
    assert shrunk.num_processes == 3
    assert shrunk.process_id == 0
    assert shrunk.coordinator_address == "job-0.svc:29500"


def test_initialize_shrinks_only_under_elastic(monkeypatch):
    calls = []

    def fake_join(config, max_attempts):
        calls.append(config)
        if config.num_processes == 4:
            raise RuntimeError("rendezvous exhausted")

    monkeypatch.setattr(distributed, "_attempt_join", fake_join)
    monkeypatch.setattr(distributed, "_initialized", False)
    cfg = DistributedConfig(4, 0, "job-0.svc:29500")
    lossy_probe = lambda address: "job-3" not in address  # noqa: E731

    # r10 behavior without the gate: the exhaustion error propagates
    monkeypatch.delenv(elastic.ELASTIC_ENV, raising=False)
    with pytest.raises(RuntimeError, match="exhausted"):
        distributed.initialize(cfg, probe=lossy_probe)

    # elastic: shrink to the 3 survivors and join at the smaller world
    monkeypatch.setenv(elastic.ELASTIC_ENV, "1")
    monkeypatch.setattr(distributed, "_initialized", False)
    joined = distributed.initialize(cfg, probe=lossy_probe)
    assert joined.num_processes == 3
    assert joined.process_id == 0
    assert calls[-1].num_processes == 3

    # every peer answered: a config error, not a lost slice — re-raise
    monkeypatch.setattr(distributed, "_initialized", False)
    with pytest.raises(RuntimeError, match="exhausted"):
        distributed.initialize(cfg, probe=lambda address: True)
    monkeypatch.setattr(distributed, "_initialized", False)


# ---------------------------------------------------------------------------
# offline checkpoint doctor (scripts/reshard_check.py)
# ---------------------------------------------------------------------------


def _reshard_check_module():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "reshard_check", os.path.join(REPO_ROOT, "scripts/reshard_check.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_reshard_check_prints_one_json_line(tmp_path, devices):
    """Subprocess contract: ONE JSON line on stdout, exit 0 iff intact
    and resumable; flipped bits flip the verdict."""
    state, _ = _traj("dp8z", 1)
    path = str(tmp_path / "ck")
    ckpt_lib.save_checkpoint(path, state, 1, 0.0, {}, sharded=True)

    def run():
        return subprocess.run(
            [
                sys.executable, os.path.join(REPO_ROOT, "scripts/reshard_check.py"),
                path, "--target", "data=4",
            ],
            capture_output=True, text=True, timeout=240, cwd=REPO_ROOT,
        )

    proc = run()
    lines = [l for l in proc.stdout.splitlines() if l.strip()]
    assert len(lines) == 1, proc.stdout + proc.stderr
    report = json.loads(lines[0])
    assert proc.returncode == 0, (report, proc.stderr)
    assert report["ok"] is True
    assert report["format"] == "sharded"
    assert report["manifest"]["format"] == elastic.MANIFEST_FORMAT
    assert report["manifest"]["axes"]["data"] == 8
    assert report["resumable"] is True
    actions = {e["action"] for e in report["reshard_plan"].values()}
    # replicated params + data-scattered ZeRO-1 moments under a resized
    # data axis
    assert "replicate" in actions and "repartition-zero1" in actions

    chaos.corrupt_file(os.path.join(
        ckpt_lib._pointed_version_dir(path), "shard_00000.msgpack"
    ))
    proc = run()
    report = json.loads(proc.stdout.splitlines()[-1])
    assert proc.returncode == 1
    assert report["ok"] is False and report["resumable"] is False


def test_reshard_check_inspect_in_process(tmp_path, devices, monkeypatch):
    """leaf_plan classification + the unstamped-is-unknowable contract
    (in-process: no second interpreter/jax import)."""
    rc = _reshard_check_module()
    assert rc.parse_target("data=4, tensor=2") == {"data": 4, "tensor": 2}
    stamped = {"data": 8, "tensor": 2, "pipe": 2}
    assert rc.leaf_plan("params/w", [], stamped, {"data": 4}) == "replicate"
    assert rc.leaf_plan(
        "params/w", ["tensor", None], stamped, {"data": 4, "tensor": 2}
    ) == "keep"
    assert rc.leaf_plan(
        "opt_state/0/mu/w", ["data", None], stamped, {"data": 4}
    ) == "repartition-zero1"
    assert rc.leaf_plan(
        "params/decoder/q_kernel", ["pipe", None], stamped, {"pipe": 1}
    ) == "rebalance-pipe"
    assert rc.leaf_plan(
        "params/w", ["data"], stamped, {"data": 4}
    ) == "reshard"

    # unstamped checkpoint: resumability is unknowable offline (None),
    # but intact artifacts still report ok
    _, _, state0, _, _ = _config("dp8")
    path = str(tmp_path / "unstamped")
    monkeypatch.setattr(elastic, "mesh_manifest", lambda state: None)
    ckpt_lib.save_checkpoint(path, state0, 1, 0.0, {}, sharded=True)
    monkeypatch.undo()
    report = rc.inspect_checkpoint(path, {"data": 4})
    assert report["resumable"] is None
    assert report["manifest"]["format"] == 2  # sealed but unstamped
    assert report["ok"] is True

"""graft-fleet: multi-replica router failover + scheduler drain contracts.

The load-bearing guarantee: a fleet of N engine replicas behind the
router produces tokens bit-identical to a single engine — in steady
state, across session-affine placement, and (the hard case) through a
replica dying mid-decode with its requests replayed elsewhere. Position-
folded per-request rng (serving/sampling.py) is what makes replay exact;
these tests pin that the routing machinery never leaks placement into
the tokens. The scheduler drain tests pin the host-side invariants the
replay path leans on: front-requeue seniority and exact block recycling.
"""

import json
import os
import subprocess
import sys
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_pytorch_example_tpu.robustness import chaos
from distributed_pytorch_example_tpu.serving import (
    EngineFetchTimeout,
    FleetRouter,
    InferenceEngine,
    PagedCacheConfig,
    ReplicaHandle,
    Request,
    Scheduler,
)

GPT2_KW = dict(vocab_size=61, max_len=32, model_dim=16, num_layers=1,
               num_heads=2, mlp_dim=32)
PAGED = dict(paged_num_blocks=16, paged_block_size=4, paged_max_blocks=4)

_CACHE = {}


def _model():
    if "gpt2" not in _CACHE:
        from distributed_pytorch_example_tpu.models.gpt2 import GPT2

        params = GPT2(**GPT2_KW).init(
            jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
        )["params"]
        _CACHE["gpt2"] = (GPT2(**GPT2_KW, decode=True, **PAGED), params)
    return _CACHE["gpt2"]


def _engine(temperature=0.0, top_k=None, **kw):
    model, params = _model()
    return InferenceEngine(
        model, params, num_slots=3, temperature=temperature, top_k=top_k,
        **kw,
    )


@pytest.fixture(scope="module", autouse=True)
def _warm_fleet_programs():
    """XLA compile freezes replica heartbeats; warm both sampling regimes
    once so routers with tight deadlines see only steady-state beats."""
    _engine(0.0, None).warmup()
    _engine(0.9, 5).warmup()


def _requests(n=6, max_new=8, sessions=0, seed=7):
    # prompt + max_new must fit max_context (16): prompts <= 8
    rng = np.random.default_rng(seed)
    return [
        Request(
            rid=f"q{i:02d}",
            prompt=[int(t) for t in rng.integers(0, 61, 4 + i % 5)],
            max_new_tokens=max_new,
            seed=1000 + i,
            session=f"s{i % sessions}" if sessions else None,
        )
        for i in range(n)
    ]


def _fleet(n=2, temperature=0.0, top_k=None, **router_kw):
    handles = [
        ReplicaHandle(f"r{i}", _engine(temperature, top_k))
        for i in range(n)
    ]
    return FleetRouter(handles, **router_kw), handles


def _single_reference(requests, temperature=0.0, top_k=None):
    report = _engine(temperature, top_k).run(requests)
    assert all(
        r["status"] == "done" for r in report["results"].values()
    )
    return {rid: r["tokens"] for rid, r in report["results"].items()}


# ---------------------------------------------------------------------------
# steady state: fleet output == single engine, placement spreads load
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("temperature,top_k", [(0.0, None), (0.9, 5)])
def test_fleet_bit_identical_to_single_engine(temperature, top_k):
    reqs = _requests()
    refs = _single_reference(reqs, temperature, top_k)
    router, _handles = _fleet(2, temperature, top_k)
    report = router.run(reqs)
    for r in reqs:
        got = report["results"][r.rid]
        assert got["status"] == "done"
        assert got["tokens"] == refs[r.rid], r.rid
    m = report["metrics"]
    assert m["completed"] == len(reqs)
    assert m["replicas_lost"] == 0
    # least-loaded placement actually used both replicas
    assert all(
        stats["finished"] >= 1 for stats in m["per_replica"].values()
    )
    assert all(
        stats["state"] == "stopped" for stats in m["per_replica"].values()
    )


def test_session_affinity_sticks_and_spreads():
    reqs = _requests(n=8, sessions=2)
    router, _handles = _fleet(2)
    report = router.run(reqs)
    placed = {}
    for r in reqs:
        res = report["results"][r.rid]
        assert res["status"] == "done"
        placed.setdefault(r.session, set()).add(res["replica"])
    # each session pinned to exactly one replica; sessions on distinct
    # replicas (least-loaded placed s1 away from s0's replica)
    assert all(len(reps) == 1 for reps in placed.values())
    assert len(set.union(*placed.values())) == 2


# ---------------------------------------------------------------------------
# failover: kill / stall / flaky channel
# ---------------------------------------------------------------------------


def _install(*faults):
    chaos.install(chaos.ChaosPlan(faults=list(faults)))


@pytest.mark.parametrize("temperature,top_k", [(0.0, None), (0.9, 5)])
def test_kill_replica_midstream_replays_token_exact(temperature, top_k):
    reqs = _requests(n=8)
    refs = _single_reference(reqs, temperature, top_k)
    router, handles = _fleet(2, temperature, top_k,
                             heartbeat_timeout_s=2.0)
    _install(chaos.Fault("kill-replica", at="r1", step=3))
    try:
        report = router.run(reqs)
    finally:
        chaos.uninstall()
    m = report["metrics"]
    assert m["replicas_lost"] == 1
    assert m["redispatched"] >= 1
    assert m["replayed"] >= 1
    assert m["replay_token_exact"] is True
    # a dead worker thread is caught immediately, far inside the deadline
    assert m["detection_latency_s"] < 2.0
    assert handles[1].state() == "dead"
    assert "kill" in handles[1].error()
    for r in reqs:
        got = report["results"][r.rid]
        assert got["status"] == "done"
        assert got["tokens"] == refs[r.rid], r.rid


def test_kill_replica_with_speculation_replays_token_exact():
    """Journal replay stays bit-identical with speculative decoding ON:
    the accept/reject sequence is a pure function of params + prompt +
    position-folded rng, so a replica loss mid-window replays to the
    same committed tokens — checked against a NON-speculative single
    engine, the strongest form of the determinism claim."""
    model, params = _model()

    def spec_engine():
        return InferenceEngine(
            model, params, num_slots=3, temperature=0.0,
            draft_model=model, draft_params=params, spec_tokens=3,
        )

    reqs = _requests(n=8)
    refs = _single_reference(reqs)  # plain greedy engine, no speculation
    # warm the propose/verify programs (shared jit cache) so compiles
    # don't freeze replica heartbeats mid-run
    spec_engine().run(reqs)
    handles = [ReplicaHandle(f"r{i}", spec_engine()) for i in range(2)]
    router = FleetRouter(handles, heartbeat_timeout_s=2.0)
    _install(chaos.Fault("kill-replica", at="r1", step=2))
    try:
        report = router.run(reqs)
    finally:
        chaos.uninstall()
    m = report["metrics"]
    assert m["replicas_lost"] == 1
    assert m["replayed"] >= 1
    assert m["replay_token_exact"] is True
    for r in reqs:
        got = report["results"][r.rid]
        assert got["status"] == "done"
        assert got["tokens"] == refs[r.rid], r.rid


def test_stall_replica_detected_by_heartbeat_deadline():
    reqs = _requests(n=8)
    refs = _single_reference(reqs)
    router, handles = _fleet(2, heartbeat_timeout_s=0.4)
    _install(chaos.Fault("stall-replica", at="r1", step=2))
    try:
        report = router.run(reqs)
    finally:
        chaos.uninstall()
    m = report["metrics"]
    assert m["replicas_lost"] == 1
    # a stalled thread stays alive: only the beat deadline can catch it
    assert 0.4 <= m["detection_latency_s"] < 5.0
    assert handles[1].state() == "dead"
    for r in reqs:
        assert report["results"][r.rid]["tokens"] == refs[r.rid]


def test_flaky_channel_healed_by_dispatch_retry():
    reqs = _requests()
    refs = _single_reference(reqs)
    router, _handles = _fleet(2)
    fault = chaos.Fault("flaky-channel", count=2)
    _install(fault)
    try:
        report = router.run(reqs)
    finally:
        chaos.uninstall()
    m = report["metrics"]
    assert fault.fired == 2
    assert m["dispatch_retries"] == 2
    assert m["replicas_lost"] == 0
    assert m["completed"] == len(reqs)
    for r in reqs:
        assert report["results"][r.rid]["tokens"] == refs[r.rid]


# ---------------------------------------------------------------------------
# degradation: bounded queue, deadline shedding
# ---------------------------------------------------------------------------


def test_router_queue_overflow_sheds():
    reqs = _requests(n=8)
    router, _handles = _fleet(2, max_queue=2)
    report = router.run(reqs)
    m = report["metrics"]
    assert m["shed"] == 6  # all 8 arrive at t=0; the queue holds 2
    assert m["completed"] >= 2
    shed = [
        r for r in report["results"].values() if r["status"] == "shed"
    ]
    assert len(shed) == 6


def test_router_deadline_sheds_stale_queue():
    # one replica, so the tail of the burst waits past the deadline
    reqs = _requests(n=8)
    # tighter than one router tick (sleep 2ms): whatever the burst
    # leaves queued after the first dispatch round is stale next tick
    router, _handles = _fleet(1, queue_deadline_s=0.001)
    report = router.run(reqs)
    m = report["metrics"]
    assert m["shed"] >= 1
    assert m["completed"] >= 1
    assert m["completed"] + m["shed"] == len(reqs)


# ---------------------------------------------------------------------------
# bounded fetches (the engine-side timeout satellite)
# ---------------------------------------------------------------------------


def test_fetch_timeout_raises_engine_fetch_timeout():
    engine = _engine(fetch_timeout_s=0.1)
    with pytest.raises(EngineFetchTimeout, match="deadline"):
        engine._fetch(lambda: time.sleep(2.0), "hung fetch")


def test_fetch_without_deadline_unchanged():
    engine = _engine()  # fetch_timeout_s=None: straight through retries
    assert engine._fetch(lambda: 42, "plain fetch") == 42


def test_hung_fetch_surfaces_as_replica_loss():
    """A device fetch that never returns must kill the replica (bounded
    by fetch_timeout_s) instead of hanging the fleet; the router then
    replays its requests on the survivor."""
    reqs = _requests()
    refs = _single_reference(reqs)
    engines = [_engine(fetch_timeout_s=30.0), _engine(fetch_timeout_s=0.2)]
    hang = threading.Event()

    orig = engines[1]._fetch

    def hung_fetch(thunk, describe):
        def maybe_hang():
            if hang.is_set():
                time.sleep(5.0)  # a wedged runtime: the thunk never lands
            return thunk()
        return orig(maybe_hang, describe)

    engines[1]._fetch = hung_fetch
    hang.set()
    handles = [
        ReplicaHandle(f"r{i}", e) for i, e in enumerate(engines)
    ]
    router = FleetRouter(handles, heartbeat_timeout_s=5.0)
    report = router.run(reqs)
    m = report["metrics"]
    assert m["replicas_lost"] == 1
    assert "EngineFetchTimeout" in handles[1].error()
    for r in reqs:
        got = report["results"][r.rid]
        assert got["status"] == "done"
        assert got["tokens"] == refs[r.rid]


# ---------------------------------------------------------------------------
# the CLI: serve.py --replicas keeps the ONE-stdout-JSON-line contract
# ---------------------------------------------------------------------------


def test_serve_cli_fleet_emits_router_metrics_in_one_line():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("DPX_CHAOS", None)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "serve.py"),
         "--replicas", "2", "--requests", "8", "--rate", "0",
         "--model-dim", "16", "--num-layers", "1", "--num-heads", "2",
         "--vocab-size", "61", "--max-len", "32",
         "--num-blocks", "16", "--block-size", "4", "--max-blocks", "4",
         "--slots", "3", "--prompt-len", "4:8", "--max-new", "4:8",
         "--sessions", "2"],
        capture_output=True, text=True, cwd=repo, timeout=600, env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [ln for ln in proc.stdout.splitlines() if ln.strip()]
    assert len(lines) == 1, lines  # the driver contract
    rec = json.loads(lines[0])
    assert rec["metric"] == "serve_tokens_per_sec"
    assert rec["replicas"] == 2
    assert rec["completed"] == 8
    for key in ("shed", "replayed", "redispatched", "dispatch_retries",
                "replicas_lost", "detection_latency_s", "queue_depth_max",
                "steady_per_row_ms",
                # graft-lens rolling latency summaries
                "ttft_p99_ms", "queue_wait_p99_ms", "journal_lag_p99_ms",
                "kv_occupancy_max", "sentinel_triggers"):
        assert key in rec, key
    assert rec["ttft_p99_ms"] > 0.0
    assert rec["queue_wait_p99_ms"] > 0.0
    assert rec["sentinel_triggers"] == []  # clean pass: nothing fired
    assert set(rec["per_replica"]) == {"r0", "r1"}
    for stats in rec["per_replica"].values():
        assert stats["state"] == "stopped"
        assert 0.0 <= stats["occupancy"] <= 1.0
    assert rec["config"]["replicas"] == 2


# ---------------------------------------------------------------------------
# scheduler under drain (host-side invariants the replay path leans on)
# ---------------------------------------------------------------------------


def _sched(num_blocks=8, block_size=2, max_blocks=3, num_slots=2):
    return Scheduler(PagedCacheConfig(
        num_blocks=num_blocks, block_size=block_size,
        max_blocks_per_slot=max_blocks, num_slots=num_slots,
    ))


def _req(rid, plen=3, max_new=2):
    return Request(rid=rid, prompt=list(range(plen)), max_new_tokens=max_new)


def test_preempt_youngest_front_requeues_and_recycles_blocks():
    sched = _sched()
    free0 = sched.allocator.free_count()
    for rid in ("a", "b"):
        sched.submit(_req(rid), now=0.0)
    sched.admit(now=0.0)
    assert sched.free_slots() == 0
    held = sched.allocator.free_count()
    victim = sched.preempt_youngest()
    # youngest = highest admit_order; its blocks come back exactly
    assert victim.request.rid == "b"
    assert victim.status == "queued"
    assert victim.generated == []
    assert victim.blocks == []
    assert sched.allocator.free_count() == held + 2  # blocks_for(3+1)=2
    # front-requeue: the victim keeps its seniority over later arrivals
    sched.submit(_req("c"), now=1.0)
    assert [st.request.rid for st in sched.queue] == ["b", "c"]
    admitted = sched.admit(now=1.0)
    assert admitted[0].request.rid == "b"
    # no double-allocation across the preempt/re-admit cycle
    for _slot, st in sched.active():
        sched.finish(st, "done", now=2.0)
    while sched.has_work():
        for st in sched.admit(now=3.0):
            pass
        for _slot, st in sched.active():
            sched.finish(st, "done", now=3.0)
    assert sched.allocator.free_count() == free0


def test_drain_resubmit_of_half_decoded_request_reallocates_cleanly():
    """The failover shape: a request with tokens already emitted is
    re-submitted (fresh state, same Request) after its first home
    released everything — allocation must not leak or double-count, and
    FIFO order must be preserved."""
    sched = _sched()
    free0 = sched.allocator.free_count()
    st = sched.submit(_req("a", plen=3, max_new=3), now=0.0)
    sched.admit(now=0.0)
    st.generated = [5, 6]  # half-decoded
    assert sched.grow(st)  # crosses into a second block region
    held = len(st.blocks)
    # replica dies: the engine's scheduler state is torn down wholesale
    sched.finish(st, "error", now=1.0, error="replica lost")
    assert sched.allocator.free_count() == free0
    # router replays the SAME Request on a fresh submit
    st2 = sched.submit(_req("a", plen=3, max_new=3), now=2.0)
    sched.submit(_req("z"), now=2.0)
    assert [s.request.rid for s in sched.queue] == ["a", "z"]
    sched.admit(now=2.0)
    assert st2.status == "running"
    assert st2.generated == []  # replay restarts from the prompt
    # the replay allocates afresh for the prompt only (not the half-
    # decoded footprint the first incarnation had grown to)
    assert len(st2.blocks) == 2
    assert held == 3
    sched.finish(st2, "done", now=3.0)
    for _slot, s in sched.active():
        sched.finish(s, "done", now=3.0)
    while sched.queue:
        for s in sched.admit(now=4.0):
            sched.finish(s, "done", now=4.0)
    assert sched.allocator.free_count() == free0


def test_double_allocation_impossible_under_interleaved_drain():
    """Interleaved admit/preempt/finish churn never hands the same block
    to two owners and never loses one."""
    sched = _sched(num_blocks=8, block_size=2, max_blocks=4, num_slots=2)
    free0 = sched.allocator.free_count()
    for i in range(5):
        sched.submit(_req(f"r{i}", plen=2 + i % 3, max_new=2), now=0.0)
    for round_ in range(12):
        sched.admit(now=float(round_))
        owned = [b for _s, st in sched.active() for b in st.blocks]
        assert len(owned) == len(set(owned))  # no block owned twice
        assert len(owned) + sched.allocator.free_count() == free0
        if round_ % 3 == 2 and sched.active():
            sched.preempt_youngest()
        elif sched.active():
            _slot, st = sched.active()[0]
            sched.finish(st, "done", now=float(round_))
    while sched.has_work():
        for st in sched.admit(now=99.0):
            pass
        for _slot, st in sched.active():
            sched.finish(st, "done", now=99.0)
    assert sched.allocator.free_count() == free0

"""Sharded (TP x DP) decode vs dense single-logical-device decode.

VERDICT r2 #3: generation must compose with the mesh like training does —
batch sharded over data axes, Megatron-TP decode weights and KV caches
sharded over 'tensor' — and stay token-exact against the unsharded path.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import distributed_pytorch_example_tpu as dpx
from distributed_pytorch_example_tpu.parallel.partition import (
    transformer_partitioner,
)
from distributed_pytorch_example_tpu.runtime import MeshSpec, make_mesh
from distributed_pytorch_example_tpu.train.generate import generate

GPT2_KW = dict(vocab_size=96, max_len=64, model_dim=32, num_layers=2,
               num_heads=4, mlp_dim=64)
LLAMA_KW = dict(vocab_size=96, max_len=64, model_dim=32, num_layers=2,
                num_heads=4, num_kv_heads=2, mlp_dim=64)


def _models(family):
    if family == "gpt2":
        from distributed_pytorch_example_tpu.models.gpt2 import GPT2 as M

        kw = GPT2_KW
    else:
        from distributed_pytorch_example_tpu.models.llama import Llama as M

        kw = LLAMA_KW
    return M(**kw), M(**kw, decode=True)


@pytest.mark.parametrize("family", ["gpt2", "llama"])
def test_sharded_greedy_token_exact_vs_dense(devices, family):
    """tensor=2 x data=2 cached greedy decode == dense cached greedy."""
    train_model, decode_model = _models(family)
    prompt = jnp.asarray(
        np.random.default_rng(0).integers(0, 96, (4, 8)), jnp.int32
    )
    params = train_model.init(jax.random.key(0), prompt)["params"]
    dense = generate(
        decode_model, params, prompt, max_new_tokens=12, temperature=0.0
    )

    mesh = make_mesh(MeshSpec(data=2, fsdp=2, tensor=2))
    partitioner = transformer_partitioner(mesh)
    sharded = generate(
        decode_model, params, prompt, max_new_tokens=12, temperature=0.0,
        partitioner=partitioner,
    )
    np.testing.assert_array_equal(np.asarray(sharded), np.asarray(dense))
    # the KV caches must actually live TP-sharded: re-run the cache init
    # under the mesh and check the constraint's effect via the output
    # sharding of the prompt path (batch over data axes)
    assert sharded.shape == dense.shape


def test_sharded_sampling_deterministic_across_layouts(devices):
    """Same rng: sharded sampling reproduces its own draw (and the decode
    runs under fsdp-composed batch axes)."""
    train_model, decode_model = _models("gpt2")
    prompt = jnp.zeros((4, 4), jnp.int32)
    params = train_model.init(jax.random.key(0), prompt)["params"]
    mesh = make_mesh(MeshSpec(data=2, fsdp=2, tensor=2))
    partitioner = transformer_partitioner(mesh)
    a = generate(decode_model, params, prompt, 8, temperature=1.0, top_k=5,
                 rng=jax.random.key(1), partitioner=partitioner)
    b = generate(decode_model, params, prompt, 8, temperature=1.0, top_k=5,
                 rng=jax.random.key(1), partitioner=partitioner)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_indivisible_prompt_batch_rejected(devices):
    train_model, decode_model = _models("gpt2")
    prompt = jnp.zeros((3, 4), jnp.int32)  # 3 % (data 2 * fsdp 2) != 0
    params = train_model.init(jax.random.key(0), prompt)["params"]
    mesh = make_mesh(MeshSpec(data=2, fsdp=2, tensor=2))
    with pytest.raises(ValueError, match="not divisible"):
        generate(decode_model, params, prompt, 4, temperature=0.0,
                 partitioner=transformer_partitioner(mesh))


def test_train_tp_then_decode_sharded(devices):
    """End to end: train under TP/DP, decode the TRAINED sharded params
    without regathering, token-exact vs the dense decode of the same
    params."""
    from distributed_pytorch_example_tpu.models.gpt2 import GPT2
    from distributed_pytorch_example_tpu.train.tasks import CausalLMTask

    mesh = make_mesh(MeshSpec(data=2, fsdp=2, tensor=2))
    partitioner = transformer_partitioner(mesh)
    model = GPT2(**GPT2_KW)
    trainer = dpx.train.Trainer(
        model, CausalLMTask(), optax.adam(5e-3), partitioner=partitioner
    )
    rng = np.random.default_rng(0)
    # learnable pattern: token t+1 = (t + 1) % vocab
    start = rng.integers(0, 96, (16, 1))
    tokens = (start + np.arange(16)[None, :]) % 96
    batch = {
        "tokens": jax.make_array_from_process_local_data(
            partitioner.batch_sharding(), tokens.astype(np.int32)
        )
    }
    with mesh:
        trainer.init(batch["tokens"])
        state = trainer.state
        for _ in range(60):
            state, metrics = trainer.train_step(state, batch)
    assert float(metrics["accuracy"]) > 90.0

    decode_model = GPT2(**GPT2_KW, decode=True)
    prompt = jnp.asarray((np.arange(4)[None, :] + np.array([[0], [7], [20], [33]])) % 96,
                         jnp.int32)
    # trained params are ALREADY mesh-sharded NamedSharding arrays
    sharded = generate(
        decode_model, state.params, prompt, max_new_tokens=8,
        temperature=0.0, partitioner=partitioner,
    )
    dense_params = jax.device_get(state.params)
    dense = generate(
        decode_model, dense_params, prompt, max_new_tokens=8, temperature=0.0
    )
    np.testing.assert_array_equal(np.asarray(sharded), np.asarray(dense))
    # (the pattern itself is covered by the >90% train accuracy above;
    # short out-of-distribution prompts need not continue it exactly —
    # the claim under test is sharded/dense parity of TRAINED params)

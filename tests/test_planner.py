"""graft-plan: the static auto-parallelism planner (analysis/planner.py).

Unit matrix over the three-tier oracle: the legality filter rejects
indivisible topologies, the tier-2 envelope gate prunes would-OOM plans
BEFORE any compile, int8 wire never scores more bytes than fp32 on the
same plan, and the PlanSpec lowering is bit-identical to the legacy
factory overlays for every dryrun mesh shape. The ``--auto-mesh``
subprocess contract tests (train/bench/serve end-to-end) run under
``-m slow``; everything pure-static carries the ``lint`` mark so the
pre-commit fast path (scripts/precommit.sh) covers the planner too.
"""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import optax
import pytest

from distributed_pytorch_example_tpu.analysis import planner
from distributed_pytorch_example_tpu.parallel.plan import PlanSpec
from distributed_pytorch_example_tpu.parallel.wire import WireConfig
from distributed_pytorch_example_tpu.runtime.mesh import MeshSpec, make_mesh

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _lm_info(**kw):
    base = dict(global_batch=16, num_heads=4, num_layers=2,
                pipelineable=False, max_param_elems=1 << 20, kind="lm")
    base.update(kw)
    return planner.ProgramInfo(**base)


# ---------------------------------------------------------------------------
# legality filter (pure static — no backend, no trace)
# ---------------------------------------------------------------------------


@pytest.mark.lint
def test_legality_rejects_indivisible_tensor():
    # 6 heads on a tensor span of 4: Megatron head split impossible
    plan = PlanSpec(mesh=MeshSpec(data=2, tensor=4), family="transformer")
    reason = planner.legality(plan, _lm_info(num_heads=6), 8)
    assert reason is not None and "heads" in reason


@pytest.mark.lint
def test_legality_rejects_batch_and_knob_misuse():
    # batch not divisible by the data span
    plan = PlanSpec(mesh=MeshSpec(data=8), family="data")
    reason = planner.legality(plan, _lm_info(global_batch=12), 8)
    assert reason is not None and "divisible" in reason
    # tensor axis demands the transformer rule family
    plan = PlanSpec(mesh=MeshSpec(data=4, tensor=2), family="data")
    assert "transformer" in planner.legality(plan, _lm_info(), 8)
    # zero1 without a data span is a no-op, not a plan
    plan = PlanSpec(mesh=MeshSpec(tensor=8), family="transformer", zero1=True)
    assert "zero1" in planner.legality(plan, _lm_info(num_heads=8), 8)
    # pipe needs a pipelineable model with balanced stages
    plan = PlanSpec(mesh=MeshSpec(data=4, pipe=2), family="transformer")
    assert "pipeline" in planner.legality(plan, _lm_info(), 8)


@pytest.mark.lint
def test_enumerate_plans_emits_only_legal_plans():
    info = _lm_info(num_heads=6)  # 6 heads: tensor spans 2/3/6 only
    plans = planner.enumerate_plans(8, info)
    assert plans, "search space empty"
    for p in plans:
        assert planner.legality(p, info, 8) is None, p.name()
    # and the tensor-span filter actually bit: no span-4 mesh survived
    assert all(p.mesh.resolve(8).tensor != 4 for p in plans)
    # names are unique (the dedup key)
    names = [p.name() for p in plans]
    assert len(names) == len(set(names))


@pytest.mark.lint
def test_cli_plan_space_knob_discipline():
    # the CLI grid never emits wire without zero1, and manual knobs stay
    # on the pure-DP mesh (the shapes bench's --zero1/--wire flags run)
    plans = planner.cli_plan_space(8, _lm_info())
    assert any(p.zero1 and p.wire is not None for p in plans)
    for p in plans:
        if p.wire is not None:
            assert p.zero1, p.name()
        if p.zero1 or p.wire is not None:
            assert p.family == "data", p.name()
        assert p.mesh.resolve(8).pipe == 1, p.name()


@pytest.mark.lint
def test_plan_json_roundtrip():
    plan = PlanSpec(
        mesh=MeshSpec(data=4, tensor=2), family="transformer",
        zero1=True, wire=WireConfig(compress="int8-block", block_size=128),
    )
    back = PlanSpec.from_json(json.loads(json.dumps(plan.to_json())))
    assert back == plan and back.name() == plan.name()


# ---------------------------------------------------------------------------
# zero1 floor boundary on PARAM paths (regression: the floor was pinned
# only through the opt_state overlay; the step's grad reduce-scatter dims
# come from zero1_dims over the PARAM tree and must agree)
# ---------------------------------------------------------------------------


def test_zero1_floor_boundary_param_paths(devices):
    from distributed_pytorch_example_tpu.parallel.api import data_parallel

    mesh = make_mesh(MeshSpec(data=8), devices=devices)
    n = 128 * 128
    params = {
        "dense": {"kernel": jax.ShapeDtypeStruct((128, 128), jnp.float32)},
        "bias": jax.ShapeDtypeStruct((8,), jnp.float32),
    }
    at_floor = data_parallel(
        mesh, dp_shard_opt_state=True, opt_shard_min_size=n
    )
    dims = at_floor.zero1_dims(params)
    # EXACTLY at the floor: the kernel's gradient reduce-scatters onto a
    # real dim (the `<` in zero1_dim is strict)...
    assert dims["dense"]["kernel"] is not None
    # ...while the tiny bias stays on the all-reduce path
    assert dims["bias"] is None

    one_under = data_parallel(
        mesh, dp_shard_opt_state=True, opt_shard_min_size=n + 1
    )
    dims = one_under.zero1_dims(params)
    # one element under the floor: replicated BY DESIGN, not an off-by-one
    assert dims["dense"]["kernel"] is None


# ---------------------------------------------------------------------------
# tier 2: the envelope gate prunes would-OOM plans before any compile
# ---------------------------------------------------------------------------


def _toy_lm(model_dim=64, vocab=128):
    from distributed_pytorch_example_tpu.models.gpt2 import GPT2
    from distributed_pytorch_example_tpu.train.tasks import CausalLMTask

    model = GPT2(
        vocab_size=vocab, max_len=64, model_dim=model_dim, num_layers=2,
        num_heads=4, mlp_dim=2 * model_dim, logits_mode="hidden",
    )
    return model, CausalLMTask(), optax.adam(1e-3)


def _toy_batch(global_batch=16, seq=32):
    tokens = jax.ShapeDtypeStruct((global_batch, seq), jnp.int32)
    return tokens, {"tokens": tokens}


def test_hbm_gate_prunes_infeasible_plans_precompile(devices):
    model, task, optimizer = _toy_lm(model_dim=128, vocab=256)
    tokens, batch = _toy_batch()
    info = planner.ProgramInfo(
        global_batch=16, num_heads=4, num_layers=2, kind="lm",
    )
    plans = planner.cli_plan_space(8, info)
    scores = planner.rank_train_plans(
        model, task, optimizer, tokens, batch, plans,
        devices=devices, hbm_limit=2 << 20,
    )
    gated = [
        s for s in scores
        if s.predicted_peak_bytes and s.predicted_peak_bytes > (2 << 20)
    ]
    assert gated, "fixture model too small to trip the 2 MiB gate"
    for s in gated:
        # pruned AT tier 2 — the reason is the envelope, never a compile
        assert not s.feasible and s.tier == 2, s.plan.name()
        assert "HBM limit" in s.reason, s.reason
    assert planner.best_plan(scores) is None or all(
        s.predicted_peak_bytes <= (2 << 20)
        for s in scores if s.feasible
    )


def test_wire_int8_never_scores_more_bytes_than_fp32(devices):
    model, task, optimizer = _toy_lm()
    tokens, batch = _toy_batch()
    base = dict(mesh=MeshSpec(data=8), family="data", zero1=True,
                opt_shard_min_size=1)
    fp32 = PlanSpec(**base)
    int8 = PlanSpec(
        **base, wire=WireConfig(compress="int8-block", min_size=1),
    )
    scores = {
        s.plan.name(): s
        for s in planner.rank_train_plans(
            model, task, optimizer, tokens, batch, [fp32, int8],
            devices=devices,
        )
    }
    s_fp32, s_int8 = scores[fp32.name()], scores[int8.name()]
    assert s_fp32.feasible and s_int8.feasible
    # the compressed payload is counted at its wire width: never MORE
    # traffic than the fp32 schedule of the identical plan. (cost_ms can
    # legitimately go the other way at toy scale: the int8 schedule emits
    # extra per-block scale collectives, and their fixed link latency
    # outweighs the byte savings on KB-sized grads — the BYTES invariant
    # is what pins the quantizer accounting.)
    assert s_int8.comm_bytes <= s_fp32.comm_bytes


# ---------------------------------------------------------------------------
# PlanSpec <-> legacy factory equivalence: the refactor is sharding-neutral
# for every dryrun mesh shape (the committed budget signatures gate the
# same fact post-compile; this pins it at the spec level, pre-compile)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def toy_state_shapes():
    from distributed_pytorch_example_tpu.train import step as step_mod

    model, task, optimizer = _toy_lm()
    return step_mod.abstract_state(
        model, optimizer, jax.ShapeDtypeStruct((16, 32), jnp.int32)
    )


def _spec_trees_equal(a, b):
    from jax.sharding import PartitionSpec as P

    la = jax.tree_util.tree_leaves(a, is_leaf=lambda s: isinstance(s, P))
    lb = jax.tree_util.tree_leaves(b, is_leaf=lambda s: isinstance(s, P))
    return len(la) == len(lb) and all(x == y for x, y in zip(la, lb))


def test_planspec_matches_legacy_factories_per_dryrun_config(
    devices, toy_state_shapes
):
    sys.path.insert(0, REPO_ROOT)
    import __graft_entry__ as entry

    from distributed_pytorch_example_tpu.parallel.partition import (
        transformer_partitioner,
    )

    checked = 0
    for config in entry.DRYRUN_CONFIGS:
        priority = config
        tags = set()
        while priority and priority[-1] in entry._VARIANT_TAGS:
            tags.add(priority[-1])
            priority = priority[:-1]
        sizes = entry._alloc_axes(8, priority)
        mesh = make_mesh(MeshSpec(**sizes), devices=devices)
        zero1 = "zero1" in tags
        wire = (
            WireConfig(compress="int8-block", min_size=1)
            if "wire-int8" in tags else None
        )
        kw = dict(opt_shard_min_size=1, wire=wire) if zero1 else {}
        legacy = transformer_partitioner(
            mesh, fsdp_rest=True, dp_shard_opt_state=zero1, **kw
        )
        direct = PlanSpec(
            mesh=MeshSpec(**sizes), family="transformer", fsdp_rest=True,
            zero1=zero1, **kw,
        ).lower(mesh=mesh)
        assert _spec_trees_equal(
            legacy.tree_specs(toy_state_shapes),
            direct.tree_specs(toy_state_shapes),
        ), f"{config}: PlanSpec lowering diverged from the legacy factory"
        assert legacy.batch_spec() == direct.batch_spec(), config
        checked += 1
    assert checked == len(entry.DRYRUN_CONFIGS)


def test_data_and_fsdp_factories_are_planspec_lowerings(
    devices, toy_state_shapes
):
    from distributed_pytorch_example_tpu.parallel.api import (
        data_parallel,
        fsdp,
    )

    mesh = make_mesh(MeshSpec(data=4, fsdp=2), devices=devices)
    assert _spec_trees_equal(
        data_parallel(mesh).tree_specs(toy_state_shapes),
        PlanSpec(family="data").lower(mesh=mesh).tree_specs(toy_state_shapes),
    )
    assert _spec_trees_equal(
        fsdp(mesh).tree_specs(toy_state_shapes),
        PlanSpec(family="fsdp").lower(mesh=mesh).tree_specs(toy_state_shapes),
    )


# ---------------------------------------------------------------------------
# staleness advisory for the committed plans.json (bench_gate consumes it)
# ---------------------------------------------------------------------------


@pytest.mark.lint
def test_plans_staleness_missing_and_current(tmp_path):
    missing = planner.plans_staleness(
        plans_path=str(tmp_path / "nope.json"), budgets_path=None
    )
    assert missing is not None and "plan_search" in missing

    fresh = tmp_path / "plans.json"
    fresh.write_text(json.dumps(
        {"_meta": {"jax": jax.__version__}, "programs": {}}
    ))
    assert planner.plans_staleness(
        plans_path=str(fresh), budgets_path=None
    ) is None

    skewed = tmp_path / "skewed.json"
    skewed.write_text(json.dumps(
        {"_meta": {"jax": "0.0.1"}, "programs": {}}
    ))
    note = planner.plans_staleness(plans_path=str(skewed), budgets_path=None)
    assert note is not None and "jax" in note


@pytest.mark.lint
def test_committed_plans_json_is_loadable_and_ranked():
    doc = planner.load_plans()
    assert doc is not None, "analysis/plans.json missing or unreadable"
    programs = doc.get("programs", {})
    # every BASELINE train program plus both serve programs are committed
    for prog in (
        "train/resnet18", "train/resnet50", "train/vit-b16",
        "train/bert-base", "train/gpt2", "serve/prefill", "serve/decode",
    ):
        entry = programs.get(prog)
        assert entry and entry.get("top"), prog
        costs = [t["cost_ms"] for t in entry["top"]]
        assert costs == sorted(costs), f"{prog}: top plans not ranked"
        assert all(t["feasible"] for t in entry["top"]), prog


# ---------------------------------------------------------------------------
# --auto-mesh subprocess contract (end-to-end CLIs; slow set)
# ---------------------------------------------------------------------------


def _cli_env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()
    return env


def _one_json_line(stdout):
    lines = [l for l in stdout.strip().splitlines() if l.strip()]
    assert len(lines) == 1, f"expected ONE JSON line on stdout, got {lines!r}"
    return json.loads(lines[0])


def test_train_auto_mesh_rejects_conflicting_flags():
    # fast path: the conflict dies in argparse before any backend work
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "train.py"),
         "--auto-mesh", "--mesh-tensor", "2"],
        capture_output=True, text=True, env=_cli_env(), timeout=120,
    )
    assert proc.returncode != 0
    assert "--auto-mesh" in proc.stderr


@pytest.mark.slow
def test_train_auto_mesh_end_to_end(tmp_path):
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "train.py"),
         "--auto-mesh", "--model", "mlp", "--epochs", "1",
         "--num-samples", "64", "--batch-size", "2", "--log-every", "1",
         "--checkpoint-dir", str(tmp_path / "ckpt")],
        capture_output=True, text=True, env=_cli_env(), timeout=600,
        cwd=REPO_ROOT,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "auto-mesh" in proc.stderr and "dp:" in proc.stderr


@pytest.mark.slow
def test_bench_auto_mesh_one_json_line():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "bench.py"),
         "--auto-mesh", "--model", "resnet18", "--image-size", "32",
         "--batch-per-chip", "2", "--warmup", "1", "--steps", "2"],
        capture_output=True, text=True, env=_cli_env(), timeout=600,
        cwd=REPO_ROOT,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    doc = _one_json_line(proc.stdout)
    assert doc["config"]["auto_mesh"], "picked plan missing from config"


@pytest.mark.slow
def test_serve_auto_mesh_one_json_line():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "serve.py"),
         "--auto-mesh", "--requests", "4", "--slots", "2",
         "--max-len", "32", "--max-blocks", "4",
         "--prompt-len", "4:8", "--max-new", "4:8"],
        capture_output=True, text=True, env=_cli_env(), timeout=600,
        cwd=REPO_ROOT,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    doc = _one_json_line(proc.stdout)
    assert doc["config"]["auto_mesh"], "picked plan missing from config"

"""graft-scope telemetry: sentinels, cost registry, step clock, traces.

Tier-1 coverage of the four pillars (telemetry/__init__.py) plus the
acceptance gates: per-step metrics records + a valid Chrome trace-event
file from one instrumented fit, the nonfinite sentinel firing on an
injected NaN batch, instrumentation overhead <= 2% over the SAME compiled
executable, and the profiler auto-arm trigger path.
"""

import json
import threading

import jax
import numpy as np
import optax
import pytest

import distributed_pytorch_example_tpu as dpx
from distributed_pytorch_example_tpu.telemetry import (
    CostRegistry,
    SENTINEL_KEYS,
    StepClock,
    Telemetry,
    TelemetryConfig,
    TraceWriter,
    compiled_cost_record,
    exchange_step_times,
    peak_bf16_flops,
)


def tiny_trainer(tmp_path, **kw):
    mesh = dpx.runtime.make_mesh()
    return dpx.train.Trainer(
        dpx.models.SimpleNet(hidden_size=32),
        dpx.train.ClassificationTask(),
        optax.adam(1e-3),
        partitioner=dpx.parallel.data_parallel(mesh),
        checkpoint_dir=str(tmp_path / "ckpt"),
        **kw,
    ), mesh


def tiny_loader(mesh, n=64):
    ds = dpx.data.SyntheticClassificationDataset(num_samples=n, input_size=784)
    return dpx.data.DeviceLoader(ds, 16, mesh=mesh, seed=0)


def _sharded_batch(trainer, batch_np):
    sharding = trainer.partitioner.batch_sharding()
    return {
        k: jax.make_array_from_process_local_data(sharding, v)
        for k, v in batch_np.items()
    }


# ---------------------------------------------------------------------------
# end-to-end: one instrumented fit produces records, trace, and summary
# ---------------------------------------------------------------------------


def test_instrumented_fit_records_and_trace(devices, tmp_path):
    trainer, mesh = tiny_trainer(
        tmp_path, telemetry=TelemetryConfig(every=1, sample_every=2)
    )
    history = trainer.fit(tiny_loader(mesh), tiny_loader(mesh, 32), epochs=2)
    assert len(history) == 2

    records = [
        json.loads(l)
        for l in (tmp_path / "ckpt" / "metrics.jsonl").read_text().splitlines()
    ]
    # 2 epochs x 4 batches -> 8 per-step records alongside the epoch records
    steps = [r for r in records if "step" in r and "event" not in r]
    assert [r["step"] for r in steps] == list(range(1, 9))
    for key in ("loss",) + tuple(SENTINEL_KEYS):
        assert key in steps[0], key
    assert steps[0]["nonfinite_grads"] == 0
    assert steps[0]["grad_norm"] > 0
    # compile-time cost registry rode along into the records
    assert steps[0]["flops_per_step_per_device"] > 0
    assert steps[0]["hbm_peak_bytes"] is None or steps[0]["hbm_peak_bytes"] > 0
    # the clock's first true sample lands at step 3 (anchor at 1, window 2)
    assert any(r["step_time_ms"] is not None for r in steps)
    # world size 1: NO straggler fields, by contract
    assert not any("step_time_ms_per_host" in r for r in records)
    compiles = {r["tag"] for r in records if r.get("event") == "compile"}
    assert compiles == {"train_step", "eval_step"}
    epochs = [r for r in records if "epoch" in r]
    assert len(epochs) == 2  # historical epoch records still written

    # Chrome trace-event stream: valid JSON, every span kind present
    trace = json.loads((tmp_path / "ckpt" / "trace_events.json").read_text())
    names = {e["name"] for e in trace}
    assert {"data_load", "h2d", "step", "eval", "checkpoint"} <= names
    for e in trace:
        if e["ph"] == "X":
            assert e["dur"] >= 1 and e["ts"] >= 0
            assert "pid" in e and "tid" in e

    summary = trainer.telemetry_summary
    assert summary["last_record"]["step"] == 8
    assert summary["straggler"] == {}
    assert summary["compiles"]["train_step"]["flops_per_step_per_device"] > 0
    assert trainer.scope is None  # scope torn down with the fit


def test_telemetry_off_means_no_scope(devices, tmp_path):
    trainer, mesh = tiny_trainer(tmp_path, telemetry=False)
    trainer.fit(tiny_loader(mesh), epochs=1)
    assert trainer.telemetry_summary == {}
    assert not (tmp_path / "ckpt" / "trace_events.json").exists()


# ---------------------------------------------------------------------------
# sentinels: the nonfinite counter fires on a poisoned batch
# ---------------------------------------------------------------------------


def test_nonfinite_sentinel_fires_on_nan_batch(devices, tmp_path):
    trainer, mesh = tiny_trainer(tmp_path)
    rng = np.random.default_rng(0)
    x = rng.standard_normal((16, 784)).astype(np.float32)
    clean = {"x": x.copy(), "y": rng.integers(0, 10, (16,)).astype(np.int32)}
    x[0, 0] = np.nan  # one poisoned sample NaN-s the loss, hence every grad
    poisoned = {"x": x, "y": clean["y"].copy()}
    # clean batch first (the step donates its input state): zero nonfinite
    with mesh:
        clean = _sharded_batch(trainer, clean)
        trainer.init(clean["x"])
        state, metrics = trainer.train_step(trainer.state, clean)
        assert float(metrics["nonfinite_grads"]) == 0
        assert float(metrics["grad_norm"]) > 0
        assert float(metrics["param_norm"]) > 0
        # then the poisoned batch trips the sentinel
        poisoned = _sharded_batch(trainer, poisoned)
        _, metrics = trainer.train_step(state, poisoned)
        assert float(metrics["nonfinite_grads"]) > 0


# ---------------------------------------------------------------------------
# overhead: instrumented loop within 2% of the bare loop (same executable)
# ---------------------------------------------------------------------------


def test_overhead_within_two_percent(devices, tmp_path):
    import gc
    import time

    mesh = dpx.runtime.make_mesh()
    trainer = dpx.train.Trainer(
        dpx.models.SimpleNet(hidden_size=512),
        dpx.train.ClassificationTask(),
        optax.adam(1e-3),
        partitioner=dpx.parallel.data_parallel(mesh),
        telemetry=False,
    )
    rng = np.random.default_rng(0)
    batch = {
        "x": rng.standard_normal((64, 784)).astype(np.float32),
        "y": rng.integers(0, 10, (64,)).astype(np.int32),
    }
    n_steps, rounds = 15, 10
    with mesh:
        batch = _sharded_batch(trainer, batch)
        trainer.init(batch["x"])
        step = trainer.train_step.lower(trainer.state, batch).compile()
        # the step donates its input state, so a single state threads
        # through every loop via this holder (no reuse-after-donation)
        holder = {"state": trainer.state}
        metrics = None
        for _ in range(5):  # warmup the executable + allocator
            holder["state"], metrics = step(holder["state"], batch)
        float(metrics["loss"])

        def bare():
            # the UNinstrumented Trainer loop: the log boundary already
            # fetches that step's loss every log_every steps
            # (train/loop.py); graft-scope's budget is measured on top of
            # that pre-existing cadence, not an idealized fence-free loop
            metrics = None
            t0 = time.perf_counter()
            for s in range(1, n_steps + 1):
                holder["state"], metrics = step(holder["state"], batch)
                if s % 10 == 0:
                    float(metrics["loss"])
            float(metrics["loss"])
            return time.perf_counter() - t0

        def instrumented(i):
            scope = Telemetry(
                TelemetryConfig(
                    every=0,
                    sample_every=8,
                    trace_file=str(tmp_path / f"trace_{i}.json"),
                ),
                fallback_every=10,
            )
            scope.record_compile("train_step", step)  # outside the timer
            metrics = None
            t0 = time.perf_counter()
            for s in range(1, n_steps + 1):
                with scope.span("step"):
                    holder["state"], metrics = step(holder["state"], batch)
                scope.on_step(
                    s, metrics, fence=lambda m=metrics: float(m["loss"])
                )
            float(metrics["loss"])
            dt = time.perf_counter() - t0
            scope.close()
            return dt

        # interleaved rounds so machine drift hits both arms equally;
        # min-of-N is the standard noise floor for microbenchmarks (per
        # round this box jitters ~10%, far above the budget under test)
        offs, ons = [], []
        gc.disable()
        try:
            for i in range(rounds):
                offs.append(bare())
                ons.append(instrumented(i))
        finally:
            gc.enable()
        t_off, t_on = min(offs), min(ons)

    # <= 2% (+ a 15 ms absolute floor: at fake-mesh step times the 2%
    # budget is tens of milliseconds, near host timer jitter)
    assert t_on <= t_off * 1.02 + 0.015, (t_on, t_off, offs, ons)


# ---------------------------------------------------------------------------
# profiler auto-arm (graft-scope trigger -> StepProfiler.arm)
# ---------------------------------------------------------------------------


class _FakeProfiler:
    def __init__(self):
        self.calls = []

    def arm(self, start, stop, reason=""):
        self.calls.append((start, stop, reason))
        return True


def test_auto_arm_on_nonfinite_grads():
    prof = _FakeProfiler()
    scope = Telemetry(TelemetryConfig(every=1), profiler=prof)
    metrics = {
        "loss": 1.0, "grad_norm": 3.0, "param_norm": 1.0,
        "nonfinite_grads": 7.0,
    }
    scope.on_step(1, metrics, fence=lambda: None)
    assert prof.calls == [(3, 5, "nonfinite grads (7 elements)")]
    scope.close()


def test_auto_arm_on_skew(monkeypatch):
    from distributed_pytorch_example_tpu.telemetry import scope as scope_mod

    straggler = {
        "step_time_ms_per_host": [1.0, 2.6],
        "step_time_skew": 2.6,
        "slow_hosts": [1],
    }
    monkeypatch.setattr(
        scope_mod, "exchange_step_times", lambda st, thr: dict(straggler)
    )
    prof = _FakeProfiler()
    scope = Telemetry(TelemetryConfig(every=2), profiler=prof)
    metrics = {
        "loss": 1.0, "grad_norm": 3.0, "param_norm": 1.0,
        "nonfinite_grads": 0.0,
    }
    scope.on_step(1, metrics, fence=lambda: None)  # not a boundary
    assert prof.calls == []
    scope.on_step(2, metrics, fence=lambda: None)
    assert prof.calls == [(4, 6, "cross-host step-time skew 2.60x")]
    assert scope.last_straggler == straggler
    summary = scope.close()
    assert summary["straggler"] == straggler


def test_auto_arm_disabled():
    prof = _FakeProfiler()
    scope = Telemetry(
        TelemetryConfig(every=1, auto_arm_profiler=False), profiler=prof
    )
    scope.on_step(
        1,
        {"loss": 1.0, "grad_norm": 1.0, "param_norm": 1.0,
         "nonfinite_grads": 2.0},
        fence=lambda: None,
    )
    assert prof.calls == []
    scope.close()


# ---------------------------------------------------------------------------
# unit: cost registry / step clock / trace writer / straggler exchange
# ---------------------------------------------------------------------------


class _FakeMemStats:
    argument_size_in_bytes = 100
    output_size_in_bytes = 50
    temp_size_in_bytes = 30
    alias_size_in_bytes = 20
    generated_code_size_in_bytes = 5


class _FakeCompiled:
    def cost_analysis(self):
        return {"flops": 2.0e12, "bytes accessed": 1.0e9}

    def memory_analysis(self):
        return _FakeMemStats()

    def as_text(self):
        return "ENTRY main { ROOT t = f32[2] add(a, b) }"


class _FakeDevice:
    device_kind = "TPU v4"


def test_cost_record_and_analytic_mfu():
    rec = compiled_cost_record(_FakeCompiled(), _FakeDevice())
    assert rec["flops_per_step_per_device"] == 2.0e12
    assert rec["bytes_accessed"] == 1.0e9
    assert rec["hbm_peak_bytes"] == 100 + 50 + 30 - 20
    assert rec["code_bytes"] == 5
    assert rec["collectives"] == {}
    assert rec["peak_bf16_flops"] == 275e12

    reg = CostRegistry()
    reg.record("train_step", _FakeCompiled(), _FakeDevice())
    # 2e12 flops / 10 ms / 275e12 peak
    assert reg.mfu_analytic("train_step", 10.0) == pytest.approx(
        2.0e12 / 0.01 / 275e12
    )
    assert reg.mfu_analytic("train_step", None) is None
    assert reg.mfu_analytic("missing", 10.0) is None


def test_cost_record_degrades_without_analysis():
    class Opaque:
        pass  # no cost_analysis / memory_analysis / as_text

    rec = compiled_cost_record(Opaque())
    assert rec["flops_per_step_per_device"] is None
    assert rec["hbm_peak_bytes"] is None
    assert rec["collectives"] is None


def test_peak_flops_table():
    class D:
        def __init__(self, kind):
            self.device_kind = kind

    assert peak_bf16_flops(D("TPU v4")) == 275e12
    assert peak_bf16_flops(D("TPU v5e")) == 197e12
    assert peak_bf16_flops(D("TPU v5p")) == 459e12
    assert peak_bf16_flops(D("cpu")) is None


def test_step_clock_anchors_then_samples(monkeypatch):
    from distributed_pytorch_example_tpu.telemetry import steptime

    now = {"t": 100.0}
    monkeypatch.setattr(steptime.time, "perf_counter", lambda: now["t"])
    fences = []
    clock = StepClock(sample_every=4)
    clock.tick(1, lambda: fences.append(1))  # anchor only: no sample
    assert clock.step_time_ms is None and fences == [1]
    for s in (2, 3, 4):  # inside the window: NO fence, fully async
        now["t"] += 0.010
        clock.tick(s, lambda s=s: fences.append(s))
    assert fences == [1] and clock.step_time_ms is None
    now["t"] += 0.010
    clock.tick(5, lambda: fences.append(5))  # window full: one true fence
    assert fences == [1, 5]
    assert clock.step_time_ms == pytest.approx(10.0)  # 40 ms over 4 steps


def test_step_clock_rejects_bad_window():
    with pytest.raises(ValueError):
        StepClock(sample_every=0)


def test_step_clock_first_tick_excludes_warmup(monkeypatch):
    """Compile/warmup wall time before the first tick must never leak
    into the first sample: the first tick anchors only, so a 30s compile
    ahead of it is invisible to step_time_ms."""
    from distributed_pytorch_example_tpu.telemetry import steptime

    now = {"t": 0.0}
    monkeypatch.setattr(steptime.time, "perf_counter", lambda: now["t"])
    clock = StepClock(sample_every=2)
    now["t"] = 30.0  # a long compile happened before the first tick
    clock.tick(0, lambda: None)
    assert clock.step_time_ms is None  # anchored, not sampled
    now["t"] = 30.020
    clock.tick(1, lambda: None)
    now["t"] = 30.040
    clock.tick(2, lambda: None)
    # 40 ms over 2 steps: the 30 s of warmup is fully excluded
    assert clock.step_time_ms == pytest.approx(20.0)
    # the sample re-anchors the window: the next sample is independent
    now["t"] = 30.050
    clock.tick(3, lambda: None)
    now["t"] = 30.060
    clock.tick(4, lambda: None)
    assert clock.step_time_ms == pytest.approx(10.0)


def test_exchange_step_times_world_size_one(monkeypatch):
    # single-process contract: no skew fields, and no collective issued
    from jax.experimental import multihost_utils

    def _boom(*a, **kw):  # pragma: no cover - the point is NOT reached
        raise AssertionError("collective issued at world size 1")

    monkeypatch.setattr(multihost_utils, "process_allgather", _boom)
    assert exchange_step_times(12.5) == {}
    assert exchange_step_times(None) == {}


def test_exchange_step_times_multihost_skew(monkeypatch):
    """Simulated 4-host gather: skew fields + slow-host list math."""
    import jax
    from jax.experimental import multihost_utils

    monkeypatch.setattr(jax, "process_count", lambda: 4)
    monkeypatch.setattr(
        multihost_utils, "process_allgather",
        lambda x: np.asarray([[10.0], [10.0], [12.0], [30.0]], np.float32),
    )
    out = exchange_step_times(10.0, skew_threshold=1.5)
    assert out["step_time_ms_per_host"] == [10.0, 10.0, 12.0, 30.0]
    assert out["step_time_ms_median_host"] == pytest.approx(11.0)
    assert out["step_time_ms_max_host"] == pytest.approx(30.0)
    assert out["step_time_skew"] == pytest.approx(30.0 / 11.0, abs=1e-4)
    assert out["slow_hosts"] == [3]  # 30 > 1.5 * 11; 12 is not


def test_step_profiler_arm_refusal_matrix(tmp_path):
    """arm() is first-trigger-wins: refuses while a window is pending,
    refuses windows that are not strictly ahead, no-ops without logdir."""
    from distributed_pytorch_example_tpu.runtime.profiler import (
        StepProfiler,
    )

    assert StepProfiler(None).arm(10, 12) is False  # disabled: no-op
    p = StepProfiler(str(tmp_path), window=(2, 4))
    p.step(20)  # drives past the window without opening it
    assert p.arm(21, 21) is False  # empty window
    assert p.arm(19, 25) is False  # start not ahead of last step
    assert p.arm(30, 32) is True
    assert (p.start_step, p.stop_step) == (30, 32)
    assert p.arm(40, 42) is False  # pending window: first trigger wins
    assert (p.start_step, p.stop_step) == (30, 32)


def test_trace_writer_valid_json_threads_and_close(tmp_path):
    path = tmp_path / "trace.json"
    tw = TraceWriter(str(path), process_index=0)
    with tw.span("step"):
        pass
    t = threading.Thread(target=lambda: tw.add_complete("h2d", 10, 5))
    t.start()
    t.join()
    tw.close()
    events = json.loads(path.read_text())  # the array must parse as-is
    names = {e["name"] for e in events}
    assert {"process_name", "step", "h2d"} <= names
    # the producer thread gets its own track
    tids = {e["tid"] for e in events if e["ph"] == "X"}
    assert len(tids) == 2
    tw.add_complete("late", 1, 1)  # post-close span drops silently
    tw.close()  # idempotent


def test_trace_writer_disabled_is_noop():
    tw = TraceWriter(None)
    with tw.span("x"):
        pass
    tw.close()

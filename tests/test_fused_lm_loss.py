"""Fused (logits_mode='hidden' + chunked CE) vs dense task loss equivalence.

The train-step-level pin for the fused LM loss path: building the SAME model
with logits_mode='hidden' must give the same loss, accuracy, and parameter
gradients as the dense logits path, for both CausalLMTask (GPT-2/LLaMA) and
MLMTask (BERT). Loss semantics match the reference's CrossEntropyLoss
(reference train.py:250).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import distributed_pytorch_example_tpu as dpx
from distributed_pytorch_example_tpu.train.tasks import CausalLMTask, MLMTask

TINY = dict(
    vocab_size=211, max_len=32, model_dim=32, num_layers=2, num_heads=4,
    mlp_dim=64, dtype=jnp.float32, use_flash=False,
)


def _loss_and_grads(model, task, tokens, rng):
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]

    def loss_fn(p):
        loss, metrics, _ = task.compute_loss(
            model, p, {}, {"tokens": tokens}, rng, train=True
        )
        return loss, metrics

    (loss, metrics), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
    return loss, metrics, grads


@pytest.mark.parametrize("name", ["gpt2", "llama"])
def test_causal_fused_matches_dense(name):
    kwargs = dict(TINY)
    if name == "llama":
        kwargs["num_kv_heads"] = 2
        kwargs.pop("mlp_dim")
        kwargs["mlp_dim"] = 48
    dense_model = dpx.models.get_model(name, **kwargs)
    fused_model = dpx.models.get_model(name, logits_mode="hidden", **kwargs)
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (2, 16), 0, TINY["vocab_size"]
    )
    rng = jax.random.PRNGKey(2)
    task = CausalLMTask()
    loss_d, met_d, g_d = _loss_and_grads(dense_model, task, tokens, rng)
    loss_f, met_f, g_f = _loss_and_grads(fused_model, task, tokens, rng)
    np.testing.assert_allclose(loss_f, loss_d, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(
        met_f["accuracy"], met_d["accuracy"], atol=1e-5
    )
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=2e-4, atol=1e-6),
        g_f, g_d,
    )


def test_mlm_fused_matches_dense():
    dense_model = dpx.models.get_model("bert", **TINY)
    fused_model = dpx.models.get_model("bert", logits_mode="hidden", **TINY)
    tokens = jax.random.randint(
        jax.random.PRNGKey(3), (2, 16), 0, TINY["vocab_size"]
    )
    rng = jax.random.PRNGKey(4)
    task = MLMTask(vocab_size=TINY["vocab_size"], mask_token_id=3)
    loss_d, met_d, g_d = _loss_and_grads(dense_model, task, tokens, rng)
    loss_f, met_f, g_f = _loss_and_grads(fused_model, task, tokens, rng)
    np.testing.assert_allclose(loss_f, loss_d, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(
        met_f["accuracy"], met_d["accuracy"], atol=1e-5
    )
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=2e-4, atol=1e-6),
        g_f, g_d,
    )


def test_fused_trains_under_dp_mesh(mesh_1d):
    """One jitted DP train step end-to-end on the fused path."""
    import optax

    model = dpx.models.get_model("gpt2", logits_mode="hidden", **TINY)
    task = CausalLMTask()
    trainer = dpx.train.Trainer(
        model, task, optax.adam(1e-3),
        partitioner=dpx.parallel.data_parallel(mesh_1d),
    )
    tokens = np.random.default_rng(0).integers(
        0, TINY["vocab_size"], (8, 16)
    ).astype(np.int32)
    sharding = trainer.partitioner.batch_sharding()
    batch = {
        "tokens": jax.make_array_from_process_local_data(sharding, tokens)
    }
    with mesh_1d:
        trainer.init(batch["tokens"])
        state, metrics = trainer.train_step(trainer.state, batch)
        loss0 = float(metrics["loss"])
        for _ in range(3):
            state, metrics = trainer.train_step(state, batch)
    assert float(metrics["loss"]) < loss0


def test_decode_rejects_hidden_mode():
    with pytest.raises(ValueError, match="decode mode requires"):
        m = dpx.models.get_model(
            "gpt2", logits_mode="hidden", decode=True, **TINY
        )
        m.init(jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32))


def test_fused_loss_under_tensor_parallel_vocab_sharding(mesh_2x2x2):
    """Fused chunked-CE under TP where the vocab-parallel rule shards the
    tied embedding on 'tensor' (vocab 212 % 2 == 0): loss and grads must
    match the same model on a replicated (DP) layout."""
    import optax

    from distributed_pytorch_example_tpu.parallel.partition import (
        transformer_partitioner,
    )

    kwargs = dict(TINY)
    kwargs["vocab_size"] = 212  # divisible by tensor=2: vocab-parallel path
    model = dpx.models.get_model("gpt2", logits_mode="hidden", **kwargs)
    task = CausalLMTask()
    tokens = np.random.default_rng(0).integers(0, 212, (8, 16)).astype(np.int32)

    losses = {}
    for name, part in (
        ("tp", transformer_partitioner(mesh_2x2x2)),
        ("dp", dpx.parallel.data_parallel(mesh_2x2x2)),
    ):
        trainer = dpx.train.Trainer(
            model, task, optax.adam(1e-3), partitioner=part
        )
        batch = {
            "tokens": jax.make_array_from_process_local_data(
                part.batch_sharding(), tokens
            )
        }
        with mesh_2x2x2:
            trainer.init(batch["tokens"])
            if name == "tp":  # the embedding must actually be vocab-sharded
                emb = trainer.state.params["wte"]["embedding"]
                assert emb.sharding.spec[0] == "tensor"
            _, metrics = trainer.train_step(trainer.state, batch)
            losses[name] = float(metrics["loss"])
    np.testing.assert_allclose(losses["tp"], losses["dp"], rtol=1e-4)

"""Streaming image shards: correctness, LRU memmap pool, bounded memory."""

import os
import resource

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_pytorch_example_tpu.data.streaming import (
    StreamingImageShards,
    write_image_shards,
)


def _write_dataset(root, n=256, hw=8, shard_size=64, seed=0):
    rng = np.random.default_rng(seed)
    images = rng.integers(0, 256, (n, hw, hw, 3)).astype(np.uint8)
    labels = rng.integers(0, 10, (n,)).astype(np.int64)
    # feed in awkward batch sizes to exercise re-chunking
    batches = [
        (images[i : i + 37], labels[i : i + 37]) for i in range(0, n, 37)
    ]
    nshards = write_image_shards(root, batches, shard_size=shard_size)
    return images, labels, nshards


def test_writer_rechunks_and_reader_roundtrips(tmp_path):
    root = str(tmp_path / "shards")
    images, labels, nshards = _write_dataset(root)
    assert nshards == 4  # 256 / 64
    ds = StreamingImageShards(root, max_open_shards=2)
    assert len(ds) == 256
    assert ds.num_classes == 10
    idx = np.asarray([0, 5, 63, 64, 200, 255, 17])  # spans all shards
    batch = ds.get_batch(idx)
    np.testing.assert_allclose(
        batch["x"], images[idx].astype(np.float32) / 255.0, atol=1e-6
    )
    np.testing.assert_array_equal(batch["y"], labels[idx].astype(np.int32))
    assert batch["y"].dtype == np.int32


def test_single_item_and_normalize(tmp_path):
    root = str(tmp_path / "s")
    images, labels, _ = _write_dataset(root, n=64, shard_size=32)
    mean = np.float32([0.5, 0.5, 0.5])
    std = np.float32([0.25, 0.25, 0.25])
    ds = StreamingImageShards(root, normalize=(mean, std))
    item = ds[10]
    expected = (images[10].astype(np.float32) / 255.0 - mean) / std
    np.testing.assert_allclose(item["x"], expected, atol=1e-6)
    assert item["y"] == labels[10]


def test_transform_hook_applies(tmp_path):
    root = str(tmp_path / "t")
    _write_dataset(root, n=64, shard_size=32)

    def flip_all(batch):
        return {**batch, "x": batch["x"][:, :, ::-1]}

    plain = StreamingImageShards(root)
    flipped = StreamingImageShards(root, transform=flip_all)
    idx = np.arange(8)
    np.testing.assert_array_equal(
        flipped.get_batch(idx)["x"], plain.get_batch(idx)["x"][:, :, ::-1]
    )


def test_lru_pool_caps_open_maps(tmp_path):
    root = str(tmp_path / "lru")
    _write_dataset(root, n=256, shard_size=32)  # 8 shards
    ds = StreamingImageShards(root, max_open_shards=3)
    ds.get_batch(np.arange(0, 256, 16))  # touches every shard
    assert len(ds._open) <= 3


def test_through_device_loader_matches_in_ram(tmp_path, devices):
    """Same sampler contract through the pipeline as an in-RAM dataset."""
    from distributed_pytorch_example_tpu.data.loader import DeviceLoader
    from distributed_pytorch_example_tpu.data.synthetic import _ArrayDataset
    from distributed_pytorch_example_tpu.runtime import make_mesh

    root = str(tmp_path / "dl")
    images, labels, _ = _write_dataset(root, n=128, shard_size=32)
    streaming = StreamingImageShards(root)
    in_ram = _ArrayDataset(
        {
            "x": images.astype(np.float32) / 255.0,
            "y": labels.astype(np.int32),
        }
    )
    mesh = make_mesh()
    a = DeviceLoader(streaming, 16, mesh=mesh, seed=3, num_shards=1, shard_id=0)
    b = DeviceLoader(in_ram, 16, mesh=mesh, seed=3, num_shards=1, shard_id=0)
    a.set_epoch(1)
    b.set_epoch(1)
    for ba, bb in zip(a, b):
        np.testing.assert_allclose(
            np.asarray(ba["x"]), np.asarray(bb["x"]), atol=1e-6
        )
        np.testing.assert_array_equal(np.asarray(ba["y"]), np.asarray(bb["y"]))


@pytest.mark.slow
def test_rss_bounded_by_lru_window_not_dataset_size(tmp_path):
    """Full random-order epoch over ~300MB of shards with a small LRU
    window must not grow RSS by anywhere near the dataset size."""
    root = str(tmp_path / "big")
    os.makedirs(root, exist_ok=True)
    rng = np.random.default_rng(0)
    hw, per_shard, nshards = 64, 256, 100  # 256*64*64*3 = ~3MB per shard
    for s in range(nshards):
        np.save(
            os.path.join(root, f"images_{s:05d}.npy"),
            rng.integers(0, 256, (per_shard, hw, hw, 3)).astype(np.uint8),
        )
        np.save(
            os.path.join(root, f"labels_{s:05d}.npy"),
            rng.integers(0, 10, (per_shard,)).astype(np.int32),
        )
    total_mb = nshards * per_shard * hw * hw * 3 / 1e6
    assert total_mb > 250

    ds = StreamingImageShards(root, max_open_shards=4)
    rss0 = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss  # KB on linux
    order = np.random.default_rng(1).permutation(len(ds))
    for lo in range(0, len(ds), 128):
        ds.get_batch(order[lo : lo + 128])
    rss1 = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    grown_mb = (rss1 - rss0) / 1024.0
    # LRU window is 4 shards (~12MB) + batch copies; the all-in-RAM loader
    # would need the full ~300MB (float32: 1.2GB). Generous slack for
    # allocator noise:
    assert grown_mb < total_mb / 3, (
        f"RSS grew {grown_mb:.0f}MB over a {total_mb:.0f}MB dataset — "
        "streaming is not streaming"
    )


@pytest.mark.slow
def test_streaming_throughput_floor(tmp_path):
    """Random-order streaming must sustain real bandwidth (memmap reads,
    not per-sample file opens). Floor is intentionally loose (~50 MB/s);
    actual page-cache-warm rates are orders of magnitude higher."""
    import time

    root = str(tmp_path / "tp")
    rng = np.random.default_rng(0)
    images = rng.integers(0, 256, (2048, 32, 32, 3)).astype(np.uint8)
    labels = rng.integers(0, 10, (2048,)).astype(np.int64)
    write_image_shards(root, [(images, labels)], shard_size=256)
    ds = StreamingImageShards(root, max_open_shards=4)
    order = np.random.default_rng(1).permutation(len(ds))
    ds.get_batch(order[:128])  # warm
    t0 = time.perf_counter()
    for lo in range(0, len(ds), 128):
        ds.get_batch(order[lo : lo + 128])
    dt = time.perf_counter() - t0
    mb = len(ds) * 32 * 32 * 3 / 1e6
    assert mb / dt > 50, f"streaming at {mb/dt:.1f} MB/s"


def test_raw_uint8_matches_float_host_scaling(tmp_path, devices):
    """raw_uint8 shards + on-device dequantize == the float32 host-/255
    path: identical batches into the model, identical loss out of the
    train step (the r3 uint8-to-device input contract)."""
    import optax

    import distributed_pytorch_example_tpu as dpx
    from distributed_pytorch_example_tpu.runtime import MeshSpec, make_mesh
    from distributed_pytorch_example_tpu.train.tasks import (
        ClassificationTask,
        dequantize_inputs,
    )

    rng = np.random.default_rng(0)
    imgs = rng.integers(0, 256, (32, 16, 16, 3)).astype(np.uint8)
    labels = rng.integers(0, 5, 32).astype(np.int32)
    root = str(tmp_path / "shards")
    write_image_shards(root, [(imgs, labels)], shard_size=16)

    ds_f32 = StreamingImageShards(root)
    ds_u8 = StreamingImageShards(root, raw_uint8=True)
    idx = np.arange(32)
    bf, bu = ds_f32.get_batch(idx), ds_u8.get_batch(idx)
    assert bu["x"].dtype == np.uint8
    np.testing.assert_allclose(
        np.asarray(dequantize_inputs(jnp.asarray(bu["x"]))), bf["x"],
        rtol=1e-6,
    )

    with pytest.raises(ValueError, match="raw_uint8"):
        StreamingImageShards(
            root, raw_uint8=True,
            normalize=(np.zeros(3, np.float32), np.ones(3, np.float32)),
        )

    # same loss through the jitted step either way (init incl.); the batch
    # stays rank-4 (B, H, W, C) — the uint8-IS-an-image contract is
    # rank-gated, and SimpleNet flattens internally
    mesh = make_mesh(MeshSpec(data=8))
    model = dpx.models.get_model("mlp")
    losses = {}
    for name, ds in (("u8", ds_u8), ("f32", ds_f32)):
        b = ds.get_batch(idx)
        trainer = dpx.train.Trainer(
            model, ClassificationTask(), optax.adam(1e-3),
            partitioner=dpx.parallel.data_parallel(mesh),
        )
        sharding = trainer.partitioner.batch_sharding()
        batch = {
            k: jax.make_array_from_process_local_data(sharding, v)
            for k, v in b.items()
        }
        with mesh:
            trainer.init(batch["x"])
            _, metrics = trainer.train_step(trainer.state, batch)
            losses[name] = float(metrics["loss"])
    np.testing.assert_allclose(losses["u8"], losses["f32"], rtol=1e-5)


def test_dequantize_rejects_non_image_uint8(devices):
    """The uint8-IS-an-image contract fails LOUDLY: a rank-2 uint8 input
    (e.g. byte-valued token ids) must raise, not be silently rescaled."""
    from distributed_pytorch_example_tpu.train.tasks import dequantize_inputs

    with pytest.raises(TypeError, match="uint8"):
        dequantize_inputs(jnp.zeros((4, 16), jnp.uint8))
    # rank-3+ uint8 is an image batch: rescaled
    out = dequantize_inputs(jnp.full((2, 4, 4, 3), 255, jnp.uint8))
    assert out.dtype == jnp.float32 and float(out.max()) == 1.0
    # non-uint8 passes through untouched
    tok = jnp.zeros((4, 16), jnp.int32)
    assert dequantize_inputs(tok) is tok


def test_shard_cache_eliminates_epoch2_input_stalls(tmp_path, devices):
    """Two epochs through the real input plane over slow shard IO: epoch
    1 decodes from disk and stalls the prefetch worker; epoch 2 serves
    every row from the in-memory ShardCache (cache hits skip the chaos
    site with the disk), so input_stall_frac collapses to ~0. The memmap
    pool is pinned far below the shard count so the pool alone cannot
    explain the drop — the cache-off control stays stalled on epoch 2."""
    import time

    from distributed_pytorch_example_tpu.data.loader import DeviceLoader
    from distributed_pytorch_example_tpu.robustness import chaos
    from distributed_pytorch_example_tpu.runtime import make_mesh

    root = str(tmp_path / "stall")
    rng = np.random.default_rng(0)
    write_image_shards(
        root,
        [(rng.integers(0, 256, (64, 8, 8, 3)).astype(np.uint8),
          rng.integers(0, 10, (64,)).astype(np.int64))
         for _ in range(6)],
        shard_size=64, seal=True,
    )
    mesh = make_mesh()

    def stall_fracs(cache_mb):
        ds = StreamingImageShards(
            root, raw_uint8=True, max_open_shards=2, cache_mb=cache_mb
        )
        chaos.install(chaos.ChaosPlan(faults=[chaos.Fault(
            "slow-shard-io", path_substr="images_",
            count=10_000, delay_s=0.05,
        )]))
        try:
            fracs = []
            for _epoch in range(2):
                loader = DeviceLoader(
                    ds, 32, mesh=mesh, shuffle=False, prefetch=2,
                    num_shards=1, shard_id=0,
                )
                for _ in loader:
                    time.sleep(0.01)  # a consumer faster than slow IO
                fracs.append(
                    loader.stalled_batches / max(loader.batches_served, 1)
                )
        finally:
            chaos.uninstall()
        return fracs, ds.cache_stats

    fracs, stats = stall_fracs(cache_mb=64)
    assert fracs[0] > 0.3, fracs  # epoch 1 really stalled on slow disk
    assert fracs[1] <= 0.15, fracs  # epoch 2 served from RAM
    assert stats["entries"] == 6 and stats["hits"] > 0

    control, no_stats = stall_fracs(cache_mb=0)
    assert no_stats is None
    assert control[1] > 0.3, control  # without the cache epoch 2 stalls

"""Fused paged flash-decode kernel vs the XLA gather reference.

The kernel (ops/pallas/paged_attention.py) scalar-prefetches the block
table and reads only live KV blocks from the pool; the reference gathers
the whole table and runs dense attention — the exact pre-kernel decode
path. These tests pin the two together (interpret mode stands in for the
TPU lowering, the flash_attention.py convention), check the dispatcher's
off-TPU fallback is the reference BITWISE, and run the kernel under a
tensor=2 shard_map over kv heads — the sharding the serving engine's
page pool uses.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_pytorch_example_tpu.ops.pallas.paged_attention import (
    paged_attention_reference,
    paged_decode_attention,
    paged_decode_supported,
    paged_flash_decode,
)

BLOCK_SIZE = 4


def make_case(
    batch=3, num_heads=4, kv_heads=4, head_dim=16, num_blocks=16,
    max_blocks=5, seed=0,
):
    """Random pool + a permuted block table with dead tails -> scratch 0.

    Row lengths straddle block boundaries (first/last position of a
    block, single-block rows) so the mask and the live-block sweep are
    both exercised off the easy aligned cases.
    """
    rng = np.random.default_rng(seed)
    q = jnp.asarray(
        rng.standard_normal((batch, num_heads, head_dim)), jnp.float32
    )
    pages_k = jnp.asarray(
        rng.standard_normal((num_blocks, BLOCK_SIZE, kv_heads, head_dim)),
        jnp.float32,
    )
    pages_v = jnp.asarray(
        rng.standard_normal((num_blocks, BLOCK_SIZE, kv_heads, head_dim)),
        jnp.float32,
    )
    # non-identity placement: each row's live blocks are scattered through
    # the pool (block 0 is the scratch block dead entries point at)
    perm = rng.permutation(np.arange(1, num_blocks))
    lens = np.asarray([2, BLOCK_SIZE - 1, 4 * BLOCK_SIZE], np.int32)[:batch]
    table = np.zeros((batch, max_blocks), np.int32)
    k = 0
    for b in range(batch):
        live = int(lens[b]) // BLOCK_SIZE + 1
        for j in range(min(live, max_blocks)):
            table[b, j] = perm[k]
            k += 1
    return q, pages_k, pages_v, jnp.asarray(table), jnp.asarray(lens)


@pytest.mark.parametrize(
    "num_heads,kv_heads", [(4, 4), (4, 2)], ids=["mha", "gqa"]
)
def test_kernel_matches_reference_interpret(num_heads, kv_heads):
    """Online-softmax kernel == dense gather reference at tolerance."""
    q, pk, pv, table, lens = make_case(
        num_heads=num_heads, kv_heads=kv_heads
    )
    ref = paged_attention_reference(
        q[:, None], pk, pv, table, lens[:, None]
    )[:, 0]
    got = paged_flash_decode(q, pk, pv, table, lens, interpret=True)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(ref), atol=2e-5
    )


def test_kernel_ignores_garbage_in_dead_blocks():
    """Dead table entries point at the scratch block; poisoning it (and
    every block past a row's length) must not move the output — the
    live-block skip plus the position mask make dead KV unreachable."""
    q, pk, pv, table, lens = make_case()
    base = paged_flash_decode(q, pk, pv, table, lens, interpret=True)
    poisoned_k = pk.at[0].set(1e4)
    poisoned_v = pv.at[0].set(1e4)
    got = paged_flash_decode(
        q, poisoned_k, poisoned_v, table, lens, interpret=True
    )
    np.testing.assert_array_equal(np.asarray(got), np.asarray(base))


def test_dispatcher_fallback_is_reference_bitwise():
    """Off-TPU with no interpret override the dispatcher must return the
    gather reference EXACTLY — this is the bit-exactness gate that keeps
    every token-equivalence test meaningful on the fake CPU mesh."""
    assert not paged_decode_supported()  # CPU backend under conftest
    assert os.environ.get("DPX_PAGED_KERNEL", "") != "interpret"
    q, pk, pv, table, lens = make_case(seed=1)
    ref = paged_attention_reference(q[:, None], pk, pv, table, lens[:, None])
    got = paged_decode_attention(q[:, None], pk, pv, table, lens[:, None])
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


def test_dispatcher_env_knob_forces_kernel(monkeypatch):
    """DPX_PAGED_KERNEL=interpret drives the fused path off-TPU (the
    SKILL.md drive recipe); output stays at-tolerance vs the fallback."""
    q, pk, pv, table, lens = make_case(seed=2)
    ref = paged_decode_attention(q[:, None], pk, pv, table, lens[:, None])
    monkeypatch.setenv("DPX_PAGED_KERNEL", "interpret")
    got = paged_decode_attention(q[:, None], pk, pv, table, lens[:, None])
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(ref), atol=2e-5
    )


def test_verify_chunk_takes_reference_path():
    """seq > 1 (the speculative verify window) always dispatches to the
    reference, kernel forced or not — per-position causal masking over a
    window is the reference's job."""
    q, pk, pv, table, lens = make_case(seed=3)
    qw = jnp.stack([q, q * 0.5], axis=1)  # (batch, 2, heads, head_dim)
    pos = jnp.stack([lens, lens + 1], axis=1)
    ref = paged_attention_reference(qw, pk, pv, table, pos)
    got = paged_decode_attention(qw, pk, pv, table, pos, interpret=True)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


def test_kernel_tensor2_sharded_kv_heads(devices):
    """The kernel under shard_map with kv heads split over tensor=2 (the
    engine's pool sharding) matches the unsharded reference — the grid
    never indexes across the head shard, so each shard runs a standalone
    kernel over its local heads."""
    import functools

    from jax.sharding import PartitionSpec as P

    from distributed_pytorch_example_tpu.runtime import MeshSpec, make_mesh
    from distributed_pytorch_example_tpu.runtime.jax_compat import shard_map

    q, pk, pv, table, lens = make_case(
        num_heads=4, kv_heads=2, head_dim=16, seed=4
    )
    ref = paged_attention_reference(
        q[:, None], pk, pv, table, lens[:, None]
    )[:, 0]
    mesh = make_mesh(MeshSpec(data=2, fsdp=2, tensor=2))
    sharded = shard_map(
        functools.partial(paged_flash_decode, interpret=True),
        mesh=mesh,
        in_specs=(
            P(None, "tensor", None),  # q: heads (group-aligned) split
            P(None, None, "tensor", None),  # pages_k: kv heads split
            P(None, None, "tensor", None),
            P(None, None),  # table replicated
            P(None,),  # lens replicated
        ),
        out_specs=P(None, "tensor", None),
        # the pallas HLO interpreter does not propagate varying manual
        # axes (test_ring_attention.py convention); TPU runs fully checked
        check_vma=False,
    )
    got = sharded(q, pk, pv, table, lens)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(ref), atol=2e-5
    )

"""Native backend: bit-identical determinism and gather correctness.

The native library auto-builds on import (g++ is in the image); if the
toolchain is genuinely absent these tests skip and the NumPy fallbacks
carry the contract.
"""

import numpy as np
import pytest

from distributed_pytorch_example_tpu.data.sampler import _permutation_numpy

binding = pytest.importorskip(
    "distributed_pytorch_example_tpu.native.binding",
    reason="native toolchain unavailable",
)


@pytest.mark.parametrize("n", [0, 1, 2, 7, 128, 10_000])
@pytest.mark.parametrize("seed", [0, 1, 123456789])
def test_permutation_bit_identical_to_numpy(n, seed):
    np.testing.assert_array_equal(
        binding.permutation(n, seed), _permutation_numpy(n, seed)
    )


def test_permutation_is_a_permutation():
    perm = binding.permutation(1000, 42)
    assert sorted(perm.tolist()) == list(range(1000))


@pytest.mark.parametrize("n_threads", [1, 4])
def test_gather_rows_matches_fancy_index(n_threads):
    rng = np.random.default_rng(0)
    src = rng.standard_normal((100, 32, 32, 3)).astype(np.float32)
    idx = rng.integers(0, 100, 37)
    np.testing.assert_array_equal(
        binding.gather_rows(src, idx, n_threads=n_threads), src[idx]
    )


def test_gather_rows_int_dtype():
    rng = np.random.default_rng(1)
    src = rng.integers(0, 50000, (64, 512)).astype(np.int32)
    idx = rng.integers(0, 64, 16)
    np.testing.assert_array_equal(binding.gather_rows(src, idx), src[idx])


def test_dataset_get_batch_uses_wide_row_path():
    """get_batch through _gather equals fancy indexing on image-sized rows."""
    from distributed_pytorch_example_tpu.data.synthetic import SyntheticImageDataset

    ds = SyntheticImageDataset(num_samples=50, image_size=32)
    idx = np.asarray([3, 1, 4, 1, 5, 9, 2, 6])
    batch = ds.get_batch(idx)
    np.testing.assert_array_equal(batch["x"], ds.arrays["x"][idx])
    np.testing.assert_array_equal(batch["y"], ds.arrays["y"][idx])


def test_gather_rows_numpy_indexing_semantics():
    """Negatives wrap, out-of-range raises — matching the NumPy path."""
    src = np.arange(8 * 1024, dtype=np.float32).reshape(8, 1024)
    np.testing.assert_array_equal(
        binding.gather_rows(src, np.asarray([-1, -8])), src[[-1, -8]]
    )
    with pytest.raises(IndexError):
        binding.gather_rows(src, np.asarray([8]))
    with pytest.raises(IndexError):
        binding.gather_rows(src, np.asarray([-9]))


def test_resized_crop_batch_bit_identical_to_numpy():
    """The C++ random-resized-crop kernel must match the NumPy
    _bilinear_resize + mirror path BIT-identically (same sample positions,
    double blends, ties-to-even rounding)."""
    import numpy as np

    binding = pytest.importorskip(
        "distributed_pytorch_example_tpu.native.binding"
    )
    from distributed_pytorch_example_tpu.data.augment import _bilinear_resize

    rng = np.random.default_rng(7)
    b, h, w, size = 12, 96, 80, 48
    imgs = rng.integers(0, 256, (b, h, w, 3)).astype(np.uint8)
    crops = []
    for _ in range(b):
        ch = int(rng.integers(1, h + 1))
        cw = int(rng.integers(1, w + 1))
        crops.append((
            int(rng.integers(0, h - ch + 1)),
            int(rng.integers(0, w - cw + 1)), ch, cw,
        ))
    crops = np.asarray(crops, np.int64)
    mirror = rng.random(b) < 0.5

    got = binding.resized_crop_batch(imgs, crops, mirror, size)
    for i, (oy, ox, ch, cw) in enumerate(crops):
        ref = _bilinear_resize(imgs[i, oy:oy + ch, ox:ox + cw], size)
        if mirror[i]:
            ref = ref[:, ::-1]
        np.testing.assert_array_equal(got[i], ref)


def test_resized_crop_batch_validates_rects():
    import numpy as np

    binding = pytest.importorskip(
        "distributed_pytorch_example_tpu.native.binding"
    )
    imgs = np.zeros((2, 16, 16, 3), np.uint8)
    bad = np.asarray([[0, 0, 16, 16], [4, 4, 16, 16]], np.int64)  # 2nd OOB
    with pytest.raises(ValueError, match="inside the image"):
        binding.resized_crop_batch(imgs, bad, np.zeros(2, bool), 8)

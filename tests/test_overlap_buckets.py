"""Bucketed comm/compute overlap for the gradient sync (parallel/wire.py
``plan_buckets``/``sync_grads``, telemetry/overlap.py ``scheduled_overlap``).

Evidence layers, mirroring the ZeRO-1/wire test structure:

- bucket-plan structure: reverse issue order, size-targeted sealing,
  scatter/psum kind separation, non-divisible leaf sizes covered exactly;
- sync numerics on the 8-device fake CPU mesh: the UNCOMPRESSED bucketed
  path is BIT-EXACT vs the inline per-leaf path (concatenating leaves
  never changes the element-wise psum reduction), the compressed path
  within the analytic per-block quantization bound;
- K-step Adam trajectory bucketed-vs-inline within the test_zero1 bars,
  with the fused buckets visible as FEWER gradient collectives in the
  compiled step;
- checkpoint resume across a bucketed<->inline flip is bit-exact (the
  bucket schedule changes the wire, never the state contract);
- scheduler-level overlap estimate meets the >= 0.5 CI floor for the
  ZeRO-1+wire config and stamps per-bucket issue spans into the trace.

(The ``inline-grad-sync`` lint rule guarding this schedule is covered in
tests/test_graft_lint.py, which scripts/precommit.sh runs backend-free.)
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax
from jax.sharding import PartitionSpec as P

from distributed_pytorch_example_tpu.analysis.collectives import (
    parse_collective_dtypes,
    parse_collectives,
)
from distributed_pytorch_example_tpu.models.gpt2 import GPT2
from distributed_pytorch_example_tpu.parallel.api import data_parallel
from distributed_pytorch_example_tpu.parallel.wire import (
    WireConfig,
    plan_buckets,
    sync_grads,
)
from distributed_pytorch_example_tpu.runtime import jax_compat
from distributed_pytorch_example_tpu.telemetry.overlap import (
    scheduled_overlap,
)
from distributed_pytorch_example_tpu.train import checkpoint as ckpt_lib
from distributed_pytorch_example_tpu.train.step import (
    build_train_step,
    init_state,
)
from distributed_pytorch_example_tpu.train.tasks import CausalLMTask

# one quantize/dequantize pass error in units of the block amax
# (tests/test_wire.py derives the constant)
_STEP_BOUND = 1.02 / 127.0


def _tiny_model():
    return GPT2(
        vocab_size=64, max_len=32, model_dim=32, num_layers=1,
        num_heads=2, mlp_dim=64, logits_mode="hidden",
    )


def _batch(partitioner, n=16, seq=16, seed=0):
    tokens = np.random.default_rng(seed).integers(
        0, 64, (n, seq)
    ).astype(np.int32)
    return {
        "tokens": jax.device_put(tokens, partitioner.batch_sharding())
    }


def _smap(mesh, fn, in_specs, out_specs):
    return jax_compat.shard_map(
        fn, mesh, in_specs=in_specs, out_specs=out_specs,
        axis_names={"data"},
    )


def _max_diff(a, b):
    diffs = jax.tree_util.tree_map(
        lambda x, y: float(
            jnp.max(jnp.abs(x.astype(jnp.float32) - y.astype(jnp.float32)))
        ),
        a, b,
    )
    return max(jax.tree_util.tree_leaves(diffs))


# ---------------------------------------------------------------------------
# bucket plan structure (static — no mesh)
# ---------------------------------------------------------------------------


def test_plan_buckets_structure_and_boundaries():
    """Reverse issue order, kind separation, exact leaf coverage — with
    leaf sizes that divide NEITHER the bucket target NOR the block size."""
    grads = {
        "a": jax.ShapeDtypeStruct((16, 5), jnp.float32),   # scatter, 80
        "b": jax.ShapeDtypeStruct((24,), jnp.float32),     # scatter, 24
        "c": jax.ShapeDtypeStruct((7,), jnp.float32),      # psum, 7
        "e": jax.ShapeDtypeStruct((3, 3), jnp.float32),    # psum, 9
        "z": jax.ShapeDtypeStruct((0,), jnp.float32),      # zero-size
    }
    dims = {"a": 0, "b": 0, "c": None, "e": None, "z": None}
    cfg = WireConfig(bucket_bytes=64)
    plan = plan_buckets(dims, grads, cfg, axis_size=8)

    leaves = jax.tree_util.tree_leaves(grads)
    covered = [i for b in plan.buckets for i in b.leaves]
    # every non-empty leaf exactly once; the zero-size leaf never planned
    nonzero = [i for i, x in enumerate(leaves) if x.size]
    assert sorted(covered) == sorted(nonzero)
    assert len(covered) == len(set(covered))
    for b in plan.buckets:
        kinds = {
            "scatter" if jax.tree_util.tree_leaves(
                dims, is_leaf=lambda d: d is None
            )[i] is not None else "psum"
            for i in b.leaves
        }
        assert kinds == {b.kind}  # kinds never mix inside a bucket
        assert b.elements == sum(int(leaves[i].size) for i in b.leaves)
        # issue order within a bucket is reverse trace order
        assert list(b.leaves) == sorted(b.leaves, reverse=True)
    # the 64 B target actually splits the tree (not one bucket per kind)
    assert len(plan.buckets) >= 3, plan.to_json()
    js = plan.to_json()
    assert js["num_buckets"] == len(plan.buckets)
    assert all(b["wire_bytes"] > 0 for b in js["buckets"])


# ---------------------------------------------------------------------------
# sync numerics: bucketed vs inline on the fake 8-device mesh
# ---------------------------------------------------------------------------


def _sync_tree(mesh, config):
    """Run sync_grads over a mixed non-divisible tree; returns np leaves."""
    rng = np.random.default_rng(7)
    grads = {
        "a": rng.standard_normal((8, 16, 5)).astype(np.float32),
        "b": rng.standard_normal((8, 24)).astype(np.float32),
        "c": rng.standard_normal((8, 7)).astype(np.float32),
        "e": rng.standard_normal((8, 3, 3)).astype(np.float32),
    }
    dims = {"a": 1, "b": 1, "c": None, "e": None}

    def fn(g):
        return sync_grads(g, dims, "data", config=config, scale=0.125)

    specs = jax.tree_util.tree_map(lambda _: P("data"), grads)
    with mesh:
        out = _smap(mesh, fn, (specs,), specs)(grads)
    return {k: np.asarray(v) for k, v in out.items()}, grads


def test_bucketed_uncompressed_is_bit_exact(mesh_1d):
    """Fused fp32 buckets must be BIT-identical to the inline per-leaf
    sync: concatenation re-groups rows, never re-orders the reduction."""
    inline, _ = _sync_tree(mesh_1d, WireConfig())
    bucketed, _ = _sync_tree(mesh_1d, WireConfig(bucket_bytes=64))
    for k in inline:
        np.testing.assert_array_equal(bucketed[k], inline[k])


def test_bucketed_compressed_within_block_bound(mesh_1d):
    """Quantization blocks span leaf joins in a bucket; the error bound
    (sum of d per-source block errors, 2 passes for psum) still holds."""
    exact, grads = _sync_tree(mesh_1d, WireConfig())
    got, _ = _sync_tree(
        mesh_1d,
        WireConfig(
            compress="int8-block", block_size=64, min_size=1,
            bucket_bytes=64,
        ),
    )
    amax = max(np.abs(v).max() for v in grads.values())
    scale = 0.125
    diff = 0.0
    for k in exact:
        passes = 2 if k in ("c", "e") else 1  # psum = RS + quantized AG
        bound = passes * 8 * amax * _STEP_BOUND * scale
        d = np.abs(got[k] - exact[k]).max()
        assert d <= bound, (k, d, bound)
        diff = max(diff, d)
    assert diff > 0.0  # it really quantized


# ---------------------------------------------------------------------------
# trajectory: K Adam steps through the full train step
# ---------------------------------------------------------------------------

_RUN_CACHE = {}


def _run(mesh, *, bucket_bytes, compress="none", steps=3):
    """(final state, collectives, dtype mix, losses) per sync mode,
    memoized — each entry is a full jit compile on the one-core box."""
    key = (bucket_bytes, compress, steps)
    if key in _RUN_CACHE:
        return _RUN_CACHE[key]
    model, task, opt = _tiny_model(), CausalLMTask(), optax.adam(1e-3)
    cfg = WireConfig(
        compress=compress, min_size=1, bucket_bytes=bucket_bytes
    )
    part = data_parallel(
        mesh, dp_shard_opt_state=True, opt_shard_min_size=1, wire=cfg
    )
    batch = _batch(part)
    with mesh:
        state, _ = init_state(
            model, opt, batch["tokens"], jax.random.key(0), part
        )
        step = build_train_step(
            model, task, opt, partitioner=part, grad_accum_steps=1
        )
        text = step.lower(state, batch).compile().as_text()
        coll = parse_collectives(text)
        dtypes = parse_collective_dtypes(text)
        losses = []
        for _ in range(steps):
            state, metrics = step(state, batch)
            losses.append(float(metrics["loss"]))
    _RUN_CACHE[key] = (state, coll, dtypes, losses)
    return _RUN_CACHE[key]


def test_bucketed_step_matches_inline(mesh_1d):
    """Params within the test_zero1 bar after K Adam steps, and the
    compiled step fuses the per-leaf reduce-scatters into buckets."""
    s_inline, coll_i, _, losses_i = _run(mesh_1d, bucket_bytes=0)
    s_bucket, coll_b, _, losses_b = _run(mesh_1d, bucket_bytes=8192)

    assert _max_diff(s_bucket.params, s_inline.params) < 5e-4
    for li, lb in zip(losses_i, losses_b):
        assert abs(li - lb) < 1e-3, (losses_i, losses_b)

    # fused buckets: strictly fewer gradient reduce-scatters than the
    # per-leaf inline step, but still at least one (no silent all-reduce)
    rs_inline = coll_i.get("reduce-scatter", {}).get("count", 0)
    rs_bucket = coll_b.get("reduce-scatter", {}).get("count", 0)
    assert rs_inline > rs_bucket >= 1, (rs_inline, rs_bucket)
    # ZeRO-1 invariant holds under bucketing: no gradient-sized AR
    grad_bytes = coll_b["reduce-scatter"]["bytes"]
    assert coll_b.get("all-reduce", {}).get("bytes", 0) < grad_bytes


def test_bucketed_compressed_trajectory(mesh_1d):
    """Bucketed int8 wire: loss trajectory within the test_wire Adam bar
    vs the uncompressed inline step, and the step really moves s8."""
    _, _, dt_plain, losses_i = _run(mesh_1d, bucket_bytes=0)
    _, _, dt_q, losses_q = _run(
        mesh_1d, bucket_bytes=8192, compress="int8-block"
    )
    for li, lq in zip(losses_i, losses_q):
        assert abs(li - lq) < 1e-3, (losses_i, losses_q)
    assert losses_i != losses_q  # identical would mean silent fp32
    assert sum(rec.get("s8", 0) for rec in dt_q.values()) > 0, dt_q
    assert sum(rec.get("s8", 0) for rec in dt_plain.values()) == 0
    # the quantized bucket RS decomposes to all-to-all, like the inline
    # compressed path
    assert "all-to-all" in dt_q


def test_checkpoint_resume_across_bucketing_flip(mesh_1d, tmp_path):
    """A bucketed run's checkpoint restores into an inline step (and
    back) bit-exact: bucketing changes the wire schedule, never the
    checkpointed state contract."""
    path = str(tmp_path / "ckpt")
    model, task = _tiny_model(), CausalLMTask()
    optimizer = optax.adam(1e-3)

    def build(bucket_bytes):
        cfg = WireConfig(min_size=1, bucket_bytes=bucket_bytes)
        part = data_parallel(
            mesh_1d, dp_shard_opt_state=True, opt_shard_min_size=1,
            wire=cfg,
        )
        batch = _batch(part)
        with mesh_1d:
            state, shardings = init_state(
                model, optimizer, batch["tokens"], jax.random.key(0), part
            )
            step = build_train_step(
                model, task, optimizer, partitioner=part,
                grad_accum_steps=1,
            )
        return batch, state, shardings, step

    batch, state, _, step = build(8192)
    with mesh_1d:
        for _ in range(2):
            state, _ = step(state, batch)
    ckpt_lib.save_checkpoint(path, state, 1, 0.0, {})

    batch_i, template_i, shardings_i, step_i = build(0)
    loaded, epoch, _ = ckpt_lib.load_checkpoint(
        path, template_i, shardings_i
    )
    assert epoch == 1
    assert _max_diff(loaded.params, state.params) == 0.0
    assert _max_diff(loaded.opt_state[0].mu, state.opt_state[0].mu) == 0.0
    with mesh_1d:
        stepped, _ = step_i(loaded, batch_i)

    ckpt_lib.save_checkpoint(path, stepped, 2, 0.0, {})
    batch_b, template_b, shardings_b, step_b = build(8192)
    loaded_b, epoch_b, _ = ckpt_lib.load_checkpoint(
        path, template_b, shardings_b
    )
    assert epoch_b == 2
    assert _max_diff(loaded_b.params, stepped.params) == 0.0
    with mesh_1d:
        step_b(loaded_b, batch_b)


# ---------------------------------------------------------------------------
# scheduler-level overlap estimate (the off-TPU CI gate)
# ---------------------------------------------------------------------------


def test_scheduled_overlap_meets_ci_floor(mesh_1d, tmp_path):
    """ZeRO-1 + int8 wire + 8 KiB buckets on the tiny model: scheduled
    overlap >= 0.5 (the ISSUE-19 acceptance floor), per-bucket scopes
    named wire_bucket<k>, and issue spans stamped into the trace."""
    from distributed_pytorch_example_tpu.telemetry.trace import TraceWriter

    cfg = WireConfig(compress="int8-block", min_size=1, bucket_bytes=8192)
    part = data_parallel(
        mesh_1d, dp_shard_opt_state=True, opt_shard_min_size=1, wire=cfg
    )
    params = jax.eval_shape(
        lambda: _tiny_model().init(
            jax.random.key(0), jnp.zeros((2, 8), jnp.int32)
        )["params"]
    )
    dims = part.zero1_dims(params)
    plan = plan_buckets(dims, params, cfg, axis_size=8)

    trace_path = str(tmp_path / "trace.json")
    writer = TraceWriter(trace_path)
    report = scheduled_overlap(plan, grad_accum_steps=2, trace=writer)
    writer.close()

    assert report["overlap_frac_scheduled"] >= 0.5, report
    assert report["num_buckets"] >= 2
    assert report["total_wire_bytes"] > report["hideable_wire_bytes"] > 0
    scopes = [b["scope"] for b in report["per_bucket"]]
    assert scopes == [f"wire_bucket{k}" for k in range(len(scopes))]
    # only the LAST bucket is exposed; everything earlier is hideable
    hideable = [b["hideable"] for b in report["per_bucket"]]
    assert hideable[:-1] == [True] * (len(hideable) - 1)
    assert hideable[-1] is False
    with open(trace_path) as f:
        text = f.read()
    assert "wire_bucket0/issue" in text
    assert f"wire_bucket{len(scopes) - 1}/issue" in text

    # unbucketed degrades to an honest zero, not a crash
    empty = scheduled_overlap(None)
    assert empty["overlap_frac_scheduled"] == 0.0
    assert empty["num_buckets"] == 0


# the inline-grad-sync lint rule's fixtures live in tests/
# test_graft_lint.py (test_inline_grad_sync_*), which scripts/
# precommit.sh runs backend-free; the shipped train/step.py clean gate
# is test_zero1.test_step_source_is_lint_clean

"""Key-padding masks through BOTH sequence-parallel modes (VERDICT r2 #2).

Ring: the (B, S_chunk) mask chunk rotates around the ring with its K/V
chunk and feeds the flash kernel's kv_mask port. Ulysses: the mask is
all-gathered after the heads<->sequence all-to-all. Both must match the
dense masked XLA reference — values and gradients — and BERT with
--pad-token-id must train under a sequence-spanning mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_pytorch_example_tpu.ops.attention import _xla_attention
from distributed_pytorch_example_tpu.ops.ring_attention import (
    ring_attention_sharded,
)
from distributed_pytorch_example_tpu.ops.ulysses import (
    ulysses_attention_sharded,
)
from distributed_pytorch_example_tpu.runtime import MeshSpec, make_mesh


def make_qkv(batch=2, seq=256, heads=4, head_dim=32, seed=0):
    rng = np.random.default_rng(seed)
    shape = (batch, seq, heads, head_dim)
    return tuple(
        jnp.asarray(rng.standard_normal(shape), jnp.float32) for _ in range(3)
    )


def make_mask(batch=2, seq=256, seed=1):
    """Realistic padding: each row valid up to a random length (>= 1)."""
    rng = np.random.default_rng(seed)
    lengths = rng.integers(1, seq + 1, size=(batch,))
    return jnp.asarray(np.arange(seq)[None, :] < lengths[:, None])


@pytest.mark.parametrize("causal", [False, True])
def test_ring_masked_matches_dense(devices, causal):
    mesh = make_mesh(MeshSpec(data=2, sequence=4))
    q, k, v = make_qkv()
    mask = make_mask()
    scale = q.shape[-1] ** -0.5
    expected = _xla_attention(q, k, v, None, mask, causal, scale)
    got = ring_attention_sharded(
        q, k, v, mesh, kv_mask=mask, causal=causal
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_masked_matches_dense(devices, causal):
    mesh = make_mesh(MeshSpec(data=2, sequence=4))
    q, k, v = make_qkv()
    mask = make_mask()
    scale = q.shape[-1] ** -0.5
    expected = _xla_attention(q, k, v, None, mask, causal, scale)
    got = ulysses_attention_sharded(
        q, k, v, mesh, kv_mask=mask, causal=causal
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=2e-5)


@pytest.mark.parametrize("mode", ["ring", "ulysses"])
def test_masked_grads_match_dense(devices, mode):
    mesh = make_mesh(MeshSpec(data=2, sequence=4))
    q, k, v = make_qkv(seq=128)
    mask = make_mask(seq=128)
    scale = q.shape[-1] ** -0.5
    sharded = (
        ring_attention_sharded if mode == "ring" else ulysses_attention_sharded
    )

    def loss_ref(q, k, v):
        return jnp.sum(_xla_attention(q, k, v, None, mask, False, scale) ** 2)

    def loss_sp(q, k, v):
        return jnp.sum(sharded(q, k, v, mesh, kv_mask=mask) ** 2)

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_sp = jax.grad(loss_sp, argnums=(0, 1, 2))(q, k, v)
    for gr, gg, name in zip(g_ref, g_sp, "qkv"):
        np.testing.assert_allclose(
            np.asarray(gg), np.asarray(gr), atol=1e-4, err_msg=f"d{name}"
        )


def test_ring_fully_padded_row(devices):
    """A row with every key masked: zero output, zero grads, no NaNs."""
    mesh = make_mesh(MeshSpec(data=2, sequence=4))
    q, k, v = make_qkv(seq=128)
    mask = make_mask(seq=128)
    mask = mask.at[0].set(False)  # row 0: nothing to attend to

    def loss(q, k, v):
        return jnp.sum(
            ring_attention_sharded(q, k, v, mesh, kv_mask=mask) ** 2
        )

    out = ring_attention_sharded(q, k, v, mesh, kv_mask=mask)
    assert np.all(np.isfinite(np.asarray(out)))
    np.testing.assert_array_equal(np.asarray(out[0]), 0.0)
    gq, gk, gv = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    for g in (gq, gk, gv):
        assert np.all(np.isfinite(np.asarray(g)))
        np.testing.assert_array_equal(np.asarray(g[0]), 0.0)


@pytest.mark.parametrize("sp_mode", ["ring", "ulysses"])
def test_bert_pad_token_trains_under_sp_mesh(devices, sp_mode):
    """BERT + --pad-token-id + mesh sequence=2: the combination VERDICT r2
    flagged as refused; one full fused-loss train step must run and the
    masked loss must match the same model on a no-sequence mesh."""
    import optax

    import distributed_pytorch_example_tpu as dpx
    from distributed_pytorch_example_tpu.train.tasks import MLMTask

    vocab, seq = 97, 32
    kwargs = dict(
        vocab_size=vocab, max_len=seq, model_dim=32, num_layers=2,
        num_heads=4, mlp_dim=64, dtype=jnp.float32, use_flash=False,
        pad_token_id=0, logits_mode="hidden",  # fused CE: train.py default
    )
    rng = np.random.default_rng(0)
    tokens_np = rng.integers(1, vocab, (8, seq)).astype(np.int32)
    tokens_np[:, seq - 6:] = 0  # pad tail
    task = MLMTask(vocab_size=vocab, mask_token_id=3, pad_token_id=0)

    losses = {}
    for spec, seq_axis in (
        (MeshSpec(data=4, sequence=2), "sequence"),
        (MeshSpec(data=8), None),
    ):
        mesh = make_mesh(spec)
        model = dpx.models.get_model(
            "bert", seq_axis=seq_axis,
            sp_mode=sp_mode if seq_axis else "ring", **kwargs
        )
        trainer = dpx.train.Trainer(
            model, task, optax.adam(1e-3),
            partitioner=dpx.parallel.data_parallel(mesh),
        )
        sharding = trainer.partitioner.batch_sharding()
        batch = {
            "tokens": jax.make_array_from_process_local_data(
                sharding, tokens_np
            )
        }
        with mesh:
            trainer.init(batch["tokens"])
            _, metrics = trainer.train_step(trainer.state, batch)
            losses[seq_axis] = float(metrics["loss"])
    assert np.isfinite(losses["sequence"])
    np.testing.assert_allclose(
        losses["sequence"], losses[None], rtol=1e-4
    )

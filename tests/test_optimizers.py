"""Optimizer factory: schedules, clipping, accumulation semantics."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributed_pytorch_example_tpu.train.optimizers import (
    make_optimizer,
    make_schedule,
)


class TestSchedules:
    def test_constant(self):
        s = make_schedule("constant", 0.1)
        assert s == 0.1

    def test_warmup_then_cosine(self):
        s = make_schedule("cosine", 1.0, warmup_steps=10, total_steps=110)
        assert float(s(0)) == 0.0
        assert float(s(10)) == pytest.approx(1.0)
        assert float(s(110)) == pytest.approx(0.0, abs=1e-6)
        assert 0.0 < float(s(60)) < 1.0

    def test_linear(self):
        s = make_schedule("linear", 1.0, total_steps=100, final_scale=0.1)
        assert float(s(0)) == pytest.approx(1.0)
        assert float(s(100)) == pytest.approx(0.1)

    def test_cosine_requires_total(self):
        with pytest.raises(ValueError, match="total_steps"):
            make_schedule("cosine", 1.0)


class TestOptimizers:
    def _step(self, tx, grads, params, n=1):
        state = tx.init(params)
        for _ in range(n):
            updates, state = tx.update(grads, state, params)
            params = optax.apply_updates(params, updates)
        return params, state

    def test_all_optimizers_step(self):
        params = {"w": jnp.ones((4,))}
        grads = {"w": jnp.full((4,), 0.5)}
        for name in ("adam", "adamw", "sgd", "lamb"):
            tx = make_optimizer(name, 0.1, weight_decay=0.01)
            new, _ = self._step(tx, grads, params)
            assert not np.allclose(np.asarray(new["w"]), 1.0), name

    def test_grad_clip_limits_update(self):
        params = {"w": jnp.zeros((4,))}
        huge = {"w": jnp.full((4,), 1e6)}
        tx = make_optimizer("sgd", 1.0, grad_clip_norm=1.0, momentum=0.0)
        new, _ = self._step(tx, huge, params)
        # clipped to global norm 1 then lr 1.0: ||update|| == 1
        assert np.linalg.norm(np.asarray(new["w"])) == pytest.approx(1.0, rel=1e-5)

    def test_accumulation_matches_mean_grad(self):
        """k accumulated micro-grads == one step with their mean."""
        params = {"w": jnp.zeros((3,))}
        g1 = {"w": jnp.asarray([1.0, 0.0, 2.0])}
        g2 = {"w": jnp.asarray([3.0, 2.0, 0.0])}
        mean = {"w": (g1["w"] + g2["w"]) / 2}

        acc = make_optimizer("sgd", 0.1, momentum=0.0, every_k=2)
        state = acc.init(params)
        p = params
        for g in (g1, g2):
            updates, state = acc.update(g, state, p)
            p = optax.apply_updates(p, updates)

        ref = make_optimizer("sgd", 0.1, momentum=0.0)
        ref_p, _ = TestOptimizers()._step(ref, mean, params)
        np.testing.assert_allclose(np.asarray(p["w"]), np.asarray(ref_p["w"]), atol=1e-6)

    def test_accumulation_no_update_mid_window(self):
        params = {"w": jnp.zeros((3,))}
        g = {"w": jnp.ones((3,))}
        tx = make_optimizer("sgd", 0.1, momentum=0.0, every_k=4)
        state = tx.init(params)
        updates, state = tx.update(g, state, params)
        p = optax.apply_updates(params, updates)
        np.testing.assert_array_equal(np.asarray(p["w"]), 0.0)  # not yet

    def test_trainer_integration(self, devices, tmp_path):
        import distributed_pytorch_example_tpu as dpx

        mesh = dpx.runtime.make_mesh()
        tx = make_optimizer(
            "adamw", 1e-3, schedule="cosine", warmup_steps=2,
            total_steps=8, weight_decay=0.01, grad_clip_norm=1.0, every_k=2,
        )
        trainer = dpx.train.Trainer(
            dpx.models.SimpleNet(hidden_size=32),
            dpx.train.ClassificationTask(),
            tx,
            partitioner=dpx.parallel.data_parallel(mesh),
        )
        ds = dpx.data.SyntheticClassificationDataset(num_samples=64)
        loader = dpx.data.DeviceLoader(ds, 16, mesh=mesh, seed=0)
        history = trainer.fit(loader, epochs=2)
        assert np.isfinite(history[-1]["train_loss"])


def test_adafactor_trains():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributed_pytorch_example_tpu.train.optimizers import make_optimizer

    opt = make_optimizer("adafactor", 1e-2)
    params = {"w": jnp.ones((8, 4)), "b": jnp.zeros((4,))}
    state = opt.init(params)
    grads = jax.tree_util.tree_map(jnp.ones_like, params)
    updates, state = opt.update(grads, state, params)
    new = jax.tree_util.tree_map(lambda p, u: p + u, params, updates)
    assert all(
        np.isfinite(np.asarray(l)).all()
        for l in jax.tree_util.tree_leaves(new)
    )
    assert not np.allclose(np.asarray(new["w"]), np.asarray(params["w"]))


def test_mlm_pad_positions_never_masked_or_scored():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributed_pytorch_example_tpu.models.bert import BertBase
    from distributed_pytorch_example_tpu.train.tasks import MLMTask

    model = BertBase(vocab_size=64, max_len=32, model_dim=16, num_layers=1,
                     num_heads=2, mlp_dim=32, pad_token_id=0)
    tokens = np.random.default_rng(0).integers(1, 64, (2, 16)).astype(np.int32)
    tokens[:, 10:] = 0  # padded tail
    tokens = jnp.asarray(tokens)
    params = model.init(jax.random.key(0), tokens)["params"]
    task = MLMTask(vocab_size=64, mask_token_id=3, mask_rate=0.9,
                   pad_token_id=0)
    loss, metrics, _ = task.compute_loss(
        model, params, {}, {"tokens": tokens}, jax.random.key(1), train=False
    )
    assert np.isfinite(float(loss)) and float(loss) > 0

    # discriminating check: an ALL-pad batch has nothing selectable, so
    # the loss must be exactly 0 — it would be positive if pad positions
    # could be selected
    all_pad = jnp.zeros_like(tokens)
    loss_pad, _, _ = task.compute_loss(
        model, params, {}, {"tokens": all_pad}, jax.random.key(1), train=False
    )
    assert float(loss_pad) == 0.0


def test_mlm_random_replacement_never_draws_pad():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributed_pytorch_example_tpu.train.tasks import MLMTask

    captured = {}

    class SpyModel:
        def apply(self, variables, inputs, **kw):
            captured["inputs"] = inputs
            return jnp.zeros((*inputs.shape, 64), jnp.float32)

    task = MLMTask(vocab_size=64, mask_token_id=3, mask_rate=1.0,
                   pad_token_id=7)
    tokens = jnp.asarray(
        np.random.default_rng(1).integers(8, 64, (4, 64)), jnp.int32
    )
    task.compute_loss(
        SpyModel(), {}, {}, {"tokens": tokens}, jax.random.key(0), train=False
    )
    # real tokens were all >= 8; any 7 in the masked inputs could only
    # come from the random-replacement draw — which must exclude pad
    assert not np.any(np.asarray(captured["inputs"]) == 7)

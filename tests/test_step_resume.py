"""Step-level resume: kill mid-epoch, restart at the exact batch.

Beyond-reference capability (the reference resumes at epoch granularity,
train.py:256-257): ``--save-every-steps`` checkpoints carry the loader
cursor (epoch, batch_in_epoch), and resume skips to that batch. The
determinism contract that makes this PROVABLE: the sampler permutation is
a pure function of (seed, epoch) (data/sampler.py), and the per-step rng
folds the checkpointed ``state.rng`` with the checkpointed ``state.step``
(train/step.py) — so a SIGKILLed-and-resumed run's per-batch losses must
equal an uninterrupted control's exactly.
"""

import json
import os
import re
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# 800 steps/epoch so the victim is reliably mid-epoch when the SIGKILL
# lands (a tiny run finishes before the signal can be delivered)
BASE_ARGS = [
    "--epochs", "2", "--num-samples", "12800", "--batch-size", "2",
    "--log-every", "1", "--seed", "5", "--lr", "0.01",
]

LOSS_RE = re.compile(r"Epoch (\d+), Batch (\d+)/\d+, Loss: ([0-9.]+)")


def _env():
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # force CPU past the axon plugin
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    ).strip()
    return env


def _losses(stderr: str) -> dict:
    """{(epoch, batch): 'loss string'} from --log-every 1 output."""
    return {
        (int(m.group(1)), int(m.group(2))): m.group(3)
        for m in LOSS_RE.finditer(stderr)
    }


def _run(args, timeout=600):
    # one retry on crash-by-signal BEFORE any training step logged: under
    # a full-suite run on the 1-core box the spawned interpreter
    # occasionally SIGABRTs in XLA thread teardown before training starts
    # (observed once in ~10 suite runs; passes in isolation). The no-Loss
    # guard keeps the retry from re-running a --resume invocation whose
    # first attempt already trained past the mid-epoch checkpoint (which
    # would silently degrade this test to epoch-boundary resume). A real
    # trainer bug exits nonzero (no retry) or aborts repeatably.
    for attempt in (0, 1):
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "train.py"), *args],
            capture_output=True, text=True, env=_env(), cwd=REPO,
            timeout=timeout,
        )
        if proc.returncode >= 0 or "Loss:" in proc.stderr or attempt:
            break
    assert proc.returncode == 0, proc.stderr[-2000:]
    return proc.stderr


@pytest.mark.slow
def test_sigkill_mid_epoch_resumes_bit_identical(tmp_path):
    ctrl_dir, vict_dir = str(tmp_path / "ctrl"), str(tmp_path / "vict")

    # 1. uninterrupted control
    ctrl_err = _run([*BASE_ARGS, "--checkpoint-dir", ctrl_dir])
    ctrl = _losses(ctrl_err)
    assert (0, 0) in ctrl and (1, 799) in ctrl  # 800 batches x 2 epochs

    # 2. victim: per-step checkpoints, SIGKILLed once batch 3 of epoch 0
    # has run (so `latest` carries a mid-epoch cursor)
    victim = subprocess.Popen(
        [
            sys.executable, os.path.join(REPO, "train.py"), *BASE_ARGS,
            "--checkpoint-dir", vict_dir, "--save-every-steps", "1",
        ],
        stderr=subprocess.PIPE, text=True, env=_env(), cwd=REPO,
    )
    import threading

    seen = []
    # watchdog: a wedged victim that stops logging would block the pipe
    # read forever; killing it closes the pipe and fails the test loudly
    watchdog = threading.Timer(600, victim.kill)
    watchdog.start()
    try:
        for line in victim.stderr:
            seen.append(line)
            m = LOSS_RE.search(line)
            if m and (int(m.group(1)), int(m.group(2))) >= (0, 3):
                break
        else:
            raise AssertionError(
                "victim exited/wedged before batch 3:\n" + "".join(seen[-30:])
            )
    finally:
        watchdog.cancel()
    # no settling sleep: dozens of async per-step saves have landed by now
    victim.send_signal(signal.SIGKILL)
    victim.wait(timeout=60)
    victim.stderr.close()

    ckpt = os.path.join(vict_dir, "latest_model.ckpt")
    assert os.path.exists(ckpt), "no mid-epoch checkpoint survived the kill"

    # 3. resume: must restart MID-epoch at the checkpointed cursor
    res_err = _run(
        [*BASE_ARGS, "--checkpoint-dir", vict_dir, "--resume", ckpt]
    )
    m = re.search(r"Resuming epoch (\d+) at batch (\d+)/800", res_err)
    assert m, res_err[-2000:]
    resume_at = (int(m.group(1)), int(m.group(2)))
    assert (0, 1) <= resume_at <= (1, 799)

    # 4. bit-identical trajectory: every post-resume (epoch, batch) loss
    # equals the control's, and the pre-kill victim losses do too
    res = _losses(res_err)
    expected = {k: v for k, v in ctrl.items() if k >= resume_at}
    assert expected, "control produced no comparable steps"
    for key, loss in expected.items():
        assert res.get(key) == loss, (
            f"loss diverged at {key}: resumed {res.get(key)} != control {loss}"
        )
    vict = _losses("".join(seen))
    for key, loss in vict.items():
        assert ctrl[key] == loss, f"victim diverged at {key} pre-kill"

    # 5. final state equality: metrics.jsonl last epoch records match the
    # control exactly (full-precision floats)
    def last_record(d):
        with open(os.path.join(d, "metrics.jsonl")) as f:
            return json.loads(f.readlines()[-1])

    ctrl_rec, res_rec = last_record(ctrl_dir), last_record(vict_dir)
    for k in ("epoch", "val_loss", "val_accuracy"):
        assert ctrl_rec[k] == res_rec[k], (k, ctrl_rec[k], res_rec[k])


@pytest.mark.slow
def test_torn_sharded_save_resumes_previous_intact_checkpoint(tmp_path):
    """SIGKILL between the shard writes and the manifest/pointer flip
    (graft-armor chaos crash point): the torn version is never committed,
    so the pointer still names the previous intact version and resume
    lands on it — no operator intervention, no fallback walk needed."""
    ckdir = str(tmp_path / "ck")
    args = [
        "--epochs", "1", "--num-samples", "640", "--batch-size", "2",
        "--log-every", "1", "--seed", "5", "--checkpoint-dir", ckdir,
        "--checkpoint-format", "sharded", "--save-every-steps", "1",
    ]
    plan = json.dumps({"faults": [
        {"kind": "kill", "at": "sharded-save:post-shards", "nth": 3},
    ]})
    victim = subprocess.run(
        [sys.executable, os.path.join(REPO, "train.py"), *args,
         "--chaos", plan],
        capture_output=True, text=True, env=_env(), cwd=REPO, timeout=600,
    )
    assert victim.returncode == -signal.SIGKILL, victim.stderr[-2000:]

    # saves 1 and 2 committed; save 3 died post-shards: its version dir
    # has shard files but no manifest, and the pointer still names save 2
    latest = os.path.join(ckdir, "latest_model.ckpt")
    assert os.path.isfile(latest)
    versions = sorted(os.listdir(latest + ".shards"))
    assert len(versions) == 3, versions
    torn = os.path.join(latest + ".shards", versions[-1])
    assert not os.path.exists(os.path.join(torn, "manifest.msgpack"))

    res_err = _run([*args, "--resume", latest])
    m = re.search(r"Resuming epoch (\d+) at batch (\d+)/40", res_err)
    assert m, res_err[-2000:]
    # batch 2 = the second (last intact) mid-epoch save's cursor
    assert (int(m.group(1)), int(m.group(2))) == (0, 2)


def test_iter_from_matches_tail_of_full_iteration(devices):
    """loader.iter_from(k) yields exactly the batches a full iteration
    yields from step k on (the cursor contract resume relies on)."""
    from distributed_pytorch_example_tpu.data.loader import DeviceLoader
    from distributed_pytorch_example_tpu.data.synthetic import (
        SyntheticClassificationDataset,
    )

    ds = SyntheticClassificationDataset(num_samples=40)
    loader = DeviceLoader(ds, 8, num_shards=1, shard_id=0, seed=3)
    loader.set_epoch(2)
    full = [
        {k: np.asarray(v) for k, v in b.items()} for b in iter(loader)
    ]
    loader.set_epoch(2)
    tail = [
        {k: np.asarray(v) for k, v in b.items()} for b in loader.iter_from(2)
    ]
    assert len(tail) == len(full) - 2
    for a, b in zip(full[2:], tail):
        for k in a:
            np.testing.assert_array_equal(a[k], b[k])
    with pytest.raises(ValueError, match="start_step"):
        list(loader.iter_from(len(loader) + 1))

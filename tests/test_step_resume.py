"""Step-level resume: kill mid-epoch, restart at the exact batch.

Beyond-reference capability (the reference resumes at epoch granularity,
train.py:256-257): ``--save-every-steps`` checkpoints carry the loader
cursor (epoch, batch_in_epoch), and resume skips to that batch. The
determinism contract that makes this PROVABLE: the sampler permutation is
a pure function of (seed, epoch) (data/sampler.py), and the per-step rng
folds the checkpointed ``state.rng`` with the checkpointed ``state.step``
(train/step.py) — so a SIGKILLed-and-resumed run's per-batch losses must
equal an uninterrupted control's exactly.
"""

import json
import os
import re
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# 800 steps/epoch so the victim is reliably mid-epoch when the SIGKILL
# lands (a tiny run finishes before the signal can be delivered)
BASE_ARGS = [
    "--epochs", "2", "--num-samples", "12800", "--batch-size", "2",
    "--log-every", "1", "--seed", "5", "--lr", "0.01",
]

LOSS_RE = re.compile(r"Epoch (\d+), Batch (\d+)/\d+, Loss: ([0-9.]+)")


def _env():
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # force CPU past the axon plugin
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    ).strip()
    return env


def _losses(stderr: str) -> dict:
    """{(epoch, batch): 'loss string'} from --log-every 1 output."""
    return {
        (int(m.group(1)), int(m.group(2))): m.group(3)
        for m in LOSS_RE.finditer(stderr)
    }


def _run(args, timeout=600):
    # one retry on crash-by-signal BEFORE any training step logged: under
    # a full-suite run on the 1-core box the spawned interpreter
    # occasionally SIGABRTs in XLA thread teardown before training starts
    # (observed once in ~10 suite runs; passes in isolation). The no-Loss
    # guard keeps the retry from re-running a --resume invocation whose
    # first attempt already trained past the mid-epoch checkpoint (which
    # would silently degrade this test to epoch-boundary resume). A real
    # trainer bug exits nonzero (no retry) or aborts repeatably.
    for attempt in (0, 1):
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "train.py"), *args],
            capture_output=True, text=True, env=_env(), cwd=REPO,
            timeout=timeout,
        )
        if proc.returncode >= 0 or "Loss:" in proc.stderr or attempt:
            break
    assert proc.returncode == 0, proc.stderr[-2000:]
    return proc.stderr


@pytest.mark.slow
def test_sigkill_mid_epoch_resumes_bit_identical(tmp_path):
    ctrl_dir, vict_dir = str(tmp_path / "ctrl"), str(tmp_path / "vict")

    # 1. uninterrupted control
    ctrl_err = _run([*BASE_ARGS, "--checkpoint-dir", ctrl_dir])
    ctrl = _losses(ctrl_err)
    assert (0, 0) in ctrl and (1, 799) in ctrl  # 800 batches x 2 epochs

    # 2. victim: per-step checkpoints, SIGKILLed once batch 3 of epoch 0
    # has run (so `latest` carries a mid-epoch cursor)
    victim = subprocess.Popen(
        [
            sys.executable, os.path.join(REPO, "train.py"), *BASE_ARGS,
            "--checkpoint-dir", vict_dir, "--save-every-steps", "1",
        ],
        stderr=subprocess.PIPE, text=True, env=_env(), cwd=REPO,
    )
    import threading

    seen = []
    # watchdog: a wedged victim that stops logging would block the pipe
    # read forever; killing it closes the pipe and fails the test loudly
    watchdog = threading.Timer(600, victim.kill)
    watchdog.start()
    try:
        for line in victim.stderr:
            seen.append(line)
            m = LOSS_RE.search(line)
            if m and (int(m.group(1)), int(m.group(2))) >= (0, 3):
                break
        else:
            raise AssertionError(
                "victim exited/wedged before batch 3:\n" + "".join(seen[-30:])
            )
    finally:
        watchdog.cancel()
    # no settling sleep: dozens of async per-step saves have landed by now
    victim.send_signal(signal.SIGKILL)
    victim.wait(timeout=60)
    victim.stderr.close()

    ckpt = os.path.join(vict_dir, "latest_model.ckpt")
    assert os.path.exists(ckpt), "no mid-epoch checkpoint survived the kill"

    # 3. resume: must restart MID-epoch at the checkpointed cursor
    res_err = _run(
        [*BASE_ARGS, "--checkpoint-dir", vict_dir, "--resume", ckpt]
    )
    m = re.search(r"Resuming epoch (\d+) at batch (\d+)/800", res_err)
    assert m, res_err[-2000:]
    resume_at = (int(m.group(1)), int(m.group(2)))
    assert (0, 1) <= resume_at <= (1, 799)

    # 4. bit-identical trajectory: every post-resume (epoch, batch) loss
    # equals the control's, and the pre-kill victim losses do too
    res = _losses(res_err)
    expected = {k: v for k, v in ctrl.items() if k >= resume_at}
    assert expected, "control produced no comparable steps"
    for key, loss in expected.items():
        assert res.get(key) == loss, (
            f"loss diverged at {key}: resumed {res.get(key)} != control {loss}"
        )
    vict = _losses("".join(seen))
    for key, loss in vict.items():
        assert ctrl[key] == loss, f"victim diverged at {key} pre-kill"

    # 5. final state equality: metrics.jsonl last epoch records match the
    # control exactly (full-precision floats)
    def last_record(d):
        with open(os.path.join(d, "metrics.jsonl")) as f:
            return json.loads(f.readlines()[-1])

    ctrl_rec, res_rec = last_record(ctrl_dir), last_record(vict_dir)
    for k in ("epoch", "val_loss", "val_accuracy"):
        assert ctrl_rec[k] == res_rec[k], (k, ctrl_rec[k], res_rec[k])


@pytest.mark.slow
def test_torn_sharded_save_resumes_previous_intact_checkpoint(tmp_path):
    """SIGKILL between the shard writes and the manifest/pointer flip
    (graft-armor chaos crash point): the torn version is never committed,
    so the pointer still names the previous intact version and resume
    lands on it — no operator intervention, no fallback walk needed."""
    ckdir = str(tmp_path / "ck")
    args = [
        "--epochs", "1", "--num-samples", "640", "--batch-size", "2",
        "--log-every", "1", "--seed", "5", "--checkpoint-dir", ckdir,
        "--checkpoint-format", "sharded", "--save-every-steps", "1",
    ]
    plan = json.dumps({"faults": [
        {"kind": "kill", "at": "sharded-save:post-shards", "nth": 3},
    ]})
    victim = subprocess.run(
        [sys.executable, os.path.join(REPO, "train.py"), *args,
         "--chaos", plan],
        capture_output=True, text=True, env=_env(), cwd=REPO, timeout=600,
    )
    assert victim.returncode == -signal.SIGKILL, victim.stderr[-2000:]

    # saves 1 and 2 committed; save 3 died post-shards: its version dir
    # has shard files but no manifest, and the pointer still names save 2
    latest = os.path.join(ckdir, "latest_model.ckpt")
    assert os.path.isfile(latest)
    versions = sorted(os.listdir(latest + ".shards"))
    assert len(versions) == 3, versions
    torn = os.path.join(latest + ".shards", versions[-1])
    assert not os.path.exists(os.path.join(torn, "manifest.msgpack"))

    res_err = _run([*args, "--resume", latest])
    m = re.search(r"Resuming epoch (\d+) at batch (\d+)/40", res_err)
    assert m, res_err[-2000:]
    # batch 2 = the second (last intact) mid-epoch save's cursor
    assert (int(m.group(1)), int(m.group(2))) == (0, 2)


def test_torn_publish_sigkill_keeps_pointer_and_heals(tmp_path):
    """SIGKILL between the publish-channel artifact write and the LATEST
    pointer flip (graft-swap's torn window, robustness/publish.py): the
    torn version must stay invisible to readers — the pointer still
    names v1, so a polling fleet keeps serving it — and the next
    successful publish flips the pointer past the leftover, restoring
    the channel to fully healthy."""
    from distributed_pytorch_example_tpu.robustness.publish import (
        PublishChannel,
    )

    root = str(tmp_path / "chan")
    child = (
        "import sys\n"
        "from distributed_pytorch_example_tpu.robustness.publish import (\n"
        "    PublishChannel,\n"
        ")\n"
        "ch = PublishChannel(sys.argv[1])\n"
        "ch.publish_blob(b'payload-v1')\n"
        "ch.publish_blob(b'payload-v2')  # SIGKILLed before pointer flip\n"
        "print('UNREACHABLE')\n"
    )
    env = _env()
    env["DPX_CHAOS"] = json.dumps(
        {"faults": [{"kind": "torn-publish", "nth": 2}]}
    )
    proc = subprocess.run(
        [sys.executable, "-c", child, root],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=300,
    )
    assert proc.returncode == -signal.SIGKILL, (
        proc.returncode, proc.stderr[-2000:]
    )
    assert "UNREACHABLE" not in proc.stdout

    # the torn version's artifact landed on disk, but the commit point
    # (the pointer flip) never happened: readers cannot see it
    ch = PublishChannel(root)
    assert ch.versions() == ["00000001", "00000002"]
    assert os.path.exists(ch.artifact_path("00000002"))
    assert ch.pointer_version() == "00000001"
    assert ch.latest() == "00000001"
    assert ch.read("00000001") == b"payload-v1"
    state = ch.state()
    # torn-but-uncommitted leftovers do not even degrade the channel
    assert state["ok"] is True
    assert state["latest_intact"] == "00000001"
    assert [v["committed"] for v in state["versions"]] == [True, False]

    # the next publish numbers PAST the leftover and flips the pointer:
    # the channel is healthy again with no operator intervention
    healed = ch.publish_blob(b"payload-v3")
    assert healed == "00000003"
    assert ch.pointer_version() == "00000003"
    assert ch.latest() == "00000003"
    assert ch.state()["ok"] is True


def test_iter_from_matches_tail_of_full_iteration(devices):
    """loader.iter_from(k) yields exactly the batches a full iteration
    yields from step k on (the cursor contract resume relies on)."""
    from distributed_pytorch_example_tpu.data.loader import DeviceLoader
    from distributed_pytorch_example_tpu.data.synthetic import (
        SyntheticClassificationDataset,
    )

    ds = SyntheticClassificationDataset(num_samples=40)
    loader = DeviceLoader(ds, 8, num_shards=1, shard_id=0, seed=3)
    loader.set_epoch(2)
    full = [
        {k: np.asarray(v) for k, v in b.items()} for b in iter(loader)
    ]
    loader.set_epoch(2)
    tail = [
        {k: np.asarray(v) for k, v in b.items()} for b in loader.iter_from(2)
    ]
    assert len(tail) == len(full) - 2
    for a, b in zip(full[2:], tail):
        for k in a:
            np.testing.assert_array_equal(a[k], b[k])
    with pytest.raises(ValueError, match="start_step"):
        list(loader.iter_from(len(loader) + 1))


# ---------------------------------------------------------------------------
# graft-intake mid-epoch resume matrix: exact global sample sequence —
# no repeat, no skip — across prefetch, quarantine, and elastic reshape
# ---------------------------------------------------------------------------


class _RecordingDataset:
    """Map-style dataset whose batches ARE the served sample indices, so a
    test can read the exact global sample sequence off the batch stream."""

    def __init__(self, n):
        self.n = n

    def __len__(self):
        return self.n

    def get_batch(self, indices):
        idx = np.asarray(indices, np.int64)
        return {
            "x": idx.astype(np.float32).reshape(-1, 1),
            "y": idx.astype(np.int32),
        }


def _served(batches):
    """Per-step served global sample ids from a batch stream."""
    return [np.sort(np.asarray(b["y"]).reshape(-1)) for b in batches]


def test_resume_non_prefetch_aligned_start_with_prefetch(devices):
    """iter_from at a cursor that is NOT a multiple of the prefetch depth
    must still yield exactly the uninterrupted tail — the supervised
    worker's start cursor is the consumer cursor, not a queue boundary."""
    import threading

    from distributed_pytorch_example_tpu.data.loader import DeviceLoader

    ds = _RecordingDataset(64)
    loader = DeviceLoader(ds, 8, num_shards=1, shard_id=0, seed=9,
                          prefetch=3)
    loader.set_epoch(4)
    full = _served(iter(loader))
    loader.set_epoch(4)
    tail = _served(loader.iter_from(5))  # 5 % 3 != 0: mid-queue cursor
    assert len(tail) == len(full) - 5
    for a, b in zip(full[5:], tail):
        np.testing.assert_array_equal(a, b)
    # both iterations closed their supervised workers: no leaked threads
    assert not [
        t for t in threading.enumerate()
        if t.name.startswith("intake-") and t.is_alive()
    ]


def test_resume_with_quarantined_shard_via_loader_manifest(tmp_path, devices):
    """A checkpoint stamped after a quarantine must resume onto the SAME
    remapped sample stream: restore re-arms the quarantine set before the
    first batch, so the tail equals a control that trained with the shard
    quarantined from the start."""
    from distributed_pytorch_example_tpu.data import intake
    from distributed_pytorch_example_tpu.data.loader import DeviceLoader
    from distributed_pytorch_example_tpu.data.streaming import (
        StreamingImageShards,
        write_image_shards,
    )

    root = str(tmp_path / "shards")
    rng = np.random.default_rng(0)
    imgs = rng.integers(0, 256, (128, 4, 4, 3)).astype(np.uint8)
    labels = rng.integers(0, 9, 128).astype(np.int64)
    write_image_shards(root, [(imgs, labels)], shard_size=32, seal=True)

    def make_loader(quarantine):
        ds = StreamingImageShards(root)
        if quarantine:
            ds.quarantine(quarantine, reason="test")
        loader = DeviceLoader(ds, 16, shuffle=True, seed=3, prefetch=2,
                              num_shards=1, shard_id=0)
        loader.set_epoch(1)
        return ds, loader

    # control: shard 1 quarantined from the very start of the epoch
    _, control = make_loader([1])
    ctrl_batches = [
        {k: np.asarray(v) for k, v in b.items()} for b in iter(control)
    ]

    # "crashed" run stamped a manifest at batch 5 with shard 1 quarantined
    man_ds, man_loader = make_loader([1])
    man = intake.loader_manifest(man_loader, epoch=1, batch_in_epoch=5)
    assert man["quarantine"] == [1]

    # resume: FRESH dataset (no quarantine knowledge) + manifest restore
    fresh_ds, fresh = make_loader([])
    cursor = intake.restore_loader_state(fresh, man)
    assert cursor == 5 and fresh_ds.quarantined_shards == {1}
    for got, want in zip(fresh.iter_from(cursor), ctrl_batches[5:]):
        for k in want:
            np.testing.assert_array_equal(np.asarray(got[k]), want[k])


def test_elastic_dp8_to_dp4_resume_exact_global_sequence(devices):
    """Kill a dp8 run mid-epoch, resume on dp4: the combined pre-kill and
    post-resume global batches must serve every sample EXACTLY once, in
    the same per-step global order an uninterrupted run serves — the
    loader_manifest cursor is in global-batch steps, so it transfers
    across the reshape unchanged."""
    from distributed_pytorch_example_tpu.data.loader import DeviceLoader

    n, gbs, seed, epoch, cut = 128, 16, 7, 2, 3
    ds = _RecordingDataset(n)

    def shard_loaders(num_shards):
        loaders = []
        for sid in range(num_shards):
            ld = DeviceLoader(ds, gbs, num_shards=num_shards, shard_id=sid,
                              seed=seed, prefetch=2)
            ld.set_epoch(epoch)
            loaders.append(ld)
        return loaders

    # uninterrupted single-process control: per-step global sample sets
    control = shard_loaders(1)[0]
    ctrl = _served(iter(control))
    assert len(ctrl) == n // gbs

    # dp8 "run" serves global steps [0, cut); the kill lands there
    pre = [_served(ld.iter_from(0)) for ld in shard_loaders(8)]
    # dp4 resume serves global steps [cut, end) from the stamped cursor
    post = [_served(ld.iter_from(cut)) for ld in shard_loaders(4)]

    served = []
    for step in range(cut):
        served.append(np.sort(np.concatenate(
            [pre[sid][step] for sid in range(8)]
        )))
    for step in range(len(ctrl) - cut):
        served.append(np.sort(np.concatenate(
            [post[sid][step] for sid in range(4)]
        )))

    # same per-step global batch as the uninterrupted control...
    for step, (got, want) in enumerate(zip(served, ctrl)):
        np.testing.assert_array_equal(got, want, err_msg=f"step {step}")
    # ...and the epoch as a whole repeats no sample and skips none
    all_served = np.sort(np.concatenate(served))
    np.testing.assert_array_equal(all_served, np.arange(n))

"""Worker process for the true multi-process distributed test.

Launched (not collected) by tests/test_multiprocess.py: two of these rendezvous
via jax.distributed over localhost (the real runtime.initialize path), train a
sharded-FSDP MLP for one epoch with cross-process batch sharding, and write a
sharded checkpoint (per-process shard files + process-0 manifest/pointer —
the auto format at multi-host scale) through the async saver.

Topology comes from the same env contract the launcher uses
(NUM_PROCESSES / PROCESS_ID / COORDINATOR_ADDRESS — runtime/distributed.py).
"""

import json
import os
import sys

# one CPU device per process -> 2 global devices across the job
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=1"
).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import optax  # noqa: E402

import distributed_pytorch_example_tpu as dpx  # noqa: E402


def main():
    config = dpx.runtime.initialize()
    assert jax.process_count() == config.num_processes, (
        jax.process_count(), config.num_processes
    )
    mesh = dpx.runtime.make_mesh(dpx.runtime.MeshSpec(data=1, fsdp=-1))
    partitioner = dpx.parallel.fsdp(mesh)  # params sharded ACROSS processes

    dataset = dpx.data.SyntheticClassificationDataset(num_samples=256, seed=0)
    loader = dpx.data.DeviceLoader(dataset, 32, mesh=mesh, shuffle=True, seed=0)
    val = dpx.data.DeviceLoader(
        dpx.data.SyntheticClassificationDataset(num_samples=64, seed=1),
        32, mesh=mesh, shuffle=False,
    )

    from distributed_pytorch_example_tpu.telemetry import TelemetryConfig

    trainer = dpx.train.Trainer(
        dpx.models.SimpleNet(),
        dpx.train.ClassificationTask(),
        optax.adam(1e-3),
        partitioner=partitioner,
        checkpoint_dir=os.environ["DPX_TEST_CKPT_DIR"],
        log_every=1000,
        # graft-scope straggler path: clock samples at steps 3/5/7, the
        # boundary at steps 4/6/8 runs the cross-host step-time exchange
        telemetry=TelemetryConfig(every=2, sample_every=2),
    )
    history = trainer.fit(loader, val, epochs=1)

    # every process must agree on the global metrics (computed inside jit on
    # the globally sharded batch)
    summary = trainer.telemetry_summary
    print(json.dumps({
        "process": jax.process_index(),
        "n_devices": len(jax.devices()),
        "train_loss": history[-1]["train_loss"],
        "val_loss": history[-1]["val_loss"],
        "straggler": summary.get("straggler", {}),
        "grad_norm": summary.get("last_record", {}).get("grad_norm"),
    }))
    dpx.runtime.shutdown()


if __name__ == "__main__":
    sys.exit(main())

"""Train step, Trainer loop, checkpoint round-trip + resume.

The regression suite the reference lacks (SURVEY.md §4): checkpoint
round-trip (reference train.py:178-209), metric semantics (train.py:275-277),
and end-to-end fit on the fake 8-device mesh.
"""

import os

import jax
import numpy as np
import optax
import pytest

import distributed_pytorch_example_tpu as dpx
from distributed_pytorch_example_tpu.data.synthetic import _ArrayDataset
from distributed_pytorch_example_tpu.models import SimpleNet
from distributed_pytorch_example_tpu.train import (
    ClassificationTask,
    Trainer,
    build_train_step,
    init_state,
    load_checkpoint,
    save_checkpoint,
)


def learnable_dataset(n=256, d=16, classes=4, seed=0):
    """Labels derived from inputs, so loss can actually fall."""
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, d), dtype=np.float32)
    w = rng.standard_normal((d, classes), dtype=np.float32)
    y = np.argmax(x @ w, axis=1).astype(np.int32)
    return _ArrayDataset({"x": x, "y": y})


def make_trainer(mesh, d=16, classes=4, lr=1e-2, ckpt=None, log_every=100):
    model = SimpleNet(input_size=d, hidden_size=32, num_classes=classes)
    return Trainer(
        model,
        ClassificationTask(),
        optax.adam(lr),
        partitioner=dpx.parallel.data_parallel(mesh),
        checkpoint_dir=ckpt,
        log_every=log_every,
    )


def test_mlp_param_count_reference_parity(mesh_1d):
    """Reference SimpleNet has 269,322 params (train.py:32-50,235)."""
    trainer = Trainer(
        SimpleNet(),
        ClassificationTask(),
        optax.adam(1e-3),
        partitioner=dpx.parallel.data_parallel(mesh_1d),
    )
    state = trainer.init(np.zeros((2, 784), np.float32))
    n = sum(int(x.size) for x in jax.tree_util.tree_leaves(state.params))
    assert n == 269_322


def test_loss_decreases(mesh_1d):
    ds = learnable_dataset()
    loader = dpx.data.DeviceLoader(ds, 64, mesh=mesh_1d, seed=0)
    trainer = make_trainer(mesh_1d)
    history = trainer.fit(loader, epochs=5)
    assert history[-1]["train_loss"] < history[0]["train_loss"] * 0.7


def test_params_replicated_and_grads_reduced(mesh_1d):
    """DP contract: params stay identical on every device after a step."""
    ds = learnable_dataset()
    loader = dpx.data.DeviceLoader(ds, 64, mesh=mesh_1d, seed=0)
    trainer = make_trainer(mesh_1d)
    trainer.init(next(iter(loader))["x"])
    batch = next(iter(loader))
    state, metrics = trainer.train_step(trainer.state, batch)
    leaf = jax.tree_util.tree_leaves(state.params)[0]
    shards = [np.asarray(s.data) for s in leaf.addressable_shards]
    for s in shards[1:]:
        assert np.array_equal(shards[0], s)
    assert float(metrics["loss"]) > 0


def test_sharded_training_matches_single_device(mesh_1d):
    """Compiled all-reduce DP == single-device math (same batches, same rng)."""
    ds = learnable_dataset()
    single = jax.devices()[0]

    results = []
    for mesh in (mesh_1d, None):
        loader = dpx.data.DeviceLoader(ds, 64, mesh=mesh, shuffle=True, seed=3)
        trainer = make_trainer(mesh if mesh is not None else dpx.runtime.make_mesh(
            devices=[single]
        ))
        loader.set_epoch(0)
        it = iter(loader)
        first = next(it)
        trainer.init(first["x"])
        state = trainer.state
        for batch in [first] + [next(it) for _ in range(2)]:
            state, _ = trainer.train_step(state, batch)
        results.append(jax.device_get(state.params))

    flat_a = jax.tree_util.tree_leaves(results[0])
    flat_b = jax.tree_util.tree_leaves(results[1])
    for a, b in zip(flat_a, flat_b):
        np.testing.assert_allclose(a, b, rtol=2e-5, atol=2e-6)


def test_metrics_are_global_means(mesh_1d):
    """Global-batch mean == mean of per-shard means (train.py:275-277)."""
    ds = learnable_dataset(n=64)
    loader = dpx.data.DeviceLoader(ds, 64, mesh=mesh_1d, shuffle=False)
    trainer = make_trainer(mesh_1d)
    batch = next(iter(loader))
    trainer.init(batch["x"])
    metrics = trainer.eval_step(trainer.state, batch)
    # recompute on host from the full logical batch
    logits = trainer.model.apply(
        {"params": jax.device_get(trainer.state.params)},
        np.asarray(batch["x"]),
        train=False,
    )
    acc = 100.0 * np.mean(np.argmax(logits, -1) == np.asarray(batch["y"]))
    np.testing.assert_allclose(float(metrics["accuracy"]), acc, atol=1e-3)


def test_checkpoint_roundtrip(tmp_path, mesh_1d):
    ds = learnable_dataset()
    loader = dpx.data.DeviceLoader(ds, 64, mesh=mesh_1d, seed=0)
    trainer = make_trainer(mesh_1d)
    trainer.init(next(iter(loader))["x"])
    state0 = trainer.state
    path = str(tmp_path / "ck.ckpt")
    save_checkpoint(path, state0, epoch=7, loss=1.25, extra={"best_accuracy": 33.0})

    # clobber the live state, then restore
    clobbered = jax.tree_util.tree_map(
        lambda x: x * 0
        if hasattr(x, "dtype") and getattr(x.dtype, "kind", None) == "f"
        else x,
        state0,
    )
    restored, epoch, extra = load_checkpoint(path, clobbered)
    assert epoch == 7 and extra["best_accuracy"] == 33.0
    for a, b in zip(
        jax.tree_util.tree_leaves(jax.device_get(state0.params)),
        jax.tree_util.tree_leaves(jax.device_get(restored.params)),
    ):
        np.testing.assert_array_equal(a, b)
    # restored arrays carry the template's sharding
    leaf0 = jax.tree_util.tree_leaves(restored.params)[0]
    assert leaf0.sharding == jax.tree_util.tree_leaves(state0.params)[0].sharding


def test_fit_checkpoints_and_resume(tmp_path, mesh_1d):
    ds = learnable_dataset()
    ckdir = str(tmp_path / "ckpts")

    loader = dpx.data.DeviceLoader(ds, 64, mesh=mesh_1d, seed=0)
    val = dpx.data.DeviceLoader(ds, 64, mesh=mesh_1d, shuffle=False)
    t1 = make_trainer(mesh_1d, ckpt=ckdir)
    h1 = t1.fit(loader, val, epochs=2)
    assert os.path.exists(os.path.join(ckdir, "latest_model.ckpt"))
    assert os.path.exists(os.path.join(ckdir, "best_model.ckpt"))
    assert [r["epoch"] for r in h1] == [0, 1]

    # resume → continues at epoch 2, not 0
    t2 = make_trainer(mesh_1d, ckpt=ckdir)
    h2 = t2.fit(
        loader, val, epochs=4, resume=os.path.join(ckdir, "latest_model.ckpt")
    )
    assert [r["epoch"] for r in h2] == [2, 3]
    # training actually continued (step counter advanced past epoch 1)
    assert int(t2.state.step) == 4 * len(loader)


def test_resume_continues_after_finished_epoch(tmp_path, mesh_1d):
    """Resume semantics, pinned: the checkpoint saved at the end of epoch N
    is stamped N+1 and a resumed fit's FIRST epoch index is N+1 — the
    finished epoch is never re-run. Deliberate deviation from the
    reference, which stamps the finished epoch itself and re-trains it on
    resume (reference train.py:185,209,257); see train/checkpoint.py
    module docstring."""
    ds = learnable_dataset()
    ckdir = str(tmp_path / "ck")
    loader = dpx.data.DeviceLoader(ds, 64, mesh=mesh_1d, seed=0)
    val = dpx.data.DeviceLoader(ds, 64, mesh=mesh_1d, shuffle=False)
    t1 = make_trainer(mesh_1d, ckpt=ckdir)
    t1.fit(loader, val, epochs=3)  # runs epochs 0..2

    latest = os.path.join(ckdir, "latest_model.ckpt")
    _, saved_epoch, _ = load_checkpoint(latest, t1.state)
    assert saved_epoch == 3  # finished epoch 2, stamped 3 = next to run

    t2 = make_trainer(mesh_1d, ckpt=ckdir)
    h2 = t2.fit(loader, val, epochs=5, resume=latest)
    assert [r["epoch"] for r in h2] == [3, 4]  # continues AFTER, no re-run


def test_best_checkpoint_tracks_accuracy(tmp_path, mesh_1d):
    """best_model is only rewritten on val-accuracy improvement
    (train.py:292-300)."""
    ds = learnable_dataset()
    ckdir = str(tmp_path / "ck")
    loader = dpx.data.DeviceLoader(ds, 64, mesh=mesh_1d, seed=0)
    val = dpx.data.DeviceLoader(ds, 64, mesh=mesh_1d, shuffle=False)
    t = make_trainer(mesh_1d, ckpt=ckdir)
    t.fit(loader, val, epochs=3)
    best, best_epoch, extra = load_checkpoint(
        os.path.join(ckdir, "best_model.ckpt"), t.state
    )
    assert extra["best_accuracy"] > 0

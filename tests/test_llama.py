"""LLaMA-style model family: RoPE, RMSNorm, SwiGLU, GQA."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributed_pytorch_example_tpu.models.llama import Llama, RMSNorm
from distributed_pytorch_example_tpu.ops.rope import rope
from distributed_pytorch_example_tpu.runtime import MeshSpec, make_mesh

TINY = dict(
    vocab_size=101, max_len=64, model_dim=32, num_layers=2, num_heads=4,
    num_kv_heads=2, mlp_dim=64,
)


def test_rope_preserves_norm_and_is_position_dependent():
    x = jnp.asarray(
        np.random.default_rng(0).standard_normal((2, 16, 4, 8)), jnp.float32
    )
    y = rope(x)
    # rotation: per-position norms preserved
    np.testing.assert_allclose(
        np.linalg.norm(np.asarray(y), axis=-1),
        np.linalg.norm(np.asarray(x), axis=-1),
        atol=1e-5,
    )
    # position 0 is the identity rotation; later positions are not
    np.testing.assert_allclose(np.asarray(y[:, 0]), np.asarray(x[:, 0]), atol=1e-6)
    assert not np.allclose(np.asarray(y[:, 5]), np.asarray(x[:, 5]))


def test_rope_relative_property():
    """Dot products of rotated q/k depend only on relative offsets."""
    rng = np.random.default_rng(1)
    q1 = jnp.asarray(rng.standard_normal((1, 1, 1, 16)), jnp.float32)
    k1 = jnp.asarray(rng.standard_normal((1, 1, 1, 16)), jnp.float32)
    def dot_at(p_q, p_k):
        qr = rope(q1, positions=jnp.asarray([p_q]))
        kr = rope(k1, positions=jnp.asarray([p_k]))
        return float(jnp.sum(qr * kr))
    assert dot_at(3, 1) == pytest.approx(dot_at(10, 8), abs=1e-4)
    assert dot_at(3, 1) != pytest.approx(dot_at(3, 2), abs=1e-4)


def test_rmsnorm_matches_manual():
    x = jnp.asarray(np.random.default_rng(2).standard_normal((4, 8)), jnp.float32)
    mod = RMSNorm()
    variables = mod.init(jax.random.key(0), x)
    y = mod.apply(variables, x)
    expected = np.asarray(x) / np.sqrt(
        (np.asarray(x) ** 2).mean(-1, keepdims=True) + 1e-5
    )
    np.testing.assert_allclose(np.asarray(y), expected, atol=1e-5)


def test_llama_forward_shapes_and_param_structure():
    model = Llama(**TINY)
    tokens = jnp.zeros((2, 16), jnp.int32)
    variables = model.init(jax.random.key(0), tokens)
    logits = model.apply(variables, tokens)
    assert logits.shape == (2, 16, 101)
    p = variables["params"]["layer_0"]
    # GQA: kv projections are half the q projection (2 of 4 heads)
    assert p["attn"]["q"]["kernel"].shape == (32, 32)
    assert p["attn"]["k"]["kernel"].shape == (32, 16)
    # SwiGLU: gate/up/down, no biases
    assert set(p["mlp"].keys()) == {"gate", "up", "down"}
    assert "bias" not in p["mlp"]["gate"]


def test_llama_is_causal():
    """Future tokens cannot influence earlier logits."""
    model = Llama(**TINY)
    rng = np.random.default_rng(3)
    t1 = rng.integers(0, 101, (1, 16))
    t2 = t1.copy()
    t2[0, 10:] = (t2[0, 10:] + 1) % 101  # perturb the future
    variables = model.init(jax.random.key(0), jnp.asarray(t1, jnp.int32))
    l1 = model.apply(variables, jnp.asarray(t1, jnp.int32))
    l2 = model.apply(variables, jnp.asarray(t2, jnp.int32))
    np.testing.assert_allclose(
        np.asarray(l1[:, :10]), np.asarray(l2[:, :10]), atol=1e-5
    )


def test_llama_tensor_parallel_matches_single_device(devices):
    from distributed_pytorch_example_tpu.parallel.partition import (
        transformer_partitioner,
    )

    mesh = make_mesh(MeshSpec(data=4, tensor=2))
    model = Llama(**TINY)
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, 101, (4, 16)), jnp.int32
    )
    variables = model.init(jax.random.key(0), tokens)
    expected = model.apply(variables, tokens)
    part = transformer_partitioner(mesh)
    specs = part.tree_specs(variables)["params"]["layer_0"]["mlp"]
    assert specs["gate"]["kernel"] == jax.sharding.PartitionSpec(None, "tensor")
    sharded = jax.device_put(variables, part.tree_shardings(variables))
    out = jax.jit(lambda v, t: model.apply(v, t))(sharded, tokens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=2e-4)


def test_llama_trains_end_to_end(devices):
    import distributed_pytorch_example_tpu as dpx

    mesh = make_mesh(MeshSpec())
    model = Llama(**TINY)
    ds = dpx.data.SyntheticTokenDataset(num_samples=64, seq_len=16, vocab_size=101)
    loader = dpx.data.DeviceLoader(ds, 16, mesh=mesh, num_shards=1, shard_id=0)
    trainer = dpx.train.Trainer(
        model, dpx.train.CausalLMTask(), optax.adam(1e-2),
        partitioner=dpx.parallel.data_parallel(mesh),
    )
    history = trainer.fit(loader, epochs=3)
    assert history[-1]["train_loss"] < history[0]["train_loss"]


def test_gqa_through_model_matches_mha_shapes(devices):
    """GQA model output has full q-head arity despite fewer kv heads."""
    model = Llama(**{**TINY, "num_kv_heads": 1})
    tokens = jnp.zeros((2, 16), jnp.int32)
    variables = model.init(jax.random.key(0), tokens)
    assert model.apply(variables, tokens).shape == (2, 16, 101)

"""graft-lint: every rule fires exactly once on a seeded violation, a
clean tree produces zero findings, and the collective budget gate catches
a deliberately widened sharding end-to-end.

Tier-1 scope: AST/parser/jaxpr unit tests plus ONE cheap mesh-config
budget gate (data+fsdp+expert, ~7 s compile on the fake CPU mesh). The
full 14-config sweep runs under ``-m slow``.
"""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from distributed_pytorch_example_tpu.analysis import collectives as coll
from distributed_pytorch_example_tpu.analysis import pylint_rules
from distributed_pytorch_example_tpu.analysis import shardlint

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CHEAP_CONFIG = "data+fsdp+expert"


def _rules(findings):
    return [f.rule for f in findings]


# ---------------------------------------------------------------------------
# AST lints: seeded violations fire exactly once; escapes work
# ---------------------------------------------------------------------------


@pytest.mark.lint
def test_host_sync_item_fires_once():
    src = (
        "def step(loss):\n"
        "    history = []\n"
        "    history.append(loss.item())\n"
        "    return history\n"
    )
    findings = pylint_rules.lint_source("train/tasks.py", src)
    assert _rules(findings) == ["host-sync"]
    assert "tasks.py:3" in findings[0].where


@pytest.mark.lint
def test_host_sync_numpy_alias_and_device_get():
    src = (
        "import numpy as xp\n"
        "import jax as j\n"
        "def f(x):\n"
        "    a = xp.asarray(x)\n"
        "    b = j.device_get(x)\n"
        "    return a, b\n"
    )
    findings = pylint_rules.lint_source("ops/fused.py", src)
    assert _rules(findings) == ["host-sync", "host-sync"]


@pytest.mark.lint
def test_host_sync_outside_traced_scope_ignored():
    src = "def f(x):\n    return x.item()\n"
    assert pylint_rules.lint_source("runtime/logging.py", src) == []


@pytest.mark.lint
def test_host_sync_suppression_comment():
    src = (
        "def f(x):\n"
        "    return x.item()  # graft-lint: host-sync\n"
    )
    assert pylint_rules.lint_source("ops/fused.py", src) == []


@pytest.mark.lint
def test_mesh_size_guess_fires_once():
    src = (
        "def guard(n, mesh):\n"
        "    n_shard = n // data_parallel_size(mesh)\n"
        "    return n_shard * 4\n"
    )
    findings = pylint_rules.lint_source("ops/fused.py", src)
    assert _rules(findings) == ["mesh-size-guess"]


@pytest.mark.lint
def test_mesh_size_guess_mesh_shape_subscript():
    src = (
        "def guard(n, mesh):\n"
        "    return n // mesh.shape['data']\n"
    )
    findings = pylint_rules.lint_source("ops/fused.py", src)
    assert _rules(findings) == ["mesh-size-guess"]


@pytest.mark.lint
def test_mesh_size_guess_excused_by_sharding_inspection():
    # consulting the committed layout first makes the mesh span a
    # sanctioned fallback (the fixed chunked_ce pattern)
    src = (
        "def guard(x, n, mesh):\n"
        "    s = getattr(x, 'sharding', None)\n"
        "    if s is not None:\n"
        "        return shard_tokens(s)\n"
        "    return n // data_parallel_size(mesh)\n"
    )
    assert pylint_rules.lint_source("ops/fused.py", src) == []


@pytest.mark.lint
def test_mutable_default_fires_once_public_only():
    src = (
        "def public_api(x, cache={}):\n"
        "    return cache\n"
        "def _private(x, cache={}):\n"
        "    return cache\n"
    )
    findings = pylint_rules.lint_source("runtime/util.py", src)
    assert _rules(findings) == ["mutable-default"]
    assert "public_api" in findings[0].message


@pytest.mark.lint
def test_debug_callback_fires_in_scope():
    src = (
        "import jax\n"
        "def step(x):\n"
        "    jax.debug.print('x={}', x)\n"
        "    jax.debug.callback(lambda v: None, x)\n"
        "    return x\n"
    )
    findings = pylint_rules.lint_source("ops/fused.py", src)
    assert _rules(findings) == ["debug-callback", "debug-callback"]
    assert "sentinel" in findings[0].message  # points at the graft-scope path


@pytest.mark.lint
def test_debug_callback_from_import_and_alias_forms():
    src = (
        "from jax import debug\n"
        "import jax as j\n"
        "def step(x):\n"
        "    debug.callback(lambda v: None, x)\n"
        "    j.debug.print('{}', x)\n"
        "    return x\n"
    )
    findings = pylint_rules.lint_source("train/step.py", src)
    assert _rules(findings) == ["debug-callback", "debug-callback"]


@pytest.mark.lint
def test_debug_callback_suppression_and_scope():
    src = (
        "import jax\n"
        "def step(x):\n"
        "    jax.debug.print('x={}', x)  # graft-lint: debug-callback\n"
        "    return x\n"
    )
    assert pylint_rules.lint_source("ops/fused.py", src) == []
    # outside the hot-path scope (loop.py, scripts) the rule stays quiet
    src2 = "import jax\ndef f(x):\n    jax.debug.print('x', x)\n    return x\n"
    assert pylint_rules.lint_source("train/loop.py", src2) == []
    # plain print / unrelated .print attributes are not jax.debug
    src3 = "def f(x, log):\n    print(x)\n    log.print(x)\n    return x\n"
    assert pylint_rules.lint_source("ops/fused.py", src3) == []


@pytest.mark.lint
def test_nan_launder_fires_in_scope():
    src = (
        "import jax.numpy as jnp\n"
        "import numpy as np\n"
        "def step(g):\n"
        "    g = jnp.nan_to_num(g)\n"
        "    h = np.nan_to_num(g, nan=0.0)\n"
        "    return g, h\n"
    )
    findings = pylint_rules.lint_source("train/step.py", src)
    assert _rules(findings) == ["nan-launder", "nan-launder"]
    assert "launders" in findings[0].message
    # ops/ is in scope too
    assert _rules(
        pylint_rules.lint_source("ops/fused.py", src)
    ) == ["nan-launder", "nan-launder"]


@pytest.mark.lint
def test_nan_launder_suppression_and_scope():
    src = (
        "import jax.numpy as jnp\n"
        "def step(g):\n"
        "    return jnp.nan_to_num(g)  # graft-lint: nan-launder\n"
    )
    assert pylint_rules.lint_source("train/step.py", src) == []
    # outside ops//train/ (analysis tooling, scripts) the rule stays quiet
    src2 = "import numpy as np\ndef f(x):\n    return np.nan_to_num(x)\n"
    assert pylint_rules.lint_source("analysis/numerics.py", src2) == []
    # unrelated names don't trip it
    src3 = "def f(x):\n    return x.nan_guard()\n"
    assert pylint_rules.lint_source("train/step.py", src3) == []


@pytest.mark.lint
def test_ckpt_stamp_fires_on_unstamped_serialize():
    src = (
        "from flax import serialization\n"
        "def _write(path, state):\n"
        "    blob = serialization.msgpack_serialize({'params': state})\n"
        "    open(path, 'wb').write(blob)\n"
    )
    findings = pylint_rules.lint_source("train/checkpoint.py", src)
    assert _rules(findings) == ["ckpt-stamp"]
    assert "mesh-manifest stamp" in findings[0].message


@pytest.mark.lint
def test_ckpt_stamp_quiet_when_manifest_threaded():
    # referencing the stamp anywhere in the enclosing function sanctions
    # the write (keyword arg, name, or the payload-key string literal)
    for ref in (
        "    payload['mesh_manifest'] = stamp\n",
        "    use(mesh_manifest)\n",
    ):
        src = (
            "from flax import serialization\n"
            "def _write(path, payload, stamp, mesh_manifest=None):\n"
            + ref +
            "    return serialization.msgpack_serialize(payload)\n"
        )
        assert pylint_rules.lint_source("train/checkpoint.py", src) == []


@pytest.mark.lint
def test_ckpt_stamp_suppression_and_scope():
    src = (
        "from flax import serialization\n"
        "def _write(p):\n"
        "    return serialization.msgpack_serialize(p)"
        "  # graft-lint: ckpt-stamp\n"
    )
    assert pylint_rules.lint_source("train/checkpoint.py", src) == []
    # outside train/checkpoint.py (tools, tests) the rule stays quiet
    src2 = (
        "from flax import serialization\n"
        "def dump(p):\n"
        "    return serialization.msgpack_serialize(p)\n"
    )
    assert pylint_rules.lint_source("analysis/export.py", src2) == []


@pytest.mark.lint
def test_ckpt_stamp_real_checkpoint_module_lints_clean():
    # the acceptance gate: every committed checkpoint writer threads the
    # format-3 stamp (graft-elastic), so the shipped module has no findings
    path = os.path.join(
        REPO_ROOT, "distributed_pytorch_example_tpu", "train",
        "checkpoint.py",
    )
    with open(path) as f:
        src = f.read()
    assert pylint_rules.lint_source("train/checkpoint.py", src) == []


@pytest.mark.lint
def test_decode_gather_fires_on_pool_gather():
    """A serving/models function that touches the paged pool via
    take/dynamic_update_slice without routing through the fused dispatch
    is re-materializing the gathered cache — the cost the kernel exists
    to remove."""
    src = (
        "import jax.numpy as jnp\n"
        "def decode(pages_k, table):\n"
        "    return jnp.take(pages_k, table, axis=0)\n"
    )
    findings = pylint_rules.lint_source("models/transformer.py", src)
    assert _rules(findings) == ["decode-gather"]
    findings = pylint_rules.lint_source("serving/engine.py", src)
    assert _rules(findings) == ["decode-gather"]


@pytest.mark.lint
def test_decode_gather_quiet_with_fused_dispatch():
    # routing through the dispatcher sanctions pool access in the same
    # function (the dispatcher owns the gather fallback internally)
    src = (
        "import jax.numpy as jnp\n"
        "from x import paged_decode_attention\n"
        "def decode(q, pages_k, pages_v, table, lens):\n"
        "    pages_k = jax.lax.dynamic_update_slice(pages_k, q, (0,))\n"
        "    return paged_decode_attention(q, pages_k, pages_v, table, lens)\n"
    )
    assert pylint_rules.lint_source("models/transformer.py", src) == []


@pytest.mark.lint
def test_decode_gather_suppression_and_scope():
    src = (
        "import jax.numpy as jnp\n"
        "def decode(pages_k, table):\n"
        "    return jnp.take(pages_k, table, axis=0)"
        "  # graft-lint: decode-gather\n"
    )
    assert pylint_rules.lint_source("models/transformer.py", src) == []
    # outside serving//models/ (the reference implementation in ops/, a
    # test helper) the rule stays quiet
    src2 = (
        "import jax.numpy as jnp\n"
        "def reference(pages_k, table):\n"
        "    return jnp.take(pages_k, table, axis=0)\n"
    )
    assert pylint_rules.lint_source(
        "ops/pallas/paged_attention.py", src2
    ) == []
    # functions that never touch a pages_* identifier are not decode
    src3 = (
        "import jax.numpy as jnp\n"
        "def embed(table, ids):\n"
        "    return jnp.take(table, ids, axis=0)\n"
    )
    assert pylint_rules.lint_source("models/transformer.py", src3) == []


@pytest.mark.lint
def test_serve_dynamic_shape_fires_on_shape_branch_and_append():
    src = (
        "from functools import partial\n"
        "import jax\n"
        "@partial(jax.jit, static_argnums=(0,))\n"
        "def decode(model, cache, tokens):\n"
        "    out = []\n"
        "    if tokens.shape[1] > 1:\n"
        "        out.append(tokens)\n"
        "    return out\n"
    )
    findings = pylint_rules.lint_source("serving/engine.py", src)
    assert _rules(findings) == [
        "serve-dynamic-shape", "serve-dynamic-shape",
    ]
    assert "engine.py:6" in findings[0].where  # the .shape branch
    assert "engine.py:7" in findings[1].where  # the .append


@pytest.mark.lint
def test_serve_dynamic_shape_scope_suppression_and_host_code():
    # bare @jax.jit spelling also counts as a jitted region
    src = (
        "import jax\n"
        "@jax.jit\n"
        "def step(x):\n"
        "    while x.shape[0] > 1:  # graft-lint: serve-dynamic-shape\n"
        "        x = x[1:]\n"
        "    return x\n"
    )
    assert pylint_rules.lint_source("serving/engine.py", src) == []
    # the same source outside serving/ is out of scope
    src2 = src.replace("# graft-lint: serve-dynamic-shape", "")
    assert pylint_rules.lint_source("serving/engine.py", src2) != []
    assert pylint_rules.lint_source("telemetry/trace.py", src2) == []
    # host-side (un-jitted) scheduler code appends freely
    src3 = (
        "def admit(queue, slots):\n"
        "    admitted = []\n"
        "    if len(slots) > 0:\n"
        "        admitted.append(queue.popleft())\n"
        "    return admitted\n"
    )
    assert pylint_rules.lint_source("serving/scheduler.py", src3) == []


@pytest.mark.lint
def test_serve_real_engine_module_lints_clean():
    # the acceptance gate: the shipped engine keeps every shape decision
    # on the host (tables/lens/buckets), so the jitted programs are clean
    path = os.path.join(
        REPO_ROOT, "distributed_pytorch_example_tpu", "serving",
        "engine.py",
    )
    with open(path) as f:
        src = f.read()
    assert pylint_rules.lint_source("serving/engine.py", src) == []


@pytest.mark.lint
def test_serve_bare_clock_fires_on_direct_and_from_import_calls():
    src = (
        "import time\n"
        "from time import perf_counter as pc\n"
        "def tick(entry):\n"
        "    entry.t = time.time()\n"
        "    dt = pc()\n"
        "    return dt\n"
    )
    findings = pylint_rules.lint_source("serving/router.py", src)
    assert _rules(findings) == ["serve-bare-clock", "serve-bare-clock"]
    assert "router.py:4" in findings[0].where
    assert "router.py:5" in findings[1].where


@pytest.mark.lint
def test_serve_bare_clock_alias_module_and_all_clock_names():
    src = (
        "import time as t\n"
        "def tick():\n"
        "    a = t.monotonic()\n"
        "    b = t.perf_counter_ns(), t.monotonic()  # one per line\n"
        "    return a, b\n"
    )
    findings = pylint_rules.lint_source("serving/engine.py", src)
    assert _rules(findings) == ["serve-bare-clock", "serve-bare-clock"]
    assert "engine.py:3" in findings[0].where
    assert "engine.py:4" in findings[1].where


@pytest.mark.lint
def test_serve_bare_clock_quiet_on_injected_clock_and_sleep():
    # the sanctioned forms: a default-arg REFERENCE (injected clock,
    # fake-able in tests) and time.sleep (a wait, not a timestamp)
    src = (
        "import time\n"
        "def __init__(self, clock=time.monotonic, sleep=time.sleep):\n"
        "    self.clock = clock\n"
        "def pace(self):\n"
        "    time.sleep(0.01)\n"
        "    return self.clock()\n"
    )
    assert pylint_rules.lint_source("serving/router.py", src) == []


@pytest.mark.lint
def test_serve_bare_clock_scope_and_suppression():
    src = (
        "import time\n"
        "def tick():\n"
        "    return time.time()\n"
    )
    # out of scope: train-side code times steps however it likes
    assert pylint_rules.lint_source("train/loop.py", src) == []
    assert pylint_rules.lint_source("telemetry/steptime.py", src) == []
    src2 = src.replace(
        "time.time()", "time.time()  # graft-lint: serve-bare-clock"
    )
    assert pylint_rules.lint_source("serving/router.py", src2) == []


@pytest.mark.lint
def test_serve_bare_clock_real_serving_modules_clean():
    # the acceptance gate: every serving module reads time through its
    # injected clock (or the engine's _ts_us), never a bare module call
    serving_dir = os.path.join(
        REPO_ROOT, "distributed_pytorch_example_tpu", "serving"
    )
    for fname in sorted(os.listdir(serving_dir)):
        if not fname.endswith(".py"):
            continue
        with open(os.path.join(serving_dir, fname)) as f:
            src = f.read()
        findings = [
            fi for fi in pylint_rules.lint_source(f"serving/{fname}", src)
            if fi.rule == "serve-bare-clock"
        ]
        assert findings == [], [fi.render() for fi in findings]


@pytest.mark.lint
def test_fleet_unbounded_wait_fires_on_bare_waits():
    src = (
        "def pump(inbox, done, worker):\n"
        "    req = inbox.get()\n"
        "    done.wait()\n"
        "    worker.join()\n"
        "    return req\n"
    )
    findings = pylint_rules.lint_source("serving/fleet.py", src)
    assert _rules(findings) == ["fleet-unbounded-wait"] * 3
    assert "fleet.py:2" in findings[0].where


@pytest.mark.lint
def test_fleet_unbounded_wait_quiet_on_bounded_and_lookalikes():
    # timeout kwarg, non-blocking get, dict.get, str.join: all fine
    src = (
        "def pump(inbox, done, worker, table, parts):\n"
        "    a = inbox.get(timeout=1.0)\n"
        "    b = inbox.get(block=False)\n"
        "    done.wait(0.05)\n"
        "    worker.join(timeout=5.0)\n"
        "    c = table.get('key')\n"
        "    d = ','.join(parts)\n"
        "    return a, b, c, d\n"
    )
    assert pylint_rules.lint_source("serving/router.py", src) == []


@pytest.mark.lint
def test_fleet_unbounded_wait_scope_and_suppression():
    src = (
        "def pump(inbox):\n"
        "    return inbox.get()\n"
    )
    # scope is serving/ + data/ (the supervised thread paths): a
    # training-side queue may still block forever
    assert pylint_rules.lint_source("train/loop.py", src) == []
    supp = src.replace(
        "inbox.get()", "inbox.get()  # graft-lint: fleet-unbounded-wait"
    )
    assert pylint_rules.lint_source("serving/fleet.py", supp) == []


@pytest.mark.lint
def test_fleet_unbounded_wait_covers_data_scope():
    # graft-intake extended the rule to data/: a prefetch-path wait
    # without a timeout can hang a training step on a dead decode worker
    src = (
        "def pump(q, worker):\n"
        "    item = q.get()\n"
        "    worker.join()\n"
        "    return item\n"
    )
    findings = pylint_rules.lint_source("data/intake.py", src)
    assert _rules(findings) == ["fleet-unbounded-wait"] * 2
    bounded = (
        "def pump(q, worker):\n"
        "    item = q.get(timeout=0.2)\n"
        "    worker.join(timeout=5.0)\n"
        "    return item\n"
    )
    assert pylint_rules.lint_source("data/loader.py", bounded) == []
    supp = src.replace(
        "q.get()", "q.get()  # graft-lint: fleet-unbounded-wait"
    ).replace(
        "worker.join()", "worker.join()  # graft-lint: fleet-unbounded-wait"
    )
    assert pylint_rules.lint_source("data/intake.py", supp) == []


@pytest.mark.lint
def test_swap_unversioned_params_fires_on_adhoc_assignments():
    # flipping live engine weights anywhere but __init__/install_params
    # skips the version retag + drain bracket (graft-swap contract)
    src = (
        "class Engine:\n"
        "    def refresh(self, new):\n"
        "        self.params = new\n"
        "        self.draft_params, other = new, 1\n"
        "        self.params += 0\n"
        "def hotfix(handle, new):\n"
        "    handle.engine.params = new\n"
    )
    findings = pylint_rules.lint_source("serving/swap.py", src)
    assert _rules(findings) == ["swap-unversioned-params"] * 4
    assert "swap.py:3" in findings[0].where
    assert "install_params" in findings[0].message


@pytest.mark.lint
def test_swap_unversioned_params_sanctioned_and_lookalikes_quiet():
    # __init__ and install_params are THE sanctioned mutation sites; a
    # subscript keyed by .params reads, not rebinds, the live pytree
    src = (
        "class Engine:\n"
        "    def __init__(self, params):\n"
        "        self.params = params\n"
        "        self.draft_params = None\n"
        "    def install_params(self, params, version):\n"
        "        self.params = params\n"
        "        self.draft_params = params\n"
        "    def lookup(self, cache, new):\n"
        "        cache[self.params] = new\n"
        "        hyper = new.params\n"
        "        return hyper\n"
    )
    assert pylint_rules.lint_source("serving/engine.py", src) == []


@pytest.mark.lint
def test_swap_unversioned_params_scope_and_suppression():
    src = (
        "def adopt(trainer, new):\n"
        "    trainer.state.params = new\n"
    )
    # out of scope: the trainer rebinds its own state params freely
    assert pylint_rules.lint_source("train/loop.py", src) == []
    supp = src.replace(
        "= new", "= new  # graft-lint: swap-unversioned-params"
    )
    assert pylint_rules.lint_source("serving/swap.py", supp) == []


@pytest.mark.lint
def test_swap_real_serving_modules_clean():
    # the acceptance gate: every shipped serving module mutates live
    # params only through __init__/install_params
    serving_dir = os.path.join(
        REPO_ROOT, "distributed_pytorch_example_tpu", "serving"
    )
    for fname in sorted(os.listdir(serving_dir)):
        if not fname.endswith(".py"):
            continue
        with open(os.path.join(serving_dir, fname)) as f:
            src = f.read()
        findings = [
            fi for fi in pylint_rules.lint_source(f"serving/{fname}", src)
            if fi.rule == "swap-unversioned-params"
        ]
        assert findings == [], [fi.render() for fi in findings]


@pytest.mark.lint
def test_wire_raw_collective_fires_in_step_scope():
    # a raw gradient collective in the step bypasses the WireConfig
    # dispatch — fp32 payloads regardless of --wire int8-block
    src = (
        "from jax import lax\n"
        "def sync(g):\n"
        "    g = lax.psum_scatter(g, 'data', scatter_dimension=0)\n"
        "    return lax.psum(g, 'data')\n"
    )
    findings = pylint_rules.lint_source("train/step.py", src)
    assert _rules(findings) == ["wire-raw-collective"] * 2
    assert "parallel/wire.py" in findings[0].message


@pytest.mark.lint
def test_wire_raw_collective_scope_suppression_and_lookalikes():
    src = (
        "from jax import lax\n"
        "def sync(g):\n"
        "    return lax.psum(g, 'data')\n"
    )
    # only train/step.py is in scope: wire.py ITSELF implements the
    # fallbacks with raw collectives, as do other manual regions
    assert pylint_rules.lint_source("parallel/wire.py", src) == []
    assert pylint_rules.lint_source("ops/pallas/collectives.py", src) == []
    supp = src.replace(
        "lax.psum(g, 'data')",
        "lax.psum(g, 'data')  # graft-lint: wire-raw-collective",
    )
    assert pylint_rules.lint_source("train/step.py", supp) == []
    # the sanctioned spellings never fire: the bucketed sync dispatcher
    # and the metrics pmean (the per-leaf wire_* wrappers are wire-raw
    # clean but fire the inline-grad-sync rule in step scope — see
    # test_inline_grad_sync_* below)
    ok = (
        "from jax import lax\n"
        "from distributed_pytorch_example_tpu.parallel import wire\n"
        "def sync(g, dims, m):\n"
        "    g = wire.sync_grads(g, dims, 'data')\n"
        "    return g, lax.pmean(m, 'data')\n"
    )
    assert pylint_rules.lint_source("train/step.py", ok) == []


@pytest.mark.lint
def test_inline_grad_sync_fires_on_per_leaf_wire_calls_in_step():
    # the bucketed comm/compute-overlap schedule owns the gradient-sync
    # issue order: a per-leaf wire_* call added back to the step is an
    # inline collective that serializes against the whole backward
    src = (
        "from distributed_pytorch_example_tpu.parallel import wire\n"
        "def body(g):\n"
        "    return wire.wire_psum_scatter(g, 'data', scatter_dimension=0)\n"
    )
    findings = pylint_rules.lint_source("train/step.py", src)
    assert _rules(findings) == ["inline-grad-sync"]
    assert "sync_grads" in findings[0].message
    # bare-name calls and every inline collective spelling fire too
    for call in ("wire_psum_scatter(g, 'data')",
                 "wire.wire_all_gather(g, 'data')",
                 "wire_psum(g, 'data')"):
        one = f"def body(g):\n    return {call}\n"
        assert _rules(pylint_rules.lint_source("train/step.py", one)) == [
            "inline-grad-sync"
        ], call


@pytest.mark.lint
def test_inline_grad_sync_sanctioned_scope_and_suppression():
    # sync_grads/replicate_params are the sanctioned entry points
    ok = (
        "from distributed_pytorch_example_tpu.parallel import wire\n"
        "def body(g, dims):\n"
        "    g = wire.sync_grads(g, dims, 'data')\n"
        "    return wire.replicate_params(g, None, None)\n"
    )
    assert pylint_rules.lint_source("train/step.py", ok) == []
    # only train/step.py is in scope: the wire module IS the dispatcher
    bad = "def body(g):\n    return wire_psum_scatter(g, 'data')\n"
    assert pylint_rules.lint_source("parallel/wire.py", bad) == []
    assert pylint_rules.lint_source("parallel/api.py", bad) == []
    supp = bad.replace(
        "'data')", "'data')  # graft-lint: inline-grad-sync"
    )
    assert pylint_rules.lint_source("train/step.py", supp) == []


@pytest.mark.lint
def test_plan_overlay_fires_on_literal_specs():
    # graft-plan: a string-literal PartitionSpec in the shipped sharding
    # surfaces is an overlay the static planner cannot score
    src = (
        "from jax.sharding import PartitionSpec as P\n"
        "def rules(mesh):\n"
        "    a = P('data', None)\n"
        "    b = PartitionSpec(None, 'tensor')\n"
        "    c = P(('data', 'fsdp'), None)\n"
        "    d = P([None, 'tensor'])\n"
        "    return a, b, c, d\n"
    )
    findings = pylint_rules.lint_source("parallel/api.py", src)
    assert _rules(findings) == ["plan-overlay"] * 4
    assert "PlanSpec" in findings[0].message
    # same scope rule for the step module
    step = pylint_rules.lint_source(
        "train/step.py", "def f():\n    return P('data')\n"
    )
    assert _rules(step) == ["plan-overlay"]


@pytest.mark.lint
def test_plan_overlay_dynamic_construction_passes():
    # the sanctioned pattern: specs built from the plan's mesh axes, not
    # hard-coded axis strings — P(), P(*entries), P(axis_var)
    ok = (
        "from jax.sharding import PartitionSpec as P\n"
        "def rules(entries, axis):\n"
        "    a = P()\n"
        "    b = P(*entries)\n"
        "    c = P(axis, None)\n"
        "    d = P(tuple(entries), None)\n"
        "    return a, b, c, d\n"
    )
    assert pylint_rules.lint_source("parallel/api.py", ok) == []


@pytest.mark.lint
def test_plan_overlay_scope_and_suppression():
    src = "def f():\n    return P('data')\n"
    # partition.py / plan.py themselves NAME the axes — they are the
    # lowering, not an overlay; only api.py and step.py are in scope
    assert pylint_rules.lint_source("parallel/partition.py", src) == []
    assert pylint_rules.lint_source("parallel/plan.py", src) == []
    assert pylint_rules.lint_source("models/gpt2.py", src) == []
    supp = "def f():\n    return P('data')  # graft-lint: plan-overlay\n"
    assert pylint_rules.lint_source("parallel/api.py", supp) == []


@pytest.mark.lint
def test_plan_overlay_real_modules_lint_clean():
    # the acceptance gate: the shipped api.py and step.py lower every
    # sharding through PlanSpec — no literal overlays remain
    for rel in (("parallel", "api.py"), ("train", "step.py")):
        path = os.path.join(
            REPO_ROOT, "distributed_pytorch_example_tpu", *rel
        )
        with open(path) as fh:
            src = fh.read()
        assert pylint_rules.lint_source("/".join(rel), src) == [], rel


@pytest.mark.lint
def test_fleet_real_modules_lint_clean():
    # the acceptance gate: the shipped fleet/router layers carry a
    # timeout on every blocking wait, as committed
    for mod in ("fleet.py", "router.py", "engine.py"):
        path = os.path.join(
            REPO_ROOT, "distributed_pytorch_example_tpu", "serving", mod,
        )
        with open(path) as fh:
            src = fh.read()
        assert pylint_rules.lint_source(f"serving/{mod}", src) == [], mod


@pytest.mark.lint
def test_data_real_modules_lint_clean():
    # the acceptance gate for the data/ extension: the shipped input
    # plane carries a timeout on every blocking wait, as committed
    for mod in ("intake.py", "loader.py", "streaming.py", "text.py"):
        path = os.path.join(
            REPO_ROOT, "distributed_pytorch_example_tpu", "data", mod,
        )
        with open(path) as fh:
            src = fh.read()
        assert pylint_rules.lint_source(f"data/{mod}", src) == [], mod


@pytest.mark.lint
def test_real_instrumented_step_lints_clean():
    # the acceptance gate: the sentinel-instrumented train step passes the
    # full AST rule set (host-sync AND debug-callback) as committed
    path = os.path.join(
        REPO_ROOT, "distributed_pytorch_example_tpu", "train", "step.py"
    )
    with open(path) as fh:
        src = fh.read()
    assert pylint_rules.lint_source("train/step.py", src) == []


@pytest.mark.lint
def test_clean_package_zero_ast_findings():
    assert pylint_rules.lint_package() == []


# ---------------------------------------------------------------------------
# HLO collective parser + budget comparator (pure string/dict logic)
# ---------------------------------------------------------------------------

_HLO_FIXTURE = """\
HloModule jit_step, input_output_alias={ {0}: (0, {}, may-alias), {3}: (2, {}, may-alias) }

ENTRY main {
  %p0 = f32[4,16]{1,0} parameter(0)
  %all-reduce = f32[4,16]{1,0} all-reduce(f32[4,16]{1,0} %p0)
  %reduce = f32[] reduce(f32[4,16]{1,0} %all-reduce, f32[] %c)
  %ag-start = (f32[4,16]{1,0}, f32[8,16]{1,0}) all-gather-start(f32[4,16]{1,0} %p0)
  %ag-done = f32[8,16]{1,0} all-gather-done((f32[4,16]{1,0}, f32[8,16]{1,0}) %ag-start)
  %rs = bf16[2,16]{1,0} reduce-scatter(bf16[4,16]{1,0} %x)
  ROOT %cp = f32[4,16]{1,0} collective-permute(f32[4,16]{1,0} %all-reduce)
}
"""


@pytest.mark.lint
def test_parse_collectives_counts_and_bytes():
    got = coll.parse_collectives(_HLO_FIXTURE)
    # the `reduce(... %all-reduce ...)` operand must NOT count as a second
    # all-reduce (ops are matched in the `= <shape> <op>(` position)
    assert got["all-reduce"] == {"count": 1, "bytes": 4 * 16 * 4}
    # -start/-done async pair counts once, bytes from the full start tuple
    assert got["all-gather"]["count"] == 1
    assert got["all-gather"]["bytes"] == (4 * 16 + 8 * 16) * 4
    assert got["reduce-scatter"] == {"count": 1, "bytes": 2 * 16 * 2}
    assert got["collective-permute"]["count"] == 1
    assert "reduce" not in got  # plain reduce is not a collective


@pytest.mark.lint
def test_alias_parse():
    assert shardlint.aliased_parameter_numbers(_HLO_FIXTURE) == {0, 2}
    assert shardlint.aliased_parameter_numbers(
        "HloModule bare\nENTRY e {}\n"
    ) is None


@pytest.mark.lint
def test_compare_budgets_count_increase_is_violation():
    committed = {"all-reduce": {"count": 2, "bytes": 100}}
    measured = {"all-reduce": {"count": 3, "bytes": 100}}
    v, notes = coll.compare_budgets(committed, measured, config="cfg")
    assert _rules(v) == ["comm-budget-count"]
    assert v[0].config == "cfg" and v[0].where == "all-reduce"


@pytest.mark.lint
def test_compare_budgets_byte_tolerance():
    committed = {"all-gather": {"count": 1, "bytes": 1000}}
    within = {"all-gather": {"count": 1, "bytes": 1040}}
    beyond = {"all-gather": {"count": 1, "bytes": 1100}}
    assert coll.compare_budgets(committed, within)[0] == []
    v, _ = coll.compare_budgets(committed, beyond)
    assert _rules(v) == ["comm-budget-bytes"]


@pytest.mark.lint
def test_compare_budgets_new_kind_and_improvement():
    committed = {"all-reduce": {"count": 2, "bytes": 100}}
    measured = {
        "all-reduce": {"count": 1, "bytes": 50},
        "all-to-all": {"count": 1, "bytes": 10},
    }
    v, notes = coll.compare_budgets(committed, measured)
    assert _rules(v) == ["comm-budget-count", "comm-budget-bytes"]
    assert all(f.where == "all-to-all" for f in v)  # the NEW kind fails
    assert any("improvement" in n for n in notes)  # the decrease is a note


@pytest.mark.lint
def test_parse_markers_greps_named_scopes():
    text = (
        'HloModule m\n fusion.1 = f32[4]{0} fusion(...), metadata='
        '{op_name="jit(step)/transpose/1f1b_stash_apply/dot_general"}\n'
    )
    assert coll.parse_markers(text) == {
        "1f1b_stash_apply": True, "1f1b_recompute_apply": False,
        "paged_decode_fused": False,
    }


@pytest.mark.lint
def test_compare_budgets_stash_signature():
    """The 1f1b-stash structural contract: the stash marker must be
    present and the recompute marker absent — byte/count budgets cannot
    catch a silent fallback (it changes no collective at all)."""
    committed = {"collective-permute": {"count": 4, "bytes": 100}}
    measured = {"collective-permute": {"count": 4, "bytes": 100}}
    ok = {"1f1b_stash_apply": True, "1f1b_recompute_apply": False}
    fell_back = {"1f1b_stash_apply": False, "1f1b_recompute_apply": True}

    v, _ = coll.compare_budgets(
        committed, measured, signature="1f1b-stash", markers=ok
    )
    assert v == []
    v, _ = coll.compare_budgets(
        committed, measured, signature="1f1b-stash", markers=fell_back
    )
    assert _rules(v) == [
        "comm-1f1b-stash-signature", "comm-1f1b-stash-signature"
    ]
    assert {f.where for f in v} == {
        "1f1b_stash_apply", "1f1b_recompute_apply"
    }
    # no markers at all (e.g. a hand-edited budget refresh): still loud
    v, _ = coll.compare_budgets(
        committed, measured, signature="1f1b-stash", markers=None
    )
    assert _rules(v) == ["comm-1f1b-stash-signature"]
    # without the signature the same marker drift is invisible
    assert coll.compare_budgets(committed, measured, markers=fell_back)[0] \
        == []


@pytest.mark.lint
def test_compare_budgets_wire_signature():
    """The wire-int8-step structural contract: an s8 collective payload,
    the re-replication all-gather, and the >=3x analytic ratio must all
    hold — a silent fp32 fallback changes no count/byte ratchet (the
    fp32 collectives fit comfortably inside a stale compressed budget's
    tolerance on this toy scale), so only the signature can catch it."""
    committed = {
        "all-to-all": {"count": 40, "bytes": 4000},
        "all-gather": {"count": 20, "bytes": 2000},
    }
    measured = dict(committed)
    ok_dtypes = {
        "all-to-all": {"s8": 3000, "bf16": 1000},
        "all-gather": {"s8": 1500, "bf16": 500},
    }
    ok_wire = {"wire_compression_ratio": 3.97}

    v, _ = coll.compare_budgets(
        committed, measured, signature="wire-int8-step",
        dtypes=ok_dtypes, wire=ok_wire,
    )
    assert v == []

    # silent fp32 fallback: all-f32 payloads + no compression ratio
    v, _ = coll.compare_budgets(
        committed, measured, signature="wire-int8-step",
        dtypes={"all-to-all": {"f32": 4000}}, wire=None,
    )
    assert _rules(v) == ["comm-wire-signature"] * 2
    assert {f.where for f in v} == {"s8-payload", "wire_compression_ratio"}

    # no dtype breakdown at all (hand-edited budget refresh): still loud
    v, _ = coll.compare_budgets(
        committed, measured, signature="wire-int8-step",
        dtypes=None, wire=ok_wire,
    )
    assert _rules(v) == ["comm-wire-signature"]
    assert v[0].where == "s8-payload"

    # the param re-replication all-gather must survive compression
    v, _ = coll.compare_budgets(
        committed, {"all-to-all": {"count": 40, "bytes": 4000}},
        signature="wire-int8-step", dtypes=ok_dtypes, wire=ok_wire,
    )
    assert any(f.where == "all-gather" for f in v)

    # a sub-3x ratio fails even with the s8 payload present
    v, _ = coll.compare_budgets(
        committed, measured, signature="wire-int8-step",
        dtypes=ok_dtypes, wire={"wire_compression_ratio": 2.4},
    )
    assert _rules(v) == ["comm-wire-signature"]
    assert v[0].where == "wire_compression_ratio"

    # without the signature the fp32 fallback sails through: the
    # signature is load-bearing, not redundant with the ratchet
    v, _ = coll.compare_budgets(
        committed, measured, dtypes={"all-to-all": {"f32": 4000}},
    )
    assert v == []


@pytest.mark.lint
def test_compare_budgets_paged_decode_signature():
    """The paged-decode structural contract: serve/decode must carry the
    fused-dispatch named-scope marker. A silent fall-back to gathering
    the whole pool moves no collective bytes on a replicated pool — only
    the signature catches it."""
    committed = {"all-reduce": {"count": 8, "bytes": 17408}}
    measured = {"all-reduce": {"count": 8, "bytes": 17408}}
    ok = {"paged_decode_fused": True}
    fell_back = {"paged_decode_fused": False}

    v, _ = coll.compare_budgets(
        committed, measured, signature="paged-decode-fused", markers=ok
    )
    assert v == []
    v, _ = coll.compare_budgets(
        committed, measured, signature="paged-decode-fused",
        markers=fell_back,
    )
    assert _rules(v) == ["comm-paged-decode-signature"]
    assert v[0].where == "paged_decode_fused"
    # no markers at all (hand-edited budget refresh): still loud
    v, _ = coll.compare_budgets(
        committed, measured, signature="paged-decode-fused", markers=None
    )
    assert _rules(v) == ["comm-paged-decode-signature"]
    # without the signature the marker's absence is invisible
    assert coll.compare_budgets(
        committed, measured, markers=fell_back
    )[0] == []

@pytest.mark.lint
def test_parse_collective_dtypes_breakdown():
    got = coll.parse_collective_dtypes(_HLO_FIXTURE)
    assert got["all-reduce"] == {"f32": 4 * 16 * 4}
    # async pair counts once, from the start tuple's full byte set
    assert got["all-gather"] == {"f32": (4 * 16 + 8 * 16) * 4}
    assert got["reduce-scatter"] == {"bf16": 2 * 16 * 2}
    s8_fixture = (
        "HloModule m\nENTRY e {\n"
        "  %a2a = s8[4,64]{1,0} all-to-all(s8[4,64]{1,0} %q)\n"
        "  %sc = bf16[4,1]{1,0} all-to-all(bf16[4,1]{1,0} %s)\n"
        "}\n"
    )
    got = coll.parse_collective_dtypes(s8_fixture)
    assert got["all-to-all"] == {"s8": 4 * 64, "bf16": 4 * 1 * 2}


# ---------------------------------------------------------------------------
# jaxpr numerics lint
# ---------------------------------------------------------------------------


def test_bf16_upcast_seeded_fires():
    def f(x):
        big = x.astype(jnp.float32)  # (512, 256) = 128k elements
        return big.sum()

    jaxpr = jax.make_jaxpr(f)(
        jax.ShapeDtypeStruct((512, 256), jnp.bfloat16)
    )
    findings = shardlint.lint_dtype_promotions(jaxpr)
    assert _rules(findings) == ["bf16-upcast"]
    assert "(512, 256)" in findings[0].message


def test_bf16_upcast_small_and_allowlisted_pass():
    def f(x):
        return x.astype(jnp.float32).sum()

    small = jax.make_jaxpr(f)(
        jax.ShapeDtypeStruct((8, 8), jnp.bfloat16)
    )
    assert shardlint.lint_dtype_promotions(small) == []
    big = jax.make_jaxpr(f)(
        jax.ShapeDtypeStruct((512, 256), jnp.bfloat16)
    )
    assert shardlint.lint_dtype_promotions(
        big, allowlist=(r"test_graft_lint\.py",)
    ) == []


def test_flagship_numerics_clean():
    # the bf16 flagship-shaped step carries only allowlisted f32 islands
    jaxpr = shardlint.flagship_numerics_jaxpr()
    findings = shardlint.lint_dtype_promotions(jaxpr)
    assert findings == []


# ---------------------------------------------------------------------------
# donation + replication lints (compiled on the fake CPU backend)
# ---------------------------------------------------------------------------


def test_dropped_donation_seeded(devices):
    def f(x):
        return x[::2] * 2.0  # output shape != input: donation must drop

    x = jnp.zeros((128, 256), jnp.float32)  # 128 KB, above the floor
    lowered = jax.jit(f, donate_argnums=0).lower(x)
    findings = shardlint.lint_dropped_donation(lowered, lowered.compile())
    assert _rules(findings) == ["dropped-donation"]


def test_dropped_donation_clean(devices):
    def f(x):
        return x + 1.0

    x = jnp.zeros((128, 256), jnp.float32)
    lowered = jax.jit(f, donate_argnums=0).lower(x)
    assert shardlint.lint_dropped_donation(lowered, lowered.compile()) == []


def test_replicated_large_param_seeded(mesh_2x2x2):
    from distributed_pytorch_example_tpu.parallel.partition import (
        transformer_partitioner,
    )

    partitioner = transformer_partitioner(mesh_2x2x2)
    big = jax.device_put(
        jnp.zeros((512, 512), jnp.float32),  # 1 MB, rule spans tensor=2
        NamedSharding(mesh_2x2x2, P()),
    )
    params = {"decoder": {"attn": {"q": {"kernel": big}}}}
    findings = shardlint.lint_replicated_params(params, partitioner)
    assert _rules(findings) == ["replicated-large-param"]
    assert "attn/q/kernel" in findings[0].where

    placed = jax.device_put(
        jnp.zeros((512, 512), jnp.float32),
        NamedSharding(mesh_2x2x2, P(None, "tensor")),
    )
    assert shardlint.lint_replicated_params(
        {"decoder": {"attn": {"q": {"kernel": placed}}}}, partitioner
    ) == []


def test_replicated_opt_state_zero1_floor_boundary(mesh_2x2x2):
    """The ZeRO-1 overlay's size floor is strict: a moment EXACTLY at
    ``opt_shard_min_size`` elements is sharded by the overlay (so its
    replicated placement is flagged); one element under the floor stays
    replicated BY DESIGN and must not be flagged. Guards the `<` in
    ``parallel/api.py zero1_dim`` against an off-by-one regression."""
    from distributed_pytorch_example_tpu.parallel.api import data_parallel

    n = 128 * 128  # leaf element count, 64 KiB f32
    moment = jax.device_put(
        jnp.zeros((128, 128), jnp.float32), NamedSharding(mesh_2x2x2, P())
    )
    opt_state = {"mu": {"decoder": {"mlp": {"wi": {"kernel": moment}}}}}

    at_floor = data_parallel(
        mesh_2x2x2, dp_shard_opt_state=True, opt_shard_min_size=n
    )
    findings = shardlint.lint_replicated_params(
        opt_state, at_floor, min_bytes=1024, path_prefix="opt_state"
    )
    assert _rules(findings) == ["replicated-large-param"]
    assert findings[0].where.startswith("opt_state/")

    above_floor = data_parallel(
        mesh_2x2x2, dp_shard_opt_state=True, opt_shard_min_size=n + 1
    )
    assert shardlint.lint_replicated_params(
        opt_state, above_floor, min_bytes=1024, path_prefix="opt_state"
    ) == []


# ---------------------------------------------------------------------------
# collective budget gate: one cheap config in tier-1, perturbation check
# ---------------------------------------------------------------------------


def _build_case(name, devices):
    sys.path.insert(0, REPO_ROOT)
    import __graft_entry__ as entry

    config = next(
        c for c in entry.DRYRUN_CONFIGS
        if entry.dryrun_config_name(c) == name
    )
    case = entry.build_dryrun_case(config, devices)
    assert not isinstance(case, str), case
    return case


def test_budget_gate_cheap_config_green(devices):
    budgets = coll.load_budgets()
    committed = budgets["configs"][CHEAP_CONFIG]
    assert "collectives" in committed, committed
    case = _build_case(CHEAP_CONFIG, devices)
    lowered, compiled = coll.compile_case(case)
    record = coll.collective_record(case, compiled)
    if coll.jax_version_skew(budgets) is not None:
        pytest.skip("budget file from a different jax; gate degrades to "
                    "warnings (refresh with --write-budgets)")
    violations, _ = coll.compare_budgets(
        committed["collectives"], record["collectives"], config=CHEAP_CONFIG
    )
    assert violations == [], [f.render() for f in violations]
    # the same compile also passes the placement lints
    assert shardlint.lint_dropped_donation(lowered, compiled) == []
    assert shardlint.lint_replicated_params(
        case.trainer.state.params, case.trainer.partitioner
    ) == []


def test_budget_gate_catches_widened_sharding(devices):
    """Deliberately widening the sharding (dropping every partition rule
    so params replicate) must fail the committed budget, naming the
    config and the collective op kind."""
    from distributed_pytorch_example_tpu.parallel.api import Partitioner

    budgets = coll.load_budgets()
    if coll.jax_version_skew(budgets) is not None:
        pytest.skip("budget file from a different jax; gate degrades to "
                    "warnings (refresh with --write-budgets)")
    case = _build_case(CHEAP_CONFIG, devices)
    # widen: no rules, replicate everything the partitioner used to shard
    case.trainer.partitioner = Partitioner(case.mesh)
    _, compiled = coll.compile_case(case)
    record = coll.collective_record(case, compiled)
    violations, _ = coll.compare_budgets(
        budgets["configs"][CHEAP_CONFIG]["collectives"],
        record["collectives"],
        config=CHEAP_CONFIG,
    )
    assert violations, "replicating all params must change the collectives"
    assert all(f.config == CHEAP_CONFIG for f in violations)
    assert all(f.where in coll.COLLECTIVE_KINDS for f in violations)


@pytest.mark.lint
def test_budget_file_covers_all_configs():
    sys.path.insert(0, REPO_ROOT)
    import __graft_entry__ as entry

    budgets = coll.load_budgets()
    names = {entry.dryrun_config_name(c) for c in entry.DRYRUN_CONFIGS}
    # the serving engine's programs are first-class budget entries
    names |= {"serve/prefill", "serve/decode"}
    assert set(budgets["configs"]) == names
    meta = budgets["_meta"]
    assert meta["n_devices"] == 8 and "jax" in meta
    # serve/decode is pinned to the fused paged-decode dispatch: the
    # committed entry must carry the structural signature + its marker
    decode = budgets["configs"]["serve/decode"]
    assert decode["signature"] == "paged-decode-fused"
    assert decode["markers"]["paged_decode_fused"] is True


# ---------------------------------------------------------------------------
# CLI driver contract
# ---------------------------------------------------------------------------


def test_cli_one_json_line_contract():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "scripts", "graft_lint.py"),
         "--no-collectives", "--no-numerics"],
        capture_output=True, text=True, timeout=300, cwd=REPO_ROOT,
    )
    lines = [l for l in proc.stdout.splitlines() if l.strip()]
    assert len(lines) == 1, proc.stdout
    payload = json.loads(lines[0])
    assert payload["tool"] == "graft_lint"
    assert payload["ok"] is True and proc.returncode == 0
    assert payload["violations"] == 0


# ---------------------------------------------------------------------------
# full sweep (slow): every config either audits green or reproduces its
# committed error record
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_full_budget_sweep(devices):
    from distributed_pytorch_example_tpu.analysis import runner

    budgets = coll.load_budgets()
    result = runner.audit_configs(None, budgets=budgets)
    assert result.violations == [], [f.render() for f in result.violations]
    covered = result.configs_audited + result.configs_errored
    assert covered + sum(
        1 for r in result.records.values() if "skip" in r
    ) == len(budgets["configs"])

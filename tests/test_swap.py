"""graft-swap: the publish channel's commit/corruption guarantees, the
restore transport, and the SwapController's drain-install-readmit roll
plane.

The channel and controller units run against fake handles/routers (no
engine compile, tier-1 cheap); the real-engine token-exactness e2e is
``slow`` (the hot-swap-midstream chaos scenario covers the full fleet
path in tier-1 via ``tests/test_chaos.py``). SIGKILL-shaped torn-publish
coverage lives in ``tests/test_step_resume.py`` (subprocess child).
"""

import os

import numpy as np
import pytest
from flax import serialization

from distributed_pytorch_example_tpu.robustness import chaos
from distributed_pytorch_example_tpu.robustness.chaos import corrupt_file
from distributed_pytorch_example_tpu.robustness.integrity import (
    CheckpointCorruptError,
)
from distributed_pytorch_example_tpu.robustness.publish import (
    PublishChannel,
    is_publish_channel,
)
from distributed_pytorch_example_tpu.serving.swap import (
    SwapController,
    restore_params,
)

# ---------------------------------------------------------------------------
# publish channel
# ---------------------------------------------------------------------------


def test_channel_publish_read_roundtrip(tmp_path):
    ch = PublishChannel(str(tmp_path / "chan"))
    assert ch.latest() is None and ch.load_latest() is None
    v1 = ch.publish_blob(b"alpha")
    v2 = ch.publish_blob(b"beta")
    assert (v1, v2) == ("00000001", "00000002")
    assert ch.pointer_version() == v2
    assert ch.latest() == v2
    assert ch.read(v1) == b"alpha"
    assert ch.load_latest() == (v2, b"beta")
    assert is_publish_channel(ch.root)
    assert not is_publish_channel(str(tmp_path))


def test_channel_retention_gc_keeps_newest_intact(tmp_path):
    ch = PublishChannel(str(tmp_path / "chan"), retain=2)
    for i in range(4):
        ch.publish_blob(f"payload-{i}".encode())
    # newest `retain` committed versions survive; older dirs are gone
    assert ch.versions() == ["00000003", "00000004"]
    assert ch.latest() == "00000004"


def test_channel_corrupt_head_falls_back_then_heals(tmp_path):
    ch = PublishChannel(str(tmp_path / "chan"))
    good = ch.publish_blob(b"good")
    bad = ch.publish_blob(b"soon-corrupt")
    corrupt_file(ch.artifact_path(bad), mode="bitflip", seed=0)
    # the pointer names the corrupt head; the intact-ancestor walk must
    # serve the committed ancestor instead — and a direct read of the
    # corrupt version must raise, never hand back garbage
    assert ch.pointer_version() == bad
    assert ch.latest() == good
    with pytest.raises(CheckpointCorruptError):
        ch.read(bad)
    state = ch.state()
    assert state["ok"] is False
    assert state["latest_intact"] == good
    # GC spares the pointed version even when corrupt (the doctor must
    # be able to say WHY readers walked past it) ...
    assert bad in ch.versions()
    # ... and the next successful publish removes it: healed
    healed = ch.publish_blob(b"fixed")
    assert ch.latest() == healed
    assert bad not in ch.versions()
    assert ch.state()["ok"] is True


def test_channel_corrupt_pointer_degrades_to_scan(tmp_path):
    ch = PublishChannel(str(tmp_path / "chan"))
    v1 = ch.publish_blob(b"one")
    ch.publish_blob(b"two")
    corrupt_file(ch.artifact_path("00000002"), mode="truncate")
    corrupt_file(ch.pointer_path, mode="bitflip", seed=1)
    assert ch.pointer_version() is None
    # the scan only trusts versions it can verify
    assert ch.latest() == v1
    state = ch.state()
    assert state["pointer"]["exists"] and not state["pointer"]["intact"]
    assert state["ok"] is False


def test_chaos_corrupt_publish_fires_on_nth(tmp_path):
    ch = PublishChannel(str(tmp_path / "chan"))
    chaos.install(chaos.ChaosPlan(
        [chaos.Fault("corrupt-publish", nth=2)]
    ))
    try:
        v1 = ch.publish_blob(b"first")
        v2 = ch.publish_blob(b"second")
    finally:
        chaos.uninstall()
    assert ch.pointer_version() == v2
    assert ch.latest() == v1  # the nth=2 commit carries a broken CRC
    assert ch.read(v1) == b"first"


# ---------------------------------------------------------------------------
# restore transport
# ---------------------------------------------------------------------------


def _params_tree(seed=0):
    rng = np.random.default_rng(seed)
    return {
        "dense": {"kernel": rng.normal(size=(4, 8)).astype(np.float32)},
        "embed": rng.normal(size=(16, 4)).astype(np.float32),
        "steps": np.arange(6, dtype=np.int32),
    }


def _payload_body(params, **extra):
    return serialization.msgpack_serialize({
        "state": {"params": serialization.to_state_dict(params)},
        "epoch": 1, "loss": 0.25, "extra": dict(extra),
    })


def test_restore_params_exact_roundtrip():
    import jax

    published = _params_tree(seed=1)
    template = jax.tree_util.tree_map(np.zeros_like, published)
    params, meta = restore_params(
        _payload_body(published), template, transport="exact"
    )
    for got, want in zip(
        jax.tree_util.tree_leaves(params),
        jax.tree_util.tree_leaves(published),
    ):
        np.testing.assert_array_equal(np.asarray(got), want)
    assert meta["epoch"] == 1 and meta["loss"] == 0.25


def test_restore_params_int8_transport_is_lossy_but_close():
    import jax

    published = _params_tree(seed=2)
    template = jax.tree_util.tree_map(np.zeros_like, published)
    params, _ = restore_params(
        _payload_body(published), template, transport="int8"
    )
    # float leaves pass through the int8-block quantizer: close, and (at
    # this scale) NOT bit-exact — the lossiness is why the bit-identity
    # gates pin the exact transport
    kernel = np.asarray(params["dense"]["kernel"])
    want = published["dense"]["kernel"]
    np.testing.assert_allclose(kernel, want, atol=0.02)
    assert not np.array_equal(kernel, want)
    # integer leaves (step counters etc.) ship verbatim
    np.testing.assert_array_equal(
        np.asarray(params["steps"]), published["steps"]
    )


def test_restore_params_rejects_garbage_and_unknown_transport():
    template = {"w": np.zeros((2,), np.float32)}
    with pytest.raises(ValueError, match="not a published checkpoint"):
        restore_params(
            serialization.msgpack_serialize({"nope": 1}), template
        )
    with pytest.raises(ValueError, match="unknown swap transport"):
        restore_params(b"", template, transport="fp8")


def test_restore_params_rejects_wrong_geometry():
    # a structurally-matching payload from the WRONG model geometry must
    # fail at restore (→ unstageable-version quarantine), naming the
    # leaf — install_params is a pointer swap, so without this guard the
    # bad shape only surfaces as a dead replica at the next decode
    params = _params_tree(seed=0)
    wrong = {
        "dense": {"kernel": np.zeros((4, 16), np.float32)},  # 8 → 16
        "embed": params["embed"],
        "steps": params["steps"],
    }
    with pytest.raises(ValueError, match=r"kernel.*\(4, 16\).*\(4, 8\)"):
        restore_params(_payload_body(wrong), params)


# ---------------------------------------------------------------------------
# SwapController roll plane (fake handles/router: no engine compile)
# ---------------------------------------------------------------------------


class _FakeEngine:
    def __init__(self, params):
        self.params = params
        self.draft_params = None
        self.weights_version = "v0"
        self.installs = []

    def install_params(self, params, version, *, draft_params=None):
        self.params = params
        self.weights_version = str(version)
        self.installs.append(str(version))


class _FakeHandle:
    def __init__(self, rid, params):
        self.replica_id = rid
        self.engine = _FakeEngine(params)
        self.decode_steps = 100
        self.resident = 0

    def state(self):
        return "live"

    def alive(self):
        return True

    def snapshot(self):
        return {"resident": self.resident, "inbox_depth": 0}


class _FakeRouter:
    def __init__(self):
        self.paused = []
        self.resumed = []

    def pause_replica(self, rid):
        self.paused.append(rid)

    def resume_replica(self, rid):
        self.resumed.append(rid)


def _controller(tmp_path, n=2, **kw):
    template = _params_tree(seed=0)
    ch = PublishChannel(str(tmp_path / "chan"))
    handles = [_FakeHandle(f"r{i}", template) for i in range(n)]
    ctrl = SwapController(ch, handles, poll_s=0.0, **kw)
    return ch, handles, ctrl


def _tick_until_adopted(ctrl, router, start=0.0, limit=32):
    t = start
    staged = False
    for _ in range(limit):
        ctrl.tick(router, now=t)
        t += 1.0
        staged = staged or ctrl.pending()
        if staged and not ctrl.pending():
            return
    raise AssertionError("controller never staged+finished a roll")


def test_swap_controller_rolls_each_replica_once(tmp_path):
    ch, handles, ctrl = _controller(tmp_path)
    router = _FakeRouter()
    ctrl.tick(router, now=0.0)  # empty channel: nothing to do
    assert not ctrl.pending() and ctrl.current_version == "v0"

    version = ch.publish_blob(_payload_body(_params_tree(seed=3)))
    _tick_until_adopted(ctrl, router)
    assert ctrl.current_version == version
    assert ctrl.swaps_completed == 1
    # one drain bracket per replica, in order
    assert router.paused == ["r0", "r1"]
    assert router.resumed == ["r0", "r1"]
    for h in handles:
        assert h.engine.installs == [version]
        assert h.engine.weights_version == version
    m = ctrl.metrics()
    assert m["swap_rolls"] == 2 and m["swap_blackout_ms"] is not None
    # re-ticking an adopted fleet is a no-op
    ctrl.tick(router, now=100.0)
    assert ctrl.swaps_completed == 1 and router.paused == ["r0", "r1"]


def test_swap_controller_waits_for_drain_and_min_decode_steps(tmp_path):
    ch, handles, ctrl = _controller(tmp_path, n=1, min_decode_steps=5)
    router = _FakeRouter()
    handles[0].decode_steps = 0
    ch.publish_blob(_payload_body(_params_tree(seed=4)))
    ctrl.tick(router, now=0.0)  # stages
    ctrl.tick(router, now=1.0)
    assert router.paused == []  # not provably mid-stream yet
    handles[0].decode_steps = 5
    handles[0].resident = 2
    ctrl.tick(router, now=2.0)
    assert router.paused == ["r0"]
    ctrl.tick(router, now=3.0)
    assert handles[0].engine.installs == []  # residents still draining
    handles[0].resident = 0
    ctrl.tick(router, now=4.0)
    assert handles[0].engine.installs and router.resumed == ["r0"]


def test_swap_controller_skips_unstageable_version(tmp_path):
    ch, handles, ctrl = _controller(tmp_path, n=1)
    router = _FakeRouter()
    ch.publish_blob(serialization.msgpack_serialize({"not": "a ckpt"}))
    for t in range(4):
        ctrl.tick(router, now=float(t))
    # staging failed once, the version is quarantined, the fleet stays up
    assert ctrl.current_version == "v0" and not ctrl.pending()
    assert router.paused == [] and handles[0].engine.installs == []
    good = ch.publish_blob(_payload_body(_params_tree(seed=5)))
    _tick_until_adopted(ctrl, router, start=10.0)
    assert ctrl.current_version == good


def test_swap_controller_kill_during_swap_aborts_then_completes(tmp_path):
    ch, handles, ctrl = _controller(tmp_path, n=1)
    router = _FakeRouter()
    version = ch.publish_blob(_payload_body(_params_tree(seed=6)))
    chaos.install(chaos.ChaosPlan(
        [chaos.Fault("kill-during-swap", at="pre-install", nth=1)]
    ))
    try:
        ctrl.tick(router, now=0.0)  # stage + pause
        ctrl.tick(router, now=1.0)  # drained -> chaos aborts pre-install
        assert ctrl.swap_aborts == 1
        assert handles[0].engine.installs == []
        assert router.resumed == ["r0"]  # released un-swapped
        assert ctrl.pending()  # the staged version is still owed
        _tick_until_adopted(ctrl, router)
    finally:
        chaos.uninstall()
    assert ctrl.current_version == version
    assert handles[0].engine.installs == [version]
    assert ctrl.swaps_completed == 1


def test_swap_controller_skips_dead_replica_mid_roll(tmp_path):
    ch, handles, ctrl = _controller(tmp_path, n=2)
    router = _FakeRouter()
    version = ch.publish_blob(_payload_body(_params_tree(seed=7)))
    handles[0].state = lambda: "dead"
    _tick_until_adopted(ctrl, router)
    # the dead replica is skipped (its journal replays elsewhere); the
    # live one still rolls and the fleet adopts the version
    assert handles[0].engine.installs == []
    assert handles[1].engine.installs == [version]
    assert ctrl.current_version == version


# ---------------------------------------------------------------------------
# trainer wiring: Trainer(publish_dir=...) publishes every LATEST save
# ---------------------------------------------------------------------------


def test_trainer_publish_dir_publishes_each_epoch(tmp_path, mesh_1d):
    """The `--publish-dir` train flag (Trainer publish_dir kwarg) commits
    one channel version per epoch, and the published payload restores to
    the trainer's live params bit-exactly."""
    import jax
    import optax

    import distributed_pytorch_example_tpu as dpx
    from distributed_pytorch_example_tpu.data.synthetic import _ArrayDataset
    from distributed_pytorch_example_tpu.models.mlp import SimpleNet
    from distributed_pytorch_example_tpu.train import ClassificationTask, Trainer

    rng = np.random.default_rng(0)
    x = rng.standard_normal((64, 16)).astype(np.float32)
    y = (x.sum(axis=1) > 0).astype(np.int32)
    loader = dpx.data.DeviceLoader(
        _ArrayDataset({"x": x, "y": y}), 32, mesh=mesh_1d, seed=0
    )
    trainer = Trainer(
        SimpleNet(input_size=16, hidden_size=8, num_classes=2),
        ClassificationTask(),
        optax.adam(1e-2),
        partitioner=dpx.parallel.data_parallel(mesh_1d),
        checkpoint_dir=str(tmp_path / "ckpt"),
        publish_dir=str(tmp_path / "pub"),
        log_every=100,
    )
    trainer.fit(loader, epochs=2)

    ch = PublishChannel(str(tmp_path / "pub"))
    assert ch.versions() == ["00000001", "00000002"]
    assert ch.latest() == "00000002"
    restored = restore_params(
        ch.read("00000002"),
        jax.tree_util.tree_map(np.asarray, trainer.state.params),
    )
    live, pub = jax.tree_util.tree_leaves(
        trainer.state.params
    ), jax.tree_util.tree_leaves(restored)
    for a, b in zip(live, pub):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ---------------------------------------------------------------------------
# real engine: publish -> restore -> install token-exactness
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_publish_restore_install_token_exact(tmp_path, devices):
    """Weights published by the channel, restored over the exact
    transport, and installed into a live engine must serve the same
    tokens as a fresh engine BUILT with those weights."""
    import jax
    import jax.numpy as jnp

    from distributed_pytorch_example_tpu.models.gpt2 import GPT2
    from distributed_pytorch_example_tpu.serving import (
        InferenceEngine, Request,
    )

    kw = dict(vocab_size=61, max_len=32, model_dim=16, num_layers=1,
              num_heads=2, mlp_dim=32)
    pool = dict(paged_num_blocks=16, paged_block_size=4,
                paged_max_blocks=4)
    model = GPT2(**kw, decode=True, **pool)
    v0 = GPT2(**kw).init(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    tuned = GPT2(**kw).init(
        jax.random.key(9), jnp.zeros((1, 8), jnp.int32)
    )["params"]

    rng = np.random.default_rng(0)
    requests = [
        Request(
            rid=f"q{i}", prompt=[int(t) for t in rng.integers(0, 61, 6)],
            max_new_tokens=8, seed=500 + i,
        )
        for i in range(6)
    ]

    swapped = InferenceEngine(model, v0, num_slots=3, temperature=0.0)
    swapped.run(requests)  # warm + proves it serves v0 first
    version = PublishChannel(str(tmp_path / "chan")).publish_blob(
        serialization.msgpack_serialize({
            "state": {"params": serialization.to_state_dict(
                jax.tree_util.tree_map(np.asarray, tuned)
            )},
            "epoch": 2, "loss": 0.1, "extra": {},
        })
    )
    body = PublishChannel(str(tmp_path / "chan")).read(version)
    params, meta = restore_params(body, swapped.params, transport="exact")
    assert meta["epoch"] == 2
    swapped.install_params(params, version)
    assert swapped.weights_version == version

    reference = InferenceEngine(model, tuned, num_slots=3, temperature=0.0)
    got = swapped.run(requests)["results"]
    want = reference.run(requests)["results"]
    for r in requests:
        assert got[r.rid]["tokens"] == want[r.rid]["tokens"], r.rid
        assert got[r.rid]["status"] == "done"

"""KV-cache generation vs full-recompute decoding."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_pytorch_example_tpu.train.generate import generate

GPT2_KW = dict(vocab_size=97, max_len=64, model_dim=32, num_layers=2,
               num_heads=4, mlp_dim=64)
LLAMA_KW = dict(vocab_size=97, max_len=64, model_dim=32, num_layers=2,
                num_heads=4, num_kv_heads=2, mlp_dim=64)


def _greedy_no_cache(model, params, prompt, n):
    """Reference: full forward recompute each step, argmax."""
    tokens = prompt
    for _ in range(n):
        logits = model.apply({"params": params}, tokens, train=False)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        tokens = jnp.concatenate([tokens, nxt[:, None]], axis=1)
    return tokens


@pytest.mark.parametrize("family", ["gpt2", "llama"])
def test_cached_greedy_matches_full_recompute(family):
    if family == "gpt2":
        from distributed_pytorch_example_tpu.models.gpt2 import GPT2 as M

        kw = GPT2_KW
    else:
        from distributed_pytorch_example_tpu.models.llama import Llama as M

        kw = LLAMA_KW
    train_model = M(**kw)
    decode_model = M(**kw, decode=True)
    prompt = jnp.asarray(
        np.random.default_rng(0).integers(0, 97, (2, 8)), jnp.int32
    )
    params = train_model.init(jax.random.key(0), prompt)["params"]

    expected = _greedy_no_cache(train_model, params, prompt, 12)
    got = generate(
        decode_model, params, prompt, max_new_tokens=12, temperature=0.0
    )
    np.testing.assert_array_equal(np.asarray(got), np.asarray(expected))


def test_sampling_respects_top_k_and_rng():
    from distributed_pytorch_example_tpu.models.gpt2 import GPT2

    model = GPT2(**GPT2_KW, decode=True)
    train_model = GPT2(**GPT2_KW)
    prompt = jnp.zeros((1, 4), jnp.int32)
    params = train_model.init(jax.random.key(0), prompt)["params"]
    a = generate(model, params, prompt, 8, temperature=1.0, top_k=5,
                 rng=jax.random.key(1))
    b = generate(model, params, prompt, 8, temperature=1.0, top_k=5,
                 rng=jax.random.key(1))
    c = generate(model, params, prompt, 8, temperature=1.0, top_k=5,
                 rng=jax.random.key(2))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))  # same rng
    assert not np.array_equal(np.asarray(a), np.asarray(c))  # diff rng
    assert a.shape == (1, 12)


def test_generate_requires_decode_model():
    from distributed_pytorch_example_tpu.models.gpt2 import GPT2

    model = GPT2(**GPT2_KW)
    with pytest.raises(ValueError, match="decode=True"):
        generate(model, {}, jnp.zeros((1, 4), jnp.int32), 4)


def test_eos_freezes_finished_sequences():
    from distributed_pytorch_example_tpu.models.gpt2 import GPT2

    model = GPT2(**GPT2_KW, decode=True)
    train_model = GPT2(**GPT2_KW)
    prompt = jnp.zeros((2, 4), jnp.int32)
    params = train_model.init(jax.random.key(0), prompt)["params"]
    # stochastic baseline without EOS, fixed rng
    rng = jax.random.key(3)
    free = np.asarray(
        generate(model, params, prompt, 12, temperature=1.0, rng=rng)
    )[0, 4:]
    # declare the SECOND sampled token to be EOS: it provably occurs, and
    # the frozen run shares rng consumption so pre-EOS draws are identical
    eos = int(free[1])
    frozen = np.asarray(
        generate(model, params, prompt, 12, temperature=1.0, eos_id=eos,
                 rng=rng)
    )[0, 4:]
    hit = int(np.where(frozen == eos)[0][0])
    np.testing.assert_array_equal(frozen[: hit + 1], free[: hit + 1])
    np.testing.assert_array_equal(frozen[hit:], eos)  # frozen after EOS
    # the free run kept sampling past it (else the assertion is vacuous)
    assert not (free[hit:] == eos).all()


def test_trained_model_generates_learned_pattern(devices):
    """The whole stack coheres: train LLaMA on a successor language
    (token t+1 = token t + 1 mod V), then cached greedy generation must
    reproduce the rule exactly."""
    import optax

    import distributed_pytorch_example_tpu as dpx
    from distributed_pytorch_example_tpu.models.llama import Llama

    V, S = 32, 16
    kw = dict(vocab_size=V, max_len=64, model_dim=64, num_layers=2,
              num_heads=4, num_kv_heads=2, mlp_dim=128)

    # successor-language corpus: rows are consecutive ints mod V
    rng = np.random.default_rng(0)
    starts = rng.integers(0, V, (512,))
    data = (starts[:, None] + np.arange(S)[None, :]) % V

    class _Successor:
        def __len__(self):
            return len(data)

        def get_batch(self, idx):
            return {"tokens": data[idx].astype(np.int32)}

    mesh = dpx.runtime.make_mesh()
    loader = dpx.data.DeviceLoader(
        _Successor(), 64, mesh=mesh, num_shards=1, shard_id=0, seed=0
    )
    trainer = dpx.train.Trainer(
        Llama(**kw), dpx.train.CausalLMTask(), optax.adam(3e-3),
        partitioner=dpx.parallel.data_parallel(mesh),
    )
    history = trainer.fit(loader, epochs=25)
    assert history[-1]["train_loss"] < 0.1, history[-1]

    decode_model = Llama(**kw, decode=True)
    prompt = jnp.asarray([[7, 8, 9, 10], [30, 31, 0, 1]], jnp.int32)
    out = np.asarray(
        generate(decode_model, trainer.state.params, prompt, 12,
                 temperature=0.0)
    )
    expected = (out[:, 3:4] + np.arange(1, 13)) % V
    np.testing.assert_array_equal(out[:, 4:], expected)


def test_top_p_truncates_to_nucleus():
    """With a peaked distribution and small top_p, sampling must only
    ever draw the top token; the raw distribution would not."""
    from distributed_pytorch_example_tpu.train.generate import _sample

    logits = jnp.asarray([[4.0, 3.5, 0.0, -1.0]])  # top-1 prob ~0.61
    draws = {
        int(_sample(logits, jax.random.key(i), 1.0, None, 0.5)[0])
        for i in range(50)
    }
    assert draws == {0}  # nucleus at p=0.5 is exactly the argmax token
    free = {
        int(_sample(logits, jax.random.key(i), 1.0, None, None)[0])
        for i in range(50)
    }
    assert len(free) > 1  # unconstrained sampling spreads


def test_invalid_top_p_rejected():
    from distributed_pytorch_example_tpu.models.gpt2 import GPT2

    model = GPT2(**GPT2_KW, decode=True)
    with pytest.raises(ValueError, match="top_p"):
        generate(model, {}, jnp.zeros((1, 4), jnp.int32), 4, top_p=0.0)

"""KV-cache generation vs full-recompute decoding."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_pytorch_example_tpu.train.generate import generate

GPT2_KW = dict(vocab_size=97, max_len=64, model_dim=32, num_layers=2,
               num_heads=4, mlp_dim=64)
LLAMA_KW = dict(vocab_size=97, max_len=64, model_dim=32, num_layers=2,
                num_heads=4, num_kv_heads=2, mlp_dim=64)


def _greedy_no_cache(model, params, prompt, n):
    """Reference: full forward recompute each step, argmax."""
    tokens = prompt
    for _ in range(n):
        logits = model.apply({"params": params}, tokens, train=False)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        tokens = jnp.concatenate([tokens, nxt[:, None]], axis=1)
    return tokens


@pytest.mark.parametrize("family", ["gpt2", "llama"])
def test_cached_greedy_matches_full_recompute(family):
    if family == "gpt2":
        from distributed_pytorch_example_tpu.models.gpt2 import GPT2 as M

        kw = GPT2_KW
    else:
        from distributed_pytorch_example_tpu.models.llama import Llama as M

        kw = LLAMA_KW
    train_model = M(**kw)
    decode_model = M(**kw, decode=True)
    prompt = jnp.asarray(
        np.random.default_rng(0).integers(0, 97, (2, 8)), jnp.int32
    )
    params = train_model.init(jax.random.key(0), prompt)["params"]

    expected = _greedy_no_cache(train_model, params, prompt, 12)
    got = generate(
        decode_model, params, prompt, max_new_tokens=12, temperature=0.0
    )
    np.testing.assert_array_equal(np.asarray(got), np.asarray(expected))


def test_sampling_respects_top_k_and_rng():
    from distributed_pytorch_example_tpu.models.gpt2 import GPT2

    model = GPT2(**GPT2_KW, decode=True)
    train_model = GPT2(**GPT2_KW)
    prompt = jnp.zeros((1, 4), jnp.int32)
    params = train_model.init(jax.random.key(0), prompt)["params"]
    a = generate(model, params, prompt, 8, temperature=1.0, top_k=5,
                 rng=jax.random.key(1))
    b = generate(model, params, prompt, 8, temperature=1.0, top_k=5,
                 rng=jax.random.key(1))
    c = generate(model, params, prompt, 8, temperature=1.0, top_k=5,
                 rng=jax.random.key(2))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))  # same rng
    assert not np.array_equal(np.asarray(a), np.asarray(c))  # diff rng
    assert a.shape == (1, 12)


def test_generate_requires_decode_model():
    from distributed_pytorch_example_tpu.models.gpt2 import GPT2

    model = GPT2(**GPT2_KW)
    with pytest.raises(ValueError, match="decode=True"):
        generate(model, {}, jnp.zeros((1, 4), jnp.int32), 4)

"""Launcher contract tests: hostname→topology derivation (SURVEY.md §4).

Runs the real entrypoint.sh with a stub training script that dumps the env
it would hand to ``jax.distributed.initialize`` via resolve_config.
"""

import json
import os
import subprocess
import sys

import pytest

from distributed_pytorch_example_tpu.runtime.distributed import (
    derive_coordinator_address,
    derive_process_id,
    resolve_config,
)

ENTRYPOINT = os.path.join(
    os.path.dirname(__file__), "..",
    "distributed_pytorch_example_tpu", "launch", "entrypoint.sh",
)


def run_entrypoint(env_extra, tmp_path):
    stub = tmp_path / "stub.py"
    stub.write_text(
        "import json, os\n"
        "print(json.dumps({k: os.environ.get(k) for k in "
        "('PROCESS_ID', 'COORDINATOR_ADDRESS', 'REPLICAS')}))\n"
    )
    env = {
        "PATH": os.environ["PATH"],
        "TRAINING_SCRIPT": str(stub),
        **env_extra,
    }
    proc = subprocess.run(
        ["bash", ENTRYPOINT], env=env, capture_output=True, text=True, timeout=30
    )
    assert proc.returncode == 0, proc.stderr
    return json.loads(proc.stdout.strip().splitlines()[-1])


def test_single_host_no_env_needed(tmp_path):
    out = run_entrypoint({}, tmp_path)
    assert out["REPLICAS"] == "1"
    assert out["PROCESS_ID"] is None  # resolve_config defaults to 0


def test_multi_host_derivation(tmp_path):
    out = run_entrypoint(
        {"REPLICAS": "4", "HOSTNAME": "trainer-3",
         "NF_DISCOVERY_SERVICE": "svc.ns.local"},
        tmp_path,
    )
    assert out["PROCESS_ID"] == "3"
    assert out["COORDINATOR_ADDRESS"] == "trainer-0.svc.ns.local:29500"


def test_multi_host_missing_discovery_fails_fast(tmp_path):
    stub = tmp_path / "stub.py"
    stub.write_text("print('should not run')\n")
    proc = subprocess.run(
        ["bash", ENTRYPOINT],
        env={"PATH": os.environ["PATH"], "REPLICAS": "2",
             "TRAINING_SCRIPT": str(stub), "HOSTNAME": "x-1"},
        capture_output=True, text=True, timeout=30,
    )
    assert proc.returncode == 1
    assert "NF_DISCOVERY_SERVICE" in proc.stderr


def test_non_numeric_hostname_fails_fast(tmp_path):
    proc = subprocess.run(
        ["bash", ENTRYPOINT],
        env={"PATH": os.environ["PATH"], "REPLICAS": "2",
             "NF_DISCOVERY_SERVICE": "svc", "HOSTNAME": "nosuffix",
             "TRAINING_SCRIPT": "unused.py"},
        capture_output=True, text=True, timeout=30,
    )
    assert proc.returncode == 1
    assert "PROCESS_ID" in proc.stderr


def test_python_side_derivation_matches_shell():
    """resolve_config derives the same topology as entrypoint.sh."""
    assert derive_process_id("worker-7") == 7
    assert derive_process_id("nosuffix") == 0
    assert (
        derive_coordinator_address("myjob-3", "svc", 29500)
        == "myjob-0.svc:29500"
    )
    cfg = resolve_config(
        {"REPLICAS": "4", "HOSTNAME": "myjob-2", "NF_DISCOVERY_SERVICE": "svc"}
    )
    assert cfg.process_id == 2
    assert cfg.num_processes == 4
    assert cfg.coordinator_address == "myjob-0.svc:29500"


def test_custom_port(tmp_path):
    out = run_entrypoint(
        {"REPLICAS": "2", "HOSTNAME": "w-1", "NF_DISCOVERY_SERVICE": "d",
         "COORDINATOR_PORT": "12345"},
        tmp_path,
    )
    assert out["COORDINATOR_ADDRESS"] == "w-0.d:12345"


def test_max_restarts_resumes_after_crash(tmp_path):
    """MAX_RESTARTS: a crashing script is relaunched with --resume
    <CHECKPOINT_DIR>/latest_model.ckpt appended; success stops the loop."""
    stub = tmp_path / "stub.py"
    marker = tmp_path / "attempts"
    stub.write_text(
        "import pathlib, sys\n"
        f"m = pathlib.Path({str(marker)!r})\n"
        "n = int(m.read_text()) if m.exists() else 0\n"
        "m.write_text(str(n + 1))\n"
        "print('ARGS:' + ' '.join(sys.argv[1:]))\n"
        "sys.exit(1 if n < 2 else 0)\n"  # crash twice, then succeed
    )
    env = {
        "PATH": os.environ["PATH"],
        "TRAINING_SCRIPT": str(stub),
        "SCRIPT_ARGS": "--epochs 5",
        "MAX_RESTARTS": "3",
        "CHECKPOINT_DIR": "/ck",
    }
    proc = subprocess.run(
        ["bash", ENTRYPOINT], env=env, capture_output=True, text=True,
        timeout=60,
    )
    assert proc.returncode == 0, proc.stderr
    args_lines = [
        l for l in proc.stdout.splitlines() if l.startswith("ARGS:")
    ]
    assert args_lines[0] == "ARGS:--epochs 5"  # first run: no resume
    assert args_lines[1] == "ARGS:--epochs 5 --resume /ck/latest_model.ckpt"
    assert args_lines[2] == "ARGS:--epochs 5 --resume /ck/latest_model.ckpt"
    assert marker.read_text() == "3"
    assert proc.stderr.count("WARN: training exited") == 2


def test_max_restarts_exhausted_fails_with_last_rc(tmp_path):
    stub = tmp_path / "stub.py"
    stub.write_text("import sys; sys.exit(7)\n")
    env = {
        "PATH": os.environ["PATH"],
        "TRAINING_SCRIPT": str(stub),
        "MAX_RESTARTS": "2",
    }
    proc = subprocess.run(
        ["bash", ENTRYPOINT], env=env, capture_output=True, text=True,
        timeout=60,
    )
    assert proc.returncode == 7
    assert "giving up" in proc.stderr
    assert proc.stderr.count("WARN: training exited") == 2


def test_restart_resume_dir_follows_script_args(tmp_path):
    """--checkpoint-dir inside SCRIPT_ARGS wins over $CHECKPOINT_DIR, so
    the retry resumes from where the trainer actually writes."""
    stub = tmp_path / "stub.py"
    marker = tmp_path / "attempts"
    stub.write_text(
        "import pathlib, sys\n"
        f"m = pathlib.Path({str(marker)!r})\n"
        "n = int(m.read_text()) if m.exists() else 0\n"
        "m.write_text(str(n + 1))\n"
        "print('ARGS:' + ' '.join(sys.argv[1:]))\n"
        "sys.exit(1 if n < 1 else 0)\n"
    )
    env = {
        "PATH": os.environ["PATH"],
        "TRAINING_SCRIPT": str(stub),
        "SCRIPT_ARGS": "--checkpoint-dir /mnt/ckpt --epochs 9",
        "MAX_RESTARTS": "2",
        "CHECKPOINT_DIR": "/wrong",
    }
    proc = subprocess.run(
        ["bash", ENTRYPOINT], env=env, capture_output=True, text=True,
        timeout=60,
    )
    assert proc.returncode == 0, proc.stderr
    args_lines = [
        l for l in proc.stdout.splitlines() if l.startswith("ARGS:")
    ]
    assert args_lines[1].endswith("--resume /mnt/ckpt/latest_model.ckpt")
    assert "/wrong" not in proc.stdout


def test_restart_loop_does_not_fight_signals(tmp_path):
    """A child killed by an ORCHESTRATOR signal (TERM/INT/HUP) must NOT be
    restarted — the platform is tearing the pod down."""
    stub = tmp_path / "stub.py"
    stub.write_text(
        "import os, signal\n"
        "os.kill(os.getpid(), signal.SIGTERM)\n"
    )
    env = {
        "PATH": os.environ["PATH"],
        "TRAINING_SCRIPT": str(stub),
        "MAX_RESTARTS": "3",
    }
    proc = subprocess.run(
        ["bash", ENTRYPOINT], env=env, capture_output=True, text=True,
        timeout=60,
    )
    assert proc.returncode > 128
    assert "not restarting" in proc.stderr
    assert "WARN: training exited" not in proc.stderr


def test_restart_loop_recovers_crash_signals(tmp_path):
    """Crash-by-signal (OOM-kill 137, SIGSEGV 139) IS restarted — these are
    exactly the failures MAX_RESTARTS exists to recover; only orchestrator
    teardown signals (HUP/INT/TERM) are exempt."""
    stub = tmp_path / "stub.py"
    marker = tmp_path / "attempts"
    stub.write_text(
        "import os, pathlib, signal, sys\n"
        f"m = pathlib.Path({str(marker)!r})\n"
        "n = int(m.read_text()) if m.exists() else 0\n"
        "m.write_text(str(n + 1))\n"
        "if n == 0:\n"
        "    os.kill(os.getpid(), signal.SIGKILL)\n"  # rc 137, like OOM
        "sys.exit(0)\n"
    )
    env = {
        "PATH": os.environ["PATH"],
        "TRAINING_SCRIPT": str(stub),
        "MAX_RESTARTS": "2",
        "CHECKPOINT_DIR": "/ck",
    }
    proc = subprocess.run(
        ["bash", ENTRYPOINT], env=env, capture_output=True, text=True,
        timeout=60,
    )
    assert proc.returncode == 0, proc.stderr
    assert marker.read_text() == "2"
    assert "restart 1/2" in proc.stderr


def test_restart_resume_dir_equals_form(tmp_path):
    """--checkpoint-dir=PATH (argparse's '=' spelling) is parsed too."""
    stub = tmp_path / "stub.py"
    marker = tmp_path / "attempts"
    stub.write_text(
        "import pathlib, sys\n"
        f"m = pathlib.Path({str(marker)!r})\n"
        "n = int(m.read_text()) if m.exists() else 0\n"
        "m.write_text(str(n + 1))\n"
        "print('ARGS:' + ' '.join(sys.argv[1:]))\n"
        "sys.exit(1 if n < 1 else 0)\n"
    )
    env = {
        "PATH": os.environ["PATH"],
        "TRAINING_SCRIPT": str(stub),
        "SCRIPT_ARGS": "--checkpoint-dir=/mnt/eq --epochs 9",
        "MAX_RESTARTS": "2",
    }
    proc = subprocess.run(
        ["bash", ENTRYPOINT], env=env, capture_output=True, text=True,
        timeout=60,
    )
    assert proc.returncode == 0, proc.stderr
    args_lines = [
        l for l in proc.stdout.splitlines() if l.startswith("ARGS:")
    ]
    assert args_lines[1].endswith("--resume /mnt/eq/latest_model.ckpt")


@pytest.mark.slow
def test_sigterm_graceful_preemption_checkpoint(tmp_path):
    """Graceful preemption (VERDICT r4 ask #4): SIGTERM mid-epoch finishes
    the in-flight step, writes `latest` with the loader cursor, exits with
    the teardown rc 143 (launcher does NOT restart, entrypoint.sh:133-141),
    and a relaunch resumes from that exact batch."""
    import re
    import signal
    import time

    repo = os.path.join(os.path.dirname(__file__), "..")
    ckpt_dir = str(tmp_path / "ck")
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()
    args = [
        sys.executable, os.path.join(repo, "train.py"),
        "--epochs", "2", "--num-samples", "12800", "--batch-size", "2",
        "--log-every", "1", "--seed", "7", "--checkpoint-dir", ckpt_dir,
    ]
    victim = subprocess.Popen(
        args, stderr=subprocess.PIPE, text=True, env=env, cwd=repo
    )
    import threading

    loss_re = re.compile(r"Epoch (\d+), Batch (\d+)/\d+, Loss")
    # watchdog: a wedged victim that stops logging would block the pipe
    # read forever (tail below); kill it so the test fails loudly instead
    watchdog = threading.Timer(600, victim.kill)
    watchdog.start()
    try:
        for line in victim.stderr:
            m = loss_re.search(line)
            if m and int(m.group(2)) >= 3:
                break
        else:
            raise AssertionError("victim exited/wedged before batch 3")
    finally:
        watchdog.cancel()
    victim.send_signal(signal.SIGTERM)
    rest = victim.stderr.read()
    rc = victim.wait(timeout=300)

    assert rc == 143, (rc, rest[-2000:])
    m = re.search(
        r"Preemption checkpoint complete \(epoch (\d+), batch (\d+)\)", rest
    )
    assert m, rest[-2000:]
    saved = (int(m.group(1)), int(m.group(2)))
    ckpt = os.path.join(ckpt_dir, "latest_model.ckpt")
    assert os.path.exists(ckpt)

    # relaunch resumes at the exact saved cursor (--epochs 1 keeps the
    # rerun to the remainder of epoch 0)
    proc = subprocess.run(
        [*args, "--resume", ckpt, "--epochs", "1"],
        capture_output=True, text=True, env=env, cwd=repo, timeout=600,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    m2 = re.search(r"Resuming epoch (\d+) at batch (\d+)/\d+", proc.stderr)
    assert m2, proc.stderr[-2000:]
    assert (int(m2.group(1)), int(m2.group(2))) == saved

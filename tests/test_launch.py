"""Launcher contract tests: hostname→topology derivation (SURVEY.md §4).

Runs the real entrypoint.sh with a stub training script that dumps the env
it would hand to ``jax.distributed.initialize`` via resolve_config.
"""

import json
import os
import subprocess
import sys

import pytest

from distributed_pytorch_example_tpu.runtime.distributed import (
    derive_coordinator_address,
    derive_process_id,
    resolve_config,
)

ENTRYPOINT = os.path.join(
    os.path.dirname(__file__), "..",
    "distributed_pytorch_example_tpu", "launch", "entrypoint.sh",
)


def run_entrypoint(env_extra, tmp_path):
    stub = tmp_path / "stub.py"
    stub.write_text(
        "import json, os\n"
        "print(json.dumps({k: os.environ.get(k) for k in "
        "('PROCESS_ID', 'COORDINATOR_ADDRESS', 'REPLICAS')}))\n"
    )
    env = {
        "PATH": os.environ["PATH"],
        "TRAINING_SCRIPT": str(stub),
        **env_extra,
    }
    proc = subprocess.run(
        ["bash", ENTRYPOINT], env=env, capture_output=True, text=True, timeout=30
    )
    assert proc.returncode == 0, proc.stderr
    return json.loads(proc.stdout.strip().splitlines()[-1])


def test_single_host_no_env_needed(tmp_path):
    out = run_entrypoint({}, tmp_path)
    assert out["REPLICAS"] == "1"
    assert out["PROCESS_ID"] is None  # resolve_config defaults to 0


def test_multi_host_derivation(tmp_path):
    out = run_entrypoint(
        {"REPLICAS": "4", "HOSTNAME": "trainer-3",
         "NF_DISCOVERY_SERVICE": "svc.ns.local"},
        tmp_path,
    )
    assert out["PROCESS_ID"] == "3"
    assert out["COORDINATOR_ADDRESS"] == "trainer-0.svc.ns.local:29500"


def test_multi_host_missing_discovery_fails_fast(tmp_path):
    stub = tmp_path / "stub.py"
    stub.write_text("print('should not run')\n")
    proc = subprocess.run(
        ["bash", ENTRYPOINT],
        env={"PATH": os.environ["PATH"], "REPLICAS": "2",
             "TRAINING_SCRIPT": str(stub), "HOSTNAME": "x-1"},
        capture_output=True, text=True, timeout=30,
    )
    assert proc.returncode == 1
    assert "NF_DISCOVERY_SERVICE" in proc.stderr


def test_non_numeric_hostname_fails_fast(tmp_path):
    proc = subprocess.run(
        ["bash", ENTRYPOINT],
        env={"PATH": os.environ["PATH"], "REPLICAS": "2",
             "NF_DISCOVERY_SERVICE": "svc", "HOSTNAME": "nosuffix",
             "TRAINING_SCRIPT": "unused.py"},
        capture_output=True, text=True, timeout=30,
    )
    assert proc.returncode == 1
    assert "PROCESS_ID" in proc.stderr


def test_python_side_derivation_matches_shell():
    """resolve_config derives the same topology as entrypoint.sh."""
    assert derive_process_id("worker-7") == 7
    assert derive_process_id("nosuffix") == 0
    assert (
        derive_coordinator_address("myjob-3", "svc", 29500)
        == "myjob-0.svc:29500"
    )
    cfg = resolve_config(
        {"REPLICAS": "4", "HOSTNAME": "myjob-2", "NF_DISCOVERY_SERVICE": "svc"}
    )
    assert cfg.process_id == 2
    assert cfg.num_processes == 4
    assert cfg.coordinator_address == "myjob-0.svc:29500"


def test_custom_port(tmp_path):
    out = run_entrypoint(
        {"REPLICAS": "2", "HOSTNAME": "w-1", "NF_DISCOVERY_SERVICE": "d",
         "COORDINATOR_PORT": "12345"},
        tmp_path,
    )
    assert out["COORDINATOR_ADDRESS"] == "w-0.d:12345"

"""graft-lens: unified train+serve request tracing, rolling latency
books, comm/compute overlap accounting, and serve-side self-arming
sentinels.

The load-bearing contracts pinned here:

- the trace file is valid Chrome trace JSON through counters, instants,
  per-replica pid lanes, re-close, and abnormal teardown (``__del__``);
- a 2-replica fleet run lands router AND engine request spans across
  distinct replica pids in ONE trace file;
- ``ServeSentinels`` detectors fire at most once until ``disarm`` and
  drive the real ``StepProfiler.arm`` first-trigger-wins window;
- overlap accounting math (``overlap_frac``) and its degrade-to-None
  contract;
- tracing-enabled steady state costs <= 5% over tracing-off (the
  graft-lens overhead acceptance bound).
"""

import gc
import json
import os
import subprocess
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_pytorch_example_tpu.runtime.profiler import StepProfiler
from distributed_pytorch_example_tpu.serving import (
    FleetRouter,
    InferenceEngine,
    ReplicaHandle,
    Request,
)
from distributed_pytorch_example_tpu.telemetry import (
    LatencyBook,
    PrefixedTrace,
    RollingStats,
    SERVE_TRIGGER_KINDS,
    ServeSentinels,
    TraceWriter,
    overlap_frac_from_times,
    split_trace_times,
)
from distributed_pytorch_example_tpu.telemetry import overlap as overlap_mod

# same tiny GPT-2 as test_fleet.py: one jit cache serves both modules
GPT2_KW = dict(vocab_size=61, max_len=32, model_dim=16, num_layers=1,
               num_heads=2, mlp_dim=32)
PAGED = dict(paged_num_blocks=16, paged_block_size=4, paged_max_blocks=4)

_CACHE = {}


def _model():
    if "gpt2" not in _CACHE:
        from distributed_pytorch_example_tpu.models.gpt2 import GPT2

        params = GPT2(**GPT2_KW).init(
            jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
        )["params"]
        _CACHE["gpt2"] = (GPT2(**GPT2_KW, decode=True, **PAGED), params)
    return _CACHE["gpt2"]


def _engine(**kw):
    model, params = _model()
    return InferenceEngine(
        model, params, num_slots=3, temperature=0.0, **kw
    )


def _requests(n=6, max_new=8, seed=7):
    rng = np.random.default_rng(seed)
    return [
        Request(
            rid=f"q{i:02d}",
            prompt=[int(t) for t in rng.integers(0, 61, 4 + i % 5)],
            max_new_tokens=max_new,
            seed=1000 + i,
        )
        for i in range(n)
    ]


@pytest.fixture(scope="module", autouse=True)
def _warm_programs():
    """Compile once so fleet heartbeats and overhead timing are steady."""
    _engine().warmup()


# ---------------------------------------------------------------------------
# rolling stats / latency book
# ---------------------------------------------------------------------------


def test_rolling_stats_window_and_percentiles():
    s = RollingStats(window=4)
    assert s.percentile(99) is None
    assert s.snapshot() == {"count": 0, "p50": None, "p99": None,
                            "max": None}
    s.extend([1.0, 2.0, 3.0, 4.0, 100.0])  # 1.0 evicted by the window
    snap = s.snapshot()
    assert snap["count"] == 5  # all-time count survives eviction
    assert snap["max"] == 100.0
    assert snap["p50"] == pytest.approx(3.5)
    assert len(s) == 4
    with pytest.raises(ValueError):
        RollingStats(window=0)


def test_latency_book_metrics_and_snapshot(tmp_path):
    book = LatencyBook(window=8)
    assert set(book.snapshot()) == set(LatencyBook.METRICS)
    book.extend("ttft_ms", [5.0, 10.0])
    book.add("kv_occupancy", 0.5)
    assert book.p99("ttft_ms") == pytest.approx(9.95)
    assert book.p99("tpot_ms") is None
    path = tmp_path / "sub" / "snap.json"
    payload = book.write_snapshot(str(path), extra={"tag": "t"})
    on_disk = json.loads(path.read_text())
    assert on_disk == json.loads(json.dumps(payload))
    assert on_disk["tag"] == "t"
    assert on_disk["metrics"]["ttft_ms"]["count"] == 2


# ---------------------------------------------------------------------------
# trace writer: counters, instants, pid lanes, abnormal teardown
# ---------------------------------------------------------------------------


def test_trace_counter_and_instant_events(tmp_path):
    path = tmp_path / "trace.json"
    w = TraceWriter(str(path))
    w.counter("queue_depth", 3, ts_us=100)
    w.counter("kv", {"free_blocks": 7, "rows": 2}, ts_us=200)
    w.instant("trigger:kv-pressure", ts_us=300, kv_used_frac=0.97)
    w.close()
    events = json.loads(path.read_text())
    c = [e for e in events if e["ph"] == "C"]
    assert [e["args"] for e in c] == [
        {"value": 3}, {"free_blocks": 7, "rows": 2},
    ]
    (i,) = [e for e in events if e["ph"] == "i"]
    assert i["name"] == "trigger:kv-pressure"
    assert i["s"] == "p"  # process-scoped instant
    assert i["args"] == {"kv_used_frac": 0.97}


def test_trace_valid_json_after_del_without_close(tmp_path):
    import atexit

    path = tmp_path / "trace.json"
    w = TraceWriter(str(path))
    w.add_complete("step", 0, 10)
    w.counter("depth", 1)
    # the atexit hook pins the writer alive; drop it so plain GC
    # teardown exercises the __del__ -> close finalizer path
    atexit.unregister(w.close)
    del w
    gc.collect()
    events = json.loads(path.read_text())
    assert {e["name"] for e in events} >= {"step", "depth"}


def test_trace_reclose_and_post_close_drop(tmp_path):
    path = tmp_path / "trace.json"
    w = TraceWriter(str(path))
    w.add_complete("kept", 0, 5)
    w.close()
    w.close()  # atexit re-close tolerated
    w.add_complete("dropped", 0, 5)
    w.counter("dropped_c", 1)
    w.instant("dropped_i")
    names = {e["name"] for e in json.loads(path.read_text())}
    assert "kept" in names
    assert not names & {"dropped", "dropped_c", "dropped_i"}


def test_prefixed_trace_pid_lanes(tmp_path):
    path = tmp_path / "trace.json"
    base = TraceWriter(str(path))
    r0 = PrefixedTrace(base, "r0", pid=1)
    r1 = PrefixedTrace(base, "r1", pid=2, process_name="replica-one")
    r0.add_complete("decode_step", 0, 10)
    with r1.span("prefill:q"):
        pass
    r1.counter("kv", {"free_blocks": 5})
    base.close()
    events = json.loads(path.read_text())
    lanes = {
        e["args"]["name"]: e["pid"] for e in events
        if e["ph"] == "M" and e["name"] == "process_name"
    }
    assert lanes["r0"] == 1 and lanes["replica-one"] == 2
    by_name = {e["name"]: e for e in events if e["ph"] != "M"}
    assert by_name["r0/decode_step"]["pid"] == 1
    assert by_name["r1/prefill:q"]["pid"] == 2
    assert by_name["r1/kv"]["pid"] == 2


# ---------------------------------------------------------------------------
# serve sentinels: fire-once, disarm, profiler arm pipeline, degrade
# ---------------------------------------------------------------------------


class _FakeProfiler:
    def __init__(self):
        self.calls = []

    def arm(self, start, stop, reason=""):
        self.calls.append((start, stop, reason))
        return True


class _FakeTrace:
    def __init__(self):
        self.instants = []

    def instant(self, name, **args):
        self.instants.append((name, args))


def test_serve_sentinels_window_validation():
    with pytest.raises(ValueError):
        ServeSentinels(recent_window=1)
    with pytest.raises(ValueError):
        ServeSentinels(baseline_window=4, recent_window=8)


def test_tpot_regression_fires_once_then_disarm_rearms():
    prof, tr = _FakeProfiler(), _FakeTrace()
    s = ServeSentinels(
        profiler=prof, trace=tr, baseline_window=8, recent_window=4,
        regression_factor=2.0, arm_offset=1, arm_span=2,
    )
    for _ in range(8):
        s.observe_tpot(1.0)
    assert s.check(10) == []  # healthy baseline: nothing fires
    for _ in range(4):
        s.observe_tpot(10.0)  # 10x the baseline median
    (trig,) = s.check(20)
    assert trig["kind"] == "tpot-regression"
    assert trig["ratio"] > 2.0
    assert prof.calls == [(21, 23, "serve tpot-regression")]
    assert tr.instants[0][0] == "trigger:tpot-regression"
    # fire-once until disarm: same regression, no new trigger
    assert s.check(21) == []
    s.disarm("tpot-regression")
    (again,) = s.check(22)
    assert again["kind"] == "tpot-regression"
    assert len(s.triggers) == 2  # history survives disarm


def test_straggler_detector_absolute_and_outlier():
    s = ServeSentinels(straggler_age_s=1.0)
    # multi-replica: absolute bound alone is not enough (everyone slow)
    assert s.check(0, heartbeat_ages={"r0": 1.2, "r1": 1.1}) == []
    # the median includes the straggler itself, so a 3x outlier needs
    # healthy company: r2 at 4.0s vs a 0.12s median is one
    (trig,) = s.check(
        1, heartbeat_ages={"r0": 0.1, "r1": 0.12, "r2": 4.0}
    )
    assert trig["kind"] == "straggler-replica"
    assert trig["replica"] == "r2"
    # single-replica fleet: absolute bound alone fires
    s2 = ServeSentinels(straggler_age_s=1.0)
    (t2,) = s2.check(0, heartbeat_ages={"r0": 1.5})
    assert t2["replica"] == "r0"


def test_kv_pressure_threshold_and_notice_lost_replica():
    tr = _FakeTrace()
    s = ServeSentinels(trace=tr, pressure_frac=0.9)
    assert s.check(0, kv_used_frac=0.85) == []
    (trig,) = s.check(1, kv_used_frac=0.93)
    assert trig["kind"] == "kv-pressure"
    # a router-declared loss is the terminal straggler, fire-once too
    assert s.notice_lost_replica("r1", 0.02, step=5)["lost"] is True
    assert s.notice_lost_replica("r1", 0.02, step=6) is None
    assert [t["kind"] for t in s.triggers] == [
        "kv-pressure", "straggler-replica",
    ]
    assert {n for n, _ in tr.instants} == {
        "trigger:kv-pressure", "trigger:straggler-replica",
    }
    assert set(SERVE_TRIGGER_KINDS) >= {t["kind"] for t in s.triggers}


def test_sentinels_degrade_without_profiler_or_trace():
    s = ServeSentinels()  # neither profiler nor trace: pure statistics
    (trig,) = s.check(0, kv_used_frac=1.0)
    assert trig["kind"] == "kv-pressure"
    assert s.summary() == {"triggers": [trig]}


def test_serve_trigger_arms_real_profiler_first_trigger_wins(tmp_path):
    prof = StepProfiler(str(tmp_path), window=(10, 13))
    # drive past the configured window WITHOUT opening it (window check
    # is start <= step < stop), so arm() sees a passed window
    prof.step(20)
    s = ServeSentinels(profiler=prof, arm_offset=1, arm_span=2)
    s.check(30, kv_used_frac=1.0)
    assert (prof.start_step, prof.stop_step) == (31, 33)
    # second trigger while the armed window is pending: arm refused,
    # first trigger wins (StepProfiler contract)
    s.check(32, heartbeat_ages={"r0": 99.0})
    assert (prof.start_step, prof.stop_step) == (31, 33)
    assert len(s.triggers) == 2  # the detection still recorded


# ---------------------------------------------------------------------------
# overlap accounting
# ---------------------------------------------------------------------------


def test_overlap_frac_math():
    assert overlap_frac_from_times(100.0, 0.0, 100.0) is None
    # nothing hidden: wall == compute + collective
    assert overlap_frac_from_times(150.0, 50.0, 100.0) == 0.0
    # fully hidden: wall == compute
    assert overlap_frac_from_times(100.0, 50.0, 100.0) == 1.0
    assert overlap_frac_from_times(125.0, 50.0, 100.0) == 0.5
    # clamped against timer noise
    assert overlap_frac_from_times(90.0, 50.0, 100.0) == 1.0
    assert overlap_frac_from_times(500.0, 50.0, 100.0) == 0.0


def test_is_collective_category_and_scope_fallback():
    assert overlap_mod.is_collective("all-reduce")
    assert overlap_mod.is_collective("AllGather")
    assert overlap_mod.is_collective("reduce scatter")
    assert overlap_mod.is_collective("collective-permute")
    assert not overlap_mod.is_collective("convolution")
    # category silent, named scope in the framework op name decides
    assert overlap_mod.is_collective("", "jit(step)/wire_psum_scatter/...")
    assert not overlap_mod.is_collective("", "jit(step)/einsum")


def test_split_trace_times_degrades_to_none(tmp_path):
    assert split_trace_times(str(tmp_path / "nope")) is None


def test_split_trace_times_synthetic_rows(monkeypatch):
    rows = [
        ("jit(step)/wire_psum_scatter/reduce-scatter", "all-reduce", 40.0),
        ("jit(step)/wire_all_gather/ag", "all-gather", 10.0),
        ("jit(step)/transformer/einsum", "convolution fusion", 150.0),
        ("jit(step)/ring_all_gather/ppermute", "collective-permute", 6.0),
    ]
    monkeypatch.setattr(overlap_mod, "_hlo_stats_rows", lambda d: rows)
    split = split_trace_times("ignored")
    assert split["collective_us"] == pytest.approx(56.0)
    assert split["compute_us"] == pytest.approx(150.0)
    assert split["by_scope"] == {
        "wire_psum_scatter": 40.0, "wire_all_gather": 10.0,
        "ring_all_gather": 6.0,
    }


def test_measure_overlap_per_step_accounting(monkeypatch, tmp_path):
    monkeypatch.setattr(
        jax.profiler, "start_trace", lambda d, **kw: None
    )
    monkeypatch.setattr(jax.profiler, "stop_trace", lambda: None)
    monkeypatch.setattr(
        overlap_mod, "split_trace_times",
        lambda d: {"collective_us": 100.0, "compute_us": 300.0,
                   "by_scope": {"wire_psum": 100.0}},
    )
    ticks = iter([0.0, 350e-6])  # wall = 350 us for 2 steps
    rep = overlap_mod.measure_overlap(
        lambda n: None, str(tmp_path), steps=2,
        clock=lambda: next(ticks),
    )
    assert rep["overlap_frac"] == pytest.approx(0.5)
    assert rep["wall_us_per_step"] == pytest.approx(175.0)
    assert rep["collective_us_per_step"] == pytest.approx(50.0)
    assert rep["by_scope"] == {"wire_psum": 50.0}

    monkeypatch.setattr(overlap_mod, "split_trace_times", lambda d: None)
    assert overlap_mod.measure_overlap(
        lambda n: None, str(tmp_path), clock=time.perf_counter
    ) is None


# ---------------------------------------------------------------------------
# fleet request tracing end to end (tentpole): one trace, many pids
# ---------------------------------------------------------------------------


def test_fleet_trace_request_spans_across_replica_pids(tmp_path):
    path = tmp_path / "fleet_trace.json"
    base = TraceWriter(str(path))
    handles = [
        ReplicaHandle(
            f"r{i}",
            _engine(trace=PrefixedTrace(base, f"r{i}", pid=i + 1)),
        )
        for i in range(2)
    ]
    sentinels = ServeSentinels(trace=base, pressure_frac=0.01)
    router = FleetRouter(
        handles, trace=base, sentinels=sentinels,
        sentinel_interval_s=0.0,
    )
    # 8 requests > 6 fleet slots: some requests must queue, so the
    # queue-wait histogram gets nonzero samples
    report = router.run(_requests(n=8))
    base.close()
    assert all(
        r["status"] == "done" for r in report["results"].values()
    )

    events = json.loads(path.read_text())
    x_pids = {e["pid"] for e in events if e["ph"] == "X"}
    assert {1, 2} <= x_pids  # request spans on BOTH replica pid lanes
    names_by_pid = {}
    for e in events:
        if e["ph"] == "X":
            names_by_pid.setdefault(e["pid"], set()).add(e["name"])
    # router spans ride the host pid lane (0)
    assert any(n.startswith("router/queue:") for n in names_by_pid[0])
    # engine phase spans ride each replica's own lane
    for pid, prefix in ((1, "r0"), (2, "r1")):
        assert any(
            n.startswith(f"{prefix}/prefill:") or n == f"{prefix}/decode_step"
            for n in names_by_pid[pid]
        ), names_by_pid[pid]
    # counter tracks: router queue depth + per-replica kv pool
    counters = {e["name"] for e in events if e["ph"] == "C"}
    assert "router/queue_depth" in counters
    assert counters & {"r0/kv", "r1/kv"}
    # the low-pressure sentinel fired and stamped the timeline
    assert any(
        e["ph"] == "i" and e["name"] == "trigger:kv-pressure"
        for e in events
    )
    m = report["metrics"]
    assert m["ttft_p99_ms"] > 0.0
    assert m["queue_wait_p99_ms"] > 0.0
    assert m["kv_occupancy_max"] > 0.0
    assert [t["kind"] for t in m["sentinel_triggers"]] == ["kv-pressure"]
    assert m["latency"]["tpot_ms"]["count"] >= 0  # snapshot shape


# ---------------------------------------------------------------------------
# overhead: tracing-enabled steady state <= 5% over tracing-off
# ---------------------------------------------------------------------------


def test_serve_tracing_overhead_within_five_percent(tmp_path):
    """The graft-lens acceptance bound: spans+counters on the serving
    path cost <= 5% wall time on an identical warmed workload. Min-of-N
    over interleaved rounds: host scheduling noise is one-sided, so the
    best round measures the machinery."""
    reqs = _requests(n=4, max_new=6)

    def once(trace):
        eng = _engine(trace=trace)
        t0 = time.perf_counter()
        report = eng.run(reqs)
        dt = time.perf_counter() - t0
        assert all(
            r["status"] == "done" for r in report["results"].values()
        )
        return dt

    once(None)  # shake out any residual compile/dispatch warmup
    t_off, t_on = [], []
    gc.disable()
    try:
        for i in range(3):  # interleaved: slow drift cancels per pair
            t_off.append(once(None))
            w = TraceWriter(str(tmp_path / f"t{i}.json"))
            t_on.append(once(w))
            w.close()
    finally:
        gc.enable()
    best_off, best_on = min(t_off), min(t_on)
    # 5% bound plus a small absolute floor for timer/scheduler jitter on
    # a one-core box (same shape as graft-scope's 2% train-side bound)
    assert best_on <= best_off * 1.05 + 0.015, (t_on, t_off)


# ---------------------------------------------------------------------------
# driver contract (slow): ONE JSON line carries the lens metrics
# ---------------------------------------------------------------------------

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _cli_env():
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("DPX_CHAOS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()
    return env


def _one_json_line(stdout):
    lines = [l for l in stdout.strip().splitlines() if l.strip()]
    assert len(lines) == 1, f"expected ONE JSON line on stdout, got {lines!r}"
    return json.loads(lines[0])


@pytest.mark.slow
def test_bench_cli_line_includes_overlap_frac():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "bench.py"),
         "--model", "resnet18", "--image-size", "32",
         "--batch-per-chip", "2", "--warmup", "1", "--steps", "2"],
        capture_output=True, text=True, env=_cli_env(), timeout=600,
        cwd=REPO_ROOT,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    doc = _one_json_line(proc.stdout)
    # the key is ALWAYS present; the value degrades to None where the
    # profile has no per-op device plane (plain CPU runs)
    assert "overlap_frac" in doc
    v = doc["overlap_frac"]
    assert v is None or 0.0 <= v <= 1.0


@pytest.mark.slow
def test_serve_cli_line_and_metrics_snapshot(tmp_path):
    trace = tmp_path / "trace.json"
    snap = tmp_path / "snap.json"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "serve.py"),
         "--requests", "4", "--slots", "2",
         "--vocab-size", "61", "--max-len", "32", "--model-dim", "16",
         "--num-layers", "1", "--num-heads", "2",
         "--num-blocks", "16", "--block-size", "4", "--max-blocks", "4",
         "--prompt-len", "4:8", "--max-new", "4:8",
         "--trace", str(trace), "--metrics-snapshot", str(snap)],
        capture_output=True, text=True, env=_cli_env(), timeout=600,
        cwd=REPO_ROOT,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    doc = _one_json_line(proc.stdout)
    assert doc["ttft_p99_ms"] > 0.0
    assert doc["queue_wait_p99_ms"] >= 0.0
    # sidecar artifacts: a Perfetto-valid trace + the histogram snapshot
    events = json.loads(trace.read_text())
    assert any(e["ph"] == "X" for e in events)
    payload = json.loads(snap.read_text())
    assert set(payload) == {"metrics", "config"}
    assert payload["metrics"]["ttft_ms"]["p99"] > 0.0

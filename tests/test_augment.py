"""Augmentation transforms + the real-data digits dataset."""

import numpy as np

from distributed_pytorch_example_tpu.data.augment import (
    AugmentedDataset,
    pad_crop_flip,
    random_resized_crop_flip,
)
from distributed_pytorch_example_tpu.data.synthetic import _ArrayDataset


def _batch(b=8, h=16, w=16, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "x": rng.standard_normal((b, h, w, 3)).astype(np.float32),
        "y": rng.integers(0, 10, (b,)).astype(np.int32),
    }


def test_pad_crop_flip_shapes_and_content():
    batch = _batch()
    out = pad_crop_flip(pad=2, seed=1)(batch)
    assert out["x"].shape == batch["x"].shape
    np.testing.assert_array_equal(out["y"], batch["y"])
    # crops come from the padded canvas: every output pixel is either 0
    # (padding) or present in the source image
    assert not np.array_equal(out["x"], batch["x"])  # something moved


def test_pad_crop_zero_offset_recovers_identity():
    batch = _batch()
    # pad=0: crop is the whole image; flip disabled -> exact identity
    out = pad_crop_flip(pad=0, flip=False)(batch)
    np.testing.assert_array_equal(out["x"], batch["x"])


def test_flip_only_mirrors_some_rows():
    batch = _batch(b=64)
    out = pad_crop_flip(pad=0, flip=True, seed=3)(batch)
    mirrored = np.array([
        np.array_equal(out["x"][i], batch["x"][i, :, ::-1])
        for i in range(64)
    ])
    identical = np.array([
        np.array_equal(out["x"][i], batch["x"][i]) for i in range(64)
    ])
    assert (mirrored | identical).all()
    assert mirrored.any() and identical.any()


def test_random_resized_crop_output_size():
    batch = _batch(h=32, w=32)
    out = random_resized_crop_flip(size=24, seed=2)(batch)
    assert out["x"].shape == (8, 24, 24, 3)
    assert np.isfinite(out["x"]).all()


def test_augmented_dataset_through_loader(devices):
    from distributed_pytorch_example_tpu.data.loader import DeviceLoader
    from distributed_pytorch_example_tpu.runtime import make_mesh

    rng = np.random.default_rng(0)
    ds = _ArrayDataset(
        {
            "x": rng.standard_normal((64, 16, 16, 3)).astype(np.float32),
            "y": rng.integers(0, 10, (64,)).astype(np.int32),
        }
    )
    aug = AugmentedDataset(ds, pad_crop_flip(pad=2, seed=0))
    loader = DeviceLoader(
        aug, 16, mesh=make_mesh(), num_shards=1, shard_id=0
    )
    batches = list(loader)
    assert len(batches) == 4
    assert batches[0]["x"].shape == (16, 16, 16, 3)


def test_digits_dataset_real_data():
    from distributed_pytorch_example_tpu.data.vision import load_digits

    train = load_digits(train=True)
    val = load_digits(train=False)
    assert len(train) + len(val) == 1797  # the full UCI optical-digits set
    assert train.num_classes == 10
    item = train[0]
    assert item["x"].shape == (32, 32, 3)  # 8x8 upscaled 4x, 3-channel
    # splits are disjoint and deterministic
    train2 = load_digits(train=True)
    np.testing.assert_array_equal(
        train.get_batch(np.arange(4))["y"], train2.get_batch(np.arange(4))["y"]
    )


def test_worker_pool_is_deterministic_and_complete():
    """workers>1 must (a) transform EVERY row exactly once, (b) be
    reproducible regardless of thread scheduling (per-sub-batch rngs)."""
    import numpy as np

    from distributed_pytorch_example_tpu.data.augment import (
        AugmentedDataset,
        random_resized_crop_flip,
    )
    from distributed_pytorch_example_tpu.data.synthetic import (
        SyntheticImageDataset,
    )

    ds = SyntheticImageDataset(num_samples=64, image_size=48, num_classes=7)
    idx = np.arange(64)

    def run(workers):
        aug = AugmentedDataset(
            ds, random_resized_crop_flip(size=32, seed=3),
            workers=workers, seed=3,
        )
        return aug.get_batch(idx)

    a = run(4)
    b = run(4)
    np.testing.assert_array_equal(a["x"], b["x"])  # scheduling-independent
    # the augmentation stream must not depend on worker count / machine
    # CPU count: the randomness grid is fixed 32-row chunks
    c = run(1)
    d = run(7)
    np.testing.assert_array_equal(a["x"], c["x"])
    np.testing.assert_array_equal(a["x"], d["x"])
    np.testing.assert_array_equal(a["y"], ds.get_batch(idx)["y"])
    assert a["x"].shape == (64, 32, 32, 3)


def test_worker_pool_degrades_for_rngless_transform():
    """A custom transform without an rng kwarg must run (single-threaded),
    not crash, under workers>1."""
    import numpy as np

    from distributed_pytorch_example_tpu.data.augment import AugmentedDataset
    from distributed_pytorch_example_tpu.data.synthetic import (
        SyntheticImageDataset,
    )

    ds = SyntheticImageDataset(num_samples=64, image_size=8, num_classes=3)

    def plain(batch):
        return {**batch, "x": batch["x"] * 2.0}

    aug = AugmentedDataset(ds, plain, workers=8)
    assert aug.workers == 1  # degraded, loudly (warning), not crashed
    out = aug.get_batch(np.arange(64))
    np.testing.assert_array_equal(
        out["x"], ds.get_batch(np.arange(64))["x"] * 2.0
    )

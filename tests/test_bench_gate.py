"""scripts/bench_gate.py: the perf-regression gate must actually gate.

Round 3 shipped a 29% ViT regression that nothing caught (VERDICT r3 #1);
the gate exists to make that impossible, so its failure semantics are
pinned here: throughput drops fail, errored models fail, new/missing
models don't, config drift is surfaced, and both payload formats (driver
wrapper with 'parsed'/'tail', raw bench stdout) parse.
"""

import json
import os
import subprocess
import sys

import pytest

GATE = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "scripts", "bench_gate.py",
)


def _model(name, value, unit="samples/sec/chip", config=None, error=None):
    if error is not None:
        return {"error": error}
    entry = {
        "metric": f"{name.replace('-', '_')}_samples_per_sec_per_chip",
        "value": value,
        "unit": unit,
    }
    if config:
        entry["config"] = config
    return entry


def _payload(models):
    first = next(v for v in models.values() if "error" not in v)
    return {**first, "models": models}


def _run_gate(prev, cur, tmp_path, extra=()):
    prev_path = tmp_path / "prev.json"
    prev_path.write_text(json.dumps(prev))
    # --noise '' / --scaling '' keep these hermetic: without them the
    # gate auto-discovers the repo's committed results/bench_noise and
    # results/scaling artifacts and these fixture models would pick up
    # the real per-model tolerances and curves
    proc = subprocess.run(
        [sys.executable, GATE, "--prev", str(prev_path), "--noise", "",
         "--scaling", "", *extra],
        input=json.dumps(cur), capture_output=True, text=True,
    )
    return proc.returncode, proc.stderr


def test_ok_within_tolerance(tmp_path):
    prev = _payload({"resnet50": _model("resnet50", 1000.0)})
    cur = _payload({"resnet50": _model("resnet50", 980.0)})  # -2%
    rc, err = _run_gate(prev, cur, tmp_path)
    assert rc == 0, err
    assert "OK" in err


def test_regression_fails(tmp_path):
    prev = _payload({"resnet50": _model("resnet50", 1000.0)})
    cur = _payload({"resnet50": _model("resnet50", 900.0)})  # -10%
    rc, err = _run_gate(prev, cur, tmp_path)
    assert rc == 1
    assert "REGRESSION" in err


def test_errored_model_fails(tmp_path):
    """A model that CRASHES must fail the gate, not read as 'missing'."""
    prev = _payload({
        "resnet50": _model("resnet50", 1000.0),
        "vit-b16": _model("vit-b16", 990.0),
    })
    cur = _payload({
        "resnet50": _model("resnet50", 1000.0),
        "vit-b16": _model("vit-b16", 0, error="compile exploded"),
    })
    rc, err = _run_gate(prev, cur, tmp_path)
    assert rc == 1
    assert "ERRORED" in err and "compile exploded" in err


def test_new_and_missing_models_pass(tmp_path):
    """--model single runs legitimately omit the sweep; new models have no
    baseline. Neither fails, both are visible in the report."""
    prev = _payload({
        "resnet50": _model("resnet50", 1000.0),
        "vit-b16": _model("vit-b16", 990.0),
    })
    cur = _payload({
        "resnet50": _model("resnet50", 1000.0),
        "llama": _model("llama", 500.0),
    })
    rc, err = _run_gate(prev, cur, tmp_path)
    assert rc == 0, err
    assert "MISSING" in err and "NEW" in err


def test_config_drift_is_surfaced(tmp_path):
    prev = _payload({
        "resnet50": _model(
            "resnet50", 1000.0, config={"batch_per_chip": 128, "steps": 40}
        ),
    })
    cur = _payload({
        "resnet50": _model(
            "resnet50", 960.0, config={"batch_per_chip": 64, "steps": 40}
        ),
    })
    rc, err = _run_gate(prev, cur, tmp_path)
    assert rc == 0  # -4% is inside tolerance; the drift itself doesn't fail
    assert "CONFIG CHANGED" in err and "batch_per_chip" in err


def test_steps_change_not_flagged_as_config_drift(tmp_path):
    """steps/warmup are measurement-window knobs, not workload config."""
    prev = _payload({
        "resnet50": _model(
            "resnet50", 1000.0, config={"batch_per_chip": 128, "steps": 20}
        ),
    })
    cur = _payload({
        "resnet50": _model(
            "resnet50", 990.0, config={"batch_per_chip": 128, "steps": 40}
        ),
    })
    rc, err = _run_gate(prev, cur, tmp_path)
    assert rc == 0
    assert "CONFIG CHANGED" not in err


def test_driver_wrapper_parsed_field(tmp_path):
    """Driver-wrapped BENCH_r*.json: the pre-parsed stdout line wins even
    when the tail log is truncated mid-line."""
    inner = _payload({
        "resnet50": _model("resnet50", 1000.0),
        "vit-b16": _model("vit-b16", 990.0),
    })
    wrapper = {
        "n": 3, "cmd": "python bench.py", "rc": 0,
        "tail": json.dumps(inner)[:50],  # truncated mid-JSON
        "parsed": inner,
    }
    cur = _payload({
        "resnet50": _model("resnet50", 1000.0),
        "vit-b16": _model("vit-b16", 700.0),  # -29%: the r3 scenario
    })
    rc, err = _run_gate(wrapper, cur, tmp_path)
    assert rc == 1
    assert "vit-b16" in err and "REGRESSION" in err


def test_single_model_raw_line(tmp_path):
    """A bare single-model bench line (no 'models') compares by metric name."""
    prev = _payload({"gpt2": _model("gpt2", 130000.0, unit="tokens/sec/chip")})
    cur = _model("gpt2", 100000.0, unit="tokens/sec/chip")
    rc, err = _run_gate(prev, cur, tmp_path)
    assert rc == 1
    assert "gpt2" in err


def test_tolerance_flag(tmp_path):
    prev = _payload({"resnet50": _model("resnet50", 1000.0)})
    cur = _payload({"resnet50": _model("resnet50", 900.0)})
    rc, _ = _run_gate(prev, cur, tmp_path, extra=("--tolerance", "0.15"))
    assert rc == 0


def test_per_model_noise_tolerances(tmp_path):
    """The measured noise floor gates per model: a drop inside a noisy
    model's floor passes while a smaller drop past a quiet model's floor
    fails — one uniform tolerance can't do both."""
    noise_path = tmp_path / "noise.json"
    noise_path.write_text(json.dumps({
        "models": {
            "resnet18": {"tolerance": 0.14},
            "vit-b16": {"tolerance": 0.03},
        }
    }))
    prev = _payload({
        "resnet18": _model("resnet18", 1000.0),
        "vit-b16": _model("vit-b16", 1000.0),
    })
    cur = _payload({
        "resnet18": _model("resnet18", 900.0),  # -10%: inside its 14% floor
        "vit-b16": _model("vit-b16", 960.0),    # -4%: past its 3% floor
    })
    prev_path = tmp_path / "prev.json"
    prev_path.write_text(json.dumps(prev))
    proc = subprocess.run(
        [sys.executable, GATE, "--prev", str(prev_path),
         "--noise", str(noise_path)],
        input=json.dumps(cur), capture_output=True, text=True,
    )
    assert proc.returncode == 1
    lines = {ln.strip().split(":")[0]: ln for ln in proc.stderr.splitlines()
             if ln.strip().startswith(("resnet18", "vit-b16"))}
    assert "REGRESSION" in lines["vit-b16"]
    assert "REGRESSION" not in lines["resnet18"]
    assert "gate 14%" in lines["resnet18"]


def test_latest_bench_sorts_numerically(tmp_path):
    """r100 must beat r99 (lexicographic sort picks r99)."""
    sys.path.insert(0, os.path.dirname(GATE))
    try:
        from bench_gate import _latest_bench
    finally:
        sys.path.pop(0)
    for name in ("BENCH_r99.json", "BENCH_r100.json", "BENCH_r04.json"):
        (tmp_path / name).write_text("{}")
    assert _latest_bench(str(tmp_path)).endswith("BENCH_r100.json")


def test_not_a_bench_payload(tmp_path):
    prev_path = tmp_path / "prev.json"
    prev_path.write_text(json.dumps({"nonsense": True}))
    proc = subprocess.run(
        [sys.executable, GATE, "--prev", str(prev_path)],
        input="{}", capture_output=True, text=True,
    )
    assert proc.returncode != 0


def _scaling_artifact(eff_by_world, model="resnet18", mode="overlap"):
    return {
        "kind": "dp-weak-scaling",
        "host_multiplexed": True,
        "world_sizes": sorted(int(w) for w in eff_by_world),
        "baseline_models": [model],
        "models": {
            model: {"modes": {mode: {"efficiency": eff_by_world}}}
        },
    }


def test_scaling_curve_below_floor_fails_by_model_and_world(tmp_path):
    """A committed dp-scaling curve sagging below the floor fails the
    gate naming (model, world size) — the ISSUE-19 acceptance gate."""
    scaling_path = tmp_path / "scaling.json"
    scaling_path.write_text(json.dumps(_scaling_artifact(
        {"1": 1.0, "2": 0.97, "4": 0.95, "8": 0.84}
    )))
    prev = _payload({"resnet50": _model("resnet50", 1000.0)})
    cur = _payload({"resnet50": _model("resnet50", 1000.0)})
    rc, err = _run_gate(
        prev, cur, tmp_path, extra=("--scaling", str(scaling_path)),
    )
    assert rc == 1
    assert "resnet18 (W=8, overlap)" in err
    assert "dp-scaling below floor" in err
    assert "W=4" not in err.split("FAIL")[-1]  # only W=8 named as failing


def test_scaling_curve_above_floor_passes_and_reports(tmp_path):
    scaling_path = tmp_path / "scaling.json"
    scaling_path.write_text(json.dumps(_scaling_artifact(
        {"1": 1.0, "2": 0.99, "4": 0.96, "8": 0.93}
    )))
    prev = _payload({"resnet50": _model("resnet50", 1000.0)})
    cur = _payload({"resnet50": _model("resnet50", 1000.0)})
    rc, err = _run_gate(
        prev, cur, tmp_path, extra=("--scaling", str(scaling_path)),
    )
    assert rc == 0, err
    assert "scaling resnet18/overlap W=8" in err  # curve visible in report


def test_scaling_floor_flag_and_non_baseline_models_advisory(tmp_path):
    """--scaling-floor moves the bar; models not in baseline_models are
    exempt (experimental zoo entries don't gate)."""
    art = _scaling_artifact({"1": 1.0, "8": 0.85})
    art["models"]["llama-exp"] = {
        "modes": {"overlap": {"efficiency": {"1": 1.0, "8": 0.5}}}
    }
    scaling_path = tmp_path / "scaling.json"
    scaling_path.write_text(json.dumps(art))
    prev = _payload({"resnet50": _model("resnet50", 1000.0)})
    cur = _payload({"resnet50": _model("resnet50", 1000.0)})
    rc, err = _run_gate(
        prev, cur, tmp_path,
        extra=("--scaling", str(scaling_path), "--scaling-floor", "0.80"),
    )
    assert rc == 0, err
    assert "llama-exp" not in err

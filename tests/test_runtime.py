"""Runtime: hostname→rank derivation, coordinator DNS, mesh construction.

Covers the launcher contract (reference entrypoint.sh:24-28) that SURVEY.md
§4 lists as a required unit test.
"""

import pytest

from distributed_pytorch_example_tpu.runtime import MeshSpec, make_mesh
from distributed_pytorch_example_tpu.runtime.distributed import (
    derive_coordinator_address,
    derive_process_id,
    resolve_config,
)
from distributed_pytorch_example_tpu.runtime.mesh import (
    data_axes,
    data_parallel_size,
)


def test_derive_process_id_hostname_suffix():
    # NODE_RANK=${HOSTNAME##*-} parity (entrypoint.sh:25)
    assert derive_process_id("trainer-3") == 3
    assert derive_process_id("my-job-12") == 12
    assert derive_process_id("nosuffix") == 0
    assert derive_process_id("trailing-dash-") == 0


def test_derive_coordinator_address():
    # MASTER_ADDR="${BASE_NAME}-0.${HEADLESS_SERVICE}" parity (entrypoint.sh:26-28)
    addr = derive_coordinator_address(
        hostname="trainer-3", discovery_service="svc.ns", port=29500
    )
    assert addr == "trainer-0.svc.ns:29500"
    assert (
        derive_coordinator_address(hostname="job-1", discovery_service=None, port=1234)
        == "job-0:1234"
    )


def test_resolve_config_single_process_default():
    cfg = resolve_config(env={})
    assert cfg.num_processes == 1 and cfg.process_id == 0
    assert not cfg.is_distributed


def test_resolve_config_from_reference_env_contract():
    # REPLICAS + NF_DISCOVERY_SERVICE + HOSTNAME, as the container sets them
    # (Dockerfile:13-15, entrypoint.sh:5-8)
    cfg = resolve_config(
        env={
            "REPLICAS": "4",
            "NF_DISCOVERY_SERVICE": "disc.svc",
            "HOSTNAME": "worker-2",
            "MASTER_PORT": "29501",
        }
    )
    assert cfg.num_processes == 4
    assert cfg.process_id == 2
    assert cfg.coordinator_address == "worker-0.disc.svc:29501"


def test_resolve_config_explicit_overrides():
    cfg = resolve_config(
        env={
            "NUM_PROCESSES": "2",
            "PROCESS_ID": "1",
            "COORDINATOR_ADDRESS": "10.0.0.1:9999",
        }
    )
    assert cfg.process_id == 1
    assert cfg.coordinator_address == "10.0.0.1:9999"


def test_mesh_default_all_data(devices):
    mesh = make_mesh()
    assert dict(mesh.shape) == {"data": 8, "fsdp": 1, "tensor": 1, "sequence": 1, "expert": 1, "pipe": 1}
    assert data_parallel_size(mesh) == 8


def test_mesh_spec_resolution(devices):
    mesh = make_mesh(MeshSpec(data=2, fsdp=2, tensor=2))
    assert dict(mesh.shape) == {"data": 2, "fsdp": 2, "tensor": 2, "sequence": 1, "expert": 1, "pipe": 1}
    assert data_axes(mesh) == ("data", "fsdp")
    assert data_parallel_size(mesh) == 4


def test_mesh_spec_errors(devices):
    with pytest.raises(ValueError):
        MeshSpec(data=3, fsdp=1).resolve(8)  # not divisible
    with pytest.raises(ValueError):
        MeshSpec(data=-1, fsdp=-1).resolve(8)  # two unknowns


class TestMultiSliceMesh:
    """DCN-aware hybrid-mesh policy (decision logic; the hybrid call itself
    needs real multi-slice hardware and falls back gracefully without it)."""

    def test_hybrid_shapes_put_slices_on_data(self):
        from distributed_pytorch_example_tpu.runtime.mesh import (
            MeshSpec,
            _hybrid_shapes,
        )

        spec = MeshSpec(data=8, tensor=4).resolve(32)
        per_slice, dcn = _hybrid_shapes(spec, 2)
        assert per_slice == (4, 1, 4, 1, 1, 1)  # data halved per slice
        assert dcn == (2, 1, 1, 1, 1, 1)  # slice dim on 'data' only

    def test_hybrid_declined_when_indivisible_or_single_slice(self):
        from distributed_pytorch_example_tpu.runtime.mesh import (
            MeshSpec,
            _hybrid_shapes,
        )

        assert _hybrid_shapes(MeshSpec(data=3).resolve(3), 2) is None
        assert _hybrid_shapes(MeshSpec(data=8).resolve(8), 1) is None

    def test_num_slices_unknown_is_single(self):
        from distributed_pytorch_example_tpu.runtime.mesh import _num_slices

        class D:  # CPU devices: no slice_index attr
            pass

        assert _num_slices([D(), D()]) == 1

        class S:
            def __init__(self, i):
                self.slice_index = i

        assert _num_slices([S(0), S(0), S(1), S(1)]) == 2

    def test_hybrid_falls_back_to_fsdp_axis_for_zero_configs(self):
        from distributed_pytorch_example_tpu.runtime.mesh import (
            MeshSpec,
            _hybrid_shapes,
        )

        spec = MeshSpec(data=1, fsdp=-1).resolve(16)  # ZeRO: all on fsdp
        per_slice, dcn = _hybrid_shapes(spec, 2)
        assert per_slice == (1, 8, 1, 1, 1, 1)
        assert dcn == (1, 2, 1, 1, 1, 1)  # slice dim on 'fsdp'

    def test_hybrid_mesh_layout_on_virtual_slices(self, devices):
        """make_mesh(n_slices=2) on 8 CPU devices: the device array places
        the two slice groups along the DATA axis (crossing data crosses
        the declared DCN boundary) and TP stays within a slice."""
        from distributed_pytorch_example_tpu.runtime.mesh import (
            MeshSpec,
            make_mesh,
        )

        mesh = make_mesh(MeshSpec(data=4, tensor=2), n_slices=2)
        assert dict(mesh.shape)["data"] == 4
        dev = mesh.devices  # (data=4, fsdp=1, tensor=2, 1, 1, 1)
        first_half = {d.id for d in devices[:4]}
        # data rows 0..1 come from slice 0, rows 2..3 from slice 1
        assert {d.id for d in dev[:2].flatten()} <= first_half
        assert {d.id for d in dev[2:].flatten()}.isdisjoint(first_half)
        # each tensor pair (fixed data row) stays inside ONE slice
        for row in range(4):
            ids = {d.id for d in dev[row].flatten()}
            assert ids <= first_half or ids.isdisjoint(first_half)

    def test_hybrid_mesh_trains_end_to_end(self, devices):
        """A full sharded train step executes over the 2-virtual-slice
        hybrid mesh — the SURVEY L2 ICI/DCN mapping as a compiled program,
        not a decision table (VERDICT r4 ask #5)."""
        import optax

        from distributed_pytorch_example_tpu.data.loader import DeviceLoader
        from distributed_pytorch_example_tpu.data.synthetic import (
            SyntheticTokenDataset,
        )
        from distributed_pytorch_example_tpu.models.gpt2 import GPT2
        from distributed_pytorch_example_tpu.parallel.partition import (
            transformer_partitioner,
        )
        from distributed_pytorch_example_tpu.runtime.mesh import (
            MeshSpec,
            make_mesh,
        )
        from distributed_pytorch_example_tpu.train.loop import Trainer
        from distributed_pytorch_example_tpu.train.tasks import CausalLMTask

        import numpy as np

        mesh = make_mesh(MeshSpec(data=4, tensor=2), n_slices=2)
        model = GPT2(
            vocab_size=64, max_len=32, model_dim=16, num_layers=2,
            num_heads=2, mlp_dim=32, logits_mode="hidden",
        )
        dataset = SyntheticTokenDataset(
            num_samples=32, seq_len=16, vocab_size=64
        )
        loader = DeviceLoader(dataset, 8, mesh=mesh, num_shards=1, shard_id=0)
        trainer = Trainer(
            model, CausalLMTask(), optax.adam(1e-2),
            partitioner=transformer_partitioner(mesh),
        )
        with mesh:
            trainer.init(next(iter(loader))["tokens"])
            state, metrics = trainer.train_step(
                trainer.state, next(iter(loader))
            )
        assert np.isfinite(float(metrics["loss"]))

"""Ring attention vs full attention on the fake 8-device mesh.

The sequence axis spans 4 devices; results must match the single-device
XLA reference bit-closely for both causal and non-causal, proving the
cross-shard online-softmax merge and the global causal mask reconstruction.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_pytorch_example_tpu.ops.attention import _xla_attention
from distributed_pytorch_example_tpu.ops.ring_attention import ring_attention_sharded
from distributed_pytorch_example_tpu.runtime import MeshSpec, make_mesh
from distributed_pytorch_example_tpu.runtime.jax_compat import shard_map as _shard_map


def make_qkv(batch=2, seq=256, heads=2, head_dim=32, seed=0):
    rng = np.random.default_rng(seed)
    shape = (batch, seq, heads, head_dim)
    return tuple(
        jnp.asarray(rng.standard_normal(shape), jnp.float32) for _ in range(3)
    )


@pytest.mark.parametrize("causal", [False, True])
def test_matches_full_attention(devices, causal):
    mesh = make_mesh(MeshSpec(data=2, sequence=4))
    q, k, v = make_qkv()
    scale = q.shape[-1] ** -0.5
    expected = _xla_attention(q, k, v, None, None, causal, scale)
    got = ring_attention_sharded(q, k, v, mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_grads_match_full_attention(devices, causal):
    mesh = make_mesh(MeshSpec(data=2, sequence=4))
    q, k, v = make_qkv(seq=128)
    scale = q.shape[-1] ** -0.5

    def loss_ref(q, k, v):
        return jnp.sum(_xla_attention(q, k, v, None, None, causal, scale) ** 2)

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention_sharded(q, k, v, mesh, causal=causal) ** 2)

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    for gr, gg, name in zip(g_ref, g_ring, "qkv"):
        np.testing.assert_allclose(
            np.asarray(gg), np.asarray(gr), atol=1e-4, err_msg=f"d{name}"
        )


def test_full_sequence_axis(devices):
    """All 8 devices on the sequence axis (deepest ring)."""
    mesh = make_mesh(MeshSpec(data=1, sequence=8))
    q, k, v = make_qkv(seq=512)
    scale = q.shape[-1] ** -0.5
    expected = _xla_attention(q, k, v, None, None, True, scale)
    got = ring_attention_sharded(q, k, v, mesh, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=2e-5)


def test_inside_jit(devices):
    """Ring attention composes under jit with mesh-sharded inputs."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = make_mesh(MeshSpec(data=2, sequence=4))
    q, k, v = make_qkv()
    sharding = NamedSharding(mesh, P("data", "sequence", None, None))
    q, k, v = (jax.device_put(x, sharding) for x in (q, k, v))

    @jax.jit
    def f(q, k, v):
        return ring_attention_sharded(q, k, v, mesh, causal=True)

    got = f(q, k, v)
    expected = _xla_attention(q, k, v, None, None, True, q.shape[-1] ** -0.5)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=2e-5)


def test_gpt2_seq_parallel_matches_dense(devices):
    """Full model with seq_axis under a sequence mesh == no-SP output."""
    from distributed_pytorch_example_tpu.models.gpt2 import GPT2

    kw = dict(vocab_size=101, max_len=64, model_dim=32, num_layers=2,
              num_heads=4, mlp_dim=64)
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, 101, (2, 64)), jnp.int32
    )
    dense = GPT2(**kw)
    sp = GPT2(seq_axis="sequence", **kw)
    variables = dense.init(jax.random.key(0), tokens, train=False)
    expected = dense.apply(variables, tokens, train=False)

    mesh = make_mesh(MeshSpec(data=2, sequence=4))
    with mesh:
        got = sp.apply(variables, tokens, train=False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=2e-5)


def test_dryrun_multichip_exercises_sp():
    """The driver dry-run (dp+fsdp+tp+sp mesh) runs a full train step."""
    import __graft_entry__ as g

    g.dryrun_multichip(8)


def test_trainer_actually_uses_ring(devices, monkeypatch, tmp_path):
    """Trainer enters the mesh context, so seq_axis reaches the ring path.

    The dense fallback is numerically identical, so this guards the wiring
    (not the math) with a call spy.
    """
    import optax

    from distributed_pytorch_example_tpu import ops
    from distributed_pytorch_example_tpu.data.loader import DeviceLoader
    from distributed_pytorch_example_tpu.data.synthetic import SyntheticTokenDataset
    from distributed_pytorch_example_tpu.models.gpt2 import GPT2
    from distributed_pytorch_example_tpu.parallel.api import data_parallel
    from distributed_pytorch_example_tpu.train.loop import Trainer
    from distributed_pytorch_example_tpu.train.tasks import CausalLMTask
    from distributed_pytorch_example_tpu.ops import ring_attention as ring_mod

    calls = []
    real = ring_mod.ring_attention_sharded

    def spy(*a, **kw):
        calls.append(1)
        return real(*a, **kw)

    monkeypatch.setattr(ring_mod, "ring_attention_sharded", spy)

    mesh = make_mesh(MeshSpec(data=2, sequence=4))
    model = GPT2(vocab_size=64, max_len=32, model_dim=32, num_layers=1,
                 num_heads=4, mlp_dim=64, seq_axis="sequence")
    ds = SyntheticTokenDataset(num_samples=16, seq_len=16, vocab_size=64)
    loader = DeviceLoader(ds, 4, mesh=mesh, num_shards=1, shard_id=0)
    trainer = Trainer(model, CausalLMTask(), optax.adam(1e-3),
                      partitioner=data_parallel(mesh))
    it = iter(loader)
    trainer.init(next(it)["tokens"])  # Trainer enters the mesh itself
    calls.clear()  # prove the TRAIN STEP traces ring, not just init
    with mesh:  # raw train_step bypasses Trainer._mesh_ctx: caller's job
        trainer.train_step(trainer.state, next(it))
    assert calls, "ring_attention_sharded was never invoked via the Trainer"


@pytest.mark.parametrize("causal", [False, True])
def test_flash_folds_match_full_attention(devices, causal):
    """Pallas local folds (interpret mode) through the ring: fwd + grads."""
    import functools

    from distributed_pytorch_example_tpu.ops.ring_attention import ring_attention
    from jax.sharding import PartitionSpec as P

    mesh = make_mesh(MeshSpec(data=2, sequence=4))
    # flash shapes: s_local (512/4=128) % 128 == 0, head_dim 64
    q, k, v = make_qkv(seq=512, head_dim=64)
    scale = q.shape[-1] ** -0.5
    spec = P("data", "sequence", None, None)
    # check_vma=False: the pallas HLO *interpreter* (CPU stand-in for the
    # TPU kernels) does not propagate varying-manual-axes through its
    # internal slicing; the compiled TPU path runs under full vma checking
    ring = _shard_map(
        functools.partial(
            ring_attention, axis_name="sequence", causal=causal,
            use_flash=True, flash_interpret=True,
        ),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )

    expected = _xla_attention(q, k, v, None, None, causal, scale)
    got = ring(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=2e-5)

    def loss_ref(q, k, v):
        return jnp.sum(_xla_attention(q, k, v, None, None, causal, scale) ** 2)

    def loss_ring(q, k, v):
        return jnp.sum(ring(q, k, v) ** 2)

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    for gr, gg, name in zip(g_ref, g_ring, "qkv"):
        np.testing.assert_allclose(
            np.asarray(gg), np.asarray(gr), atol=2e-3, err_msg=f"d{name}"
        )


def test_backward_residuals_are_o_of_local_seq(devices):
    """The custom VJP saves only O(S_local) residuals: q,k,v,out,lse —
    no per-fold softmax weights (the ADVICE round-1 memory finding)."""
    import functools

    from distributed_pytorch_example_tpu.ops.ring_attention import ring_attention
    from jax.sharding import PartitionSpec as P

    mesh = make_mesh(MeshSpec(data=2, sequence=4))
    q, k, v = make_qkv(batch=2, seq=256, head_dim=32)
    spec = P(None, "sequence", None, None)
    ring = _shard_map(
        functools.partial(ring_attention, axis_name="sequence", causal=True),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
    )
    # residual budget: count total f32 words saved between fwd and bwd via
    # the jaxpr of the VJP: quadratic per-fold residuals (S_local x S_global
    # = 64*256 per head) would blow past q/k/v/out/lse (~5 * 1*64*2*32)
    out, vjp = jax.vjp(lambda q, k, v: ring(q, k, v), q, k, v)
    res_leaves = jax.tree_util.tree_leaves(vjp)
    words = sum(int(np.prod(l.shape)) for l in res_leaves if hasattr(l, "shape"))
    batch, seq, heads, hd = q.shape
    linear_budget = 6 * batch * seq * heads * hd  # q,k,v,out,lse + slack
    # quadratic per-fold residuals would be n_chunks * B*S_loc*N*S_loc
    # = 4 * 2*64*2*64 = 65k words on TOP of the linear set
    assert words <= linear_budget, (
        f"VJP residuals hold {words} words — quadratic per-fold softmax "
        f"residuals are back (budget {linear_budget})"
    )


def test_flash_folds_non_512_divisible_shard(devices):
    """s_local % 512 != 0 (640): blocks must shrink to divide, not truncate."""
    import functools

    from distributed_pytorch_example_tpu.ops.ring_attention import ring_attention
    from jax.sharding import PartitionSpec as P

    mesh = make_mesh(MeshSpec(data=4, sequence=2))
    q, k, v = make_qkv(batch=1, seq=1280, heads=1, head_dim=64)
    scale = q.shape[-1] ** -0.5
    spec = P(None, "sequence", None, None)
    ring = _shard_map(
        functools.partial(
            ring_attention, axis_name="sequence", causal=True,
            use_flash=True, flash_interpret=True,
        ),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )
    expected = _xla_attention(q, k, v, None, None, True, scale)
    got = ring(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_gqa_matches_full_attention(devices, causal):
    """Grouped-query attention on the ring: kv chunks carry only kv_heads
    and are expanded chunk-locally (O(S_chunk), unlike Ulysses' whole-
    sequence replication); must match the dense GQA reference."""
    mesh = make_mesh(MeshSpec(data=2, sequence=4))
    rng = np.random.default_rng(2)
    q = jnp.asarray(rng.standard_normal((2, 256, 4, 32)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((2, 256, 2, 32)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((2, 256, 2, 32)), jnp.float32)
    scale = q.shape[-1] ** -0.5
    expected = _xla_attention(q, k, v, None, None, causal, scale)
    got = ring_attention_sharded(q, k, v, mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=2e-5)


def test_gqa_grads_match_full_attention(devices):
    mesh = make_mesh(MeshSpec(data=2, sequence=4))
    rng = np.random.default_rng(3)
    q = jnp.asarray(rng.standard_normal((2, 128, 4, 32)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((2, 128, 2, 32)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((2, 128, 2, 32)), jnp.float32)
    scale = q.shape[-1] ** -0.5

    def loss_ref(q, k, v):
        return jnp.sum(_xla_attention(q, k, v, None, None, True, scale) ** 2)

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention_sharded(q, k, v, mesh, causal=True) ** 2)

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    for gr, gg, name in zip(g_ref, g_ring, "qkv"):
        assert gg.shape == gr.shape, name  # dk/dv stay at kv_heads
        np.testing.assert_allclose(
            np.asarray(gg), np.asarray(gr), atol=1e-4, err_msg=f"d{name}"
        )


def test_indivisible_gqa_heads_rejected(devices):
    mesh = make_mesh(MeshSpec(data=2, sequence=4))
    q = jnp.zeros((2, 128, 4, 32))
    kv = jnp.zeros((2, 128, 3, 32))  # 4 % 3 != 0
    with pytest.raises(ValueError, match="multiple of kv heads"):
        ring_attention_sharded(q, kv, kv, mesh, causal=True)


def test_llama_trains_with_ring_sp(devices):
    """The LLaMA family (GQA + RoPE) on the RING path under a sequence
    mesh: the combination the r2 code refused (pointing users at Ulysses)
    now trains, giving GQA models O(S_local) ring memory for long
    context."""
    import optax

    import distributed_pytorch_example_tpu as dpx
    from distributed_pytorch_example_tpu.train.tasks import CausalLMTask

    mesh = make_mesh(MeshSpec(data=4, sequence=2))
    model = dpx.models.get_model(
        "llama", vocab_size=64, max_len=32, model_dim=32, num_layers=2,
        num_heads=4, num_kv_heads=2, mlp_dim=64, seq_axis="sequence",
        sp_mode="ring", use_flash=False, logits_mode="hidden",
    )
    trainer = dpx.train.Trainer(
        model, CausalLMTask(), optax.adam(1e-2),
        partitioner=dpx.parallel.data_parallel(mesh),
    )
    tokens = np.random.default_rng(0).integers(0, 64, (8, 16)).astype(np.int32)
    sharding = trainer.partitioner.batch_sharding()
    batch = {"tokens": jax.make_array_from_process_local_data(sharding, tokens)}
    with mesh:
        trainer.init(batch["tokens"])
        losses = []
        state = trainer.state
        for _ in range(4):
            state, metrics = trainer.train_step(state, batch)
            losses.append(float(metrics["loss"]))
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0], losses


@pytest.mark.parametrize("causal", [False, True])
def test_gqa_flash_folds_match_full_attention(devices, causal):
    """GQA through the ring's FLASH chunk path (interpret mode) — the
    combination real TPUs auto-select: the kernel's n//group kv routing
    composed with the ring's lax.switch variants and travelling dk/dv
    accumulators must match the dense GQA reference, values and grads."""
    import functools

    from distributed_pytorch_example_tpu.ops.ring_attention import (
        ring_attention,
    )
    from jax.sharding import PartitionSpec as P

    mesh = make_mesh(MeshSpec(data=2, sequence=4))
    rng = np.random.default_rng(4)
    q = jnp.asarray(rng.standard_normal((2, 512, 4, 64)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((2, 512, 2, 64)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((2, 512, 2, 64)), jnp.float32)
    scale = q.shape[-1] ** -0.5
    spec = P("data", "sequence", None, None)
    with mesh:
        ring = _shard_map(
            functools.partial(
                ring_attention, axis_name="sequence", causal=causal,
                use_flash=True, flash_interpret=True,
            ),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False,  # see test_flash_folds_* note above
        )
        expected = _xla_attention(q, k, v, None, None, causal, scale)
        got = ring(q, k, v)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(expected), atol=2e-5
        )

        def loss_ref(q, k, v):
            return jnp.sum(
                _xla_attention(q, k, v, None, None, causal, scale) ** 2
            )

        def loss_ring(q, k, v):
            return jnp.sum(ring(q, k, v) ** 2)

        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    for gr, gg, name in zip(g_ref, g_ring, "qkv"):
        assert gg.shape == gr.shape, name  # dk/dv stay at kv_heads
        np.testing.assert_allclose(
            np.asarray(gg), np.asarray(gr), atol=2e-3, err_msg=f"d{name}"
        )

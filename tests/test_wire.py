"""graft-wire: block-quantized collectives (parallel/wire.py) and the
Pallas async ring kernels (ops/pallas/collectives.py).

Three layers of evidence, mirroring the ZeRO-1 test structure:

- quantizer unit bounds (round-trip error per block size, stochastic
  unbiasedness) — pure math, no mesh;
- collective equivalence on the 8-device fake CPU mesh: each wire_*
  drop-in vs the raw ``lax`` collective it replaces, with analytic
  per-block error bounds for the compressed forms and EXACT equality for
  the passthrough forms;
- trajectory equivalence: K optimizer steps fp32 vs int8-block within
  the test_zero1 bars (Adam loss trajectory, SGD param parity — Adam's
  sign-sensitive moments amplify quantization noise on PARAMS far above
  what the LOSS trajectory shows, so the Adam bar is on the loss), plus
  checkpoint resume across a compress-mode flip.

The Pallas ring kernels only lower on TPU; on this CPU mesh every ring
entry point must take the identical-numerics XLA fallback, which is
asserted exactly. The TPU numerics comparison runs wherever the kernel
actually lowers (skipped here).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import PartitionSpec as P

from distributed_pytorch_example_tpu.analysis.collectives import (
    parse_collective_dtypes,
)
from distributed_pytorch_example_tpu.models.gpt2 import GPT2
from distributed_pytorch_example_tpu.ops.pallas import collectives as ring
from distributed_pytorch_example_tpu.parallel import wire as wirelib
from distributed_pytorch_example_tpu.parallel.api import data_parallel
from distributed_pytorch_example_tpu.parallel.wire import (
    WireConfig,
    dequantize_blocks,
    grad_wire_report,
    quantize_blocks,
    wire_all_gather,
    wire_psum,
    wire_psum_scatter,
)
from distributed_pytorch_example_tpu.runtime import jax_compat
from distributed_pytorch_example_tpu.train import checkpoint as ckpt_lib
from distributed_pytorch_example_tpu.train.step import (
    build_train_step,
    init_state,
)
from distributed_pytorch_example_tpu.train.tasks import CausalLMTask

# per-element round-trip bound for one quantize/dequantize pass, in units
# of the block's amax: 0.5/127 round-to-nearest plus up to 2^-8 relative
# bf16 scale error (8-bit significand) on a value up to amax — ~1.0
# quantization steps total (measured worst case ~0.82)
_STEP_BOUND = 1.02 / 127.0


def _tiny_model():
    return GPT2(
        vocab_size=64, max_len=32, model_dim=32, num_layers=1,
        num_heads=2, mlp_dim=64, logits_mode="hidden",
    )


def _batch(partitioner, n=16, seq=16, seed=0):
    tokens = np.random.default_rng(seed).integers(
        0, 64, (n, seq)
    ).astype(np.int32)
    return {
        "tokens": jax.device_put(tokens, partitioner.batch_sharding())
    }


def _smap(mesh, fn, in_specs, out_specs):
    return jax_compat.shard_map(
        fn, mesh, in_specs=in_specs, out_specs=out_specs,
        axis_names={"data"},
    )


# ---------------------------------------------------------------------------
# WireConfig policy
# ---------------------------------------------------------------------------


def test_wireconfig_validation_and_floor():
    with pytest.raises(ValueError, match="compress"):
        WireConfig(compress="fp8")
    with pytest.raises(ValueError, match="param_gather"):
        WireConfig(param_gather="fp16")
    with pytest.raises(ValueError, match="ring"):
        WireConfig(ring="always")
    with pytest.raises(ValueError, match="block_size"):
        WireConfig(block_size=0)

    assert not WireConfig().active
    assert WireConfig(compress="int8-block").active
    assert WireConfig(param_gather="bf16").active

    cfg = WireConfig(compress="int8-block", min_size=2048)
    assert cfg.compresses(2048) and cfg.compresses(1 << 20)
    assert not cfg.compresses(2047)  # bias-sized leaves stay fp32
    assert not WireConfig().compresses(1 << 20)


# ---------------------------------------------------------------------------
# block quantizer: round-trip bounds per block size
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("block_size", [32, 64, 256, 1024])
def test_quantize_roundtrip_error_bound(block_size):
    rng = np.random.default_rng(block_size)
    # 3000 elements: NOT a block multiple for any tested size — the tail
    # block pads with zeros and must slice back off exactly
    x = (rng.standard_normal(3000) * rng.uniform(0.1, 10)).astype(
        np.float32
    )
    q, scales = quantize_blocks(jnp.asarray(x), block_size)
    assert q.dtype == jnp.int8 and scales.dtype == jnp.bfloat16
    out = np.asarray(dequantize_blocks(q, scales, x.shape))
    assert out.shape == x.shape

    err = np.abs(out - x)
    pad = (-x.size) % block_size
    blocks = np.pad(x, (0, pad)).reshape(-1, block_size)
    amax = np.abs(blocks).max(axis=1, keepdims=True)
    bound = np.broadcast_to(amax * _STEP_BOUND, blocks.shape)
    assert (err <= bound.reshape(-1)[: x.size] + 1e-12).all(), err.max()


def test_quantize_zero_block_exact_and_shapes():
    x = jnp.zeros((512,), jnp.float32)
    q, scales = quantize_blocks(x, 128)
    assert np.asarray(dequantize_blocks(q, scales, x.shape)).max() == 0.0
    # one scale per block, values grouped per block
    assert q.shape == (4, 128) and scales.shape == (4, 1)


def test_stochastic_rounding_is_unbiased():
    # unbiasedness is a property of the ROUNDING, so test it on the
    # integer lattice (before the bf16 scale multiplies back in, which
    # adds its own small deterministic error): E[q] must converge to the
    # exact scaled value, which round-to-nearest cannot do
    rng = np.random.default_rng(7)
    x = rng.uniform(-1.0, 1.0, 256).astype(np.float32)
    blocks = x.reshape(-1, 64)
    amax = np.abs(blocks).max(axis=1, keepdims=True)
    scaled = (blocks * (127.0 / amax)).reshape(-1)  # exact target

    rows = jnp.asarray(x)[None]
    acc = np.zeros(x.shape, np.float64)
    n = 200
    for i in range(n):
        q, _ = wirelib._quantize_rows(rows, 64, key=jax.random.key(i))
        draw = np.asarray(q[0], np.float64).reshape(-1)
        # floor(y + u), u ~ U[0,1): every draw within ONE step of y
        assert (np.abs(draw - scaled) < 1.0 + 1e-5).all()
        acc += draw
    mean_err = np.abs(acc / n - scaled).max()
    # std of the mean <= 0.5/sqrt(n) ~ 0.035 steps: 0.2 is ~5 sigma,
    # while round-to-nearest sits a deterministic ~0.5 steps off for
    # mid-step values
    assert mean_err < 0.2, mean_err
    q_det, _ = wirelib._quantize_rows(rows, 64)
    det_err = np.abs(
        np.asarray(q_det[0], np.float64).reshape(-1) - scaled
    ).max()
    assert det_err > mean_err  # nearest-rounding bias really is larger


# ---------------------------------------------------------------------------
# collective drop-ins vs the raw lax collectives (8-device fake mesh)
# ---------------------------------------------------------------------------

_INT8 = WireConfig(compress="int8-block", block_size=64, min_size=1)


def test_wire_psum_scatter_matches_lax(mesh_1d):
    rng = np.random.default_rng(0)
    x = rng.standard_normal((8, 256)).astype(np.float32)

    def wire_fn(v):
        return wire_psum_scatter(
            v, "data", scatter_dimension=1, config=_INT8
        )

    def lax_fn(v):
        return lax.psum_scatter(
            v, "data", scatter_dimension=1, tiled=True
        )

    with mesh_1d:
        # in_specs P("data"): each device contributes a DISTINCT (1, 256)
        # shard; out P("data") stacks each device's scattered chunk
        got = _smap(mesh_1d, wire_fn, (P("data"),), P("data"))(x)
        ref = _smap(mesh_1d, lax_fn, (P("data"),), P("data"))(x)
    got, ref = np.asarray(got), np.asarray(ref)
    assert got.shape == ref.shape == (8, 32)
    # 8 independently quantized contributions sum: bound is the sum of
    # the per-source per-block bounds (conservatively: global amax)
    bound = 8 * np.abs(x).max() * _STEP_BOUND
    assert np.abs(got - ref).max() <= bound
    assert np.abs(got - ref).max() > 0.0  # it really quantized

    # passthrough forms are EXACT: compress="none" and the min_size floor
    for cfg in (WireConfig(), WireConfig(compress="int8-block",
                                         min_size=1 << 20)):
        with mesh_1d:
            exact = _smap(
                mesh_1d,
                lambda v, c=cfg: wire_psum_scatter(
                    v, "data", scatter_dimension=1, config=c
                ),
                (P("data"),), P("data"),
            )(x)
        np.testing.assert_array_equal(np.asarray(exact), ref)


def test_wire_psum_scatter_rejects_indivisible(mesh_1d):
    x = np.zeros((8, 12), np.float32)  # 12 % 8 != 0
    with mesh_1d:
        fn = _smap(
            mesh_1d,
            lambda v: wire_psum_scatter(
                v, "data", scatter_dimension=1, config=_INT8
            ),
            (P("data"),), P("data"),
        )
        with pytest.raises(ValueError, match="must divide"):
            fn(x)


def test_wire_psum_matches_lax(mesh_1d):
    rng = np.random.default_rng(1)
    # 300 elements per shard: NOT divisible by the 8-way axis, so the
    # compressed path exercises its pad/unpad
    x = rng.standard_normal((8, 300)).astype(np.float32)

    with mesh_1d:
        got = _smap(
            mesh_1d,
            lambda v: wire_psum(v, "data", config=_INT8),
            (P("data"),), P("data"),
        )(x)
        ref = _smap(
            mesh_1d,
            lambda v: lax.psum(v, "data"),
            (P("data"),), P("data"),
        )(x)
    got, ref = np.asarray(got), np.asarray(ref)
    # two quantized wire passes: the RS pass sums 8 quantized
    # contributions, then the reduced chunk (magnitude up to 8x the
    # input amax) quantizes once more for the gather
    bound = (8 + 8) * np.abs(x).max() * _STEP_BOUND
    assert np.abs(got - ref).max() <= bound
    assert np.abs(got - ref).max() > 0.0

    with mesh_1d:
        exact = _smap(
            mesh_1d,
            lambda v: wire_psum(v, "data", config=WireConfig()),
            (P("data"),), P("data"),
        )(x)
    np.testing.assert_array_equal(np.asarray(exact), ref)


def test_wire_all_gather_matches_lax(mesh_1d):
    rng = np.random.default_rng(2)
    x = rng.standard_normal((8, 64)).astype(np.float32)

    with mesh_1d:
        got = _smap(
            mesh_1d,
            lambda v: wire_all_gather(
                v, "data", gather_dimension=0, config=_INT8
            ),
            (P("data"),), P(),
        )(x)
        ref = _smap(
            mesh_1d,
            lambda v: lax.all_gather(v, "data", axis=0, tiled=True),
            (P("data"),), P(),
        )(x)
    got, ref = np.asarray(got), np.asarray(ref)
    assert got.shape == ref.shape == (8, 64)
    # gather does not sum: each element carries only ITS OWN shard's
    # one-pass quantization error
    assert np.abs(got - ref).max() <= np.abs(x).max() * _STEP_BOUND
    assert np.abs(got - ref).max() > 0.0


def test_ring_entry_points_fall_back_exactly_on_cpu(mesh_1d):
    """Off-TPU the ring kernels must BE the XLA collective: identical
    bits, not just close — the fallback contract every caller relies on."""
    assert not ring.ring_supported()  # fake CPU mesh
    rng = np.random.default_rng(3)
    x = rng.standard_normal((8, 2, 128)).astype(np.float32)

    with mesh_1d:
        ag = _smap(
            mesh_1d,
            lambda v: ring.ring_all_gather(v, "data"),
            (P("data"),), P(),
        )(x)
        ag_ref = _smap(
            mesh_1d,
            lambda v: lax.all_gather(v, "data", axis=0, tiled=True),
            (P("data"),), P(),
        )(x)
        # shard_map local shape is (1, 256): scatter over dim 1
        rs = _smap(
            mesh_1d,
            lambda v: ring.ring_reduce_scatter(
                v, "data", scatter_dimension=1
            ),
            (P("data"),), P("data"),
        )(np.ascontiguousarray(x.reshape(8, 256)))
        rs_ref = _smap(
            mesh_1d,
            lambda v: lax.psum_scatter(
                v, "data", scatter_dimension=1, tiled=True
            ),
            (P("data"),), P("data"),
        )(np.ascontiguousarray(x.reshape(8, 256)))
    np.testing.assert_array_equal(np.asarray(ag), np.asarray(ag_ref))
    np.testing.assert_array_equal(np.asarray(rs), np.asarray(rs_ref))


def test_ring_kernel_numerics_on_tpu(mesh_1d):
    """The ring kernels vs the XLA collectives where they actually lower
    (f32 adds in ring order vs XLA's order: tight but not bit-exact)."""
    if not ring.ring_supported():
        pytest.skip("Pallas ring kernels need a multi-chip TPU backend")
    rng = np.random.default_rng(4)
    x = rng.standard_normal((8, 1024)).astype(np.float32)
    with mesh_1d:
        ag = _smap(
            mesh_1d,
            lambda v: ring.ring_all_gather(v, "data"),
            (P("data"),), P(),
        )(x)
        ag_ref = _smap(
            mesh_1d,
            lambda v: lax.all_gather(v, "data", axis=0, tiled=True),
            (P("data"),), P(),
        )(x)
        rs = _smap(
            mesh_1d,
            lambda v: ring.ring_reduce_scatter(
                v, "data", scatter_dimension=1
            ),
            (P("data"),), P("data"),
        )(x)
        rs_ref = _smap(
            mesh_1d,
            lambda v: lax.psum_scatter(
                v, "data", scatter_dimension=1, tiled=True
            ),
            (P("data"),), P("data"),
        )(x)
    np.testing.assert_array_equal(np.asarray(ag), np.asarray(ag_ref))
    np.testing.assert_allclose(
        np.asarray(rs), np.asarray(rs_ref), atol=1e-5
    )


# ---------------------------------------------------------------------------
# trajectory equivalence: the compressed step trains the same model
# ---------------------------------------------------------------------------

_RUN_CACHE = {}


def _run(mesh, *, wire, opt="adam", steps=3):
    """(final state, per-step losses, compiled dtype mix) for one config."""
    key = (wire, opt, steps)
    if key in _RUN_CACHE:
        return _RUN_CACHE[key]
    model, task = _tiny_model(), CausalLMTask()
    optimizer = optax.adam(1e-3) if opt == "adam" else optax.sgd(1e-2)
    cfg = (
        WireConfig(compress="int8-block", min_size=1)
        if wire else WireConfig()
    )
    part = data_parallel(
        mesh, dp_shard_opt_state=True, opt_shard_min_size=1, wire=cfg
    )
    batch = _batch(part)
    with mesh:
        state, _ = init_state(
            model, optimizer, batch["tokens"], jax.random.key(0), part
        )
        step = build_train_step(
            model, task, optimizer, partitioner=part, grad_accum_steps=1
        )
        dtypes = parse_collective_dtypes(
            step.lower(state, batch).compile().as_text()
        )
        losses = []
        for _ in range(steps):
            state, metrics = step(state, batch)
            losses.append(float(metrics["loss"]))
    _RUN_CACHE[key] = (state, losses, dtypes)
    return _RUN_CACHE[key]


def _max_diff(a, b):
    diffs = jax.tree_util.tree_map(
        lambda x, y: float(
            jnp.max(jnp.abs(x.astype(jnp.float32) - y.astype(jnp.float32)))
        ),
        a, b,
    )
    return max(jax.tree_util.tree_leaves(diffs))


def test_int8_step_trajectory_matches_fp32_adam(mesh_1d):
    """K-step Adam LOSS trajectory within the test_zero1 bar, and the
    compiled step really moves s8 bytes."""
    _, losses_fp32, dt_fp32 = _run(mesh_1d, wire=False)
    _, losses_int8, dt_int8 = _run(mesh_1d, wire=True)

    for lf, li in zip(losses_fp32, losses_int8):
        assert abs(lf - li) < 1e-3, (losses_fp32, losses_int8)
    # the losses must DIFFER somewhere: identical trajectories would mean
    # the compressed path silently fell back to fp32
    assert losses_fp32 != losses_int8

    s8 = sum(rec.get("s8", 0) for rec in dt_int8.values())
    assert s8 > 0, dt_int8
    assert sum(rec.get("s8", 0) for rec in dt_fp32.values()) == 0
    # the quantized RS decomposes to all-to-all; the fp32 step keeps the
    # literal reduce-scatter
    assert "all-to-all" in dt_int8 and "reduce-scatter" not in dt_int8
    assert "reduce-scatter" in dt_fp32


def test_int8_step_param_parity_sgd(mesh_1d):
    """SGD has no sign-sensitive moment accumulation, so PARAMS stay
    within the ZeRO-1 equivalence bar under quantized gradients."""
    s_fp32, _, _ = _run(mesh_1d, wire=False, opt="sgd")
    s_int8, _, _ = _run(mesh_1d, wire=True, opt="sgd")
    assert _max_diff(s_fp32.params, s_int8.params) < 5e-4


def test_checkpoint_resume_across_compress_flip(mesh_1d, tmp_path):
    """A checkpoint written by a wire-compressed run restores into an
    fp32-wire step (and back): compression changes bytes on the WIRE,
    never the checkpointed state contract."""
    path = str(tmp_path / "ckpt")
    model, task = _tiny_model(), CausalLMTask()
    optimizer = optax.adam(1e-3)

    def build(compress):
        cfg = WireConfig(compress=compress, min_size=1)
        part = data_parallel(
            mesh_1d, dp_shard_opt_state=True, opt_shard_min_size=1,
            wire=cfg,
        )
        batch = _batch(part)
        with mesh_1d:
            state, shardings = init_state(
                model, optimizer, batch["tokens"], jax.random.key(0), part
            )
            step = build_train_step(
                model, task, optimizer, partitioner=part,
                grad_accum_steps=1,
            )
        return part, batch, state, shardings, step

    _, batch, state, _, step = build("int8-block")
    with mesh_1d:
        for _ in range(2):
            state, _ = step(state, batch)
    ckpt_lib.save_checkpoint(path, state, 1, 0.0, {})

    _, batch_f, template_f, shardings_f, step_f = build("none")
    loaded, epoch, _ = ckpt_lib.load_checkpoint(
        path, template_f, shardings_f
    )
    assert epoch == 1
    assert _max_diff(loaded.params, state.params) == 0.0
    assert _max_diff(loaded.opt_state[0].mu, state.opt_state[0].mu) == 0.0
    with mesh_1d:
        stepped, _ = step_f(loaded, batch_f)

    ckpt_lib.save_checkpoint(path, stepped, 2, 0.0, {})
    _, batch_q, template_q, shardings_q, step_q = build("int8-block")
    loaded_q, epoch_q, _ = ckpt_lib.load_checkpoint(
        path, template_q, shardings_q
    )
    assert epoch_q == 2
    assert _max_diff(loaded_q.params, stepped.params) == 0.0
    with mesh_1d:
        step_q(loaded_q, batch_q)


# ---------------------------------------------------------------------------
# analytic wire accounting (what bench.py and the budget signature read)
# ---------------------------------------------------------------------------


def test_grad_wire_report_ratio_and_bytes(mesh_1d):
    part = data_parallel(
        mesh_1d, dp_shard_opt_state=True, opt_shard_min_size=1,
        wire=WireConfig(compress="int8-block", min_size=1),
    )
    params = {
        "w": jnp.zeros((64, 64), jnp.float32),
        "b": jnp.zeros((64,), jnp.float32),
    }
    report = grad_wire_report(params, part)
    assert report["compress"] == "int8-block"
    assert report["dp_degree"] == 8
    # every leaf compresses (min_size=1): the ratio approaches
    # 4 / (1 + 2/block) regardless of the RS-vs-AR pass mix
    assert report["wire_compression_ratio"] >= 3.0
    assert (
        report["grad_wire_bytes_per_step"]
        < report["grad_wire_bytes_per_step_fp32"]
    )

    # uncompressed config: identical byte model on both sides, ratio 1
    flat = grad_wire_report(params, part, WireConfig())
    assert flat["wire_compression_ratio"] == 1.0
    assert (
        flat["grad_wire_bytes_per_step"]
        == flat["grad_wire_bytes_per_step_fp32"]
    )
    # ring accounting, fp32: scatterable leaves pay (D-1)/D * n * 4 once
    # (RS), unscatterable twice (AR = RS + AG)
    dims = part.zero1_dims(params)
    expect = 0.0
    for dim, leaf in zip(
        jax.tree_util.tree_leaves(dims, is_leaf=lambda d: d is None),
        jax.tree_util.tree_leaves(params),
    ):
        passes = 1.0 if dim is not None else 2.0
        expect += passes * (7 / 8) * leaf.size * 4.0
    assert flat["grad_wire_bytes_per_step_fp32"] == int(round(expect))


def test_min_size_floor_keeps_small_leaves_fp32(mesh_1d):
    part = data_parallel(
        mesh_1d, dp_shard_opt_state=True, opt_shard_min_size=1,
        wire=WireConfig(compress="int8-block", min_size=1 << 20),
    )
    params = {"w": jnp.zeros((64, 64), jnp.float32)}
    report = grad_wire_report(params, part)
    # everything under the floor: compressed bytes == fp32 bytes
    assert report["wire_compression_ratio"] == 1.0

"""True multi-process distributed training over localhost.

The TPU-native analogue of running the reference under ``torchrun
--nnodes=1 --nproc-per-node=2`` with gloo (SURVEY.md §4 "Multi-node without
a cluster"): two OS processes rendezvous through ``jax.distributed``
(runtime.initialize), each contributing one CPU device, and train with the
batch sharded across processes and params FSDP-sharded across processes —
exercising the real cross-process collective, metric-agreement, and
gathered-checkpoint paths that the fake single-process 8-device mesh cannot.
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

WORKER = os.path.join(os.path.dirname(__file__), "_distributed_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_two_process_training(tmp_path):
    port = _free_port()
    procs = []
    for pid in range(2):
        repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env = {
            **os.environ,
            "NUM_PROCESSES": "2",
            "PROCESS_ID": str(pid),
            "COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
            "DPX_TEST_CKPT_DIR": str(tmp_path),
            "PYTHONPATH": repo_root + os.pathsep + os.environ.get("PYTHONPATH", ""),
        }
        env.pop("XLA_FLAGS", None)  # worker sets its own device count
        procs.append(
            subprocess.Popen(
                [sys.executable, WORKER],
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                text=True,
            )
        )
    results = []
    for p in procs:
        out, err = p.communicate(timeout=300)
        assert p.returncode == 0, f"worker failed:\n{err[-3000:]}"
        results.append(json.loads(out.strip().splitlines()[-1]))

    # both processes saw the 2-device global mesh
    assert all(r["n_devices"] == 2 for r in results)
    # global metrics agree bit-for-bit across processes
    assert results[0]["train_loss"] == pytest.approx(results[1]["train_loss"])
    assert results[0]["val_loss"] == pytest.approx(results[1]["val_loss"])
    assert np.isfinite(results[0]["train_loss"])

    # graft-scope straggler telemetry: each process saw BOTH hosts' step
    # times via the boundary process_allgather, and derived the skew
    for r in results:
        straggler = r["straggler"]
        times = straggler["step_time_ms_per_host"]
        assert len(times) == 2 and all(t > 0 for t in times)
        assert straggler["step_time_ms_max_host"] >= (
            straggler["step_time_ms_median_host"]
        )
        assert straggler["step_time_skew"] >= 1.0
        assert isinstance(straggler.get("slow_hosts", []), list)
        assert r["grad_norm"] and np.isfinite(r["grad_norm"])
    assert results[0]["straggler"] == results[1]["straggler"]

    # at process_count > 1 the Trainer auto-selects the SHARDED format
    # (collective-free, async-safe): the pointer file + per-process shard
    # files must restore in THIS (single-process, different-topology)
    # interpreter via load_checkpoint's auto-detection
    ckpt = tmp_path / "latest_model.ckpt"
    assert ckpt.exists()
    from distributed_pytorch_example_tpu.train import checkpoint as _ck

    assert _ck._is_sharded(str(ckpt)), "multi-host save should be sharded"
    shard_dir = tmp_path / "latest_model.ckpt.shards"
    shard_files = [
        f for v in shard_dir.iterdir() for f in v.iterdir()
        if f.name.startswith("shard_")
    ]
    assert len(shard_files) == 2, "one shard file per process"

    import jax
    import optax

    import distributed_pytorch_example_tpu as dpx
    from distributed_pytorch_example_tpu.train import checkpoint as ckpt_lib
    from distributed_pytorch_example_tpu.train.step import init_state

    state, _ = init_state(
        dpx.models.SimpleNet(),
        optax.adam(1e-3),
        np.zeros((1, 784), np.float32),
        jax.random.key(0),
    )
    restored, epoch, extra = ckpt_lib.load_checkpoint(str(ckpt), state)
    assert epoch == 1
    assert int(restored.step) == 8  # 256 samples / 32 global batch = 8 steps

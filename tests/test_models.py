"""Model zoo tests: registry dispatch, forward shapes, full-size param counts.

Param counts are checked with ``jax.eval_shape`` (no FLOPs, no memory), so
the full-size BASELINE.json configs are verified cheaply; forward passes run
on tiny model variants.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_pytorch_example_tpu import models


def n_params(model, sample):
    shapes = jax.eval_shape(
        lambda rng: model.init(rng, sample, train=False), jax.random.key(0)
    )
    return sum(int(np.prod(x.shape)) for x in jax.tree_util.tree_leaves(shapes["params"]))


class TestParamCounts:
    """Full-size configs match the published architecture sizes."""

    def test_mlp_matches_reference_exactly(self):
        # reference SimpleNet: 269,322 params (train.py:32-50)
        model = models.get_model("mlp")
        x = jnp.zeros((1, 784), jnp.float32)
        assert n_params(model, x) == 269_322

    def test_resnet18(self):
        model = models.get_model("resnet18")
        x = jnp.zeros((1, 32, 32, 3), jnp.float32)
        assert 11.0e6 < n_params(model, x) < 11.4e6

    def test_resnet50(self):
        model = models.get_model("resnet50")
        x = jnp.zeros((1, 224, 224, 3), jnp.float32)
        assert 25.0e6 < n_params(model, x) < 26.0e6

    def test_vit_b16(self):
        model = models.get_model("vit-b16")
        x = jnp.zeros((1, 224, 224, 3), jnp.float32)
        assert 85.0e6 < n_params(model, x) < 87.5e6

    def test_bert_base(self):
        model = models.get_model("bert-base")
        tokens = jnp.zeros((1, 128), jnp.int32)
        assert 108.0e6 < n_params(model, tokens) < 112.0e6

    def test_gpt2_124m(self):
        model = models.get_model("gpt2")
        tokens = jnp.zeros((1, 64), jnp.int32)
        assert 123.0e6 < n_params(model, tokens) < 126.0e6


class TestForward:
    """Tiny variants produce the right output shapes and finite values."""

    def _check(self, model, inputs, expect_shape, train=False):
        variables = model.init(
            {"params": jax.random.key(0), "dropout": jax.random.key(1)},
            inputs,
            train=False,
        )
        mutable = [k for k in variables if k != "params"]
        out = model.apply(
            variables,
            inputs,
            train=train,
            rngs={"dropout": jax.random.key(2)} if train else {},
            mutable=mutable if (train and mutable) else False,
        )
        if train and mutable:
            out = out[0]
        assert out.shape == expect_shape
        assert np.isfinite(np.asarray(out)).all()
        return out

    def test_resnet18_forward(self):
        from distributed_pytorch_example_tpu.models.resnet import ResNet18

        model = ResNet18(num_classes=10)
        x = jnp.asarray(np.random.default_rng(0).standard_normal((2, 32, 32, 3)), jnp.float32)
        self._check(model, x, (2, 10), train=True)

    def test_resnet50_forward_small(self):
        from distributed_pytorch_example_tpu.models.resnet import ResNet50

        model = ResNet50(num_classes=7, small_inputs=True)
        x = jnp.asarray(np.random.default_rng(0).standard_normal((2, 32, 32, 3)), jnp.float32)
        self._check(model, x, (2, 7), train=True)

    def test_vit_tiny_forward(self):
        from distributed_pytorch_example_tpu.models.vit import VisionTransformer

        model = VisionTransformer(
            num_classes=5, patch_size=4, model_dim=32, num_layers=2,
            num_heads=4, mlp_dim=64, dropout_rate=0.1,
        )
        x = jnp.asarray(np.random.default_rng(0).standard_normal((2, 16, 16, 3)), jnp.float32)
        self._check(model, x, (2, 5), train=True)

    def test_bert_tiny_forward(self):
        from distributed_pytorch_example_tpu.models.bert import BertBase

        model = BertBase(
            vocab_size=101, max_len=32, model_dim=32, num_layers=2,
            num_heads=4, mlp_dim=64,
        )
        tokens = jnp.asarray(np.random.default_rng(0).integers(0, 101, (2, 16)), jnp.int32)
        self._check(model, tokens, (2, 16, 101))

    def test_gpt2_tiny_forward(self):
        from distributed_pytorch_example_tpu.models.gpt2 import GPT2

        model = GPT2(
            vocab_size=101, max_len=32, model_dim=32, num_layers=2,
            num_heads=4, mlp_dim=64,
        )
        tokens = jnp.asarray(np.random.default_rng(0).integers(0, 101, (2, 16)), jnp.int32)
        self._check(model, tokens, (2, 16, 101))

    def test_gpt2_causality(self):
        """Changing a future token must not change past logits."""
        from distributed_pytorch_example_tpu.models.gpt2 import GPT2

        model = GPT2(vocab_size=101, max_len=32, model_dim=32, num_layers=2,
                     num_heads=4, mlp_dim=64)
        rng = np.random.default_rng(0)
        tokens = jnp.asarray(rng.integers(0, 101, (1, 16)), jnp.int32)
        variables = model.init(jax.random.key(0), tokens, train=False)
        out1 = model.apply(variables, tokens, train=False)
        tokens2 = tokens.at[0, 10].set((tokens[0, 10] + 1) % 101)
        out2 = model.apply(variables, tokens2, train=False)
        np.testing.assert_allclose(out1[0, :10], out2[0, :10], atol=1e-5)
        assert not np.allclose(out1[0, 10:], out2[0, 10:])

    def test_remat_matches_no_remat(self):
        from distributed_pytorch_example_tpu.models.gpt2 import GPT2

        kw = dict(vocab_size=101, max_len=32, model_dim=32, num_layers=2,
                  num_heads=4, mlp_dim=64)
        tokens = jnp.asarray(np.random.default_rng(0).integers(0, 101, (2, 16)), jnp.int32)
        m1, m2 = GPT2(**kw), GPT2(remat=True, **kw)
        v = m1.init(jax.random.key(0), tokens, train=False)
        np.testing.assert_allclose(
            m1.apply(v, tokens, train=False),
            m2.apply(v, tokens, train=False),
            atol=1e-5,
        )


class TestTensorParallel:
    """TP rules shard transformer weights and the forward still agrees."""

    def test_tp_forward_matches_replicated(self, devices):
        from distributed_pytorch_example_tpu.models.gpt2 import GPT2
        from distributed_pytorch_example_tpu.parallel.partition import (
            transformer_partitioner,
        )
        from distributed_pytorch_example_tpu.runtime import MeshSpec, make_mesh

        mesh = make_mesh(MeshSpec(data=2, tensor=4))
        model = GPT2(vocab_size=101, max_len=32, model_dim=32, num_layers=2,
                     num_heads=4, mlp_dim=64)
        tokens = jnp.asarray(np.random.default_rng(0).integers(0, 101, (4, 16)), jnp.int32)
        variables = model.init(jax.random.key(0), tokens, train=False)
        expected = model.apply(variables, tokens, train=False)

        part = transformer_partitioner(mesh)
        shardings = part.tree_shardings(variables)
        sharded_vars = jax.device_put(variables, shardings)
        # q kernel must actually be sharded over 'tensor'
        q_spec = part.tree_specs(variables)["params"]["decoder"]["layer_0"]["attn"]["q"]["kernel"]
        assert q_spec == jax.sharding.PartitionSpec(None, "tensor")

        out = jax.jit(lambda v, t: model.apply(v, t, train=False))(sharded_vars, tokens)
        np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=1e-4)


class TestSpaceToDepthStem:
    def test_bit_equivalent_to_standard_stem(self):
        """s2d stem with copied 7x7 weights == standard 7x7/s2 SAME conv."""
        from distributed_pytorch_example_tpu.models.resnet import ResNet50

        x = jnp.asarray(
            np.random.default_rng(0).standard_normal((2, 224, 224, 3)),
            jnp.float32,
        )
        std = ResNet50(num_classes=10)
        s2d = ResNet50(num_classes=10, space_to_depth_stem=True)
        v_std = std.init(jax.random.key(0), x, train=False)
        v_s2d = s2d.init(jax.random.key(0), x, train=False)
        # graft the standard stem weights into the s2d variant
        v_s2d["params"]["stem_conv_kernel"] = v_std["params"]["stem_conv"]["kernel"]
        for k in v_std["params"]:
            if k not in ("stem_conv",):
                v_s2d["params"][k] = v_std["params"][k]
        v_s2d["batch_stats"] = v_std["batch_stats"]
        out_std = std.apply(v_std, x, train=False)
        out_s2d = s2d.apply(v_s2d, x, train=False)
        np.testing.assert_allclose(
            np.asarray(out_s2d), np.asarray(out_std), atol=1e-4
        )

    def test_param_count_unchanged(self):
        from distributed_pytorch_example_tpu.models.resnet import ResNet50

        x = jnp.zeros((1, 224, 224, 3), jnp.float32)
        assert n_params(ResNet50(), x) == n_params(
            ResNet50(space_to_depth_stem=True), x
        )


def test_vocab_sharding_when_divisible(devices):
    """Divisible vocab shards on 'tensor'; indivisible falls back."""
    from jax.sharding import PartitionSpec as P

    from distributed_pytorch_example_tpu.models.gpt2 import GPT2
    from distributed_pytorch_example_tpu.parallel.partition import (
        transformer_partitioner,
    )
    from distributed_pytorch_example_tpu.runtime import MeshSpec, make_mesh

    mesh = make_mesh(MeshSpec(data=2, tensor=4))
    part = transformer_partitioner(mesh)

    import jax
    import jax.numpy as jnp
    import numpy as np

    model = GPT2(vocab_size=128, max_len=32, model_dim=32, num_layers=1,
                 num_heads=4, mlp_dim=64)
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, 128, (4, 16)), jnp.int32
    )
    variables = model.init(jax.random.key(0), tokens, train=False)
    specs = part.tree_specs(variables)["params"]
    assert specs["wte"]["embedding"] == P("tensor", None)  # 128 % 4 == 0
    # TP equivalence with the vocab-sharded table
    expected = model.apply(variables, tokens, train=False)
    sharded = jax.device_put(variables, part.tree_shardings(variables))
    got = jax.jit(lambda v, t: model.apply(v, t, train=False))(sharded, tokens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=2e-4)

    # vocab 101 % 4 != 0: falls back to replicated (the default policy)
    m2 = GPT2(vocab_size=101, max_len=32, model_dim=32, num_layers=1,
              num_heads=4, mlp_dim=64)
    v2 = m2.init(jax.random.key(0), tokens, train=False)
    assert part.tree_specs(v2)["params"]["wte"]["embedding"] == P()

"""ZeRO-1 sharded update + in-step gradient accumulation (train/step.py).

Equivalence tolerances are TIGHT but not zero: the ZeRO-1 step sums
gradients in a different order than the replicated step (per-shard local
sums reduce-scattered vs one global mean), and accumulation sums
microbatch means instead of one batch mean — bit-identity across
floating-point reduction orders is impossible by construction, so the
tests pin "same training trajectory to ~1e-4 after a few Adam steps"
(the same bar the TP/SP equivalence tests use).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from distributed_pytorch_example_tpu.analysis.collectives import (
    compare_budgets,
    parse_collectives,
)
from distributed_pytorch_example_tpu.models.gpt2 import GPT2
from distributed_pytorch_example_tpu.parallel.api import data_parallel
from distributed_pytorch_example_tpu.train import checkpoint as ckpt_lib
from distributed_pytorch_example_tpu.train.optimizers import (
    opt_state_bytes_per_chip,
)
from distributed_pytorch_example_tpu.train.step import (
    build_train_step,
    init_state,
)
from distributed_pytorch_example_tpu.train.tasks import CausalLMTask


def _tiny_model():
    return GPT2(
        vocab_size=64, max_len=32, model_dim=32, num_layers=1,
        num_heads=2, mlp_dim=64, logits_mode="hidden",
    )


def _batch(partitioner, n=16, seq=16, seed=0):
    tokens = np.random.default_rng(seed).integers(
        0, 64, (n, seq)
    ).astype(np.int32)
    return {
        "tokens": jax.device_put(tokens, partitioner.batch_sharding())
    }


_RUN_CACHE = {}


def _run(mesh, *, zero1, accum, steps=3, manual=True):
    """(final state, step collectives) for one gradient-sync mode.

    Memoized per mode: the zero1/accum=1 trajectory anchors two tests and
    each entry costs a full jit compile on the one-core build box.
    """
    key = (zero1, accum, steps, manual)
    if key in _RUN_CACHE:
        return _RUN_CACHE[key]
    model, task, opt = _tiny_model(), CausalLMTask(), optax.adam(1e-3)
    part = data_parallel(mesh, dp_shard_opt_state=zero1, opt_shard_min_size=1)
    batch = _batch(part)
    with mesh:
        state, _ = init_state(
            model, opt, batch["tokens"], jax.random.key(0), part
        )
        step = build_train_step(
            model, task, opt,
            partitioner=part if (manual or zero1) else None,
            grad_accum_steps=accum,
        )
        coll = parse_collectives(step.lower(state, batch).compile().as_text())
        for _ in range(steps):
            state, metrics = step(state, batch)
    _RUN_CACHE[key] = (state, coll, metrics)
    return state, coll, metrics


def _max_diff(a, b):
    diffs = jax.tree_util.tree_map(
        lambda x, y: float(
            jnp.max(jnp.abs(x.astype(jnp.float32) - y.astype(jnp.float32)))
        ),
        a, b,
    )
    return max(jax.tree_util.tree_leaves(diffs))


def test_zero1_matches_replicated(mesh_1d):
    """Same params after K Adam steps; RS+AG gradient sync; 1/D opt bytes."""
    s_zero1, coll_z, _ = _run(mesh_1d, zero1=True, accum=1)
    s_repl, coll_r, _ = _run(mesh_1d, zero1=False, accum=1, manual=False)

    assert _max_diff(s_zero1.params, s_repl.params) < 5e-4

    # the ZeRO-1 collective signature on a data-only mesh: literal
    # reduce-scatters and all-gathers carry the gradients/params, and NO
    # gradient-sized all-reduce remains (only scalar metric pmeans)
    assert coll_z.get("reduce-scatter", {}).get("count", 0) >= 1
    assert coll_z.get("all-gather", {}).get("count", 0) >= 1
    grad_bytes = coll_z["reduce-scatter"]["bytes"]
    assert coll_z.get("all-reduce", {}).get("bytes", 0) < grad_bytes
    # the replicated step syncs gradients by all-reduce and never scatters
    assert coll_r.get("reduce-scatter", {}).get("count", 0) == 0

    # Adam moments actually sharded over data...
    mu_specs = {
        str(leaf.sharding.spec)
        for leaf in jax.tree_util.tree_leaves(s_zero1.opt_state[0].mu)
    }
    assert any("data" in s for s in mu_specs), mu_specs
    # ...so per-chip optimizer bytes shrink by ~the DP degree (8); the
    # replicated scalars (count) keep the ratio just above 1/8
    ratio = opt_state_bytes_per_chip(
        s_zero1.opt_state
    ) / opt_state_bytes_per_chip(s_repl.opt_state)
    assert ratio < 0.2, ratio


def test_grad_accum_matches_single_batch(mesh_1d):
    """N microbatches of B/N == one batch of B, one collective either way."""
    s_one, coll_one, m_one = _run(mesh_1d, zero1=True, accum=1)
    s_acc, coll_acc, m_acc = _run(mesh_1d, zero1=True, accum=2)

    assert _max_diff(s_acc.params, s_one.params) < 5e-4
    assert abs(float(m_acc["loss"]) - float(m_one["loss"])) < 1e-3
    # accumulation must NOT multiply the gradient collective: same number
    # of reduce-scatters as the single-batch step (one per param leaf)
    assert (
        coll_acc["reduce-scatter"]["count"]
        == coll_one["reduce-scatter"]["count"]
    )


def test_grad_accum_requires_divisible_batch(mesh_1d):
    model, task, opt = _tiny_model(), CausalLMTask(), optax.adam(1e-3)
    part = data_parallel(mesh_1d, dp_shard_opt_state=True, opt_shard_min_size=1)
    batch = _batch(part, n=24)  # 3 per shard: not divisible by 2
    with mesh_1d:
        state, _ = init_state(
            model, opt, batch["tokens"], jax.random.key(0), part
        )
        step = build_train_step(
            model, task, opt, partitioner=part, grad_accum_steps=2
        )
        with pytest.raises(ValueError, match="grad_accum_steps"):
            step(state, batch)


@pytest.mark.parametrize("fmt", ["gathered", "sharded"])
def test_checkpoint_mode_flip_roundtrip(mesh_1d, tmp_path, fmt):
    """Resume flips gradient-sync mode in BOTH directions, both formats."""
    path = str(tmp_path / "ckpt")
    model, task, opt = _tiny_model(), CausalLMTask(), optax.adam(1e-3)

    def build(zero1):
        part = data_parallel(
            mesh_1d, dp_shard_opt_state=zero1, opt_shard_min_size=1
        )
        batch = _batch(part)
        with mesh_1d:
            state, shardings = init_state(
                model, opt, batch["tokens"], jax.random.key(0), part
            )
            step = build_train_step(
                model, task, opt, partitioner=part, grad_accum_steps=1
            )
        return part, batch, state, shardings, step

    # replicated -> train -> save -> restore into a ZeRO-1 layout
    _, batch, state, _, step = build(zero1=False)
    with mesh_1d:
        for _ in range(2):
            state, _ = step(state, batch)
    ckpt_lib.save_checkpoint(
        path, state, 1, 0.0, {}, sharded=(fmt == "sharded")
    )

    part_z, batch_z, template_z, shardings_z, step_z = build(zero1=True)
    loaded, epoch, _ = ckpt_lib.load_checkpoint(
        path, template_z, shardings_z
    )
    assert epoch == 1
    assert _max_diff(loaded.params, state.params) == 0.0
    assert _max_diff(loaded.opt_state[0].mu, state.opt_state[0].mu) == 0.0
    mu_leaf = jax.tree_util.tree_leaves(loaded.opt_state[0].mu)[0]
    assert "data" in str(mu_leaf.sharding.spec)  # re-sharded on load
    with mesh_1d:
        stepped, _ = step_z(loaded, batch_z)  # and the ZeRO-1 step runs

    # ZeRO-1 -> save -> restore into the replicated layout
    ckpt_lib.save_checkpoint(
        path, stepped, 2, 0.0, {}, sharded=(fmt == "sharded")
    )
    _, batch_r, template_r, shardings_r, step_r = build(zero1=False)
    loaded_r, epoch_r, _ = ckpt_lib.load_checkpoint(
        path, template_r, shardings_r
    )
    assert epoch_r == 2
    assert _max_diff(loaded_r.params, stepped.params) == 0.0
    assert _max_diff(
        loaded_r.opt_state[0].mu, stepped.opt_state[0].mu
    ) == 0.0
    mu_leaf = jax.tree_util.tree_leaves(loaded_r.opt_state[0].mu)[0]
    assert "data" not in str(mu_leaf.sharding.spec)
    with mesh_1d:
        step_r(loaded_r, batch_r)


def test_budget_gate_catches_silent_re_replication():
    """The zero1 signature turns 'no reduce-scatter' into a violation even
    when counts/bytes would pass a stale budget."""
    committed = {
        "reduce-scatter": {"count": 10, "bytes": 1000},
        "all-gather": {"count": 10, "bytes": 1000},
        "all-reduce": {"count": 2, "bytes": 8},
    }
    # silent re-replication: gradient sync collapsed back to all-reduce;
    # counts DECREASED, so the plain ratchet sees only improvements
    measured = {"all-reduce": {"count": 2, "bytes": 8}}
    violations, _ = compare_budgets(
        committed, measured, config="data+tensor+zero1",
        signature="zero1-dp-step",
    )
    rules = {v.rule for v in violations}
    assert "comm-zero1-signature" in rules
    msgs = " ".join(v.message for v in violations)
    assert "re-replicated" in msgs and "reduce-scatter" in msgs

    # without the signature the same drift sails through: the signature
    # is load-bearing, not redundant with the count/byte ratchet
    violations_plain, _ = compare_budgets(
        committed, measured, config="data+tensor+zero1"
    )
    assert not violations_plain

    # all-reduce growth on a zero1 config gets the self-explanatory hint
    violations_ar, _ = compare_budgets(
        committed,
        {
            "reduce-scatter": {"count": 10, "bytes": 1000},
            "all-gather": {"count": 10, "bytes": 1000},
            "all-reduce": {"count": 30, "bytes": 4000},
        },
        config="data+tensor+zero1",
        signature="zero1-dp-step",
    )
    ar = [v for v in violations_ar if v.where == "all-reduce"]
    assert ar and "reduce-scatter path" in ar[0].message

    # a healthy zero1 record passes clean
    ok, _ = compare_budgets(
        committed, dict(committed), config="data+tensor+zero1",
        signature="zero1-dp-step",
    )
    assert not ok


def test_bf16_accum_lint():
    from distributed_pytorch_example_tpu.analysis import pylint_rules

    bad = (
        "import jax, jax.numpy as jnp\n"
        "def accumulate(xs):\n"
        "    acc = jnp.zeros((4,), dtype=jnp.bfloat16)\n"
        "    def body(c, x):\n"
        "        return c + x, None\n"
        "    acc, _ = jax.lax.scan(body, acc, xs)\n"
        "    return acc\n"
    )
    findings = pylint_rules.lint_source("ops/fake.py", bad)
    assert [f.rule for f in findings] == ["bf16-accum"]
    assert "float32" in findings[0].message

    # train/ is in scope too (the step's accumulator lives there)
    assert pylint_rules.lint_source("train/fake.py", bad)
    # models/ is not
    assert not pylint_rules.lint_source("models/fake.py", bad)

    suppressed = bad.replace(
        "dtype=jnp.bfloat16)", "dtype=jnp.bfloat16)  # graft-lint: bf16-accum"
    )
    assert not pylint_rules.lint_source("ops/fake.py", suppressed)

    f32 = bad.replace("bfloat16", "float32")
    assert not pylint_rules.lint_source("ops/fake.py", f32)

    no_scan = (
        "import jax.numpy as jnp\n"
        "def make_mask():\n"
        "    return jnp.zeros((4,), dtype=jnp.bfloat16)\n"
    )
    assert not pylint_rules.lint_source("ops/fake.py", no_scan)


def test_step_source_is_lint_clean():
    """The shipped accumulator must satisfy its own rule."""
    import os

    from distributed_pytorch_example_tpu.analysis import pylint_rules

    root = pylint_rules.package_root()
    with open(os.path.join(root, "train", "step.py")) as f:
        findings = pylint_rules.lint_source("train/step.py", f.read())
    assert not findings, [f.render() for f in findings]

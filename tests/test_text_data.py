"""Tokenized-text dataset: windowing, batch gather, file formats."""

import numpy as np
import pytest

from distributed_pytorch_example_tpu.data.text import (
    TokenWindowDataset,
    load_token_file,
)


def test_windowing_non_overlapping():
    ids = np.arange(100, dtype=np.int32)
    ds = TokenWindowDataset(ids, seq_len=32)
    assert len(ds) == 3  # (100 - 32) // 32 + 1
    np.testing.assert_array_equal(ds[0]["tokens"], np.arange(32))
    np.testing.assert_array_equal(ds[2]["tokens"], np.arange(64, 96))


def test_windowing_strided_overlap():
    ids = np.arange(100, dtype=np.int32)
    ds = TokenWindowDataset(ids, seq_len=32, stride=16)
    assert len(ds) == (100 - 32) // 16 + 1
    np.testing.assert_array_equal(ds[1]["tokens"], np.arange(16, 48))


def test_get_batch_matches_getitem():
    ids = np.random.default_rng(0).integers(0, 1000, 500).astype(np.int32)
    ds = TokenWindowDataset(ids, seq_len=64)
    batch = ds.get_batch([2, 0, 5])
    for row, idx in zip(batch["tokens"], [2, 0, 5]):
        np.testing.assert_array_equal(row, ds[idx]["tokens"])


def test_too_short_corpus_raises():
    with pytest.raises(ValueError, match="shorter"):
        TokenWindowDataset(np.arange(10, dtype=np.int32), seq_len=32)


def test_load_npy_and_bin(tmp_path):
    ids = np.random.default_rng(1).integers(0, 50000, 1000).astype(np.uint16)
    np.save(tmp_path / "c.npy", ids)
    ids.tofile(tmp_path / "c.bin")
    ds_npy = load_token_file(str(tmp_path / "c.npy"), seq_len=128)
    ds_bin = load_token_file(str(tmp_path / "c.bin"), seq_len=128)
    np.testing.assert_array_equal(ds_npy[0]["tokens"], ds_bin[0]["tokens"])
    # the corpus stays memory-mapped; windows come out int32 for the device
    assert isinstance(ds_bin.ids, np.memmap)
    assert ds_npy[0]["tokens"].dtype == np.int32
    assert ds_npy.get_batch([0])["tokens"].dtype == np.int32


def test_load_bin_int32_dtype(tmp_path):
    ids = np.random.default_rng(3).integers(0, 70000, 500).astype(np.int32)
    ids.tofile(tmp_path / "c32.bin")
    ds = load_token_file(str(tmp_path / "c32.bin"), seq_len=64, dtype="int32")
    np.testing.assert_array_equal(ds[0]["tokens"], ids[:64])


def test_missing_file_guidance():
    with pytest.raises(FileNotFoundError, match="synthetic-tokens"):
        load_token_file("/nonexistent/train.bin", seq_len=128)


def test_loader_integration(devices):
    """Windows flow through the DeviceLoader sharded pipeline."""
    from distributed_pytorch_example_tpu.data.loader import DeviceLoader
    from distributed_pytorch_example_tpu.runtime import make_mesh

    ids = np.random.default_rng(2).integers(0, 100, 2048).astype(np.int32)
    ds = TokenWindowDataset(ids, seq_len=64)
    mesh = make_mesh()
    loader = DeviceLoader(ds, 8, mesh=mesh, num_shards=1, shard_id=0)
    batch = next(iter(loader))
    assert batch["tokens"].shape == (8, 64)

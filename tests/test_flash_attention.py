"""Flash attention numerics vs the pure-XLA reference (interpret mode on CPU).

Forward and full VJP (dq, dk, dv) must match ``ops.attention._xla_attention``
for causal and non-causal, including multi-block sequence lengths that
exercise the online-softmax accumulation across k-blocks and the block-skip
logic on the causal diagonal.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_pytorch_example_tpu.ops.attention import _xla_attention
from distributed_pytorch_example_tpu.ops.pallas.flash_attention import flash_attention


def make_qkv(batch=2, seq=256, heads=2, head_dim=64, seed=0):
    rng = np.random.default_rng(seed)
    shape = (batch, seq, heads, head_dim)
    return tuple(
        jnp.asarray(rng.standard_normal(shape), jnp.float32) for _ in range(3)
    )


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("seq", [128, 256, 384])
def test_forward_matches_xla(causal, seq):
    q, k, v = make_qkv(seq=seq)
    scale = q.shape[-1] ** -0.5
    expected = _xla_attention(q, k, v, None, None, causal, scale)
    got = flash_attention(q, k, v, causal=causal, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_grads_match_xla(causal):
    q, k, v = make_qkv(seq=256)
    scale = q.shape[-1] ** -0.5

    def loss_ref(q, k, v):
        return jnp.sum(_xla_attention(q, k, v, None, None, causal, scale) ** 2)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal, interpret=True) ** 2)

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    for gr, gf, name in zip(g_ref, g_flash, "qkv"):
        np.testing.assert_allclose(
            np.asarray(gf), np.asarray(gr), atol=5e-4, err_msg=f"d{name}"
        )


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("gqa", [False, True])
@pytest.mark.parametrize("masked", [False, True])
def test_multiblock_fused_backward_grads(causal, gqa, masked):
    """The fused multi-block backward (one logits recompute for dq/dk/dv,
    persistent dq scratch): explicit 128x64 blocks at seq 256 force the
    multi-block grid the default-blocks tests never reach."""
    rng = np.random.default_rng(21)
    q = jnp.asarray(rng.standard_normal((2, 256, 4, 64)), jnp.float32)
    kvh = 2 if gqa else 4
    k = jnp.asarray(rng.standard_normal((2, 256, kvh, 64)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((2, 256, kvh, 64)), jnp.float32)
    kv_mask = make_kv_mask(seq=256, seed=22) if masked else None
    scale = 64 ** -0.5

    def loss_ref(q, k, v):
        return jnp.sum(
            _xla_attention(q, k, v, None, kv_mask, causal, scale) ** 2
        )

    def loss_flash(q, k, v):
        return jnp.sum(
            flash_attention(
                q, k, v, causal=causal, kv_mask=kv_mask, interpret=True,
                block_q=128, block_k=64,
            ) ** 2
        )

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    for gr, gf, name in zip(g_ref, g_flash, "qkv"):
        assert gf.shape == gr.shape
        np.testing.assert_allclose(
            np.asarray(gf), np.asarray(gr), atol=5e-4, err_msg=f"d{name}"
        )


@pytest.mark.parametrize("gqa", [False, True])
@pytest.mark.parametrize("masked", [False, True])
@pytest.mark.parametrize("ni", [2, 4, 6])
def test_folded_causal_grid_forward_and_grads(gqa, masked, ni):
    """The triangular (folded) causal schedule — equal square blocks, even
    block count — must match the XLA reference exactly like the square
    grid it replaces (every grid step a needed pair, no skipped ticks)."""
    seq = 128 * ni
    rng = np.random.default_rng(31 + ni)
    q = jnp.asarray(rng.standard_normal((2, seq, 4, 64)), jnp.float32)
    kvh = 2 if gqa else 4
    k = jnp.asarray(rng.standard_normal((2, seq, kvh, 64)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((2, seq, kvh, 64)), jnp.float32)
    kv_mask = make_kv_mask(seq=seq, seed=32) if masked else None
    scale = 64 ** -0.5

    expected = _xla_attention(q, k, v, None, kv_mask, True, scale)
    got = flash_attention(
        q, k, v, causal=True, kv_mask=kv_mask, interpret=True,
        block_q=128, block_k=128,
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=2e-5)

    def loss_ref(q, k, v):
        return jnp.sum(
            _xla_attention(q, k, v, None, kv_mask, True, scale) ** 2
        )

    def loss_flash(q, k, v):
        return jnp.sum(
            flash_attention(
                q, k, v, causal=True, kv_mask=kv_mask, interpret=True,
                block_q=128, block_k=128,
            ) ** 2
        )

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    for gr, gf, name in zip(g_ref, g_flash, "qkv"):
        np.testing.assert_allclose(
            np.asarray(gf), np.asarray(gr), atol=5e-4, err_msg=f"d{name}"
        )


@pytest.mark.parametrize("causal", [False, True])
def test_multiblock_split_fallback_grads(causal, monkeypatch):
    """The two-kernel fallback (_bwd_split, used when the fused kernel's
    dq scratch would exceed VMEM) must stay numerically identical — forced
    here by shrinking the limit below seq*head_dim*4."""
    from distributed_pytorch_example_tpu.ops.pallas import flash_attention as fa

    monkeypatch.setattr(fa, "_FUSED_DQ_VMEM_LIMIT", 0)
    rng = np.random.default_rng(23)
    q = jnp.asarray(rng.standard_normal((2, 256, 4, 64)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((2, 256, 2, 64)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((2, 256, 2, 64)), jnp.float32)
    kv_mask = make_kv_mask(seq=256, seed=24)
    scale = 64 ** -0.5

    def loss_ref(q, k, v):
        return jnp.sum(
            _xla_attention(q, k, v, None, kv_mask, causal, scale) ** 2
        )

    def loss_flash(q, k, v):
        return jnp.sum(
            fa.flash_attention(
                q, k, v, causal=causal, kv_mask=kv_mask, interpret=True,
                block_q=128, block_k=64,
            ) ** 2
        )

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    for gr, gf, name in zip(g_ref, g_flash, "qkv"):
        np.testing.assert_allclose(
            np.asarray(gf), np.asarray(gr), atol=5e-4, err_msg=f"d{name}"
        )


def test_uneven_blocks_rejected():
    q, k, v = make_qkv(seq=200)
    with pytest.raises(ValueError):
        flash_attention(q, k, v, interpret=True, block_q=128, block_k=128)


def test_small_seq_shrinks_blocks():
    # seq < block: block shrinks to seq, single-block path
    q, k, v = make_qkv(seq=64)
    scale = q.shape[-1] ** -0.5
    expected = _xla_attention(q, k, v, None, None, True, scale)
    got = flash_attention(q, k, v, causal=True, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=2e-5)


def test_forced_flash_unsupported_raises():
    """use_flash=True must fail loudly, not silently degrade (CPU here)."""
    from distributed_pytorch_example_tpu.ops.attention import dot_product_attention

    q, k, v = make_qkv(seq=128)
    with pytest.raises(ValueError, match="flash"):
        dot_product_attention(q, k, v, use_flash=True)  # CPU → unsupported


def test_causal_cross_length_not_auto_selected():
    """Causal seq_q != seq_k disagrees between kernels; auto must pick XLA."""
    from distributed_pytorch_example_tpu.ops.attention import (
        _flash_unsupported_reason,
    )

    q, _, _ = make_qkv(seq=128)
    k, v, _ = make_qkv(seq=256)
    assert _flash_unsupported_reason(q, k, v, None, True) is not None


def make_kv_mask(batch=2, seq=256, seed=5, min_valid=1):
    """Random key-padding mask with >= min_valid valid keys per row."""
    rng = np.random.default_rng(seed)
    mask = rng.random((batch, seq)) > 0.3
    mask[:, :min_valid] = True  # no fully-padded rows by default
    return jnp.asarray(mask)


@pytest.mark.parametrize("causal", [False, True])
def test_kv_mask_forward_matches_xla(causal):
    q, k, v = make_qkv(seq=256)
    kv_mask = make_kv_mask(seq=256)
    scale = q.shape[-1] ** -0.5
    expected = _xla_attention(q, k, v, None, kv_mask, causal, scale)
    got = flash_attention(
        q, k, v, causal=causal, kv_mask=kv_mask, interpret=True
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=2e-5)


def test_kv_mask_grads_match_xla():
    q, k, v = make_qkv(seq=256, seed=7)
    kv_mask = make_kv_mask(seq=256, seed=8)
    scale = q.shape[-1] ** -0.5

    def loss_ref(q, k, v):
        return jnp.sum(_xla_attention(q, k, v, None, kv_mask, False, scale) ** 2)

    def loss_flash(q, k, v):
        return jnp.sum(
            flash_attention(q, k, v, kv_mask=kv_mask, interpret=True) ** 2
        )

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    for gr, gf, name in zip(g_ref, g_flash, "qkv"):
        np.testing.assert_allclose(
            np.asarray(gf), np.asarray(gr), atol=5e-4, err_msg=f"d{name}"
        )


def test_kv_mask_fully_padded_batch_row_is_finite():
    """A batch row with ZERO valid keys: zero output, zero grads, no NaNs."""
    q, k, v = make_qkv(seq=128, seed=9)
    mask = np.ones((2, 128), bool)
    mask[1, :] = False  # batch row 1 fully padded
    kv_mask = jnp.asarray(mask)

    def loss(q, k, v):
        return jnp.sum(
            flash_attention(q, k, v, kv_mask=kv_mask, interpret=True) ** 2
        )

    out = flash_attention(q, k, v, kv_mask=kv_mask, interpret=True)
    assert np.all(np.isfinite(np.asarray(out)))
    np.testing.assert_array_equal(np.asarray(out)[1], 0.0)
    grads = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    for g, name in zip(grads, "qkv"):
        g = np.asarray(g)
        assert np.all(np.isfinite(g)), f"d{name} has non-finite values"
        np.testing.assert_array_equal(g[1], 0.0, err_msg=f"d{name} row 1")


def test_kv_mask_via_dispatcher_keeps_xla_on_cpu():
    """kv_mask through dot_product_attention matches the masked reference."""
    from distributed_pytorch_example_tpu.ops.attention import (
        dot_product_attention,
    )

    q, k, v = make_qkv(seq=128)
    kv_mask = make_kv_mask(seq=128)
    scale = q.shape[-1] ** -0.5
    expected = _xla_attention(q, k, v, None, kv_mask, False, scale)
    got = dot_product_attention(q, k, v, kv_mask=kv_mask)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=2e-5)


def test_fully_padded_rows_zero_on_xla_path_too():
    """XLA and flash paths must agree on fully-padded rows (both zero)."""
    q, k, v = make_qkv(seq=128, seed=11)
    mask = np.ones((2, 128), bool)
    mask[0, :] = False
    kv_mask = jnp.asarray(mask)
    scale = q.shape[-1] ** -0.5
    xla = _xla_attention(q, k, v, None, kv_mask, False, scale)
    np.testing.assert_array_equal(np.asarray(xla)[0], 0.0)
    flash = flash_attention(q, k, v, kv_mask=kv_mask, interpret=True)
    np.testing.assert_allclose(
        np.asarray(flash), np.asarray(xla), atol=2e-5
    )


@pytest.mark.parametrize("causal", [False, True])
def test_gqa_forward_and_grads_match_xla(causal):
    """Grouped-query attention: 4 q-heads sharing 2 kv-heads."""
    rng = np.random.default_rng(12)
    q = jnp.asarray(rng.standard_normal((2, 256, 4, 64)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((2, 256, 2, 64)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((2, 256, 2, 64)), jnp.float32)
    scale = 64 ** -0.5

    expected = _xla_attention(q, k, v, None, None, causal, scale)
    got = flash_attention(q, k, v, causal=causal, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=2e-5)

    def loss_ref(q, k, v):
        return jnp.sum(_xla_attention(q, k, v, None, None, causal, scale) ** 2)

    def loss_flash(q, k, v):
        return jnp.sum(
            flash_attention(q, k, v, causal=causal, interpret=True) ** 2
        )

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    for gr, gf, name in zip(g_ref, g_flash, "qkv"):
        assert gf.shape == gr.shape
        np.testing.assert_allclose(
            np.asarray(gf), np.asarray(gr), atol=5e-4, err_msg=f"d{name}"
        )


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("seq", [197, 100])
def test_lane_padded_forward_matches_xla(causal, seq):
    """Explicit-opt-in lane-padded flash at seq % 128 != 0 (ViT's 197)."""
    from distributed_pytorch_example_tpu.ops.attention import _flash_lane_padded

    q, k, v = make_qkv(seq=seq)
    scale = q.shape[-1] ** -0.5
    expected = _xla_attention(q, k, v, None, None, causal, scale)
    got = _flash_lane_padded(q, k, v, None, causal, scale, interpret=True)
    assert got.shape == q.shape
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_lane_padded_grads_match_xla(causal):
    """Padded queries' cotangents are zero: grads at 197 tokens are exact."""
    from distributed_pytorch_example_tpu.ops.attention import _flash_lane_padded

    q, k, v = make_qkv(seq=197, seed=3)
    scale = q.shape[-1] ** -0.5

    def loss_ref(q, k, v):
        return jnp.sum(_xla_attention(q, k, v, None, None, causal, scale) ** 2)

    def loss_flash(q, k, v):
        return jnp.sum(
            _flash_lane_padded(q, k, v, None, causal, scale, interpret=True)
            ** 2
        )

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    for gr, gf, name in zip(g_ref, g_flash, "qkv"):
        np.testing.assert_allclose(
            np.asarray(gf), np.asarray(gr), atol=5e-4, err_msg=f"d{name}"
        )


def test_lane_padded_kv_mask_and_fully_padded_row():
    """kv_mask streams through the pad; a fully-padded batch row emits
    zero output and zero grads (the flash kv_mask contract survives
    lane-padding)."""
    from distributed_pytorch_example_tpu.ops.attention import _flash_lane_padded

    q, k, v = make_qkv(seq=197, seed=13)
    mask = np.ones((2, 197), bool)
    mask[0, 150:] = False  # partial padding on row 0
    mask[1, :] = False     # row 1 fully padded
    kv_mask = jnp.asarray(mask)
    scale = q.shape[-1] ** -0.5

    expected = _xla_attention(q, k, v, None, kv_mask, False, scale)
    got = _flash_lane_padded(q, k, v, kv_mask, False, scale, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=2e-5)
    np.testing.assert_array_equal(np.asarray(got)[1], 0.0)

    def loss(q, k, v):
        return jnp.sum(
            _flash_lane_padded(q, k, v, kv_mask, False, scale, interpret=True)
            ** 2
        )

    grads = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    for g, name in zip(grads, "qkv"):
        g = np.asarray(g)
        assert np.all(np.isfinite(g)), f"d{name} has non-finite values"
        np.testing.assert_array_equal(g[1], 0.0, err_msg=f"d{name} row 1")


def test_misaligned_seq_auto_dispatch_takes_xla(monkeypatch):
    """Auto dispatch at seq % 128 != 0 must use the XLA path — the
    lane-padded flash path measured SLOWER at ViT bench shapes and is
    opt-in only (BENCH_r03 regression, VERDICT r3 #1)."""
    from distributed_pytorch_example_tpu.ops import attention

    def _boom(*a, **kw):  # pragma: no cover - fails the test if reached
        raise AssertionError("auto dispatch took the lane-padded flash path")

    monkeypatch.setattr(attention, "_flash_lane_padded", _boom)
    # pretend we're on TPU so seq misalignment is the ONLY flash blocker —
    # otherwise the r3 (regressing) dispatch would also skip the padded
    # path here (CPU rig) and the guard would pass vacuously
    monkeypatch.setattr(attention, "_on_tpu", lambda: True)
    q, k, v = make_qkv(seq=197)
    scale = q.shape[-1] ** -0.5
    expected = _xla_attention(q, k, v, None, None, False, scale)
    got = attention.dot_product_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=2e-5)


def test_gqa_indivisible_heads_not_selected():
    from distributed_pytorch_example_tpu.ops.attention import (
        _flash_unsupported_reason,
    )

    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((1, 128, 6, 64)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((1, 128, 4, 64)), jnp.float32)
    assert "heads" in _flash_unsupported_reason(q, k, k, None, False)


def test_fused_layout_attention_matches_classic(monkeypatch):
    """The fused projection layout (einsum prologue -> BNSH kernel ->
    einsum epilogue, models/transformer.py) computes the SAME attention
    as the classic Dense -> reshape -> flash path, with the identical
    param tree (checkpoints interchangeable between platforms/paths).
    CPU drive: eligibility forced, kernel in interpret mode."""
    import functools

    import numpy as np
    import optax

    from distributed_pytorch_example_tpu.models import transformer as tf_mod
    from distributed_pytorch_example_tpu.ops.pallas import (
        flash_attention as fa_mod,
    )

    mha = tf_mod.MultiHeadAttention(
        num_heads=2, head_dim=64, model_dim=128, causal=True,
    )
    x = jnp.asarray(
        np.random.default_rng(0).standard_normal((2, 128, 128)) * 0.3,
        jnp.float32,
    )
    params = mha.init(jax.random.key(0), x, train=False)["params"]
    classic = mha.apply({"params": params}, x, train=False)

    monkeypatch.setattr(tf_mod, "fused_layout_eligible", lambda *a, **k: True)
    monkeypatch.setattr(
        fa_mod, "flash_attention_bnsh",
        functools.partial(fa_mod.flash_attention_bnsh, interpret=True),
    )
    fused_params = mha.init(jax.random.key(0), x, train=False)["params"]
    # identical param tree and values between the two paths
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)
        ),
        params, fused_params,
    )
    fused = mha.apply({"params": params}, x, train=False)
    np.testing.assert_allclose(
        np.asarray(fused), np.asarray(classic), atol=2e-5
    )

    # gradients agree too (the custom-VJP backward under the new layout)
    g_fused = jax.grad(lambda p: jnp.sum(
        mha.apply({"params": p}, x, train=False) ** 2
    ))(params)
    monkeypatch.undo()
    g_classic = jax.grad(lambda p: jnp.sum(
        mha.apply({"params": p}, x, train=False) ** 2
    ))(params)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-4
        ),
        g_fused, g_classic,
    )

"""Flash attention numerics vs the pure-XLA reference (interpret mode on CPU).

Forward and full VJP (dq, dk, dv) must match ``ops.attention._xla_attention``
for causal and non-causal, including multi-block sequence lengths that
exercise the online-softmax accumulation across k-blocks and the block-skip
logic on the causal diagonal.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_pytorch_example_tpu.ops.attention import _xla_attention
from distributed_pytorch_example_tpu.ops.pallas.flash_attention import flash_attention


def make_qkv(batch=2, seq=256, heads=2, head_dim=64, seed=0):
    rng = np.random.default_rng(seed)
    shape = (batch, seq, heads, head_dim)
    return tuple(
        jnp.asarray(rng.standard_normal(shape), jnp.float32) for _ in range(3)
    )


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("seq", [128, 256, 384])
def test_forward_matches_xla(causal, seq):
    q, k, v = make_qkv(seq=seq)
    scale = q.shape[-1] ** -0.5
    expected = _xla_attention(q, k, v, None, causal, scale)
    got = flash_attention(q, k, v, causal=causal, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_grads_match_xla(causal):
    q, k, v = make_qkv(seq=256)
    scale = q.shape[-1] ** -0.5

    def loss_ref(q, k, v):
        return jnp.sum(_xla_attention(q, k, v, None, causal, scale) ** 2)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal, interpret=True) ** 2)

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    for gr, gf, name in zip(g_ref, g_flash, "qkv"):
        np.testing.assert_allclose(
            np.asarray(gf), np.asarray(gr), atol=5e-4, err_msg=f"d{name}"
        )


def test_uneven_blocks_rejected():
    q, k, v = make_qkv(seq=200)
    with pytest.raises(ValueError):
        flash_attention(q, k, v, interpret=True, block_q=128, block_k=128)


def test_small_seq_shrinks_blocks():
    # seq < block: block shrinks to seq, single-block path
    q, k, v = make_qkv(seq=64)
    scale = q.shape[-1] ** -0.5
    expected = _xla_attention(q, k, v, None, True, scale)
    got = flash_attention(q, k, v, causal=True, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=2e-5)


def test_forced_flash_unsupported_raises():
    """use_flash=True must fail loudly, not silently degrade (CPU here)."""
    from distributed_pytorch_example_tpu.ops.attention import dot_product_attention

    q, k, v = make_qkv(seq=128)
    with pytest.raises(ValueError, match="flash"):
        dot_product_attention(q, k, v, use_flash=True)  # CPU → unsupported


def test_causal_cross_length_not_auto_selected():
    """Causal seq_q != seq_k disagrees between kernels; auto must pick XLA."""
    from distributed_pytorch_example_tpu.ops.attention import (
        _flash_unsupported_reason,
    )

    q, _, _ = make_qkv(seq=128)
    k, v, _ = make_qkv(seq=256)
    assert _flash_unsupported_reason(q, k, v, None, True) is not None

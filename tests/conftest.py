"""Test harness: fake 8-device CPU mesh.

The TPU-native analogue of the reference's "gloo on localhost" test mode
(SURVEY.md §4): ``--xla_force_host_platform_device_count=8`` gives every test
an 8-device CPU backend, so all sharding/collective paths (the code DDP would
exercise via multi-process gloo) run in a single pytest process.
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
).strip()

import jax  # noqa: E402

# The axon sitecustomize force-registers the TPU plugin and overrides
# jax_platforms; point it back at CPU before any backend initializes.
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    ds = jax.devices()
    assert len(ds) == 8, f"expected 8 fake CPU devices, got {ds}"
    return ds


@pytest.fixture()
def mesh_1d(devices):
    from distributed_pytorch_example_tpu.runtime import make_mesh

    return make_mesh()


@pytest.fixture()
def mesh_2x2x2(devices):
    from distributed_pytorch_example_tpu.runtime import MeshSpec, make_mesh

    return make_mesh(MeshSpec(data=2, fsdp=2, tensor=2))

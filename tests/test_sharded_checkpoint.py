"""Sharded checkpoint format: no gather on save, reshard on load.

The gathered format (tests/test_train.py) re-materializes the full state;
the sharded format must (a) write only addressable replica-0 shards per
process, (b) restore bit-identically, (c) restore under a DIFFERENT mesh
shape than it was saved under, and (d) be auto-detected by load_checkpoint.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import distributed_pytorch_example_tpu as dpx
from distributed_pytorch_example_tpu.models.mlp import SimpleNet
from distributed_pytorch_example_tpu.runtime import MeshSpec, make_mesh
from distributed_pytorch_example_tpu.train import checkpoint as ckpt_lib
from distributed_pytorch_example_tpu.train.loop import Trainer
from distributed_pytorch_example_tpu.train.step import init_state
from distributed_pytorch_example_tpu.train.tasks import ClassificationTask


def _fsdp_state(mesh):
    model = SimpleNet()
    x = jnp.zeros((8, 784), jnp.float32)
    part = dpx.parallel.fsdp(mesh)
    state, shardings = init_state(
        model, optax.adam(1e-3), x, jax.random.key(0), part
    )
    return state, shardings


def _tree_equal(a, b):
    for x, y in zip(
        jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    ):
        if jnp.issubdtype(jnp.asarray(x).dtype, jax.dtypes.prng_key):
            x, y = jax.random.key_data(x), jax.random.key_data(y)
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_sharded_roundtrip_fsdp(tmp_path, devices):
    mesh = make_mesh(MeshSpec(data=1, fsdp=8))
    state, shardings = _fsdp_state(mesh)
    path = str(tmp_path / "latest_model.ckpt")
    ckpt_lib.save_checkpoint(path, state, 3, 0.5, {"k": 1.0}, sharded=True)

    # pointer file + versioned shard dir + manifest all exist
    assert os.path.isfile(path)
    with open(path, "rb") as f:
        assert f.read().startswith(ckpt_lib.SHARDED_MAGIC)
    step_dir = os.path.join(f"{path}.shards", "00000003")
    assert os.path.isfile(os.path.join(step_dir, "manifest.msgpack"))
    assert os.path.isfile(os.path.join(step_dir, "shard_00000.msgpack"))

    restored, epoch, extra = ckpt_lib.load_checkpoint(path, state, shardings)
    assert epoch == 3 and extra["k"] == 1.0
    _tree_equal(restored, state)
    # restored leaves carry the target shardings
    leaf = jax.tree_util.tree_leaves(restored.params)[0]
    assert leaf.sharding == jax.tree_util.tree_leaves(shardings.params)[0]


def test_sharded_save_writes_no_replicated_duplicates(tmp_path, devices):
    """A replicated leaf appears exactly once in the shard files."""
    from flax import serialization

    mesh = make_mesh(MeshSpec(data=8))
    model = SimpleNet()
    x = jnp.zeros((8, 784), jnp.float32)
    part = dpx.parallel.data_parallel(mesh)  # everything replicated
    state, _ = init_state(model, optax.adam(1e-3), x, jax.random.key(0), part)
    path = str(tmp_path / "ck")
    ckpt_lib.save_checkpoint(path, state, 1, 0.0, sharded=True)
    # shard files are sealed in the CRC envelope (graft-armor);
    # read_verified strips + checks it
    from distributed_pytorch_example_tpu.robustness.integrity import (
        read_verified,
    )

    chunks = serialization.msgpack_restore(read_verified(
        os.path.join(f"{path}.shards", "00000001", "shard_00000.msgpack")
    ))
    for p, entries in chunks.items():
        assert len(entries) == 1, f"{p} saved {len(entries)} copies"


def test_sharded_restores_under_different_mesh(tmp_path, devices):
    """Saved under fsdp=8, restored under data=2 x fsdp=4: same values,
    new shardings."""
    mesh_a = make_mesh(MeshSpec(data=1, fsdp=8))
    state_a, _ = _fsdp_state(mesh_a)
    path = str(tmp_path / "ck")
    ckpt_lib.save_checkpoint(path, state_a, 2, 0.1, sharded=True)

    mesh_b = make_mesh(MeshSpec(data=2, fsdp=4))
    state_b, shardings_b = _fsdp_state(mesh_b)
    restored, epoch, _ = ckpt_lib.load_checkpoint(path, state_b, shardings_b)
    assert epoch == 2
    _tree_equal(restored, state_a)
    leaf_r = jax.tree_util.tree_leaves(restored.params)[0]
    leaf_b = jax.tree_util.tree_leaves(state_b.params)[0]
    assert leaf_r.sharding == leaf_b.sharding


def test_gathered_and_sharded_interchangeable(tmp_path, devices):
    """load_checkpoint auto-detects: a job saved sharded resumes a job
    reading with no format hint, and vice versa."""
    mesh = make_mesh(MeshSpec(data=1, fsdp=8))
    state, shardings = _fsdp_state(mesh)
    p_gathered = str(tmp_path / "g.ckpt")
    p_sharded = str(tmp_path / "s.ckpt")
    ckpt_lib.save_checkpoint(p_gathered, state, 1, 0.0, sharded=False)
    ckpt_lib.save_checkpoint(p_sharded, state, 1, 0.0, sharded=True)
    r1, _, _ = ckpt_lib.load_checkpoint(p_gathered, state, shardings)
    r2, _, _ = ckpt_lib.load_checkpoint(p_sharded, state, shardings)
    _tree_equal(r1, r2)


def test_sharded_gc_keeps_only_live_version(tmp_path, devices):
    """retain=1 reproduces the pre-r10 single-live-version GC; the
    keep-last-K default (DEFAULT_RETAIN) is covered in tests/test_chaos.py."""
    mesh = make_mesh(MeshSpec(data=1, fsdp=8))
    state, _ = _fsdp_state(mesh)
    path = str(tmp_path / "ck")
    ckpt_lib.save_checkpoint(path, state, 1, 0.0, sharded=True, retain=1)
    ckpt_lib.save_checkpoint(path, state, 2, 0.0, sharded=True, retain=1)
    versions = sorted(os.listdir(f"{path}.shards"))
    assert versions == ["00000002"]


def test_trainer_fit_resume_with_sharded_format(tmp_path, devices):
    """End-to-end: fit with checkpoint_format='sharded', resume continues."""
    mesh = make_mesh(MeshSpec(data=1, fsdp=8))
    ds = dpx.data.SyntheticClassificationDataset(num_samples=256, seed=0)
    ckdir = str(tmp_path / "ck")
    part = dpx.parallel.fsdp(mesh)

    def trainer():
        return Trainer(
            SimpleNet(), ClassificationTask(), optax.adam(1e-3),
            partitioner=part, checkpoint_dir=ckdir,
            checkpoint_format="sharded",
        )

    loader = dpx.data.DeviceLoader(ds, 64, mesh=mesh, seed=0)
    t1 = trainer()
    t1.fit(loader, loader, epochs=2)
    latest = os.path.join(ckdir, ckpt_lib.LATEST_NAME)
    assert os.path.isfile(latest)
    with open(latest, "rb") as f:
        assert f.read().startswith(ckpt_lib.SHARDED_MAGIC)

    t2 = trainer()
    h2 = t2.fit(loader, loader, epochs=4, resume=latest)
    assert [r["epoch"] for r in h2] == [2, 3]


def test_bad_checkpoint_format_rejected(devices):
    mesh = make_mesh(MeshSpec(data=8))
    with pytest.raises(ValueError, match="checkpoint_format"):
        Trainer(
            SimpleNet(), ClassificationTask(), optax.adam(1e-3),
            partitioner=dpx.parallel.data_parallel(mesh),
            checkpoint_format="bogus",
        )


def test_stale_crashed_save_dir_is_cleaned_not_committed(tmp_path, devices):
    """Leftover shard files from a killed save at the SAME epoch must not
    be committed into the new checkpoint (the rendezvous checks existence,
    so process 0 cleans the version dir before anyone writes)."""
    from flax import serialization

    mesh = make_mesh(MeshSpec(data=1, fsdp=8))
    state, shardings = _fsdp_state(mesh)
    path = str(tmp_path / "ck")

    # simulate a crashed prior save: version dir exists with garbage shard
    # files (even extra ones from a larger imaginary job)
    stale_dir = os.path.join(f"{path}.shards", "00000005")
    os.makedirs(stale_dir)
    for i in range(3):
        with open(os.path.join(stale_dir, f"shard_{i:05d}.msgpack"), "wb") as f:
            f.write(serialization.msgpack_serialize({"garbage": np.zeros(3)}))
    # no manifest, no pointer: the crash happened before commit

    ckpt_lib.save_checkpoint(path, state, 5, 0.0, sharded=True)
    # the stale extra shard is gone; only this 1-process job's shard remains
    names = sorted(os.listdir(stale_dir))
    assert names == ["manifest.msgpack", "shard_00000.msgpack"]
    restored, epoch, _ = ckpt_lib.load_checkpoint(path, state, shardings)
    assert epoch == 5
    _tree_equal(restored, state)


def test_pointer_flips_only_after_manifest_commit(tmp_path, devices, monkeypatch):
    """A reader mid-save sees either no pointer or a fully committed one:
    the write ORDER must be shards -> manifest -> pointer (the pointer is
    the last atomic write). Pinned by recording every atomic write."""
    order = []
    real = ckpt_lib._atomic_write

    def spy(path, blob):
        order.append(os.path.basename(path))
        real(path, blob)

    monkeypatch.setattr(ckpt_lib, "_atomic_write", spy)
    mesh = make_mesh(MeshSpec(data=1, fsdp=8))
    state, _ = _fsdp_state(mesh)
    path = str(tmp_path / "ck")
    ckpt_lib.save_checkpoint(path, state, 1, 0.0, sharded=True)
    assert order.index("manifest.msgpack") < order.index("ck"), order
    assert order.index("shard_00000.msgpack") < order.index(
        "manifest.msgpack"
    ), order


def test_format_switch_gcs_stale_shard_root(tmp_path, devices):
    """Switching --checkpoint-format sharded -> gathered mid-life must not
    strand {path}.shards forever (VERDICT r2 weak #6): committing the
    gathered file removes the now-unreferenced shard root, and the
    checkpoint keeps loading (as gathered)."""
    mesh = make_mesh(MeshSpec(data=1, fsdp=8))
    state, shardings = _fsdp_state(mesh)
    path = str(tmp_path / "latest_model.ckpt")
    ckpt_lib.save_checkpoint(path, state, 1, 0.9, sharded=True)
    assert os.path.isdir(path + ".shards")

    ckpt_lib.save_checkpoint(path, state, 2, 0.8, sharded=False)
    assert not os.path.exists(path + ".shards")  # stale root GC'd
    restored, epoch, _ = ckpt_lib.load_checkpoint(path, state, shardings)
    assert epoch == 2
    _tree_equal(restored, state)


def test_best_and_latest_shard_roots_are_independent(tmp_path, devices):
    """best/latest each own their shard root ({path}.shards); saving one at
    a newer version must not GC or corrupt the other's, and each pointer
    restores its own epoch."""
    mesh = make_mesh(MeshSpec(data=1, fsdp=8))
    state, shardings = _fsdp_state(mesh)
    best = str(tmp_path / "best_model.ckpt")
    latest = str(tmp_path / "latest_model.ckpt")

    ckpt_lib.save_checkpoint(best, state, 3, 0.5, sharded=True, retain=1)
    # latest advances several epochs past best (retain=1: single live
    # version per root, so cross-root GC bleed would be visible)
    for epoch in (3, 4, 5):
        ckpt_lib.save_checkpoint(
            latest, state, epoch, 0.4, sharded=True, retain=1
        )

    _, best_epoch, _ = ckpt_lib.load_checkpoint(best, state, shardings)
    _, latest_epoch, _ = ckpt_lib.load_checkpoint(latest, state, shardings)
    assert (best_epoch, latest_epoch) == (3, 5)
    # latest's GC kept only its newest version; best's root is untouched
    assert len(os.listdir(latest + ".shards")) == 1
    assert len(os.listdir(best + ".shards")) == 1

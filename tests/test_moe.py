"""MoE layer: routing math, capacity, aux loss, expert parallelism."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributed_pytorch_example_tpu.models.moe import MoEMlpBlock
from distributed_pytorch_example_tpu.runtime import MeshSpec, make_mesh


def make_block(**kw):
    defaults = dict(num_experts=4, mlp_dim=64, model_dim=32)
    defaults.update(kw)
    return MoEMlpBlock(**defaults)


def apply_block(block, x, train=False):
    variables = block.init(jax.random.key(0), x, train=False)
    out = block.apply(
        variables, x, train=train, mutable=["losses"] if train else False
    )
    if train:
        return out  # (y, {"losses": ...})
    return out, None


def test_output_shape_and_finite():
    x = jnp.asarray(np.random.default_rng(0).standard_normal((2, 16, 32)), jnp.float32)
    out, _ = apply_block(make_block(), x, train=True)
    assert out.shape == x.shape
    assert np.isfinite(np.asarray(out)).all()


def test_aux_loss_emitted_and_bounded():
    x = jnp.asarray(np.random.default_rng(0).standard_normal((2, 64, 32)), jnp.float32)
    block = make_block(aux_loss_weight=1.0, z_loss_weight=0.0)
    variables = block.init(jax.random.key(0), x, train=False)
    _, state = block.apply(variables, x, train=True, mutable=["losses"])
    aux = float(
        np.asarray(state["losses"]["load_balancing"]).reshape(())
    )
    # Switch aux loss is minimized at 1.0 (uniform routing); random init
    # should be close to, and never far below, that bound
    assert 0.9 < aux < 4.0


def test_router_z_loss_emitted():
    x = jnp.asarray(np.random.default_rng(0).standard_normal((2, 32, 32)), jnp.float32)
    block = make_block(z_loss_weight=1.0)
    variables = block.init(jax.random.key(0), x, train=False)
    _, state = block.apply(variables, x, train=True, mutable=["losses"])
    z = float(np.asarray(state["losses"]["router_z"]).reshape(()))
    assert z > 0  # mean squared logsumexp of real logits is positive


def test_every_surviving_token_routed_once():
    """With generous capacity, output is each token's gated expert output."""
    x = jnp.asarray(np.random.default_rng(1).standard_normal((1, 8, 32)), jnp.float32)
    block = make_block(capacity_factor=8.0)  # capacity >= tokens: no drops
    variables = block.init(jax.random.key(0), x, train=False)
    out = block.apply(variables, x, train=False)
    # manual recompute from the router and expert params
    p = variables["params"]
    logits = x @ p["router"]["kernel"] + p["router"]["bias"]
    probs = jax.nn.softmax(logits, axis=-1)
    idx = jnp.argmax(probs, axis=-1)[0]  # (S,)
    gate = jnp.max(probs, axis=-1)[0]
    expected = []
    for t in range(8):
        e = int(idx[t])
        h = jax.nn.gelu(x[0, t] @ p["up_kernel"][e] + p["up_bias"][e])
        expected.append(gate[t] * (h @ p["down_kernel"][e] + p["down_bias"][e]))
    np.testing.assert_allclose(
        np.asarray(out[0]), np.stack(expected), atol=1e-5
    )


def test_capacity_drops_pass_through_as_zero():
    """Over-capacity tokens contribute zero from the MoE branch."""
    x = jnp.asarray(np.random.default_rng(2).standard_normal((1, 64, 32)), jnp.float32)
    tight = make_block(capacity_factor=0.25)
    variables = tight.init(jax.random.key(0), x, train=False)
    out = tight.apply(variables, x, train=False)
    assert out.shape == x.shape
    # some rows must be exactly zero (dropped tokens)
    row_norms = np.linalg.norm(np.asarray(out[0]), axis=-1)
    assert (row_norms == 0).any()


def test_gradients_flow_to_experts_and_router():
    x = jnp.asarray(np.random.default_rng(3).standard_normal((2, 16, 32)), jnp.float32)
    block = make_block()
    variables = block.init(jax.random.key(0), x, train=False)

    def loss_fn(params):
        out, state = block.apply(
            {"params": params}, x, train=True, mutable=["losses"]
        )
        aux = sum(jax.tree_util.tree_leaves(state["losses"]))
        return jnp.sum(out**2) + aux

    grads = jax.grad(loss_fn)(variables["params"])
    for name in ("router", "up_kernel", "down_kernel"):
        g = grads[name]
        leaves = jax.tree_util.tree_leaves(g)
        assert any(float(jnp.abs(l).max()) > 0 for l in leaves), name


def test_expert_parallel_matches_single_device(devices):
    """EP-sharded weights under jit == unsharded reference output."""
    from distributed_pytorch_example_tpu.parallel.partition import (
        transformer_partitioner,
    )
    from distributed_pytorch_example_tpu.models.gpt2 import GPT2

    mesh = make_mesh(MeshSpec(data=2, expert=4))
    model = GPT2(vocab_size=101, max_len=32, model_dim=32, num_layers=2,
                 num_heads=4, mlp_dim=64, moe_experts=4, moe_every=2)
    tokens = jnp.asarray(np.random.default_rng(0).integers(0, 101, (4, 16)), jnp.int32)
    variables = model.init(jax.random.key(0), tokens, train=False)
    expected = model.apply(variables, tokens, train=False)

    part = transformer_partitioner(mesh)
    specs = part.tree_specs(variables)["params"]["decoder"]["layer_1"]["moe"]
    assert specs["up_kernel"] == jax.sharding.PartitionSpec("expert", None, None)
    sharded = jax.device_put(variables, part.tree_shardings(variables))
    out = jax.jit(lambda v, t: model.apply(v, t, train=False))(sharded, tokens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=2e-4)


def test_moe_gpt2_trains_end_to_end(devices):
    """Full Trainer loop with MoE + aux loss on the fake mesh."""
    import distributed_pytorch_example_tpu as dpx

    mesh = make_mesh(MeshSpec(data=2, expert=4))
    model = dpx.models.get_model(
        "gpt2", vocab_size=64, max_len=32, model_dim=32, num_layers=2,
        num_heads=4, mlp_dim=64, moe_experts=4,
    )
    ds = dpx.data.SyntheticTokenDataset(num_samples=32, seq_len=16, vocab_size=64)
    loader = dpx.data.DeviceLoader(ds, 8, mesh=mesh, num_shards=1, shard_id=0)
    from distributed_pytorch_example_tpu.parallel.partition import (
        transformer_partitioner,
    )

    trainer = dpx.train.Trainer(
        model, dpx.train.CausalLMTask(), optax.adam(1e-3),
        partitioner=transformer_partitioner(mesh),
    )
    history = trainer.fit(loader, epochs=1)
    assert np.isfinite(history[-1]["train_loss"])


def test_top2_matches_per_token_recompute():
    """Generous capacity: output == sum of the two gated expert outputs."""
    x = jnp.asarray(np.random.default_rng(4).standard_normal((1, 8, 32)), jnp.float32)
    block = make_block(top_k=2, capacity_factor=8.0)
    variables = block.init(jax.random.key(0), x, train=False)
    out = block.apply(variables, x, train=False)

    p = variables["params"]
    logits = x @ p["router"]["kernel"] + p["router"]["bias"]
    probs = np.asarray(jax.nn.softmax(logits, axis=-1))[0]  # (S, E)
    expected = []
    for t in range(8):
        top2 = np.argsort(probs[t])[::-1][:2]
        gsum = probs[t][top2].sum()
        acc = np.zeros(32, np.float32)
        for e in top2:
            h = jax.nn.gelu(x[0, t] @ p["up_kernel"][e] + p["up_bias"][e])
            y = h @ p["down_kernel"][e] + p["down_bias"][e]
            acc += (probs[t][e] / gsum) * np.asarray(y)
        expected.append(acc)
    np.testing.assert_allclose(
        np.asarray(out[0]), np.stack(expected), atol=1e-5
    )


def test_top2_first_choices_outrank_second_choices():
    """Under tight capacity, a token's FIRST choice is never displaced by
    an earlier token's SECOND choice (k-major priority)."""
    rng = np.random.default_rng(5)
    x = jnp.asarray(rng.standard_normal((1, 64, 32)), jnp.float32)
    block = make_block(top_k=2, capacity_factor=0.5)
    variables = block.init(jax.random.key(0), x, train=False)
    out = block.apply(variables, x, train=False)
    assert np.isfinite(np.asarray(out)).all()

    # recompute slots with numpy: first choices over all tokens first
    p = variables["params"]
    logits = np.asarray(x[0] @ p["router"]["kernel"] + p["router"]["bias"])
    probs = np.asarray(jax.nn.softmax(jnp.asarray(logits), axis=-1))
    order = np.argsort(probs, axis=-1)[:, ::-1][:, :2]  # (S, 2)
    import math

    capacity = max(1, math.ceil(2 * 64 * 0.5 / 4))
    counts = {e: 0 for e in range(4)}
    kept = set()
    for k in range(2):  # k-major: all first choices, then all second
        for t in range(64):
            e = int(order[t, k])
            if counts[e] < capacity:
                counts[e] += 1
                kept.add((t, k))
    # every token with BOTH choices dropped must be an exact-zero row
    zero_rows = {
        t for t in range(64)
        if (t, 0) not in kept and (t, 1) not in kept
    }
    row_norms = np.linalg.norm(np.asarray(out[0]), axis=-1)
    for t in zero_rows:
        assert row_norms[t] == 0.0, t


def test_top2_ep_sharded_matches_single_device(devices):
    """Top-2 routing under the expert-parallel mesh == unsharded output."""
    from distributed_pytorch_example_tpu.parallel.partition import (
        transformer_partitioner,
    )
    from distributed_pytorch_example_tpu.models.gpt2 import GPT2

    mesh = make_mesh(MeshSpec(data=2, expert=4))
    model = GPT2(vocab_size=101, max_len=32, model_dim=32, num_layers=2,
                 num_heads=4, mlp_dim=64, moe_experts=4, moe_top_k=2)
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, 101, (4, 16)), jnp.int32
    )
    variables = model.init(jax.random.key(0), tokens, train=False)
    expected = model.apply(variables, tokens, train=False)
    part = transformer_partitioner(mesh)
    sharded = jax.device_put(variables, part.tree_shardings(variables))
    out = jax.jit(lambda v, t: model.apply(v, t, train=False))(sharded, tokens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=2e-4)


def test_invalid_top_k_rejected():
    x = jnp.zeros((1, 8, 32), jnp.float32)
    with pytest.raises(ValueError, match="top_k"):
        make_block(top_k=5).init(jax.random.key(0), x, train=False)


def test_dropped_fraction_metric_monotone_in_capacity():
    """Capacity-dropped tokens are observable (VERDICT r2 #7): the sown
    moe_metrics/dropped_fraction shrinks monotonically as capacity_factor
    grows, and vanishes once every (token, choice) pair fits."""
    x = jnp.asarray(
        np.random.default_rng(5).standard_normal((2, 64, 32)), jnp.float32
    )

    def dropped(cf):
        block = make_block(capacity_factor=cf, top_k=2)
        variables = block.init(jax.random.key(0), x, train=False)
        _, state = block.apply(
            variables, x, train=True, mutable=["losses", "moe_metrics"]
        )
        leaves = jax.tree_util.tree_leaves(state["moe_metrics"])
        assert len(leaves) == 1
        return float(leaves[0])

    fracs = [dropped(cf) for cf in (0.25, 0.5, 1.0, 4.0)]
    assert all(0.0 <= f <= 1.0 for f in fracs)
    assert all(a >= b for a, b in zip(fracs, fracs[1:])), fracs
    assert fracs[0] > 0.0  # starved capacity must actually drop
    assert fracs[-1] == pytest.approx(0.0)  # capacity 4x: nothing dropped


def test_dropped_fraction_surfaces_in_train_metrics(devices):
    """The metric reaches the train-step metrics dict via the task layer."""
    import distributed_pytorch_example_tpu as dpx
    from distributed_pytorch_example_tpu.train.tasks import CausalLMTask

    mesh = make_mesh(MeshSpec(data=8))
    model = dpx.models.get_model(
        "gpt2", vocab_size=64, max_len=32, model_dim=32, num_layers=2,
        num_heads=4, mlp_dim=64, moe_experts=4, moe_top_k=2,
        moe_capacity_factor=0.5, use_flash=False,
    )
    trainer = dpx.train.Trainer(
        model, CausalLMTask(), optax.adam(1e-3),
        partitioner=dpx.parallel.data_parallel(mesh),
    )
    tokens = np.random.default_rng(0).integers(0, 64, (8, 16)).astype(np.int32)
    sharding = trainer.partitioner.batch_sharding()
    batch = {"tokens": jax.make_array_from_process_local_data(sharding, tokens)}
    with mesh:
        trainer.init(batch["tokens"])
        _, metrics = trainer.train_step(trainer.state, batch)
    assert "moe_dropped_fraction" in metrics
    frac = float(metrics["moe_dropped_fraction"])
    assert 0.0 <= frac <= 1.0


def test_swiglu_experts_match_per_token_recompute():
    """Mixtral-style SwiGLU experts: output == gated sum of
    silu(x @ gate) * (x @ up + b) @ down per selected expert."""
    x = jnp.asarray(
        np.random.default_rng(6).standard_normal((1, 8, 32)), jnp.float32
    )
    block = make_block(top_k=2, capacity_factor=8.0, swiglu=True)
    variables = block.init(jax.random.key(0), x, train=False)
    out = block.apply(variables, x, train=False)

    p = variables["params"]
    assert p["gate_kernel"].shape == (4, 32, 64)
    assert "up_bias" not in p  # SwiGLU experts are bias-free (llama parity)
    logits = x @ p["router"]["kernel"] + p["router"]["bias"]
    probs = np.asarray(jax.nn.softmax(logits, axis=-1))[0]  # (S, E)
    expected = []
    for t in range(8):
        top2 = np.argsort(probs[t])[::-1][:2]
        gsum = probs[t][top2].sum()
        acc = np.zeros(32, np.float32)
        for e in top2:
            up = x[0, t] @ p["up_kernel"][e]  # bias-free: Mixtral parity
            g = jax.nn.silu(x[0, t] @ p["gate_kernel"][e])
            y = (np.asarray(g) * np.asarray(up)) @ p["down_kernel"][e]
            acc += (probs[t][e] / gsum) * np.asarray(y)
        expected.append(acc)
    np.testing.assert_allclose(
        np.asarray(out[0]), np.stack(expected), atol=1e-5
    )


def test_llama_moe_trains_under_expert_mesh(devices):
    """Mixtral-style LLaMA (GQA + RoPE + SwiGLU MoE) trains end-to-end
    with the expert axis spanning devices; aux losses and the
    drop-fraction metric flow through the task layer."""
    import distributed_pytorch_example_tpu as dpx
    from distributed_pytorch_example_tpu.parallel.partition import (
        transformer_partitioner,
    )
    from distributed_pytorch_example_tpu.train.tasks import CausalLMTask

    mesh = make_mesh(MeshSpec(data=4, expert=2))
    model = dpx.models.get_model(
        "llama", vocab_size=64, max_len=32, model_dim=32, num_layers=2,
        num_heads=4, num_kv_heads=2, mlp_dim=64, moe_experts=4,
        moe_top_k=2, use_flash=False,
    )
    trainer = dpx.train.Trainer(
        model, CausalLMTask(), optax.adam(1e-2),
        partitioner=transformer_partitioner(mesh),
    )
    tokens = np.random.default_rng(0).integers(0, 64, (8, 16)).astype(np.int32)
    sharding = trainer.partitioner.batch_sharding()
    batch = {"tokens": jax.make_array_from_process_local_data(sharding, tokens)}
    with mesh:
        trainer.init(batch["tokens"])
        # expert weights (incl. the SwiGLU gate) must live expert-sharded
        gk = trainer.state.params["layer_1"]["moe"]["gate_kernel"]
        assert gk.sharding.spec[0] == "expert"
        losses = []
        state = trainer.state
        for _ in range(4):
            state, metrics = trainer.train_step(state, batch)
            losses.append(float(metrics["loss"]))
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0], losses
    assert "moe_dropped_fraction" in metrics


def test_sp_ep_matches_dense_mesh(devices):
    """SP x EP without a pipeline: ring attention over the sequence axis
    + expert-parallel MoE MLPs in one program (the per-layer path — ring
    opens its own manual region, expert sharding stays automatic). Loss
    and grads equal the same model on a sequence-span-1 mesh."""
    from distributed_pytorch_example_tpu.models.gpt2 import GPT2
    from distributed_pytorch_example_tpu.runtime import MeshSpec, make_mesh
    from distributed_pytorch_example_tpu.train.tasks import CausalLMTask

    task = CausalLMTask()
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, 64, size=(8, 16)), jnp.int32
    )
    mk = lambda sp: GPT2(
        vocab_size=64, max_len=32, model_dim=32, num_layers=2, num_heads=4,
        mlp_dim=64, seq_axis=sp, sp_mode="ring",
        moe_experts=4, moe_every=1, moe_top_k=2, moe_capacity_factor=8.0,
        logits_mode="hidden",
    )
    mesh_sp = make_mesh(MeshSpec(data=2, sequence=2, expert=2))
    mesh_d = make_mesh(MeshSpec(data=4, expert=2))
    m_sp, m_d = mk("sequence"), mk(None)
    with mesh_sp:
        params = m_sp.init(jax.random.key(0), tokens, train=False)["params"]

    def loss(model, mesh):
        def f(p):
            with mesh:
                l, _, _ = task.compute_loss(
                    model, p, {}, {"tokens": tokens}, jax.random.key(1),
                    train=True,
                )
            return l

        return f

    l_sp, g_sp = jax.value_and_grad(loss(m_sp, mesh_sp))(params)
    l_d, g_d = jax.value_and_grad(loss(m_d, mesh_d))(params)
    np.testing.assert_allclose(float(l_sp), float(l_d), rtol=3e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-4
        ),
        g_sp, g_d,
    )

"""Checkpoint integrity envelope: CRC32-sealed msgpack blobs.

Every checkpoint artifact (gathered payload, shard file, manifest) is
written wrapped in a tiny self-describing envelope::

    b"DPX-CRC1\\n" + <4-byte little-endian crc32 of body> + <body>

so the loader can distinguish "file exists but is torn/bit-flipped" from
"file is intact" BEFORE msgpack parsing — a truncated msgpack blob can
deserialize into a silently wrong pytree, which is far worse than a loud
failure. Per-shard (not per-checkpoint) sealing matters because the
sharded format has no single writer: each process seals its own shard, so
one corrupt shard file is attributable and the fallback walk (see
``train/checkpoint.py``) can skip just that checkpoint version.

Files written before this envelope existed (no magic prefix) pass through
``unseal`` unverified — old checkpoints stay loadable.
"""

from __future__ import annotations

import struct
import zlib

ENVELOPE_MAGIC = b"DPX-CRC1\n"
_CRC_LEN = 4


class CheckpointCorruptError(RuntimeError):
    """A checkpoint artifact failed integrity verification."""


def seal(body: bytes) -> bytes:
    """Wrap ``body`` in the CRC envelope."""
    return ENVELOPE_MAGIC + struct.pack("<I", zlib.crc32(body)) + body


def is_sealed(data: bytes) -> bool:
    return data[: len(ENVELOPE_MAGIC)] == ENVELOPE_MAGIC


def unseal(data: bytes, source: str = "<bytes>") -> bytes:
    """Verify and strip the envelope; legacy (unsealed) data passes through.

    Raises :class:`CheckpointCorruptError` on a truncated envelope or a
    CRC mismatch, naming ``source`` so the fallback walk can log exactly
    which artifact was bad.
    """
    if not is_sealed(data):
        return data  # pre-envelope checkpoint: loadable, unverified
    header = len(ENVELOPE_MAGIC) + _CRC_LEN
    if len(data) < header:
        raise CheckpointCorruptError(
            f"{source}: truncated integrity envelope "
            f"({len(data)} bytes < {header}-byte header)"
        )
    (expect,) = struct.unpack_from("<I", data, len(ENVELOPE_MAGIC))
    body = data[header:]
    actual = zlib.crc32(body)
    if actual != expect:
        raise CheckpointCorruptError(
            f"{source}: checksum mismatch (stored crc32={expect:#010x}, "
            f"computed {actual:#010x}, body {len(body)} bytes) — torn or "
            f"bit-flipped write"
        )
    return body


def read_verified(path: str) -> bytes:
    """Read ``path`` and return its verified body (legacy passes through)."""
    with open(path, "rb") as f:
        return unseal(f.read(), source=path)

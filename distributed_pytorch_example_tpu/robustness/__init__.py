"""graft-armor: self-healing recovery + deterministic fault injection.

Two halves that validate each other (ISSUE 5):

- recovery surfaces threaded through the stack — checkpoint integrity
  envelopes with keep-last-K retention and automatic fallback
  (``train/checkpoint.py``), device-side bad-step predication with a
  bounded skip budget and rollback (``train/step.py`` + ``train/loop.py``),
  bounded retry on rendezvous and checkpoint I/O (:mod:`.retry`);
- the chaos harness (:mod:`.chaos`) that injects seeded, replayable
  faults at exactly those surfaces so every recovery path is provable
  (``tests/test_chaos.py``, ``scripts/chaos_sweep.py``).

graft-elastic (ISSUE 6) adds :mod:`.elastic`: the format-3 mesh-topology
manifest stamped into every checkpoint, cross-mesh resume validation,
and the ``DPX_ELASTIC=1`` gate for shrink-to-survivors rendezvous
(``runtime/distributed.py``) and newest-intact-wins fallback ordering
(``train/checkpoint.py``).
"""

from distributed_pytorch_example_tpu.robustness.chaos import (  # noqa: F401
    ChaosPlan,
    Fault,
)
from distributed_pytorch_example_tpu.robustness.elastic import (  # noqa: F401
    MANIFEST_FORMAT,
    MissingMeshManifestError,
    elastic_enabled,
    mesh_manifest,
)
from distributed_pytorch_example_tpu.robustness.integrity import (  # noqa: F401
    CheckpointCorruptError,
    read_verified,
    seal,
    unseal,
)
from distributed_pytorch_example_tpu.robustness.publish import (  # noqa: F401
    PublishChannel,
)
from distributed_pytorch_example_tpu.robustness.retry import (  # noqa: F401
    with_retries,
)


class BadStepBudgetExceeded(RuntimeError):
    """Nonfinite-step skips exhausted ``max_bad_steps`` after a rollback.

    Raised by the Trainer when the predicated update has skipped more
    nonfinite steps than the budget allows AND a one-shot rollback to the
    last good checkpoint already happened (or no checkpoint exists): the
    fault is persistent — diverged optimization, bad data shard, real
    numerics bug — and retrying further would only burn accelerator time.
    """

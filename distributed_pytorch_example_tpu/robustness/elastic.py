"""graft-elastic: mesh-shape-agnostic checkpoint resume (format 3).

The r10 checkpoint formats already reassemble full logical arrays on load
and re-shard them onto the target layout (``train/checkpoint.py`` module
docstring), so a checkpoint mechanically restores under any mesh. What
was missing is everything that makes cross-mesh resume *operable*:

- a **mesh-topology manifest** stamped into every save (``format: 3``,
  key ``mesh_manifest``): mesh axis names/sizes, per-leaf PartitionSpecs,
  and the ZeRO-1 scatter dims — derived from the live state's
  NamedShardings, so the stamp always reflects what was actually saved;
- **resume validation** (:func:`validate_resume`): elastic resume
  (``DPX_ELASTIC=1``) from an unstamped pre-format-3 checkpoint raises
  :class:`MissingMeshManifestError` naming the missing manifest instead
  of silently assuming the topology; stamped cross-mesh restores are
  logged with the stamped → target shape delta;
- **elastic fallback ordering**: under ``DPX_ELASTIC=1`` the newest
  intact checkpoint wins regardless of stamped mesh shape; without it
  the intact-ancestor walk-back prefers same-mesh ancestors
  (``load_checkpoint``);
- the **shrink-to-survivors** launcher path lives in
  ``runtime/distributed.py`` (:func:`elastic_enabled` gates it there
  too), and ``scripts/reshard_check.py`` turns the stamp into an
  offline per-leaf reshard plan.

Mesh axes are compared CANONICALLY — size-1 axes dropped — so e.g. a
``data=8`` mesh and a ``data=8, tensor=1`` mesh are the same topology
(a ZeRO-1 flip on the same device set never reads as a mesh change).
"""

from __future__ import annotations

import os
import re
from typing import Any, Dict, List, Optional, Union

import jax

from distributed_pytorch_example_tpu.runtime.logging import get_logger

logger = get_logger(__name__)

# checkpoint manifest format carrying the mesh stamp. 1 = pre-r10
# unsealed, 2 = r10 CRC-sealed (implicit, unstamped), 3 = stamped.
MANIFEST_FORMAT = 3
MANIFEST_KEY = "mesh_manifest"
ELASTIC_ENV = "DPX_ELASTIC"

# mirrors parallel/api.py's opt-state path test (kept local: robustness
# must not import the parallel layer)
_OPT_STATE_RE = re.compile(r"(^|/)opt_state(/|$)")
_VERSION_RE = re.compile(r"\d{8}(\.\d{8})?")
_HISTORY_RE = re.compile(r"\d{8}\.ckpt")

# one PartitionSpec dim serialized for msgpack: None (unsharded), one
# axis name, or a list of axis names
SpecEntry = Union[None, str, List[str]]


class MissingMeshManifestError(RuntimeError):
    """Elastic cross-mesh resume attempted from an unstamped checkpoint.

    Pre-format-3 (r10 and older) checkpoints carry no ``mesh_manifest``,
    so the loader cannot know what topology they were saved under. They
    keep loading under the legacy contract — same mesh shape, no
    validation — but ``DPX_ELASTIC=1`` resume refuses them loudly
    instead of guessing.
    """


def elastic_enabled(env: Optional[dict] = None) -> bool:
    """True when ``DPX_ELASTIC`` is set truthy (elastic resume mode)."""
    val = (env if env is not None else os.environ).get(ELASTIC_ENV, "")
    return val not in ("", "0", "false", "False")


def _path_str(key_path) -> str:
    # must produce the same '/'-joined paths as train/checkpoint.py's
    # _path_str — manifest spec keys index the same flatten
    parts = []
    for p in key_path:
        for attr in ("key", "name", "idx"):
            if hasattr(p, attr):
                parts.append(str(getattr(p, attr)))
                break
        else:
            parts.append(str(p))
    return "/".join(parts)


def _spec_entries(spec) -> List[SpecEntry]:
    entries: List[SpecEntry] = []
    for dim in tuple(spec):
        if dim is None:
            entries.append(None)
        elif isinstance(dim, (tuple, list)):
            entries.append([str(a) for a in dim])
        else:
            entries.append(str(dim))
    return entries


def _entry_axes(entry: SpecEntry) -> List[str]:
    if entry is None:
        return []
    if isinstance(entry, (list, tuple)):
        return [str(a) for a in entry]
    return [str(entry)]


def canonical_axes(axes: Optional[dict]) -> Optional[Dict[str, int]]:
    """Axis-name → size with size-1 axes dropped (topology identity)."""
    if axes is None:
        return None
    return {str(k): int(v) for k, v in axes.items() if int(v) != 1}


def mesh_manifest(state: Any) -> Optional[dict]:
    """Format-3 mesh stamp derived from the LIVE state's shardings.

    Returns ``None`` when no leaf carries a NamedSharding (pure-host
    state) — the save then stays unstamped, which loads under the
    legacy same-mesh contract.
    """
    flat, _ = jax.tree_util.tree_flatten_with_path(state)
    axes: Optional[dict] = None
    specs: Dict[str, List[SpecEntry]] = {}
    zero1_dims: Dict[str, int] = {}
    for key_path, leaf in flat:
        sharding = getattr(leaf, "sharding", None)
        if not isinstance(sharding, jax.sharding.NamedSharding):
            continue
        p = _path_str(key_path)
        if axes is None:
            axes = {
                str(k): int(v) for k, v in sharding.mesh.shape.items()
            }
        entries = _spec_entries(sharding.spec)
        specs[p] = entries
        if _OPT_STATE_RE.search(p):
            for dim, entry in enumerate(entries):
                if "data" in _entry_axes(entry):
                    zero1_dims[p] = dim
                    break
    if axes is None:
        return None
    return {
        "format": MANIFEST_FORMAT,
        "axes": axes,
        "specs": specs,
        "zero1_dims": zero1_dims,
    }


def tree_mesh_axes(tree: Any) -> Optional[Dict[str, int]]:
    """Target mesh axes from a shardings tree OR a live state template."""
    if tree is None:
        return None
    for leaf in jax.tree_util.tree_leaves(
        tree, is_leaf=lambda x: x is None
    ):
        sharding = (
            leaf
            if isinstance(leaf, jax.sharding.NamedSharding)
            else getattr(leaf, "sharding", None)
        )
        if isinstance(sharding, jax.sharding.NamedSharding):
            return {
                str(k): int(v) for k, v in sharding.mesh.shape.items()
            }
    return None


def validate_resume(
    stamp: Optional[dict],
    target_axes: Optional[dict],
    source: str,
    elastic: Optional[bool] = None,
) -> Optional[dict]:
    """Gate one restore attempt on the manifest stamp; returns the stamp.

    - unstamped + ``DPX_ELASTIC=1`` → :class:`MissingMeshManifestError`
      (elastic resume needs to know the saved topology);
    - unstamped otherwise → legacy same-mesh contract, no validation;
    - stamped + shape change → allowed in BOTH modes (the sharded format
      has promised cross-mesh restore since r5), logged loudly so a
      surprise reshard is visible in the run log.
    """
    if elastic is None:
        elastic = elastic_enabled()
    if not isinstance(stamp, dict):
        stamp = None
    if stamp is None:
        if elastic:
            raise MissingMeshManifestError(
                f"{source}: checkpoint has no '{MANIFEST_KEY}' stamp "
                f"(pre-format-{MANIFEST_FORMAT}, r10 or older). Elastic "
                f"resume ({ELASTIC_ENV}=1) cannot verify the saved mesh "
                "topology; resume on the original mesh shape with "
                f"{ELASTIC_ENV} unset (which re-stamps on the next "
                "save), then retry elastically."
            )
        return None
    stamped = canonical_axes(stamp.get("axes", {}))
    target = canonical_axes(target_axes)
    if target is not None and stamped != target:
        logger.warning(
            "Cross-mesh resume from %s: checkpoint stamped %s, restoring "
            "onto %s (%s)", source, stamped, target,
            "elastic mode" if elastic else "reshard-on-load",
        )
    return stamp


def _parse_version(name: str):
    if "." in name:
        epoch, batch = name.split(".", 1)
        return int(epoch), int(batch)
    return int(name), 0


def resume_gap_steps(
    path: str, restored_epoch: int, restored_extra: Optional[dict] = None
) -> Optional[int]:
    """Steps between the restored cursor and the newest save attempt.

    0 means the newest checkpoint restored (no work lost); a positive
    number counts the optimizer steps between the restored mid-epoch
    cursor and the newest (possibly torn) save of the SAME epoch; None
    means the gap spans an epoch boundary (steps-per-epoch unknown
    offline) or is undeterminable for the format.
    """
    restored = (
        int(restored_epoch),
        int((restored_extra or {}).get("batch_in_epoch") or 0),
    )
    shards = f"{path}.shards"
    if os.path.isdir(shards):
        names = sorted(
            n for n in os.listdir(shards) if _VERSION_RE.fullmatch(n)
        )
        if not names:
            return None
        newest = _parse_version(names[-1])
        if newest == restored:
            return 0
        if newest[0] == restored[0]:
            return max(newest[1] - restored[1], 0)
        return None
    history = f"{path}.history"
    if os.path.isdir(history):
        names = sorted(
            n for n in os.listdir(history) if _HISTORY_RE.fullmatch(n)
        )
        if names:
            try:
                if os.path.samefile(os.path.join(history, names[-1]), path):
                    return 0
            except OSError:
                pass
        return None
    # single-artifact checkpoint: nothing newer can exist
    return 0

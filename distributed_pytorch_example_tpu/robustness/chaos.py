"""graft-armor's deterministic fault-injection harness.

A :class:`ChaosPlan` is a seeded, serializable list of faults; production
code calls the tiny hook functions below at its fault-relevant points
(batch ingestion, checkpoint writes, sharded-save commit, rendezvous).
With no plan installed every hook is a no-op costing one global read —
the harness is compiled out of nothing and adds no steady-state work.

Faults are injected at exact, named sites rather than randomly in time,
so every scenario in ``scripts/chaos_sweep.py`` replays bit-identically:
the same plan always poisons the same global step, fails the same write,
and kills the same save. Plans travel to child training processes via the
``DPX_CHAOS`` environment variable (JSON).

Fault kinds:

- ``nan-batch`` / ``inf-batch`` — overwrite the first float leaf of the
  training batch with NaN/Inf for ``count`` steps starting at ``step``
  (exercises the bad-step predicated update, train/step.py);
- ``io-error`` — raise a transient ``OSError`` on the next ``count``
  checkpoint writes whose path contains ``path_substr`` (exercises the
  AsyncSaver retry path);
- ``kill`` — SIGKILL the current process the ``nth`` time the named
  crash point is reached (e.g. ``sharded-save:post-shards`` — between
  shard-file writes and the manifest/pointer commit: a torn save; or
  ``step`` — the per-step boundary in ``train/loop.py``, the
  kill-a-slice site graft-elastic's shrink-to-survivors scenario uses);
- ``rendezvous-flake`` — fail (after an optional delay) the next
  ``count`` entries into the named transient site (e.g. coordinator
  rendezvous in ``runtime/distributed.initialize``);
- ``poison-request`` — NaN-poison the logits of serving request ``at``
  (the request id) for ``count`` sampled tokens starting at generated-
  token index ``step`` (exercises graft-serve's bad-request isolation:
  the request is evicted with an error status, co-resident requests are
  untouched — serving/engine.py, scripts/chaos_sweep.py);
- ``kill-replica`` / ``stall-replica`` — fleet faults (graft-fleet): at
  decode boundary ``step`` (1-based) of serving replica ``at``, the
  replica worker dies abruptly (kill: in-flight requests lost, exactly a
  SIGKILLed serving container) or stops making progress without dying
  (stall: the hang class heartbeats exist for). The router must detect
  either within its heartbeat deadline and replay the lost requests
  elsewhere bit-identically (serving/fleet.py, serving/router.py);
- ``flaky-channel`` — transient ``OSError`` on the next ``count``
  dispatches to replica ``at`` (empty = any replica), exercising the
  router's bounded dispatch retry (robustness/retry.py);
- ``corrupt-shard`` — bit-flip the data-shard file whose path contains
  ``path_substr`` on the ``nth`` read touch (graft-intake: the sealed
  sidecar catches it at first verification and the shard is
  quarantined, data/streaming.py);
- ``slow-shard-io`` — sleep ``delay_s`` on the next ``count`` shard
  read touches matching ``path_substr`` (input-bound steps must show up
  as ``data_stall_ms``, not silently stretch the step time);
- ``kill-decode-worker`` — crash the supervised prefetch worker at the
  first produced batch index ``>= step`` (fires once; the supervisor
  must restart it re-producing the exact batch, data/intake.py);
- ``corrupt-publish`` — bit-flip the ``nth`` published checkpoint
  artifact AFTER it is fully written but before the pointer flips
  (graft-swap: the version commits but its CRC is broken, so the fleet's
  intact-ancestor walk must skip it — robustness/publish.py);
- ``torn-publish`` — SIGKILL the publisher between the version-dir
  artifact write and the pointer flip on the ``nth`` publish (the torn
  window; the fleet must keep serving the previous version and the next
  publish must heal the channel);
- ``kill-during-swap`` — abort the SwapController mid-roll at the
  ``nth`` visit of the named roll stage ``at`` (e.g. ``pre-install``:
  after the replica drained but before new weights install), simulating
  a controller crash between replicas; the next tick must resume and
  complete the roll with the fleet still consistent (serving/swap.py).
"""

from __future__ import annotations

import dataclasses
import errno
import json
import os
import signal
import time
from typing import Any, List, Optional

from distributed_pytorch_example_tpu.runtime.logging import get_logger

logger = get_logger(__name__)

ENV_VAR = "DPX_CHAOS"
KINDS = (
    "nan-batch", "inf-batch", "io-error", "kill", "rendezvous-flake",
    "poison-request", "kill-replica", "stall-replica", "flaky-channel",
    "corrupt-shard", "slow-shard-io", "kill-decode-worker",
    "corrupt-publish", "torn-publish", "kill-during-swap",
)


@dataclasses.dataclass
class Fault:
    """One seeded fault; see module docstring for per-kind semantics."""

    kind: str
    step: int = -1          # nan/inf-batch: first poisoned global step
    count: int = 1          # nan/inf-batch: steps; io/rendezvous: failures
    path_substr: str = ""   # io-error: only writes whose path contains this
    at: str = ""            # kill: crash-point name
    nth: int = 1            # kill: trigger on the Nth visit of that point
    delay_s: float = 0.0    # rendezvous-flake: sleep before failing
    fired: int = 0          # live counter (io/rendezvous firings, kill visits)

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(
                f"unknown chaos fault kind {self.kind!r} (one of {KINDS})"
            )


class ChaosPlan:
    """A seeded list of faults, serializable for child processes."""

    def __init__(self, faults: List[Fault], seed: int = 0):
        self.faults = list(faults)
        self.seed = int(seed)

    @classmethod
    def from_json(cls, text: str) -> "ChaosPlan":
        spec = json.loads(text)
        return cls(
            [Fault(**f) for f in spec.get("faults", [])],
            seed=spec.get("seed", 0),
        )

    def to_json(self) -> str:
        return json.dumps({
            "seed": self.seed,
            "faults": [
                {
                    k: v
                    for k, v in dataclasses.asdict(f).items()
                    if k != "fired"
                }
                for f in self.faults
            ],
        })

    def __repr__(self):
        return f"ChaosPlan(seed={self.seed}, faults={self.faults!r})"


def preset(name: str) -> ChaosPlan:
    """Named plans for `bench.py --chaos` and quick CLI use."""
    if name == "nan-step":
        # poison one batch well past warmup; the predicated update skips it
        return ChaosPlan([Fault("nan-batch", step=3)])
    if name == "io-flake":
        # two transient write failures on `latest`; retry heals both
        return ChaosPlan([Fault("io-error", path_substr="latest", count=2)])
    if name == "kill-replica":
        # fleet replica r1 dies at its 8th decode boundary: late enough
        # that requests are mid-stream, early enough that survivors still
        # carry real load after the loss
        return ChaosPlan([Fault("kill-replica", at="r1", step=8)])
    if name == "stall-replica":
        # same boundary, but the replica hangs instead of dying — only
        # the heartbeat deadline can catch this one
        return ChaosPlan([Fault("stall-replica", at="r1", step=8)])
    if name == "flaky-channel":
        # two transient dispatch failures; the router's bounded retry heals
        return ChaosPlan([Fault("flaky-channel", count=2)])
    raise ValueError(f"unknown chaos preset {name!r}")


# ---------------------------------------------------------------------------
# plan installation (module-global; one plan active per process)
# ---------------------------------------------------------------------------

_plan: Optional[ChaosPlan] = None
_env_checked = False


def install(plan: Optional[ChaosPlan]) -> None:
    global _plan, _env_checked
    _plan = plan
    _env_checked = True  # an explicit install wins over the env var
    if plan is not None:
        logger.warning("chaos: fault plan installed: %s", plan)


def uninstall() -> None:
    global _plan, _env_checked
    _plan = None
    _env_checked = False


def active() -> Optional[ChaosPlan]:
    """The installed plan, lazily parsing ``DPX_CHAOS`` on first use."""
    global _plan, _env_checked
    if not _env_checked:
        _env_checked = True
        spec = os.environ.get(ENV_VAR)
        if spec:
            try:
                _plan = (
                    ChaosPlan.from_json(spec)
                    if spec.lstrip().startswith("{")
                    else preset(spec)
                )
                logger.warning(
                    "chaos: fault plan from $%s: %s", ENV_VAR, _plan
                )
            except (ValueError, TypeError, KeyError) as err:
                raise ValueError(
                    f"malformed ${ENV_VAR} chaos spec: {err}"
                ) from err
    return _plan


# ---------------------------------------------------------------------------
# hooks (called from production code; no-ops without a matching fault)
# ---------------------------------------------------------------------------


def corrupt_batch(batch: Any, step: int) -> Any:
    """Poison the first float leaf of ``batch`` if a fault targets ``step``.

    The replacement is placed with ``jax.device_put`` onto the original
    leaf's sharding, so the poisoned step compiles/runs identically to a
    clean one (no resharding, no new executables — required for the
    no-recompile recovery contract).
    """
    plan = active()
    if plan is None:
        return batch
    fault = next(
        (
            f for f in plan.faults
            if f.kind in ("nan-batch", "inf-batch")
            and f.step <= step < f.step + f.count
        ),
        None,
    )
    if fault is None:
        return batch
    import jax
    import jax.numpy as jnp
    import numpy as np

    val = np.nan if fault.kind == "nan-batch" else np.inf
    out = dict(batch)
    for key, leaf in batch.items():
        if hasattr(leaf, "dtype") and jnp.issubdtype(leaf.dtype, jnp.floating):
            poisoned = np.full(leaf.shape, val, dtype=leaf.dtype)
            sharding = getattr(leaf, "sharding", None)
            out[key] = (
                jax.device_put(poisoned, sharding)
                if sharding is not None
                else poisoned
            )
            fault.fired += 1
            logger.warning(
                "chaos: %s injected into batch leaf %r at step %d",
                fault.kind, key, step,
            )
            return out
    logger.warning(
        "chaos: %s fault at step %d found no float batch leaf to poison "
        "(integer-token task?); batch left clean", fault.kind, step,
    )
    return batch


def on_write(path: str) -> None:
    """Transient-``OSError`` injection point (top of ``_atomic_write``)."""
    plan = active()
    if plan is None:
        return
    for fault in plan.faults:
        if (
            fault.kind == "io-error"
            and fault.fired < fault.count
            and fault.path_substr in path
        ):
            fault.fired += 1
            logger.warning(
                "chaos: injected transient OSError on write %d/%d to %s",
                fault.fired, fault.count, path,
            )
            raise OSError(
                errno.EIO, "chaos: injected transient I/O error", path
            )


def crash_point(name: str) -> None:
    """SIGKILL this process at a named site when a kill fault matches."""
    plan = active()
    if plan is None:
        return
    for fault in plan.faults:
        if fault.kind == "kill" and fault.at == name:
            fault.fired += 1
            if fault.fired == fault.nth:
                logger.warning(
                    "chaos: SIGKILL at crash point %r (visit %d)",
                    name, fault.fired,
                )
                os.kill(os.getpid(), signal.SIGKILL)


def transient_failure(name: str) -> None:
    """Named transient-failure site (rendezvous); raises while armed."""
    plan = active()
    if plan is None:
        return
    for fault in plan.faults:
        if (
            fault.kind == "rendezvous-flake"
            and (not fault.at or fault.at == name)
            and fault.fired < fault.count
        ):
            fault.fired += 1
            if fault.delay_s:
                time.sleep(fault.delay_s)
            logger.warning(
                "chaos: injected transient failure at %r (%d/%d)",
                name, fault.fired, fault.count,
            )
            raise RuntimeError(
                f"chaos: injected transient failure at {name!r}"
            )


def poison_request(request_id: str, token_index: int) -> bool:
    """Whether a serving request's logits should be NaN-poisoned for the
    generated token at ``token_index`` (0-based). The engine feeds the
    returned flag into its compiled step as a regular input, so the
    poisoned step runs the SAME executable as a clean one — the
    no-recompile injection contract the other hooks follow."""
    plan = active()
    if plan is None:
        return False
    for fault in plan.faults:
        if (
            fault.kind == "poison-request"
            and fault.at == str(request_id)
            and fault.step <= token_index < fault.step + fault.count
        ):
            fault.fired += 1
            logger.warning(
                "chaos: poisoning request %r at generated token %d",
                request_id, token_index,
            )
            return True
    return False


def replica_fault(replica_id: str, decode_step: int) -> Optional[str]:
    """Fleet fault poll, called by each replica worker at its decode
    boundaries (``decode_step`` is 1-based): ``"kill"`` — die abruptly,
    losing in-flight state; ``"stall"`` — stop making progress without
    dying; ``None`` — keep serving. Fires once per fault, at the first
    boundary ``>= step`` (boundary counts differ run-to-run only under
    preemption, so `>=` keeps the plan replayable)."""
    plan = active()
    if plan is None:
        return None
    for fault in plan.faults:
        if (
            fault.kind in ("kill-replica", "stall-replica")
            and fault.at == str(replica_id)
            and fault.fired == 0
            and 0 <= fault.step <= decode_step
        ):
            fault.fired += 1
            action = "kill" if fault.kind == "kill-replica" else "stall"
            logger.warning(
                "chaos: %s replica %r at decode boundary %d",
                action, replica_id, decode_step,
            )
            return action
    return None


def flaky_channel(replica_id: str) -> None:
    """Transient-``OSError`` injection on the router->replica dispatch
    channel (top of the router's retried submit); ``at`` empty matches
    any replica."""
    plan = active()
    if plan is None:
        return
    for fault in plan.faults:
        if (
            fault.kind == "flaky-channel"
            and (not fault.at or fault.at == str(replica_id))
            and fault.fired < fault.count
        ):
            fault.fired += 1
            logger.warning(
                "chaos: injected flaky channel to replica %r (%d/%d)",
                replica_id, fault.fired, fault.count,
            )
            raise OSError(
                errno.EIO,
                f"chaos: injected flaky channel to replica {replica_id}",
            )


def shard_read(path: str) -> None:
    """Data-shard read touch (graft-intake): ``corrupt-shard`` bit-flips
    the file on disk at the ``nth`` matching touch (the sealed sidecar
    must catch it on verification); ``slow-shard-io`` sleeps ``delay_s``
    for the next ``count`` matching touches."""
    plan = active()
    if plan is None:
        return
    for fault in plan.faults:
        if fault.kind == "corrupt-shard" and fault.path_substr in path:
            fault.fired += 1
            if fault.fired == fault.nth:
                logger.warning(
                    "chaos: corrupting shard %s (touch %d)",
                    path, fault.fired,
                )
                corrupt_file(path, mode="bitflip", seed=plan.seed)
        elif (
            fault.kind == "slow-shard-io"
            and fault.path_substr in path
            and fault.fired < fault.count
        ):
            fault.fired += 1
            delay = fault.delay_s or 0.05
            logger.warning(
                "chaos: slow shard I/O on %s — sleeping %.3fs (%d/%d)",
                path, delay, fault.fired, fault.count,
            )
            time.sleep(delay)


def decode_worker(batch_index: int) -> None:
    """Supervised-prefetch-worker crash site (graft-intake): a
    ``kill-decode-worker`` fault raises inside the producer at the first
    produced batch index ``>= step``, once (`>=` keeps the plan
    replayable when the restart re-produces earlier indices)."""
    plan = active()
    if plan is None:
        return
    for fault in plan.faults:
        if (
            fault.kind == "kill-decode-worker"
            and fault.fired == 0
            and 0 <= fault.step <= batch_index
        ):
            fault.fired += 1
            logger.warning(
                "chaos: killing decode worker at batch %d", batch_index
            )
            raise RuntimeError(
                f"chaos: decode worker killed at batch {batch_index}"
            )


def publish_fault(stage: str, path: str) -> None:
    """Publish-channel attack points (robustness/publish.py). Called
    twice per publish, with the artifact path: stage ``post-artifact``
    (version fully written, pointer not yet flipped — where
    ``corrupt-publish`` bit-flips the artifact so the commit carries a
    broken CRC) and stage ``pre-pointer`` (where ``torn-publish``
    SIGKILLs the publisher, leaving an uncommitted version dir). Both
    count matching visits and fire on the ``nth``; ``path_substr``
    optionally narrows to one channel."""
    plan = active()
    if plan is None:
        return
    for fault in plan.faults:
        if fault.path_substr and fault.path_substr not in path:
            continue
        if fault.kind == "corrupt-publish" and stage == "post-artifact":
            fault.fired += 1
            if fault.fired == fault.nth:
                logger.warning(
                    "chaos: corrupting published artifact %s (publish %d)",
                    path, fault.fired,
                )
                corrupt_file(path, mode="bitflip", seed=plan.seed)
        elif fault.kind == "torn-publish" and stage == "pre-pointer":
            fault.fired += 1
            if fault.fired == fault.nth:
                logger.warning(
                    "chaos: SIGKILL mid-publish (torn) before pointer "
                    "flip of %s (publish %d)", path, fault.fired,
                )
                os.kill(os.getpid(), signal.SIGKILL)


def swap_fault(stage: str) -> bool:
    """SwapController roll-stage poll (serving/swap.py): a
    ``kill-during-swap`` fault whose ``at`` matches ``stage`` (empty =
    any stage) returns True at its ``nth`` matching visit — the
    controller must abandon the current roll as if it crashed there and
    finish it on a later tick."""
    plan = active()
    if plan is None:
        return False
    for fault in plan.faults:
        if fault.kind == "kill-during-swap" and (
            not fault.at or fault.at == stage
        ):
            fault.fired += 1
            if fault.fired == fault.nth:
                logger.warning(
                    "chaos: aborting swap roll at stage %r (visit %d)",
                    stage, fault.fired,
                )
                return True
    return False


# ---------------------------------------------------------------------------
# offline corruption (tests / chaos_sweep attacking files between runs)
# ---------------------------------------------------------------------------


def corrupt_file(path: str, mode: str = "bitflip", seed: int = 0) -> None:
    """Deterministically damage an existing file.

    ``bitflip`` flips one bit at a seed-chosen offset (checksum mismatch);
    ``truncate`` cuts the file to half (torn write).
    """
    size = os.path.getsize(path)
    if size == 0:
        raise ValueError(f"cannot corrupt empty file {path}")
    if mode == "truncate":
        with open(path, "r+b") as f:
            f.truncate(max(size // 2, 1))
        logger.warning("chaos: truncated %s to %d bytes", path, size // 2)
    elif mode == "bitflip":
        # LCG keeps this dependency-free and reproducible across runs
        offset = (seed * 2654435761 + 12345) % size
        with open(path, "r+b") as f:
            f.seek(offset)
            byte = f.read(1)
            f.seek(offset)
            f.write(bytes([byte[0] ^ 0x40]))
        logger.warning("chaos: flipped bit at offset %d of %s", offset, path)
    else:
        raise ValueError(f"unknown corruption mode {mode!r}")

"""graft-swap's publish channel: corruption-safe train→serve handoff.

A :class:`PublishChannel` is a directory a training run publishes sealed,
mesh-manifest-stamped checkpoint blobs into and a serving fleet polls::

    <root>/
      versions/
        00000001/ckpt.msgpack   # CRC-sealed payload (integrity.seal)
        00000002/ckpt.msgpack
      LATEST                    # sealed pointer: b"DPX-PUB1\\n" + version

Commit protocol (same discipline as the sharded checkpoint format,
``train/checkpoint.py``): the version directory and its artifact are
fully written FIRST, then the ``LATEST`` pointer flips atomically
(tmp + ``os.replace``). Consequences, by construction:

- a **torn publish** (writer killed between artifact write and pointer
  flip) is invisible — readers never look past the committed pointer, so
  the fleet keeps serving the previous version and the next successful
  publish heals the channel;
- a **corrupt publish** (bit-flipped artifact) is caught by the CRC
  envelope at read time and skipped via the graft-armor intact-ancestor
  walk: :meth:`PublishChannel.latest` falls back to the newest intact
  version at or below the pointer;
- a **corrupt pointer** degrades to a committed-version scan (mirroring
  the sharded checkpoint's garbage-pointer fallback) — but the scan only
  trusts versions it can verify, so a torn dir still never wins over an
  intact committed ancestor unless nothing committed survives.

Chaos kinds ``corrupt-publish`` / ``torn-publish`` (robustness/chaos.py)
attack exactly these two windows; ``scripts/chaos_sweep.py`` and
``tests/test_step_resume.py`` pin both guarantees.
"""

from __future__ import annotations

import os
import re
import shutil
from typing import Callable, List, Optional, Tuple

from distributed_pytorch_example_tpu.robustness import chaos
from distributed_pytorch_example_tpu.robustness.integrity import (
    CheckpointCorruptError,
    is_sealed,
    seal,
    unseal,
)
from distributed_pytorch_example_tpu.runtime.logging import get_logger

logger = get_logger(__name__)

POINTER_MAGIC = b"DPX-PUB1\n"
POINTER_NAME = "LATEST"
VERSIONS_DIR = "versions"
ARTIFACT_NAME = "ckpt.msgpack"
DEFAULT_RETAIN = 3

_VERSION_RE = re.compile(r"\d{8}")


class PublishChannel:
    """A versioned publish directory with pointer-flip commit.

    ``retain`` keeps the newest K committed versions (the intact-ancestor
    walk's fallback depth); older dirs are garbage-collected after each
    successful pointer flip. The channel is single-writer (the training
    run) / multi-reader (fleet SwapControllers, the offline doctor).
    """

    def __init__(self, root: str, *, retain: int = DEFAULT_RETAIN):
        self.root = str(root)
        self.retain = max(int(retain), 1)
        # last (chosen, skipped) the fallback warning fired for: pollers
        # call latest() several times a second and a degraded-but-
        # servable channel must not flood the log
        self._warned_fallback: Optional[tuple] = None

    # -- paths ------------------------------------------------------------

    @property
    def pointer_path(self) -> str:
        return os.path.join(self.root, POINTER_NAME)

    @property
    def versions_root(self) -> str:
        return os.path.join(self.root, VERSIONS_DIR)

    def artifact_path(self, version: str) -> str:
        return os.path.join(self.versions_root, version, ARTIFACT_NAME)

    def versions(self) -> List[str]:
        """All version-dir names on disk, oldest first (committed or not)."""
        if not os.path.isdir(self.versions_root):
            return []
        return sorted(
            n for n in os.listdir(self.versions_root)
            if _VERSION_RE.fullmatch(n)
            and os.path.isdir(os.path.join(self.versions_root, n))
        )

    # -- writer side ------------------------------------------------------

    def publish_blob(self, blob: bytes) -> str:
        """Publish one checkpoint blob; returns the committed version name.

        ``blob`` is sealed if it isn't already (checkpoint writers hand
        over the already-sealed gathered payload, so the common path adds
        no envelope twice). The pointer flip is the commit point; chaos
        ``corrupt-publish`` fires after the artifact write and
        ``torn-publish`` SIGKILLs between artifact and pointer.
        """
        if not is_sealed(blob):
            blob = seal(blob)
        existing = self.versions()
        version = f"{(int(existing[-1]) if existing else 0) + 1:08d}"
        vdir = os.path.join(self.versions_root, version)
        os.makedirs(vdir, exist_ok=True)
        artifact = self.artifact_path(version)
        _atomic_write_bytes(artifact, blob)
        chaos.publish_fault("post-artifact", artifact)
        chaos.publish_fault("pre-pointer", artifact)
        _atomic_write_bytes(
            self.pointer_path, seal(POINTER_MAGIC + version.encode())
        )
        logger.info("publish: committed version %s to %s", version, self.root)
        self._gc(version)
        return version

    def _gc(self, pointer_version: str) -> None:
        """Keep the newest ``retain`` INTACT versions at or below the
        pointer (the intact-ancestor walk's real fallback depth);
        everything else at or below it — aged-out ancestors, corrupt
        commits, torn leftovers from a killed publisher — is removed.
        This is where a successful publish heals the channel."""
        keep = set()
        for name in reversed(self.versions()):
            if (
                name <= pointer_version
                and len(keep) < self.retain
                and self._intact(name)
            ):
                keep.add(name)
        for name in self.versions():
            # never remove the pointed version itself, even when corrupt:
            # the pointer must keep naming an on-disk dir so the doctor
            # can report WHY the reader walked past it
            if name not in keep and name < pointer_version:
                shutil.rmtree(
                    os.path.join(self.versions_root, name),
                    ignore_errors=True,
                )

    # -- reader side ------------------------------------------------------

    def pointer_version(self) -> Optional[str]:
        """The committed pointer's version name, or None if the pointer is
        missing/corrupt/malformed (readers then fall back to a scan)."""
        try:
            body = _read_sealed(self.pointer_path)
        except (OSError, CheckpointCorruptError):
            return None
        if not body.startswith(POINTER_MAGIC):
            return None
        name = body[len(POINTER_MAGIC):].decode("ascii", "replace").strip()
        return name if _VERSION_RE.fullmatch(name) else None

    def _intact(self, version: str) -> bool:
        try:
            _read_sealed(self.artifact_path(version))
            return True
        except (OSError, CheckpointCorruptError):
            return False

    def latest(
        self, on_event: Optional[Callable[..., None]] = None
    ) -> Optional[str]:
        """Newest servable version: the pointed version when intact, else
        the graft-armor intact-ancestor walk over committed versions
        (never past the pointer — torn publishes are invisible). A
        corrupt pointer degrades to the full committed scan. ``on_event``
        (kind, **fields) mirrors ``load_checkpoint``'s reporting hook.
        """
        pointed = self.pointer_version()
        candidates = [
            v for v in reversed(self.versions())
            if pointed is None or v <= pointed
        ]
        skipped = []
        for version in candidates:
            if self._intact(version):
                if skipped and on_event is not None:
                    on_event(
                        "publish_fallback", chosen=version, skipped=skipped
                    )
                if skipped and self._warned_fallback != (version, tuple(skipped)):
                    self._warned_fallback = (version, tuple(skipped))
                    logger.warning(
                        "publish: version(s) %s corrupt; serving intact "
                        "ancestor %s", skipped, version,
                    )
                return version
            skipped.append(version)
        return None

    def read(self, version: str) -> bytes:
        """The verified (unsealed) payload body of ``version``."""
        return _read_sealed(self.artifact_path(version))

    def load_latest(self) -> Optional[Tuple[str, bytes]]:
        version = self.latest()
        if version is None:
            return None
        return version, self.read(version)

    # -- offline doctor ---------------------------------------------------

    def state(self) -> dict:
        """Channel health for ``scripts/reshard_check.py``'s JSON line:
        pointer integrity, per-version seal/intact status, and the
        version a fleet would actually serve."""
        pointed = self.pointer_version()
        per_version = []
        for name in self.versions():
            artifact = self.artifact_path(name)
            sealed = False
            try:
                with open(artifact, "rb") as f:
                    data = f.read()
                sealed = is_sealed(data)
                _read_sealed(artifact)
                intact = True
                error = None
            except (OSError, CheckpointCorruptError) as err:
                intact = False
                error = str(err)
            per_version.append({
                "version": name,
                "committed": pointed is not None and name <= pointed,
                "sealed": sealed,
                "intact": intact,
                **({"error": error} if error else {}),
            })
        latest = self.latest()
        return {
            "root": self.root,
            "pointer": {
                "exists": os.path.exists(self.pointer_path),
                "intact": pointed is not None,
                "version": pointed,
            },
            "versions": per_version,
            "latest_intact": latest,
            "ok": latest is not None and latest == pointed,
        }


def is_publish_channel(path: str) -> bool:
    """Whether ``path`` looks like a channel root (for the doctor's
    format auto-detect): a ``versions/`` dir or a ``LATEST`` pointer
    carrying the publish magic."""
    if os.path.isdir(os.path.join(path, VERSIONS_DIR)):
        return True
    pointer = os.path.join(path, POINTER_NAME)
    try:
        body = _read_sealed(pointer)
    except (OSError, CheckpointCorruptError):
        return False
    return body.startswith(POINTER_MAGIC)


def _read_sealed(path: str) -> bytes:
    """Verified body of a channel artifact, REQUIRING the CRC envelope.

    ``integrity.unseal`` passes pre-envelope (legacy) files through
    unverified — right for old checkpoints, wrong here: a bit-flip
    inside the envelope header would demote a sealed artifact to
    'legacy' and skip verification. Every channel artifact is written
    sealed by construction, so an unsealed one IS corruption.
    """
    with open(path, "rb") as f:
        data = f.read()
    if not is_sealed(data):
        raise CheckpointCorruptError(
            f"{path}: publish artifact is not CRC-sealed (torn or "
            "corrupt envelope)"
        )
    return unseal(data, source=path)


def _atomic_write_bytes(path: str, blob: bytes) -> None:
    """tmp + ``os.replace`` (the checkpoint commit discipline); chaos
    ``io-error`` faults target this via the shared on_write hook."""
    chaos.on_write(path)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(blob)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)

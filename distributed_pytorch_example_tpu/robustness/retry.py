"""Bounded retry with deterministic exponential backoff.

One helper shared by the transient-failure surfaces (coordinator
rendezvous in ``runtime/distributed.py``, checkpoint I/O in
``train/checkpoint.AsyncSaver``). Backoff is deterministic — no jitter —
so the chaos matrix (``scripts/chaos_sweep.py``) replays bit-identically:
a seeded fault plan that heals after N failures always sees the same
retry schedule.
"""

from __future__ import annotations

import time
from typing import Callable, Optional, Tuple, Type

from distributed_pytorch_example_tpu.runtime.logging import get_logger

logger = get_logger(__name__)


def backoff_schedule(
    attempts: int, base_delay: float, max_delay: float
) -> list:
    """Delays slept between attempts: base * 2^k, capped at max_delay."""
    return [
        min(base_delay * (2.0 ** k), max_delay)
        for k in range(max(attempts - 1, 0))
    ]


def with_retries(
    fn: Callable,
    *,
    attempts: int = 4,
    base_delay: float = 0.05,
    max_delay: float = 2.0,
    retry_on: Tuple[Type[BaseException], ...] = (OSError,),
    describe: str = "operation",
    sleep: Callable[[float], None] = time.sleep,
    on_retry: Optional[Callable[[int, BaseException], None]] = None,
):
    """Call ``fn()``; on a ``retry_on`` failure, back off and try again.

    At most ``attempts`` total calls. The final failure propagates
    unchanged (callers keep their native exception type); every retried
    failure is logged with the delay so an operator can see transient
    flakes that healed. ``on_retry(attempt_index, error)`` fires before
    each re-attempt (telemetry counters).
    """
    if attempts < 1:
        raise ValueError(f"attempts must be >= 1, got {attempts}")
    delays = backoff_schedule(attempts, base_delay, max_delay)
    for attempt in range(attempts):
        try:
            return fn()
        except retry_on as err:
            if attempt >= len(delays):
                logger.error(
                    "%s failed after %d attempt(s): %s",
                    describe, attempts, err,
                )
                raise
            delay = delays[attempt]
            logger.warning(
                "%s failed (attempt %d/%d): %s — retrying in %.2fs",
                describe, attempt + 1, attempts, err, delay,
            )
            if on_retry is not None:
                on_retry(attempt, err)
            sleep(delay)

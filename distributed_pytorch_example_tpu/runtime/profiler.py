"""Profiling hooks: XLA trace capture around a training-step window.

The reference has no profiler (SURVEY.md §5 "Tracing / profiling: ABSENT" —
only wall-clock epoch timing, reference train.py:265,283). Here tracing is a
first-class option: a ``StepProfiler`` arms on a step window and captures an
XLA/TensorBoard trace (HLO timelines, per-op device time) via
``jax.profiler`` — the tool that actually explains TPU step time.

Host 0 profiles; other processes no-op (one trace per job).
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

from distributed_pytorch_example_tpu.runtime.logging import get_logger

logger = get_logger(__name__)


class StepProfiler:
    """Captures a device trace for global steps [start, stop).

    Drive it from the training loop: ``profiler.step(global_step)`` once per
    step; trace starts when the window opens and stops when it closes (or at
    ``close()`` if the run ends early).
    """

    def __init__(
        self,
        logdir: Optional[str],
        window: Tuple[int, int] = (10, 13),
        process_index: int = 0,
    ):
        self.logdir = logdir if process_index == 0 else None
        self.start_step, self.stop_step = window
        self._active = False

    def step(self, global_step: int) -> None:
        if self.logdir is None:
            return
        if not self._active and self.start_step <= global_step < self.stop_step:
            import jax

            os.makedirs(self.logdir, exist_ok=True)
            jax.profiler.start_trace(self.logdir)
            self._active = True
            logger.info("Profiler trace started at step %d -> %s",
                        global_step, self.logdir)
        elif self._active and global_step >= self.stop_step:
            self._stop()

    def _stop(self) -> None:
        import jax

        jax.profiler.stop_trace()
        self._active = False
        logger.info("Profiler trace written to %s", self.logdir)

    def close(self) -> None:
        if self._active:
            self._stop()

"""Profiling hooks: XLA trace capture around a training-step window.

The reference has no profiler (SURVEY.md §5 "Tracing / profiling: ABSENT" —
only wall-clock epoch timing, reference train.py:265,283). Here tracing is a
first-class option: a ``StepProfiler`` arms on a step window and captures an
XLA/TensorBoard trace (HLO timelines, per-op device time) via
``jax.profiler`` — the tool that actually explains TPU step time.

Host 0 profiles; other processes no-op (one trace per job).

The profiler is also a graft-scope consumer: telemetry health triggers
(nonfinite grads, cross-host step-time skew) can :meth:`~StepProfiler.arm`
a fresh window mid-run, so the trace that explains an anomaly is captured
in the SAME run that detected it. On resume, the Trainer calls
:meth:`~StepProfiler.rebase` so the configured window is interpreted
relative to the resumed step — a window of (10, 13) traces the 10th-12th
steps of THIS run, not of the whole job history (a resume landing past an
absolute window would otherwise never capture).
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

from distributed_pytorch_example_tpu.runtime.logging import get_logger

logger = get_logger(__name__)


class StepProfiler:
    """Captures a device trace for global steps [start, stop).

    Drive it from the training loop: ``profiler.step(global_step)`` once per
    step; trace starts when the window opens and stops when it closes (or at
    ``close()`` if the run ends early).
    """

    def __init__(
        self,
        logdir: Optional[str],
        window: Tuple[int, int] = (10, 13),
        process_index: int = 0,
    ):
        self.logdir = logdir if process_index == 0 else None
        self.start_step, self.stop_step = window
        self._active = False
        self._last_step = -1
        self._arm_reason = ""

    def rebase(self, first_step: int) -> None:
        """Re-anchor the configured window at ``first_step`` (resume).

        The window is run-relative: resuming at step 500 with window
        (10, 13) traces steps [510, 513). No-op for fresh runs
        (``first_step == 0``) and once stepping has begun.
        """
        if self.logdir is None or self._active or self._last_step >= 0:
            return
        if first_step:
            self.start_step += first_step
            self.stop_step += first_step
            logger.info(
                "Profiler window rebased to [%d, %d) from resumed step %d",
                self.start_step, self.stop_step, first_step,
            )

    def arm(self, start_step: int, stop_step: int, reason: str = "") -> bool:
        """Arm a fresh trace window (graft-scope trigger path).

        Refused while a trace is active or a not-yet-passed window is still
        pending — one window at a time, first trigger wins.
        """
        if self.logdir is None or self._active:
            return False
        if self._last_step < self.stop_step:
            return False  # the configured window is still ahead or open
        if stop_step <= start_step or start_step <= self._last_step:
            return False
        self.start_step, self.stop_step = start_step, stop_step
        self._arm_reason = reason
        logger.info(
            "Profiler armed for steps [%d, %d)%s",
            start_step, stop_step, f": {reason}" if reason else "",
        )
        return True

    def step(self, global_step: int) -> None:
        self._last_step = global_step
        if self.logdir is None:
            return
        if not self._active and self.start_step <= global_step < self.stop_step:
            import jax

            os.makedirs(self.logdir, exist_ok=True)
            jax.profiler.start_trace(self.logdir)
            self._active = True
            logger.info("Profiler trace started at step %d -> %s",
                        global_step, self.logdir)
        elif self._active and global_step >= self.stop_step:
            self._stop()

    def _stop(self) -> None:
        import jax

        try:
            jax.profiler.stop_trace()
        except Exception:
            # a capture that failed to open must not take the run down at
            # teardown; the window state is reset either way
            logger.warning("Profiler stop_trace failed", exc_info=True)
        finally:
            self._active = False
        logger.info("Profiler trace written to %s", self.logdir)

    def close(self) -> None:
        """Stop an open capture; report a window that never opened.

        Safe to call repeatedly, and clean for an armed-but-unopened window
        (run ended before ``start_step``): nothing to flush, but the miss is
        logged so a silent "no trace produced" has a visible cause.
        """
        if self._active:
            self._stop()
        elif (
            self.logdir is not None
            and self._last_step >= 0
            and self._last_step < self.start_step
        ):
            logger.info(
                "Profiler window [%d, %d) never opened (run ended at step "
                "%d)%s",
                self.start_step, self.stop_step, self._last_step,
                f"; armed: {self._arm_reason}" if self._arm_reason else "",
            )

"""Device mesh construction.

The mesh is the TPU-native replacement for the reference's process group
(reference train.py:71): instead of a flat rank/world_size with hand-called
collectives, every device joins a named multi-axis mesh and XLA compiles the
collectives implied by sharding annotations over ICI/DCN.

Axis vocabulary used across the framework:

- ``data``     — pure data parallelism (the reference's only axis; its DDP
  world maps to a 1-D ``('data',)`` mesh).
- ``fsdp``     — data parallelism whose param/optimizer state is sharded
  (ZeRO-style); batch is sharded over (data, fsdp) jointly.
- ``tensor``   — tensor (operator) parallelism inside layers.
- ``sequence`` — sequence/context parallelism (ring attention).
- ``expert``   — expert parallelism (MoE layers' expert dim).
- ``pipe``     — pipeline parallelism (GPipe stages, parallel/pipeline.py).

``MeshSpec`` sizes multiply to the device count; -1 means "absorb the rest"
(at most one axis).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Sequence

import numpy as np


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Named axis sizes for the global device mesh."""

    data: int = -1
    fsdp: int = 1
    tensor: int = 1
    sequence: int = 1
    expert: int = 1
    pipe: int = 1

    def resolve(self, n_devices: int) -> "MeshSpec":
        sizes = dataclasses.asdict(self)
        unknown = [k for k, v in sizes.items() if v == -1]
        if len(unknown) > 1:
            raise ValueError(f"At most one mesh axis may be -1, got {unknown}")
        known = math.prod(v for v in sizes.values() if v != -1)
        if unknown:
            if n_devices % known != 0:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes product {known}"
                )
            sizes[unknown[0]] = n_devices // known
        elif known != n_devices:
            raise ValueError(
                f"Mesh axes product {known} != device count {n_devices}"
            )
        return MeshSpec(**sizes)

    @property
    def axis_names(self) -> Sequence[str]:
        return ("data", "fsdp", "tensor", "sequence", "expert", "pipe")

    def axis_sizes(self) -> Sequence[int]:
        return (self.data, self.fsdp, self.tensor, self.sequence, self.expert, self.pipe)


def make_mesh(
    spec: Optional[MeshSpec] = None,
    devices: Optional[Sequence] = None,
):
    """Build a ``jax.sharding.Mesh`` over all (or given) devices.

    Default: every device on the ``data`` axis — the direct TPU equivalent of
    the reference's DDP world (train.py:233), with the remaining axes size-1 so
    the same partition specs work unchanged at any parallelism config.

    Uses ``mesh_utils.create_device_mesh`` when spanning all devices so the
    axis order matches the physical ICI topology (fastest-varying axes get the
    tightest links).
    """
    import jax
    from jax.experimental import mesh_utils
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    spec = (spec or MeshSpec()).resolve(len(devices))
    shape = tuple(spec.axis_sizes())
    if len(devices) == len(jax.devices()) and devices == list(jax.devices()):
        try:
            dev_array = mesh_utils.create_device_mesh(shape, devices=devices)
        except Exception:
            dev_array = np.array(devices).reshape(shape)
    else:
        dev_array = np.array(devices).reshape(shape)
    return Mesh(dev_array, spec.axis_names)


def current_mesh():
    """The mesh of the enclosing ``with mesh:`` context, or None.

    Lets modules deep inside a model (e.g. ring attention) find the active
    mesh without threading it through every constructor.
    """
    # private import: narrow except so a JAX relayout fails loudly here
    # instead of silently disabling every mesh-aware op
    try:
        from jax._src.mesh import thread_resources
    except (ImportError, AttributeError) as e:
        raise RuntimeError(
            "jax moved jax._src.mesh.thread_resources; update "
            "runtime.mesh.current_mesh for this jax version"
        ) from e
    mesh = thread_resources.env.physical_mesh
    return mesh if mesh.devices.size > 0 else None


def data_axes(mesh) -> Sequence[str]:
    """The mesh axes a global batch is sharded over (data + fsdp)."""
    return tuple(a for a in ("data", "fsdp") if a in mesh.axis_names)


def data_parallel_size(mesh) -> int:
    return math.prod(mesh.shape[a] for a in data_axes(mesh))

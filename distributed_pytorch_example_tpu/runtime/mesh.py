"""Device mesh construction.

The mesh is the TPU-native replacement for the reference's process group
(reference train.py:71): instead of a flat rank/world_size with hand-called
collectives, every device joins a named multi-axis mesh and XLA compiles the
collectives implied by sharding annotations over ICI/DCN.

Axis vocabulary used across the framework:

- ``data``     — pure data parallelism (the reference's only axis; its DDP
  world maps to a 1-D ``('data',)`` mesh).
- ``fsdp``     — data parallelism whose param/optimizer state is sharded
  (ZeRO-style); batch is sharded over (data, fsdp) jointly.
- ``tensor``   — tensor (operator) parallelism inside layers.
- ``sequence`` — sequence/context parallelism (ring attention).
- ``expert``   — expert parallelism (MoE layers' expert dim).
- ``pipe``     — pipeline parallelism (GPipe stages, parallel/pipeline.py).

``MeshSpec`` sizes multiply to the device count; -1 means "absorb the rest"
(at most one axis).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Sequence

import numpy as np


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Named axis sizes for the global device mesh."""

    data: int = -1
    fsdp: int = 1
    tensor: int = 1
    sequence: int = 1
    expert: int = 1
    pipe: int = 1

    def resolve(self, n_devices: int) -> "MeshSpec":
        sizes = dataclasses.asdict(self)
        unknown = [k for k, v in sizes.items() if v == -1]
        if len(unknown) > 1:
            raise ValueError(f"At most one mesh axis may be -1, got {unknown}")
        known = math.prod(v for v in sizes.values() if v != -1)
        if unknown:
            if n_devices % known != 0:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes product {known}"
                )
            sizes[unknown[0]] = n_devices // known
        elif known != n_devices:
            raise ValueError(
                f"Mesh axes product {known} != device count {n_devices}"
            )
        return MeshSpec(**sizes)

    @property
    def axis_names(self) -> Sequence[str]:
        return ("data", "fsdp", "tensor", "sequence", "expert", "pipe")

    def axis_sizes(self) -> Sequence[int]:
        return (self.data, self.fsdp, self.tensor, self.sequence, self.expert, self.pipe)


def _num_slices(devices) -> int:
    """Distinct TPU slices among ``devices`` (1 = single slice / unknown)."""
    ids = {getattr(d, "slice_index", None) for d in devices}
    if None in ids:
        return 1
    return len(ids)


def _hybrid_shapes(spec: "MeshSpec", n_slices: int):
    """(per_slice_shape, dcn_shape) for a multi-slice mesh, or None.

    Policy: the slice boundary (DCN — orders of magnitude slower than ICI)
    lands on a batch axis — ``data`` first, else ``fsdp`` — whose gradient
    all-reduce / param all-gather are the collectives most tolerant of DCN
    latency (they overlap compute); every other axis stays inside a slice
    on ICI. Requires the chosen axis size % n_slices == 0.
    """
    if n_slices <= 1:
        return None
    sizes = list(spec.axis_sizes())
    for axis in (0, 1):  # 'data', then 'fsdp' (ZeRO configs run data=1)
        if sizes[axis] % n_slices == 0:
            per_slice = list(sizes)
            dcn = [1] * len(sizes)
            per_slice[axis] = sizes[axis] // n_slices
            dcn[axis] = n_slices
            return tuple(per_slice), tuple(dcn)
    return None


def _hybrid_device_array(per_slice, dcn, devices, n_slices):
    """Device array for a multi-slice mesh: DCN boundary on one axis.

    First choice is jax's ``create_hybrid_device_mesh`` (TPU devices carry
    ``slice_index``); environments whose devices don't (virtual CPU slices
    in tests/dryruns, where the slice structure is declared via
    ``make_mesh(n_slices=...)``) get a manual construction: the device
    list is partitioned into ``n_slices`` contiguous groups, each group
    laid out as its own per-slice mesh, and the groups concatenated along
    the DCN axis — so crossing that axis IS crossing the slice boundary.
    """
    from jax.experimental import mesh_utils

    try:
        return mesh_utils.create_hybrid_device_mesh(
            per_slice, dcn, devices=devices
        )
    except Exception:
        if getattr(devices[0], "slice_index", None) is not None:
            # real multi-slice devices where jax's own construction failed:
            # the manual layout below may not respect physical slice
            # membership if the list isn't slice-contiguous — surface it
            from distributed_pytorch_example_tpu.runtime.logging import (
                get_logger,
            )

            get_logger(__name__).warning(
                "create_hybrid_device_mesh failed on devices that carry "
                "slice_index; building the hybrid layout manually by "
                "grouping on slice_index — verify the mesh if slices are "
                "unevenly populated"
            )
    if getattr(devices[0], "slice_index", None) is not None:
        # group by the devices' actual slice membership, not list order
        by_slice = {}
        for d in devices:
            by_slice.setdefault(d.slice_index, []).append(d)
        groups = [by_slice[k] for k in sorted(by_slice)]
    else:
        # virtual slices (CPU tests/dryruns): contiguous list-order groups
        groups = [
            devices[
                i * (len(devices) // n_slices):
                (i + 1) * (len(devices) // n_slices)
            ]
            for i in range(n_slices)
        ]
    try:
        slice_arrays = []
        for g in groups:
            try:
                slice_arrays.append(
                    mesh_utils.create_device_mesh(per_slice, devices=g)
                )
            except Exception:
                slice_arrays.append(np.array(g).reshape(per_slice))
        axis = dcn.index(n_slices)
        return np.concatenate(slice_arrays, axis=axis)
    except Exception:
        # e.g. unevenly populated slices after partial loss: a group can't
        # fill per_slice. Degrade to the naive layout (caller warns) rather
        # than killing the job at mesh construction.
        return None


def make_mesh(
    spec: Optional[MeshSpec] = None,
    devices: Optional[Sequence] = None,
    n_slices: Optional[int] = None,
):
    """Build a ``jax.sharding.Mesh`` over all (or given) devices.

    Default: every device on the ``data`` axis — the direct TPU equivalent of
    the reference's DDP world (train.py:233), with the remaining axes size-1 so
    the same partition specs work unchanged at any parallelism config.

    Uses ``mesh_utils.create_device_mesh`` when spanning all devices so the
    axis order matches the physical ICI topology (fastest-varying axes get
    the tightest links). Multi-slice jobs (devices spanning several TPU
    slices connected over DCN) get a hybrid mesh with the slice dimension
    on the ``data`` axis — see :func:`_hybrid_shapes`. ``n_slices``
    overrides slice detection for devices that don't report
    ``slice_index`` (virtual CPU slices in tests/dryruns).
    """
    import jax
    from jax.experimental import mesh_utils
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    spec = (spec or MeshSpec()).resolve(len(devices))
    shape = tuple(spec.axis_sizes())
    if n_slices is None:
        n_slices = _num_slices(devices)
    if n_slices > 1 and len(devices) % n_slices:
        raise ValueError(
            f"{len(devices)} devices not divisible into {n_slices} slices"
        )
    spans_all = (
        len(devices) == len(jax.devices()) and devices == list(jax.devices())
    )
    hybrid = _hybrid_shapes(spec, n_slices)
    if hybrid is not None:
        per_slice, dcn = hybrid
        dev_array = _hybrid_device_array(per_slice, dcn, devices, n_slices)
        if dev_array is None:  # degraded: fall through to naive + warning
            hybrid = None
            dev_array = np.array(devices).reshape(shape)
    elif spans_all:
        try:
            dev_array = mesh_utils.create_device_mesh(shape, devices=devices)
        except Exception:
            dev_array = np.array(devices).reshape(shape)
    else:
        dev_array = np.array(devices).reshape(shape)
    if n_slices > 1 and hybrid is None:
        from distributed_pytorch_example_tpu.runtime.logging import (
            get_logger,
        )

        get_logger(__name__).warning(
            "multi-slice job (%d slices) fell back to a naive device "
            "layout: the mesh is NOT DCN-aware and cross-slice links may "
            "land inside ICI axes. Check that a batch axis (data/fsdp) is "
            "divisible by the slice count.",
            n_slices,
        )
    return Mesh(dev_array, spec.axis_names)


def current_mesh():
    """The mesh of the enclosing ``with mesh:`` context, or None.

    Lets modules deep inside a model (e.g. ring attention) find the active
    mesh without threading it through every constructor.
    """
    # private import: narrow except so a JAX relayout fails loudly here
    # instead of silently disabling every mesh-aware op
    try:
        from jax._src.mesh import thread_resources
    except (ImportError, AttributeError) as e:
        raise RuntimeError(
            "jax moved jax._src.mesh.thread_resources; update "
            "runtime.mesh.current_mesh for this jax version"
        ) from e
    mesh = thread_resources.env.physical_mesh
    return mesh if mesh.devices.size > 0 else None


def data_axes(mesh) -> Sequence[str]:
    """The mesh axes a global batch is sharded over (data + fsdp)."""
    return tuple(a for a in ("data", "fsdp") if a in mesh.axis_names)


def data_parallel_size(mesh) -> int:
    return math.prod(mesh.shape[a] for a in data_axes(mesh))

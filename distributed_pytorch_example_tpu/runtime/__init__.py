"""Runtime layer: process bootstrap, device mesh, rank-tagged logging.

TPU-native replacement for the reference's launch/communication layers
(reference entrypoint.sh:1-39 and train.py:70-98). One Python process per
host; devices join a global mesh; collectives are compiled by XLA.
"""

from distributed_pytorch_example_tpu.runtime.distributed import (  # noqa: F401
    DistributedConfig,
    barrier,
    initialize,
    is_coordinator,
    process_count,
    process_index,
    shutdown,
)
from distributed_pytorch_example_tpu.runtime.mesh import (  # noqa: F401
    MeshSpec,
    make_mesh,
)
from distributed_pytorch_example_tpu.runtime.logging import (  # noqa: F401
    get_logger,
    setup_logging,
)

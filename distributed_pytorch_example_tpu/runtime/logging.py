"""Process-index-tagged logging.

Parity target: the reference's rank-aware logging (reference train.py:16-29),
which formats every record as ``... - [Rank %(rank)s] ...`` and injects the
rank from the ``RANK`` env var via a ``logging.Filter``. The reference attaches
the filter to a single module logger while using a global format string, so
records from other libraries lack the field (SURVEY.md §5 notes this quirk).

Here we do it cleanly with a ``logging.setLogRecordFactory`` hook so *every*
record — from any library — carries the process index, and the tag reflects
``jax.process_index()`` once the distributed runtime is up (falling back to the
``PROCESS_ID``/``RANK`` env vars before that, preserving the reference's
env-contract behavior).
"""

from __future__ import annotations

import logging
import os
from typing import Optional

_FORMAT = "%(asctime)s - %(name)s - %(levelname)s - [Rank %(rank)s] %(message)s"

_configured = False


def _current_rank() -> str:
    """Best-effort process index: live JAX value, else env, else '?'.

    Mirrors reference train.py:24 (``os.environ.get("RANK", "?")``) but
    prefers the authoritative ``jax.process_index()`` once available.
    """
    try:
        import jax

        # Only query if a backend has already been initialized; asking
        # process_index() eagerly would trigger backend init from inside a
        # log call, which we never want.
        if jax._src.xla_bridge._backends:  # noqa: SLF001
            return str(jax.process_index())
    except Exception:
        pass
    return os.environ.get("PROCESS_ID", os.environ.get("RANK", "?"))


def setup_logging(level: int = logging.INFO, force: bool = False) -> None:
    """Install the rank-tagged record factory + root handler.

    Safe to call multiple times (idempotent unless ``force``).
    """
    global _configured
    if _configured and not force:
        return

    old_factory = logging.getLogRecordFactory()

    def record_factory(*args, **kwargs):
        record = old_factory(*args, **kwargs)
        if not hasattr(record, "rank"):
            record.rank = _current_rank()
        return record

    logging.setLogRecordFactory(record_factory)
    logging.basicConfig(level=level, format=_FORMAT, force=force)
    _configured = True


def get_logger(name: Optional[str] = None) -> logging.Logger:
    """Return a logger, ensuring rank-tagged logging is configured."""
    setup_logging()
    return logging.getLogger(name)

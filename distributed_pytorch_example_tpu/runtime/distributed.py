"""Multi-host process bootstrap and rendezvous.

TPU-native replacement for the reference's torchrun + gloo process-group setup
(reference train.py:70-86, entrypoint.sh:24-39). On TPU there is one Python
process per host; ``jax.distributed.initialize`` replaces
``dist.init_process_group`` and the c10d TCP rendezvous, and XLA's compiled
collectives over ICI/DCN replace gloo.

The topology contract is the same env-var split the reference uses (SURVEY.md
§5 "Config / flag system": flags for science, env for topology):

- ``NF_DISCOVERY_SERVICE`` — headless-service DNS suffix (entrypoint.sh:8).
- ``REPLICAS``             — number of hosts / processes (entrypoint.sh:19).
- ``COORDINATOR_PORT``     — rendezvous port (reference ``MASTER_PORT``,
  entrypoint.sh:5, default 29500).
- ``PROCESS_ID``           — this host's index; when unset it is derived from
  the hostname's numeric suffix exactly like ``NODE_RANK=${HOSTNAME##*-}``
  (entrypoint.sh:25).
- ``COORDINATOR_ADDRESS``  — full override; when unset it is derived as
  ``{base}-0.{NF_DISCOVERY_SERVICE}:{port}`` exactly like entrypoint.sh:26-28.

Single-process use requires no env vars at all (parity with the reference's
``torchrun --nnodes=1`` smoke mode, SURVEY.md §4).
"""

from __future__ import annotations

import dataclasses
import os
import socket
from typing import Optional

from distributed_pytorch_example_tpu.runtime.logging import get_logger

logger = get_logger(__name__)

_initialized = False


@dataclasses.dataclass(frozen=True)
class DistributedConfig:
    """Resolved multi-host topology."""

    num_processes: int
    process_id: int
    coordinator_address: Optional[str]  # host:port, None for single-process

    @property
    def is_distributed(self) -> bool:
        return self.num_processes > 1


def derive_process_id(hostname: Optional[str] = None) -> int:
    """Node rank from the hostname's trailing numeric suffix.

    Parity with ``NODE_RANK=${HOSTNAME##*-}`` (reference entrypoint.sh:25):
    ``worker-3`` → 3. Falls back to 0 when there is no numeric suffix.
    """
    hostname = hostname if hostname is not None else socket.gethostname()
    suffix = hostname.rsplit("-", 1)[-1]
    return int(suffix) if suffix.isdigit() else 0


def derive_coordinator_address(
    hostname: Optional[str] = None,
    discovery_service: Optional[str] = None,
    port: Optional[int] = None,
) -> str:
    """Coordinator DNS name from replica-0's stable hostname.

    Parity with ``MASTER_ADDR="${BASE_NAME}-0.${HEADLESS_SERVICE}"``
    (reference entrypoint.sh:26-28): host ``myjob-3`` with discovery service
    ``svc`` → ``myjob-0.svc:<port>``. Without a discovery service the bare
    ``{base}-0`` hostname is used (single-network setups / tests).
    """
    hostname = hostname if hostname is not None else socket.gethostname()
    if discovery_service is None:
        discovery_service = os.environ.get("NF_DISCOVERY_SERVICE")
    if port is None:
        port = int(os.environ.get("COORDINATOR_PORT", os.environ.get("MASTER_PORT", "29500")))
    base = hostname.rsplit("-", 1)[0] if "-" in hostname else hostname
    coordinator_host = f"{base}-0"
    if discovery_service:
        coordinator_host = f"{coordinator_host}.{discovery_service}"
    return f"{coordinator_host}:{port}"


def resolve_config(env: Optional[dict] = None) -> DistributedConfig:
    """Resolve topology from the environment (see module docstring)."""
    env = dict(os.environ) if env is None else env
    num_processes = int(env.get("NUM_PROCESSES", env.get("REPLICAS", "1")))
    if num_processes <= 1:
        return DistributedConfig(1, 0, None)

    process_id = env.get("PROCESS_ID", env.get("NODE_RANK"))
    if process_id is None:
        process_id = derive_process_id(env.get("HOSTNAME"))
    coordinator = env.get("COORDINATOR_ADDRESS", env.get("MASTER_ADDR"))
    if coordinator is None:
        coordinator = derive_coordinator_address(
            hostname=env.get("HOSTNAME"),
            discovery_service=env.get("NF_DISCOVERY_SERVICE"),
            port=int(env.get("COORDINATOR_PORT", env.get("MASTER_PORT", "29500"))),
        )
    elif ":" not in coordinator:
        port = env.get("COORDINATOR_PORT", env.get("MASTER_PORT", "29500"))
        coordinator = f"{coordinator}:{port}"
    return DistributedConfig(num_processes, int(process_id), coordinator)


def initialize(
    config: Optional[DistributedConfig] = None,
    max_attempts: Optional[int] = None,
) -> DistributedConfig:
    """Join the multi-host job (reference ``setup_distributed``, train.py:70-82).

    No-op for single-process topologies; idempotent.

    The coordinator rendezvous is retried with bounded exponential backoff
    (graft-armor): hosts of a preempted-and-rescheduled job come up at
    different times, and the first connect to a coordinator that is not
    listening yet is a TRANSIENT failure, not a config error. Knobs:
    ``max_attempts`` (default ``$DPX_RENDEZVOUS_RETRIES`` + 1 = 4 total)
    and ``$DPX_RENDEZVOUS_BACKOFF`` (base delay seconds, default 1.0).
    """
    global _initialized
    # function-local import: robustness must stay importable before the
    # runtime package finishes initializing (no cycle at module load)
    from distributed_pytorch_example_tpu.robustness import chaos, retry

    if config is None:
        config = resolve_config()
    if _initialized:
        return config
    if max_attempts is None:
        max_attempts = int(os.environ.get("DPX_RENDEZVOUS_RETRIES", "3")) + 1

    def _join():
        # deterministic fault injection (no-op without a chaos plan); sits
        # INSIDE the retried callable so the single-process path exercises
        # the same retry loop the multi-host rendezvous uses
        chaos.transient_failure("rendezvous")
        if config.is_distributed:
            import jax

            jax.distributed.initialize(
                coordinator_address=config.coordinator_address,
                num_processes=config.num_processes,
                process_id=config.process_id,
            )
            logger.info(
                "Initialized distributed runtime: process_id=%d, "
                "num_processes=%d, coordinator=%s",
                config.process_id,
                config.num_processes,
                config.coordinator_address,
            )
        else:
            logger.info("Single-process mode (no rendezvous needed)")

    retry.with_retries(
        _join,
        attempts=max_attempts,
        base_delay=float(os.environ.get("DPX_RENDEZVOUS_BACKOFF", "1.0")),
        max_delay=30.0,
        # jax.distributed surfaces coordinator-unreachable as RuntimeError
        # (grpc DEADLINE_EXCEEDED/UNAVAILABLE) depending on version; plain
        # socket errors ride OSError/ConnectionError
        retry_on=(RuntimeError, OSError, ConnectionError),
        describe="coordinator rendezvous",
    )
    _initialized = True
    return config


def shutdown() -> None:
    """Tear down the distributed runtime (reference train.py:85-86)."""
    global _initialized
    if _initialized:
        import jax

        try:
            jax.distributed.shutdown()
        except Exception:  # single-process / already down
            pass
        _initialized = False


def process_index() -> int:
    import jax

    return jax.process_index()


def process_count() -> int:
    import jax

    return jax.process_count()


def is_coordinator() -> bool:
    """True on the host that owns rank-0 duties (checkpoint writes, logs).

    Reference analogue: ``rank == 0`` guards at train.py:253,285,314.
    """
    return process_index() == 0


def barrier(name: str = "barrier") -> None:
    """Block until every process reaches this point.

    Reference analogue: ``dist.barrier()`` (train.py:259,310). Implemented as
    a tiny blocking global collective, which is the idiomatic JAX barrier.
    """
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(name)

"""Multi-host process bootstrap and rendezvous.

TPU-native replacement for the reference's torchrun + gloo process-group setup
(reference train.py:70-86, entrypoint.sh:24-39). On TPU there is one Python
process per host; ``jax.distributed.initialize`` replaces
``dist.init_process_group`` and the c10d TCP rendezvous, and XLA's compiled
collectives over ICI/DCN replace gloo.

The topology contract is the same env-var split the reference uses (SURVEY.md
§5 "Config / flag system": flags for science, env for topology):

- ``NF_DISCOVERY_SERVICE`` — headless-service DNS suffix (entrypoint.sh:8).
- ``REPLICAS``             — number of hosts / processes (entrypoint.sh:19).
- ``COORDINATOR_PORT``     — rendezvous port (reference ``MASTER_PORT``,
  entrypoint.sh:5, default 29500).
- ``PROCESS_ID``           — this host's index; when unset it is derived from
  the hostname's numeric suffix exactly like ``NODE_RANK=${HOSTNAME##*-}``
  (entrypoint.sh:25).
- ``COORDINATOR_ADDRESS``  — full override; when unset it is derived as
  ``{base}-0.{NF_DISCOVERY_SERVICE}:{port}`` exactly like entrypoint.sh:26-28.

Single-process use requires no env vars at all (parity with the reference's
``torchrun --nnodes=1`` smoke mode, SURVEY.md §4).
"""

from __future__ import annotations

import dataclasses
import os
import socket
from typing import Optional

from distributed_pytorch_example_tpu.runtime.logging import get_logger

logger = get_logger(__name__)

_initialized = False


@dataclasses.dataclass(frozen=True)
class DistributedConfig:
    """Resolved multi-host topology."""

    num_processes: int
    process_id: int
    coordinator_address: Optional[str]  # host:port, None for single-process

    @property
    def is_distributed(self) -> bool:
        return self.num_processes > 1


def derive_process_id(hostname: Optional[str] = None) -> int:
    """Node rank from the hostname's trailing numeric suffix.

    Parity with ``NODE_RANK=${HOSTNAME##*-}`` (reference entrypoint.sh:25):
    ``worker-3`` → 3. Falls back to 0 when there is no numeric suffix.
    """
    hostname = hostname if hostname is not None else socket.gethostname()
    suffix = hostname.rsplit("-", 1)[-1]
    return int(suffix) if suffix.isdigit() else 0


def derive_coordinator_address(
    hostname: Optional[str] = None,
    discovery_service: Optional[str] = None,
    port: Optional[int] = None,
) -> str:
    """Coordinator DNS name from replica-0's stable hostname.

    Parity with ``MASTER_ADDR="${BASE_NAME}-0.${HEADLESS_SERVICE}"``
    (reference entrypoint.sh:26-28): host ``myjob-3`` with discovery service
    ``svc`` → ``myjob-0.svc:<port>``. Without a discovery service the bare
    ``{base}-0`` hostname is used (single-network setups / tests).
    """
    hostname = hostname if hostname is not None else socket.gethostname()
    if discovery_service is None:
        discovery_service = os.environ.get("NF_DISCOVERY_SERVICE")
    if port is None:
        port = int(os.environ.get("COORDINATOR_PORT", os.environ.get("MASTER_PORT", "29500")))
    base = hostname.rsplit("-", 1)[0] if "-" in hostname else hostname
    coordinator_host = f"{base}-0"
    if discovery_service:
        coordinator_host = f"{coordinator_host}.{discovery_service}"
    return f"{coordinator_host}:{port}"


def resolve_config(env: Optional[dict] = None) -> DistributedConfig:
    """Resolve topology from the environment (see module docstring)."""
    env = dict(os.environ) if env is None else env
    num_processes = int(env.get("NUM_PROCESSES", env.get("REPLICAS", "1")))
    if num_processes <= 1:
        return DistributedConfig(1, 0, None)

    process_id = env.get("PROCESS_ID", env.get("NODE_RANK"))
    if process_id is None:
        process_id = derive_process_id(env.get("HOSTNAME"))
    coordinator = env.get("COORDINATOR_ADDRESS", env.get("MASTER_ADDR"))
    if coordinator is None:
        coordinator = derive_coordinator_address(
            hostname=env.get("HOSTNAME"),
            discovery_service=env.get("NF_DISCOVERY_SERVICE"),
            port=int(env.get("COORDINATOR_PORT", env.get("MASTER_PORT", "29500"))),
        )
    elif ":" not in coordinator:
        port = env.get("COORDINATOR_PORT", env.get("MASTER_PORT", "29500"))
        coordinator = f"{coordinator}:{port}"
    return DistributedConfig(num_processes, int(process_id), coordinator)


def peer_address(config: DistributedConfig, process_id: int) -> str:
    """Host ``process_id``'s address derived from the coordinator's.

    The launch contract names hosts ``{base}-{k}`` behind one headless
    service (entrypoint.sh / reference entrypoint.sh:24-28), so peer k's
    address is the coordinator address with the replica index swapped:
    ``myjob-0.svc:29500`` → ``myjob-3.svc:29500``.
    """
    if not config.coordinator_address:
        raise ValueError("peer_address needs a distributed config")
    hostport = config.coordinator_address
    host, _, port = hostport.rpartition(":")
    name, _, domain = host.partition(".")
    base = name.rsplit("-", 1)[0] if "-" in name else name
    peer = f"{base}-{process_id}"
    if domain:
        peer = f"{peer}.{domain}"
    return f"{peer}:{port}"


def _default_probe(address: str, timeout: float = 2.0) -> bool:
    """Liveness probe for one peer address (host:port).

    A host counts as ALIVE when its kernel answers the TCP handshake —
    including ``ConnectionRefusedError``, because non-coordinator hosts
    do not listen on the rendezvous port; refused still proves the host
    exists and is reachable. DNS failure (``socket.gaierror``: a
    rescheduled-away pod loses its headless-service record), timeout,
    and unreachable-network errors count as DEAD.
    """
    host, _, port = address.rpartition(":")
    try:
        socket.create_connection((host, int(port)), timeout=timeout).close()
        return True
    except ConnectionRefusedError:
        return True
    except OSError:
        return False


def compute_survivor_config(
    config: DistributedConfig, responsive: list
) -> DistributedConfig:
    """Shrunken topology over the responsive process ids.

    Survivors are renumbered densely in original-rank order (ranks must
    be 0..n-1 for ``jax.distributed.initialize``) and the lowest
    surviving original rank becomes the coordinator. Pure function —
    unit-testable without sockets.
    """
    survivors = sorted(set(responsive) | {config.process_id})
    if config.process_id not in survivors:  # defensive; union above
        raise RuntimeError("self must be a survivor")
    new_id = survivors.index(config.process_id)
    coordinator = peer_address(config, survivors[0])
    return DistributedConfig(
        num_processes=len(survivors),
        process_id=new_id,
        coordinator_address=coordinator,
    )


def shrink_to_survivors(
    config: DistributedConfig, probe=None
) -> DistributedConfig:
    """Probe every peer and return the reduced world of responsive hosts.

    Every surviving host runs the SAME probe sweep against the same peer
    list, so they all derive the same survivor set and agree on the new
    coordinator and dense renumbering without communicating.
    """
    probe = probe if probe is not None else _default_probe
    responsive = [config.process_id]
    for k in range(config.num_processes):
        if k == config.process_id:
            continue
        address = peer_address(config, k)
        alive = probe(address)
        logger.info(
            "Elastic probe: process %d (%s) %s",
            k, address, "alive" if alive else "unresponsive",
        )
        if alive:
            responsive.append(k)
    return compute_survivor_config(config, responsive)


def _attempt_join(config: DistributedConfig, max_attempts: int) -> None:
    """One bounded-retry rendezvous against a FIXED topology."""
    from distributed_pytorch_example_tpu.robustness import chaos, retry

    def _join():
        # deterministic fault injection (no-op without a chaos plan); sits
        # INSIDE the retried callable so the single-process path exercises
        # the same retry loop the multi-host rendezvous uses
        chaos.transient_failure("rendezvous")
        if config.is_distributed:
            import jax

            jax.distributed.initialize(
                coordinator_address=config.coordinator_address,
                num_processes=config.num_processes,
                process_id=config.process_id,
            )
            logger.info(
                "Initialized distributed runtime: process_id=%d, "
                "num_processes=%d, coordinator=%s",
                config.process_id,
                config.num_processes,
                config.coordinator_address,
            )
        else:
            logger.info("Single-process mode (no rendezvous needed)")

    retry.with_retries(
        _join,
        attempts=max_attempts,
        base_delay=float(os.environ.get("DPX_RENDEZVOUS_BACKOFF", "1.0")),
        max_delay=30.0,
        # jax.distributed surfaces coordinator-unreachable as RuntimeError
        # (grpc DEADLINE_EXCEEDED/UNAVAILABLE) depending on version; plain
        # socket errors ride OSError/ConnectionError
        retry_on=(RuntimeError, OSError, ConnectionError),
        describe="coordinator rendezvous",
    )


def initialize(
    config: Optional[DistributedConfig] = None,
    max_attempts: Optional[int] = None,
    probe=None,
) -> DistributedConfig:
    """Join the multi-host job (reference ``setup_distributed``, train.py:70-82).

    No-op for single-process topologies; idempotent. Returns the config
    actually joined — callers MUST use it (not their own copy): under
    elastic mode it may describe a smaller world.

    The coordinator rendezvous is retried with bounded exponential backoff
    (graft-armor): hosts of a preempted-and-rescheduled job come up at
    different times, and the first connect to a coordinator that is not
    listening yet is a TRANSIENT failure, not a config error. Knobs:
    ``max_attempts`` (default ``$DPX_RENDEZVOUS_RETRIES`` + 1 = 4 total)
    and ``$DPX_RENDEZVOUS_BACKOFF`` (base delay seconds, default 1.0).

    Shrink-to-survivors (graft-elastic, ``DPX_ELASTIC=1``): when every
    rendezvous attempt is exhausted — the full world never assembled,
    typically because a preempted slice is gone for good — each
    surviving host probes its peers (:func:`shrink_to_survivors`),
    derives the identical reduced world, and retries the rendezvous at
    the smaller size instead of hard-failing. The caller then rebuilds
    the mesh via the normal ``make_mesh`` + ``Partitioner`` factories
    and resumes from the last intact checkpoint; the format-3 mesh
    stamp + reshard-on-load (``train/checkpoint.py``) absorb the
    topology change. Without the env gate the exhaustion error
    propagates unchanged (r10 behavior).
    """
    global _initialized
    # function-local import: robustness must stay importable before the
    # runtime package finishes initializing (no cycle at module load)
    from distributed_pytorch_example_tpu.robustness import elastic

    if config is None:
        config = resolve_config()
    if _initialized:
        return config
    if max_attempts is None:
        max_attempts = int(os.environ.get("DPX_RENDEZVOUS_RETRIES", "3")) + 1

    try:
        _attempt_join(config, max_attempts)
    except Exception as err:
        if not (elastic.elastic_enabled() and config.is_distributed):
            raise
        shrunk = shrink_to_survivors(config, probe=probe)
        if shrunk.num_processes >= config.num_processes:
            # everyone answered the probe: the failure is not a lost
            # slice (bad port, config error, ...) — shrinking would
            # deadlock the same full world at a new size
            raise
        logger.warning(
            "Rendezvous exhausted at world size %d (%s); %s=1: shrinking "
            "to %d survivor(s), new process_id=%d, coordinator=%s",
            config.num_processes, err, elastic.ELASTIC_ENV,
            shrunk.num_processes, shrunk.process_id,
            shrunk.coordinator_address,
        )
        _attempt_join(shrunk, max_attempts)
        config = shrunk
    _initialized = True
    return config


def shutdown() -> None:
    """Tear down the distributed runtime (reference train.py:85-86)."""
    global _initialized
    if _initialized:
        import jax

        try:
            jax.distributed.shutdown()
        except Exception:  # single-process / already down
            pass
        _initialized = False


def process_index() -> int:
    import jax

    return jax.process_index()


def process_count() -> int:
    import jax

    return jax.process_count()


def is_coordinator() -> bool:
    """True on the host that owns rank-0 duties (checkpoint writes, logs).

    Reference analogue: ``rank == 0`` guards at train.py:253,285,314.
    """
    return process_index() == 0


def barrier(name: str = "barrier") -> None:
    """Block until every process reaches this point.

    Reference analogue: ``dist.barrier()`` (train.py:259,310). Implemented as
    a tiny blocking global collective, which is the idiomatic JAX barrier.
    """
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(name)

"""Version-compat shims for the narrow jax-0.9 API surface this repo uses.

The framework targets the pinned ``jax==0.9.0`` (requirements.txt), but
CI/audit containers may carry an older jax (0.4.x), where the same
functionality lives under different names:

- ``jax.shard_map``            -> ``jax.experimental.shard_map.shard_map``
  (``axis_names={...}`` partial-manual selection -> the complementary
  ``auto=frozenset(...)``; the 0.4.x replication checker predates the
  custom-VJP-under-shard_map patterns used here, so it is disabled)
- ``jax.typeof``               -> ``jax.core.get_aval`` (no ``vma`` set:
  the varying-manual-axes type system does not exist in 0.4.x, so
  vma-stamping helpers degrade to no-ops, which is exactly right — there
  is nothing to stamp)

Keep this module tiny and one-directional: new code writes against the
0.9 API via these wrappers; nothing here emulates 0.4.x on 0.9.
"""

from __future__ import annotations

from typing import Optional, Set

import jax


def shard_map(f, mesh, in_specs, out_specs,
              axis_names: Optional[Set[str]] = None, **kwargs):
    """``jax.shard_map`` with 0.4.x fallback (same call shape).

    ``axis_names`` selects the manual axes (0.9 semantics); on 0.4.x the
    complement of the mesh's axis names is passed as ``auto``.
    """
    native = getattr(jax, "shard_map", None)
    if native is not None:
        if axis_names is not None:
            kwargs["axis_names"] = axis_names
        return native(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    auto = frozenset()
    if axis_names is not None:
        auto = frozenset(mesh.axis_names) - frozenset(axis_names)
    mapped = _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=False, auto=auto,
    )
    if auto:
        # 0.4.x partial-auto shard_map only lowers under jit (eager raises
        # a bare NotImplementedError); 0.9 supports eager, so match it
        mapped = jax.jit(mapped)
    return mapped


def axis_size(axis_name) -> int:
    """``jax.lax.axis_size`` with 0.4.x fallback to the axis-env lookup."""
    native = getattr(jax.lax, "axis_size", None)
    if native is not None:
        return native(axis_name)
    from jax._src import core as jcore

    return int(jcore.axis_frame(axis_name))


def typeof(x):
    """``jax.typeof`` with 0.4.x fallback to the aval (no ``vma`` attr)."""
    native = getattr(jax, "typeof", None)
    if native is not None:
        return native(x)
    return jax.core.get_aval(x)


def has_vma_types() -> bool:
    """Whether this jax has the varying-manual-axes type system."""
    return hasattr(jax, "typeof")

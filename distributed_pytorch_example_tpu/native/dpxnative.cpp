// Native backend for the host-side data path.
//
// The reference leans on PyTorch's bundled C++ runtime for its host work
// (DataLoader workers, ATen) without authoring native code (SURVEY.md §2);
// here the host-side hot paths are authored directly:
//
//  - dpx_permutation: SplitMix64-seeded Fisher-Yates, bit-identical to the
//    NumPy fallback in data/sampler.py (_permutation_numpy) so shuffles are
//    reproducible across backends, hosts, and runs.
//  - dpx_gather_rows: multi-threaded row gather (batch assembly from a
//    dataset array by index list) — parallel memcpy beats single-threaded
//    fancy-indexing for the wide rows of image datasets.
//  - dpx_resized_crop_batch: the random-resized-crop hot loop (bilinear
//    crop->resize + mirror, uint8 HWC) — bit-identical to the NumPy
//    _bilinear_resize in data/augment.py (same pixel-center sample
//    positions, same double-precision blend order, same rint), without
//    NumPy's temporaries; threaded over images.
//
// Build: make -C distributed_pytorch_example_tpu/native
// ABI: plain C, loaded via ctypes (no pybind11 in this image).

#include <cmath>
#include <cstdint>
#include <cstring>
#include <thread>
#include <utility>
#include <vector>

namespace {

inline uint64_t splitmix_scramble(uint64_t x) {
  uint64_t z = x;
  z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
  z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
  return z ^ (z >> 31);
}

}  // namespace

extern "C" {

// Fisher-Yates with one SplitMix64 draw per position, descending swaps.
// Draw for position i (i = n-1 .. 1) is scramble(seed + i * GOLDEN), taken
// mod (i+1) — exactly _permutation_numpy in data/sampler.py.
void dpx_permutation(int64_t n, uint64_t seed, int64_t* out) {
  for (int64_t i = 0; i < n; ++i) out[i] = i;
  constexpr uint64_t kGolden = 0x9E3779B97F4A7C15ULL;
  for (int64_t i = n - 1; i >= 1; --i) {
    uint64_t x = seed + static_cast<uint64_t>(i) * kGolden;
    uint64_t j = splitmix_scramble(x) % static_cast<uint64_t>(i + 1);
    std::swap(out[i], out[static_cast<int64_t>(j)]);
  }
}

// Gather rows: dst[r] = src[idx[r]] for r in [0, n_rows), row_bytes each.
// Threaded over contiguous destination ranges.
void dpx_gather_rows(const char* src, const int64_t* idx, char* dst,
                     int64_t n_rows, int64_t row_bytes, int32_t n_threads) {
  auto copy_range = [=](int64_t lo, int64_t hi) {
    for (int64_t r = lo; r < hi; ++r) {
      std::memcpy(dst + r * row_bytes, src + idx[r] * row_bytes,
                  static_cast<size_t>(row_bytes));
    }
  };
  if (n_threads <= 1 || n_rows < 2 * n_threads) {
    copy_range(0, n_rows);
    return;
  }
  std::vector<std::thread> workers;
  workers.reserve(static_cast<size_t>(n_threads));
  int64_t chunk = (n_rows + n_threads - 1) / n_threads;
  for (int32_t t = 0; t < n_threads; ++t) {
    int64_t lo = t * chunk;
    int64_t hi = lo + chunk < n_rows ? lo + chunk : n_rows;
    if (lo >= hi) break;
    workers.emplace_back(copy_range, lo, hi);
  }
  for (auto& w : workers) w.join();
}

// One bilinear crop->resize, mirroring data/augment.py::_bilinear_resize:
// output center i samples input (i + 0.5) * extent/size - 0.5, edges
// clamped ("nearest"); blends in double (NumPy's f32-array x f64-scalar
// promotion), rows first then columns; round-half-to-even + clamp to u8.
static void resized_crop_one(const uint8_t* img, int64_t w, int64_t c,
                             int64_t oy, int64_t ox, int64_t ch, int64_t cw,
                             uint8_t* out, int64_t size, bool mirror) {
  std::vector<int64_t> y0(size), y1(size), x0(size), x1(size);
  std::vector<double> wy(size), wx(size);
  for (int64_t i = 0; i < size; ++i) {
    double ys = (i + 0.5) * (static_cast<double>(ch) / size) - 0.5;
    double xs = (i + 0.5) * (static_cast<double>(cw) / size) - 0.5;
    double yf = std::floor(ys), xf = std::floor(xs);
    wy[i] = ys - yf;
    wx[i] = xs - xf;
    int64_t yi = static_cast<int64_t>(yf), xi = static_cast<int64_t>(xf);
    y0[i] = yi < 0 ? 0 : (yi > ch - 1 ? ch - 1 : yi);
    y1[i] = yi + 1 < 0 ? 0 : (yi + 1 > ch - 1 ? ch - 1 : yi + 1);
    x0[i] = xi < 0 ? 0 : (xi > cw - 1 ? cw - 1 : xi);
    x1[i] = xi + 1 < 0 ? 0 : (xi + 1 > cw - 1 ? cw - 1 : xi + 1);
  }
  const int64_t row = w * c;
  for (int64_t i = 0; i < size; ++i) {
    const uint8_t* r0 = img + (oy + y0[i]) * row + ox * c;
    const uint8_t* r1 = img + (oy + y1[i]) * row + ox * c;
    const double vy = wy[i];
    uint8_t* orow = out + i * size * c;
    for (int64_t j = 0; j < size; ++j) {
      int64_t oj = mirror ? size - 1 - j : j;
      const double vx = wx[j];
      for (int64_t k = 0; k < c; ++k) {
        double a = r0[x0[j] * c + k] * (1.0 - vy) + r1[x0[j] * c + k] * vy;
        double b = r0[x1[j] * c + k] * (1.0 - vy) + r1[x1[j] * c + k] * vy;
        double v = a * (1.0 - vx) + b * vx;
        double r = std::nearbyint(v);  // ties-to-even, like np.rint
        orow[oj * c + k] =
            static_cast<uint8_t>(r < 0.0 ? 0.0 : (r > 255.0 ? 255.0 : r));
      }
    }
  }
}

// Batch random-resized-crop: imgs (b, h, w, c) u8; crops (b, 4) as
// (oy, ox, ch, cw); mirror (b,) 0/1; out (b, size, size, c) u8.
void dpx_resized_crop_batch(const uint8_t* imgs, int64_t b, int64_t h,
                            int64_t w, int64_t c, const int64_t* crops,
                            const uint8_t* mirror, uint8_t* out,
                            int64_t size, int32_t n_threads) {
  auto run_range = [=](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) {
      const int64_t* cr = crops + i * 4;
      resized_crop_one(imgs + i * h * w * c, w, c, cr[0], cr[1], cr[2],
                       cr[3], out + i * size * size * c, size,
                       mirror[i] != 0);
    }
  };
  if (n_threads <= 1 || b < 2 * n_threads) {
    run_range(0, b);
    return;
  }
  std::vector<std::thread> workers;
  workers.reserve(static_cast<size_t>(n_threads));
  int64_t chunk = (b + n_threads - 1) / n_threads;
  for (int32_t t = 0; t < n_threads; ++t) {
    int64_t lo = t * chunk;
    int64_t hi = lo + chunk < b ? lo + chunk : b;
    if (lo >= hi) break;
    workers.emplace_back(run_range, lo, hi);
  }
  for (auto& wk : workers) wk.join();
}

}  // extern "C"

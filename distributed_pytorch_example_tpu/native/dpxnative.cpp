// Native backend for the host-side data path.
//
// The reference leans on PyTorch's bundled C++ runtime for its host work
// (DataLoader workers, ATen) without authoring native code (SURVEY.md §2);
// here the host-side hot paths are authored directly:
//
//  - dpx_permutation: SplitMix64-seeded Fisher-Yates, bit-identical to the
//    NumPy fallback in data/sampler.py (_permutation_numpy) so shuffles are
//    reproducible across backends, hosts, and runs.
//  - dpx_gather_rows: multi-threaded row gather (batch assembly from a
//    dataset array by index list) — parallel memcpy beats single-threaded
//    fancy-indexing for the wide rows of image datasets.
//
// Build: make -C distributed_pytorch_example_tpu/native
// ABI: plain C, loaded via ctypes (no pybind11 in this image).

#include <cstdint>
#include <cstring>
#include <thread>
#include <utility>
#include <vector>

namespace {

inline uint64_t splitmix_scramble(uint64_t x) {
  uint64_t z = x;
  z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
  z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
  return z ^ (z >> 31);
}

}  // namespace

extern "C" {

// Fisher-Yates with one SplitMix64 draw per position, descending swaps.
// Draw for position i (i = n-1 .. 1) is scramble(seed + i * GOLDEN), taken
// mod (i+1) — exactly _permutation_numpy in data/sampler.py.
void dpx_permutation(int64_t n, uint64_t seed, int64_t* out) {
  for (int64_t i = 0; i < n; ++i) out[i] = i;
  constexpr uint64_t kGolden = 0x9E3779B97F4A7C15ULL;
  for (int64_t i = n - 1; i >= 1; --i) {
    uint64_t x = seed + static_cast<uint64_t>(i) * kGolden;
    uint64_t j = splitmix_scramble(x) % static_cast<uint64_t>(i + 1);
    std::swap(out[i], out[static_cast<int64_t>(j)]);
  }
}

// Gather rows: dst[r] = src[idx[r]] for r in [0, n_rows), row_bytes each.
// Threaded over contiguous destination ranges.
void dpx_gather_rows(const char* src, const int64_t* idx, char* dst,
                     int64_t n_rows, int64_t row_bytes, int32_t n_threads) {
  auto copy_range = [=](int64_t lo, int64_t hi) {
    for (int64_t r = lo; r < hi; ++r) {
      std::memcpy(dst + r * row_bytes, src + idx[r] * row_bytes,
                  static_cast<size_t>(row_bytes));
    }
  };
  if (n_threads <= 1 || n_rows < 2 * n_threads) {
    copy_range(0, n_rows);
    return;
  }
  std::vector<std::thread> workers;
  workers.reserve(static_cast<size_t>(n_threads));
  int64_t chunk = (n_rows + n_threads - 1) / n_threads;
  for (int32_t t = 0; t < n_threads; ++t) {
    int64_t lo = t * chunk;
    int64_t hi = lo + chunk < n_rows ? lo + chunk : n_rows;
    if (lo >= hi) break;
    workers.emplace_back(copy_range, lo, hi);
  }
  for (auto& w : workers) w.join();
}

}  // extern "C"

"""ctypes bindings for the native host-path backend (libdpxnative.so).

Auto-builds the shared library on first import when a toolchain is present
(g++ is part of the image; pybind11 is not, hence ctypes). Import fails
cleanly when neither the library nor a compiler exists — callers
(data/sampler.py, data/synthetic.py) fall back to bit-identical NumPy.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "libdpxnative.so")
_SRC = os.path.join(_DIR, "dpxnative.cpp")
_build_lock = threading.Lock()


def _build() -> None:
    """Compile via the Makefile (single source of truth for flags) to a
    temp name, then atomically rename — concurrent builders each produce a
    complete .so and the loser's rename just re-installs identical bits."""
    tmp = f"{_SO}.build.{os.getpid()}"
    try:
        subprocess.run(
            ["make", "-C", _DIR, f"SO={os.path.basename(tmp)}"],
            check=True,
            capture_output=True,
        )
        os.replace(tmp, _SO)
    except (OSError, subprocess.CalledProcessError) as e:
        # optional component: surface as ImportError so callers (and
        # pytest.importorskip) treat "no toolchain" as absence, not a crash
        raise ImportError(f"native build failed: {e}") from e
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def _load() -> ctypes.CDLL:
    with _build_lock:
        if not os.path.exists(_SO) or (
            os.path.exists(_SRC)
            and os.path.getmtime(_SRC) > os.path.getmtime(_SO)
        ):
            _build()
        lib = ctypes.CDLL(_SO)
    lib.dpx_permutation.argtypes = [
        ctypes.c_int64, ctypes.c_uint64, ctypes.POINTER(ctypes.c_int64)
    ]
    lib.dpx_permutation.restype = None
    lib.dpx_gather_rows.argtypes = [
        ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64), ctypes.c_char_p,
        ctypes.c_int64, ctypes.c_int64, ctypes.c_int32,
    ]
    lib.dpx_gather_rows.restype = None
    lib.dpx_resized_crop_batch.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_int64, ctypes.POINTER(ctypes.c_int64), ctypes.c_char_p,
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_int32,
    ]
    lib.dpx_resized_crop_batch.restype = None
    return lib


_lib = _load()


def permutation(n: int, seed: int) -> np.ndarray:
    """Deterministic permutation of [0, n) — bit-identical to the NumPy path."""
    out = np.empty(n, dtype=np.int64)
    _lib.dpx_permutation(
        n,
        ctypes.c_uint64(seed & 0xFFFFFFFFFFFFFFFF),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
    )
    return out


def resized_crop_batch(
    images: np.ndarray,
    crops: np.ndarray,
    mirror: np.ndarray,
    size: int,
    n_threads: int = 1,
) -> np.ndarray:
    """Batched bilinear crop->resize(+mirror), uint8 NHWC.

    Bit-identical to data/augment.py::_bilinear_resize followed by the
    horizontal flip (pinned in tests/test_native.py): same pixel-center
    sampling, double-precision blends, ties-to-even rounding.

    Args:
      images: (B, H, W, C) uint8.
      crops: (B, 4) int64 rows (oy, ox, crop_h, crop_w); each crop must
        lie inside the image and be at least 1x1.
      mirror: (B,) bool/uint8 — flip the OUTPUT horizontally.
      size: square output extent.
    """
    if images.dtype != np.uint8 or images.ndim != 4:
        raise ValueError(f"images must be (B,H,W,C) uint8, got "
                         f"{images.shape} {images.dtype}")
    b, h, w, c = images.shape
    cr = np.ascontiguousarray(crops, dtype=np.int64)
    if cr.shape != (b, 4):
        raise ValueError(f"crops must be ({b}, 4), got {cr.shape}")
    oy, ox, ch, cw = cr[:, 0], cr[:, 1], cr[:, 2], cr[:, 3]
    if (
        (ch < 1).any() or (cw < 1).any() or (oy < 0).any() or (ox < 0).any()
        or (oy + ch > h).any() or (ox + cw > w).any()
    ):
        raise ValueError("crop rectangles must lie inside the image")
    if not images.flags.c_contiguous:
        images = np.ascontiguousarray(images)
    mir = np.ascontiguousarray(mirror, dtype=np.uint8)
    if mir.shape != (b,):
        raise ValueError(f"mirror must be ({b},), got {mir.shape}")
    out = np.empty((b, size, size, c), np.uint8)
    _lib.dpx_resized_crop_batch(
        images.ctypes.data_as(ctypes.c_char_p),
        b, h, w, c,
        cr.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        mir.ctypes.data_as(ctypes.c_char_p),
        out.ctypes.data_as(ctypes.c_char_p),
        size,
        n_threads,
    )
    return out


def gather_rows(
    src: np.ndarray, indices: np.ndarray, n_threads: int = 4
) -> np.ndarray:
    """dst[r] = src[indices[r]]: threaded batch assembly for wide rows.

    NumPy-compatible indexing: negatives wrap, out-of-range raises — the
    C++ side does raw memcpy and must never see a bad index.
    """
    if not src.flags.c_contiguous:
        src = np.ascontiguousarray(src)
    idx = np.ascontiguousarray(indices, dtype=np.int64)
    n = src.shape[0]
    if idx.size and (idx.min() < -n or idx.max() >= n):
        bad = idx[(idx < -n) | (idx >= n)][0]
        raise IndexError(
            f"index {bad} is out of bounds for axis 0 with size {n}"
        )
    if idx.size and idx.min() < 0:
        idx = np.where(idx < 0, idx + n, idx)
    out = np.empty((len(idx),) + src.shape[1:], dtype=src.dtype)
    row_bytes = src.dtype.itemsize * int(np.prod(src.shape[1:], dtype=np.int64))
    _lib.dpx_gather_rows(
        src.ctypes.data_as(ctypes.c_char_p),
        idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        out.ctypes.data_as(ctypes.c_char_p),
        len(idx),
        row_bytes,
        n_threads,
    )
    return out

"""Native (C++) components, bound via ctypes with pure-Python fallbacks.

Build with ``make -C distributed_pytorch_example_tpu/native`` (binding.py
also auto-builds on first import when g++ is present). Nothing in the
framework *requires* the native build — every binding has a bit-identical
Python fallback — mirroring how the reference leans on PyTorch's bundled
native runtime without authoring native code itself (SURVEY.md §2).
"""

from __future__ import annotations

_binding = None
_checked = False


def get_binding():
    """The loaded native binding module, or None when unavailable.

    One shared probe (build-once, cache-forever) for every native call site
    — data/sampler.py and data/synthetic.py dispatch through this.
    """
    global _binding, _checked
    if not _checked:
        _checked = True
        try:
            from distributed_pytorch_example_tpu.native import binding

            _binding = binding
        except Exception:
            _binding = None
    return _binding

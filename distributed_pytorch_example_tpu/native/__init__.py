"""Native (C++) components, bound via ctypes with pure-Python fallbacks.

Build with ``make -C distributed_pytorch_example_tpu/native``. Nothing in the
framework *requires* the native build — every binding has a bit-identical
Python fallback — mirroring how the reference leans on PyTorch's bundled
native runtime without authoring native code itself (SURVEY.md §2).
"""

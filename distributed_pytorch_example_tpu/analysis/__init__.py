"""graft-lint: static sharding/collective/numerics auditing.

Three layers, none of which executes a train step:

- :mod:`.collectives` — lower + compile the jitted train step per dryrun
  mesh config, parse the collectives (kind/count/bytes) out of the
  compiled HLO, and gate them against committed budgets
  (``analysis/comm_budgets.json``).
- :mod:`.shardlint` — walk the step's jaxpr and committed placements:
  large replicated params the partition rules would shard, off-allowlist
  bf16→f32 promotions, and donated arguments the executable silently
  failed to alias.
- :mod:`.pylint_rules` — repo-specific AST lints over the package
  sources (host syncs in traced scope, trace-time mesh-size layout
  guesses, mutable default args in public APIs).

This package intentionally does NOT import jax at import time:
:mod:`.pylint_rules` and the budget comparison are usable without a
backend (the jax-heavy entry points import lazily). The CLI wrapper is
``scripts/graft_lint.py``; the pytest gate is ``tests/test_graft_lint.py``.
"""

from distributed_pytorch_example_tpu.analysis.findings import Finding

__all__ = ["Finding"]

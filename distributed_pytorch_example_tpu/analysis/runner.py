"""graft-lint orchestration: AST + jaxpr + collective audits in one pass.

Glues the three analysis layers to the dryrun mesh-config table
(``__graft_entry__.DRYRUN_CONFIGS``) and the committed budgets:

- AST lints (``pylint_rules``) run first — no jax, milliseconds;
- numerics lints (``shardlint.lint_dtype_promotions``) trace the bf16
  flagship-shaped step once;
- per-config audits lower+compile each requested mesh config on the fake
  CPU mesh (never executing a step) and check collective budgets,
  dropped donations, and large replicated params.

Configs the toolchain cannot compile produce ``{"error": ...}`` records:
the committed budget file documents the gap (e.g. jax 0.4.x cannot
compile partial-auto ``shard_map`` pipelines — ``axis_index`` lowers to
a PartitionId op its SPMD partitioner rejects), and an error matching the
committed error is a note, not a violation. Budget comparisons degrade to
warnings entirely when the runtime jax differs from the budget file's
``_meta.jax`` (collective counts are only stable within one toolchain).
"""

from __future__ import annotations

import sys
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from distributed_pytorch_example_tpu.analysis import collectives as coll
from distributed_pytorch_example_tpu.analysis import congruence as cong_mod
from distributed_pytorch_example_tpu.analysis import envelope as env_mod
from distributed_pytorch_example_tpu.analysis import pylint_rules
from distributed_pytorch_example_tpu.analysis import shardflow
from distributed_pytorch_example_tpu.analysis import shardlint
from distributed_pytorch_example_tpu.analysis.findings import Finding


@dataclass
class AuditResult:
    violations: List[Finding] = field(default_factory=list)
    notes: List[str] = field(default_factory=list)
    records: Dict[str, Dict[str, object]] = field(default_factory=dict)
    # graft-prove static layers, keyed like records (not budget-serialized)
    flows: Dict[str, object] = field(default_factory=dict)
    envelope_records: Dict[str, Dict[str, object]] = field(
        default_factory=dict
    )
    configs_audited: int = 0
    configs_errored: int = 0

    @property
    def ok(self) -> bool:
        return not self.violations

    def rule_counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for f in self.violations:
            out[f.rule] = out.get(f.rule, 0) + 1
        return out


def error_record(exc: BaseException) -> Dict[str, object]:
    first = str(exc).splitlines()[0] if str(exc) else ""
    return {"error": f"{type(exc).__name__}: {first[:200]}"}


def _resolve_configs(names: Optional[Sequence[str]]):
    import __graft_entry__ as entry

    table = {
        entry.dryrun_config_name(c): c for c in entry.DRYRUN_CONFIGS
    }
    if names is None:
        return list(table.items())
    missing = [n for n in names if n not in table]
    if missing:
        raise SystemExit(
            f"unknown config(s) {missing}; known: {sorted(table)}"
        )
    return [(n, table[n]) for n in names]


def _case_jaxpr_specs(case):
    """(closed_jaxpr, in_specs, mesh_shape) of a case's train step —
    trace-only, so this works even for configs XLA cannot partition."""
    import jax

    trainer = case.trainer
    if trainer.state is None:
        with case.mesh:
            trainer.init(next(iter(case.loader))["tokens"])
    batch = next(iter(case.loader))
    with case.mesh:
        jaxpr = jax.make_jaxpr(
            lambda s, b: trainer.train_step(s, b)
        )(trainer.state, batch)
    specs = shardflow.committed_in_specs((trainer.state, batch))
    mesh_shape = {str(k): int(v) for k, v in dict(case.mesh.shape).items()}
    return jaxpr, specs, mesh_shape


def _audit_static(
    result: AuditResult,
    name: str,
    jaxpr,
    in_specs,
    mesh_shape: Dict[str, int],
    case_mesh,
    envelopes: Optional[Dict[str, object]],
    env_skew: Optional[str],
    hbm_limit: Optional[int],
    log,
) -> Optional[object]:
    """The trace-only graft-prove layers for one program: shardflow +
    congruence + the would-OOM pre-gate. Returns the FlowReport (None if
    the would-OOM gate refused the config — the caller must then skip
    the compile)."""
    flow = shardflow.trace_shardings(jaxpr, in_specs, mesh_shape)
    result.flows[name] = flow
    kinds = flow.attributed_kinds()
    log(f"graft_prove: {name} shardflow eqns={flow.eqns} "
        f"comm_events={len(flow.comm_events())} kinds={kinds} "
        f"lost={flow.lost} predicted_peak={flow.peak_bytes}B")

    cong = cong_mod.check_congruence(jaxpr)
    for f in cong.findings:
        if f.hazard:
            result.violations.append(Finding(
                rule="spmd-hang", where=f"{name}:{f.path or f.source}",
                message=f.render(), config=name,
            ))
        else:
            result.notes.append(f"{name}: {f.render()}")

    committed_env = (envelopes or {}).get("configs", {}).get(name)
    if committed_env is not None:
        for v in env_mod.compare_envelope(
            name, committed_env, flow.peak_bytes, None
        ):
            if env_skew is not None:
                result.notes.append(f"(skew-demoted) {v.render()}")
            else:
                result.violations.append(Finding(
                    rule=v.rule, where=name, message=v.detail, config=name,
                ))

    gate = env_mod.gate_envelope(name, flow.peak_bytes, hbm_limit)
    if gate is not None:
        result.violations.append(Finding(
            rule=gate.rule, where=name, message=gate.detail, config=name,
        ))
        return None
    return flow


def _check_envelope_measured(
    result: AuditResult,
    name: str,
    flow,
    measured: Optional[int],
    envelopes: Optional[Dict[str, object]],
    env_skew: Optional[str],
) -> None:
    """The measured half of envelope cross-validation (ratio band)."""
    if flow is None or not measured:
        return
    for v in env_mod.compare_envelope(name, {}, flow.peak_bytes, measured):
        if env_skew is not None:
            result.notes.append(f"(skew-demoted) {v.render()}")
        else:
            result.violations.append(Finding(
                rule=v.rule, where=name, message=v.detail, config=name,
            ))


def audit_configs(
    config_names: Optional[Sequence[str]] = None,
    budgets: Optional[Dict[str, object]] = None,
    envelopes: Optional[Dict[str, object]] = None,
    n_devices: int = 8,
    byte_tolerance: float = coll.DEFAULT_BYTE_TOLERANCE,
    check_placement: bool = True,
    check_flow: bool = True,
    hbm_limit: Optional[int] = None,
    log=lambda msg: print(msg, file=sys.stderr),
) -> AuditResult:
    """Compile each config and audit collectives / donation / placement,
    preceded by the trace-only graft-prove layers (shardflow sharding
    propagation, congruence hang check, static HBM envelope).

    With ``budgets=None`` no budget comparison happens (measure-only —
    the ``--update-budgets`` path); otherwise each measured record is
    gated against ``budgets["configs"][name]``. Same for ``envelopes``.
    The static layers run BEFORE any compile, so they cover the configs
    this toolchain cannot partition, and the would-OOM envelope gate can
    refuse a config without paying for its compile.
    """
    import __graft_entry__ as entry

    entry._ensure_cpu_devices(n_devices)
    import jax

    from distributed_pytorch_example_tpu.telemetry import cost

    devices = jax.devices()[:n_devices]
    result = AuditResult()
    skew = coll.jax_version_skew(budgets) if budgets else None
    if skew is not None:
        result.notes.append(
            f"budgets were generated under jax {skew}, runtime is "
            f"{jax.__version__}: budget comparisons degraded to warnings"
        )
    env_skew = coll.jax_version_skew(envelopes) if envelopes else None
    if env_skew is not None:
        result.notes.append(
            f"envelopes were generated under jax {env_skew}, runtime is "
            f"{jax.__version__}: envelope comparisons degraded to warnings"
        )
    committed_configs = (budgets or {}).get("configs", {})

    for name, config in _resolve_configs(config_names):
        case = entry.build_dryrun_case(config, devices)
        if isinstance(case, str):
            result.records[name] = {"skip": case}
            result.notes.append(f"{name}: skipped ({case})")
            continue

        flow = None
        if check_flow:
            try:
                jaxpr, in_specs, mesh_shape = _case_jaxpr_specs(case)
            except Exception as e:
                result.notes.append(
                    f"{name}: static trace failed "
                    f"({type(e).__name__}: {str(e)[:120]})"
                )
            else:
                flow = _audit_static(
                    result, name, jaxpr, in_specs, mesh_shape, case.mesh,
                    envelopes, env_skew, hbm_limit, log,
                )
                if flow is None:  # would-OOM: refuse before compiling
                    result.records[name] = {
                        "skip": "would-oom (static envelope gate)"
                    }
                    continue
                result.envelope_records[name] = env_mod.envelope_record(
                    case, flow, None
                )

        try:
            lowered, compiled = coll.compile_case(case)
            record = coll.collective_record(case, compiled)
        except Exception as e:  # compile failures become budget records
            record = error_record(e)
            result.records[name] = record
            result.configs_errored += 1
            committed = committed_configs.get(name)
            if budgets is None or (
                committed is not None and "error" in committed
            ):
                result.notes.append(
                    f"{name}: does not compile here ({record['error']})"
                )
            elif skew is not None:
                result.notes.append(
                    f"{name}: compile error under skewed jax "
                    f"({record['error']})"
                )
            else:
                result.violations.append(Finding(
                    rule="comm-compile-error", where=name,
                    message=record["error"], config=name,
                ))
            continue
        result.records[name] = record
        result.configs_audited += 1
        log(f"graft_lint: {name} compiled; "
            f"collectives={record['collectives']}")

        measured = cost.measured_hbm_peak(compiled)
        if flow is not None:
            result.envelope_records[name] = env_mod.envelope_record(
                case, flow, measured
            )
            _check_envelope_measured(
                result, name, flow, measured, envelopes, env_skew
            )

        if budgets is not None:
            committed = committed_configs.get(name)
            if committed is None:
                result.violations.append(Finding(
                    rule="comm-budget-missing", where=name,
                    message="no committed budget for this config; run "
                            "scripts/graft_lint.py --update-budgets",
                    config=name,
                ))
            elif "error" in committed:
                result.notes.append(
                    f"{name}: compiles now but budget records an error — "
                    f"refresh budgets to ratchet the gain in"
                )
            else:
                v, n = coll.compare_budgets(
                    committed["collectives"], record["collectives"],
                    byte_tolerance=byte_tolerance, config=name,
                    signature=committed.get(
                        "signature", record.get("signature")
                    ),
                    markers=record.get("markers"),
                    # measured values, not the committed ones: the
                    # wire-int8-step signature must fail when THIS
                    # compile lost the s8 payload or the >=3x ratio
                    dtypes=record.get("dtypes"),
                    wire=record.get("wire"),
                )
                if skew is not None:
                    result.notes.extend(
                        f"(skew-demoted) {f.render()}" for f in v
                    )
                else:
                    result.violations.extend(v)
                result.notes.extend(n)

        if check_placement:
            result.violations.extend(shardlint.lint_dropped_donation(
                lowered, compiled, config=name
            ))
            result.violations.extend(shardlint.lint_replicated_params(
                case.trainer.state.params, case.trainer.partitioner,
                config=name,
            ))
            # the same rule over the optimizer tree: the ZeRO-1 overlay
            # (parallel/api.py) only engages on opt_state/... paths, so a
            # large replicated Adam moment the overlay would dp-shard is
            # a violation too (satellite of graft-prove; regression for
            # the overlay's min-size floor lives in test_graft_lint.py)
            result.violations.extend(shardlint.lint_replicated_params(
                case.trainer.state.opt_state, case.trainer.partitioner,
                config=name, path_prefix="opt_state",
            ))
    return result


def audit_serve(
    budgets: Optional[Dict[str, object]] = None,
    envelopes: Optional[Dict[str, object]] = None,
    n_devices: int = 8,
    byte_tolerance: float = coll.DEFAULT_BYTE_TOLERANCE,
    check_flow: bool = True,
    hbm_limit: Optional[int] = None,
    log=lambda msg: print(msg, file=sys.stderr),
) -> AuditResult:
    """Budget/envelope audit of the serving engine's two programs.

    Bucketed prefill and slot decode become first-class entries
    (``serve/prefill``, ``serve/decode``) gated exactly like train
    configs: collective budgets off the compiled HLO, shardflow +
    congruence + envelopes off the traced jaxprs.
    """
    import __graft_entry__ as entry

    entry._ensure_cpu_devices(n_devices)
    import jax

    from distributed_pytorch_example_tpu.telemetry import cost

    devices = jax.devices()[:n_devices]
    result = AuditResult()
    skew = coll.jax_version_skew(budgets) if budgets else None
    env_skew = coll.jax_version_skew(envelopes) if envelopes else None
    committed_configs = (budgets or {}).get("configs", {})

    case = entry.build_serve_case(devices)
    if isinstance(case, str):
        result.notes.append(f"serve: skipped ({case})")
        return result
    mesh_shape = {str(k): int(v) for k, v in dict(case.mesh.shape).items()}

    flows: Dict[str, object] = {}
    if check_flow:
        for name, (jaxpr, in_specs) in case.engine.traced_programs().items():
            flow = _audit_static(
                result, name, jaxpr, in_specs, mesh_shape, case.mesh,
                envelopes, env_skew, hbm_limit, log,
            )
            if flow is not None:
                flows[name] = flow
                result.envelope_records[name] = env_mod.envelope_record(
                    case, flow, None
                )

    for name, lowered in case.engine.lowered_programs().items():
        try:
            compiled = lowered.compile()
        except Exception as e:
            record = error_record(e)
            result.records[name] = record
            result.configs_errored += 1
            result.notes.append(
                f"{name}: does not compile here ({record['error']})"
            )
            continue
        text = compiled.as_text()
        record = {
            "mesh": {k: int(v) for k, v in dict(case.mesh.shape).items()},
            "collectives": coll.parse_collectives(text),
        }
        if name == "serve/decode":
            # structural contract: decode attention must go through the
            # fused paged dispatch (its named scope survives into the
            # compiled module) — a silent fall-back to gathering the
            # whole cache moves no collective bytes, only this signature
            record["signature"] = "paged-decode-fused"
        markers = coll.parse_markers(text)
        if any(markers.values()):
            record["markers"] = markers
        result.records[name] = record
        result.configs_audited += 1
        log(f"graft_lint: {name} compiled; "
            f"collectives={record['collectives']}")

        measured = cost.measured_hbm_peak(compiled)
        flow = flows.get(name)
        if flow is not None:
            result.envelope_records[name] = env_mod.envelope_record(
                case, flow, measured
            )
            _check_envelope_measured(
                result, name, flow, measured, envelopes, env_skew
            )

        if budgets is not None:
            committed = committed_configs.get(name)
            if committed is None:
                result.violations.append(Finding(
                    rule="comm-budget-missing", where=name,
                    message="no committed budget for this serve program; "
                            "run scripts/graft_lint.py --update-budgets",
                    config=name,
                ))
            elif "error" not in committed:
                v, n = coll.compare_budgets(
                    committed["collectives"], record["collectives"],
                    byte_tolerance=byte_tolerance, config=name,
                    signature=committed.get(
                        "signature", record.get("signature")
                    ),
                    markers=record.get("markers"),
                )
                if skew is not None:
                    result.notes.extend(
                        f"(skew-demoted) {f.render()}" for f in v
                    )
                else:
                    result.violations.extend(v)
                result.notes.extend(n)
    return result


def audit_numerics() -> List[Finding]:
    """bf16-upcast lint over the flagship-shaped bf16 train step."""
    jaxpr = shardlint.flagship_numerics_jaxpr()
    return shardlint.lint_dtype_promotions(jaxpr)


def _merge(result: AuditResult, sub: AuditResult) -> None:
    result.violations.extend(sub.violations)
    result.notes.extend(sub.notes)
    result.records.update(sub.records)
    result.flows.update(sub.flows)
    result.envelope_records.update(sub.envelope_records)
    result.configs_audited += sub.configs_audited
    result.configs_errored += sub.configs_errored


def run_audit(
    config_names: Optional[Sequence[str]] = None,
    budgets_path: str = coll.DEFAULT_BUDGETS_PATH,
    envelopes_path: str = env_mod.DEFAULT_ENVELOPES_PATH,
    write_budgets: bool = False,
    write_envelopes: bool = False,
    n_devices: int = 8,
    with_collectives: bool = True,
    with_numerics: bool = True,
    with_ast: bool = True,
    with_serve: bool = True,
    with_flow: bool = True,
    hbm_limit: Optional[int] = None,
    log=lambda msg: print(msg, file=sys.stderr),
) -> AuditResult:
    """The full graft-lint pass (the CLI and pytest wrapper entry point)."""
    result = AuditResult()

    if with_ast:
        result.violations.extend(pylint_rules.lint_package())

    if with_numerics or with_collectives:
        import __graft_entry__ as entry

        entry._ensure_cpu_devices(n_devices)

    if with_numerics:
        result.violations.extend(audit_numerics())

    if with_collectives:
        budgets = None
        if not write_budgets:
            try:
                budgets = coll.load_budgets(budgets_path)
            except FileNotFoundError:
                result.notes.append(
                    f"no committed budgets at {budgets_path}; "
                    f"measuring without a gate (--update-budgets to commit)"
                )
        envelopes = None
        if with_flow and not write_envelopes:
            envelopes = env_mod.load_envelopes(envelopes_path)
            if envelopes is None:
                result.notes.append(
                    f"no committed envelopes at {envelopes_path}; "
                    f"measuring without a gate (--update-envelopes to "
                    f"commit)"
                )
        _merge(result, audit_configs(
            config_names, budgets=budgets, envelopes=envelopes,
            n_devices=n_devices, check_flow=with_flow,
            hbm_limit=hbm_limit, log=log,
        ))
        if with_serve and config_names is None:
            _merge(result, audit_serve(
                budgets=budgets, envelopes=envelopes, n_devices=n_devices,
                check_flow=with_flow, hbm_limit=hbm_limit, log=log,
            ))
        if write_budgets:
            coll.write_budgets(budgets_path, result.records, n_devices)
            result.notes.append(f"wrote budgets to {budgets_path}")
        if write_envelopes and result.envelope_records:
            env_mod.write_envelopes(
                envelopes_path, result.envelope_records, n_devices
            )
            result.notes.append(f"wrote envelopes to {envelopes_path}")

    stale = coll.budget_staleness(budgets_path)
    if stale and not write_budgets:
        result.notes.append(stale)
    return result


def diff_audit(
    rev: str,
    config_names: Optional[Sequence[str]] = None,
    budgets_path: str = coll.DEFAULT_BUDGETS_PATH,
    n_devices: int = 8,
    top: int = 5,
    log=lambda msg: print(msg, file=sys.stderr),
) -> Dict[str, object]:
    """Differential audit: measure the working tree, diff against the
    budget file committed at ``rev``, and attribute each collective
    count/byte delta to named ops via the shardflow report.

    The old side is read straight out of git (``git show
    rev:analysis/comm_budgets.json``) — no checkout, no second compile.
    For every (config, collective-kind) whose count or bytes moved, the
    current flow report's events of that kind are listed largest-first:
    the op, its flax module/param path, and its source line. That list is
    the answer to "which op grew the bytes" that a config-level budget
    delta cannot give.
    """
    import json
    import os
    import subprocess

    repo_root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    rel = os.path.relpath(budgets_path, repo_root)
    old_raw = subprocess.run(
        ["git", "show", f"{rev}:{rel}"],
        cwd=repo_root, capture_output=True, text=True,
    )
    if old_raw.returncode != 0:
        raise SystemExit(
            f"cannot read {rel} at {rev}: {old_raw.stderr.strip()}"
        )
    old = json.loads(old_raw.stdout)
    old_configs = old.get("configs", {})

    current = audit_configs(
        config_names, budgets=None, envelopes=None,
        n_devices=n_devices, check_flow=True, log=log,
    )

    diff: Dict[str, object] = {}
    for name, record in sorted(current.records.items()):
        new_coll = record.get("collectives")
        old_coll = (old_configs.get(name) or {}).get("collectives")
        if not new_coll or not old_coll:
            continue
        per_kind = {}
        for kind in sorted(set(new_coll) | set(old_coll)):
            n_new = new_coll.get(kind, {})
            n_old = old_coll.get(kind, {})
            d_count = int(n_new.get("count", 0)) - int(n_old.get("count", 0))
            d_bytes = int(n_new.get("bytes", 0)) - int(n_old.get("bytes", 0))
            if not d_count and not d_bytes:
                continue
            entry: Dict[str, object] = {
                "count_delta": d_count, "bytes_delta": d_bytes,
            }
            flow = current.flows.get(name)
            if flow is not None:
                entry["attribution"] = [
                    e.to_json() for e in flow.by_collective(kind)[:top]
                ]
            per_kind[kind] = entry
        if per_kind:
            diff[name] = per_kind
            for kind, entry in per_kind.items():
                log(f"graft_lint --diff: {name} {kind} "
                    f"count{entry['count_delta']:+d} "
                    f"bytes{entry['bytes_delta']:+d}")
                for att in entry.get("attribution", []):
                    log(f"    <- {att['op']} {att['bytes']}B at "
                        f"{att['path'] or '<top>'} ({att['source']})")

    return {
        "rev": rev,
        "old_jax": (old.get("_meta") or {}).get("jax"),
        "changed_configs": len(diff),
        "diff": diff,
    }

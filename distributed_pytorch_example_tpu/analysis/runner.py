"""graft-lint orchestration: AST + jaxpr + collective audits in one pass.

Glues the three analysis layers to the dryrun mesh-config table
(``__graft_entry__.DRYRUN_CONFIGS``) and the committed budgets:

- AST lints (``pylint_rules``) run first — no jax, milliseconds;
- numerics lints (``shardlint.lint_dtype_promotions``) trace the bf16
  flagship-shaped step once;
- per-config audits lower+compile each requested mesh config on the fake
  CPU mesh (never executing a step) and check collective budgets,
  dropped donations, and large replicated params.

Configs the toolchain cannot compile produce ``{"error": ...}`` records:
the committed budget file documents the gap (e.g. jax 0.4.x cannot
compile partial-auto ``shard_map`` pipelines — ``axis_index`` lowers to
a PartitionId op its SPMD partitioner rejects), and an error matching the
committed error is a note, not a violation. Budget comparisons degrade to
warnings entirely when the runtime jax differs from the budget file's
``_meta.jax`` (collective counts are only stable within one toolchain).
"""

from __future__ import annotations

import sys
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from distributed_pytorch_example_tpu.analysis import collectives as coll
from distributed_pytorch_example_tpu.analysis import pylint_rules
from distributed_pytorch_example_tpu.analysis import shardlint
from distributed_pytorch_example_tpu.analysis.findings import Finding


@dataclass
class AuditResult:
    violations: List[Finding] = field(default_factory=list)
    notes: List[str] = field(default_factory=list)
    records: Dict[str, Dict[str, object]] = field(default_factory=dict)
    configs_audited: int = 0
    configs_errored: int = 0

    @property
    def ok(self) -> bool:
        return not self.violations

    def rule_counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for f in self.violations:
            out[f.rule] = out.get(f.rule, 0) + 1
        return out


def error_record(exc: BaseException) -> Dict[str, object]:
    first = str(exc).splitlines()[0] if str(exc) else ""
    return {"error": f"{type(exc).__name__}: {first[:200]}"}


def _resolve_configs(names: Optional[Sequence[str]]):
    import __graft_entry__ as entry

    table = {
        entry.dryrun_config_name(c): c for c in entry.DRYRUN_CONFIGS
    }
    if names is None:
        return list(table.items())
    missing = [n for n in names if n not in table]
    if missing:
        raise SystemExit(
            f"unknown config(s) {missing}; known: {sorted(table)}"
        )
    return [(n, table[n]) for n in names]


def audit_configs(
    config_names: Optional[Sequence[str]] = None,
    budgets: Optional[Dict[str, object]] = None,
    n_devices: int = 8,
    byte_tolerance: float = coll.DEFAULT_BYTE_TOLERANCE,
    check_placement: bool = True,
    log=lambda msg: print(msg, file=sys.stderr),
) -> AuditResult:
    """Compile each config and audit collectives / donation / placement.

    With ``budgets=None`` no budget comparison happens (measure-only —
    the ``--write-budgets`` path); otherwise each measured record is
    gated against ``budgets["configs"][name]``.
    """
    import __graft_entry__ as entry

    entry._ensure_cpu_devices(n_devices)
    import jax

    devices = jax.devices()[:n_devices]
    result = AuditResult()
    skew = coll.jax_version_skew(budgets) if budgets else None
    if skew is not None:
        result.notes.append(
            f"budgets were generated under jax {skew}, runtime is "
            f"{jax.__version__}: budget comparisons degraded to warnings"
        )
    committed_configs = (budgets or {}).get("configs", {})

    for name, config in _resolve_configs(config_names):
        case = entry.build_dryrun_case(config, devices)
        if isinstance(case, str):
            result.records[name] = {"skip": case}
            result.notes.append(f"{name}: skipped ({case})")
            continue
        try:
            lowered, compiled = coll.compile_case(case)
            record = coll.collective_record(case, compiled)
        except Exception as e:  # compile failures become budget records
            record = error_record(e)
            result.records[name] = record
            result.configs_errored += 1
            committed = committed_configs.get(name)
            if budgets is None or (
                committed is not None and "error" in committed
            ):
                result.notes.append(
                    f"{name}: does not compile here ({record['error']})"
                )
            elif skew is not None:
                result.notes.append(
                    f"{name}: compile error under skewed jax "
                    f"({record['error']})"
                )
            else:
                result.violations.append(Finding(
                    rule="comm-compile-error", where=name,
                    message=record["error"], config=name,
                ))
            continue
        result.records[name] = record
        result.configs_audited += 1
        log(f"graft_lint: {name} compiled; "
            f"collectives={record['collectives']}")

        if budgets is not None:
            committed = committed_configs.get(name)
            if committed is None:
                result.violations.append(Finding(
                    rule="comm-budget-missing", where=name,
                    message="no committed budget for this config; run "
                            "scripts/graft_lint.py --write-budgets",
                    config=name,
                ))
            elif "error" in committed:
                result.notes.append(
                    f"{name}: compiles now but budget records an error — "
                    f"refresh budgets to ratchet the gain in"
                )
            else:
                v, n = coll.compare_budgets(
                    committed["collectives"], record["collectives"],
                    byte_tolerance=byte_tolerance, config=name,
                    signature=committed.get(
                        "signature", record.get("signature")
                    ),
                    markers=record.get("markers"),
                )
                if skew is not None:
                    result.notes.extend(
                        f"(skew-demoted) {f.render()}" for f in v
                    )
                else:
                    result.violations.extend(v)
                result.notes.extend(n)

        if check_placement:
            result.violations.extend(shardlint.lint_dropped_donation(
                lowered, compiled, config=name
            ))
            result.violations.extend(shardlint.lint_replicated_params(
                case.trainer.state.params, case.trainer.partitioner,
                config=name,
            ))
    return result


def audit_numerics() -> List[Finding]:
    """bf16-upcast lint over the flagship-shaped bf16 train step."""
    jaxpr = shardlint.flagship_numerics_jaxpr()
    return shardlint.lint_dtype_promotions(jaxpr)


def run_audit(
    config_names: Optional[Sequence[str]] = None,
    budgets_path: str = coll.DEFAULT_BUDGETS_PATH,
    write_budgets: bool = False,
    n_devices: int = 8,
    with_collectives: bool = True,
    with_numerics: bool = True,
    with_ast: bool = True,
    log=lambda msg: print(msg, file=sys.stderr),
) -> AuditResult:
    """The full graft-lint pass (the CLI and pytest wrapper entry point)."""
    result = AuditResult()

    if with_ast:
        result.violations.extend(pylint_rules.lint_package())

    if with_numerics or with_collectives:
        import __graft_entry__ as entry

        entry._ensure_cpu_devices(n_devices)

    if with_numerics:
        result.violations.extend(audit_numerics())

    if with_collectives:
        budgets = None
        if not write_budgets:
            try:
                budgets = coll.load_budgets(budgets_path)
            except FileNotFoundError:
                result.notes.append(
                    f"no committed budgets at {budgets_path}; "
                    f"measuring without a gate (--write-budgets to commit)"
                )
        sub = audit_configs(
            config_names, budgets=budgets, n_devices=n_devices, log=log,
        )
        result.violations.extend(sub.violations)
        result.notes.extend(sub.notes)
        result.records.update(sub.records)
        result.configs_audited = sub.configs_audited
        result.configs_errored = sub.configs_errored
        if write_budgets:
            coll.write_budgets(budgets_path, result.records, n_devices)
            result.notes.append(f"wrote budgets to {budgets_path}")

    stale = coll.budget_staleness(budgets_path)
    if stale and not write_budgets:
        result.notes.append(stale)
    return result

"""Repo-specific Python AST lints (no jax import, no backend).

Thirteen rules, each a distilled past-regression class:

- ``host-sync``: ``.item()`` / ``np.asarray`` / ``jax.device_get`` inside
  TRACED-SCOPE sources (``ops/``, ``models/``, ``parallel/``,
  ``train/tasks.py``, ``train/step.py``) — the modules whose functions
  are reachable from the jitted step. A host sync there either fails
  tracing or, worse, silently forces a device round-trip per step (the
  reference's per-batch ``loss.item()`` cost, reference train.py:144).
- ``mesh-size-guess``: trace-time ``mesh.shape[...]`` reads or
  ``data_parallel_size(...)`` calls inside ``ops/`` used to GUESS a
  per-chip data size — the exact ADVICE r5 ``chunked_ce`` bug class: the
  committed layout, not the mesh span, decides how much of an operand a
  chip holds. Functions that inspect committed sharding (an
  ``.sharding`` access / ``typeof`` call in the same function) pass,
  because consulting the mesh as a FALLBACK after the layout is the
  sanctioned pattern.
- ``mutable-default``: ``[]``/``{}``/``set()`` defaults on public
  functions anywhere in the package.
- ``bf16-accum``: a bfloat16 ``zeros``/``zeros_like``/``full``/``empty``
  accumulator in a function that also ``scan``s, inside ``ops/`` or
  ``train/`` — a loop-carried bf16 sum stops absorbing addends once the
  running value outgrows them by ~2^8 (8-bit mantissa), so e.g. gradient
  accumulation over microbatches silently loses the tail contributions.
  Accumulate in f32 and cast once at the end (train/step.py's
  accumulate_grads is the reference pattern).
- ``debug-callback``: ``jax.debug.print`` / ``jax.debug.callback`` inside
  ``ops/`` or ``train/step.py``. Debug callbacks schedule a host callback
  per step — a hidden device->host round-trip in the hot path (the exact
  cost class the host-sync rule exists for) that also blocks donation and
  perturbs XLA scheduling. Step telemetry goes through the graft-scope
  sentinel struct (``telemetry/sentinels.py``): on-device scalars fetched
  once per log boundary.
- ``nan-launder``: any ``nan_to_num`` call inside ``ops/`` or ``train/``.
  Replacing NaN/Inf with zeros SILENCES the fault instead of surfacing
  it: the sentinel struct stops counting, the bad-step predication in
  train/step.py never fires, and a diverging run keeps training on
  laundered garbage. The sanctioned recovery path is detection
  (``telemetry/sentinels.py``) + device-side update predication + the
  Trainer's bounded bad-step budget (graft-armor) — never value
  rewriting. Deliberate exceptions carry ``# graft-lint: nan-launder``.
- ``ckpt-stamp``: a ``msgpack_serialize`` call inside
  ``train/checkpoint.py`` from a function that never references the
  ``mesh_manifest`` stamp. Every checkpoint write must carry the
  format-3 mesh-topology manifest (graft-elastic), or the artifact can
  only ever be resumed on the exact mesh that wrote it — and elastic
  shrink-to-survivors resume from it raises. A write path added beside
  ``_write_payload`` / ``_save_sharded`` that forgets the stamp silently
  regresses cross-mesh resume; this rule makes that a lint failure.

- ``serve-dynamic-shape``: inside a jit-decorated function in
  ``serving/``, an ``if``/``while`` whose test reads ``.shape``, or a
  list ``.append(...)`` (token accumulation). graft-serve's whole
  contract is TWO compiled programs for the entire workload — bucketed
  prefill and fixed-slot decode — so continuous batching never
  recompiles; shape-dependent branching quietly re-specializes the
  program per request shape (a recompile per novel length), and
  appending tokens to a Python list inside the traced region either
  fails tracing or unrolls the loop. Variable length belongs in the
  HOST scheduler (tables, lens, buckets), never in the traced step.

- ``fleet-unbounded-wait``: a zero-argument ``.get()`` / ``.wait()`` /
  ``.join()`` call (no positional timeout, no ``timeout=`` keyword)
  inside ``serving/`` or ``data/``. graft-fleet's failover contract is
  that every blocking wait in the serving path is deadline-bounded — an
  unbounded ``queue.get()`` in a replica worker or ``Event.wait()`` in
  the router is exactly the silent-hang class the heartbeat deadline
  exists to catch, and a hang INSIDE the detector is undetectable.
  graft-intake extends the same contract to the input plane: a training
  step blocked forever on a dead decode worker's queue is the identical
  failure with a different costume. Calls with any positional argument
  never fire (``dict.get(key)``, ``sep.join(xs)``, ``event.wait(0.05)``
  are all fine), and ``block=False`` non-blocking gets are fine;
  everything else must pass ``timeout=``.

- ``serve-bare-clock``: a bare ``time.time()`` / ``time.perf_counter()``
  / ``time.monotonic()`` (or ``from time import ...`` equivalent) CALL
  inside ``serving/``. graft-lens' contract is that every timed phase
  boundary in the serving path reads the INJECTED clock (the
  ``clock=time.monotonic`` constructor default every serving class
  takes — referencing the function is fine, calling it directly is not)
  or runs under a trace ``span(...)``: a bare wall-clock call is
  invisible to the request trace, and a fake-clock test cannot steer it.

- ``wire-raw-collective``: a raw ``psum(...)`` / ``psum_scatter(...)``
  call inside ``train/step.py``. graft-wire's contract is that EVERY
  gradient collective in the step routes through ``parallel/wire.py``
  (``wire_psum`` / ``wire_psum_scatter``), which honor the
  ``WireConfig`` compression policy — a direct ``lax.psum*`` call added
  to the step silently ships fp32 payloads regardless of
  ``--wire int8-block``, exactly the fallback class the
  ``wire-int8-step`` comm-budget signature exists to catch, but at the
  source level before any compile. ``pmean`` (metrics averaging) and
  the ``wire_*`` wrappers themselves are fine.

- ``inline-grad-sync``: a per-leaf wire collective call
  (``wire_psum_scatter`` / ``wire_all_gather`` / ``wire_psum``) inside
  ``train/step.py``. The bucketed comm/compute-overlap path
  (``parallel/wire.py sync_grads``) owns the gradient-sync issue order:
  buckets launch in reverse trace order on independent dataflow chains
  so the XLA scheduler hides their wire time behind backward compute. A
  per-leaf wire call added back to the step is an INLINE collective
  outside that schedule — it serializes against the whole backward,
  silently re-creating the exposed-comm ceiling bucketing removed (and
  the scheduler-level ``overlap_frac`` CI gate would attribute the
  regression to the wrong bucket). ``sync_grads(...)`` and
  ``replicate_params(...)`` are the sanctioned entry points; the wire
  module itself is out of scope.

- ``plan-overlay``: a ``P(...)`` / ``PartitionSpec(...)`` construction
  with a STRING-LITERAL axis name inside ``parallel/api.py`` or
  ``train/step.py``. graft-plan's contract is that every sharding those
  modules emit lowers through a ``PlanSpec`` (``parallel/plan.py``) —
  the single description the static planner scores, the budget auditor
  keys on, and the factories lower. A hard-coded ``P("data", ...)``
  added beside the plan path is an overlay the planner cannot see: the
  planner ranks one program, the step runs another, and the committed
  budget signatures drift from the shipped shardings. Dynamic
  construction — ``P()``, ``P(*entries)``, ``P(axis_var)``, names
  built from the plan's mesh axes — is the sanctioned pattern; only
  literal axis strings (bare or inside tuple/list literals) fire.

- ``decode-gather``: inside ``serving/`` or ``models/``, a function that
  touches the paged KV pool (an identifier starting with ``pages_``) and
  calls ``jnp.take(...)`` or ``lax.dynamic_update_slice(...)`` WITHOUT
  also dispatching through ``paged_decode_attention`` /
  ``paged_flash_decode``. Gather-materializing the paged cache (or
  re-growing an unrolled per-block write loop) in serve-reachable jitted
  code is exactly the per-token cost class the fused Pallas flash-decode
  kernel (ops/pallas/paged_attention.py) removed — the ``.at[].set``
  scatter write and the fused dispatch are the sanctioned pair, and the
  XLA gather fallback lives ONLY inside ``ops/pallas/paged_attention.py``
  (out of scope), bit-exact behind the kernel gate. The
  ``paged-decode-fused`` comm-budget signature catches the same
  regression after compile; this rule catches it at the source.

- ``swap-unversioned-params``: an assignment to a ``.params`` /
  ``.draft_params`` attribute inside ``serving/`` from any function other
  than ``__init__`` or ``InferenceEngine.install_params``. graft-swap's
  whole guarantee is that live weights only ever flip through
  ``install_params``: drained engine, ``weights_version`` retagged, and
  the partitioner re-placing leaves onto the serve layout — all in one
  transaction the SwapController brackets with the router's
  pause/drain/resume roll plane. An ad-hoc ``engine.params = ...``
  anywhere else swaps weights mid-stream with a stale version tag,
  silently mixing two versions' logits inside one response — exactly the
  corruption class the hot-swap-midstream chaos scenario pins.

Scope is static and name-based, not a whole-program call graph — the
cheap 99% of the check. Deliberate exceptions carry a
``# graft-lint: ok`` (all rules) or ``# graft-lint: <rule>`` comment on
the offending line.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Sequence, Set

from distributed_pytorch_example_tpu.analysis.findings import Finding

TRACED_SCOPE = (
    "ops/", "models/", "parallel/", "train/tasks.py", "train/step.py",
)
MESH_GUESS_SCOPE = ("ops/",)
BF16_ACCUM_SCOPE = ("ops/", "train/")
DEBUG_CALLBACK_SCOPE = ("ops/", "train/step.py")
NAN_LAUNDER_SCOPE = ("ops/", "train/")
CKPT_STAMP_SCOPE = ("train/checkpoint.py",)
SERVE_SCOPE = ("serving/",)
# fleet-unbounded-wait covers every shipped thread-supervision surface:
# the serving fleet AND the graft-intake input plane (decode workers,
# prefetch queues) — a bare Queue.get()/Event.wait()/Thread.join() in
# either can wedge a whole host on one dead peer/worker
WAIT_SCOPE = ("serving/", "data/")
# wire-raw-collective pins the step's gradient sync to the graft-wire
# dispatch (parallel/wire.py) — a raw lax.psum*/psum_scatter in the step
# bypasses the WireConfig compression policy
WIRE_RAW_SCOPE = ("train/step.py",)
# inline-grad-sync pins the step's gradient sync to the ONE bucketed
# dispatcher (parallel/wire.py sync_grads) — a per-leaf wire_* call in
# the step is an inline collective outside the overlap issue order
INLINE_GRAD_SYNC_SCOPE = ("train/step.py",)
# plan-overlay pins the shipped sharding surfaces to the PlanSpec
# lowering (parallel/plan.py) — a string-literal PartitionSpec in either
# module is an ad-hoc overlay the static planner cannot score
PLAN_OVERLAY_SCOPE = ("parallel/api.py", "train/step.py")
# decode-gather pins serve-reachable paged-KV code to the fused-kernel
# dispatch (ops/pallas/paged_attention.py) — the gather fallback itself
# lives in that module, deliberately OUTSIDE this scope
DECODE_GATHER_SCOPE = ("serving/", "models/")
# swap-unversioned-params pins live engine weights to the ONE sanctioned
# mutation site (InferenceEngine.install_params, plus constructors) —
# an ad-hoc `.params =` in serving code flips weights without the
# version retag / drain bracket graft-swap's bit-identity rests on
SWAP_PARAMS_SCOPE = ("serving/",)

_ACCUM_CTORS = ("zeros", "zeros_like", "full", "empty")

_SUPPRESS_RE = re.compile(r"#\s*graft-lint:\s*([\w,-]+)")


def package_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _suppressions(source: str) -> Dict[int, Set[str]]:
    out: Dict[int, Set[str]] = {}
    for lineno, line in enumerate(source.splitlines(), start=1):
        m = _SUPPRESS_RE.search(line)
        if m:
            out[lineno] = {t.strip() for t in m.group(1).split(",")}
    return out


def _suppressed(supp: Dict[int, Set[str]], lineno: int, rule: str) -> bool:
    tags = supp.get(lineno, set())
    return "ok" in tags or rule in tags


def _in_scope(relpath: str, scope: Sequence[str]) -> bool:
    rel = relpath.replace(os.sep, "/")
    return any(
        rel.startswith(s) or rel == s.rstrip("/") for s in scope
    )


def _module_aliases(tree: ast.Module) -> Dict[str, Set[str]]:
    """Local names bound to the numpy and jax modules."""
    aliases = {"numpy": {"numpy"}, "jax": {"jax"}}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name in ("numpy", "jax"):
                    aliases[a.name].add(a.asname or a.name)
    return aliases


def _attr_root(node: ast.AST) -> Optional[str]:
    while isinstance(node, ast.Attribute):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


class _FuncStack(ast.NodeVisitor):
    """Generic visitor that tracks the enclosing function def chain."""

    def __init__(self):
        self.stack: List[ast.AST] = []

    def visit_FunctionDef(self, node):
        self.stack.append(node)
        self.generic_visit(node)
        self.stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef


def _inspects_committed_sharding(func: ast.AST) -> bool:
    """Whether a function consults committed layout (``.sharding`` /
    ``typeof``) — mesh-span reads are then the sanctioned fallback."""
    for node in ast.walk(func):
        if isinstance(node, ast.Attribute) and node.attr == "sharding":
            return True
        if isinstance(node, ast.Call):
            fn = node.func
            name = fn.attr if isinstance(fn, ast.Attribute) else (
                fn.id if isinstance(fn, ast.Name) else None
            )
            if name in ("typeof", "get_aval"):
                return True
            if name == "getattr" and any(
                isinstance(a, ast.Constant) and a.value == "sharding"
                for a in node.args
            ):
                return True
    return False


def _is_bf16_expr(node: ast.AST) -> bool:
    """Whether an expression names the bfloat16 dtype (``jnp.bfloat16``,
    ``"bfloat16"``, a bare ``bfloat16`` name)."""
    if isinstance(node, ast.Attribute):
        return node.attr == "bfloat16"
    if isinstance(node, ast.Name):
        return node.id == "bfloat16"
    if isinstance(node, ast.Constant):
        return node.value == "bfloat16"
    return False


def _bf16_accum_findings(
    tree: ast.Module, relpath: str, supp: Dict[int, Set[str]]
) -> List[Finding]:
    """bf16 accumulator ctors in functions that also scan (module doc)."""
    flagged: Dict[int, Finding] = {}  # keyed by line: nesting dedup
    for func in ast.walk(tree):
        if not isinstance(func, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        has_scan = False
        ctors: List[ast.Call] = []
        for node in ast.walk(func):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            name = fn.attr if isinstance(fn, ast.Attribute) else (
                fn.id if isinstance(fn, ast.Name) else None
            )
            if name == "scan":
                has_scan = True
            elif name in _ACCUM_CTORS and any(
                _is_bf16_expr(a)
                for a in list(node.args)
                + [k.value for k in node.keywords]
            ):
                ctors.append(node)
        if not has_scan:
            continue
        for node in ctors:
            if _suppressed(supp, node.lineno, "bf16-accum"):
                continue
            flagged.setdefault(node.lineno, Finding(
                rule="bf16-accum",
                where=f"{relpath}:{node.lineno}",
                message=(
                    "bfloat16 accumulator in a scanning function: a "
                    "loop-carried bf16 sum drops addends ~2^8 smaller "
                    "than the running value (8-bit mantissa) — "
                    "accumulate in float32 and cast once after the loop"
                ),
            ))
    return [flagged[k] for k in sorted(flagged)]


def _references_mesh_manifest(func: ast.AST) -> bool:
    """Whether a function touches the stamp by any spelling: a
    ``mesh_manifest`` name/parameter/keyword, an attribute access
    (``elastic.mesh_manifest``, ``elastic.MANIFEST_KEY``), or the literal
    manifest key string."""
    for node in ast.walk(func):
        if isinstance(node, ast.Name) and node.id == "mesh_manifest":
            return True
        if isinstance(node, ast.arg) and node.arg == "mesh_manifest":
            return True
        if isinstance(node, ast.keyword) and node.arg == "mesh_manifest":
            return True
        if isinstance(node, ast.Attribute) and node.attr in (
            "mesh_manifest", "MANIFEST_KEY"
        ):
            return True
        if isinstance(node, ast.Constant) and node.value == "mesh_manifest":
            return True
    return False


def _ckpt_stamp_findings(
    tree: ast.Module, relpath: str, supp: Dict[int, Set[str]]
) -> List[Finding]:
    """msgpack_serialize writes that bypass the mesh-manifest stamp."""
    # spans of functions that DO reference the stamp: any serialize call
    # inside one is sanctioned (the stamp rides in that function's payload)
    ok_spans = [
        (func.lineno, func.end_lineno or func.lineno)
        for func in ast.walk(tree)
        if isinstance(func, (ast.FunctionDef, ast.AsyncFunctionDef))
        and _references_mesh_manifest(func)
    ]
    flagged: Dict[int, Finding] = {}  # keyed by line: nesting dedup
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        name = fn.attr if isinstance(fn, ast.Attribute) else (
            fn.id if isinstance(fn, ast.Name) else None
        )
        if name != "msgpack_serialize":
            continue
        if any(a <= node.lineno <= b for a, b in ok_spans):
            continue
        if _suppressed(supp, node.lineno, "ckpt-stamp"):
            continue
        flagged.setdefault(node.lineno, Finding(
            rule="ckpt-stamp",
            where=f"{relpath}:{node.lineno}",
            message=(
                "checkpoint write bypasses the mesh-manifest stamp: "
                "msgpack_serialize in a function that never references "
                "mesh_manifest — unstamped artifacts cannot be resumed "
                "across mesh shapes (graft-elastic); thread the "
                "mesh_manifest through like _write_payload/_save_sharded"
            ),
        ))
    return [flagged[k] for k in sorted(flagged)]


def _is_jit_decorator(dec: ast.AST) -> bool:
    """Whether a decorator expression jits the function: ``jit``,
    ``jax.jit``, or a ``partial(jax.jit, ...)`` of any spelling."""
    if isinstance(dec, ast.Call):
        fn = dec.func
        name = fn.attr if isinstance(fn, ast.Attribute) else (
            fn.id if isinstance(fn, ast.Name) else None
        )
        if name == "partial":
            return any(_is_jit_decorator(a) for a in dec.args)
        return name == "jit"
    name = dec.attr if isinstance(dec, ast.Attribute) else (
        dec.id if isinstance(dec, ast.Name) else None
    )
    return name == "jit"


def _serve_dynamic_shape_findings(
    tree: ast.Module, relpath: str, supp: Dict[int, Set[str]]
) -> List[Finding]:
    """Shape-dependent branches / list-append accumulation inside jitted
    serving programs (module docstring: the two-programs contract)."""
    flagged: Dict[int, Finding] = {}  # keyed by line: nesting dedup
    for func in ast.walk(tree):
        if not isinstance(func, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if not any(_is_jit_decorator(d) for d in func.decorator_list):
            continue
        for node in ast.walk(func):
            if isinstance(node, (ast.If, ast.While)):
                shape_read = any(
                    isinstance(sub, ast.Attribute) and sub.attr == "shape"
                    for sub in ast.walk(node.test)
                )
                if shape_read and not _suppressed(
                    supp, node.lineno, "serve-dynamic-shape"
                ):
                    flagged.setdefault(node.lineno, Finding(
                        rule="serve-dynamic-shape",
                        where=f"{relpath}:{node.lineno}",
                        message=(
                            ".shape-dependent branch inside a jitted "
                            "serving program: each novel request shape "
                            "re-specializes (recompiles) the step, "
                            "breaking the two-compiled-programs contract "
                            "— route variable length through the host "
                            "scheduler (page tables / row lens / "
                            "prefill buckets)"
                        ),
                    ))
            elif (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "append"
            ):
                if not _suppressed(
                    supp, node.lineno, "serve-dynamic-shape"
                ):
                    flagged.setdefault(node.lineno, Finding(
                        rule="serve-dynamic-shape",
                        where=f"{relpath}:{node.lineno}",
                        message=(
                            "list .append(...) token accumulation inside "
                            "a jitted serving program: growing a Python "
                            "list under trace either fails or unrolls the "
                            "loop into the program; write tokens into "
                            "fixed-shape slot arrays on the host instead"
                        ),
                    ))
    return [flagged[k] for k in sorted(flagged)]


def _holds_str_literal(node: ast.AST) -> bool:
    """Whether an expression IS a string literal or a tuple/list literal
    containing one (any nesting depth)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return True
    if isinstance(node, (ast.Tuple, ast.List)):
        return any(_holds_str_literal(e) for e in node.elts)
    return False


_INLINE_SYNC_NAMES = ("wire_psum_scatter", "wire_all_gather", "wire_psum")


def _inline_grad_sync_findings(
    tree: ast.Module, relpath: str, supp: Dict[int, Set[str]]
) -> List[Finding]:
    """Per-leaf wire collective calls bypassing the bucketed sync
    dispatcher (module docstring: the inline-grad-sync contract)."""
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        name = fn.attr if isinstance(fn, ast.Attribute) else (
            fn.id if isinstance(fn, ast.Name) else None
        )
        if name not in _INLINE_SYNC_NAMES:
            continue
        if _suppressed(supp, node.lineno, "inline-grad-sync"):
            continue
        findings.append(Finding(
            rule="inline-grad-sync",
            where=f"{relpath}:{node.lineno}",
            message=(
                f"per-leaf {name}(...) in the train step is an inline "
                "collective outside the bucketed issue order: it "
                "serializes against the whole backward instead of "
                "hiding behind it, and its wire time escapes the "
                "per-bucket overlap attribution — route gradient sync "
                "through parallel/wire.py sync_grads (replicate_params "
                "for the ZeRO-1 param gather)"
            ),
        ))
    return findings


def _plan_overlay_findings(
    tree: ast.Module, relpath: str, supp: Dict[int, Set[str]]
) -> List[Finding]:
    """String-literal PartitionSpec construction bypassing the PlanSpec
    lowering (module docstring: the graft-plan contract)."""
    flagged: Dict[int, Finding] = {}  # keyed by line: nesting dedup
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        name = fn.attr if isinstance(fn, ast.Attribute) else (
            fn.id if isinstance(fn, ast.Name) else None
        )
        if name not in ("P", "PartitionSpec"):
            continue
        # only literal axis strings fire: P(), P(*entries), P(axis_var)
        # are the sanctioned dynamic construction (ast.Starred is not a
        # Constant/Tuple/List, so it falls through)
        literal = any(_holds_str_literal(a) for a in node.args) or any(
            _holds_str_literal(k.value) for k in node.keywords
        )
        if not literal:
            continue
        if _suppressed(supp, node.lineno, "plan-overlay"):
            continue
        flagged.setdefault(node.lineno, Finding(
            rule="plan-overlay",
            where=f"{relpath}:{node.lineno}",
            message=(
                f"{name}(...) built from a string-literal axis name "
                "bypasses the PlanSpec lowering: the static planner "
                "scores PlanSpec-derived shardings only, so an ad-hoc "
                "overlay here silently diverges the ranked program from "
                "the shipped one — derive axis names from the plan/mesh "
                "(parallel/plan.py) or construct the spec dynamically"
            ),
        ))
    return [flagged[k] for k in sorted(flagged)]


_WAIT_NAMES = ("get", "wait", "join")


def _fleet_unbounded_wait_findings(
    tree: ast.Module, relpath: str, supp: Dict[int, Set[str]]
) -> List[Finding]:
    """Unbounded blocking waits in the fleet/serving path (module doc)."""
    flagged: Dict[int, Finding] = {}  # keyed by line: nesting dedup
    for node in ast.walk(tree):
        if not (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _WAIT_NAMES
        ):
            continue
        if node.args:
            continue  # positional timeout / dict.get(key) / sep.join(xs)
        kwargs = {k.arg: k.value for k in node.keywords if k.arg}
        if "timeout" in kwargs:
            continue
        block = kwargs.get("block")
        if isinstance(block, ast.Constant) and block.value is False:
            continue  # non-blocking get never waits
        if _suppressed(supp, node.lineno, "fleet-unbounded-wait"):
            continue
        flagged.setdefault(node.lineno, Finding(
            rule="fleet-unbounded-wait",
            where=f"{relpath}:{node.lineno}",
            message=(
                f".{node.func.attr}() without a timeout in a supervised "
                "thread path: an unbounded blocking wait here can hang a "
                "replica worker, the router, or a training step waiting "
                "on a dead decode worker forever — outside what the "
                "heartbeat deadline can detect; pass timeout= "
                "(graft-fleet/graft-intake supervision contract)"
            ),
        ))
    return [flagged[k] for k in sorted(flagged)]


_CLOCK_NAMES = (
    "time", "perf_counter", "perf_counter_ns", "monotonic", "monotonic_ns",
)


def _serve_bare_clock_findings(
    tree: ast.Module, relpath: str, supp: Dict[int, Set[str]]
) -> List[Finding]:
    """Bare ``time.time()`` / ``time.perf_counter()`` CALLS in the
    serving path (module docstring). Referencing a clock (e.g. the
    ``clock=time.monotonic`` default arg every serving class takes) is
    fine — it is calling one directly that bypasses the injected clock
    and the ``span(...)`` phase accounting."""
    time_aliases = {"time"}
    from_imports: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "time":
                    time_aliases.add(a.asname or a.name)
        elif isinstance(node, ast.ImportFrom) and node.module == "time":
            for a in node.names:
                if a.name in _CLOCK_NAMES:
                    from_imports.add(a.asname or a.name)
    flagged: Dict[int, Finding] = {}  # keyed by line: nesting dedup
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if isinstance(fn, ast.Attribute):
            if not (
                fn.attr in _CLOCK_NAMES
                and _attr_root(fn) in time_aliases
            ):
                continue
            shown = f"time.{fn.attr}"
        elif isinstance(fn, ast.Name) and fn.id in from_imports:
            shown = fn.id
        else:
            continue
        if _suppressed(supp, node.lineno, "serve-bare-clock"):
            continue
        flagged.setdefault(node.lineno, Finding(
            rule="serve-bare-clock",
            where=f"{relpath}:{node.lineno}",
            message=(
                f"bare {shown}() call in serving/: phase boundaries must "
                "read the injected clock (the clock= ctor arg, "
                "engine._ts_us) or run under trace span(...) so fake "
                "clocks stay honest in tests and every timed phase lands "
                "in the graft-lens request trace"
            ),
        ))
    return [flagged[k] for k in sorted(flagged)]


_DECODE_GATHER_CALLS = ("take", "dynamic_update_slice")
_PAGED_DISPATCH = ("paged_decode_attention", "paged_flash_decode")


def _decode_gather_findings(
    tree: ast.Module, relpath: str, supp: Dict[int, Set[str]]
) -> List[Finding]:
    """Gather/unrolled-write KV materialization beside the paged pool
    without the fused-kernel dispatch (module docstring)."""

    def idents(node: ast.AST):
        for sub in ast.walk(node):
            if isinstance(sub, ast.Name):
                yield sub.id
            elif isinstance(sub, ast.Attribute):
                yield sub.attr

    flagged: Dict[int, Finding] = {}  # keyed by line: nesting dedup
    for func in ast.walk(tree):
        if not isinstance(func, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if not any(name.startswith("pages_") for name in idents(func)):
            continue  # not a paged-pool function
        calls = [
            node for node in ast.walk(func) if isinstance(node, ast.Call)
        ]

        def call_name(node: ast.Call) -> Optional[str]:
            fn = node.func
            return fn.attr if isinstance(fn, ast.Attribute) else (
                fn.id if isinstance(fn, ast.Name) else None
            )

        if any(call_name(c) in _PAGED_DISPATCH for c in calls):
            continue  # routes through the fused kernel: sanctioned
        for node in calls:
            if call_name(node) not in _DECODE_GATHER_CALLS:
                continue
            if _suppressed(supp, node.lineno, "decode-gather"):
                continue
            flagged.setdefault(node.lineno, Finding(
                rule="decode-gather",
                where=f"{relpath}:{node.lineno}",
                message=(
                    f"{call_name(node)}(...) in a paged-KV function that "
                    "never dispatches paged_decode_attention: gather-"
                    "materializing the block pool (or unrolling per-block "
                    "writes) in serve-reachable jitted code re-grows the "
                    "per-token decode cost the fused Pallas kernel "
                    "removed — write via .at[].set scatter and attend "
                    "through ops/pallas/paged_attention.py"
                ),
            ))
    return [flagged[k] for k in sorted(flagged)]


_SWAP_PARAM_ATTRS = ("params", "draft_params")
_SWAP_SANCTIONED_FUNCS = ("__init__", "install_params")


def _swap_unversioned_params_findings(
    tree: ast.Module, relpath: str, supp: Dict[int, Set[str]]
) -> List[Finding]:
    """Live-weight assignments outside the versioned install transaction
    (module docstring: the graft-swap contract)."""
    flagged: Dict[int, Finding] = {}  # keyed by line: tuple-target dedup

    def targets_of(node: ast.AST):
        if isinstance(node, ast.Assign):
            stack = list(node.targets)
        elif isinstance(node, ast.AugAssign):
            stack = [node.target]
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            stack = [node.target]
        else:
            return
        # direct attribute targets and tuple/list unpacking only — an
        # Attribute buried in a Subscript target (d[obj.params] = x)
        # does not rebind the live pytree
        while stack:
            tgt = stack.pop()
            if isinstance(tgt, (ast.Tuple, ast.List)):
                stack.extend(tgt.elts)
            elif isinstance(tgt, ast.Attribute):
                yield tgt

    def scan(node: ast.AST, func_name: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scan(child, child.name)
                continue
            for tgt in targets_of(child):
                if tgt.attr not in _SWAP_PARAM_ATTRS:
                    continue
                if func_name in _SWAP_SANCTIONED_FUNCS:
                    continue
                if _suppressed(
                    supp, child.lineno, "swap-unversioned-params"
                ):
                    continue
                flagged.setdefault(child.lineno, Finding(
                    rule="swap-unversioned-params",
                    where=f"{relpath}:{child.lineno}",
                    message=(
                        f"assignment to .{tgt.attr} outside __init__/"
                        "install_params: flipping live engine weights "
                        "here skips the version retag, the partitioner "
                        "re-placement, and the router's drain bracket — "
                        "a mid-stream response would mix two versions' "
                        "logits under a stale weights_version tag; route "
                        "the swap through InferenceEngine.install_params "
                        "(graft-swap contract)"
                    ),
                ))
            scan(child, func_name)

    scan(tree, "")
    return [flagged[k] for k in sorted(flagged)]


def lint_source(relpath: str, source: str) -> List[Finding]:
    """All AST findings for one package source file.

    ``relpath`` is the path relative to the package root (forward or OS
    separators), which selects the applicable rule scopes.
    """
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        return [Finding(
            rule="syntax-error", where=f"{relpath}:{e.lineno}",
            message=str(e),
        )]
    supp = _suppressions(source)
    aliases = _module_aliases(tree)
    findings: List[Finding] = []
    traced = _in_scope(relpath, TRACED_SCOPE)
    mesh_scope = _in_scope(relpath, MESH_GUESS_SCOPE)
    debug_scope = _in_scope(relpath, DEBUG_CALLBACK_SCOPE)
    nan_scope = _in_scope(relpath, NAN_LAUNDER_SCOPE)
    wire_scope = _in_scope(relpath, WIRE_RAW_SCOPE)

    visitor = _FuncStack()
    sharding_aware: Dict[ast.AST, bool] = {}

    def enclosing_inspects() -> bool:
        for func in reversed(visitor.stack):
            if func not in sharding_aware:
                sharding_aware[func] = _inspects_committed_sharding(func)
            if sharding_aware[func]:
                return True
        return False

    def visit_Call(node: ast.Call):
        if traced:
            fn = node.func
            if (
                isinstance(fn, ast.Attribute) and fn.attr == "item"
                and not node.args and not node.keywords
                and not _suppressed(supp, node.lineno, "host-sync")
            ):
                findings.append(Finding(
                    rule="host-sync",
                    where=f"{relpath}:{node.lineno}",
                    message=".item() forces a device->host sync per call "
                            "inside traced scope",
                ))
            if isinstance(fn, ast.Attribute) and (
                (fn.attr == "asarray"
                 and _attr_root(fn) in aliases["numpy"])
                or (fn.attr == "device_get"
                    and _attr_root(fn) in aliases["jax"])
            ) and not _suppressed(supp, node.lineno, "host-sync"):
                findings.append(Finding(
                    rule="host-sync",
                    where=f"{relpath}:{node.lineno}",
                    message=f"{ast.unparse(fn)}(...) materializes on host "
                            "inside traced scope",
                ))
        if debug_scope:
            fn = node.func
            if isinstance(fn, ast.Attribute) and fn.attr in (
                "print", "callback"
            ):
                owner = fn.value
                # jax.debug.print / debug.print (from jax import debug)
                is_jax_debug = (
                    isinstance(owner, ast.Attribute)
                    and owner.attr == "debug"
                    and _attr_root(owner) in aliases["jax"]
                ) or (
                    isinstance(owner, ast.Name) and owner.id == "debug"
                )
                if is_jax_debug and not _suppressed(
                    supp, node.lineno, "debug-callback"
                ):
                    findings.append(Finding(
                        rule="debug-callback",
                        where=f"{relpath}:{node.lineno}",
                        message=(
                            f"{ast.unparse(fn)}(...) schedules a host "
                            "callback per step inside the compiled hot "
                            "path; route step telemetry through the "
                            "graft-scope sentinel struct "
                            "(telemetry/sentinels.py) instead"
                        ),
                    ))
        if nan_scope:
            fn = node.func
            name = fn.attr if isinstance(fn, ast.Attribute) else (
                fn.id if isinstance(fn, ast.Name) else None
            )
            if name == "nan_to_num" and not _suppressed(
                supp, node.lineno, "nan-launder"
            ):
                findings.append(Finding(
                    rule="nan-launder",
                    where=f"{relpath}:{node.lineno}",
                    message=(
                        "nan_to_num(...) launders nonfinite values into "
                        "zeros, hiding the fault from the sentinel struct "
                        "and the bad-step predication; let detection + "
                        "update skipping (graft-armor) handle nonfinite "
                        "steps instead"
                    ),
                ))
        if wire_scope:
            fn = node.func
            name = fn.attr if isinstance(fn, ast.Attribute) else (
                fn.id if isinstance(fn, ast.Name) else None
            )
            if name in ("psum", "psum_scatter") and not _suppressed(
                supp, node.lineno, "wire-raw-collective"
            ):
                findings.append(Finding(
                    rule="wire-raw-collective",
                    where=f"{relpath}:{node.lineno}",
                    message=(
                        f"raw {name}(...) in the train step bypasses the "
                        "graft-wire dispatch: it always ships fp32 "
                        "payloads, ignoring the WireConfig compression "
                        "policy — route gradient collectives through "
                        "parallel/wire.py (wire_psum / wire_psum_scatter)"
                    ),
                ))
        if mesh_scope:
            fn = node.func
            name = fn.attr if isinstance(fn, ast.Attribute) else (
                fn.id if isinstance(fn, ast.Name) else None
            )
            if (
                name == "data_parallel_size"
                and not enclosing_inspects()
                and not _suppressed(supp, node.lineno, "mesh-size-guess")
            ):
                findings.append(Finding(
                    rule="mesh-size-guess",
                    where=f"{relpath}:{node.lineno}",
                    message="data_parallel_size(mesh) guesses a per-chip "
                            "size from the mesh span; derive it from the "
                            "operand's committed sharding (fall back to "
                            "the conservative global size when unknown)",
                ))
        visitor.generic_visit(node)

    def visit_Subscript(node: ast.Subscript):
        if mesh_scope:
            v = node.value
            if (
                isinstance(v, ast.Attribute) and v.attr == "shape"
                and isinstance(v.value, ast.Name)
                and "mesh" in v.value.id.lower()
                and not enclosing_inspects()
                and not _suppressed(supp, node.lineno, "mesh-size-guess")
            ):
                findings.append(Finding(
                    rule="mesh-size-guess",
                    where=f"{relpath}:{node.lineno}",
                    message="mesh.shape[...] read at trace time to size "
                            "data; use the committed sharding instead",
                ))
        visitor.generic_visit(node)

    def visit_def(node):
        if not node.name.startswith("_"):
            mutable = (ast.List, ast.Dict, ast.Set)
            for default in list(node.args.defaults) + [
                d for d in node.args.kw_defaults if d is not None
            ]:
                is_call_ctor = (
                    isinstance(default, ast.Call)
                    and isinstance(default.func, ast.Name)
                    and default.func.id in ("list", "dict", "set")
                )
                if (
                    (isinstance(default, mutable) or is_call_ctor)
                    and not _suppressed(
                        supp, default.lineno, "mutable-default"
                    )
                ):
                    findings.append(Finding(
                        rule="mutable-default",
                        where=f"{relpath}:{default.lineno}",
                        message=f"public API {node.name}() has a mutable "
                                "default argument (shared across calls)",
                    ))
        _FuncStack.visit_FunctionDef(visitor, node)

    visitor.visit_Call = visit_Call
    visitor.visit_Subscript = visit_Subscript
    visitor.visit_FunctionDef = visit_def
    visitor.visit_AsyncFunctionDef = visit_def
    visitor.visit(tree)
    if _in_scope(relpath, BF16_ACCUM_SCOPE):
        findings.extend(_bf16_accum_findings(tree, relpath, supp))
    if _in_scope(relpath, CKPT_STAMP_SCOPE):
        findings.extend(_ckpt_stamp_findings(tree, relpath, supp))
    if _in_scope(relpath, SERVE_SCOPE):
        findings.extend(_serve_dynamic_shape_findings(tree, relpath, supp))
        findings.extend(_serve_bare_clock_findings(tree, relpath, supp))
    if _in_scope(relpath, SWAP_PARAMS_SCOPE):
        findings.extend(
            _swap_unversioned_params_findings(tree, relpath, supp)
        )
    if _in_scope(relpath, WAIT_SCOPE):
        findings.extend(_fleet_unbounded_wait_findings(tree, relpath, supp))
    if _in_scope(relpath, INLINE_GRAD_SYNC_SCOPE):
        findings.extend(_inline_grad_sync_findings(tree, relpath, supp))
    if _in_scope(relpath, PLAN_OVERLAY_SCOPE):
        findings.extend(_plan_overlay_findings(tree, relpath, supp))
    if _in_scope(relpath, DECODE_GATHER_SCOPE):
        findings.extend(_decode_gather_findings(tree, relpath, supp))
    return findings


def lint_package(root: Optional[str] = None) -> List[Finding]:
    """AST findings over every ``.py`` source in the package tree."""
    root = root or package_root()
    findings: List[Finding] = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(
            d for d in dirnames if d != "__pycache__"
        )
        for fname in sorted(filenames):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            rel = os.path.relpath(path, root)
            with open(path) as f:
                findings.extend(lint_source(rel, f.read()))
    return findings

"""Jaxpr/placement lints: replication, f32 upcasts, dropped donation.

Three regression classes that never fail a numeric test:

- a large param left FULLY REPLICATED under a multi-axis mesh when a
  partition rule would shard it (2x..Nx param HBM + a silent all-gather
  in the step);
- a bf16→f32 ``convert_element_type`` of a LARGE array inside the
  loss/backward path that is not one of the deliberate f32 islands
  (optimizer moments, norm/softmax statistics, metric sums) — the classic
  accidental-upcast that doubles activation bytes;
- a donated argument the compiled executable did not actually alias
  (donation silently dropped = the updated state materializes NEXT TO the
  old one: 2x param+optimizer memory).

All entry points are static — they walk jaxprs, committed shardings, and
compiled-HLO metadata; nothing executes.
"""

from __future__ import annotations

import math
import re
from typing import Any, Iterable, List, Optional, Sequence, Tuple

from distributed_pytorch_example_tpu.analysis.findings import Finding

# bf16→f32 promotions whose SOURCE matches one of these regexes are
# deliberate f32 islands, not bugs. Matched against jax's source summary
# ("path/to/file.py:line (function)") of the convert_element_type site.
DEFAULT_UPCAST_ALLOWLIST: Tuple[str, ...] = (
    r"optax",                      # optimizer moments/updates are f32
    r"flax/linen/normalization",   # LayerNorm/RMSNorm statistics
    r"normalization\.py",
    r"jax/_src/nn",                # softmax/logsumexp accumulators
    r"chunked_ce\.py",             # the fused CE's own f32 accumulation
    r"metrics",                    # metric sums
    r"train/(tasks|step)\.py",     # loss reduction / metric assembly
    # graft-scope sentinels: param/grad-norm squares accumulate in f32 by
    # contract (telemetry/sentinels.py global_norm) — large bf16 param
    # leaves upcast once per step inside the compiled step
    r"telemetry/sentinels\.py",
    r"ops/attention\.py",          # deliberate f32 softmax (commented)
    # flax layers under the mixed-precision policy: f32 master params are
    # cast to bf16 compute, so AD emits a bf16->f32 convert per kernel
    # GRADIENT (master-weight accumulation), and LayerNorm statistics
    # upcast inside the module __call__ — both attributed by jax's source
    # summary to the CALLER line in models/, not the flax frame
    r"models/\S+\.py:\d+ \(__call__\)",
)

# arrays smaller than this are metric/statistic sums, not activations —
# 64k elements is far above any scalar bookkeeping and far below the
# smallest per-chip activation at bench scale (16 x 1024 x 768 = 12.6M)
DEFAULT_UPCAST_MIN_ELEMENTS = 1 << 16

DEFAULT_REPLICATED_MIN_BYTES = 1 << 20  # 1 MB

# XLA declines to alias tiny donated buffers (copying a bias is cheaper
# than constraining the schedule) — that is backend policy, not a dropped
# donation. 64 KB keeps every real param/optimizer leaf (MBs at flagship
# scale) in scope while ignoring bias/scale/scalar noise.
DEFAULT_DONATION_MIN_BYTES = 1 << 16


def _jaxpr_types():
    try:
        from jax.extend import core as jex_core

        return (jex_core.Jaxpr, jex_core.ClosedJaxpr)
    except Exception:
        import jax

        return (jax.core.Jaxpr, jax.core.ClosedJaxpr)


def iter_eqns(jaxpr) -> Iterable[Any]:
    """Every equation of a (closed) jaxpr, recursing into sub-jaxprs
    (pjit/scan/while/cond/custom_vjp/shard_map bodies)."""
    types = _jaxpr_types()
    inner = getattr(jaxpr, "jaxpr", jaxpr)  # ClosedJaxpr -> Jaxpr
    for eqn in inner.eqns:
        yield eqn
        for value in eqn.params.values():
            for sub in (
                value if isinstance(value, (list, tuple)) else (value,)
            ):
                if isinstance(sub, types):
                    yield from iter_eqns(sub)


def _summarize_source(eqn) -> str:
    try:
        from jax._src import source_info_util

        return source_info_util.summarize(eqn.source_info)
    except Exception:
        return "<unknown>"


def lint_dtype_promotions(
    jaxpr,
    allowlist: Sequence[str] = DEFAULT_UPCAST_ALLOWLIST,
    min_elements: int = DEFAULT_UPCAST_MIN_ELEMENTS,
    config: Optional[str] = None,
) -> List[Finding]:
    """Flag large off-allowlist bf16→f32 converts anywhere in ``jaxpr``."""
    import jax.numpy as jnp

    patterns = [re.compile(p) for p in allowlist]
    findings: List[Finding] = []
    for eqn in iter_eqns(jaxpr):
        if eqn.primitive.name != "convert_element_type":
            continue
        if eqn.params.get("new_dtype") != jnp.float32:
            continue
        aval = getattr(eqn.invars[0], "aval", None)
        if aval is None or getattr(aval, "dtype", None) != jnp.bfloat16:
            continue
        size = math.prod(getattr(aval, "shape", ()) or (1,))
        if size < min_elements:
            continue
        source = _summarize_source(eqn)
        if any(p.search(source) for p in patterns):
            continue
        findings.append(Finding(
            rule="bf16-upcast",
            where=source,
            message=(
                f"bf16->f32 convert of shape {tuple(aval.shape)} "
                f"({size} elements) outside the f32-island allowlist — "
                f"if deliberate, extend the allowlist with a why"
            ),
            config=config,
        ))
    return findings


def _leaf_path_str(path) -> str:
    from distributed_pytorch_example_tpu.parallel.api import _path_str

    return _path_str(path)


def lint_replicated_params(
    params: Any,
    partitioner,
    min_bytes: int = DEFAULT_REPLICATED_MIN_BYTES,
    config: Optional[str] = None,
    path_prefix: str = "",
) -> List[Finding]:
    """Flag large fully-replicated params that ``partitioner`` would shard.

    ``params`` is a COMMITTED (placed) param tree; ``partitioner`` is the
    reference ruleset declaring intent. A leaf is a violation when it is
    at least ``min_bytes``, its committed sharding is fully replicated,
    and the rules map it to a spec that actually spans a >1-size mesh
    axis (rules landing on size-1 axes are vacuously replicated).

    ``path_prefix`` prepends a tree location to every leaf path before
    the rules are consulted — pass ``"opt_state"`` to run the rule over
    optimizer-state trees, where ``Partitioner.spec_for`` additionally
    applies the ZeRO-1 overlay (``parallel/api.py _OPT_STATE_RE``): a
    large replicated Adam moment is then judged against the OVERLAID
    spec, so opt shards the rules would dp-shard get flagged too.
    Leaves the overlay's ``opt_shard_min_size`` floor keeps replicated
    (strictly below the floor) resolve to a span of 1 and stay clean.
    """
    import jax

    mesh = partitioner.mesh
    findings: List[Finding] = []
    leaves = jax.tree_util.tree_flatten_with_path(params)[0]
    for path, leaf in leaves:
        shape = tuple(getattr(leaf, "shape", ()) or ())
        nbytes = getattr(leaf, "size", 0) * getattr(
            leaf.dtype, "itemsize", 0
        ) if hasattr(leaf, "dtype") else 0
        if nbytes < min_bytes:
            continue
        sharding = getattr(leaf, "sharding", None)
        if sharding is None or not sharding.is_fully_replicated:
            continue
        path_str = _leaf_path_str(path)
        if path_prefix:
            path_str = f"{path_prefix}/{path_str}"
        spec = partitioner.spec_for(path_str, shape)
        span = 1
        for entry in spec:
            if entry is None:
                continue
            axes = entry if isinstance(entry, tuple) else (entry,)
            span *= math.prod(mesh.shape[a] for a in axes)
        if span <= 1:
            continue  # the rules would replicate it too (or axis is 1)
        findings.append(Finding(
            rule="replicated-large-param",
            where=path_str,
            message=(
                f"{nbytes / 2**20:.1f} MB param is fully replicated but "
                f"partition rules map it to {spec} ({span}-way) — "
                f"replication wastes {(span - 1) * nbytes / 2**20:.1f} MB "
                f"per {span} chips and implies a silent all-gather"
            ),
            config=config,
        ))
    return findings


_ALIAS_ENTRY_RE = re.compile(
    r"\((\d+),\s*\{[^}]*\},\s*(?:may|must)-alias\)"
)


def aliased_parameter_numbers(hlo_text: str) -> Optional[set]:
    """HLO parameter numbers aliased to outputs, from the module header.

    Returns None when the module carries no ``input_output_alias`` field
    at all (distinct from an empty alias set: None means the compiler
    recorded nothing, so every donation was dropped).
    """
    for line in hlo_text.splitlines():
        if line.startswith("HloModule"):
            if "input_output_alias=" not in line:
                return None
            return {int(m) for m in _ALIAS_ENTRY_RE.findall(line)}
    return None


def lint_dropped_donation(
    lowered, compiled, config: Optional[str] = None,
    min_bytes: int = DEFAULT_DONATION_MIN_BYTES,
) -> List[Finding]:
    """Flag donated arguments the executable did not alias to any output.

    Compares the jit's declared donations (``lowered.args_info``) against
    the compiled module's ``input_output_alias`` map. Arguments the jit
    PRUNED (unused) are skipped — an unused donated arg is dead weight,
    not a doubled live buffer — as are leaves under ``min_bytes`` (XLA
    deliberately copies tiny buffers instead of aliasing them).
    """
    import math as _math

    import jax

    def _nbytes(info) -> int:
        shape = tuple(getattr(info, "shape", ()) or ())
        itemsize = getattr(getattr(info, "dtype", None), "itemsize", 4)
        return _math.prod(shape or (1,)) * itemsize

    flat = jax.tree_util.tree_flatten_with_path(lowered.args_info)[0]
    donated = [
        (idx, _leaf_path_str(path))
        for idx, (path, info) in enumerate(flat)
        if getattr(info, "donated", False) and _nbytes(info) >= min_bytes
    ]
    if not donated:
        return []
    executable = getattr(compiled, "_executable", None)
    kept = getattr(executable, "_kept_var_idx", None)
    kept_order = sorted(kept) if kept is not None else None
    aliased = aliased_parameter_numbers(compiled.as_text())
    findings: List[Finding] = []
    for flat_idx, path_str in donated:
        if kept_order is not None:
            if flat_idx not in kept:
                continue  # pruned: never a live buffer
            param_number = kept_order.index(flat_idx)
        else:
            param_number = flat_idx
        if aliased is None or param_number not in aliased:
            info = flat[flat_idx][1]
            shape = tuple(getattr(info, "shape", ()) or ())
            findings.append(Finding(
                rule="dropped-donation",
                where=path_str,
                message=(
                    f"donated argument {shape} was not aliased by the "
                    f"compiled executable — the update materializes next "
                    f"to the old buffer (2x memory for this leaf)"
                ),
                config=config,
            ))
    return findings


def case_jaxpr(case):
    """The (closed) jaxpr of a DryrunCase's train step, traced (not run).

    Requires ``case.trainer.init`` to have happened (``compile_case`` does
    it); traces under the case's mesh so mesh-aware ops resolve.
    """
    import jax

    trainer = case.trainer
    assert trainer.state is not None, "init the case first (compile_case)"
    batch = next(iter(case.loader))
    with case.mesh:
        return jax.make_jaxpr(
            lambda state, b: trainer.train_step(state, b)
        )(trainer.state, batch)


def flagship_numerics_jaxpr():
    """Traced jaxpr of a bf16 flagship-shaped train step for numerics lints.

    The dryrun configs run f32 tiny models (their job is collectives);
    the bf16-upcast lint needs a bf16 path with activations big enough to
    clear ``DEFAULT_UPCAST_MIN_ELEMENTS`` — a scaled-down single-device
    GPT-2 with the fused-CE loss (the ``__graft_entry__.entry`` program's
    shape class) traced in seconds.
    """
    import jax
    import jax.numpy as jnp
    import optax

    from distributed_pytorch_example_tpu.models.gpt2 import GPT2
    from distributed_pytorch_example_tpu.train.step import build_train_step
    from distributed_pytorch_example_tpu.train.state import TrainState
    from distributed_pytorch_example_tpu.train.tasks import CausalLMTask

    model = GPT2(
        vocab_size=512, max_len=128, model_dim=256, num_layers=2,
        num_heads=4, mlp_dim=512, dtype=jnp.bfloat16,
        logits_mode="hidden",
    )
    optimizer = optax.adam(1e-3)
    tokens = jnp.zeros((8, 128), jnp.int32)

    def init_fn(rng):
        params = model.init(rng, tokens, train=False)["params"]
        return TrainState(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=optimizer.init(params),
            model_state={},
            rng=jax.random.key(1),
        )

    state = jax.eval_shape(init_fn, jax.random.key(0))
    step = build_train_step(model, CausalLMTask(), optimizer)
    return jax.make_jaxpr(lambda s, b: step(s, b))(
        state, {"tokens": tokens}
    )

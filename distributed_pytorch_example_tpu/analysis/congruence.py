"""Static SPMD-hang detection: collective congruence across cond branches.

An SPMD program is ONE program replicated on every chip; XLA collectives
are rendezvous points where every member of the group must arrive with
the same operation in the same order. The classic way to break that is a
``lax.cond``/``switch`` inside a ``shard_map`` manual region whose
predicate VARIES across devices: chips that take the true branch issue
(say) a ``psum`` the false-branch chips never reach, and the job hangs —
on real TPU only, silently, at whatever step first splits the predicate.
graft-armor (r5) can only catch this after the fact as a barrier timeout;
this module turns it into a static finding on the traced jaxpr, before
anything compiles.

The check is deliberately sharper than "branches must be identical":

1. Inside every ``shard_map`` region, track a per-value **variance taint**
   — the set of mesh axes along which a value may differ between chips.
   Region inputs are tainted by the axes they're split over
   (``in_names``), ``axis_index(a)`` introduces taint ``{a}``, ``psum``/
   ``all_gather`` over an axis REMOVE that axis (their result is
   identical across the group), and everything else unions its operands.
2. For each ``cond`` in the region, extract each branch's **collective
   sequence** — the ordered list of (collective kind, axis names) the
   branch would execute, nested control flow included.
3. Branches with different sequences are a finding. They are a **hazard**
   (would hang) only when some differing collective spans an axis the
   predicate is tainted by: a collective group along axis B only contains
   chips that agree on every other coordinate, so if the predicate only
   varies along A ∉ B, all members of any B-group pick the same branch
   and the mismatch is benign (this is exactly the shipped
   ``predicate_head`` pattern: the bad-step predicate varies on ``pipe``
   while its in-branch collectives run over ``data``). Benign mismatches
   are still reported as notes — they're one refactor away from a hang.

A uniform predicate (empty taint — e.g. a host scalar or a fully-psummed
loss) can never split the mesh, so its mismatches are all benign.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Sequence, Tuple

from distributed_pytorch_example_tpu.analysis.shardflow import (
    EXPLICIT_COLLECTIVES,
    _sub_jaxpr,
    _summarize,
)

# ordered (collective kind, axes) pairs — the rendezvous fingerprint
CollectiveSeq = Tuple[Tuple[str, Tuple[str, ...]], ...]

# collectives whose output is identical across the spanned axes (the
# rendezvous SYNCHRONIZES the value, clearing its variance taint there)
_TAINT_CLEARING = {"psum", "all_gather", "pbroadcast"}


def _eqn_axes(eqn) -> Tuple[str, ...]:
    axes = eqn.params.get("axes", eqn.params.get("axis_name", ()))
    if isinstance(axes, str):
        axes = (axes,)
    return tuple(str(a) for a in axes)


@dataclass
class CongruenceFinding:
    hazard: bool                      # True: would deadlock on real TPU
    op: str                           # "cond"
    path: str                         # name stack of the cond
    source: str                       # python file:line
    predicate_axes: Tuple[str, ...]   # axes the predicate varies along
    mismatch_axes: Tuple[str, ...]    # axes of the differing collectives
    branch_seqs: Tuple[CollectiveSeq, ...]

    def render(self) -> str:
        seqs = " vs ".join(
            "[" + ",".join(f"{k}@{'/'.join(a)}" for k, a in s) + "]"
            for s in self.branch_seqs
        )
        level = "HAZARD" if self.hazard else "benign"
        return (
            f"[congruence:{level}] {self.op} at {self.path or '<top>'} "
            f"({self.source}): branch collective sequences differ {seqs}; "
            f"predicate varies on {'/'.join(self.predicate_axes) or '<uniform>'}"
            f", mismatch spans {'/'.join(self.mismatch_axes) or '<none>'}"
        )

    def to_json(self) -> Dict[str, object]:
        return {
            "hazard": self.hazard, "op": self.op, "path": self.path,
            "source": self.source,
            "predicate_axes": list(self.predicate_axes),
            "mismatch_axes": list(self.mismatch_axes),
            "branch_seqs": [
                [[k, list(a)] for k, a in s] for s in self.branch_seqs
            ],
        }


@dataclass
class CongruenceReport:
    findings: List[CongruenceFinding] = field(default_factory=list)
    regions: int = 0                  # shard_map regions inspected
    conds: int = 0                    # conds inside manual regions

    @property
    def hazards(self) -> List[CongruenceFinding]:
        return [f for f in self.findings if f.hazard]

    @property
    def ok(self) -> bool:
        return not self.hazards


Taint = FrozenSet[str]
_EMPTY: Taint = frozenset()


def _collective_seq(jaxpr) -> CollectiveSeq:
    """Ordered collectives a body executes (loops/branches flattened).

    ``scan``/``while`` bodies are included once — the sequence compares
    STRUCTURE, not trip counts, and a collective inside a loop is a
    rendezvous regardless of iteration count. Nested ``cond`` branches
    are concatenated in branch order; a nested mismatch is caught by its
    own finding, so the flattening here only needs to be deterministic.
    """
    out: List[Tuple[str, Tuple[str, ...]]] = []
    for eqn in jaxpr.eqns:
        prim = eqn.primitive.name
        if prim in EXPLICIT_COLLECTIVES:
            out.append((EXPLICIT_COLLECTIVES[prim], _eqn_axes(eqn)))
            continue
        for value in eqn.params.values():
            sub = _sub_jaxpr(value)
            if sub is not None:
                out.extend(_collective_seq(sub[0]))
            elif isinstance(value, (tuple, list)):
                for item in value:
                    sub = _sub_jaxpr(item)
                    if sub is not None:
                        out.extend(_collective_seq(sub[0]))
    return tuple(out)


class _TaintWalk:
    """Variance-taint propagation + cond congruence inside one region."""

    def __init__(self, report: CongruenceReport):
        self.report = report

    def run(self, jaxpr, in_taints: Sequence[Taint]):
        env: Dict[object, Taint] = {}
        for var, taint in zip(jaxpr.invars, in_taints):
            env[var] = taint
        for var in jaxpr.constvars:
            env[var] = _EMPTY

        def read(v) -> Taint:
            if hasattr(v, "val"):
                return _EMPTY
            return env.get(v, _EMPTY)

        for eqn in jaxpr.eqns:
            prim = eqn.primitive.name
            in_taint = frozenset().union(*[read(v) for v in eqn.invars]) \
                if eqn.invars else _EMPTY

            if prim == "axis_index":
                out_taint = in_taint | frozenset(_eqn_axes(eqn))
            elif prim in _TAINT_CLEARING:
                out_taint = in_taint - frozenset(_eqn_axes(eqn))
            elif prim == "cond":
                self._check_cond(eqn, read)
                # branch outputs vary wherever predicate or operands vary
                out_taint = in_taint
                for br in eqn.params.get("branches", ()):
                    sub = _sub_jaxpr(br)
                    if sub is not None:
                        self.run(sub[0], [read(v) for v in eqn.invars[1:]])
            elif prim in ("scan", "while", "pjit", "closed_call",
                          "custom_vjp_call_jaxpr", "custom_jvp_call",
                          "custom_vjp_call", "remat", "remat2"):
                for key in ("jaxpr", "body_jaxpr", "cond_jaxpr",
                            "fun_jaxpr", "call_jaxpr"):
                    sub = _sub_jaxpr(eqn.params.get(key))
                    if sub is not None:
                        body = sub[0]
                        n = len(body.invars)
                        taints = ([read(v) for v in eqn.invars] + [in_taint] * n)[:n]
                        self.run(body, taints)
                out_taint = in_taint
            else:
                out_taint = in_taint

            for v in eqn.outvars:
                env[v] = out_taint

    def _check_cond(self, eqn, read):
        self.report.conds += 1
        branches = eqn.params.get("branches", ())
        seqs: List[CollectiveSeq] = []
        for br in branches:
            sub = _sub_jaxpr(br)
            seqs.append(_collective_seq(sub[0]) if sub is not None else ())
        if len(set(seqs)) <= 1:
            return  # congruent: every chip runs the same rendezvous list

        # axes of collectives NOT common to all branches
        common = set(seqs[0])
        for s in seqs[1:]:
            common &= set(s)
        mismatch_axes: List[str] = []
        for s in seqs:
            for item in s:
                if item not in common:
                    mismatch_axes.extend(
                        a for a in item[1] if a not in mismatch_axes
                    )

        pred_taint = read(eqn.invars[0])
        hazard = bool(pred_taint & set(mismatch_axes))
        stack, src = _summarize(eqn)
        self.report.findings.append(CongruenceFinding(
            hazard=hazard, op=eqn.primitive.name, path=stack, source=src,
            predicate_axes=tuple(sorted(pred_taint)),
            mismatch_axes=tuple(mismatch_axes),
            branch_seqs=tuple(seqs),
        ))


def _find_shard_maps(jaxpr, out: List):
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "shard_map":
            out.append(eqn)
            continue  # nested shard_map inside manual region: rare, skip
        for value in eqn.params.values():
            sub = _sub_jaxpr(value)
            if sub is not None:
                _find_shard_maps(sub[0], out)
            elif isinstance(value, (tuple, list)):
                for item in value:
                    sub = _sub_jaxpr(item)
                    if sub is not None:
                        _find_shard_maps(sub[0], out)
    return out


def check_congruence(closed_jaxpr) -> CongruenceReport:
    """Audit every shard_map region of a traced jaxpr for branch-split
    collective sequences. Pure jaxpr walk — no compile, no backend."""
    report = CongruenceReport()
    jaxpr = getattr(closed_jaxpr, "jaxpr", closed_jaxpr)
    for eqn in _find_shard_maps(jaxpr, []):
        report.regions += 1
        sub = _sub_jaxpr(eqn.params.get("jaxpr"))
        if sub is None:
            continue
        body = sub[0]
        in_names = eqn.params.get("in_names", ())
        taints: List[Taint] = []
        for i, var in enumerate(body.invars):
            names = in_names[i] if i < len(in_names) else {}
            axes: List[str] = []
            for dim_axes in (names or {}).values():
                ax = dim_axes if isinstance(dim_axes, (tuple, list)) \
                    else (dim_axes,)
                axes.extend(str(a) for a in ax)
            taints.append(frozenset(axes))
        _TaintWalk(report).run(body, taints)
    return report


def congruence_for_case(case) -> CongruenceReport:
    """Trace a DryrunCase's train step and audit it. Trace-only, so this
    runs even for configs the backend cannot SPMD-partition (the pipe
    schedules on CPU) — exactly the configs whose hang class this check
    exists for."""
    import jax

    trainer = case.trainer
    if trainer.state is None:
        with case.mesh:
            trainer.init(next(iter(case.loader))["tokens"])
    batch = next(iter(case.loader))
    with case.mesh:
        jaxpr = jax.make_jaxpr(
            lambda s, b: trainer.train_step(s, b)
        )(trainer.state, batch)
    return check_congruence(jaxpr)

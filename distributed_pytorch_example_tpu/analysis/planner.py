"""graft-plan: static auto-parallelism planner over :class:`PlanSpec`.

Generalizes the cross-replica weight-update sharding search of Xu et al.
(arxiv 2004.13336) to the full (data, fsdp, tensor, pipe, zero1,
grad_accum, wire) space: enumerate the legal plans for a topology, score
every one WITHOUT compiling or executing, and hand the ranked list to
``--auto-mesh`` (train.py / bench.py / serve.py) or the
``scripts/plan_search.py`` report.

The three-tier oracle (cheapest first, each tier refining the last):

1. **shardflow bytes** — trace the train/serve program once per plan
   (``jax.make_jaxpr`` over ShapeDtypeStructs; ``train.step.abstract_state``
   keeps even state init off the backend), walk the jaxpr with
   ``analysis/shardflow.py``, and push every predicted collective through a
   latency/bandwidth :class:`LinkModel`. Wire-compressed plans are priced
   automatically: the traced all_to_all/all_gather avals carry the int8
   payload dtype, so compressed bytes < fp32 bytes by construction.
2. **envelope HBM** — ``FlowReport.peak_bytes`` vs the ``--hbm-limit``
   would-OOM pre-gate (``analysis/envelope.py``); infeasible plans are
   pruned before anything would ever compile.
3. **compiled-cost records** — when a plan coincides with a committed
   ``analysis/comm_budgets.json`` entry (compiled-HLO collective bytes,
   incl. the ``parse_collective_dtypes`` payload breakdown), the measured
   bytes replace the traced estimate in the ranking cost.

Zero XLA compiles for uncached plans is a hard contract: everything here
is ``eval_shape`` + ``make_jaxpr`` + pure-Python jaxpr walks.
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

from distributed_pytorch_example_tpu.analysis import envelope as env_mod
from distributed_pytorch_example_tpu.analysis import shardflow
from distributed_pytorch_example_tpu.parallel.plan import PlanSpec
from distributed_pytorch_example_tpu.parallel.wire import WireConfig
from distributed_pytorch_example_tpu.runtime.mesh import MeshSpec, make_mesh

_MESH_AXES = ("data", "fsdp", "tensor", "sequence", "expert", "pipe")


# -- tier-1 cost model -----------------------------------------------------


@dataclasses.dataclass(frozen=True)
class LinkModel:
    """Ring latency/bandwidth model for predicted collectives.

    Deliberately simple — the planner ranks plans against EACH OTHER on one
    homogeneous interconnect, so only relative cost matters. Each event
    costs a fixed launch latency plus its per-device ring traffic
    (:func:`event_wire_bytes`) over the link bandwidth; plans with many
    small per-leaf collectives pay the latency term, plans with fat
    payloads pay the bandwidth term.
    """

    latency_us: float = 1.0
    bandwidth_gbps: float = 100.0

    def event_ms(self, wire_bytes: float) -> float:
        if wire_bytes <= 0:
            return 0.0
        return (
            self.latency_us * 1e-3
            + (wire_bytes / 1e9) / self.bandwidth_gbps * 1e3
        )


# ring passes over the payload: an all-reduce moves it twice
# (reduce-scatter + all-gather decomposition), everything else once
_PASSES = {"all-reduce": 2.0}


def event_wire_bytes(event, span: int, total_devices: int) -> float:
    """Per-device ring traffic (bytes) a predicted collective moves.

    Normalizes shardflow's result-buffer byte conventions to the physical
    payload: explicit events carry ``result_aval_bytes * total_devices``
    (the compiled-budget proxy), where a reduce-scatter's result is the
    1/span OUTPUT shard — so its payload is scaled back up — while
    inferred (GSPMD-propagation) events carry the global result bytes
    directly. Each ring pass moves ``(span-1)/span`` of the payload per
    device. This is what makes the oracle monotone in payload dtype: an
    int8 all_to_all genuinely scores ~4x fewer wire bytes than the fp32
    reduce-scatter of the same gradient.
    """
    if span <= 1:
        return 0.0
    if event.kind == "explicit":
        payload = event.bytes / max(total_devices, 1)
        if event.collective == "reduce-scatter":
            payload *= span
    else:
        payload = float(event.bytes)
    passes = _PASSES.get(event.collective, 1.0)
    return passes * (span - 1) / span * payload


def _span(axes: Tuple[str, ...], mesh_shape: Dict[str, int]) -> int:
    return math.prod(mesh_shape.get(a, 1) for a in axes or ())


# -- plan space ------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ProgramInfo:
    """What legality needs to know about the program being planned."""

    global_batch: int
    num_heads: int = 0
    num_layers: int = 0
    pipelineable: bool = False
    max_param_elems: int = 0  # largest leaf, for the wire floor
    kind: str = "image"  # "image" | "lm"


def legality(plan: PlanSpec, info: ProgramInfo, n_devices: int) -> Optional[str]:
    """None if the plan is legal on this topology, else the reason it isn't."""
    try:
        spec = plan.mesh.resolve(n_devices)
    except ValueError as exc:
        return str(exc)
    dp = spec.data * spec.fsdp
    if info.global_batch % max(dp, 1):
        return (
            f"global batch {info.global_batch} not divisible by the "
            f"data span {dp}"
        )
    if plan.grad_accum > 1 and (info.global_batch // max(dp, 1)) % plan.grad_accum:
        return (
            f"per-shard batch {info.global_batch // dp} not divisible by "
            f"grad_accum {plan.grad_accum}"
        )
    if spec.tensor > 1:
        if plan.family != "transformer":
            return f"tensor axis needs the transformer rule family, got {plan.family!r}"
        if info.num_heads == 0 or info.num_heads % spec.tensor:
            return (
                f"tensor span {spec.tensor} does not divide "
                f"{info.num_heads} attention heads"
            )
    if spec.pipe > 1:
        if not info.pipelineable:
            return "model has no pipeline axis"
        if info.num_layers % spec.pipe:
            return (
                f"pipe span {spec.pipe} leaves {info.num_layers} layers "
                f"unbalanced across stages"
            )
    if plan.zero1 and dp <= 1:
        return "zero1 is a no-op without a data span > 1"
    if plan.wire is not None and plan.wire.compress != "none":
        if dp <= 1:
            return "wire compression is a no-op without a data span > 1"
        if info.max_param_elems and info.max_param_elems < plan.wire.min_size:
            return (
                f"wire floor: largest param leaf ({info.max_param_elems} "
                f"elems) is below min_size {plan.wire.min_size}"
            )
    if _plan_bucketed(plan) and dp <= 1:
        return "bucketed overlap is a no-op without a data span > 1"
    return None


def _plan_bucketed(plan: PlanSpec) -> bool:
    """Whether the plan's gradient sync runs the fused bucket schedule."""
    return plan.bucket_bytes > 0 or (
        plan.wire is not None and plan.wire.bucketed
    )


def _scheduled_hidden_frac(plan: PlanSpec, data_wire_bytes: float) -> float:
    """Scheduler-level hidden fraction of the bucketed grad sync.

    Mirrors ``telemetry/overlap.scheduled_overlap`` without needing the
    leaf tree: K roughly-equal buckets hide the first K-1 behind
    remaining backward compute, so the hidden fraction is (K-1)/K with
    K estimated from the traced data-axis wire bytes over the per-bucket
    wire payload (the fp32 ``bucket_bytes`` target scaled by the wire
    config's compression factor). Conservative: capped at 0.9 — the
    link model should never score comm as entirely free.
    """
    from distributed_pytorch_example_tpu.parallel import wire as wirelib

    target = plan.bucket_bytes or (
        plan.wire.bucket_bytes if plan.wire is not None else 0
    ) or wirelib.DEFAULT_BUCKET_BYTES
    config = plan.wire or wirelib.WireConfig()
    # fp32 target -> wire-byte target under the payload compression
    per_elem = 1.0 + 2.0 / config.block_size if (
        config.compress == "int8-block"
    ) else 4.0
    bucket_wire = max(target * per_elem / 4.0, 1.0)
    k = max(1, int(round(data_wire_bytes / bucket_wire)))
    return min(0.9, (k - 1) / k)


def _axis_splits(n: int, k: int):
    """All ordered factorizations of ``n`` into ``k`` positive factors."""
    if k == 1:
        yield (n,)
        return
    for d in range(1, n + 1):
        if n % d == 0:
            for rest in _axis_splits(n // d, k - 1):
                yield (d,) + rest


def enumerate_plans(
    n_devices: int,
    info: ProgramInfo,
    families: Sequence[str] = ("data", "fsdp", "transformer"),
    zero1_options: Sequence[bool] = (False, True),
    wire_options: Sequence[Optional[WireConfig]] = (None,),
    grad_accum_options: Sequence[int] = (1,),
    opt_shard_min_size: Optional[int] = None,
    allow_pipe: bool = True,
) -> List[PlanSpec]:
    """The legal PlanSpecs for this topology, deduped by plan name.

    Enumeration is per-family so degenerate meshes never arise (a "data"
    plan puts every device on the data axis; "fsdp" requires an fsdp span
    > 1; "transformer" requires a tensor or pipe span > 1 — the pure-DP
    transformer mesh is identical to the "data" plan and is skipped).
    ZeRO-1 / wire / grad-accum knobs apply where the manual data-sync path
    supports them (no pipe composition — the dryrun table has no such
    config and the planner will not invent one).
    """
    min_kw = (
        {} if opt_shard_min_size is None
        else {"opt_shard_min_size": opt_shard_min_size}
    )
    plans: List[PlanSpec] = []
    seen = set()

    def add(plan: PlanSpec) -> None:
        name = plan.name()
        if name in seen or legality(plan, info, n_devices) is not None:
            return
        seen.add(name)
        plans.append(plan)

    def knob_grid(mesh: MeshSpec, family: str, fsdp_rest: bool = False):
        pipe_free = mesh.pipe == 1
        for zero1 in zero1_options if pipe_free else (False,):
            for wire in wire_options if pipe_free else (None,):
                for ga in grad_accum_options if pipe_free else (1,):
                    add(PlanSpec(
                        mesh=mesh, family=family, fsdp_rest=fsdp_rest,
                        zero1=zero1, wire=wire, grad_accum=ga,
                        schedule="gpipe" if mesh.pipe > 1 else None,
                        **min_kw,
                    ))

    if "data" in families:
        knob_grid(MeshSpec(data=n_devices), "data")
    if "fsdp" in families:
        for data, fs in _axis_splits(n_devices, 2):
            if fs > 1:
                # fsdp family: params born sharded — zero1/wire knobs do
                # not compose with the manual data-sync path here
                add(PlanSpec(mesh=MeshSpec(data=data, fsdp=fs), family="fsdp"))
    if "transformer" in families and info.kind == "lm":
        for data, tensor, pipe in _axis_splits(n_devices, 3):
            if tensor == 1 and pipe == 1:
                continue  # identical shardings to the "data" plan
            if pipe > 1 and (not allow_pipe or pipe < 2):
                continue
            knob_grid(
                MeshSpec(data=data, tensor=tensor, pipe=pipe), "transformer"
            )
    return plans


# -- scoring ---------------------------------------------------------------


@dataclasses.dataclass
class PlanScore:
    plan: PlanSpec
    program: str
    feasible: bool
    reason: str = ""
    tier: int = 1
    comm_ms: float = 0.0
    comm_bytes: int = 0
    bytes_by_collective: Dict[str, int] = dataclasses.field(default_factory=dict)
    predicted_peak_bytes: int = 0
    arg_bytes: int = 0
    cached_config: Optional[str] = None
    cached_comm_ms: Optional[float] = None
    overlap_hidden_frac: Optional[float] = None
    events_top: List[Dict[str, object]] = dataclasses.field(default_factory=list)

    def cost_ms(self) -> float:
        """Ranking cost: measured (tier 3) when cached, traced otherwise."""
        return self.cached_comm_ms if self.cached_comm_ms is not None else self.comm_ms

    def to_json(self) -> Dict[str, object]:
        return {
            "plan": self.plan.name(),
            "spec": self.plan.to_json(),
            "program": self.program,
            "feasible": self.feasible,
            "reason": self.reason,
            "tier": self.tier,
            "cost_ms": round(self.cost_ms(), 6),
            "comm_ms": round(self.comm_ms, 6),
            "comm_bytes": int(self.comm_bytes),
            "bytes_by_collective": {
                k: int(v) for k, v in sorted(self.bytes_by_collective.items())
            },
            "predicted_peak_bytes": int(self.predicted_peak_bytes),
            "arg_bytes": int(self.arg_bytes),
            "cached_config": self.cached_config,
            "cached_comm_ms": (
                None if self.cached_comm_ms is None
                else round(self.cached_comm_ms, 6)
            ),
            "overlap_hidden_frac": (
                None if self.overlap_hidden_frac is None
                else round(self.overlap_hidden_frac, 4)
            ),
            # named shardflow events behind the score — `plan_search --diff`
            # attributes ranking flips to these
            "events_top": list(self.events_top),
        }


def analytic_floors(
    plan: PlanSpec,
    n_devices: int,
    param_bytes: int = 0,
    global_batch: int = 0,
    seq_len: int = 0,
    model_dim: int = 0,
    num_layers: int = 0,
    dtype_bytes: int = 2,
) -> Dict[Tuple[str, ...], Tuple[str, float]]:
    """Analytic lower-bound wire bytes for collectives the trace can miss.

    The pipeline schedules run their stages inside a shard_map MANUAL
    region; GSPMD's inferred resharding events stop at that boundary, so
    shardflow sees the explicit stage-handoff ppermutes but NOT the
    data-axis gradient all-reduce or the per-layer Megatron activation
    all-reduces happening inside. Scoring such a trace at face value would
    rank a pipeline plan as near-free. These bounds are keyed by mesh
    axes; :func:`score_flow` charges each one ONLY when the traced flow
    shows zero traffic on those axes — visible traffic means the region
    was auto-partitioned and the real events are already priced.

    - data/fsdp: ring all-reduce of the gradients, ``2(dp-1)/dp`` x the
      param bytes (grads carry the param dtype).
    - tensor: the Megatron schedule's 2-forward + 2-backward activation
      all-reduces per layer over the local ``(B, S, D)`` block.
    """
    try:
        spec = plan.mesh.resolve(n_devices)
    except ValueError:
        return {}
    if spec.pipe <= 1:
        # no manual pipeline region in the program: GSPMD-inferred events
        # (auto plans) and explicit shard_map collectives (zero1/wire
        # plans) are both fully visible — the trace IS the schedule, and a
        # dtype-blind floor would overcharge compressed wire payloads
        return {}
    floors: Dict[Tuple[str, ...], Tuple[str, float]] = {}
    dp = spec.data * spec.fsdp
    if dp > 1 and param_bytes:
        floors[("data", "fsdp")] = (
            "all-reduce", 2.0 * (dp - 1) / dp * param_bytes,
        )
    if spec.tensor > 1 and global_batch and seq_len and model_dim and num_layers:
        local_act = (
            (global_batch // max(dp, 1)) * seq_len * model_dim * dtype_bytes
        )
        per_ar = 2.0 * (spec.tensor - 1) / spec.tensor * local_act
        floors[("tensor",)] = ("all-reduce", 4.0 * num_layers * per_ar)
    return floors


def score_flow(
    plan: PlanSpec,
    program: str,
    flow,
    mesh_shape: Dict[str, int],
    link: Optional[LinkModel] = None,
    hbm_limit: Optional[int] = None,
    cached: Optional[Tuple[str, Dict[str, object]]] = None,
    floors: Optional[Dict[Tuple[str, ...], Tuple[str, float]]] = None,
) -> PlanScore:
    """Tiers 1–3 over one traced program's FlowReport."""
    link = link or LinkModel()
    score = PlanScore(
        plan=plan, program=program, feasible=True,
        predicted_peak_bytes=flow.peak_bytes, arg_bytes=flow.arg_bytes,
    )
    # tier 2: would-OOM pre-gate — infeasible plans never reach a compiler
    gate = env_mod.gate_envelope(plan.name(), flow.peak_bytes, hbm_limit)
    if gate is not None:
        score.feasible = False
        score.reason = gate.detail
        score.tier = 2
        return score
    # tier 1: traced collective wire bytes through the link model
    total_devices = math.prod(mesh_shape.values()) or 1
    axis_bytes: Dict[str, float] = {}
    grad_sync_ms = 0.0  # event_ms on the data axis (the bucketable sync)
    for e in flow.comm_events():
        span = _span(e.axes, mesh_shape)
        wb = event_wire_bytes(e, span, total_devices)
        if span > 1:
            for a in e.axes:
                axis_bytes[str(a)] = axis_bytes.get(str(a), 0.0) + wb
        if wb <= 0:
            continue
        score.bytes_by_collective[e.collective] = int(
            score.bytes_by_collective.get(e.collective, 0) + wb
        )
        score.comm_bytes += int(wb)
        score.comm_ms += link.event_ms(wb)
        if "data" in (str(a) for a in e.axes):
            grad_sync_ms += link.event_ms(wb)
    # bucketed plans hide (K-1)/K of the grad-sync wire time behind the
    # backward segments still computing when early buckets issue
    # (telemetry/overlap.py scheduled_overlap) — discount the data-axis
    # comm so --auto-mesh scores overlap instead of treating bucketed and
    # inline syncs as equal-cost
    if _plan_bucketed(plan) and grad_sync_ms > 0:
        hidden = _scheduled_hidden_frac(plan, axis_bytes.get("data", 0.0))
        score.overlap_hidden_frac = hidden
        score.comm_ms -= hidden * grad_sync_ms
    score.events_top = [
        e.to_json()
        for e in sorted(
            flow.comm_events(),
            key=lambda e: -event_wire_bytes(
                e, _span(e.axes, mesh_shape), total_devices
            ),
        )[:5]
        if _span(e.axes, mesh_shape) > 1
    ]
    # analytic floors for axes whose collectives the trace could not see:
    # charge the SHORTFALL between the bound and the traffic actually
    # observed on those axes, so fully-visible (auto-partitioned) traces
    # are never double-charged
    for axes_key, (kind, bound) in (floors or {}).items():
        observed = sum(axis_bytes.get(a, 0.0) for a in axes_key)
        wb = max(0.0, bound - observed)
        if wb <= 0:
            continue
        score.bytes_by_collective[kind] = int(
            score.bytes_by_collective.get(kind, 0) + wb
        )
        score.comm_bytes += int(wb)
        score.comm_ms += link.event_ms(wb)
        score.events_top.append({
            "kind": "analytic-floor",
            "collective": kind,
            "axes": list(axes_key),
            "bytes": int(wb),
            "path": "analytic lower bound (manual-region collectives "
                    "invisible to shardflow)",
        })
    score.tier = 2  # envelope consulted and passed
    # tier 3: committed compiled-HLO bytes override the traced estimate
    if cached is not None:
        name, record = cached
        total_span = math.prod(v for v in mesh_shape.values() if v > 1) or 1
        ring = (total_span - 1) / total_span if total_span > 1 else 0.0
        measured = 0.0
        for kind, entry in (record.get("collectives") or {}).items():
            wb = _PASSES.get(kind, 1.0) * ring * int(entry.get("bytes", 0))
            count = max(int(entry.get("count", 1)), 1)
            measured += count * link.latency_us * 1e-3 + (
                link.event_ms(wb) - link.latency_us * 1e-3
            )
        score.cached_config = name
        score.cached_comm_ms = measured
        score.tier = 3
    return score


def match_budget_record(
    plan: PlanSpec,
    n_devices: int,
    budgets: Optional[Dict[str, object]],
    global_batch: Optional[int] = None,
) -> Optional[Tuple[str, Dict[str, object]]]:
    """The committed comm-budget record this plan coincides with, if any.

    A dryrun budget entry matches when its recorded mesh equals the plan's
    resolved mesh, the zero1/wire knobs agree, AND (when both sides know
    it) the global batch matches — the compiled bytes then describe the
    same collective schedule the plan would compile to. Records from a
    different program scale must NOT override the traced estimate.
    """
    if not budgets:
        return None
    try:
        spec = plan.mesh.resolve(n_devices)
    except ValueError:
        return None
    sizes = {a: getattr(spec, a) for a in _MESH_AXES}
    wire_on = plan.wire is not None and plan.wire.compress != "none"
    for name, record in (budgets.get("configs") or {}).items():
        mesh = record.get("mesh")
        if not isinstance(mesh, dict) or {
            a: int(mesh.get(a, 1)) for a in _MESH_AXES
        } != sizes:
            continue
        rec_zero1 = "zero1" in name
        rec_wire = record.get("wire") is not None or "wire" in name
        if rec_zero1 != plan.zero1 or rec_wire != wire_on:
            continue
        # bucketed and inline syncs compile different collective schedules
        # (fused per-bucket vs per-leaf) — never cross-match them
        if ("overlap" in name.split("+")) != _plan_bucketed(plan):
            continue
        rec_gb = record.get("global_batch")
        if (
            rec_gb is not None and global_batch is not None
            and int(rec_gb) != int(global_batch)
        ):
            continue
        return name, record
    return None


# -- per-plan tracing (zero compiles) --------------------------------------


def _unused_axes(partitioner, state_shapes) -> List[str]:
    """Mesh axes sized > 1 that no state spec or batch axis touches.

    A plan that pays for an axis no sharding uses is strictly dominated
    (same per-chip compute as the plan without the axis, plus reshards) —
    prune it before tracing. ``sequence``/``expert`` are exempt: models
    use them via internal constraints invisible to the state tree.
    """
    import jax

    mesh = partitioner.mesh
    used = set()
    batch_axes = partitioner.batch_spec()[0]
    if isinstance(batch_axes, str):
        batch_axes = (batch_axes,)
    used.update(batch_axes or ())
    from jax.sharding import PartitionSpec as P

    for spec in jax.tree_util.tree_leaves(
        partitioner.tree_specs(state_shapes),
        is_leaf=lambda s: isinstance(s, P),
    ):
        for entry in spec:
            if entry is None:
                continue
            used.update(entry if isinstance(entry, tuple) else (entry,))
    return [
        str(a) for a in mesh.axis_names
        if mesh.shape[a] > 1 and str(a) not in used
        and str(a) not in ("sequence", "expert")
    ]


def trace_train_plan(
    model, task, optimizer, sample_inputs, batch, plan: PlanSpec,
    devices=None, state_shapes=None, jaxpr_cache: Optional[dict] = None,
):
    """(flow, mesh_shape, partitioner) for one train plan — trace only.

    ``jaxpr_cache`` (optional dict) shares the traced jaxpr across plans
    whose compiled program is identical: every automatic-mode plan (no
    ZeRO-1 / wire / accumulation) traces the same step regardless of mesh,
    so the grid pays one big trace instead of one per plan. Manual-mode
    plans embed the partitioner in the shard_map and trace individually.
    """
    import jax

    from distributed_pytorch_example_tpu.train import step as step_mod

    devices = list(devices) if devices is not None else list(jax.devices())
    mesh = make_mesh(plan.mesh, devices=devices)
    partitioner = plan.lower(mesh=mesh)
    if state_shapes is None:
        state_shapes = step_mod.abstract_state(model, optimizer, sample_inputs)
    unused = _unused_axes(partitioner, state_shapes)
    if unused:
        raise PlanPruned(f"mesh axes {unused} unused by any sharding")

    manual = plan.zero1 or plan.grad_accum > 1 or _plan_bucketed(plan) or (
        plan.wire is not None and plan.wire.active
    )
    cache_key = plan.name() if manual else ("auto", plan.grad_accum)
    jaxpr = None if jaxpr_cache is None else jaxpr_cache.get(cache_key)
    if jaxpr is None:
        step_fn = step_mod.build_train_step(
            model, task, optimizer, partitioner=partitioner,
            grad_accum_steps=plan.grad_accum,
        )
        with mesh:
            jaxpr = jax.make_jaxpr(lambda s, b: step_fn(s, b))(
                state_shapes, batch
            )
        if jaxpr_cache is not None:
            jaxpr_cache[cache_key] = jaxpr
    from jax.sharding import PartitionSpec as P

    state_specs = partitioner.tree_specs(state_shapes)
    batch_specs = jax.tree_util.tree_map(
        lambda _: partitioner.batch_spec(), batch
    )
    in_specs = jax.tree_util.tree_leaves(
        (state_specs, batch_specs), is_leaf=lambda s: isinstance(s, P)
    )
    mesh_shape = {str(k): int(v) for k, v in dict(mesh.shape).items()}
    flow = shardflow.trace_shardings(jaxpr, in_specs, mesh_shape)
    return flow, mesh_shape, partitioner


class PlanPruned(Exception):
    """Raised when a plan is statically dominated/illegal at trace time."""


def rank_train_plans(
    model, task, optimizer, sample_inputs, batch,
    plans: Sequence[PlanSpec],
    program: str = "train",
    devices=None,
    link: Optional[LinkModel] = None,
    hbm_limit: Optional[int] = None,
    budgets: Optional[Dict[str, object]] = None,
    log=None,
    state_shapes=None,
) -> List[PlanScore]:
    """Score + rank train plans for one model. Feasible plans first,
    cheapest ranking cost first; infeasible plans trail with reasons."""
    import jax

    from distributed_pytorch_example_tpu.train import step as step_mod

    devices = list(devices) if devices is not None else list(jax.devices())
    if state_shapes is None:
        state_shapes = step_mod.abstract_state(
            model, optimizer, sample_inputs
        )
    param_leaves = jax.tree_util.tree_leaves(state_shapes.params)
    param_bytes = sum(
        math.prod(l.shape) * l.dtype.itemsize for l in param_leaves
    )
    dtype_bytes = param_leaves[0].dtype.itemsize if param_leaves else 2
    batch_leaves = jax.tree_util.tree_leaves(batch)
    global_batch = int(batch_leaves[0].shape[0]) if batch_leaves else 0
    seq_len = (
        int(batch_leaves[0].shape[1])
        if batch_leaves and len(batch_leaves[0].shape) > 1 else 0
    )
    jaxpr_cache: dict = {}
    scores: List[PlanScore] = []
    for plan in plans:
        try:
            flow, mesh_shape, _ = trace_train_plan(
                model, task, optimizer, sample_inputs, batch, plan,
                devices=devices, state_shapes=state_shapes,
                jaxpr_cache=jaxpr_cache,
            )
        except PlanPruned as exc:
            scores.append(PlanScore(
                plan=plan, program=program, feasible=False,
                reason=str(exc),
            ))
            continue
        except Exception as exc:  # trace failure = infeasible, not fatal
            scores.append(PlanScore(
                plan=plan, program=program, feasible=False,
                reason=f"{type(exc).__name__}: {str(exc).splitlines()[0][:200]}",
            ))
            continue
        cached = match_budget_record(
            plan, len(devices), budgets, global_batch=global_batch or None
        )
        floors = analytic_floors(
            plan, len(devices), param_bytes=param_bytes,
            global_batch=global_batch, seq_len=seq_len,
            model_dim=int(getattr(model, "model_dim", 0) or 0),
            num_layers=int(getattr(model, "num_layers", 0) or 0),
            dtype_bytes=dtype_bytes,
        )
        score = score_flow(
            plan, program, flow, mesh_shape,
            link=link, hbm_limit=hbm_limit, cached=cached, floors=floors,
        )
        scores.append(score)
        if log is not None:
            log(
                f"graft_plan: {program} {plan.name()} tier={score.tier} "
                f"cost_ms={score.cost_ms():.4f} comm_bytes={score.comm_bytes} "
                f"peak={score.predicted_peak_bytes}B feasible={score.feasible}"
            )
    return sort_scores(scores)


def rank_serve_plans(
    engine,
    plans: Sequence[PlanSpec],
    devices=None,
    link: Optional[LinkModel] = None,
    hbm_limit: Optional[int] = None,
    budgets: Optional[Dict[str, object]] = None,
    log=None,
) -> Dict[str, List[PlanScore]]:
    """Rank plans for the engine's prefill and decode programs SEPARATELY
    (``{"serve/prefill": [...], "serve/decode": [...]}``) — the two have
    different collective profiles, reusing the engine's representative
    traced args via :meth:`InferenceEngine.plan_programs`."""
    import jax

    devices = list(devices) if devices is not None else list(jax.devices())
    out: Dict[str, List[PlanScore]] = {}
    for plan in plans:
        try:
            mesh = make_mesh(plan.mesh, devices=devices)
            partitioner = plan.lower(mesh=mesh)
            programs = engine.plan_programs(partitioner)
        except Exception as exc:
            for prog in ("serve/prefill", "serve/decode"):
                out.setdefault(prog, []).append(PlanScore(
                    plan=plan, program=prog, feasible=False,
                    reason=f"{type(exc).__name__}: "
                           f"{str(exc).splitlines()[0][:200]}",
                ))
            continue
        mesh_shape = {str(k): int(v) for k, v in dict(mesh.shape).items()}
        for prog, (jaxpr, in_specs) in programs.items():
            flow = shardflow.trace_shardings(jaxpr, in_specs, mesh_shape)
            cached = None
            rec = (budgets or {}).get("configs", {}).get(prog)
            if rec is not None and match_budget_record(
                plan, len(devices), {"configs": {prog: rec}}
            ):
                cached = (prog, rec)
            score = score_flow(
                plan, prog, flow, mesh_shape,
                link=link, hbm_limit=hbm_limit, cached=cached,
            )
            out.setdefault(prog, []).append(score)
            if log is not None:
                log(
                    f"graft_plan: {prog} {plan.name()} tier={score.tier} "
                    f"cost_ms={score.cost_ms():.4f} "
                    f"comm_bytes={score.comm_bytes} feasible={score.feasible}"
                )
    return {prog: sort_scores(s) for prog, s in out.items()}


def sort_scores(scores: Sequence[PlanScore]) -> List[PlanScore]:
    """Feasible-first, then (ranking cost, peak bytes, name) ascending."""
    return sorted(
        scores,
        key=lambda s: (
            not s.feasible, s.cost_ms(), s.predicted_peak_bytes,
            s.plan.name(),
        ),
    )


def best_plan(scores: Sequence[PlanScore]) -> Optional[PlanScore]:
    """Top-ranked FEASIBLE score, or None when every plan was pruned."""
    for s in sort_scores(scores):
        if s.feasible:
            return s
    return None


def cli_plan_space(
    n_devices: int, info: ProgramInfo, wire_block: int = 256
) -> List[PlanSpec]:
    """The ``--auto-mesh`` search space shared by train.py / bench.py /
    scripts/plan_search.py: every automatic-mode mesh family (one shared
    trace) plus the zero1 / int8-wire knobs on the pure-DP mesh (one trace
    each — where bench's --zero1/--wire run), never wire without zero1.
    Every pure-DP ZeRO-1 plan also enters in its comm/compute-overlap
    variant (``bucket_bytes`` at the default target) so the oracle can
    pick bucketing when the hidden grad-sync time wins."""
    from distributed_pytorch_example_tpu.parallel.wire import (
        DEFAULT_BUCKET_BYTES,
    )

    wire = WireConfig(compress="int8-block", block_size=wire_block)
    plans = enumerate_plans(
        n_devices, info,
        families=("data", "fsdp", "transformer"),
        zero1_options=(False, True),
        wire_options=(None, wire),
        allow_pipe=False,
    )
    plans = [
        p for p in plans
        if (p.family == "data" or (not p.zero1 and p.wire is None))
        and (p.wire is None or p.zero1)
    ]
    bucketed = [
        dataclasses.replace(p, bucket_bytes=DEFAULT_BUCKET_BYTES)
        for p in plans
        if p.family == "data" and p.zero1
    ]
    return plans + [
        b for b in bucketed
        if legality(b, info, n_devices) is None
    ]


def pick_train_plan(
    model, task, optimizer, sample_inputs, batch,
    kind: str = "image",
    program: str = "train",
    devices=None,
    hbm_limit: Optional[int] = None,
    wire_block: int = 256,
    log=None,
) -> Tuple[Optional[PlanScore], List[PlanScore]]:
    """One-call ``--auto-mesh`` entry point: ``(winner, all scores)``.

    Enumerates :func:`cli_plan_space` for the program's topology, ranks it
    through the three-tier oracle (committed comm budgets engage when the
    recorded jax version matches the runtime), and returns the best
    feasible score — None when the envelope gate pruned everything.
    """
    import jax

    from distributed_pytorch_example_tpu.analysis import collectives

    devices = list(devices) if devices is not None else list(jax.devices())
    leaves = jax.tree_util.tree_leaves(batch)
    info = ProgramInfo(
        global_batch=int(leaves[0].shape[0]) if leaves else 0,
        num_heads=int(getattr(model, "num_heads", 0) or 0),
        num_layers=int(getattr(model, "num_layers", 0) or 0),
        pipelineable=False,
        kind=kind,
    )
    plans = cli_plan_space(len(devices), info, wire_block=wire_block)
    budgets = collectives.load_budgets()
    if budgets is not None and collectives.jax_version_skew(budgets):
        budgets = None
    scores = rank_train_plans(
        model, task, optimizer, sample_inputs, batch, plans,
        program=program, devices=devices, hbm_limit=hbm_limit,
        budgets=budgets, log=log,
    )
    return best_plan(scores), scores


def pick_serve_plan(
    engine,
    devices=None,
    hbm_limit: Optional[int] = None,
    budgets: Optional[Dict[str, object]] = None,
    log=None,
    extra_plans: Sequence[PlanSpec] = (),
) -> Tuple[Optional[PlanSpec], Optional[float], Dict[str, List[PlanScore]]]:
    """``--auto-mesh`` for serving: ``(plan, summed cost_ms, rankings)``.

    Prefill and decode are ranked SEPARATELY (different collective
    profiles); one engine must run both, so the pick minimizes the summed
    program cost over plans feasible for BOTH. Serve batch dims (slots,
    bucketed prompt) replicate in the traced programs, so the legality
    batch is the device count itself. Pass ``budgets=None`` (the default)
    unless the engine IS the committed dryrun engine — the budget records
    match by mesh alone and would pollute across model scales.
    """
    import jax

    devices = list(devices) if devices is not None else list(jax.devices())
    info = ProgramInfo(
        global_batch=len(devices),
        num_heads=int(getattr(engine.model, "num_heads", 0) or 0),
        num_layers=int(getattr(engine.model, "num_layers", 0) or 0),
        pipelineable=False,
        kind="lm",
    )
    plans = enumerate_plans(
        len(devices), info, families=("data", "transformer"),
        zero1_options=(False,), wire_options=(None,), allow_pipe=False,
    )
    seen = {p.name() for p in plans}
    for p in extra_plans:
        if p.name() not in seen and legality(p, info, len(devices)) is None:
            plans.append(p)
    ranked = rank_serve_plans(
        engine, plans, devices=devices, hbm_limit=hbm_limit,
        budgets=budgets, log=log,
    )
    by_name: Dict[str, Dict[str, PlanScore]] = {}
    for prog, scores in ranked.items():
        for s in scores:
            by_name.setdefault(s.plan.name(), {})[prog] = s
    best_spec, best_cost, best_name = None, None, None
    for nm in sorted(by_name):
        progs = by_name[nm]
        if len(progs) < len(ranked) or not all(
            s.feasible for s in progs.values()
        ):
            continue
        cost = sum(s.cost_ms() for s in progs.values())
        if best_cost is None or cost < best_cost:
            best_name, best_cost = nm, cost
            best_spec = next(iter(progs.values())).plan
    return best_spec, best_cost, ranked


# -- committed plan rankings (analysis/plans.json) -------------------------

# Committed beside comm_budgets.json: top-ranked plans per program on the
# 8-chip fake mesh, written by `scripts/plan_search.py --write-plans`.
DEFAULT_PLANS_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "plans.json"
)


def load_plans(path: str = DEFAULT_PLANS_PATH) -> Optional[Dict[str, object]]:
    """Parsed committed plan rankings, or None when absent/corrupt."""
    try:
        with open(path, "r", encoding="utf-8") as fh:
            return json.load(fh)
    except (OSError, ValueError):
        return None


def plans_staleness(
    plans_path: str = DEFAULT_PLANS_PATH,
    budgets_path: Optional[str] = None,
) -> Optional[str]:
    """Why the committed plans.json may be stale, or None when current.

    Mirrors ``collectives.budget_staleness``'s advisory contract (warn,
    never fail): plans are derived from the same traced programs as the
    committed comm budgets, so a budgets file regenerated after plans.json
    (mtime), or a jax-version skew between the two _meta blocks, means the
    rankings were computed against a schedule that no longer matches.
    """
    from distributed_pytorch_example_tpu.analysis import collectives

    if budgets_path is None:
        budgets_path = collectives.DEFAULT_BUDGETS_PATH
    plans = load_plans(plans_path)
    if plans is None:
        return (
            f"plans.json missing or unreadable at {plans_path} — generate "
            f"with scripts/plan_search.py --write-plans"
        )
    plans_jax = ((plans.get("_meta") or {}).get("jax"))
    budgets = collectives.load_budgets(budgets_path)
    if budgets is not None:
        budgets_jax = (budgets.get("_meta") or {}).get("jax")
        if plans_jax and budgets_jax and plans_jax != budgets_jax:
            return (
                f"plans.json jax {plans_jax} != comm_budgets.json jax "
                f"{budgets_jax} — regenerate with scripts/plan_search.py "
                f"--write-plans"
            )
        try:
            if os.path.getmtime(budgets_path) > os.path.getmtime(plans_path):
                return (
                    "comm_budgets.json is newer than plans.json — rankings "
                    "may not reflect the committed budgets; regenerate with "
                    "scripts/plan_search.py --write-plans"
                )
        except OSError:
            pass
    import jax

    if plans_jax and plans_jax != jax.__version__:
        return (
            f"plans.json written under jax {plans_jax}, runtime is "
            f"{jax.__version__} — rankings advisory only"
        )
    return None
